"""TpuSparkSession: the user entry point (the analogue of a Spark session
with the rapids plugin installed — SQLPlugin + RapidsExecutorPlugin,
Plugin.scala:106-146).

Construction initializes the device runtime once per process: device
discovery, the TpuSemaphore (device admission), and the spill-tier catalog —
mirroring RapidsExecutorPlugin.init (Plugin.scala:122-146).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import HostBatch
from spark_rapids_tpu.config import RapidsConf, conf as global_conf


class _MetricsFrame:
    """Per-call holder for one query's metrics dict.

    ``execute_with_metrics`` fills a frame, then publishes it to
    ``session.last_metrics`` with a single reference assignment — the
    serving runtime runs N executes against one session, and filling
    ``self.last_metrics`` in place would let a concurrent reader observe
    a half-written mixture of two queries."""

    __slots__ = ("last_metrics",)

    def __init__(self, op_metrics: Dict[str, Any]):
        self.last_metrics: Dict[str, Any] = op_metrics


# process-wide session numbering: event-log headers stamp it so
# rapidsprof can group one shared log's queries by the session that ran
# them (query ids are already process-globally unique)
_SESSION_SEQ_LOCK = threading.Lock()
_SESSION_SEQ = 0


def _next_session_id() -> int:
    global _SESSION_SEQ
    with _SESSION_SEQ_LOCK:
        _SESSION_SEQ += 1
        return _SESSION_SEQ


class TpuSparkSession:
    _lock = threading.Lock()
    _active: Optional["TpuSparkSession"] = None

    def __init__(self, conf: Optional[RapidsConf] = None,
                 use_device: bool = True):
        self.conf = conf or global_conf.copy()
        self.session_id = _next_session_id()
        from spark_rapids_tpu.config import COMPILE_CACHE_DIR
        cache_dir = COMPILE_CACHE_DIR.get(self.conf)
        if cache_dir:
            from spark_rapids_tpu.utils.compile_registry import (
                enable_persistent_cache,
            )
            enable_persistent_cache(cache_dir)
        from spark_rapids_tpu.runtime.device import DeviceRuntime
        self.runtime = DeviceRuntime.get(self.conf) if use_device else None
        self._views: Dict[str, Any] = {}
        # bounded per-query observability profiles (obs.profile), newest
        # last; see query_history() / explain_last().  Guarded by
        # _history_lock: the serving runtime executes on N threads
        # against one session.
        self._query_history: List[Any] = []
        self._history_lock = threading.Lock()
        # last completed query's metrics; REPLACED wholesale per query
        # (never mutated in place) so concurrent readers see a
        # consistent dict
        self.last_metrics: Dict[str, Any] = {}
        # the logical-plan -> physical-plan memo is process-wide
        # (serve.excache.SharedPlanCache): N sessions serving the same
        # query shape share exec instances and therefore every compiled
        # executable.  Size it from this session's conf.
        from spark_rapids_tpu.config import SERVE_PLAN_CACHE_MAX
        from spark_rapids_tpu.serve.excache import shared_plan_cache
        shared_plan_cache().set_max_plans(SERVE_PLAN_CACHE_MAX.get(self.conf))
        with TpuSparkSession._lock:
            TpuSparkSession._active = self

    # -- catalog ------------------------------------------------------------

    def register_view(self, name: str, df) -> None:
        self._views[name.lower()] = df

    def table(self, name: str):
        df = self._views.get(name.lower())
        if df is None:
            raise KeyError(f"table or view not found: {name}")
        return df

    # -- builders -----------------------------------------------------------

    @classmethod
    def builder(cls) -> "SessionBuilder":
        return SessionBuilder()

    @classmethod
    def active(cls) -> "TpuSparkSession":
        with cls._lock:
            if cls._active is None:
                cls._active = TpuSparkSession()
            return cls._active

    # -- conf ---------------------------------------------------------------

    def set_conf(self, key: str, value: Any) -> "TpuSparkSession":
        self.conf.set(key, value)
        return self

    # -- data sources -------------------------------------------------------

    def create_dataframe(self, data, schema=None, num_partitions: int = 1):
        """Build a DataFrame from a pydict {name: (dtype, values)} /
        {name: values} / list of row tuples + schema."""
        from spark_rapids_tpu.dataframe import DataFrame
        from spark_rapids_tpu.plan.logical import InMemoryScan
        batch = _to_host_batch(data, schema)
        return DataFrame(InMemoryScan([batch], batch.schema, num_partitions),
                         self)

    def range(self, start: int, end: Optional[int] = None, step: int = 1,
              num_partitions: int = 1):
        from spark_rapids_tpu.dataframe import DataFrame
        from spark_rapids_tpu.plan.logical import Range
        if end is None:
            start, end = 0, start
        return DataFrame(Range(start, end, step, num_partitions), self)

    @property
    def read(self) -> "DataFrameReader":
        return DataFrameReader(self)

    def sql(self, query: str):
        from spark_rapids_tpu.sql.parser import parse_sql
        return parse_sql(query, self)

    # -- execution ----------------------------------------------------------

    def plan_physical(self, plan):
        """Lower a logical plan, memoized per (canonical plan fingerprint,
        conf state) — the canonicalized-plan-reuse role
        (GpuOverrides + Spark plan canonicalization): two structurally
        identical DataFrames (e.g. ``df.count()`` called twice, each
        building a fresh Aggregate node) share one physical plan and
        therefore every compiled XLA kernel.  The memo is PROCESS-wide
        (serve.excache): every session serving the same (fingerprint,
        conf-state) shape shares one physical plan, so only the first
        execution anywhere in the process compiles."""
        from spark_rapids_tpu.plan.logical import plan_fingerprint
        from spark_rapids_tpu.serve.excache import shared_plan_cache
        key = plan_fingerprint(plan)
        # metrics-detail and obs knobs never change the plan: excluding
        # them keeps the memo (and therefore every compiled kernel)
        # hittable when a measurement run toggles accurate device-time
        # syncing or the observability bus
        conf_state = tuple(sorted(
            (k, str(v)) for k, v in self.conf._settings.items()
            if not (k.startswith("spark.rapids.sql.tpu.metrics.")
                    or k.startswith("spark.rapids.sql.tpu.obs."))))

        def _build():
            from spark_rapids_tpu.plan.overrides import TpuOverrides
            overrides = TpuOverrides(self.conf)
            phys = overrides.apply(plan)
            return plan, phys, overrides.last_explain

        phys, explain, _hit = shared_plan_cache().get_or_build(
            key, conf_state, _build)
        self.last_explain = explain
        return phys

    def _shuffle_mesh(self):
        """The >1-device mesh for the ICI collective shuffle, or None.

        Opt-in via spark.rapids.shuffle.ici.enabled (the reference's
        accelerated UCX shuffle is likewise explicitly configured:
        RapidsShuffleManager in docs/get-started).  On a single-chip
        process this is always None and exchanges use the host path.
        """
        from spark_rapids_tpu.config import ENABLE_ICI_SHUFFLE
        if not ENABLE_ICI_SHUFFLE.get(self.conf):
            return None
        if not hasattr(self, "_mesh"):
            import jax
            from spark_rapids_tpu.parallel.mesh_shuffle import make_mesh
            self._mesh = make_mesh() if len(jax.devices()) > 1 else None
        return self._mesh

    def execute(self, plan) -> HostBatch:
        out, _metrics = self.execute_with_metrics(plan)
        return out

    def execute_with_metrics(self, plan) -> Tuple[HostBatch, Dict[str, Any]]:
        """Execute and return ``(rows, this query's metrics dict)``.

        ``self.last_metrics`` is also published (one reference
        assignment, so concurrent executes on a shared session never
        expose a half-written dict), but under concurrency only the
        returned dict is guaranteed to describe THIS call — the serving
        scheduler uses it for per-tenant rollups."""
        from spark_rapids_tpu.config import (
            FAULTS_SPEC, OBS_ENABLED, OBS_RING_MAX_EVENTS,
            OBS_TELEMETRY_ENABLED, OBS_TELEMETRY_INTERVAL_MS,
            OBS_TELEMETRY_MAX_INTERVALS,
        )
        from spark_rapids_tpu.fault import inject as fault_inject
        from spark_rapids_tpu.fault import metrics as FM
        from spark_rapids_tpu.obs import events as obs_events
        from spark_rapids_tpu.obs import timeseries as obs_ts
        from spark_rapids_tpu.plan.physical import ExecContext, collect_host
        from spark_rapids_tpu.utils import compile_registry as CR
        # (re)shape the process telemetry ring from this session's conf
        # and (re)register the engine gauges — a repeat execute with the
        # same shape keeps the live ring and its accumulated intervals
        obs_ts.configure(OBS_TELEMETRY_ENABLED.get(self.conf),
                         OBS_TELEMETRY_INTERVAL_MS.get(self.conf),
                         OBS_TELEMETRY_MAX_INTERVALS.get(self.conf))
        self._register_telemetry_gauges()
        # the Pallas kernel tier consults this session's conf for its
        # per-kernel gates at trace time (kernels.pallas_tier)
        from spark_rapids_tpu.kernels import pallas_tier
        pallas_tier.configure(self.conf)
        phys = self.plan_physical(plan)
        if self.conf.test_enforce_tpu:
            _assert_on_tpu(phys)
        if self.runtime is not None:
            # re-resolve: a device-lost recovery mid-query rebuilds the
            # process runtime (new semaphore/device, same catalog) — the
            # next query must ride the live instance, not the dead one
            from spark_rapids_tpu.runtime.device import DeviceRuntime
            self.runtime = DeviceRuntime.get(self.conf)
        ctx = ExecContext(
            self.conf,
            semaphore=self.runtime.semaphore if self.runtime else None,
            device=self.runtime.device if self.runtime else None,
            mesh=self._shuffle_mesh())
        # the fault-recovery CPU fallback re-lowers THIS logical plan
        # with sql.enabled=false to replay a failed partition on the CPU
        # operator path (fault.recovery)
        ctx.logical_plan = plan
        self.last_physical_plan = phys
        self.last_exec_ctx = ctx
        # open the query scope exactly around the metric snapshots so
        # the event window and the CR/FM deltas describe the same
        # interval; the scope also carries this query's counters and
        # fault registry under concurrent serving
        obs_token = obs_events.begin_query(
            enabled=OBS_ENABLED.get(self.conf),
            max_events=OBS_RING_MAX_EVENTS.get(self.conf))
        # query-intelligence hooks (history/): seed the plan from the
        # statistics store and arm the fragment-cache key on the context
        # — a single conf read when no history dir is configured
        from spark_rapids_tpu import history as qhistory
        qhistory.begin_query(self, plan, phys, ctx)
        # (re)install the deterministic fault registry per query (on the
        # scope just opened, so concurrent queries keep separate specs):
        # call counters reset so "the Nth dispatch" is query-relative;
        # an empty spec clears any previously installed registry, and
        # the finally clears an armed one so persistent @N+ rules cannot
        # outlive the query and fire at sites with no recovery around
        # them (e.g. ml.to_device_batches staging outside execute)
        spec = FAULTS_SPEC.get(self.conf)
        fault_inject.install(spec)
        t_query0 = time.monotonic_ns()
        before = CR.snapshot()
        fm_before = FM.snapshot()
        pt_before = pallas_tier.fallback_count()
        cat_before = dict(self.runtime.catalog.metrics) \
            if self.runtime is not None else {}
        try:
            out = collect_host(phys, ctx)
        except BaseException:
            # close the scope so a failed query can't leak its bus into
            # the next query's window
            obs_events.end_query(obs_token)
            raise
        finally:
            if spec:
                fault_inject.uninstall()
        # ONE query-end stamp: the wall metric, the history record and
        # the critical-path window must agree to the nanosecond or the
        # decomposition's exactness contract breaks
        t_query1 = time.monotonic_ns()
        if obs_token is not None:
            # per-scope counters: exactly this query's activity, even
            # with N queries in flight (the global snapshot delta would
            # mix them)
            d = obs_token.counters_for(before)
            fm_d = obs_token.counters_for(fm_before)
        else:
            # nested execute (prewarm, recovery re-lowering) rides the
            # outer scope: fall back to the historical global deltas
            fm_d = FM.delta(fm_before, FM.snapshot())
            d = CR.delta(before, CR.snapshot())
        frame = _MetricsFrame({
            op: {name: m.value for name, m in ms.items()}
            for op, ms in ctx.metrics.items()})
        # compile/dispatch economics for THIS query (process-wide counters
        # snapshotted around the collect; compiledShapes is the cumulative
        # compiled-executable cardinality the bucket policy bounds)
        # kernel-tier economics: XLA fallbacks the Pallas tier took at
        # trace time during this query (backend/budget/lowering failure;
        # cached executables trace nothing and count nothing)
        frame.last_metrics["pallasFallbackCount"] = \
            pallas_tier.fallback_count() - pt_before
        frame.last_metrics["compileCount"] = d["compiles"]
        frame.last_metrics["compileWallNs"] = d["compile_wall_ns"]
        frame.last_metrics["dispatchCount"] = d["dispatches"]
        frame.last_metrics["backendCompileNs"] = d["backend_compile_ns"]
        frame.last_metrics["compiledShapes"] = CR.compiled_shapes()
        # data-plane economics: input bytes donated to dispatches (HBM
        # reused for outputs) and the host<->device staging volume/time
        frame.last_metrics["donatedBytes"] = d["donated_bytes"]
        frame.last_metrics["h2dBytes"] = d["h2d_bytes"]
        frame.last_metrics["h2dTimeNs"] = d["h2d_ns"]
        frame.last_metrics["d2hBytes"] = d["d2h_bytes"]
        frame.last_metrics["d2hTimeNs"] = d["d2h_ns"]
        frame.last_metrics["deviceTimeNs"] = sum(
            ms["deviceTimeNs"].value for ms in ctx.metrics.values()
            if "deviceTimeNs" in ms)
        # shuffle split economics, summed over every exchange op: split
        # programs dispatched, blocking host syncs paid, catalog pieces
        # registered, and the bytes/wall the split moved (GB/s derivable)
        frame.last_metrics["shuffleSplitDispatches"] = sum(
            ms["shuffleSplitDispatches"].value for ms in ctx.metrics.values()
            if "shuffleSplitDispatches" in ms)
        frame.last_metrics["shuffleSyncs"] = sum(
            ms["shuffleSyncs"].value for ms in ctx.metrics.values()
            if "shuffleSyncs" in ms)
        frame.last_metrics["shufflePieces"] = sum(
            ms["shufflePieces"].value for ms in ctx.metrics.values()
            if "shufflePieces" in ms)
        frame.last_metrics["shuffleBytes"] = sum(
            ms["shuffleBytes"].value for ms in ctx.metrics.values()
            if "shuffleBytes" in ms)
        frame.last_metrics["shuffleWallNs"] = sum(
            ms["shuffleWallNs"].value for ms in ctx.metrics.values()
            if "shuffleWallNs" in ms)
        # dict-aware shuffle economics: materialized string bytes the
        # split did NOT move because pieces stayed dictionary-encoded
        # (codes + merged dictionary instead of raw bytes); 0 when the
        # query shuffled no encoded columns or dictAware is off
        frame.last_metrics["shuffleEncodedBytesSaved"] = sum(
            ms["shuffleEncodedBytesSaved"].value
            for ms in ctx.metrics.values()
            if "shuffleEncodedBytesSaved" in ms)
        # mesh-SPMD economics (parallel.mesh_spmd): whole-stage programs
        # dispatched, exchange boundaries fused into them (each one is a
        # shuffle that ran as an in-program all_to_all with ZERO host
        # syncs), and which backend the shuffle mesh actually ran on —
        # bench consumers must not mislabel a CPU-virtual-device curve
        # as TPU ICI scaling
        frame.last_metrics["meshProgramDispatches"] = sum(
            ms["meshProgramDispatches"].value for ms in ctx.metrics.values()
            if "meshProgramDispatches" in ms)
        frame.last_metrics["meshBoundariesFused"] = sum(
            ms["meshBoundariesFused"].value for ms in ctx.metrics.values()
            if "meshBoundariesFused" in ms)
        # mesh-SPMD v2: joins compiled INTO fused stage programs (static
        # bucketed output sizing, no host sync), stages that overflowed a
        # bucket and transparently reran host-driven, and the string
        # bytes mesh exchanges materialized out of dictionary encoding
        # (the wire moves decoded rows — the give-up side of the scan's
        # dict corridor at mesh boundaries)
        frame.last_metrics["meshJoinsFused"] = sum(
            ms["meshJoinsFused"].value for ms in ctx.metrics.values()
            if "meshJoinsFused" in ms)
        frame.last_metrics["meshFallbacks"] = sum(
            ms["meshFallbacks"].value for ms in ctx.metrics.values()
            if "meshFallbacks" in ms)
        frame.last_metrics["meshEncodedMaterializedBytes"] = sum(
            ms["meshEncodedMaterializedBytes"].value
            for ms in ctx.metrics.values()
            if "meshEncodedMaterializedBytes" in ms)
        _mesh = self._shuffle_mesh()
        frame.last_metrics["meshBackend"] = (
            str(next(iter(_mesh.devices.flat)).platform)
            if _mesh is not None else "")
        # scan/ingest economics (io.scan_v2), summed over every scan op:
        # decode wall across pool workers, the part of it hidden behind
        # the consumer's H2D/compute, decoded volume, dictionary-encoded
        # column instances staged, and late-mat chunks skipped entirely
        def _scan_sum(key):
            return sum(ms[key].value for ms in ctx.metrics.values()
                       if key in ms)
        frame.last_metrics["scanDecodeWallNs"] = _scan_sum("scanDecodeWallNs")
        frame.last_metrics["scanH2dOverlapNs"] = _scan_sum("scanH2dOverlapNs")
        frame.last_metrics["scanBytesDecoded"] = _scan_sum("scanBytesDecoded")
        frame.last_metrics["scanDictColumns"] = _scan_sum("scanDictColumns")
        frame.last_metrics["scanChunksSkipped"] = _scan_sum("scanChunksSkipped")
        # adaptive read-ahead: the deepest effective depth any scan op's
        # controller reached this query (equals the static conf when the
        # user pinned scan.readAhead.depth explicitly)
        _depths = [ms["readaheadDepthEffective"].value
                   for ms in ctx.metrics.values()
                   if "readaheadDepthEffective" in ms]
        frame.last_metrics["readaheadDepthEffective"] = \
            max(_depths) if _depths else 0
        # adaptive-execution economics (plan/adaptive), summed over every
        # op that replanned: partitions merged away by post-shuffle
        # coalescing, joins switched to the broadcast shape at runtime,
        # skewed partitions isolated/split, and the volume of host-known
        # statistics those decisions consumed (all recorded with zero
        # extra host syncs — the shuffle split already fetched them)
        frame.last_metrics["aqeCoalescedPartitions"] = sum(
            ms["aqeCoalescedPartitions"].value
            for ms in ctx.metrics.values()
            if "aqeCoalescedPartitions" in ms)
        frame.last_metrics["aqeBroadcastSwitches"] = sum(
            ms["aqeBroadcastSwitches"].value for ms in ctx.metrics.values()
            if "aqeBroadcastSwitches" in ms)
        frame.last_metrics["aqeSkewSplits"] = sum(
            ms["aqeSkewSplits"].value for ms in ctx.metrics.values()
            if "aqeSkewSplits" in ms)
        frame.last_metrics["aqeStatsRows"] = sum(
            ms["aqeStatsRows"].value for ms in ctx.metrics.values()
            if "aqeStatsRows" in ms)
        frame.last_metrics["aqeStatsBytes"] = sum(
            ms["aqeStatsBytes"].value for ms in ctx.metrics.values()
            if "aqeStatsBytes" in ms)
        # planner size-estimate error vs. actual shuffle bytes, averaged
        # over the exchanges that carried a static estimate (0.0 when the
        # query had none)
        _errs = [ms["aqeEstimateErrorPct"].value
                 for ms in ctx.metrics.values()
                 if "aqeEstimateErrorPct" in ms]
        frame.last_metrics["aqeEstimateErrorPct"] = \
            sum(_errs) / len(_errs) if _errs else 0.0
        # query-intelligence economics (history/): planning decisions the
        # store seeded up front, fragment-cache reuse (a hit re-executes
        # the whole subtree with ZERO dispatches), and how often the
        # persistent store was consulted
        frame.last_metrics["historySeededDecisions"] = _scan_sum(
            "historySeededDecisions")
        frame.last_metrics["fragmentCacheHits"] = _scan_sum(
            "fragmentCacheHits")
        frame.last_metrics["fragmentCacheBytes"] = _scan_sum(
            "fragmentCacheBytes")
        frame.last_metrics["statsStoreQueries"] = _scan_sum(
            "statsStoreQueries")
        # fault-tolerance economics (fault.metrics deltas): recovery
        # replays, deterministic-backoff wall, device losses handled,
        # partitions completed via the CPU path, and injected faults
        frame.last_metrics["retryCount"] = fm_d["retries"]
        frame.last_metrics["backoffWallNs"] = fm_d["backoff_wall_ns"]
        frame.last_metrics["deviceLostCount"] = fm_d["device_lost"]
        frame.last_metrics["partitionFallbackCount"] = \
            fm_d["partition_fallbacks"]
        frame.last_metrics["faultsInjected"] = fm_d["faults_injected"]
        # spill-engine economics for THIS query (catalog counters are
        # process-cumulative, so delta against the pre-query snapshot):
        # writer wall, peak writer-queue depth, read-aheads that hid an
        # unspill, and the bytes each tier hop moved
        cat_now = dict(self.runtime.catalog.metrics) \
            if self.runtime is not None else {}

        def cat_delta(key):
            return cat_now.get(key, 0) - cat_before.get(key, 0)

        frame.last_metrics["spillWallNs"] = cat_delta("spill_wall_ns")
        frame.last_metrics["spillQueueDepthMax"] = \
            cat_now.get("spill_queue_depth_max", 0)
        frame.last_metrics["unspillPrefetchHits"] = \
            cat_delta("unspill_prefetch_hits")
        frame.last_metrics["spillToHostBytes"] = cat_delta(
            "spill_to_host_bytes")
        frame.last_metrics["spillToDiskBytes"] = cat_delta(
            "spill_to_disk_bytes")
        if self.runtime is not None:
            frame.last_metrics["memory"] = dict(self.runtime.catalog.metrics)
        # telemetry economics: how many aggregation intervals the
        # process ring has completed so far (monotone across queries)
        frame.last_metrics["telemetryIntervals"] = obs_ts.completed_total()
        # persist this query's runtime facts for future plan seeding and
        # run the regression sentinel against the store's aggregate of
        # previous runs (history/; no-op without a history dir).  This
        # runs BEFORE the obs drain so each alert's ``regression``
        # instant lands inside this query's event window
        alerts = qhistory.end_query(self, plan, phys, ctx,
                                    frame.last_metrics,
                                    t_query1 - t_query0, out)
        frame.last_metrics["regressionAlerts"] = len(alerts)
        # drain the obs epoch and fold it into a bounded-history profile
        # (obs.profile); the event counts become metrics so tests and
        # bench can assert the bus's own economics
        obs_events_list, obs_dropped, obs_dropped_by_site = \
            obs_events.end_query(obs_token)
        frame.last_metrics["obsEventCount"] = len(obs_events_list)
        frame.last_metrics["obsEventsDropped"] = obs_dropped
        # exact wall decomposition (obs.critpath): the segments partition
        # [t_query0, t_query1) so attributed + wait == wall EXACTLY
        from spark_rapids_tpu.obs import critpath as obs_critpath
        cp = obs_critpath.compute(obs_events_list, t_query0, t_query1)
        frame.last_metrics["critpathAttributedNs"] = cp.attributed_ns
        # publish by one reference assignment: a concurrent reader of
        # self.last_metrics sees the previous complete dict or this one,
        # never a half-filled frame
        self.last_metrics = frame.last_metrics
        if obs_token is not None and obs_token.bus is not None:
            self._record_profile(obs_token.query_id, obs_events_list,
                                 obs_dropped, t_query1 - t_query0,
                                 frame.last_metrics,
                                 dropped_by_site=obs_dropped_by_site,
                                 qt0_ns=t_query0, qt1_ns=t_query1)
        return out, frame.last_metrics

    def _register_telemetry_gauges(self) -> None:
        """(Re)register the engine gauges on the telemetry ring.  Gauges
        are sampled at export time only (never inside the emit path), so
        taking engine locks here is safe."""
        from spark_rapids_tpu.obs import events as obs_events
        from spark_rapids_tpu.obs import timeseries as obs_ts
        if obs_ts.ring() is None:
            return
        obs_ts.register_gauge(
            "obs.ring_drops", lambda: float(obs_events.ring_drops_total()))
        from spark_rapids_tpu.history.fragcache import fragment_cache
        obs_ts.register_gauge(
            "fragcache.bytes",
            lambda: float(fragment_cache().stats().get(
                "fragment_cache_bytes", 0)))
        from spark_rapids_tpu.io.decode_pool import decode_pool_utilization
        obs_ts.register_gauge("io.decode_pool_utilization",
                              decode_pool_utilization)
        rt = self.runtime
        if rt is None:
            return
        cat = rt.catalog
        for tier in ("device", "host", "disk"):
            obs_ts.register_gauge(
                f"catalog.{tier}_bytes",
                lambda t=tier: float(cat.tier_bytes()[t]))
        obs_ts.register_gauge("spill.writer_utilization",
                              cat.writer_utilization)
        obs_ts.register_gauge(
            "spill.writer_queue_depth",
            lambda: float(cat.writer_queue_depth()))

    def _record_profile(self, query_id: int, events, dropped: int,
                        wall_ns: int, metrics: Dict[str, Any],
                        dropped_by_site: Optional[Dict[str, int]] = None,
                        qt0_ns: int = 0, qt1_ns: int = 0) -> None:
        """Fold one query's drained events into the bounded history and
        append to the JSONL event log when configured."""
        from spark_rapids_tpu.config import (
            OBS_EVENT_LOG_DIR, OBS_HISTORY_MAX,
        )
        from spark_rapids_tpu.obs.profile import QueryProfile
        scalars = {k: v for k, v in metrics.items()
                   if not isinstance(v, dict)}
        op_metrics = {k: v for k, v in metrics.items()
                      if isinstance(v, dict) and k != "memory"}
        prof = QueryProfile(query_id, events, dropped, wall_ns=wall_ns,
                            metrics=scalars, op_metrics=op_metrics,
                            dropped_by_site=dropped_by_site,
                            session_id=self.session_id,
                            qt0_ns=qt0_ns, qt1_ns=qt1_ns)
        keep = max(1, OBS_HISTORY_MAX.get(self.conf))
        with self._history_lock:
            self._query_history.append(prof)
            while len(self._query_history) > keep:
                self._query_history.pop(0)
        log_dir = OBS_EVENT_LOG_DIR.get(self.conf)
        if log_dir:
            from spark_rapids_tpu.obs import export as obs_export
            path = os.path.join(log_dir, f"events-{os.getpid()}.jsonl")
            obs_export.write_event_log(path, prof.query_record(), events)
            from spark_rapids_tpu.obs import timeseries as obs_ts
            r = obs_ts.ring()
            if r is not None:
                try:
                    r.flush_jsonl(os.path.join(
                        log_dir, f"telemetry-{os.getpid()}.jsonl"))
                except OSError:
                    pass

    def query_history(self) -> List[Any]:
        """The last ``spark.rapids.sql.tpu.obs.history.maxQueries``
        :class:`~spark_rapids_tpu.obs.profile.QueryProfile` objects,
        oldest first (empty when obs is disabled)."""
        with self._history_lock:
            return list(self._query_history)

    def explain_last(self, metrics: bool = False) -> str:
        """The last query's explain output; with ``metrics=True`` the
        physical tree follows, annotated per operator with the last
        profile's rollups (the SQL-UI exec-metrics analogue)."""
        base = getattr(self, "last_explain", "") or ""
        if not metrics:
            return base
        phys = getattr(self, "last_physical_plan", None)
        if phys is None or not self._query_history:
            return base
        from spark_rapids_tpu.obs.profile import annotate_plan
        return base + "\n\n" + annotate_plan(phys, self._query_history[-1])

    def prewarm(self, *dataframes) -> Dict[str, int]:
        """Compile the hot bucket set once, ahead of the timed path.

        Executes each given DataFrame (default: every registered view) end
        to end, so every stage program compiles against the shared bucket
        policy's capacities — with ``spark.rapids.sql.tpu.compileCacheDir``
        set the executables also land in the persistent cache, making the
        next process's warmup near-free.  Returns the compile economics of
        the warmup: ``{"compileCount", "compileWallNs", "dispatchCount",
        "compiledShapes"}``.
        """
        from spark_rapids_tpu.utils import compile_registry as CR
        targets = list(dataframes) or list(self._views.values())
        before = CR.snapshot()
        for df in targets:
            self.execute(df.plan)
        d = CR.delta(before, CR.snapshot())
        return {
            "compileCount": d["compiles"],
            "compileWallNs": d["compile_wall_ns"],
            "dispatchCount": d["dispatches"],
            "compiledShapes": CR.compiled_shapes(),
        }

    def explain_plan(self, plan) -> str:
        from spark_rapids_tpu.plan.overrides import TpuOverrides
        overrides = TpuOverrides(self.conf)
        phys = overrides.apply(plan)
        return overrides.last_explain + "\n\n" + phys.tree_string()


class SessionBuilder:
    def __init__(self):
        self._conf = global_conf.copy()

    def config(self, key: str, value: Any) -> "SessionBuilder":
        self._conf.set(key, value)
        return self

    def get_or_create(self) -> TpuSparkSession:
        return TpuSparkSession(self._conf)


class DataFrameReader:
    """session.read.parquet(...) / .csv(...) / .orc(...) entry
    (GpuReadParquetFileFormat / GpuParquetScan analogues)."""

    def __init__(self, session: TpuSparkSession):
        self.session = session
        self._options: Dict[str, Any] = {}
        self._schema: Optional[T.Schema] = None

    def option(self, key: str, value: Any) -> "DataFrameReader":
        self._options[key] = value
        return self

    def schema(self, schema: T.Schema) -> "DataFrameReader":
        self._schema = schema
        return self

    def _scan(self, fmt: str, paths: Union[str, Sequence[str]]):
        from spark_rapids_tpu.dataframe import DataFrame
        from spark_rapids_tpu.io.discovery import (
            discover_partitions, expand_paths, infer_schema,
        )
        from spark_rapids_tpu.plan.logical import FileScan
        if isinstance(paths, str):
            paths = [paths]
        files = expand_paths(list(paths), fmt)
        schema = self._schema or infer_schema(fmt, files, self._options)
        partitions = discover_partitions(list(paths), files)
        if partitions is not None:
            part_schema, _vals = partitions
            new_fields = [f for f in part_schema.fields
                          if f.name not in set(schema.names)]
            if new_fields:
                schema = T.Schema(list(schema.fields) + new_fields)
            else:
                partitions = None
        return DataFrame(
            FileScan(fmt, files, schema, dict(self._options),
                     partitions=partitions), self.session)

    def parquet(self, *paths: str):
        return self._scan("parquet", list(paths))

    def csv(self, *paths: str):
        return self._scan("csv", list(paths))

    def orc(self, *paths: str):
        return self._scan("orc", list(paths))


def _to_host_batch(data, schema) -> HostBatch:
    import numpy as np
    if isinstance(data, HostBatch):
        return data
    if isinstance(data, dict):
        first = next(iter(data.values()), None)
        if isinstance(first, tuple) and len(first) == 2 and \
                isinstance(first[0], T.DataType):
            return HostBatch.from_pydict(data)
        # {name: values}: infer types
        out = {}
        for name, values in data.items():
            dt = _infer_dtype(values)
            out[name] = (dt, list(values))
        return HostBatch.from_pydict(out)
    if isinstance(data, (list, tuple)):
        assert schema is not None, "list-of-rows input requires a schema"
        if schema and not isinstance(schema, T.Schema):
            schema = T.Schema(schema)
        cols = {f.name: (f.dtype, [row[i] for row in data])
                for i, f in enumerate(schema.fields)}
        return HostBatch.from_pydict(cols)
    raise TypeError(f"cannot build DataFrame from {type(data)}")


def _infer_dtype(values) -> T.DataType:
    for v in values:
        if v is None:
            continue
        if isinstance(v, bool):
            return T.BOOLEAN
        if isinstance(v, int):
            return T.LONG
        if isinstance(v, float):
            return T.DOUBLE
        if isinstance(v, str):
            return T.STRING
        if isinstance(v, (list, tuple)):
            elems = [e for arr in values if arr is not None
                     for e in arr if e is not None]
            return T.ArrayType(_infer_dtype(elems) if elems else T.LONG)
    return T.STRING


def _assert_on_tpu(op, allow=("HostToDeviceExec", "CpuInMemoryScanExec",
                              "CpuFileScanExec", "FileScanV2Exec",
                              "DeviceToHostExec",
                              "CpuShuffleExchangeExec")):
    """spark.rapids.sql.test.enabled analogue
    (GpuTransitionOverrides.scala:277-322)."""
    name = type(op).__name__
    if not op.is_tpu and name not in allow:
        raise AssertionError(f"operator {name} fell back to CPU with "
                             "spark.rapids.sql.test.enabled=true")
    for c in op.children:
        _assert_on_tpu(c, allow)
