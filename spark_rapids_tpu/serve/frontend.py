"""Network front door (serve/): out-of-process serving over TCP.

:class:`ServeScheduler` gives *in-process* callers weighted fairness,
deadlines and micro-batching; everything still rode in one Python
process.  :class:`FrontDoorServer` puts a long-lived, stdlib-only
(``socketserver``) network face on that same scheduler so clients in
other processes — other languages, even — get the identical guarantees
over the newline-delimited JSON protocol of
:mod:`~spark_rapids_tpu.serve.protocol`:

* one ``ServeScheduler`` (and hence one Session, one shared plan
  cache, one device runtime) behind any number of connections — the
  second client's repeat of the first client's query compiles nothing
  (``compileCount == 0``);
* a **result cache** (:mod:`~spark_rapids_tpu.serve.resultcache`):
  a repeat query over unchanged inputs answers from catalog-registered
  spillables with zero compiles AND zero dispatches — the request
  never enters ``session.execute``;
* **sentinel-driven admission control**: before executing, the front
  door consults the history store's median/MAD wall-time aggregate for
  the query's fingerprint; a query whose *predicted* latency already
  misses its deadline is shed immediately (DeadlineExceeded taxonomy,
  counted per tenant) instead of burning device time on a doomed run —
  the serving analogue of the PR-15 regression sentinel, pointed
  forward instead of backward.

Request handling is thread-per-connection (``ThreadingTCPServer``,
daemon threads); every accept/read wait is a bounded <=0.25s slice
(``serve_forever(poll_interval=...)`` + socket timeouts in
protocol.LineChannel), honoring the R2/R3 blocking discipline.
Observability: per-request spans on the ``serve.frontend`` site,
connection/queue gauges, and per-tenant queue/inflight/deadline-miss
gauges (registered by the scheduler) in the Prometheus export.
"""

from __future__ import annotations

import socketserver
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

from spark_rapids_tpu.serve import protocol
from spark_rapids_tpu.serve.resultcache import (
    ResultCache, cache_key, result_cache,
)
from spark_rapids_tpu.serve.scheduler import DeadlineExceeded, ServeScheduler

_WAIT_SLICE_S = 0.25


def _error_class(e: BaseException) -> str:
    """The fault-taxonomy name for the wire (fault/errors discipline):
    prefer the exception's declared rapids_error_class context, fall
    back to the exception type name."""
    if isinstance(e, DeadlineExceeded):
        return "DeadlineExceeded"
    if isinstance(e, protocol.ProtocolError):
        return "ProtocolError"
    return type(e).__name__


class _Handler(socketserver.BaseRequestHandler):
    """One connection: a request/response loop until EOF."""

    def handle(self) -> None:
        server: "FrontDoorServer" = self.server.front_door  # type: ignore
        chan = protocol.LineChannel(self.request, max_line=server.max_line)
        server._conn_delta(+1)
        try:
            while not server._closing.is_set():
                try:
                    req = chan.recv(timeout=_WAIT_SLICE_S)
                except TimeoutError:
                    continue  # idle connection; re-check _closing
                except protocol.ProtocolError as e:
                    chan.send({"ok": False, "error": str(e),
                               "error_class": "ProtocolError"})
                    return  # framing is gone; the stream can't recover
                if req is None:
                    return  # clean EOF
                chan.send(server.handle_request(req))
        except OSError:
            pass  # peer vanished mid-response; nothing to tell it
        finally:
            server._conn_delta(-1)
            chan.close()


class _TCPServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True
    front_door: "FrontDoorServer"


class FrontDoorServer:
    """The serve front door: a TCP listener feeding one ServeScheduler.

    >>> server = FrontDoorServer(session)
    >>> server.start()
    >>> server.port  # 0 in conf -> ephemeral; read the bound port here
    >>> ...
    >>> server.close()

    ``scheduler`` may be passed in (tests share one with in-process
    submitters); otherwise one is built over ``session``.  Use as a
    context manager or call :meth:`close`."""

    def __init__(self, session, scheduler: Optional[ServeScheduler] = None,
                 cache: Optional[ResultCache] = None):
        from spark_rapids_tpu.config import (
            SERVE_ADMISSION_ENABLED, SERVE_ADMISSION_MAD_K,
            SERVE_ADMISSION_MIN_RUNS, SERVE_FRONTEND_HOST,
            SERVE_FRONTEND_MAX_LINE, SERVE_FRONTEND_PORT,
            SERVE_RESULT_CACHE_ENABLED, SERVE_RESULT_CACHE_MAX_BYTES,
            SERVE_RESULT_CACHE_MAX_ENTRIES,
            SERVE_RESULT_CACHE_MIN_NS_PER_BYTE,
        )
        self.session = session
        self.conf = session.conf
        self.scheduler = scheduler or ServeScheduler(session)
        self.host = SERVE_FRONTEND_HOST.get(self.conf)
        self._conf_port = SERVE_FRONTEND_PORT.get(self.conf)
        self.max_line = SERVE_FRONTEND_MAX_LINE.get(self.conf)
        self._cache_enabled = SERVE_RESULT_CACHE_ENABLED.get(self.conf)
        self.cache = cache or result_cache()
        self.cache.configure(
            SERVE_RESULT_CACHE_MAX_ENTRIES.get(self.conf),
            SERVE_RESULT_CACHE_MAX_BYTES.get(self.conf),
            SERVE_RESULT_CACHE_MIN_NS_PER_BYTE.get(self.conf))
        self._admission_enabled = SERVE_ADMISSION_ENABLED.get(self.conf)
        self._admission_min_runs = SERVE_ADMISSION_MIN_RUNS.get(self.conf)
        self._admission_mad_k = SERVE_ADMISSION_MAD_K.get(self.conf)
        self._templates: Dict[str, Any] = {}
        # prepared-statement cache: repeated SQL text reuses ONE logical
        # plan object.  The shared plan cache (serve/excache) ties entry
        # lifetime to the logical plan's liveness, so a per-request
        # parse would let the compiled executables die with each
        # response; pinning the plan here is what makes the second
        # client's compileCount == 0.  Bounded by the same conf as the
        # plan cache it feeds (serve.planCache.maxPlans).
        from spark_rapids_tpu.config import SERVE_PLAN_CACHE_MAX
        self._stmt_max = max(1, SERVE_PLAN_CACHE_MAX.get(self.conf))
        self._stmt_cache: "OrderedDict[str, Any]" = OrderedDict()
        self._lock = threading.Lock()
        self._connections = 0
        self._requests = 0
        self._admission_shed = 0
        self._admission_shed_by_tenant: Dict[str, int] = {}
        self._closing = threading.Event()
        self._tcp: Optional[_TCPServer] = None
        self._accept_thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------------

    @property
    def port(self) -> int:
        """The bound port (valid after start())."""
        return self._tcp.server_address[1] if self._tcp else self._conf_port

    def start(self) -> "FrontDoorServer":
        if self._tcp is not None:
            return self
        self.scheduler.start()
        self._tcp = _TCPServer((self.host, self._conf_port), _Handler)
        self._tcp.front_door = self
        self._accept_thread = threading.Thread(
            # poll_interval bounds the accept wait (R3 slice): close()
            # is observed within one slice
            target=lambda: self._tcp.serve_forever(
                poll_interval=_WAIT_SLICE_S),
            daemon=True, name="serve-frontend-accept")
        self._accept_thread.start()
        from spark_rapids_tpu.obs import timeseries as obs_ts
        obs_ts.register_gauge("serve.frontend.connections",
                              lambda: float(self._connections))
        obs_ts.register_gauge("serve.frontend.requests",
                              lambda: float(self._requests))
        obs_ts.register_gauge("serve.frontend.admission_shed",
                              lambda: float(self._admission_shed))
        return self

    def close(self, timeout: float = 10.0) -> None:
        """Stop accepting, then stop the scheduler.  In-flight handler
        threads notice ``_closing`` within one wait slice."""
        self._closing.set()
        if self._tcp is not None:
            self._tcp.shutdown()
            self._tcp.server_close()
        t = self._accept_thread
        if t is not None:
            deadline = time.monotonic() + timeout
            while t.is_alive() and time.monotonic() < deadline:
                t.join(_WAIT_SLICE_S)
        self.scheduler.close(timeout=timeout)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()
        return False

    # -- templates ----------------------------------------------------------

    def register_template(self, template) -> None:
        """Expose a QueryTemplate to wire clients under its key."""
        with self._lock:
            self._templates[template.key] = template

    # -- request handling ---------------------------------------------------

    def _conn_delta(self, d: int) -> None:
        with self._lock:
            self._connections += d

    def handle_request(self, req: Dict[str, Any]) -> Dict[str, Any]:
        """One wire request -> one wire response (never raises; every
        failure becomes an ``ok: false`` response)."""
        from spark_rapids_tpu.obs import events as obs_events
        with self._lock:
            self._requests += 1
        op = req.get("op")
        t0 = time.monotonic_ns()
        try:
            if op == "submit":
                resp = self._handle_submit(req)
            elif op == "stats":
                resp = {"ok": True, "scheduler": self.scheduler.stats(),
                        "frontend": self.stats()}
            elif op == "drain":
                resp = self._handle_drain(req)
            elif op == "ping":
                resp = {"ok": True}
            else:
                resp = {"ok": False, "error": f"unknown op: {op!r}",
                        "error_class": "ProtocolError"}
        except Exception as e:
            # a failed request must not take down the connection loop
            resp = {"ok": False, "error": f"{type(e).__name__}: {e}",
                    "error_class": _error_class(e)}
        t1 = time.monotonic_ns()
        obs_events.emit_span("serve.frontend", f"op_{op}", "serve",
                             t0=t0, t1=t1, ok=bool(resp.get("ok")))
        return resp

    def _handle_drain(self, req: Dict[str, Any]) -> Dict[str, Any]:
        drained = self.scheduler.drain(
            timeout=float(req.get("timeout", 60.0)))
        rt = self.session.runtime
        held = rt.semaphore.held_depth() if rt is not None else 0
        return {"ok": True, "drained": drained, "held_depth": held}

    def _plan_for_sql(self, sql: str):
        """One logical plan per (whitespace-normalized) SQL text, LRU.

        Parsing is cheap; what the reuse actually buys is plan-object
        IDENTITY — the stable anchor for the shared plan cache's weak
        entries and the result cache's id()-keyed input identity.  Note
        a view re-registered after a statement was cached keeps serving
        the old binding for that text until the entry ages out; the
        front door owns its session, so bindings are fixed for the
        server's lifetime."""
        key = " ".join(sql.split())
        with self._lock:
            plan = self._stmt_cache.get(key)
            if plan is not None:
                self._stmt_cache.move_to_end(key)
                return plan
        plan = self.session.sql(sql).plan  # parse outside the lock
        with self._lock:
            existing = self._stmt_cache.get(key)
            if existing is not None:
                return existing  # racer won; share its plan object
            self._stmt_cache[key] = plan
            while len(self._stmt_cache) > self._stmt_max:
                self._stmt_cache.popitem(last=False)
        return plan

    def _handle_submit(self, req: Dict[str, Any]) -> Dict[str, Any]:
        tenant = str(req.get("tenant", "default"))
        deadline_sec = float(req.get("deadline_sec", 0.0))
        encoding = str(req.get("encoding", "json"))
        if req.get("template") is not None:
            return self._submit_template(req, tenant, deadline_sec,
                                         encoding)
        sql = req.get("sql")
        if not isinstance(sql, str) or not sql.strip():
            raise protocol.ProtocolError("submit needs 'sql' or 'template'")
        plan = self._plan_for_sql(sql)
        key = cache_key(self.session, plan)
        use_cache = self._cache_enabled and bool(req.get("cache", True)) \
            and key[2] is not None
        if use_cache:
            hit = self.cache.fetch(key)
            if hit is not None:
                # answered without entering session.execute: zero
                # compiles, zero dispatches, zero scheduler queueing —
                # and no admission check, since the prediction models
                # the execution a hit never performs
                return {"ok": True,
                        "result": protocol.batch_to_wire(hit, encoding),
                        "metrics": {"resultCacheHits": 1,
                                    "admissionShed": 0,
                                    "compileCount": 0,
                                    "dispatchCount": 0}}
        shed = self._admission_check(key, tenant, deadline_sec)
        if shed is not None:
            return shed
        t0_ns = time.monotonic_ns()
        # wire deadline 0 means "none requested": fall back to the
        # scheduler's conf default rather than forcing deadline-free
        fut = self.scheduler.submit(
            plan, tenant=tenant,
            deadline_sec=deadline_sec if deadline_sec > 0 else None)
        out = fut.result(
            timeout=deadline_sec + 30.0 if deadline_sec > 0 else 600.0)
        wall_ns = time.monotonic_ns() - t0_ns
        if use_cache:
            # submit->result wall as the recorded compute cost: it
            # includes queueing, which is the latency a cache hit
            # actually saves the next client
            self.cache.insert(key, plan, out, wall_ns, self.conf)
        metrics = dict(fut.metrics or {})
        metrics.setdefault("resultCacheHits", 0)
        metrics.setdefault("admissionShed", 0)
        return {"ok": True,
                "result": protocol.batch_to_wire(out, encoding),
                "metrics": metrics}

    def _submit_template(self, req: Dict[str, Any], tenant: str,
                         deadline_sec: float, encoding: str
                         ) -> Dict[str, Any]:
        # template path: no result cache (each request carries fresh
        # in-memory rows, so the input identity never repeats) and no
        # admission prediction (micro-batch latency is dominated by the
        # coalescing linger, which history's per-query walls don't model)
        name = str(req.get("template"))
        with self._lock:
            template = self._templates.get(name)
        if template is None:
            raise protocol.ProtocolError(f"unknown template: {name!r}")
        batch = protocol.wire_to_batch(req.get("batch") or {})
        fut = self.scheduler.submit_micro(
            template, batch, tenant=tenant,
            deadline_sec=deadline_sec if deadline_sec > 0 else None)
        out = fut.result(
            timeout=deadline_sec + 30.0 if deadline_sec > 0 else 600.0)
        metrics = dict(fut.metrics or {})
        metrics.setdefault("resultCacheHits", 0)
        metrics.setdefault("admissionShed", 0)
        return {"ok": True,
                "result": protocol.batch_to_wire(out, encoding),
                "metrics": metrics}

    def _admission_check(self, key: Tuple[str, str, Optional[str]],
                         tenant: str, deadline_sec: float
                         ) -> Optional[Dict[str, Any]]:
        """Shed-before-execute: None to admit, or the error response
        for a query whose predicted wall already misses its deadline."""
        if not self._admission_enabled or deadline_sec <= 0:
            return None
        from spark_rapids_tpu.history import predicted_wall_ns
        pred_ns = predicted_wall_ns(
            self.conf, key[0], key[1],
            min_runs=self._admission_min_runs,
            mad_k=self._admission_mad_k)
        if pred_ns is None or pred_ns / 1e9 <= deadline_sec:
            return None
        self.scheduler.record_shed(tenant)
        with self._lock:
            self._admission_shed += 1
            self._admission_shed_by_tenant[tenant] = \
                self._admission_shed_by_tenant.get(tenant, 0) + 1
        from spark_rapids_tpu.obs import events as obs_events
        obs_events.emit_instant("serve.frontend", "admission_shed", "serve",
                                tenant=tenant, fp=key[0],
                                predicted_ms=pred_ns / 1e6,
                                deadline_ms=deadline_sec * 1e3)
        return {"ok": False,
                "error": (f"admission control: predicted wall "
                          f"{pred_ns / 1e9:.3f}s exceeds deadline "
                          f"{deadline_sec:g}s for tenant {tenant!r}"),
                "error_class": "DeadlineExceeded", "shed": True,
                "metrics": {"admissionShed": 1, "resultCacheHits": 0}}

    # -- stats --------------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out = {
                "connections": self._connections,
                "requests": self._requests,
                "admission_shed": self._admission_shed,
                "admission_shed_by_tenant":
                    dict(self._admission_shed_by_tenant),
            }
        out.update(self.cache.stats())
        return out
