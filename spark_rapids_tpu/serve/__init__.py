"""Serving runtime: a multi-tenant scheduler layered above
``session.execute`` (serve.scheduler), the process-wide shared
plan/executable cache it amortizes compiles through (serve.excache),
and micro-query batching for template workloads (serve.batching).
See docs/serving.md.
"""

from spark_rapids_tpu.serve.batching import MicroBatcher, QueryTemplate
from spark_rapids_tpu.serve.excache import SharedPlanCache, shared_plan_cache
from spark_rapids_tpu.serve.scheduler import (
    DeadlineExceeded, ServeFuture, ServeScheduler,
)

__all__ = [
    "DeadlineExceeded",
    "MicroBatcher",
    "QueryTemplate",
    "ServeFuture",
    "ServeScheduler",
    "SharedPlanCache",
    "shared_plan_cache",
]
