"""Serving runtime: a multi-tenant scheduler layered above
``session.execute`` (serve.scheduler), the process-wide shared
plan/executable cache it amortizes compiles through (serve.excache),
micro-query batching for template workloads (serve.batching), and the
out-of-process network front door (serve.frontend / serve.protocol)
with its final-result cache (serve.resultcache).
See docs/serving.md.
"""

from spark_rapids_tpu.serve.batching import MicroBatcher, QueryTemplate
from spark_rapids_tpu.serve.excache import SharedPlanCache, shared_plan_cache
from spark_rapids_tpu.serve.frontend import FrontDoorServer
from spark_rapids_tpu.serve.protocol import FrontDoorClient, FrontDoorError
from spark_rapids_tpu.serve.resultcache import (
    ResultCache, cache_key, result_cache,
)
from spark_rapids_tpu.serve.scheduler import (
    DeadlineExceeded, ServeFuture, ServeScheduler,
)

__all__ = [
    "DeadlineExceeded",
    "FrontDoorClient",
    "FrontDoorError",
    "FrontDoorServer",
    "MicroBatcher",
    "QueryTemplate",
    "ResultCache",
    "ServeFuture",
    "ServeScheduler",
    "SharedPlanCache",
    "cache_key",
    "result_cache",
    "shared_plan_cache",
]
