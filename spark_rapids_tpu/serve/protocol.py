"""Front-door wire protocol (serve/): newline-delimited JSON over TCP.

One request per line, one response per line, UTF-8 JSON with no
embedded newlines — trivially speakable from any language (`nc` included)
while still carrying columnar payloads.  Requests and responses share
one batch encoding so a client can both send template rows and receive
results:

* ``json`` — ``{"encoding": "json", "names": [...], "types": [...],
  "data": {col: [values...]}}``; type names are the engine's
  ``DataType.name`` strings (``long``, ``double``, ``string``, ...),
  values are plain JSON scalars with ``null`` for SQL NULL.
* ``arrow`` — the same ``names``/``types`` plus ``ipc_b64``: a
  base64-encoded Arrow IPC stream.  Used only when pyarrow is
  importable on both ends; the server silently falls back to ``json``
  when a client asks for arrow it cannot produce.

Requests (``op`` field): ``submit`` (``sql`` text or ``template`` name
+ ``batch``, with ``tenant``, ``deadline_sec``, ``cache``,
``encoding``), ``stats``, ``drain``, ``ping``.  Responses carry
``ok``; a submit response adds ``result`` (encoded batch) and
``metrics`` (the query's camelCase metrics dict plus the front door's
``resultCacheHits``/``admissionShed``), or on failure ``error`` +
``error_class`` (the fault taxonomy name — ``DeadlineExceeded`` for
deadline/admission sheds).

Blocking discipline: every socket read waits in bounded <=0.25s slices
(lint rule R3's contract) under an overall per-call deadline, so a
drain or watchdog async-exc can always land on a serving thread.
"""

from __future__ import annotations

import base64
import json
import socket
import time
from typing import Any, Dict, Optional, Tuple

from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import HostBatch

_WAIT_SLICE_S = 0.25
DEFAULT_MAX_LINE = 64 << 20


class ProtocolError(RuntimeError):
    """Malformed or oversized protocol traffic."""


class FrontDoorError(RuntimeError):
    """A server-side failure relayed to the client.

    ``error_class`` carries the server's fault-taxonomy class name so
    callers can branch without string-matching messages."""

    def __init__(self, message: str, error_class: str = ""):
        super().__init__(message)
        self.error_class = error_class


def have_arrow() -> bool:
    try:
        import pyarrow  # noqa: F401
        return True
    except ImportError:
        return False


# -- batch <-> wire ----------------------------------------------------------


def batch_to_wire(batch: HostBatch, encoding: str = "json"
                  ) -> Dict[str, Any]:
    """Encode a HostBatch for one protocol line."""
    names = list(batch.schema.names)
    type_names = [f.dtype.name for f in batch.schema.fields]
    if encoding == "arrow" and have_arrow():
        import pyarrow as pa
        data = batch.to_pydict()
        table = pa.table({n: data[n] for n in names})
        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, table.schema) as writer:
            writer.write_table(table)
        return {"encoding": "arrow", "names": names, "types": type_names,
                "ipc_b64": base64.b64encode(
                    sink.getvalue().to_pybytes()).decode("ascii")}
    return {"encoding": "json", "names": names, "types": type_names,
            "data": batch.to_pydict()}


def wire_to_batch(obj: Dict[str, Any]) -> HostBatch:
    """Decode one protocol batch object back into a HostBatch."""
    names = obj.get("names") or []
    type_names = obj.get("types") or []
    if len(names) != len(type_names):
        raise ProtocolError("batch names/types length mismatch")
    if obj.get("encoding") == "arrow":
        import pyarrow as pa
        buf = base64.b64decode(obj["ipc_b64"])
        with pa.ipc.open_stream(pa.BufferReader(buf)) as reader:
            table = reader.read_all()
        data = {c: table.column(c).to_pylist() for c in table.column_names}
    else:
        data = obj.get("data") or {}
    return HostBatch.from_pydict({
        name: (T.type_from_name(tn), data.get(name, []))
        for name, tn in zip(names, type_names)})


# -- line transport ----------------------------------------------------------


def encode_line(obj: Dict[str, Any]) -> bytes:
    return json.dumps(obj, separators=(",", ":")).encode("utf-8") + b"\n"


class LineChannel:
    """Newline-delimited JSON over one socket, both directions.

    Reads wait in bounded slices (socket timeout = 0.25s) under the
    per-call ``timeout`` so the owning thread stays interruptible."""

    def __init__(self, sock: socket.socket,
                 max_line: int = DEFAULT_MAX_LINE):
        self._sock = sock
        self._buf = bytearray()
        self._max_line = max(1024, int(max_line))
        self._sock.settimeout(_WAIT_SLICE_S)

    def send(self, obj: Dict[str, Any]) -> None:
        self._sock.sendall(encode_line(obj))

    def recv(self, timeout: float = 60.0) -> Optional[Dict[str, Any]]:
        """One decoded message; None on clean EOF; TimeoutError past
        ``timeout``; ProtocolError on junk or an oversized line."""
        deadline = time.monotonic() + max(0.0, float(timeout))
        while True:
            nl = self._buf.find(b"\n")
            if nl >= 0:
                raw = bytes(self._buf[:nl])
                del self._buf[:nl + 1]
                if not raw.strip():
                    continue
                try:
                    msg = json.loads(raw.decode("utf-8"))
                except (UnicodeDecodeError, json.JSONDecodeError) as e:
                    raise ProtocolError(f"bad protocol line: {e}")
                if not isinstance(msg, dict):
                    raise ProtocolError("protocol line is not an object")
                return msg
            if len(self._buf) > self._max_line:
                raise ProtocolError(
                    f"protocol line exceeds {self._max_line} bytes")
            try:
                chunk = self._sock.recv(1 << 16)
            except socket.timeout:
                chunk = None
            except OSError:
                return None  # peer reset / socket closed under us
            if chunk == b"":
                return None  # clean EOF
            if chunk:
                self._buf.extend(chunk)
            elif time.monotonic() >= deadline:
                raise TimeoutError(
                    f"no complete protocol line within {timeout:g}s")

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


# -- client ------------------------------------------------------------------


class FrontDoorClient:
    """Out-of-process client of one serve front door.

    >>> c = FrontDoorClient("127.0.0.1", port)
    >>> rows, metrics = c.submit_sql("SELECT k, SUM(v) AS s "
    ...                              "FROM events GROUP BY k")
    >>> c.close()

    One request in flight per client (the protocol is strictly
    request/response per connection); open one client per concurrent
    stream.  Context-manager friendly."""

    def __init__(self, host: str, port: int, timeout: float = 120.0,
                 max_line: int = DEFAULT_MAX_LINE):
        self._timeout = float(timeout)
        sock = socket.create_connection((host, port), timeout=10.0)
        self._chan = LineChannel(sock, max_line=max_line)

    def _rpc(self, req: Dict[str, Any],
             timeout: Optional[float] = None) -> Dict[str, Any]:
        self._chan.send(req)
        resp = self._chan.recv(
            self._timeout if timeout is None else timeout)
        if resp is None:
            raise FrontDoorError("server closed the connection",
                                 "ConnectionClosed")
        if not resp.get("ok", False):
            msg = str(resp.get("error", "front door error"))
            klass = str(resp.get("error_class", ""))
            if klass == "DeadlineExceeded":
                from spark_rapids_tpu.serve.scheduler import DeadlineExceeded
                raise DeadlineExceeded(msg)
            raise FrontDoorError(msg, klass)
        return resp

    def submit_sql(self, sql: str, tenant: str = "default",
                   deadline_sec: float = 0.0, cache: bool = True,
                   encoding: str = "json",
                   timeout: Optional[float] = None
                   ) -> Tuple[HostBatch, Dict[str, Any]]:
        """Execute ``sql`` on the server; (rows, metrics)."""
        resp = self._rpc({"op": "submit", "sql": sql, "tenant": tenant,
                          "deadline_sec": float(deadline_sec),
                          "cache": bool(cache), "encoding": encoding},
                         timeout=timeout)
        return wire_to_batch(resp["result"]), dict(resp.get("metrics") or {})

    def submit_template(self, template: str, batch: HostBatch,
                        tenant: str = "default", deadline_sec: float = 0.0,
                        encoding: str = "json",
                        timeout: Optional[float] = None
                        ) -> Tuple[HostBatch, Dict[str, Any]]:
        """Run a server-registered micro-query template over ``batch``
        (eligible for server-side coalescing); (rows, metrics)."""
        resp = self._rpc({"op": "submit", "template": template,
                          "batch": batch_to_wire(batch, encoding),
                          "tenant": tenant,
                          "deadline_sec": float(deadline_sec),
                          "encoding": encoding}, timeout=timeout)
        return wire_to_batch(resp["result"]), dict(resp.get("metrics") or {})

    def stats(self) -> Dict[str, Any]:
        resp = self._rpc({"op": "stats"})
        return {"scheduler": resp.get("scheduler", {}),
                "frontend": resp.get("frontend", {})}

    def drain(self, timeout: float = 60.0) -> Dict[str, Any]:
        resp = self._rpc({"op": "drain", "timeout": float(timeout)},
                         timeout=timeout + 30.0)
        return {"drained": bool(resp.get("drained", False)),
                "held_depth": int(resp.get("held_depth", 0))}

    def ping(self) -> bool:
        return bool(self._rpc({"op": "ping"}).get("ok", False))

    def close(self) -> None:
        self._chan.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
