"""Process-wide shared physical-plan / executable cache.

PR 2 introduced the plan-fingerprint memo so re-executing the same
DataFrame reuses physical exec instances and therefore their
``jax.jit`` caches; until this PR the memo lived per session
(``session._plan_cache``), so N sessions serving the same query shape
each paid the full compile tax.  This module lifts the memo to a
lock-guarded process singleton: the compiled executables live on the
physical plan's op instances (``plan/pipeline._stage_program`` caches
jits on the root op), so sharing the plan object shares every
executable — the second session's warm execution reports
``compileCount == 0``.

Keying is (plan fingerprint, plan-relevant conf state); see
``session.plan_physical`` for what the conf state excludes.  Entries
are LRU-bounded (``spark.rapids.sql.tpu.serve.planCache.maxPlans``)
because cached plans pin their source batches.

Metrics stay attributed per query: the cache only shares PLANS; every
execution still opens its own QueryScope and counts its own dispatches
(a shared-cache hit shows up precisely as ``compileCount == 0``).

Thread safety: lookups and inserts hold the cache lock; plan BUILDING
(``TpuOverrides.apply``) runs outside it so a slow lowering cannot
stall unrelated sessions.  Two sessions racing to build the same key
both build; the first insert wins and the loser adopts the winner's
plan (build is pure planning — no device state — so discarding the
duplicate is free).
"""

from __future__ import annotations

import threading
import weakref
from collections import OrderedDict
from typing import Any, Callable, Tuple

DEFAULT_MAX_PLANS = 256


class SharedPlanCache:
    """Fingerprint -> (logical plan ref, conf state, physical plan,
    explain) with LRU eviction, shared by every session in the process.

    Entry lifetime is tied to the LOGICAL plan's liveness: the entry
    holds only a weak reference to the root logical node, and dead
    entries are swept on every access.  A serving client (DataFrame,
    QueryTemplate bound group, bench probe) keeps its plan object
    alive, so its entry — and the compiled executables on the physical
    plan — persist across sessions; a batch/test workload that builds
    hundreds of one-shot plans releases each entry (physical plan,
    executables, pinned source batches) as soon as the plan goes out of
    scope, instead of pinning ``maxPlans`` worth of dead queries for
    the life of the process.  This is also what keeps the id()-keyed
    plan fingerprint sound: an entry can never outlive the batch
    objects its fingerprint identifies, so a recycled ``id()`` cannot
    produce a false hit."""

    def __init__(self, max_plans: int = DEFAULT_MAX_PLANS):
        self._lock = threading.Lock()
        self._plans: "OrderedDict[Any, Tuple]" = OrderedDict()
        self._max = max(1, int(max_plans))
        self.hits = 0
        self.misses = 0

    @staticmethod
    def _ref(plan: Any):
        try:
            return weakref.ref(plan)
        except TypeError:
            # not weakrefable: fall back to a strong holder with the
            # same call signature (entry then lives until LRU eviction)
            return lambda: plan

    def _sweep_locked(self) -> None:
        dead = [k for k, ent in self._plans.items() if ent[0]() is None]
        for k in dead:
            del self._plans[k]

    def set_max_plans(self, max_plans: int) -> None:
        with self._lock:
            self._max = max(1, int(max_plans))
            self._sweep_locked()
            while len(self._plans) > self._max:
                self._plans.popitem(last=False)

    def get_or_build(self, key: Any, conf_state: Tuple,
                     builder: Callable[[], Tuple[Any, Any, str]]):
        """Return ``(phys, explain, hit)`` for ``key``; on miss call
        ``builder() -> (plan, phys, explain)`` outside the lock and
        insert first-writer-wins.

        The stored key is ``(key, conf_state)``: two sessions with
        different plan-relevant conf alternating over the same
        fingerprint each keep their own entry instead of thrashing
        one slot (and re-compiling on every alternation)."""
        full = (key, conf_state)
        with self._lock:
            self._sweep_locked()
            ent = self._plans.get(full)
            if ent is not None:
                self._plans.move_to_end(full)
                self.hits += 1
                return ent[2], ent[3], True
        plan, phys, explain = builder()
        with self._lock:
            ent = self._plans.get(full)
            if ent is not None and ent[0]() is not None:
                # a concurrent builder won the race: use ITS plan so
                # both sessions share one set of executables
                self._plans.move_to_end(full)
                self.hits += 1
                return ent[2], ent[3], True
            self.misses += 1
            self._plans[full] = (self._ref(plan), conf_state, phys, explain)
            self._plans.move_to_end(full)
            while len(self._plans) > self._max:
                self._plans.popitem(last=False)
        return phys, explain, False

    def stats(self):
        with self._lock:
            self._sweep_locked()
            return {"plan_cache_entries": len(self._plans),
                    "plan_cache_hits": self.hits,
                    "plan_cache_misses": self.misses}

    def clear(self) -> None:
        with self._lock:
            self._plans.clear()

    def __len__(self):
        with self._lock:
            return len(self._plans)


_SHARED: SharedPlanCache = SharedPlanCache()


def shared_plan_cache() -> SharedPlanCache:
    """The process singleton every ``session.plan_physical`` consults."""
    return _SHARED
