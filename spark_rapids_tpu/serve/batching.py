"""Micro-query batching: coalesce same-shape template queries into one
dispatch.

Serving workloads are dominated by *template* queries — the same
filter/project shape over a small per-request batch of rows.  Executing
each individually pays the full per-dispatch overhead (staging, device
admission, result assembly) for a handful of rows.  This module
coalesces queued queries that resolve to the same **group** —
``(template key, input schema signature, row bucket)`` — into a single
execution: rows concatenated with a hidden ``__serve_qid`` column,
one ``session.execute``, results split back per caller bit-identically.

Executable reuse across dispatches is by construction: every group owns
ONE mutable batches-holder list bound into ONE logical plan.  Each
dispatch replaces ``holder[0]`` with the newly combined batch —
``InMemoryScan`` (and its physical ``CpuInMemoryScanExec``) hold the
list *by reference* and read it at ``partitions()`` time, and
``plan_fingerprint`` keys batch lists by identity, so the fingerprint
is constant across dispatches: every dispatch after the first hits the
shared plan cache, and when the combined rows land in the same bucket
the compiled stage program is reused too (``compileCount == 0``).

Correctness contract: templates must be **row-wise and
order-preserving** (filter / project / with_column).  Both preserve
input row order, so the concatenated queries' qid blocks stay
contiguous in the output and the split-back is a pair of binary
searches per caller.  The qid column is threaded through the template's
plan mechanically (:func:`_inject_qid` appends a passthrough reference
to every ``Project``); templates containing any other operator —
aggregates reduce across callers' rows, sorts interleave them — are
rejected at bind time with a clear error.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import BUCKETS, HostBatch, HostColumn

#: Hidden column carrying each row's originating query id through the
#: batched plan; appended last at bind time, stripped before results
#: return to callers.
QID_COLUMN = "__serve_qid"


class QueryTemplate:
    """A named, reusable query shape for :meth:`ServeScheduler.submit_micro`.

    ``build`` maps a scan DataFrame (schema = the submitted batch's
    schema) to the result DataFrame using only row-wise,
    order-preserving operations (``filter`` / ``select`` /
    ``with_column``).  ``key`` identifies the template across
    submissions — two submissions coalesce only when their keys,
    input schemas and row buckets all match."""

    def __init__(self, key: str, build: Callable[[Any], Any]):
        self.key = str(key)
        self.build = build

    def __repr__(self):
        return f"QueryTemplate({self.key!r})"


def schema_signature(schema: T.Schema) -> Tuple:
    """Hashable identity of an input schema for group matching."""
    return tuple((f.name, str(f.dtype), bool(f.nullable))
                 for f in schema.fields)


def group_key(template: QueryTemplate, batch: HostBatch) -> Tuple:
    """The coalescing identity: same template, same input schema, same
    row bucket (so combined sizes stay near one bucket step)."""
    return (template.key, schema_signature(batch.schema),
            BUCKETS.rows(max(1, batch.num_rows)))


def _inject_qid(plan):
    """Rewrite a row-wise logical plan so :data:`QID_COLUMN` flows from
    the scan to the output (appended as the LAST output column).

    ``Filter`` passes every input column through untouched; ``Project``
    gains a trailing passthrough reference.  Any other node breaks the
    per-row caller attribution micro-batching depends on and is
    rejected."""
    from spark_rapids_tpu.exprs.base import ColumnRef
    from spark_rapids_tpu.plan import logical as L
    if isinstance(plan, L.InMemoryScan):
        # the bound scan already carries the qid column (appended last)
        return plan
    if isinstance(plan, L.Filter):
        return L.Filter(plan.condition, _inject_qid(plan.children[0]))
    if isinstance(plan, L.Project):
        child = _inject_qid(plan.children[0])
        if QID_COLUMN in plan.names:
            return L.Project(plan.exprs, plan.names, child)
        return L.Project(
            plan.exprs + [ColumnRef(QID_COLUMN, T.LONG, False)],
            plan.names + [QID_COLUMN], child)
    raise ValueError(
        f"micro-batch template produced a {type(plan).__name__} node: "
        "templates must be row-wise and order-preserving "
        "(filter/select/with_column only) so batched callers' rows "
        "cannot mix")


def _with_qid_column(batch: HostBatch, qid: int) -> HostBatch:
    """``batch`` plus a constant int64 qid column appended last."""
    n = batch.num_rows
    col = HostColumn(T.LONG, np.full(n, qid, dtype=np.int64),
                     np.ones(n, dtype=np.bool_))
    schema = T.Schema(list(batch.schema.fields) + [T.Field(QID_COLUMN,
                                                           T.LONG, False)])
    return HostBatch(schema, list(batch.columns) + [col])


def _strip_qid(batch: HostBatch) -> HostBatch:
    """Drop the trailing qid column before returning rows to a caller."""
    assert batch.schema.fields[-1].name == QID_COLUMN
    return HostBatch(T.Schema(list(batch.schema.fields[:-1])),
                     list(batch.columns[:-1]))


class BoundGroup:
    """One group's bound state: the mutable batches holder and the
    qid-threaded logical plan built over it (built ONCE; reused —
    identity-stable — for every dispatch of the group)."""

    def __init__(self, session, template: QueryTemplate,
                 schema: T.Schema):
        from spark_rapids_tpu.dataframe import DataFrame
        from spark_rapids_tpu.plan.logical import InMemoryScan
        qid_schema = T.Schema(list(schema.fields)
                              + [T.Field(QID_COLUMN, T.LONG, False)])
        #: ONE batch object, REFILLED in place per dispatch
        #: (plan_fingerprint keys it by identity, so the fingerprint —
        #: and with it the shared-plan-cache entry and its compiled
        #: stages — survives across dispatches).  Safe because
        #: dispatches are serialized per group and the engine's
        #: id-keyed batch maps are per-execution transients.
        self._batch = HostBatch(qid_schema, [
            HostColumn(f.dtype,
                       np.empty(0, dtype=object) if (f.dtype.is_string
                                                     or f.dtype.is_array)
                       else np.empty(0, dtype=f.dtype.np_dtype),
                       np.empty(0, dtype=np.bool_))
            for f in qid_schema.fields])
        self.holder: List[HostBatch] = [self._batch]
        scan = InMemoryScan(self.holder, qid_schema, num_partitions=1)
        built = template.build(DataFrame(scan, session))
        self.plan = _inject_qid(built.plan)
        self._lock = threading.Lock()

    def dispatch(self, session, requests: List[Tuple[int, HostBatch]]):
        """Execute one coalesced dispatch for ``requests`` (``(qid,
        batch)`` pairs, any order) and return ``({qid: HostBatch},
        metrics)``."""
        # ascending qid order keeps the output qid column non-decreasing
        # (row-wise plans preserve row order), so the per-caller
        # split-back is a binary search
        requests = sorted(requests, key=lambda r: r[0])
        combined = HostBatch.concat(
            [_with_qid_column(b, qid) for qid, b in requests])
        with self._lock:
            # one dispatch at a time per group: the holder batch is
            # shared state and the plan (hence its compiled stages) is
            # bound to it by reference — refill, don't replace
            self._batch.columns = combined.columns
            self._batch.num_rows = combined.num_rows
            out, metrics = session.execute_with_metrics(self.plan)
            qids = np.asarray(out.columns[-1].values, dtype=np.int64) \
                if out.num_rows else np.empty(0, dtype=np.int64)
            results: Dict[int, HostBatch] = {}
            for qid, _b in requests:
                lo = int(np.searchsorted(qids, qid, side="left"))
                hi = int(np.searchsorted(qids, qid, side="right"))
                results[qid] = _strip_qid(out.slice(lo, hi - lo))
        return results, metrics


#: Process-wide bound-group registry: like the shared plan cache, the
#: binding (holder batch + qid-threaded plan + its compiled stages) is
#: identity-keyed state, so every scheduler serving the same template
#: group must share ONE BoundGroup or each would recompile from scratch.
_GROUPS: Dict[Tuple, BoundGroup] = {}
_GROUPS_LOCK = threading.Lock()


class MicroBatcher:
    """Bound-group front end for one scheduler: resolves group keys to
    the process-shared :class:`BoundGroup` bindings and tracks this
    scheduler's own coalescing counters."""

    def __init__(self, session):
        self.session = session
        self._lock = threading.Lock()
        #: queries that rode a shared dispatch (batch size >= 2)
        self.batched_queries = 0
        self.dispatches = 0

    def bind(self, template: QueryTemplate, key: Tuple,
             schema: T.Schema) -> BoundGroup:
        with _GROUPS_LOCK:
            grp = _GROUPS.get(key)
            if grp is not None:
                return grp
        # build outside the registry lock (planning can be slow); ties
        # broken first-insert-wins like the shared plan cache
        grp = BoundGroup(self.session, template, schema)
        with _GROUPS_LOCK:
            return _GROUPS.setdefault(key, grp)

    def run(self, grp: BoundGroup,
            requests: List[Tuple[int, HostBatch]]):
        results, metrics = grp.dispatch(self.session, requests)
        with self._lock:
            self.dispatches += 1
            if len(requests) > 1:
                self.batched_queries += len(requests)
        return results, metrics
