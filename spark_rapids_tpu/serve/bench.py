"""Serving benchmark core: the workload behind ``tools/rapidsserve.py``
and the ``serve`` lane of ``tools/bench.py``.

The lane answers the serving runtime's three headline claims with one
deterministic template workload (filter+project over per-request row
batches, round-robined across tenants):

1. **Concurrent beats serial**: the same queries served through the
   scheduler (N runners, micro-batching on) finish in less wall time
   than strictly one-at-a-time submission (``serve_vs_serial > 1`` with
   ``serve_batched_queries > 0``) — while staying bit-identical
   (``serve_parity``).
2. **The executable cache is process-wide**: a second session executing
   the same plan reports ``compileCount == 0``
   (``serve_second_session_compiles``).
3. **Tenancy is observable**: per-tenant completed/failed/deadline
   counts and p50/p99 latencies roll up into the result
   (``serve_tenants``).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import HostBatch


def _request_batch(i: int, rows: int) -> HostBatch:
    """Deterministic per-request rows (seeded by the request index)."""
    base = i * 1315423911 % 1000003
    xs = [(base + 7 * j) % 1000 for j in range(rows)]
    ys = [float((base + 3 * j) % 97) for j in range(rows)]
    return HostBatch.from_pydict({"x": (T.LONG, xs),
                                  "y": (T.DOUBLE, ys)})


def _rows_sorted(batch: HostBatch) -> List[tuple]:
    """Row tuples in sorted order (aggregation output order is not
    deterministic across partition schedules)."""
    cols = batch.to_pydict()
    return sorted(zip(*[cols[name] for name in batch.schema.names]))


def _template():
    from spark_rapids_tpu.serve.batching import QueryTemplate
    return QueryTemplate(
        "bench-filter-project",
        lambda df: df.filter("x % 2 = 0").select("x", "y"))


def run_serve_bench(queries: int = 32, rows: int = 512,
                    tenants: Optional[Dict[str, float]] = None,
                    fault: str = "", deadline_sec: float = 0.0,
                    max_concurrency: int = 2,
                    conf=None) -> Dict[str, Any]:
    """Run the serving workload; returns the ``serve_*`` metric dict."""
    from spark_rapids_tpu.session import TpuSparkSession
    from spark_rapids_tpu.serve.scheduler import ServeScheduler
    tenants = tenants or {"a": 2.0, "b": 1.0}
    builder = TpuSparkSession.builder()
    if conf is not None:
        for k, v in conf._settings.items():
            builder.config(k, v)
    for name, weight in tenants.items():
        builder.config(
            f"spark.rapids.sql.tpu.serve.tenant.{name}.weight", str(weight))
    if fault:
        builder.config("spark.rapids.sql.tpu.faults.spec", fault)
    builder.config("spark.rapids.sql.tpu.serve.maxConcurrency",
                   str(max_concurrency))
    session = builder.get_or_create()
    tmpl = _template()
    tenant_names = sorted(tenants)
    batches = [_request_batch(i, rows) for i in range(queries)]

    # plain (non-micro) lane: a two-partition aggregation (multiple
    # dispatches per query), so a per-query fault spec like
    # dispatch:oom@2 actually fires mid-query and must be absorbed by
    # the recovery ladder without wrong rows
    from spark_rapids_tpu.dataframe import DataFrame
    from spark_rapids_tpu.plan.logical import InMemoryScan
    n = max(rows, 64)
    plain_parts = [HostBatch.from_pydict({
        "k": (T.LONG, [(p * n + j) % 5 for j in range(n)]),
        "v": (T.LONG, [(p * n + 3 * j) % 997 for j in range(n)]),
    }) for p in range(2)]
    plain_df = DataFrame(
        InMemoryScan(plain_parts, plain_parts[0].schema, num_partitions=2),
        session).group_by("k").sum("v")
    plain_expected, _pm = session.execute_with_metrics(plain_df.plan)
    plain_queries = max(2, queries // 4)

    # -- serial baseline: same template path, one at a time (no overlap,
    # no coalescing) --------------------------------------------------------
    serial_sched = ServeScheduler(session, max_concurrency=1)
    serial_sched._batch_enabled = False
    # warm the executables outside both timed phases so the comparison
    # measures serving, not first-compile
    serial_sched.submit_micro(tmpl, batches[0]).result(timeout=120)
    t0 = time.monotonic()
    serial_out: List[HostBatch] = []
    for i, b in enumerate(batches):
        fut = serial_sched.submit_micro(
            tmpl, b, tenant=tenant_names[i % len(tenant_names)],
            deadline_sec=deadline_sec)
        serial_out.append(fut.result(timeout=120))
    for i in range(plain_queries):
        serial_sched.submit(
            plain_df, tenant=tenant_names[i % len(tenant_names)],
            deadline_sec=deadline_sec).result(timeout=120)
    serial_wall = time.monotonic() - t0
    serial_sched.close()

    # -- concurrent served phase: one unmeasured pass compiles the
    # coalesced-bucket programs, the measured pass is steady-state
    # serving (the regime the scheduler exists for) ------------------------
    warm = ServeScheduler(session, max_concurrency=max_concurrency)
    for f in [warm.submit_micro(
            tmpl, b, tenant=tenant_names[i % len(tenant_names)])
            for i, b in enumerate(batches)]:
        f.result(timeout=120)
    warm.close()
    sched = ServeScheduler(session, max_concurrency=max_concurrency,
                           autostart=False)
    futs = [sched.submit_micro(
        tmpl, b, tenant=tenant_names[i % len(tenant_names)],
        deadline_sec=deadline_sec) for i, b in enumerate(batches)]
    plain_futs = [sched.submit(
        plain_df, tenant=tenant_names[i % len(tenant_names)],
        deadline_sec=deadline_sec) for i in range(plain_queries)]
    t0 = time.monotonic()
    sched.start()
    results = [f.result(timeout=120) for f in futs]
    plain_results = [f.result(timeout=120) for f in plain_futs]
    wall = time.monotonic() - t0
    stats = sched.stats()
    sched.close()

    parity = all(a.to_pydict() == b.to_pydict()
                 for a, b in zip(serial_out, results))
    expected_rows = _rows_sorted(plain_expected)
    parity = parity and all(_rows_sorted(r) == expected_rows
                            for r in plain_results)
    fault_metrics = [f.metrics for f in futs + plain_futs
                     if f.metrics is not None]
    faults_injected = sum(m.get("faultsInjected", 0)
                          for m in fault_metrics)
    retries = sum(m.get("retryCount", 0) for m in fault_metrics)

    # -- shared executable cache: a second session, same plan object ---
    probe = session.create_dataframe(
        {"x": (T.LONG, list(range(rows)))}).filter("x > 1").select("x")
    _out, _m = session.execute_with_metrics(probe.plan)
    second = TpuSparkSession(session.conf.copy())
    _out2, m2 = second.execute_with_metrics(probe.plan)

    total = queries + plain_queries
    return {
        "serve_queries": total,
        "serve_plain_queries": plain_queries,
        "serve_rows_per_query": rows,
        "serve_wall_s": round(wall, 4),
        "serve_serial_wall_s": round(serial_wall, 4),
        "serve_queries_per_sec": round(total / wall, 2) if wall else 0.0,
        "serve_vs_serial": round(serial_wall / wall, 3) if wall else 0.0,
        "serve_p50_ms": round(stats["p50_ms"], 3),
        "serve_p99_ms": round(stats["p99_ms"], 3),
        "serve_batched_queries": stats["batched_queries"],
        "serve_micro_dispatches": stats["micro_dispatches"],
        "serve_completed": stats["completed"],
        "serve_failed": stats["failed"],
        "serve_deadline_exceeded": stats["deadline_exceeded"],
        "serve_faults_injected": faults_injected,
        "serve_retries": retries,
        "serve_parity": bool(parity),
        "serve_second_session_compiles": m2["compileCount"],
        "serve_plan_cache_hits": stats["plan_cache_hits"],
        "serve_tenants": stats["tenants"],
    }


# -- network front-door lane -------------------------------------------------


FRONTEND_VIEW = "bench_events"
FRONTEND_SQLS = [
    f"SELECT k, SUM(v) AS s FROM {FRONTEND_VIEW} "
    f"WHERE v < {c} GROUP BY k"
    for c in (700, 800, 900, 997)
]


def frontend_demo_session(tenants: Optional[Dict[str, float]] = None,
                          history_dir: str = "", rows: int = 4096,
                          conf=None):
    """A session with the deterministic front-door demo view registered
    — shared by this lane, ``rapidsserve --server`` and the CI smoke so
    every client speaks the same schema."""
    from spark_rapids_tpu.dataframe import DataFrame
    from spark_rapids_tpu.plan.logical import InMemoryScan
    from spark_rapids_tpu.session import TpuSparkSession
    tenants = tenants or {"a": 2.0, "b": 1.0}
    builder = TpuSparkSession.builder()
    if conf is not None:
        for k, v in conf._settings.items():
            builder.config(k, v)
    for name, weight in tenants.items():
        builder.config(
            f"spark.rapids.sql.tpu.serve.tenant.{name}.weight", str(weight))
    if history_dir:
        builder.config("spark.rapids.sql.tpu.history.dir", history_dir)
    session = builder.get_or_create()
    n = max(64, rows // 2)
    parts = [HostBatch.from_pydict({
        "k": (T.LONG, [(p * n + j) % 5 for j in range(n)]),
        "v": (T.LONG, [(p * n + 3 * j) % 997 for j in range(n)]),
    }) for p in range(2)]
    session.register_view(FRONTEND_VIEW, DataFrame(
        InMemoryScan(parts, parts[0].schema, num_partitions=2), session))
    return session


def run_frontend_bench(queries: int = 24, rows: int = 4096,
                       tenants: Optional[Dict[str, float]] = None,
                       max_concurrency: int = 2,
                       conf=None) -> Dict[str, Any]:
    """The network lane: the demo workload through a real TCP front
    door (serve/frontend.py), client threads on real sockets.  Covers
    the PR-16 headline claims: socket results bit-identical to
    in-process, a second client connection compiling nothing, a warm
    repeat answering from the result cache with zero dispatches, and a
    sentinel-predicted deadline miss shed before executing."""
    import shutil
    import tempfile
    import threading
    from spark_rapids_tpu.serve.frontend import FrontDoorServer
    from spark_rapids_tpu.serve.protocol import FrontDoorClient
    from spark_rapids_tpu.serve.resultcache import result_cache
    from spark_rapids_tpu.serve.scheduler import DeadlineExceeded
    tenants = tenants or {"a": 2.0, "b": 1.0}
    tenant_names = sorted(tenants)
    hist = tempfile.mkdtemp(prefix="rapids-frontend-bench-")
    try:
        session = frontend_demo_session(tenants, history_dir=hist,
                                        rows=rows, conf=conf)
        expected = {
            sql: _rows_sorted(session.execute_with_metrics(
                session.sql(sql).plan)[0])
            for sql in FRONTEND_SQLS}
        result_cache().clear()
        from spark_rapids_tpu.serve.scheduler import ServeScheduler
        server = FrontDoorServer(session, scheduler=ServeScheduler(
            session, max_concurrency=max_concurrency))
        server.start()
        host, port = "127.0.0.1", server.port

        def submit(client, i, cache=False, deadline=0.0):
            return client.submit_sql(
                FRONTEND_SQLS[i % len(FRONTEND_SQLS)],
                tenant=tenant_names[i % len(tenant_names)],
                cache=cache, deadline_sec=deadline)

        # warm pass (cache=false): compiles every plan AND appends the
        # history records the admission predictor needs (>= minRuns per
        # fingerprint) — a result-cache hit skips execution entirely and
        # would leave the baseline empty
        with FrontDoorClient(host, port) as warm_client:
            for _r in range(3):
                for i in range(len(FRONTEND_SQLS)):
                    submit(warm_client, i)

            # serial baseline: one connection, strictly one request in
            # flight, caching off
            t0 = time.monotonic()
            serial_ok = all(
                _rows_sorted(submit(warm_client, i)[0])
                == expected[FRONTEND_SQLS[i % len(FRONTEND_SQLS)]]
                for i in range(queries))
            serial_wall = time.monotonic() - t0

        # concurrent phase: one client (connection + thread) per tenant,
        # still caching off — this measures the serving path, not the
        # result cache
        lat_ms: List[float] = []
        lat_lock = threading.Lock()
        errors: List[str] = []

        def worker(t_idx: int):
            try:
                with FrontDoorClient(host, port) as c:
                    for i in range(t_idx, queries, len(tenant_names)):
                        q0 = time.monotonic()
                        out, _m = submit(c, i)
                        ms = (time.monotonic() - q0) * 1e3
                        ok = _rows_sorted(out) == \
                            expected[FRONTEND_SQLS[i % len(FRONTEND_SQLS)]]
                        with lat_lock:
                            lat_ms.append(ms)
                            if not ok:
                                errors.append(f"parity:{i}")
            except Exception as e:
                with lat_lock:
                    errors.append(f"{type(e).__name__}: {e}")

        threads = [threading.Thread(target=worker, args=(t_idx,))
                   for t_idx in range(len(tenant_names))]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            while t.is_alive():
                t.join(0.25)
        wall = time.monotonic() - t0
        lat_ms.sort()

        # second client connection, caching off: the shared plan cache
        # is process-wide behind the front door, so it compiles nothing
        with FrontDoorClient(host, port) as c2:
            _out, m2 = submit(c2, 0)
            second_compiles = int(m2.get("compileCount", -1))

            # warm repeat through the result cache: first cache=true
            # submission executes and inserts, the repeat answers with
            # zero compiles and zero dispatches
            submit(c2, 0, cache=True)
            _hit, mh = submit(c2, 0, cache=True)
            cache_hit_dispatches = int(mh.get("dispatchCount", -1))

            # intentionally doomed: the admission predictor's baseline
            # says this fingerprint takes ms, the deadline allows 1us
            shed = 0
            try:
                submit(c2, 1, deadline=1e-6)
            except DeadlineExceeded:
                shed = 1
            fstats = c2.stats()["frontend"]
            d = c2.drain()
        server.close()

        parity = serial_ok and not errors
        return {
            "frontend_queries": queries,
            "frontend_wall_s": round(wall, 4),
            "frontend_serial_wall_s": round(serial_wall, 4),
            "frontend_queries_per_sec":
                round(queries / wall, 2) if wall else 0.0,
            "frontend_vs_serial":
                round(serial_wall / wall, 3) if wall else 0.0,
            "frontend_p50_ms": round(_percentile_ms(lat_ms, 0.50), 3),
            "frontend_p99_ms": round(_percentile_ms(lat_ms, 0.99), 3),
            "frontend_parity": bool(parity),
            "frontend_second_client_compiles": second_compiles,
            "frontend_cache_hit_dispatches": cache_hit_dispatches,
            "result_cache_hits": int(fstats.get("result_cache_hits", 0)),
            "admission_shed": int(fstats.get("admission_shed", 0))
                if shed else 0,
            "frontend_drained": bool(d["drained"]),
            "frontend_held_depth": int(d["held_depth"]),
        }
    finally:
        shutil.rmtree(hist, ignore_errors=True)


def _percentile_ms(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]
