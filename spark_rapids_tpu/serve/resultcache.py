"""Front-door query result cache (serve/).

The fragment cache (history/fragcache.py) memoizes *intermediate*
device fragments inside one process's execute path; this cache extends
the same key and invalidation rules to **final result sets** served by
the network front door (serve/frontend.py), so a repeat query over
unchanged inputs answers an out-of-process client with zero compiles
AND zero dispatches — the response is rebuilt from catalog-registered
spillable batches without ever entering ``session.execute``.

Key: ``(plan fingerprint hash, plan-relevant conf signature, input
identity)`` — exactly the fragment-cache key (history.input_identity).
Invalidation therefore follows the same three edges:

* **input mtime/size**: the key is recomputed per request from a live
  ``os.stat`` of every scanned file, so an overwritten input produces a
  different key and misses naturally;
* **conf signature**: any plan-relevant conf change (history.store's
  ``conf_signature`` exclusions aside) changes the key;
* **device generation**: entries record the DeviceRuntime generation
  they were built under; a device-lost recovery bump drops them on the
  next fetch.

Entries hold a STRONG reference to the logical plan — a deliberate
deviation from the fragment cache's weakref discipline.  Front-door
plans are parsed per request and would die the moment the response is
sent, yet the id()-keyed parts of the fingerprint and input identity
(InMemoryScan batch holders) stay sound only while the plan tree that
owns them is alive.  Pinning the plan keeps them sound; the LRU entry
and byte bounds keep the pin bounded.

**Cost-weighted admission**: a result is cached only when its recorded
compute wall beats its byte footprint
(``serve.resultCache.minNsPerByte``) — a cheap-to-recompute bulky
result (a full-input projection, say) would evict genuinely expensive
results for no latency win.

Storage: the result HostBatch is staged to device once and registered
in the spill catalog at PRIORITY_RESULT — the most spillable band, so
cached results yield HBM before any live query data and before even
fragment-cache entries.  A hit rehydrates through the catalog
prefetcher and runs only D2H.

Thread safety: bookkeeping under one lock; staging, registration and
victim closing run outside it (fragcache discipline).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, List, Optional, Tuple

DEFAULT_MAX_ENTRIES = 64
DEFAULT_MAX_BYTES = 128 << 20
DEFAULT_MIN_NS_PER_BYTE = 10.0


class _Result:
    __slots__ = ("plan", "handles", "generation", "nbytes", "wall_ns")

    def __init__(self, plan, handles, generation, nbytes, wall_ns):
        self.plan = plan
        self.handles = handles
        self.generation = generation
        self.nbytes = nbytes
        self.wall_ns = wall_ns


def cache_key(session, plan) -> Tuple[str, str, Optional[str]]:
    """(fingerprint hash, conf signature, input identity | None) for
    ``plan`` under ``session``'s conf — the identity triple shared by
    the result cache and the admission predictor.  The input identity
    is None (uncacheable) when a source kind is unknown or an input
    file went missing."""
    from spark_rapids_tpu.history import input_identity
    from spark_rapids_tpu.history import store
    from spark_rapids_tpu.plan.logical import plan_fingerprint
    fp_hash = store.fingerprint_hash(plan_fingerprint(plan))
    conf_sig = store.conf_signature(session.conf._settings.items())
    return fp_hash, conf_sig, input_identity(plan)


class ResultCache:
    """LRU of final result sets, shared by every front door in the
    process (serve/excache singleton discipline)."""

    def __init__(self, max_entries: int = DEFAULT_MAX_ENTRIES,
                 max_bytes: int = DEFAULT_MAX_BYTES,
                 min_ns_per_byte: float = DEFAULT_MIN_NS_PER_BYTE):
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Any, _Result]" = OrderedDict()
        self._max_entries = max(1, int(max_entries))
        self._max_bytes = int(max_bytes)
        self._min_ns_per_byte = float(min_ns_per_byte)
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.admission_rejects = 0

    def configure(self, max_entries: int, max_bytes: int,
                  min_ns_per_byte: float) -> None:
        with self._lock:
            self._max_entries = max(1, int(max_entries))
            self._max_bytes = int(max_bytes)
            self._min_ns_per_byte = float(min_ns_per_byte)
            victims = self._evict_locked()
        self._close_all(victims)

    # -- internal -----------------------------------------------------------

    def _evict_locked(self) -> List[_Result]:
        """Collect LRU victims past either bound; caller closes them
        OUTSIDE the lock."""
        victims: List[_Result] = []
        total = sum(e.nbytes for e in self._entries.values())
        while self._entries and (
                len(self._entries) > self._max_entries
                or total > max(0, self._max_bytes)):
            _, ent = self._entries.popitem(last=False)
            total -= ent.nbytes
            victims.append(ent)
            self.evictions += 1
        return victims

    @staticmethod
    def _close_all(results: List[_Result]) -> None:
        for ent in results:
            for h in ent.handles:
                h.close()

    # -- public -------------------------------------------------------------

    def fetch(self, key: Any):
        """The cached result as a fresh HostBatch, or None on miss.

        A hit rehydrates the catalog handles (overlapped unspill via the
        prefetcher) and runs only D2H — no compile, no dispatch, no
        device admission.  A generation mismatch or any rehydration
        failure drops the entry and reports a miss (the front door then
        executes normally and re-inserts)."""
        from spark_rapids_tpu.runtime.device import DeviceRuntime
        gen_now = DeviceRuntime.generation()
        with self._lock:
            ent = self._entries.get(key)
            if ent is not None and ent.generation != gen_now:
                del self._entries[key]
                self.misses += 1
                stale = ent
            elif ent is None:
                self.misses += 1
                return None
            else:
                self._entries.move_to_end(key)
                stale = None
        if stale is not None:
            self._close_all([stale])
            return None
        from spark_rapids_tpu.batch import HostBatch, device_to_host_many
        from spark_rapids_tpu.plan.physical import prefetch_spillables
        try:
            devs = list(prefetch_spillables(ent.handles, depth=1))
            hosts = device_to_host_many(devs)
        except Exception:
            # DeviceLostError racing past the generation check, a handle
            # closed by a concurrent eviction, an unspill failure — drop
            # the entry and let the front door execute normally
            with self._lock:
                if self._entries.get(key) is ent:
                    del self._entries[key]
                self.misses += 1
            self._close_all([ent])
            return None
        with self._lock:
            self.hits += 1
        from spark_rapids_tpu.obs import events as obs_events
        obs_events.emit_instant("serve.resultcache", "result_hit", "serve",
                                bytes=ent.nbytes, batches=len(hosts))
        return HostBatch.concat(hosts)

    def insert(self, key: Any, plan: Any, result, wall_ns: int,
               conf) -> bool:
        """Adopt a finished query's result HostBatch under ``key``.

        Applies cost-weighted admission first (recorded compute wall
        must beat the byte footprint at ``minNsPerByte``), then stages
        the rows to device once and registers them as a catalog
        spillable at PRIORITY_RESULT.  First insert wins on a race.
        Returns False when not admitted."""
        if key is None or result is None or result.num_rows == 0:
            return False
        from spark_rapids_tpu.batch import host_batch_bytes
        nbytes = host_batch_bytes(result)
        with self._lock:
            if self._max_bytes <= 0:
                return False
            if self._min_ns_per_byte > 0 and \
                    wall_ns < self._min_ns_per_byte * nbytes:
                self.admission_rejects += 1
                return False
            if key in self._entries:
                return False
        from spark_rapids_tpu.batch import host_to_device
        from spark_rapids_tpu.mem.catalog import (
            PRIORITY_RESULT, device_batch_bytes,
        )
        from spark_rapids_tpu.runtime.device import DeviceRuntime
        try:
            dev = host_to_device(result)
            nbytes = device_batch_bytes(dev)
        except Exception:
            # a result shape the device layout cannot hold (e.g. a
            # host-only array<string> column) is simply not cacheable
            return False
        rt = DeviceRuntime.get(conf)
        handle = rt.catalog.register(dev, priority=PRIORITY_RESULT)
        ent = _Result(plan, [handle], DeviceRuntime.generation(),
                      nbytes, int(wall_ns))
        with self._lock:
            if key in self._entries:
                loser: Optional[_Result] = ent  # racer won; drop ours
                victims: List[_Result] = []
            else:
                self._entries[key] = ent
                self._entries.move_to_end(key)
                loser = None
                victims = self._evict_locked()
        if loser is not None:
            self._close_all([loser])
            return False
        self._close_all(victims)
        return True

    def drop(self, key: Any) -> None:
        with self._lock:
            ent = self._entries.pop(key, None)
        if ent is not None:
            self._close_all([ent])

    def clear(self) -> None:
        with self._lock:
            victims = list(self._entries.values())
            self._entries.clear()
        self._close_all(victims)

    def stats(self):
        with self._lock:
            return {
                "result_cache_entries": len(self._entries),
                "result_cache_bytes": sum(
                    e.nbytes for e in self._entries.values()),
                "result_cache_hits": self.hits,
                "result_cache_misses": self.misses,
                "result_cache_evictions": self.evictions,
                "result_cache_admission_rejects": self.admission_rejects,
            }

    def __len__(self):
        with self._lock:
            return len(self._entries)


_SHARED: ResultCache = ResultCache()


def result_cache() -> ResultCache:
    """The process singleton (serve/excache.shared_plan_cache
    analogue)."""
    return _SHARED
