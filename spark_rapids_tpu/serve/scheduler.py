"""Multi-tenant serving scheduler above ``session.execute``.

The engine's device admission (``runtime.device.TpuSemaphore``) governs
*dispatch* concurrency; nothing before this PR governed *query*
admission — a burst from one client would queue unboundedly ahead of
everyone else.  :class:`ServeScheduler` adds that layer, the analogue of
Spark's fair-scheduler pools over the rapids plugin:

* **Weighted fair queueing** across named tenants: each tenant's
  virtual time advances by ``1/weight`` per query popped, and runners
  always pop from the lowest-vtime non-empty tenant — a weight-2 tenant
  drains twice as fast as a weight-1 tenant under contention, and an
  idle tenant's first query never waits behind a backlog it didn't
  create (its vtime is floored to the global minimum on arrival).
  Weights come from ``spark.rapids.sql.tpu.serve.tenant.<name>.weight``
  (default 1.0).
* **Per-query deadlines**: measured from *submit*.  A query whose
  deadline lapses while queued fails fast without executing; one that
  starts arms the PR-4 partition watchdog with the remaining budget and
  a NON_RETRYABLE :class:`DeadlineExceeded` — the retry ladder
  propagates it immediately (no recovery replay, no CPU fallback), so
  one slow query misses ITS deadline while its neighbors finish.
* **Micro-query batching** (``serve.batch.enabled``): template
  submissions coalesce per (template, schema, bucket) group — see
  :mod:`spark_rapids_tpu.serve.batching`.  A runner popping a micro
  query drains every queued group partner (each charged to its own
  tenant's vtime) and may linger up to ``serve.batch.maxDelayMs`` for
  stragglers before dispatching once for all of them — or, with
  ``serve.batch.adaptive.enabled``, an arrival-rate-driven linger
  clamped to [0, maxDelayMs] (see :meth:`_adaptive_delay_s`).

Blocking discipline (rapidslint R2/R3): every wait is a bounded
<=0.25s slice inside a loop with an exit condition; every lock acquire
is a ``with`` block.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from spark_rapids_tpu.batch import HostBatch
from spark_rapids_tpu.fault.errors import ErrorClass
from spark_rapids_tpu.serve.batching import (
    MicroBatcher, QueryTemplate, group_key,
)

_WAIT_SLICE_S = 0.25


class DeadlineExceeded(RuntimeError):
    """A served query missed its deadline.

    NON_RETRYABLE by construction: the deadline is a *latency* contract
    — replaying the query (the DEVICE_LOST recovery path) could only
    miss it harder, so the retry ladder must propagate this
    immediately."""

    rapids_error_class = ErrorClass.NON_RETRYABLE


class ServeFuture:
    """Completion handle for one submitted query.

    ``result()`` returns the query's :class:`HostBatch`; ``metrics``
    holds the query's per-execution metrics dict once done (shared by
    every rider of a coalesced micro-dispatch)."""

    def __init__(self, tenant: str, qid: int):
        self.tenant = tenant
        self.qid = qid
        self.metrics: Optional[Dict[str, Any]] = None
        self._done = threading.Event()
        self._value: Optional[HostBatch] = None
        self._error: Optional[BaseException] = None

    def _resolve(self, value: HostBatch,
                 metrics: Optional[Dict[str, Any]]) -> None:
        self._value = value
        self.metrics = metrics
        self._done.set()

    def _fail(self, err: BaseException) -> None:
        self._error = err
        self._done.set()

    def done(self) -> bool:
        return self._done.is_set()

    def exception(self, timeout: Optional[float] = None):
        self._wait(timeout)
        return self._error

    def result(self, timeout: Optional[float] = None) -> HostBatch:
        self._wait(timeout)
        if self._error is not None:
            raise self._error
        return self._value

    def _wait(self, timeout: Optional[float]) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        while not self._done.is_set():
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutError(
                    f"query {self.qid} (tenant {self.tenant}) not done "
                    f"after {timeout:g}s")
            self._done.wait(_WAIT_SLICE_S)


class _Item:
    """One queued submission."""

    __slots__ = ("future", "plan", "template", "batch", "gkey",
                 "submit_ns", "deadline_sec")

    def __init__(self, future: ServeFuture, plan=None, template=None,
                 batch=None, gkey=None, deadline_sec: float = 0.0):
        self.future = future
        self.plan = plan
        self.template = template
        self.batch = batch
        self.gkey = gkey
        self.submit_ns = time.monotonic_ns()
        self.deadline_sec = float(deadline_sec or 0.0)

    def remaining_sec(self) -> float:
        """Seconds of deadline budget left; +inf when undeadlined."""
        if self.deadline_sec <= 0:
            return float("inf")
        used = (time.monotonic_ns() - self.submit_ns) / 1e9
        return self.deadline_sec - used


class _Tenant:
    """One tenant's queue, WFQ virtual time and SLO rollup."""

    def __init__(self, name: str, weight: float):
        self.name = name
        self.weight = max(1e-6, float(weight))
        self.vtime = 0.0
        self.queue: deque = deque()
        self.submitted = 0
        self.completed = 0
        self.failed = 0
        self.deadline_exceeded = 0
        self.inflight = 0
        self.latencies_ms: List[float] = []

    def charge(self) -> None:
        self.vtime += 1.0 / self.weight

    def record(self, item: _Item, ok: bool, deadline: bool = False) -> None:
        lat_ms = (time.monotonic_ns() - item.submit_ns) / 1e6
        if len(self.latencies_ms) < 100000:
            self.latencies_ms.append(lat_ms)
        # feed the telemetry ring so stats() can report SLIDING-window
        # percentiles (the all-time lists above never forget a cold start)
        from spark_rapids_tpu.obs import timeseries as obs_ts
        obs_ts.record_value("serve.latency_ms", lat_ms)
        obs_ts.record_value(f"serve.latency_ms.{self.name}", lat_ms)
        if deadline:
            self.deadline_exceeded += 1
            self.failed += 1
        elif ok:
            self.completed += 1
        else:
            self.failed += 1


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


class ServeScheduler:
    """Weighted-fair multi-tenant query scheduler over one session.

    ``max_concurrency`` runner threads (conf
    ``spark.rapids.sql.tpu.serve.maxConcurrency``) pull queries off the
    tenant queues and drive ``session.execute_with_metrics``; results
    land in :class:`ServeFuture`\\ s.  Use as a context manager or call
    :meth:`close`."""

    def __init__(self, session, max_concurrency: Optional[int] = None,
                 autostart: bool = True):
        from spark_rapids_tpu.config import (
            SERVE_BATCH_ADAPTIVE, SERVE_BATCH_ENABLED,
            SERVE_BATCH_MAX_DELAY_MS, SERVE_BATCH_MAX_QUERIES,
            SERVE_DEADLINE_SEC, SERVE_MAX_CONCURRENCY,
        )
        self.session = session
        self.conf = session.conf
        self._concurrency = int(max_concurrency
                                or SERVE_MAX_CONCURRENCY.get(self.conf))
        self._batch_enabled = SERVE_BATCH_ENABLED.get(self.conf)
        self._batch_delay_s = SERVE_BATCH_MAX_DELAY_MS.get(self.conf) / 1e3
        self._batch_adaptive = SERVE_BATCH_ADAPTIVE.get(self.conf)
        self._batch_max = max(1, SERVE_BATCH_MAX_QUERIES.get(self.conf))
        self._default_deadline = SERVE_DEADLINE_SEC.get(self.conf)
        self._batcher = MicroBatcher(session)
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._tenants: Dict[str, _Tenant] = {}
        self._closed = False
        self._qid_seq = 0
        self._inflight = 0
        self._runners: List[threading.Thread] = []
        # per-tenant gauges created in _tenant() (caller holds our lock)
        # are registered later, outside it — never call into the
        # telemetry registry while holding the scheduler lock
        self._pending_gauges: List[Tuple[str, Any]] = []
        if autostart:
            self.start()

    def start(self) -> None:
        """Start the runner threads (idempotent).  ``autostart=False``
        plus a deferred ``start()`` lets tests queue a whole workload
        first, making the weighted pop order deterministic."""
        with self._lock:
            if self._runners or self._closed:
                return
            self._runners = [
                threading.Thread(target=self._run, daemon=True,
                                 name=f"serve-runner-{i}")
                for i in range(self._concurrency)]
        for t in self._runners:
            t.start()
        # queued + in-flight queries, sampled at telemetry export time
        from spark_rapids_tpu.obs import timeseries as obs_ts
        obs_ts.register_gauge("serve.queue_depth", self._queue_depth)

    def _queue_depth(self) -> float:
        with self._lock:
            return float(self._inflight + sum(
                len(t.queue) for t in self._tenants.values()))

    # -- submission ---------------------------------------------------------

    def _tenant(self, name: str) -> _Tenant:
        """Get-or-create under self._lock (caller holds it)."""
        t = self._tenants.get(name)
        if t is None:
            raw = self.conf.get(
                f"spark.rapids.sql.tpu.serve.tenant.{name}.weight")
            t = _Tenant(name, float(raw) if raw is not None else 1.0)
            # floor a newly-active tenant's vtime to the current minimum
            # so it competes from "now" instead of replaying the past
            if self._tenants:
                t.vtime = min(x.vtime for x in self._tenants.values())
            self._tenants[name] = t
            self._pending_gauges.extend([
                (f"serve.tenant.{name}.queue_depth",
                 lambda t=t: float(len(t.queue))),
                (f"serve.tenant.{name}.inflight",
                 lambda t=t: float(t.inflight)),
                (f"serve.tenant.{name}.deadline_miss",
                 lambda t=t: float(t.deadline_exceeded)),
            ])
        return t

    def _flush_tenant_gauges(self) -> None:
        """Register any gauges queued by _tenant() (outside the lock).
        While telemetry is down, registration would be a silent no-op —
        keep them pending until a ring exists to adopt them."""
        from spark_rapids_tpu.obs import timeseries as obs_ts
        if obs_ts.ring() is None:
            return
        with self._lock:
            pending, self._pending_gauges = self._pending_gauges, []
        for name, fn in pending:
            obs_ts.register_gauge(name, fn)

    def _enqueue(self, item: _Item, tenant: str) -> ServeFuture:
        with self._work:
            if self._closed:
                raise RuntimeError("ServeScheduler is closed")
            t = self._tenant(tenant)
            t.submitted += 1
            t.queue.append(item)
            self._work.notify()
        self._flush_tenant_gauges()
        # arrival marker for the adaptive micro-batch window: the ring's
        # sample count over its window IS the arrival rate estimate
        from spark_rapids_tpu.obs import timeseries as obs_ts
        obs_ts.record_value("serve.arrivals", 1.0)
        return item.future

    def record_shed(self, tenant: str) -> None:
        """Count an admission-control shed (serve/frontend.py) against
        ``tenant``'s SLO rollup: the query was submitted to the front
        door and failed its deadline — it just never reached a queue."""
        with self._lock:
            t = self._tenant(tenant)
            t.submitted += 1
            t.failed += 1
            t.deadline_exceeded += 1
        self._flush_tenant_gauges()

    def submit(self, query, tenant: str = "default",
               deadline_sec: Optional[float] = None) -> ServeFuture:
        """Queue a DataFrame (or logical plan) for execution."""
        plan = getattr(query, "plan", query)
        fut = ServeFuture(tenant, self._next_qid())
        return self._enqueue(
            _Item(fut, plan=plan,
                  deadline_sec=self._deadline(deadline_sec)), tenant)

    def submit_micro(self, template: QueryTemplate, batch: HostBatch,
                     tenant: str = "default",
                     deadline_sec: Optional[float] = None) -> ServeFuture:
        """Queue a template query over ``batch``; eligible for
        coalescing with same-group submissions."""
        fut = ServeFuture(tenant, self._next_qid())
        gkey = group_key(template, batch)
        return self._enqueue(
            _Item(fut, template=template, batch=batch, gkey=gkey,
                  deadline_sec=self._deadline(deadline_sec)), tenant)

    def _deadline(self, deadline_sec: Optional[float]) -> float:
        return self._default_deadline if deadline_sec is None \
            else float(deadline_sec)

    def _next_qid(self) -> int:
        with self._lock:
            self._qid_seq += 1
            return self._qid_seq

    # -- runner loop --------------------------------------------------------

    def _pop_locked(self) -> Optional[Tuple[_Tenant, _Item]]:
        """Pop from the lowest-vtime non-empty tenant (caller holds the
        lock); charges the tenant's vtime."""
        best = None
        for t in self._tenants.values():
            if t.queue and (best is None or t.vtime < best.vtime):
                best = t
        if best is None:
            return None
        item = best.queue.popleft()
        best.charge()
        return best, item

    def _drain_group_locked(self, gkey, limit: int) -> List[Tuple[_Tenant,
                                                                  _Item]]:
        """Remove up to ``limit`` queued same-group micro items (any
        tenant, FIFO per tenant), charging each to its tenant."""
        out: List[Tuple[_Tenant, _Item]] = []
        for t in self._tenants.values():
            if len(out) >= limit:
                break
            kept = deque()
            while t.queue and len(out) < limit:
                it = t.queue.popleft()
                if it.gkey == gkey:
                    t.charge()
                    out.append((t, it))
                else:
                    kept.append(it)
            while kept:
                t.queue.appendleft(kept.pop())
        return out

    def _run(self) -> None:
        while True:
            with self._work:
                popped = self._pop_locked()
                while popped is None:
                    if self._closed:
                        return
                    self._work.wait(_WAIT_SLICE_S)
                    popped = self._pop_locked()
                tenant, item = popped
                self._inflight += 1
                tenant.inflight += 1
            try:
                if item.template is not None:
                    self._run_micro(tenant, item)
                else:
                    self._run_plan(tenant, item)
            finally:
                with self._work:
                    self._inflight -= 1
                    tenant.inflight -= 1
                    self._work.notify_all()

    def _expire(self, tenant: _Tenant, item: _Item) -> bool:
        """Fail ``item`` fast if its deadline lapsed while queued."""
        if item.remaining_sec() <= 0:
            item.future._fail(DeadlineExceeded(
                f"query {item.future.qid} (tenant {tenant.name}) missed "
                f"deadline {item.deadline_sec:g}s before executing"))
            with self._lock:
                tenant.record(item, ok=False, deadline=True)
            return True
        return False

    def _run_plan(self, tenant: _Tenant, item: _Item) -> None:
        if self._expire(tenant, item):
            return
        from spark_rapids_tpu.fault.watchdog import partition_deadline
        try:
            with partition_deadline(
                    item.remaining_sec() if item.deadline_sec > 0 else 0.0,
                    label=f"serve:{tenant.name}",
                    exc_type=DeadlineExceeded):
                out, metrics = self.session.execute_with_metrics(item.plan)
        except BaseException as e:  # runner must survive any query error
            with self._lock:
                tenant.record(item, ok=False,
                              deadline=isinstance(e, DeadlineExceeded))
            item.future._fail(e)
            if not isinstance(e, Exception):
                raise  # KeyboardInterrupt/SystemExit: fail the caller, then propagate
            return
        with self._lock:
            tenant.record(item, ok=True)
        item.future._resolve(out, metrics)

    def _adaptive_delay_s(self) -> float:
        """Arrival-rate-driven micro-batch linger
        (``serve.batch.adaptive.enabled``): aim to linger about two
        inter-arrival gaps — long enough to catch the next same-group
        submission when traffic is steady, and collapsing to zero when
        the queue has gone quiet (an isolated query shouldn't pay the
        full maxDelayMs for riders that never come).  Clamped to
        [0, maxDelayMs]; falls back to the static linger while
        telemetry is disabled (no arrival estimate to steer by)."""
        from spark_rapids_tpu.obs import timeseries as obs_ts
        ring = obs_ts.ring()
        if ring is None:
            return self._batch_delay_s
        window_s = ring.window_seconds()
        if window_s <= 0:
            return self._batch_delay_s
        rate = len(ring.window_values("serve.arrivals")) / window_s
        if rate <= 0.0:
            return 0.0
        return max(0.0, min(self._batch_delay_s, 2.0 / rate))

    def _collect_riders(self, head_item: _Item) -> List[Tuple[_Tenant,
                                                              _Item]]:
        """Drain queued group partners of ``head_item``; linger up to
        maxDelayMs (in bounded slices) for stragglers while below
        maxQueries."""
        riders: List[Tuple[_Tenant, _Item]] = []
        budget = self._batch_max - 1
        if not self._batch_enabled or budget <= 0:
            return riders
        delay_s = self._adaptive_delay_s() if self._batch_adaptive \
            else self._batch_delay_s
        wait_deadline = time.monotonic() + delay_s
        while True:
            with self._work:
                riders.extend(
                    self._drain_group_locked(head_item.gkey,
                                             budget - len(riders)))
            if len(riders) >= budget:
                break
            now = time.monotonic()
            if now >= wait_deadline:
                break
            # the head query also may not linger past its own deadline
            slack = min(_WAIT_SLICE_S, wait_deadline - now,
                        max(0.0, head_item.remaining_sec() - 0.01))
            if slack <= 0:
                break
            with self._work:
                self._work.wait(slack)
        return riders

    def _run_micro(self, tenant: _Tenant, item: _Item) -> None:
        if self._expire(tenant, item):
            return
        members = [(tenant, item)] + self._collect_riders(item)
        live: List[Tuple[_Tenant, _Item]] = []
        for t, it in members:
            if it is item or not self._expire(t, it):
                live.append((t, it))
        from spark_rapids_tpu.fault.watchdog import partition_deadline
        # the dispatch honors the tightest live deadline on board
        remaining = min(it.remaining_sec() for _t, it in live)
        try:
            grp = self._batcher.bind(item.template, item.gkey,
                                     item.batch.schema)
            requests = [(it.future.qid, it.batch) for _t, it in live]
            with partition_deadline(
                    remaining if remaining != float("inf") else 0.0,
                    label=f"serve-batch:{item.gkey[0]}",
                    exc_type=DeadlineExceeded):
                results, metrics = self._batcher.run(grp, requests)
        except BaseException as e:
            for t, it in live:
                with self._lock:
                    t.record(it, ok=False,
                             deadline=isinstance(e, DeadlineExceeded))
                it.future._fail(e)
            if not isinstance(e, Exception):
                raise  # KeyboardInterrupt/SystemExit: fail the riders, then propagate
            return
        for t, it in live:
            with self._lock:
                t.record(it, ok=True)
            it.future._resolve(results[it.future.qid], metrics)

    # -- lifecycle / stats --------------------------------------------------

    def drain(self, timeout: float = 60.0) -> bool:
        """Wait (bounded) until every queued and in-flight query has
        completed; True on quiescence, False on timeout."""
        deadline = time.monotonic() + timeout
        while True:
            with self._work:
                idle = self._inflight == 0 and not any(
                    t.queue for t in self._tenants.values())
                if idle:
                    return True
                if time.monotonic() >= deadline:
                    return False
                self._work.wait(_WAIT_SLICE_S)

    def close(self, timeout: float = 10.0) -> None:
        """Stop the runners (queued-but-unstarted work is abandoned:
        their futures fail with RuntimeError)."""
        with self._work:
            self._closed = True
            abandoned = []
            for t in self._tenants.values():
                while t.queue:
                    abandoned.append(t.queue.popleft())
            self._work.notify_all()
        for it in abandoned:
            it.future._fail(RuntimeError("ServeScheduler closed before "
                                         "this query executed"))
        deadline = time.monotonic() + timeout
        for t in self._runners:
            while t.is_alive() and time.monotonic() < deadline:
                t.join(_WAIT_SLICE_S)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def stats(self) -> Dict[str, Any]:
        """Aggregate + per-tenant SLO rollup (the bench/CI surface).

        ``p50_ms``/``p99_ms`` stay all-time (every completion since
        start); the ``window_*`` fields cover only the telemetry ring's
        current window, so a long-running server's percentiles track
        what latency looks like NOW rather than averaging in its cold
        start.  Window fields are 0.0 while telemetry is disabled."""
        from spark_rapids_tpu.obs import timeseries as obs_ts
        from spark_rapids_tpu.serve.excache import shared_plan_cache
        ring = obs_ts.ring()

        def window(series: str) -> Tuple[float, float]:
            if ring is None:
                return 0.0, 0.0
            vals = sorted(ring.window_values(series))
            return _percentile(vals, 0.50), _percentile(vals, 0.99)

        with self._lock:
            all_lat = sorted(
                v for t in self._tenants.values() for v in t.latencies_ms)
            tenants = {}
            for t in self._tenants.values():
                w50, w99 = window(f"serve.latency_ms.{t.name}")
                tenants[t.name] = {
                    "weight": t.weight,
                    "submitted": t.submitted,
                    "completed": t.completed,
                    "failed": t.failed,
                    "deadline_exceeded": t.deadline_exceeded,
                    "inflight": t.inflight,
                    "p50_ms": _percentile(sorted(t.latencies_ms), 0.50),
                    "p99_ms": _percentile(sorted(t.latencies_ms), 0.99),
                    "window_p50_ms": w50,
                    "window_p99_ms": w99,
                }
            w50, w99 = window("serve.latency_ms")
            out = {
                "completed": sum(t.completed
                                 for t in self._tenants.values()),
                "failed": sum(t.failed for t in self._tenants.values()),
                "deadline_exceeded": sum(
                    t.deadline_exceeded for t in self._tenants.values()),
                "p50_ms": _percentile(all_lat, 0.50),
                "p99_ms": _percentile(all_lat, 0.99),
                "window_p50_ms": w50,
                "window_p99_ms": w99,
                "window_seconds": ring.window_seconds() if ring else 0.0,
                "batched_queries": self._batcher.batched_queries,
                "micro_dispatches": self._batcher.dispatches,
                "tenants": tenants,
            }
        out.update(shared_plan_cache().stats())
        # query-intelligence rollup (history/): the statistics store the
        # serving runtime warms for tenant N+1, plus fragment-cache reuse
        from spark_rapids_tpu.history import runtime_stats
        out.update(runtime_stats())
        return out
