"""Pluggable buffer compression codecs (TableCompressionCodec analogue,
TableCompressionCodec.scala:42; codec selected by
``spark.rapids.shuffle.compression.codec``, RapidsConf.scala:669).

The reference ships only COPY (passthrough); here COPY plus zlib/lz4-style
host codecs for spill/shuffle bytes.  Codecs operate on host ``bytes`` —
device batches are staged host-side before the wire/disk anyway.
"""

from __future__ import annotations

import zlib
from typing import Callable, Dict, Tuple


class Codec:
    name = "copy"

    def compress(self, data: bytes) -> bytes:
        return data

    def decompress(self, data: bytes, uncompressed_size: int) -> bytes:
        return data


class CopyCodec(Codec):
    name = "copy"


class ZlibCodec(Codec):
    name = "zlib"

    def __init__(self, level: int = 1):
        self.level = level

    def compress(self, data: bytes) -> bytes:
        return zlib.compress(data, self.level)

    def decompress(self, data: bytes, uncompressed_size: int) -> bytes:
        return zlib.decompress(data)


_CODECS: Dict[str, Callable[[], Codec]] = {
    "copy": CopyCodec,
    "uncompressed": CopyCodec,
    "zlib": ZlibCodec,
}


def get_codec(name: str) -> Codec:
    try:
        return _CODECS[name.lower()]()
    except KeyError:
        raise ValueError(f"unknown compression codec: {name}") from None


def register_codec(name: str, factory: Callable[[], Codec]):
    _CODECS[name.lower()] = factory
