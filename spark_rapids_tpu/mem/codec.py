"""Pluggable buffer compression codecs (TableCompressionCodec analogue,
TableCompressionCodec.scala:42; codec selected by
``spark.rapids.shuffle.compression.codec``, RapidsConf.scala:669).

The reference ships only COPY (passthrough); here COPY plus zlib/lz4-style
host codecs for spill/shuffle bytes.  Codecs operate on host ``bytes`` —
device batches are staged host-side before the wire/disk anyway.
"""

from __future__ import annotations

import struct
import zlib
from typing import BinaryIO, Callable, Dict, Tuple


class Codec:
    name = "copy"

    def compress(self, data: bytes) -> bytes:
        return data

    def decompress(self, data: bytes, uncompressed_size: int) -> bytes:
        return data


class CopyCodec(Codec):
    name = "copy"


class ZlibCodec(Codec):
    name = "zlib"

    def __init__(self, level: int = 1):
        self.level = level

    def compress(self, data: bytes) -> bytes:
        return zlib.compress(data, self.level)

    def decompress(self, data: bytes, uncompressed_size: int) -> bytes:
        return zlib.decompress(data)


class NativeLZCodec(Codec):
    """C++ LZ4-style block codec (native/batch_runtime.cc lz_*): the
    TableCompressionCodec fast path.  A 1-byte header marks whether the
    block is compressed or stored raw (incompressible input, or the
    native library unavailable at compress time), so decompression is
    self-describing either way."""

    name = "nativelz"

    def compress(self, data: bytes) -> bytes:
        from spark_rapids_tpu.native_rt import lz_compress
        enc = lz_compress(data)
        if enc is None or len(enc) >= len(data):
            return b"\x00" + data
        return b"\x01" + enc

    def decompress(self, data: bytes, uncompressed_size: int) -> bytes:
        if not data:
            return b""
        tag, body = data[0], data[1:]
        if tag == 0:
            return body
        from spark_rapids_tpu.native_rt import lz_decompress
        out = lz_decompress(body, uncompressed_size)
        if out is None:
            raise RuntimeError(
                "nativelz block but the native library is unavailable")
        return out


_CODECS: Dict[str, Callable[[], Codec]] = {
    "copy": CopyCodec,
    "uncompressed": CopyCodec,
    "zlib": ZlibCodec,
    # NOTE: deliberately NOT aliased as "lz4" — the wire format (1-byte
    # raw/compressed header + bespoke token stream) is not interoperable
    # with standard LZ4 frames/blocks (ADVICE r4).
    "nativelz": NativeLZCodec,
}


def get_codec(name: str) -> Codec:
    try:
        return _CODECS[name.lower()]()
    except KeyError:
        raise ValueError(f"unknown compression codec: {name}") from None


def register_codec(name: str, factory: Callable[[], Codec]):
    _CODECS[name.lower()] = factory


# ---------------------------------------------------------------------------
# Chunked disk frames (spill engine v2)
#
# A spill file is a sequence of independently-compressed frames instead of
# one whole-batch blob, so the writer's compression overlaps the file write
# and unspill decompresses frame i while frame i+1 is still being read:
#
#     header:   "<QQ"  total_raw_len, frame_count
#     frame i:  "<QQ"  raw_len, enc_len   followed by enc_len codec bytes
#
# chunk_bytes <= 0 degenerates to a single whole-batch frame (the v1 blob
# shape, still wearing the frame header so the reader is uniform).
# ---------------------------------------------------------------------------

_FRAME_HEADER = struct.Struct("<QQ")


def write_chunked(f: BinaryIO, data: bytes, codec: Codec,
                  chunk_bytes: int) -> int:
    """Stream ``data`` through ``codec`` into ``f`` in fixed-size frames;
    returns the encoded byte count (frame payloads, headers excluded)."""
    step = max(1, len(data)) if chunk_bytes <= 0 else max(1, int(chunk_bytes))
    n = max(1, -(-len(data) // step)) if data else 1
    f.write(_FRAME_HEADER.pack(len(data), n))
    enc_total = 0
    for off in range(0, len(data) or 1, step):
        raw = data[off:off + step]
        enc = codec.compress(raw)
        f.write(_FRAME_HEADER.pack(len(raw), len(enc)))
        f.write(enc)
        enc_total += len(enc)
    return enc_total


def read_chunked(f: BinaryIO, codec: Codec) -> bytes:
    """Reverse of :func:`write_chunked`: decompress frame-by-frame (frame i
    decodes while the file position advances to frame i+1)."""
    total_raw, n = _FRAME_HEADER.unpack(f.read(_FRAME_HEADER.size))
    parts = []
    got = 0
    for _ in range(n):
        raw_len, enc_len = _FRAME_HEADER.unpack(f.read(_FRAME_HEADER.size))
        enc = f.read(enc_len)
        if len(enc) != enc_len:
            raise ValueError("truncated spill frame")
        parts.append(codec.decompress(enc, raw_len))
        got += raw_len
    if got != total_raw:
        raise ValueError(
            f"spill frame total {got} != header raw length {total_raw}")
    return b"".join(parts)
