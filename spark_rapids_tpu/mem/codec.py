"""Pluggable buffer compression codecs (TableCompressionCodec analogue,
TableCompressionCodec.scala:42; codec selected by
``spark.rapids.shuffle.compression.codec``, RapidsConf.scala:669).

The reference ships only COPY (passthrough); here COPY plus zlib/lz4-style
host codecs for spill/shuffle bytes.  Codecs operate on host ``bytes`` —
device batches are staged host-side before the wire/disk anyway.
"""

from __future__ import annotations

import zlib
from typing import Callable, Dict, Tuple


class Codec:
    name = "copy"

    def compress(self, data: bytes) -> bytes:
        return data

    def decompress(self, data: bytes, uncompressed_size: int) -> bytes:
        return data


class CopyCodec(Codec):
    name = "copy"


class ZlibCodec(Codec):
    name = "zlib"

    def __init__(self, level: int = 1):
        self.level = level

    def compress(self, data: bytes) -> bytes:
        return zlib.compress(data, self.level)

    def decompress(self, data: bytes, uncompressed_size: int) -> bytes:
        return zlib.decompress(data)


class NativeLZCodec(Codec):
    """C++ LZ4-style block codec (native/batch_runtime.cc lz_*): the
    TableCompressionCodec fast path.  A 1-byte header marks whether the
    block is compressed or stored raw (incompressible input, or the
    native library unavailable at compress time), so decompression is
    self-describing either way."""

    name = "nativelz"

    def compress(self, data: bytes) -> bytes:
        from spark_rapids_tpu.native_rt import lz_compress
        enc = lz_compress(data)
        if enc is None or len(enc) >= len(data):
            return b"\x00" + data
        return b"\x01" + enc

    def decompress(self, data: bytes, uncompressed_size: int) -> bytes:
        if not data:
            return b""
        tag, body = data[0], data[1:]
        if tag == 0:
            return body
        from spark_rapids_tpu.native_rt import lz_decompress
        out = lz_decompress(body, uncompressed_size)
        if out is None:
            raise RuntimeError(
                "nativelz block but the native library is unavailable")
        return out


_CODECS: Dict[str, Callable[[], Codec]] = {
    "copy": CopyCodec,
    "uncompressed": CopyCodec,
    "zlib": ZlibCodec,
    # NOTE: deliberately NOT aliased as "lz4" — the wire format (1-byte
    # raw/compressed header + bespoke token stream) is not interoperable
    # with standard LZ4 frames/blocks (ADVICE r4).
    "nativelz": NativeLZCodec,
}


def get_codec(name: str) -> Codec:
    try:
        return _CODECS[name.lower()]()
    except KeyError:
        raise ValueError(f"unknown compression codec: {name}") from None


def register_codec(name: str, factory: Callable[[], Codec]):
    _CODECS[name.lower()] = factory
