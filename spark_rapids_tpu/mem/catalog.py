"""Spillable-buffer catalog with device -> host -> disk tiers.

Reference analogues: RapidsBufferCatalog.scala:36 (id->buffer registry wiring
the spill chain), RapidsBufferStore.scala:39 (priority-ordered spillable
tracking + synchronousSpill), RapidsDeviceMemoryStore / RapidsHostMemoryStore
/ RapidsDiskStore, SpillableColumnarBatch.scala:27 (operator-facing handle),
SpillPriorities.scala.

TPU adaptation: XLA owns HBM allocation and exposes no alloc-failure callback
(the RMM event-handler hook, DeviceMemoryEventHandler.scala:35).  Instead the
catalog enforces a *budget*: every operator that holds batches across
pipeline breaks registers them as SpillableBatch handles; when registered
device bytes exceed the budget the catalog spills lowest-priority handles to
host numpy, and past the host-store bound to disk — same three tiers, push
model instead of callback model.

Spill engine v2 (asynchronous tiered spill):

* ``reserve()`` picks victims and transitions them DEVICE -> SPILLING under
  the lock, but the D2H copy and any compress+disk write run on a bounded
  background writer pool (``spill.async.enabled`` / ``spill.writer.threads``)
  so the triggering register/get returns immediately.  A ``get()`` racing a
  spill that has not started yet cancels it cheaply (the device copy never
  moved); one racing a started spill joins just that handle's completion.
  ``spill.async.enabled=false`` restores the v1 synchronous semantics: the
  same state machine executed inline, errors surfacing from the triggering
  call.
* Accounting is incremental: per-tier running byte counters updated at every
  transition replace the O(n) re-scan per budget-loop iteration, and a
  handle's host bytes are computed once at spill time (string columns walk
  every value).  ``verify_accounting()`` (analysis/plan_verify.py) asserts
  counters == scan.
* ``prefetch()`` generalizes the shuffle drain's one-piece read-ahead: it
  yields handles' device batches with the next unspill (disk read +
  decompress + async H2D enqueue) already in flight.
* Disk frames are chunked (``spill.chunkBytes``, mem/codec.py) so
  compression overlaps the file write and unspill decompresses before the
  whole file is read.
* Fault interplay: ``spill:*`` injections fire on the writer thread and the
  classified error surfaces at the consumer's next ``get()`` (the handle
  reverts to the device tier, so the recovery ladder's replay succeeds);
  ``unspill:*`` fires on the rehydration path.  ``invalidate_device_tier``
  drains/aborts in-flight spills before rescuing.
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
from collections import deque
from typing import Deque, Dict, Iterator, List, Optional, Sequence

import numpy as np

from spark_rapids_tpu.batch import (
    ColumnBatch, HostBatch, device_to_host, host_batch_bytes, host_to_device,
)
from spark_rapids_tpu.config import (
    RapidsConf, SPILL_ASYNC_ENABLED, SPILL_CHUNK_BYTES, SPILL_WRITER_THREADS,
    conf_bytes,
)
from spark_rapids_tpu.obs import events as obs_events

DEVICE_SPILL_BUDGET = conf_bytes(
    "spark.rapids.memory.tpu.spillBudgetBytes", 8 << 30,
    "Device bytes the catalog lets spillable batches occupy before "
    "spilling lowest-priority ones to host.")

# Spill priority bands (SpillPriorities.scala:17-61).
PRIORITY_INPUT = 0
PRIORITY_SHUFFLE_OUTPUT = -1000
PRIORITY_ON_DECK = 1000
# Cross-query fragment-cache entries (history.fragcache): the MOST
# spillable band — a cached fragment is a speculative reuse bet and must
# yield HBM before any live query's inputs or shuffle outputs.
PRIORITY_FRAGMENT = -2000
# Front-door result-cache entries (serve.resultcache): below even
# fragments — a final result set was already delivered to its client, so
# keeping it resident is the purest reuse bet of all and yields first.
PRIORITY_RESULT = -3000

#: Bounded wait slice (seconds) for every blocking loop in this module:
#: notify still wakes immediately, the bound only caps the C-level block so
#: the fault watchdog's async PartitionTimeout can land (lint rule R3).
_WAIT_SLICE = 0.25


def device_batch_bytes(batch: ColumnBatch) -> int:
    total = 0
    for c in batch.columns:
        total += int(np.dtype(c.data.dtype).itemsize) * int(c.data.size)
        total += int(c.validity.size)
        if c.offsets is not None:
            total += 4 * int(c.offsets.size)
        if c.codes is not None:
            total += 4 * int(c.codes.size)
    return total


def device_batch_shard_bytes(batch: ColumnBatch) -> List[int]:
    """Per-device resident bytes of a MESH-SHARDED batch (every leaf a
    multi-device global array), ordered by device id.  Pure addressable-
    shard metadata — shapes and dtypes, never a transfer or sync — so the
    mesh-SPMD dispatcher can account a fused stage's HBM footprint per
    shard (and obs can report bytes_per_device) without touching the
    arrays.  Sums to :func:`device_batch_bytes` of the global batch for
    the standard int32-offsets/codes layout."""
    per: dict = {}

    def _add(arr) -> None:
        for s in arr.addressable_shards:
            per[s.device] = per.get(s.device, 0) + int(s.data.nbytes)

    for c in batch.columns:
        _add(c.data)
        _add(c.validity)
        if c.offsets is not None:
            _add(c.offsets)
        if c.codes is not None:
            _add(c.codes)
    return [per[d] for d in sorted(per, key=lambda d: d.id)]


class _SpillTask:
    """One in-flight tier move.  ``state`` transitions are guarded by the
    owning catalog's lock (queued -> running -> done, or queued ->
    cancelled); ``_done`` signals completion to joiners with bounded
    waits."""

    __slots__ = ("handle", "bytes", "state", "error", "_done", "scope")

    QUEUED, RUNNING, DONE, CANCELLED = "queued", "running", "done", \
        "cancelled"

    def __init__(self, handle: "SpillableBatch"):
        self.handle = handle
        self.bytes = handle.device_bytes
        self.state = self.QUEUED
        self.error: Optional[BaseException] = None
        self._done = threading.Event()
        # the query whose memory pressure queued this move: the writer
        # thread adopts it so spill events/transfer counters attribute
        # to the right query under concurrent serving
        self.scope = obs_events.current_scope()

    def mark_done(self) -> None:
        self._done.set()

    def wait_done(self) -> None:
        while not self._done.wait(_WAIT_SLICE):
            pass


class SpillableBatch:
    """Operator-facing handle for a batch that may move between tiers.

    Tier state machine (v2)::

        DEVICE --begin spill--> SPILLING --writer D2H--> HOST --> DISK
          ^                        |                      |        |
          |<---cancel (get race)---+      get() unspill --+--------+
          |
        LOST (device loss with no surviving copy; get() raises classified)

    SPILLING covers both directions of the middle hop: a device->host D2H
    on the writer (cancellable while queued) and a host->disk
    compress+write (runs to completion; get() joins it).
    """

    TIER_DEVICE, TIER_HOST, TIER_DISK, TIER_LOST, TIER_SPILLING = \
        0, 1, 2, 3, 4

    def __init__(self, catalog: "BufferCatalog", batch_id: int,
                 device_batch: ColumnBatch, priority: int):
        self._catalog = catalog
        self.batch_id = batch_id
        self.priority = priority
        self.tier = self.TIER_DEVICE
        self._device: Optional[ColumnBatch] = device_batch
        self._host: Optional[HostBatch] = None
        self._disk_path: Optional[str] = None
        self._schema = device_batch.schema
        self._capacity = device_batch.capacity
        self.device_bytes = device_batch_bytes(device_batch)
        #: host bytes, computed ONCE when the host copy materializes
        self._host_nbytes = 0
        #: in-flight tier move, None when settled (guarded by catalog lock)
        self._spill_task: Optional[_SpillTask] = None
        #: writer-thread failure awaiting the consumer's next get()
        self._pending_error: Optional[BaseException] = None
        self.closed = False

    # -- disk frames (catalog-internal) -------------------------------------

    def _write_disk(self, host: HostBatch, directory: str) -> int:
        """Serialize + chunk-compress ``host`` to the disk tier; returns
        encoded bytes written.  Pure IO — caller owns tier transitions."""
        from spark_rapids_tpu.mem.codec import get_codec, write_chunked
        from spark_rapids_tpu.native_rt import serialize_host_batch
        codec = get_codec(self._catalog.spill_codec)
        raw = serialize_host_batch(host)
        path = os.path.join(directory, f"spill-{self.batch_id}.tpub")
        with open(path, "wb") as f:
            enc = write_chunked(f, raw, codec, self._catalog.spill_chunk_bytes)
        self._disk_path = path
        return enc

    def _read_disk(self) -> HostBatch:
        from spark_rapids_tpu.mem.codec import get_codec, read_chunked
        from spark_rapids_tpu.native_rt import deserialize_host_batch
        codec = get_codec(self._catalog.spill_codec)
        with open(self._disk_path, "rb") as f:
            raw = read_chunked(f, codec)
        return deserialize_host_batch(raw, self._schema)

    def host_bytes(self) -> int:
        """Host bytes this handle's host-tier copy occupies (cached at
        spill time — never a per-call value walk)."""
        return self._host_nbytes if self._host is not None else 0

    # -- public -------------------------------------------------------------

    def get(self) -> ColumnBatch:
        """Materialize on device (joining an in-flight spill and/or
        unspilling as needed)."""
        assert not self.closed
        cat = self._catalog
        while True:
            with cat._lock:
                err = self._pending_error
                if err is not None:
                    # a writer-thread spill failed: surface the classified
                    # error ONCE (the handle already reverted to its prior
                    # tier, so the recovery ladder's replay will succeed)
                    self._pending_error = None
                    raise err
                tier = self.tier
                if tier == self.TIER_LOST:
                    from spark_rapids_tpu.fault.errors import DeviceLostError
                    raise DeviceLostError(
                        f"spillable batch {self.batch_id} was "
                        "device-resident when the device was lost and no "
                        "host/disk copy survived; its lineage must be "
                        "recomputed")
                if tier == self.TIER_DEVICE:
                    return self._device
                task = self._spill_task
                if tier == self.TIER_SPILLING and task is not None \
                        and task.state == _SpillTask.QUEUED:
                    # won the race against an unstarted spill: cancel
                    # cheaply — the device copy never moved
                    cat._cancel_spill_locked(self, task)
                    dev = self._device
                    cancelled = True
                else:
                    cancelled = False
                if tier in (self.TIER_HOST, self.TIER_DISK):
                    break
            if cancelled:
                # the budget pressure that picked this handle has not gone
                # away: re-run the loop (off the lock) so it lands on a
                # victim the consumer is NOT about to read
                cat.reserve(0, exclude=self.batch_id)
                return dev
            # spill in flight and already running: join THIS handle's
            # completion (not the writer queue), then re-examine
            if task is not None:
                task.wait_done()
        return self._unspill(tier)

    def _unspill(self, tier: int) -> ColumnBatch:
        """Rehydrate from host or disk.  IO runs off the lock; tier
        transitions and counters update under it."""
        from spark_rapids_tpu.fault import inject
        cat = self._catalog
        un_t0 = time.monotonic_ns()
        inject.maybe_fire("unspill")
        host = self._read_disk() if tier == self.TIER_DISK else self._host
        with cat._lock:
            raced = self.tier != tier
            if not raced:
                # Mark device-resident BEFORE reserving so the budget loop
                # cannot pick this handle as its own spill victim
                # mid-rehydration; keep the host copy until the upload
                # lands so a failure can revert.
                if tier == self.TIER_HOST:
                    cat._host_bytes -= self._host_nbytes
                self.tier = self.TIER_DEVICE
                cat._device_bytes += self.device_bytes
                cat.metrics["unspilled"] += 1
        if raced:
            # lost to a concurrent get()/spill that moved the handle:
            # retry the state machine from the top, OUTSIDE the lock (the
            # retry may join a writer task that needs it)
            return self.get()
        try:
            cat.reserve(self.device_bytes, exclude=self.batch_id)
            dev = host_to_device(host, capacity=self._capacity)
        except BaseException:
            with cat._lock:
                if self.tier == self.TIER_DEVICE and self._device is None:
                    self.tier = tier
                    cat._device_bytes -= self.device_bytes
                    cat.metrics["unspilled"] -= 1
                    if tier == self.TIER_HOST:
                        cat._host_bytes += self._host_nbytes
            raise
        with cat._lock:
            self._device = dev
            self._host = None
            self._host_nbytes = 0
        if tier == self.TIER_DISK and self._disk_path:
            if os.path.exists(self._disk_path):
                os.unlink(self._disk_path)
            self._disk_path = None
        obs_events.emit_span(
            "unspill", "disk" if tier == self.TIER_DISK else "host",
            t0=un_t0, t1=time.monotonic_ns(), bytes=self.device_bytes)
        return dev

    def close(self):
        cat = self._catalog
        with cat._lock:
            if self.closed:
                return
            self.closed = True
            task = self._spill_task
            if task is not None and task.state == _SpillTask.QUEUED:
                cat._cancel_spill_locked(self, task)
            # a RUNNING task finishes on the writer; its finalize sees
            # ``closed`` and drops the copy
            if self.tier == self.TIER_DEVICE:
                cat._device_bytes -= self.device_bytes
            elif self.tier == self.TIER_HOST:
                cat._host_bytes -= self._host_nbytes
            self._device = None
            self._host = None
            self._host_nbytes = 0
            path = self._disk_path
            self._disk_path = None
            cat._handles.pop(self.batch_id, None)
        if path and os.path.exists(path):
            os.unlink(path)


class BufferCatalog:
    """Process-wide registry of spillable batches with a device budget."""

    def __init__(self, conf: RapidsConf):
        self.conf = conf
        self.device_budget = DEVICE_SPILL_BUDGET.get(conf)
        self.host_budget = conf.host_spill_storage_size
        self.spill_codec = conf.get(
            "spark.rapids.shuffle.compression.codec", "copy") or "copy"
        self.async_spill = SPILL_ASYNC_ENABLED.get(conf)
        self.writer_threads = max(1, SPILL_WRITER_THREADS.get(conf))
        self.spill_chunk_bytes = SPILL_CHUNK_BYTES.get(conf)
        self._handles: Dict[int, SpillableBatch] = {}
        self._next_id = 0
        self._lock = threading.RLock()
        self._spill_dir: Optional[str] = None
        # -- incremental accounting: running per-tier byte counters updated
        # at every transition (verify_accounting asserts == scan)
        self._device_bytes = 0
        self._host_bytes = 0
        # -- async writer pool state (lazily started)
        self._queue: Deque[_SpillTask] = deque()
        self._queue_cond = threading.Condition(self._lock)
        self._writers: List[threading.Thread] = []
        self._writers_busy = 0
        self.metrics = {"spilled_to_host": 0, "spilled_to_disk": 0,
                        "unspilled": 0, "spill_cancelled": 0,
                        "spill_wall_ns": 0, "spill_queue_depth_max": 0,
                        "unspill_prefetch_hits": 0,
                        "spill_to_host_bytes": 0, "spill_to_disk_bytes": 0}

    def _dir(self) -> str:
        if self._spill_dir is None:
            self._spill_dir = tempfile.mkdtemp(prefix="rapids_tpu_spill_")
        return self._spill_dir

    # -- registry -----------------------------------------------------------

    def register(self, batch: ColumnBatch,
                 priority: int = PRIORITY_INPUT) -> SpillableBatch:
        with self._lock:
            h = SpillableBatch(self, self._next_id, batch, priority)
            self._next_id += 1
            self._handles[h.batch_id] = h
            self._device_bytes += h.device_bytes
        # budget enforcement OUTSIDE the registry mutation: a synchronous
        # spill's D2H/compress must not stall concurrent register/get
        self.reserve(0, exclude=h.batch_id)
        return h

    def register_sharded(self, batch: ColumnBatch,
                         priority: int = PRIORITY_ON_DECK) -> SpillableBatch:
        """Register a MESH-SHARDED batch (every leaf a multi-device global
        array) ONCE: one handle covers all shards, ``device_bytes`` is the
        global total and ``handle.shard_bytes`` carries the per-device
        split (:func:`device_batch_shard_bytes`).  Defaults to
        PRIORITY_ON_DECK — the least spillable band — because a victim
        pass spilling a sharded global would D2H-gather every shard and
        rehydrate to ONE device; the mesh-SPMD dispatcher holds such
        handles only across the unshard window and closes them before the
        per-device outputs flow downstream."""
        h = self.register(batch, priority)
        h.shard_bytes = device_batch_shard_bytes(batch)
        return h

    def _unregister(self, h: SpillableBatch):
        with self._lock:
            self._handles.pop(h.batch_id, None)

    # -- accounting ---------------------------------------------------------

    def device_bytes_in_use(self) -> int:
        """O(1): the running device-tier counter (v1 re-scanned every
        handle per budget-loop iteration)."""
        with self._lock:
            return self._device_bytes

    def host_bytes_in_use(self) -> int:
        with self._lock:
            return self._host_bytes

    # -- telemetry gauges (obs.timeseries; sampled at export time) ----------

    def writer_utilization(self) -> float:
        """Fraction of the spill-writer pool running a task right now."""
        with self._lock:
            return self._writers_busy / float(max(1, self.writer_threads))

    def writer_queue_depth(self) -> int:
        """Spill tasks queued but not yet picked up by a writer."""
        with self._lock:
            return len(self._queue)

    def tier_bytes(self) -> Dict[str, int]:
        """Bytes resident per tier right now: the device/host running
        counters plus a disk scan over spilled files (OSError-tolerant —
        a file mid-delete reads as absent)."""
        with self._lock:
            disk = 0
            for h in self._handles.values():
                path = h._disk_path
                if path:
                    try:
                        disk += os.path.getsize(path)
                    except OSError:
                        continue
            return {"device": self._device_bytes,
                    "host": self._host_bytes, "disk": disk}

    def verify_accounting(self) -> List[str]:
        """Debug invariant (analysis/plan_verify.py): the incremental
        counters must equal a full scan at any lock-quiesced instant —
        every transition updates both tier and counter under the lock."""
        with self._lock:
            dev = sum(h.device_bytes for h in self._handles.values()
                      if h.tier == SpillableBatch.TIER_DEVICE)
            host = sum(h._host_nbytes for h in self._handles.values()
                       if h.tier == SpillableBatch.TIER_HOST)
            problems = []
            if dev != self._device_bytes:
                problems.append(
                    f"catalog device-bytes counter {self._device_bytes} != "
                    f"scan {dev}")
            if host != self._host_bytes:
                problems.append(
                    f"catalog host-bytes counter {self._host_bytes} != "
                    f"scan {host}")
            return problems

    def verify_encoded_host_batches(self) -> List[str]:
        """Encoded-corridor invariant half (analysis/plan_verify.py): a
        host-tier handle holding dictionary-encoded columns must be
        structurally reconstructible — non-empty dictionary, integer
        codes inside it — or unspill would rebuild a different column."""
        with self._lock:
            hosts = [(hid, h._host) for hid, h in self._handles.items()
                     if h.tier == SpillableBatch.TIER_HOST and
                     h._host is not None]
        problems = []
        for hid, hb in hosts:
            for f, c in zip(hb.schema.fields, hb.columns):
                if c.dictionary is None:
                    continue
                codes = np.asarray(c.values)
                nd = len(c.dictionary)
                if codes.dtype.kind not in "iu":
                    problems.append(
                        f"catalog handle {hid}: encoded column {f.name!r} "
                        f"has non-integer codes dtype {codes.dtype}")
                elif nd == 0:
                    problems.append(
                        f"catalog handle {hid}: encoded column {f.name!r} "
                        "has an empty dictionary")
                elif len(codes) and (int(codes.min()) < 0 or
                                     int(codes.max()) >= nd):
                    problems.append(
                        f"catalog handle {hid}: encoded column {f.name!r} "
                        f"codes outside [0, {nd})")
        return problems

    # -- spill state machine ------------------------------------------------

    def _begin_spill_locked(self, victim: SpillableBatch) -> _SpillTask:
        """DEVICE -> SPILLING under the lock: the victim's bytes leave the
        device counter now (the copy is committed to go), the task carries
        the work."""
        task = _SpillTask(victim)
        victim._spill_task = task
        victim.tier = SpillableBatch.TIER_SPILLING
        self._device_bytes -= victim.device_bytes
        self.metrics["spilled_to_host"] += 1
        return task

    def _cancel_spill_locked(self, h: SpillableBatch,
                             task: _SpillTask) -> None:
        """SPILLING -> DEVICE for a still-queued task (get() won the race,
        or the handle closed): the device copy never moved."""
        task.state = _SpillTask.CANCELLED
        task.mark_done()
        h._spill_task = None
        h.tier = SpillableBatch.TIER_DEVICE
        self._device_bytes += h.device_bytes
        self.metrics["spilled_to_host"] -= 1
        self.metrics["spill_cancelled"] += 1
        obs_events.emit_instant("spill", "cancelled")

    def _submit(self, task: _SpillTask) -> None:
        with self._lock:
            self._ensure_writers_locked()
            self._queue.append(task)
            depth = len(self._queue)
            if depth > self.metrics["spill_queue_depth_max"]:
                self.metrics["spill_queue_depth_max"] = depth
            self._queue_cond.notify()

    def _ensure_writers_locked(self) -> None:
        self._writers = [t for t in self._writers if t.is_alive()]
        while len(self._writers) < self.writer_threads:
            t = threading.Thread(target=self._writer_loop, daemon=True,
                                 name=f"spill-writer-{len(self._writers)}")
            self._writers.append(t)
            t.start()

    def _writer_loop(self) -> None:
        while True:
            with self._queue_cond:
                while not self._queue:
                    self._queue_cond.wait(_WAIT_SLICE)
                task = self._queue.popleft()
                self._writers_busy += 1
            try:
                with obs_events.adopt(task.scope):
                    self._run_spill_task(task)
            finally:
                with self._lock:
                    self._writers_busy -= 1

    def _run_spill_task(self, task: _SpillTask,
                        raise_errors: bool = False) -> None:
        """Execute one device->host spill: D2H off the lock, finalize under
        it, then host-budget enforcement (compress+write, also off-lock).

        ``raise_errors`` is the synchronous mode (async disabled, or the
        eager OOM path): the failure reverts the handle and propagates to
        the triggering caller — exact v1 semantics.  Async mode stashes
        the error on the handle for the consumer's next ``get()``.
        """
        h = task.handle
        t0 = time.monotonic_ns()
        with self._lock:
            if task.state != _SpillTask.QUEUED:
                return  # cancelled while queued
            task.state = _SpillTask.RUNNING
            dev = h._device
        try:
            from spark_rapids_tpu.fault import inject
            inject.maybe_fire("spill")
            host = device_to_host(dev, keep_dictionary=True)
            nbytes = host_batch_bytes(host)
            with self._lock:
                live = h._spill_task is task and \
                    h.tier == SpillableBatch.TIER_SPILLING and not h.closed
                if live:
                    h._host = host
                    h._host_nbytes = nbytes
                    h._device = None
                    h.tier = SpillableBatch.TIER_HOST
                    self._host_bytes += nbytes
                    self.metrics["spill_to_host_bytes"] += nbytes
                    # the copy is safe on host now: an earlier attempt's
                    # stashed failure is moot, don't fail a later get()
                    h._pending_error = None
                # else: aborted (invalidate/close) mid-copy — drop the copy
            obs_events.emit_span("spill", "to_host", t0=t0,
                                 t1=time.monotonic_ns(),
                                 bytes=nbytes if live else 0)
        except BaseException as e:
            with self._lock:
                if h._spill_task is task and \
                        h.tier == SpillableBatch.TIER_SPILLING:
                    # revert: the device copy is untouched, so a replay
                    # after the surfaced error succeeds bit-identically
                    h.tier = SpillableBatch.TIER_DEVICE
                    self._device_bytes += h.device_bytes
                    self.metrics["spilled_to_host"] -= 1
                    if not raise_errors:
                        h._pending_error = e
                task.error = e
            obs_events.emit_instant("spill", "error",
                                    error_type=type(e).__name__)
            if raise_errors or not isinstance(e, Exception):
                raise
            return
        finally:
            with self._lock:
                if h._spill_task is task:
                    h._spill_task = None
                task.state = _SpillTask.DONE
                self.metrics["spill_wall_ns"] += time.monotonic_ns() - t0
            task.mark_done()
        self._enforce_host_budget(raise_errors=raise_errors)

    # -- budget enforcement -------------------------------------------------

    def reserve(self, incoming_bytes: int, exclude: int = -1):
        """Spill until (in_use + incoming) fits the budget (the
        synchronousSpill loop, RapidsBufferStore.scala:144).  Victim
        selection and the SPILLING transition happen under the lock; the
        copy itself runs on the writer pool (async) or inline off the lock
        (sync) — either way concurrent register/get never stall behind a
        multi-GB D2H."""
        while True:
            with self._lock:
                if self._device_bytes + incoming_bytes <= self.device_budget:
                    return
                victim = self._pick_victim(
                    SpillableBatch.TIER_DEVICE, exclude)
                if victim is None:
                    return
                task = self._begin_spill_locked(victim)
            if self.async_spill:
                obs_events.emit_instant("spill", "queued",
                                        bytes=victim.device_bytes)
                self._submit(task)
            else:
                self._run_spill_task(task, raise_errors=True)

    def _enforce_host_budget(self, raise_errors: bool = False):
        """Push host-tier handles to disk until the host store fits.  The
        victim transitions to SPILLING under the lock; serialize +
        chunk-compress + write run OUTSIDE it (v1 held the lock through
        the whole compress+write, stalling every register/get)."""
        while True:
            with self._lock:
                if self._host_bytes <= self.host_budget:
                    return
                victim = self._pick_victim(SpillableBatch.TIER_HOST, -1)
                if victim is None:
                    return
                task = _SpillTask(victim)
                task.state = _SpillTask.RUNNING
                victim._spill_task = task
                victim.tier = SpillableBatch.TIER_SPILLING
                self._host_bytes -= victim._host_nbytes
                host = victim._host
            t0 = time.monotonic_ns()
            try:
                enc = victim._write_disk(host, self._dir())
                with self._lock:
                    if victim.closed:
                        path = victim._disk_path
                        victim._disk_path = None
                    else:
                        path = None
                        victim._host = None
                        victim._host_nbytes = 0
                        victim.tier = SpillableBatch.TIER_DISK
                        victim._pending_error = None
                        self.metrics["spilled_to_disk"] += 1
                        self.metrics["spill_to_disk_bytes"] += enc
                if path and os.path.exists(path):
                    os.unlink(path)
            except BaseException as e:
                with self._lock:
                    if victim._spill_task is task and \
                            victim.tier == SpillableBatch.TIER_SPILLING:
                        victim.tier = SpillableBatch.TIER_HOST
                        self._host_bytes += victim._host_nbytes
                        if not raise_errors:
                            victim._pending_error = e
                    task.error = e
                    task.state = _SpillTask.DONE
                    if victim._spill_task is task:
                        victim._spill_task = None
                task.mark_done()
                if raise_errors or not isinstance(e, Exception):
                    raise
                return
            with self._lock:
                task.state = _SpillTask.DONE
                if victim._spill_task is task:
                    victim._spill_task = None
                self.metrics["spill_wall_ns"] += time.monotonic_ns() - t0
            task.mark_done()
            obs_events.emit_span("spill", "to_disk", t0=t0,
                                 t1=time.monotonic_ns(), bytes=enc)

    def drain_spills(self) -> None:
        """Join every in-flight async spill (tests, bench, shutdown
        barriers).  Queued tasks run to completion; the wait is bounded
        per slice (watchdog-compatible).

        A writer thread clears its D2H task *before* it runs host-budget
        enforcement, so "no tasks visible" does not yet mean the host
        store fits: the host->disk push may not have started.  The host
        bytes ARE counted by then, so running enforcement here closes
        that window — it either does the push itself or loses the victim
        pick to the writer's concurrent loop, and the re-check below
        waits out whichever task that created."""
        while True:
            with self._lock:
                tasks = [h._spill_task for h in self._handles.values()
                         if h._spill_task is not None]
            if not tasks:
                self._enforce_host_budget()
                with self._lock:
                    tasks = [h._spill_task for h in self._handles.values()
                             if h._spill_task is not None]
                if not tasks:
                    return
            for t in tasks:
                t.wait_done()

    # -- OOM / device-loss entry points -------------------------------------

    def handle_alloc_failure(self, pinned=()) -> int:
        """Spill ALL device-tier spillables; bytes freed.

        The DeviceMemoryEventHandler role (DeviceMemoryEventHandler.scala:35):
        RMM invokes the reference's handler from inside a failed cudaMalloc;
        XLA exposes no such callback, so the engine instead catches the
        RESOURCE_EXHAUSTED runtime error at dispatch boundaries
        (:func:`run_with_oom_retry`) and calls this.  A real device OOM means
        the soft budget under-counted (unregistered transients, fragmentation),
        so everything spillable goes to host, not just down to the budget.

        Always EAGER — every spill completes (and every already-in-flight
        async spill is joined) before this returns, so the caller's retry
        runs against freed HBM — but the copies execute OFF the catalog
        lock: concurrent register/get don't stall behind them.

        ``pinned`` holds batches the retrying computation still references
        (its input args): spilling those would free nothing — the jax buffers
        stay alive through the caller's reference — while marking the handle
        host-tier, so a later ``get()`` would allocate a SECOND device copy.
        They are skipped and excluded from the freed count.
        """
        # Pin by LEAF array identity, not batch-wrapper identity: colocation
        # may rebuild wrappers around the same device arrays, and only a
        # handle whose underlying buffers are aliased by the retrying args
        # is futile to spill.
        import jax
        pinned_ids = {id(leaf) for b in pinned
                      for leaf in jax.tree_util.tree_leaves(b)}
        freed = 0
        mine: List[_SpillTask] = []
        inflight: List[_SpillTask] = []
        with self._lock:
            victims = sorted(
                (h for h in self._handles.values()
                 if h.tier == SpillableBatch.TIER_DEVICE and not h.closed
                 and h._device is not None
                 and not any(id(leaf) in pinned_ids for leaf in
                             jax.tree_util.tree_leaves(h._device))),
                key=lambda h: (h.priority, h.batch_id))
            for victim in victims:
                freed += victim.device_bytes
                mine.append(self._begin_spill_locked(victim))
            for h in self._handles.values():
                t = h._spill_task
                if t is not None and t not in mine:
                    inflight.append(t)
        for task in mine:
            self._run_spill_task(task, raise_errors=True)
        for task in inflight:
            # a spill the writer already owns frees HBM too once joined —
            # count it so the retry isn't abandoned as futile
            task.wait_done()
            if task.error is None and task.state == _SpillTask.DONE:
                freed += task.bytes
        if mine or inflight:
            self._enforce_host_budget(raise_errors=True)
        if freed:
            with self._lock:
                self.metrics["oom_spill_bytes"] = \
                    self.metrics.get("oom_spill_bytes", 0) + freed
        return freed

    def invalidate_device_tier(self, rescue: bool = True) -> int:
        """Device-lost recovery (fault.recovery): every device-tier
        handle is rescued to host when the buffers still answer (the
        simulated-fault case — and real losses where XLA kept the copy
        readable), else marked TIER_LOST so a later ``get()`` raises a
        classified DeviceLostError and the consumer's replay recomputes
        the batch from lineage.  ``rescue=False`` (timeout-classified
        recovery: the device is WEDGED, a rescue D2H against it would
        block the recovery path on the very hang being recovered from)
        marks device-tier handles lost without touching the device.
        Host- and disk-tier handles are untouched: they re-upload
        lazily on the next ``get()``.  Returns the number of handles
        that transitioned.

        In-flight spills are drained/aborted FIRST: queued writer tasks
        are cancelled (their device copies are handled here instead);
        running ones are abandoned when ``rescue=False`` (their D2H may
        be the very hang being recovered from — the late finalize sees
        the LOST tier and drops its copy) or joined briefly when
        rescuing.
        """
        running: List[_SpillTask] = []
        with self._lock:
            for h in list(self._handles.values()):
                t = h._spill_task
                if t is None or h.closed:
                    continue
                if t.state == _SpillTask.QUEUED:
                    self._cancel_spill_locked(h, t)
                elif t.state == _SpillTask.RUNNING:
                    running.append(t)
        if rescue:
            for t in running:
                t.wait_done()
        moved = 0
        with self._lock:
            for h in list(self._handles.values()):
                if h.closed or h.tier not in (SpillableBatch.TIER_DEVICE,
                                              SpillableBatch.TIER_SPILLING):
                    continue
                was_spilling = h.tier == SpillableBatch.TIER_SPILLING
                moved += 1
                if rescue and not was_spilling:
                    try:
                        host = device_to_host(h._device,
                                              keep_dictionary=True)
                        h._host = host
                        h._host_nbytes = host_batch_bytes(host)
                        h._device = None
                        h.tier = SpillableBatch.TIER_HOST
                        self._device_bytes -= h.device_bytes
                        self._host_bytes += h._host_nbytes
                        self.metrics["spilled_to_host"] += 1
                        continue
                    except Exception:  # noqa: BLE001 — buffers truly gone
                        pass
                if not was_spilling:
                    self._device_bytes -= h.device_bytes
                h._device = None
                h._host = None
                h._host_nbytes = 0
                h._spill_task = None
                h.tier = SpillableBatch.TIER_LOST
                self.metrics["lost_batches"] = \
                    self.metrics.get("lost_batches", 0) + 1
            if moved:
                self.metrics["device_invalidated"] = \
                    self.metrics.get("device_invalidated", 0) + moved
        if moved:
            self._enforce_host_budget()
        return moved

    # -- overlapped unspill --------------------------------------------------

    def prefetch(self, handles: Sequence[SpillableBatch],
                 depth: int = 1) -> Iterator[ColumnBatch]:
        """Yield each handle's device batch with up to ``depth`` unspills
        in flight ahead of the consumer: handle i+1's disk read +
        decompress + async H2D enqueue overlaps compute on batch i (the
        shuffle drain's one-piece read-ahead, generalized to any handle
        list).  Admission stays with the existing machinery — ``get()``'s
        reserve() bounds device bytes and the consumer task's semaphore
        permit is already held (re-entrant, task-wide) — so read-ahead
        cannot blow the budget or leak depth."""
        handles = list(handles)
        if not handles:
            return
        depth = max(1, depth)

        def _fetch(h: SpillableBatch) -> ColumnBatch:
            if h.tier != SpillableBatch.TIER_DEVICE:
                # the read-ahead actually hid an unspill (vs a device hit)
                with self._lock:
                    self.metrics["unspill_prefetch_hits"] += 1
                obs_events.emit_instant("unspill", "prefetch_hit")
            return h.get()

        window: Deque[ColumnBatch] = deque()
        nxt = 0
        while nxt < len(handles) and len(window) < depth:
            window.append(_fetch(handles[nxt]))
            nxt += 1
        while window:
            cur = window.popleft()
            if nxt < len(handles):
                window.append(_fetch(handles[nxt]))
                nxt += 1
            yield cur

    # -- victim selection ----------------------------------------------------

    def _pick_victim(self, tier: int, exclude: int
                     ) -> Optional[SpillableBatch]:
        best = None
        for h in self._handles.values():
            if h.tier != tier or h.batch_id == exclude or h.closed:
                continue
            if tier == SpillableBatch.TIER_DEVICE and h._device is None:
                continue  # mid-rehydration (get() marked early)
            if h._pending_error is not None:
                # a failed writer spill reverted this handle; re-picking
                # it before a get() consumed the error would livelock the
                # budget loop against a persistent fault
                continue
            if best is None or h.priority < best.priority or \
                    (h.priority == best.priority and
                     h.batch_id < best.batch_id):
                best = h
        return best


def is_device_oom(err: BaseException) -> bool:
    """True when ``err`` is an XLA out-of-device-memory failure.  JAX raises
    ``XlaRuntimeError``/``JaxRuntimeError`` whose message carries the ABSL
    status code name; allocation failures are RESOURCE_EXHAUSTED."""
    return "RESOURCE_EXHAUSTED" in str(err) \
        and type(err).__name__ in ("XlaRuntimeError", "JaxRuntimeError")


def run_with_oom_retry(catalog: "BufferCatalog", thunk,
                       retries: Optional[int] = None,
                       pinned=(), on_retry=None):
    """Run ``thunk`` and, on a device OOM, spill everything spillable and
    re-run — the engine-side analogue of the reference's alloc-failure →
    synchronous-spill → retry loop (DeviceMemoryEventHandler.scala:35,
    RmmRapidsRetryIterator.scala's withRetry).

    Thin wrapper over the unified fault machinery: the error must
    classify RETRYABLE_OOM (fault.errors — covers real XLA
    RESOURCE_EXHAUSTED and injected OOMs alike) and the attempt bound
    comes from the one RetryPolicy
    (``spark.rapids.sql.tpu.retry.maxAttempts``) unless ``retries``
    overrides it (``retries=0`` = fail fast, the donated-dispatch
    path).  No backoff sleep here: the corrective action (the spill)
    already completed synchronously, so there is no transient condition
    to wait out — backoff belongs to the device-lost replay ladder.
    Still gives up early when a retry frees nothing — spilling can no
    longer help.  ``pinned``: batches the thunk re-reads on retry (see
    :meth:`BufferCatalog.handle_alloc_failure`).
    """
    from spark_rapids_tpu.fault import metrics as fault_metrics
    from spark_rapids_tpu.fault.errors import ErrorClass, classify_error
    from spark_rapids_tpu.fault.retry import RetryPolicy
    max_attempts = RetryPolicy.from_conf(catalog.conf).max_attempts \
        if retries is None else retries + 1
    attempt = 0
    while True:
        attempt += 1
        try:
            return thunk()
        except Exception as e:  # noqa: BLE001 — filtered by classification
            if classify_error(e) is not ErrorClass.RETRYABLE_OOM or \
                    attempt >= max_attempts:
                raise
            freed = catalog.handle_alloc_failure(pinned=pinned)
            if freed == 0:
                raise
            if on_retry is not None:
                on_retry(freed)
            fault_metrics.record("retries")
