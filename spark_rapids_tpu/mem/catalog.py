"""Spillable-buffer catalog with device -> host -> disk tiers.

Reference analogues: RapidsBufferCatalog.scala:36 (id->buffer registry wiring
the spill chain), RapidsBufferStore.scala:39 (priority-ordered spillable
tracking + synchronousSpill), RapidsDeviceMemoryStore / RapidsHostMemoryStore
/ RapidsDiskStore, SpillableColumnarBatch.scala:27 (operator-facing handle),
SpillPriorities.scala.

TPU adaptation: XLA owns HBM allocation and exposes no alloc-failure callback
(the RMM event-handler hook, DeviceMemoryEventHandler.scala:35).  Instead the
catalog enforces a *budget*: every operator that holds batches across
pipeline breaks registers them as SpillableBatch handles; when registered
device bytes exceed the budget the catalog synchronously spills
lowest-priority handles to host numpy, and past the host-store bound to disk
(.npz files) — same three tiers, push model instead of callback model.
"""

from __future__ import annotations

import os
import tempfile
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import (
    ColumnBatch, HostBatch, device_to_host, host_to_device,
)
from spark_rapids_tpu.config import RapidsConf, conf_bytes

DEVICE_SPILL_BUDGET = conf_bytes(
    "spark.rapids.memory.tpu.spillBudgetBytes", 8 << 30,
    "Device bytes the catalog lets spillable batches occupy before "
    "synchronously spilling lowest-priority ones to host.")

# Spill priority bands (SpillPriorities.scala:17-61).
PRIORITY_INPUT = 0
PRIORITY_SHUFFLE_OUTPUT = -1000
PRIORITY_ON_DECK = 1000


def device_batch_bytes(batch: ColumnBatch) -> int:
    total = 0
    for c in batch.columns:
        total += int(np.dtype(c.data.dtype).itemsize) * int(c.data.size)
        total += int(c.validity.size)
        if c.offsets is not None:
            total += 4 * int(c.offsets.size)
    return total


class SpillableBatch:
    """Operator-facing handle for a batch that may move between tiers."""

    TIER_DEVICE, TIER_HOST, TIER_DISK, TIER_LOST = 0, 1, 2, 3

    def __init__(self, catalog: "BufferCatalog", batch_id: int,
                 device_batch: ColumnBatch, priority: int):
        self._catalog = catalog
        self.batch_id = batch_id
        self.priority = priority
        self.tier = self.TIER_DEVICE
        self._device: Optional[ColumnBatch] = device_batch
        self._host: Optional[HostBatch] = None
        self._disk_path: Optional[str] = None
        self._schema = device_batch.schema
        self._capacity = device_batch.capacity
        self.device_bytes = device_batch_bytes(device_batch)
        self.closed = False

    # -- tier moves (catalog-internal) --------------------------------------

    def _spill_to_host(self):
        assert self.tier == self.TIER_DEVICE
        from spark_rapids_tpu.fault import inject
        inject.maybe_fire("spill")
        self._host = device_to_host(self._device)
        self._device = None
        self.tier = self.TIER_HOST

    def _spill_to_disk(self, directory: str):
        """Disk tier: one file per batch in the engine's native frame format
        (native_rt serializer = JCudfSerialization analogue) run through the
        configured compression codec (TableCompressionCodec analogue)."""
        assert self.tier == self.TIER_HOST
        import struct

        from spark_rapids_tpu.mem.codec import get_codec
        from spark_rapids_tpu.native_rt import serialize_host_batch
        codec = get_codec(self._catalog.spill_codec)
        raw = serialize_host_batch(self._host)
        enc = codec.compress(raw)
        path = os.path.join(directory, f"spill-{self.batch_id}.tpub")
        with open(path, "wb") as f:
            f.write(struct.pack("<Q", len(raw)))
            f.write(enc)
        self._disk_path = path
        self._host = None
        self.tier = self.TIER_DISK

    def _read_disk(self) -> HostBatch:
        import struct

        from spark_rapids_tpu.mem.codec import get_codec
        from spark_rapids_tpu.native_rt import deserialize_host_batch
        codec = get_codec(self._catalog.spill_codec)
        with open(self._disk_path, "rb") as f:
            (raw_len,) = struct.unpack("<Q", f.read(8))
            enc = f.read()
        raw = codec.decompress(enc, raw_len)
        return deserialize_host_batch(raw, self._schema)

    def host_bytes(self) -> int:
        if self._host is None:
            return 0
        total = 0
        for c in self._host.columns:
            if c.dtype.is_string:
                total += sum(len(str(x)) for x in c.values) + len(c.values)
            else:
                total += c.values.nbytes
            total += c.validity.nbytes
        return total

    # -- public -------------------------------------------------------------

    def get(self) -> ColumnBatch:
        """Materialize on device (unspilling if needed)."""
        assert not self.closed
        if self.tier == self.TIER_LOST:
            from spark_rapids_tpu.fault.errors import DeviceLostError
            raise DeviceLostError(
                f"spillable batch {self.batch_id} was device-resident "
                "when the device was lost and no host/disk copy "
                "survived; its lineage must be recomputed")
        if self.tier == self.TIER_DEVICE:
            return self._device
        if self.tier == self.TIER_DISK:
            host = self._read_disk()
            if self._disk_path and os.path.exists(self._disk_path):
                os.unlink(self._disk_path)
            self._disk_path = None
        else:
            host = self._host
        # Mark device-resident BEFORE reserving so the budget loop cannot
        # pick this handle as its own spill victim mid-rehydration.
        self._host = None
        self.tier = self.TIER_DEVICE
        self._catalog.metrics["unspilled"] += 1
        self._catalog.reserve(self.device_bytes, exclude=self.batch_id)
        self._device = host_to_device(host, capacity=self._capacity)
        return self._device

    def close(self):
        if self.closed:
            return
        self.closed = True
        if self._disk_path and os.path.exists(self._disk_path):
            os.unlink(self._disk_path)
        self._device = None
        self._host = None
        self._catalog._unregister(self)


class BufferCatalog:
    """Process-wide registry of spillable batches with a device budget."""

    def __init__(self, conf: RapidsConf):
        self.conf = conf
        self.device_budget = DEVICE_SPILL_BUDGET.get(conf)
        self.host_budget = conf.host_spill_storage_size
        self.spill_codec = conf.get(
            "spark.rapids.shuffle.compression.codec", "copy") or "copy"
        self._handles: Dict[int, SpillableBatch] = {}
        self._next_id = 0
        self._lock = threading.RLock()
        self._spill_dir: Optional[str] = None
        self.metrics = {"spilled_to_host": 0, "spilled_to_disk": 0,
                        "unspilled": 0}

    def _dir(self) -> str:
        if self._spill_dir is None:
            self._spill_dir = tempfile.mkdtemp(prefix="rapids_tpu_spill_")
        return self._spill_dir

    def register(self, batch: ColumnBatch,
                 priority: int = PRIORITY_INPUT) -> SpillableBatch:
        with self._lock:
            h = SpillableBatch(self, self._next_id, batch, priority)
            self._next_id += 1
            self._handles[h.batch_id] = h
            self.reserve(0, exclude=h.batch_id)
            return h

    def _unregister(self, h: SpillableBatch):
        with self._lock:
            self._handles.pop(h.batch_id, None)

    def device_bytes_in_use(self) -> int:
        with self._lock:
            return sum(h.device_bytes for h in self._handles.values()
                       if h.tier == SpillableBatch.TIER_DEVICE)

    def host_bytes_in_use(self) -> int:
        with self._lock:
            return sum(h.host_bytes() for h in self._handles.values()
                       if h.tier == SpillableBatch.TIER_HOST)

    def reserve(self, incoming_bytes: int, exclude: int = -1):
        """Synchronously spill until (in_use + incoming) fits the budget
        (the synchronousSpill loop, RapidsBufferStore.scala:144)."""
        with self._lock:
            while self.device_bytes_in_use() + incoming_bytes > \
                    self.device_budget:
                victim = self._pick_victim(
                    SpillableBatch.TIER_DEVICE, exclude)
                if victim is None:
                    break
                victim._spill_to_host()
                self.metrics["spilled_to_host"] += 1
                self._enforce_host_budget()

    def _enforce_host_budget(self):
        while self.host_bytes_in_use() > self.host_budget:
            victim = self._pick_victim(SpillableBatch.TIER_HOST, -1)
            if victim is None:
                break
            victim._spill_to_disk(self._dir())
            self.metrics["spilled_to_disk"] += 1

    def handle_alloc_failure(self, pinned=()) -> int:
        """Spill ALL device-tier spillables; bytes freed.

        The DeviceMemoryEventHandler role (DeviceMemoryEventHandler.scala:35):
        RMM invokes the reference's handler from inside a failed cudaMalloc;
        XLA exposes no such callback, so the engine instead catches the
        RESOURCE_EXHAUSTED runtime error at dispatch boundaries
        (:func:`run_with_oom_retry`) and calls this.  A real device OOM means
        the soft budget under-counted (unregistered transients, fragmentation),
        so everything spillable goes to host, not just down to the budget.

        ``pinned`` holds batches the retrying computation still references
        (its input args): spilling those would free nothing — the jax buffers
        stay alive through the caller's reference — while marking the handle
        host-tier, so a later ``get()`` would allocate a SECOND device copy.
        They are skipped and excluded from the freed count.
        """
        # Pin by LEAF array identity, not batch-wrapper identity: colocation
        # may rebuild wrappers around the same device arrays, and only a
        # handle whose underlying buffers are aliased by the retrying args
        # is futile to spill.
        import jax
        pinned_ids = {id(leaf) for b in pinned
                      for leaf in jax.tree_util.tree_leaves(b)}
        freed = 0
        with self._lock:
            victims = sorted(
                (h for h in self._handles.values()
                 if h.tier == SpillableBatch.TIER_DEVICE and not h.closed
                 and not any(id(leaf) in pinned_ids for leaf in
                             jax.tree_util.tree_leaves(h._device))),
                key=lambda h: (h.priority, h.batch_id))
            for victim in victims:
                freed += victim.device_bytes
                victim._spill_to_host()
                self.metrics["spilled_to_host"] += 1
            if victims:
                self._enforce_host_budget()
            if freed:
                self.metrics["oom_spill_bytes"] = \
                    self.metrics.get("oom_spill_bytes", 0) + freed
        return freed

    def invalidate_device_tier(self, rescue: bool = True) -> int:
        """Device-lost recovery (fault.recovery): every device-tier
        handle is rescued to host when the buffers still answer (the
        simulated-fault case — and real losses where XLA kept the copy
        readable), else marked TIER_LOST so a later ``get()`` raises a
        classified DeviceLostError and the consumer's replay recomputes
        the batch from lineage.  ``rescue=False`` (timeout-classified
        recovery: the device is WEDGED, a rescue D2H against it would
        block the recovery path on the very hang being recovered from)
        marks device-tier handles lost without touching the device.
        Host- and disk-tier handles are untouched: they re-upload
        lazily on the next ``get()``.  Returns the number of handles
        that transitioned.
        """
        moved = 0
        with self._lock:
            for h in list(self._handles.values()):
                if h.closed or h.tier != SpillableBatch.TIER_DEVICE:
                    continue
                moved += 1
                if rescue:
                    try:
                        h._spill_to_host()
                        self.metrics["spilled_to_host"] += 1
                        continue
                    except Exception:  # noqa: BLE001 — buffers truly gone
                        pass
                h._device = None
                h._host = None
                h.tier = SpillableBatch.TIER_LOST
                self.metrics["lost_batches"] = \
                    self.metrics.get("lost_batches", 0) + 1
            if moved:
                self.metrics["device_invalidated"] = \
                    self.metrics.get("device_invalidated", 0) + moved
                self._enforce_host_budget()
        return moved

    def _pick_victim(self, tier: int, exclude: int
                     ) -> Optional[SpillableBatch]:
        best = None
        for h in self._handles.values():
            if h.tier != tier or h.batch_id == exclude or h.closed:
                continue
            if best is None or h.priority < best.priority or \
                    (h.priority == best.priority and
                     h.batch_id < best.batch_id):
                best = h
        return best


def is_device_oom(err: BaseException) -> bool:
    """True when ``err`` is an XLA out-of-device-memory failure.  JAX raises
    ``XlaRuntimeError``/``JaxRuntimeError`` whose message carries the ABSL
    status code name; allocation failures are RESOURCE_EXHAUSTED."""
    return "RESOURCE_EXHAUSTED" in str(err) \
        and type(err).__name__ in ("XlaRuntimeError", "JaxRuntimeError")


def run_with_oom_retry(catalog: "BufferCatalog", thunk,
                       retries: Optional[int] = None,
                       pinned=(), on_retry=None):
    """Run ``thunk`` and, on a device OOM, spill everything spillable and
    re-run — the engine-side analogue of the reference's alloc-failure →
    synchronous-spill → retry loop (DeviceMemoryEventHandler.scala:35,
    RmmRapidsRetryIterator.scala's withRetry).

    Thin wrapper over the unified fault machinery: the error must
    classify RETRYABLE_OOM (fault.errors — covers real XLA
    RESOURCE_EXHAUSTED and injected OOMs alike) and the attempt bound
    comes from the one RetryPolicy
    (``spark.rapids.sql.tpu.retry.maxAttempts``) unless ``retries``
    overrides it (``retries=0`` = fail fast, the donated-dispatch
    path).  No backoff sleep here: the corrective action (the spill)
    already completed synchronously, so there is no transient condition
    to wait out — backoff belongs to the device-lost replay ladder.
    Still gives up early when a retry frees nothing — spilling can no
    longer help.  ``pinned``: batches the thunk re-reads on retry (see
    :meth:`BufferCatalog.handle_alloc_failure`).
    """
    from spark_rapids_tpu.fault import metrics as fault_metrics
    from spark_rapids_tpu.fault.errors import ErrorClass, classify_error
    from spark_rapids_tpu.fault.retry import RetryPolicy
    max_attempts = RetryPolicy.from_conf(catalog.conf).max_attempts \
        if retries is None else retries + 1
    attempt = 0
    while True:
        attempt += 1
        try:
            return thunk()
        except Exception as e:  # noqa: BLE001 — filtered by classification
            if classify_error(e) is not ErrorClass.RETRYABLE_OOM or \
                    attempt >= max_attempts:
                raise
            freed = catalog.handle_alloc_failure(pinned=pinned)
            if freed == 0:
                raise
            if on_retry is not None:
                on_retry(freed)
            fault_metrics.record("retries")
