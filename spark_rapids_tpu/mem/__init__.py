"""Tiered memory management: device -> host -> disk spill
(reference: RapidsBufferCatalog + RapidsBufferStore tiers, SURVEY.md
section 2.4)."""
