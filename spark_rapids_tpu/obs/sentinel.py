"""Cross-run regression sentinel.

The history store (history/store.py) now folds a robust aggregate —
median and MAD over the last ``history.aggregateRuns`` runs — per plan
fingerprint.  This module is the comparison half: given a fresh query's
harvest record and that aggregate, flag every guarded key whose value
sits above its acceptance band

    value > median + madThreshold * max(MAD, 25% * median, key floor)

The MAD floor matters: N identical clean runs give MAD == 0, and a
hair-trigger band would flag ordinary scheduler jitter.  The relative
floor (25% of median) plus a per-key absolute floor keeps the band wide
enough that only real regressions — an injected ``dispatch:slow``, a
lost cache, a plan change — clear it.  Only upward excursions alert:
getting faster is not a regression.

Engine-free (stdlib only) like the rest of ``obs/``; the session glue
lives in ``history.end_query`` (compare BEFORE appending the fresh run,
so a regressed run never poisons its own baseline), which emits one
``regression`` obs instant per alert and sets
``last_metrics['regressionAlerts']``.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List

#: Harvest-record keys the sentinel guards, with per-key absolute band
#: floors (units match the record: ns for wall, counts, bytes).
GUARDED_KEYS: Dict[str, float] = {
    "wall_ns": 2e6,          # 2 ms: sub-noise walls never alert
    "dispatches": 2.0,
    "compile_count": 1.0,
    "shuffle_bytes": 1 << 16,
    "spill_host_bytes": 1 << 16,
    "spill_disk_bytes": 1 << 16,
}

#: Relative floor on the band half-width, as a fraction of the median.
REL_FLOOR = 0.25

_lock = threading.Lock()
_alerts_total = 0


def check(record: Dict[str, Any], aggregate: Dict[str, Any],
          threshold: float, min_runs: int) -> List[Dict[str, Any]]:
    """Compare a fresh harvest ``record`` against a store ``aggregate``
    (``history.store.aggregate`` shape: ``{"n": int, "keys": {key:
    {"median", "mad"}}}``).  Returns one alert dict per regressed key —
    empty when the baseline is too thin (< ``min_runs``) or everything
    is in band."""
    n = int(aggregate.get("n", 0) or 0)
    if n < max(1, int(min_runs)):
        return []
    alerts: List[Dict[str, Any]] = []
    for key, st in (aggregate.get("keys") or {}).items():
        floor = GUARDED_KEYS.get(key)
        if floor is None:
            continue
        med = float(st.get("median", 0.0) or 0.0)
        mad = float(st.get("mad", 0.0) or 0.0)
        value = float(record.get(key, 0) or 0)
        band = med + float(threshold) * max(mad, REL_FLOOR * abs(med),
                                            floor)
        if value > band:
            alerts.append({
                "key": key, "value": value, "median": med, "mad": mad,
                "band": band, "runs": n,
            })
    if alerts:
        global _alerts_total
        with _lock:
            _alerts_total += len(alerts)
    return alerts


def alerts_total() -> int:
    """Process-cumulative alert count (the serve ``stats()`` rollup
    key ``regression_alerts_total``)."""
    with _lock:
        return _alerts_total


def reset_alerts_total() -> None:
    global _alerts_total
    with _lock:
        _alerts_total = 0
