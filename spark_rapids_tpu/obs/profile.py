"""Fold a query's event-bus timeline into a :class:`QueryProfile`.

``session.execute`` builds one profile per query from the drained events
and keeps a bounded history (``session.query_history()``, conf
``spark.rapids.sql.tpu.obs.history.maxQueries``) — the SQL-UI role of
the reference's per-exec ``GpuMetric`` tables, answering "which operator
ate the device time" and "when did the spill storm start" from data the
chokepoints already produced.

Engine-free (stdlib only): ``tools/rapidsprof.py`` builds the same
profiles from a JSONL event log, so events are accessed duck-typed via
:func:`~spark_rapids_tpu.obs.events.field` (Event objects in-process,
plain dicts after a log round-trip).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from .events import SPAN, field


def _new_rollup(name: str) -> Dict[str, Any]:
    return {
        "name": name, "dispatches": 0, "device_ns": 0, "errors": 0,
        "rows": 0, "batches": 0, "shuffle_bytes": 0, "shuffle_rows": 0,
        "shuffle_pieces": 0, "adaptive": {},
    }


class QueryProfile:
    """Per-operator rollups + per-site totals + wall-clock bounds for one
    query's event window.

    ``op_rollups`` is keyed by physical-plan ``op_id`` (device spans carry
    the stage root's op_id; exchange spans carry the exchange's); each
    rollup keeps the operator's display ``name``.  ``site_totals`` maps
    site -> {count, wall_ns, bytes}.  ``metrics`` / ``op_metrics`` are the
    query's ``last_metrics`` scalars and per-op metric dicts, stashed so a
    history entry is self-contained.
    """

    def __init__(self, query_id: int, events: List, dropped: int = 0,
                 wall_ns: int = 0,
                 metrics: Optional[Dict[str, Any]] = None,
                 op_metrics: Optional[Dict[str, Dict[str, Any]]] = None,
                 dropped_by_site: Optional[Dict[str, int]] = None,
                 session_id: int = 0, qt0_ns: int = 0, qt1_ns: int = 0):
        self.query_id = query_id
        self.events = list(events)
        self.dropped = int(dropped)
        self.wall_ns = int(wall_ns)
        self.metrics = dict(metrics or {})
        self.op_metrics = dict(op_metrics or {})
        self.dropped_by_site = dict(dropped_by_site or {})
        self.session_id = int(session_id)
        self.qt0_ns = int(qt0_ns)
        self.qt1_ns = int(qt1_ns)
        self.op_rollups: Dict[str, Dict[str, Any]] = {}
        self.site_totals: Dict[str, Dict[str, int]] = {}
        self.t_min = 0
        self.t_max = 0
        self._fold()

    # -- folding ------------------------------------------------------------

    def _rollup(self, op_id: str, name: str) -> Dict[str, Any]:
        r = self.op_rollups.get(op_id)
        if r is None:
            r = self.op_rollups[op_id] = _new_rollup(name)
        elif name and not r["name"]:
            r["name"] = name
        return r

    def _fold(self) -> None:
        for ev in self.events:
            kind = field(ev, "kind")
            site = field(ev, "site") or "?"
            name = field(ev, "name") or ""
            op_id = field(ev, "op_id") or ""
            t0 = int(field(ev, "t0", 0) or 0)
            t1 = int(field(ev, "t1", 0) or 0)
            pay = field(ev, "payload") or {}
            st = self.site_totals.setdefault(
                site, {"count": 0, "wall_ns": 0, "bytes": 0})
            st["count"] += 1
            st["wall_ns"] += max(0, t1 - t0)
            st["bytes"] += int(pay.get("bytes", 0) or 0)
            if t0:
                self.t_min = t0 if not self.t_min else min(self.t_min, t0)
                self.t_max = max(self.t_max, t1)
            if site == "device":
                r = self._rollup(op_id, name)
                r["dispatches"] += 1
                r["device_ns"] += max(0, t1 - t0)
                r["rows"] += int(pay.get("rows", 0) or 0)
                r["batches"] += int(pay.get("batches", 0) or 0)
                if pay.get("error"):
                    r["errors"] += 1
            elif site == "exchange" and kind == SPAN:
                r = self._rollup(op_id, name or "exchange")
                r["shuffle_bytes"] += int(pay.get("bytes", 0) or 0)
                r["shuffle_rows"] += int(pay.get("rows", 0) or 0)
                r["shuffle_pieces"] += int(pay.get("pieces", 0) or 0)
            elif site == "adaptive" and op_id:
                r = self._rollup(op_id, "")
                r["adaptive"][name] = r["adaptive"].get(name, 0) + 1

    # -- derived ------------------------------------------------------------

    @property
    def event_count(self) -> int:
        return len(self.events)

    @property
    def attributed_device_ns(self) -> int:
        """Device ns the profile ties to concrete operators — compare
        against ``last_metrics['deviceTimeNs']`` for coverage."""
        return sum(r["device_ns"] for r in self.op_rollups.values())

    def top_operators(self, n: int = 10) -> List[Dict[str, Any]]:
        """Rollups sorted by device time (then shuffle bytes), op_id
        attached under ``op_id``."""
        rows = [dict(r, op_id=op) for op, r in self.op_rollups.items()]
        rows.sort(key=lambda r: (r["device_ns"], r["shuffle_bytes"]),
                  reverse=True)
        return rows[:n]

    def site(self, name: str) -> Dict[str, int]:
        return self.site_totals.get(
            name, {"count": 0, "wall_ns": 0, "bytes": 0})

    def query_record(self) -> Dict[str, Any]:
        """The JSONL event-log header line for this query (scalars only —
        the per-event lines follow it)."""
        return {
            "type": "query", "id": self.query_id, "wall_ns": self.wall_ns,
            "event_count": self.event_count, "dropped": self.dropped,
            "dropped_by_site": self.dropped_by_site,
            "session": self.session_id,
            "t0_ns": self.qt0_ns, "t1_ns": self.qt1_ns,
            "metrics": self.metrics,
        }

    def summary(self) -> str:
        """Top-of-profile text block (rapidsprof's per-query header)."""
        dev = self.metrics.get("deviceTimeNs", 0) or 0
        attr = self.attributed_device_ns
        pct = 100.0 * attr / dev if dev else 100.0
        lines = [
            f"query {self.query_id}: wall {self.wall_ns / 1e6:.2f} ms, "
            f"{self.event_count} events ({self.dropped} dropped), "
            f"device {attr / 1e6:.2f} ms attributed ({pct:.0f}% of "
            f"deviceTimeNs)"
        ]
        if self.dropped:
            sites = ", ".join(
                f"{s}={n}" for s, n in sorted(self.dropped_by_site.items(),
                                              key=lambda kv: -kv[1])) \
                or "unknown sites"
            lines.append(
                f"  !! TRUNCATED: {self.dropped} events dropped at the "
                f"ring ({sites}) — per-site totals undercount; raise "
                f"spark.rapids.sql.tpu.obs.ring.maxEvents")
        for r in self.top_operators(5):
            lines.append(
                f"  {r['name'] or r['op_id'] or '?'}: "
                f"{r['device_ns'] / 1e6:.2f} ms device, "
                f"{r['dispatches']} dispatches"
                + (f", {r['errors']} errored" if r["errors"] else ""))
        return "\n".join(lines)


def _fmt_rollup(r: Dict[str, Any], ms: Dict[str, Any]) -> str:
    parts = []
    if r:
        if r["dispatches"]:
            parts.append(f"dispatches={r['dispatches']}")
        if r["device_ns"]:
            parts.append(f"device={r['device_ns'] / 1e6:.2f}ms")
        if r["errors"]:
            parts.append(f"errors={r['errors']}")
        if r["shuffle_bytes"]:
            parts.append(f"shuffleBytes={r['shuffle_bytes']}")
        if r["shuffle_pieces"]:
            parts.append(f"pieces={r['shuffle_pieces']}")
        if r["adaptive"]:
            parts.append("adaptive=" + ",".join(
                f"{k}x{v}" for k, v in sorted(r["adaptive"].items())))
    # per-op metric dict entries the events don't carry (e.g. an
    # exchange's shuffleWallNs, AQE stats) ride along from last_metrics
    for key in ("shuffleWallNs", "aqeCoalescedPartitions", "aqeSkewSplits"):
        v = ms.get(key)
        if v:
            parts.append(f"{key}={v}")
    return " ".join(parts) if parts else "-"


def annotate_plan(root, profile: "QueryProfile") -> str:
    """Render the physical tree with each node's rollup attached — the
    ``session.explain_last(metrics=True)`` body (the reference SQL UI's
    exec-metric annotations).  Duck-typed over PhysicalOp (``name``,
    ``op_id``, ``children``); rollups that match no tree node (e.g. the
    whole-pipeline dispatch bucket) land in a footer."""
    lines: List[str] = []
    seen: set = set()

    def walk(op, depth: int) -> None:
        op_id = getattr(op, "op_id", "")
        seen.add(op_id)
        r = profile.op_rollups.get(op_id)
        ms = profile.op_metrics.get(op_id, {})
        lines.append("  " * depth + f"{getattr(op, 'name', type(op).__name__)}"
                     f"  [{_fmt_rollup(r, ms)}]")
        for c in getattr(op, "children", ()) or ():
            walk(c, depth + 1)

    walk(root, 0)
    extras = [(op, r) for op, r in profile.op_rollups.items()
              if op not in seen]
    if extras:
        lines.append("unattributed:")
        for op, r in extras:
            lines.append(f"  {r['name'] or op}  [{_fmt_rollup(r, {})}]")
    return "\n".join(lines)
