"""Critical-path attribution: an exact wall-time decomposition per query.

The per-site totals in :class:`~spark_rapids_tpu.obs.profile.QueryProfile`
sum each site's span wall independently, so overlapping work double
counts and host gaps vanish — "what would make this query faster" stays
a guess.  This module computes it instead: a sweep over the query's
event spans (all threads — a decode-pool or spill-writer span that the
runner blocks on is exactly the critical path) attributes every
nanosecond of the query window ``[t0, t1)`` to the highest-priority
site covering it, and the uncovered remainder to ``wait`` (host compute
/ runner wait).  By construction the segments sum to the window EXACTLY
— the same parity discipline PR 10 pinned with
``attributed_device_ns == deviceTimeNs`` — and the pinned test asserts
it on a query that shuffles, spills and retries, serial and under
3-thread serve concurrency.

Priority encodes the blocking chain (runner wait -> decode -> H2D ->
dispatch -> shuffle sync -> spill stall -> D2H): ``device`` first, so
an exchange's credit is its span wall MINUS the device time nested
inside it — i.e. the host-side shuffle sync cost, not a recount of the
dispatches it drove.

Engine-free (stdlib only, duck-typed events) so ``rapidsprof
--critpath`` reconstructs the same decomposition offline from a JSONL
event log.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from .events import SPAN, field

#: Site attribution priority, highest first.  ``wait`` (uncovered wall)
#: is not a site — it is the remainder.
SITE_PRIORITY: Tuple[str, ...] = (
    "device", "h2d", "d2h", "spill", "unspill", "exchange", "mesh",
    "scan", "io", "dispatch", "pallas", "retry", "fault",
)

WAIT = "wait"
OTHER = "other"


def _rank(site: str) -> int:
    try:
        return SITE_PRIORITY.index(site)
    except ValueError:
        return len(SITE_PRIORITY)  # unknown sites: lowest known priority


class CritPath:
    """One query's decomposition.  ``segments`` maps site (plus
    ``wait``) -> attributed ns; ``chain`` is the merged timeline of
    (site, t0, t1) runs, in order.  ``total_ns`` == window width and
    ``sum(segments.values()) == total_ns`` exactly."""

    def __init__(self, t0: int, t1: int, segments: Dict[str, int],
                 chain: List[Tuple[str, int, int]]):
        self.t0 = t0
        self.t1 = t1
        self.total_ns = max(0, t1 - t0)
        self.segments = segments
        self.chain = chain

    @property
    def attributed_ns(self) -> int:
        """Nanoseconds attributed to concrete sites (window minus the
        ``wait`` remainder) — the ``critpathAttributedNs`` metric."""
        return self.total_ns - self.segments.get(WAIT, 0)

    def top_site(self) -> str:
        """The dominant segment — bench's ``critpath_top_site``."""
        if not self.segments:
            return ""
        return max(self.segments.items(), key=lambda kv: kv[1])[0]

    def summary(self) -> str:
        lines = [
            f"critical path: {self.total_ns / 1e6:.2f} ms wall, "
            f"{self.attributed_ns / 1e6:.2f} ms attributed "
            f"({100.0 * self.attributed_ns / self.total_ns if self.total_ns else 0.0:.0f}%)"
        ]
        for site, ns in sorted(self.segments.items(),
                               key=lambda kv: -kv[1]):
            if ns <= 0:
                continue
            pct = 100.0 * ns / self.total_ns if self.total_ns else 0.0
            lines.append(f"  {site:<9} {ns / 1e6:>9.2f} ms  {pct:>5.1f}%")
        return "\n".join(lines)


def compute(events: List[Any], t0: int, t1: int) -> CritPath:
    """Decompose the window ``[t0, t1)`` over ``events``.

    Spans are clipped to the window; instants carry no width and are
    ignored.  Every elementary slice between consecutive span boundaries
    is attributed to the highest-priority site with a span covering it;
    slices no span covers go to ``wait``.  Total is exact by
    construction: the slices partition the window."""
    t0, t1 = int(t0), int(t1)
    if t1 <= t0:
        return CritPath(t0, t1, {}, [])
    spans: List[Tuple[int, int, int, str]] = []  # (start, end, rank, site)
    cuts = {t0, t1}
    for ev in events:
        if field(ev, "kind") != SPAN:
            continue
        raw_t0 = int(field(ev, "t0", 0) or 0)
        if raw_t0 <= 0:
            continue  # unstamped span: no defensible placement
        s = max(t0, raw_t0)
        e = min(t1, int(field(ev, "t1", 0) or 0))
        if e <= s:
            continue
        site = field(ev, "site") or OTHER
        spans.append((s, e, _rank(site), site))
        cuts.add(s)
        cuts.add(e)
    bounds = sorted(cuts)
    # active-span sweep: spans sorted by start; a heap-free variant is
    # fine at per-query event counts (ring-bounded)
    spans.sort()
    segments: Dict[str, int] = {}
    chain: List[Tuple[str, int, int]] = []
    si = 0
    active: List[Tuple[int, int, str]] = []  # (rank, end, site)
    for i in range(len(bounds) - 1):
        lo, hi = bounds[i], bounds[i + 1]
        while si < len(spans) and spans[si][0] <= lo:
            s, e, rank, site = spans[si]
            active.append((rank, e, site))
            si += 1
        active = [a for a in active if a[1] > lo]
        if active:
            site = min(active)[2]
        else:
            site = WAIT
        segments[site] = segments.get(site, 0) + (hi - lo)
        if chain and chain[-1][0] == site and chain[-1][2] == lo:
            chain[-1] = (site, chain[-1][1], hi)
        else:
            chain.append((site, lo, hi))
    return CritPath(t0, t1, segments, chain)


def from_profile(profile) -> Optional[CritPath]:
    """Decompose a :class:`QueryProfile` over its recorded query window
    (``qt0_ns``/``qt1_ns``, stamped by ``session.execute``).  Falls back
    to the event extent for pre-v2 logs without window stamps; None when
    no window is known at all."""
    qt0 = int(getattr(profile, "qt0_ns", 0) or 0)
    qt1 = int(getattr(profile, "qt1_ns", 0) or 0)
    if qt1 <= qt0:
        qt0 = int(getattr(profile, "t_min", 0) or 0)
        qt1 = int(getattr(profile, "t_max", 0) or 0)
    if qt1 <= qt0:
        return None
    return compute(profile.events, qt0, qt1)
