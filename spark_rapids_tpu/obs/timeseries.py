"""Continuous time-series telemetry: the process-wide aggregation ring.

The per-query event bus (obs.events) answers "where did THIS query's
time go"; nothing before this module answered "what is the process doing
right now, over time" — the role Spark's metrics sinks + Prometheus
servlet play for the reference accelerator.  Every span the obs
chokepoints emit also folds here into a fixed-interval aggregation ring:

* one :class:`Interval` per ``obs.telemetry.intervalMs`` wall-clock
  bucket, holding per-site ``[count, wall_ns, bytes]`` rollups plus
  bounded per-interval value samples (the serve scheduler feeds query
  latencies for its sliding-window percentiles);
* a bounded deque of completed intervals — drop-OLDEST past
  ``obs.telemetry.maxIntervals`` (the live view must keep the newest
  data; the per-query ring keeps the oldest for the opposite reason);
* gauges (catalog tier bytes, spill-writer/decode-pool utilization,
  serve queue depth, fragment-cache occupancy, obs ring drops) are
  registered as callables and sampled at export time — never inside the
  emit path, so a gauge that takes the catalog lock can never deadlock
  against a spill span emitted under it.

Exports: JSONL flushes (``telemetry-<pid>.jsonl`` beside the event log,
the ``tools/rapidstop.py`` input) and Prometheus-style exposition text.
Engine-free (stdlib only) like the rest of ``obs/`` so rapidstop loads
the package standalone; the fold path is one lock-protected dict update
and the disabled path is a single ``is None`` test in obs.events.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

#: Per-interval cap on stored value samples per series (bounds memory
#: when a burst lands thousands of serve completions in one interval).
MAX_VALUES_PER_INTERVAL = 512

#: Prometheus metric-name prefix for every exported series.
PROM_PREFIX = "rapids"


class Interval:
    """One closed aggregation window: ``sites`` maps site ->
    ``[count, wall_ns, bytes]``; ``values`` maps series name -> bounded
    sample list; ``gauges`` is attached at export time."""

    __slots__ = ("idx", "t0_ns", "dur_ns", "sites", "values", "gauges")

    def __init__(self, idx: int, t0_ns: int, dur_ns: int):
        self.idx = idx
        self.t0_ns = t0_ns
        self.dur_ns = dur_ns
        self.sites: Dict[str, List[int]] = {}
        self.values: Dict[str, List[float]] = {}
        self.gauges: Dict[str, float] = {}

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "type": "interval", "idx": self.idx, "t0_ns": self.t0_ns,
            "dur_ns": self.dur_ns, "sites": self.sites,
        }
        if self.values:
            d["values"] = self.values
        if self.gauges:
            d["gauges"] = self.gauges
        return d


class TelemetryRing:
    """The aggregation ring.  ``record_span`` is the hot path: one lock,
    one bucket-index division, one dict update.  Interval rotation
    happens lazily when a fold lands in a newer bucket (an idle process
    rotates at the next export instead — see :meth:`roll_now`)."""

    def __init__(self, interval_ms: int, max_intervals: int):
        self.interval_ns = max(1, int(interval_ms)) * 1_000_000
        self.max_intervals = max(1, int(max_intervals))
        self._lock = threading.Lock()
        self._cur: Optional[Interval] = None
        self._done: deque = deque(maxlen=self.max_intervals)
        self._gauges: Dict[str, Callable[[], float]] = {}
        self.completed_total = 0
        self.dropped_intervals = 0
        self._flush_offset = 0  # completed_total already flushed to JSONL

    # -- fold (hot path) ----------------------------------------------------

    def record_span(self, site: str, wall_ns: int, nbytes: int = 0) -> None:
        now = time.monotonic_ns()
        with self._lock:
            cur = self._rotate_locked(now)
            st = cur.sites.get(site)
            if st is None:
                st = cur.sites[site] = [0, 0, 0]
            st[0] += 1
            st[1] += max(0, int(wall_ns))
            st[2] += int(nbytes or 0)

    def record_value(self, name: str, value: float) -> None:
        """Append one sample to the current interval's ``name`` series
        (bounded per interval) — the sliding-window feed."""
        now = time.monotonic_ns()
        with self._lock:
            cur = self._rotate_locked(now)
            vals = cur.values.get(name)
            if vals is None:
                vals = cur.values[name] = []
            if len(vals) < MAX_VALUES_PER_INTERVAL:
                vals.append(float(value))

    def _rotate_locked(self, now_ns: int) -> Interval:
        idx = now_ns // self.interval_ns
        cur = self._cur
        if cur is not None and cur.idx == idx:
            return cur
        if cur is not None and (cur.sites or cur.values):
            # empty intervals (an idle process, or the fresh bucket an
            # export's roll_now opened) never complete: they would pad
            # the ring and the JSONL with zero rows
            if len(self._done) == self._done.maxlen:
                self.dropped_intervals += 1
            self._done.append(cur)
            self.completed_total += 1
        cur = self._cur = Interval(idx, idx * self.interval_ns,
                                   self.interval_ns)
        return cur

    # -- gauges -------------------------------------------------------------

    def register_gauge(self, name: str, fn: Callable[[], float]) -> None:
        """Register (or replace) a gauge sampled at export time.  The
        callable runs OUTSIDE the ring lock and may take engine locks."""
        with self._lock:
            self._gauges[name] = fn

    def sample_gauges(self) -> Dict[str, float]:
        with self._lock:
            fns = list(self._gauges.items())
        out: Dict[str, float] = {}
        for name, fn in fns:
            try:
                out[name] = float(fn())
            except Exception:
                # a gauge over a torn-down subsystem (closed catalog,
                # stopped scheduler) must never break telemetry export
                continue
        out["telemetry.dropped_intervals"] = float(self.dropped_intervals)
        return out

    # -- read side ----------------------------------------------------------

    def roll_now(self) -> None:
        """Force-close the current interval if its window has passed —
        export paths call this so an idle tail interval still lands."""
        now = time.monotonic_ns()
        with self._lock:
            cur = self._cur
            if cur is not None and now // self.interval_ns != cur.idx:
                self._rotate_locked(now)

    def snapshot(self) -> List[Interval]:
        """Completed intervals, oldest first (current interval excluded:
        it is still accumulating)."""
        self.roll_now()
        with self._lock:
            return list(self._done)

    def window_values(self, name: str) -> List[float]:
        """Every stored sample of ``name`` across the ring window
        (completed intervals + the open one), oldest first."""
        with self._lock:
            out: List[float] = []
            for iv in self._done:
                out.extend(iv.values.get(name, ()))
            if self._cur is not None:
                out.extend(self._cur.values.get(name, ()))
            return out

    def window_seconds(self) -> float:
        """Wall seconds the ring can span when full."""
        return self.max_intervals * self.interval_ns / 1e9

    # -- export -------------------------------------------------------------

    def flush_jsonl(self, path: str) -> int:
        """Append intervals completed since the last flush to ``path``
        (gauges sampled once per flush, attached to the newest flushed
        interval).  Returns how many intervals were written."""
        self.roll_now()
        with self._lock:
            done = list(self._done)
            total = self.completed_total
            start = len(done) - (total - self._flush_offset)
            fresh = done[max(0, start):]
            self._flush_offset = total
        if not fresh:
            return 0
        fresh[-1].gauges = self.sample_gauges()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "a", encoding="utf-8") as f:
            for iv in fresh:
                f.write(json.dumps(iv.to_dict()) + "\n")
        return len(fresh)

    def prometheus_text(self) -> str:
        """Prometheus exposition-format text: per-site counters summed
        over the ring window plus the current gauge samples."""
        totals: Dict[str, List[int]] = {}
        for iv in self.snapshot():
            for site, st in iv.sites.items():
                t = totals.setdefault(site, [0, 0, 0])
                t[0] += st[0]
                t[1] += st[1]
                t[2] += st[2]
        return render_prometheus(totals, self.sample_gauges(),
                                 self.completed_total)


# -- shared renderers (live ring + rapidstop's offline JSONL) -----------------

def _prom_name(raw: str) -> str:
    return "".join(c if c.isalnum() or c == "_" else "_" for c in raw)


def render_prometheus(site_totals: Dict[str, List[int]],
                      gauges: Dict[str, float],
                      intervals_total: int) -> str:
    """Render site ``[count, wall_ns, bytes]`` totals + gauges as
    Prometheus exposition text (shared by the live ring and rapidstop's
    offline ``--prom`` over a flushed JSONL)."""
    lines = [
        f"# TYPE {PROM_PREFIX}_telemetry_intervals_total counter",
        f"{PROM_PREFIX}_telemetry_intervals_total {intervals_total}",
    ]
    for suffix, pos in (("events_total", 0), ("wall_ns_total", 1),
                        ("bytes_total", 2)):
        lines.append(f"# TYPE {PROM_PREFIX}_site_{suffix} counter")
        for site in sorted(site_totals):
            lines.append(
                f'{PROM_PREFIX}_site_{suffix}{{site="{_prom_name(site)}"}} '
                f"{site_totals[site][pos]}")
    for name in sorted(gauges):
        metric = f"{PROM_PREFIX}_{_prom_name(name)}"
        lines.append(f"# TYPE {metric} gauge")
        lines.append(f"{metric} {gauges[name]:g}")
    return "\n".join(lines) + "\n"


def read_telemetry_log(path: str) -> List[Dict[str, Any]]:
    """Parse a flushed telemetry JSONL back into interval dicts, oldest
    first (rapidstop's input; torn tail lines are skipped)."""
    out: List[Dict[str, Any]] = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and rec.get("type") == "interval":
                out.append(rec)
    return out


def render_intervals(intervals: List[Dict[str, Any]], last: int = 0) -> str:
    """The rapidstop "top" view: newest interval's per-site table plus a
    window rollup over ``last`` (0 = all) intervals."""
    if not intervals:
        return "(no telemetry intervals)"
    if last and last > 0:
        intervals = intervals[-last:]
    newest = intervals[-1]
    lines = [
        f"telemetry: {len(intervals)} interval(s), "
        f"{int(newest.get('dur_ns', 0)) / 1e6:.0f} ms each, newest idx "
        f"{newest.get('idx')}",
        "",
        "  site      |   events |   wall ms |       MB |    GB/s",
    ]

    def row(site: str, st: List[int]) -> str:
        count, wall, nbytes = int(st[0]), int(st[1]), int(st[2])
        gbps = f"{nbytes / wall:.3f}" if wall else "-"
        return (f"  {site:<9} | {count:>8} | {wall / 1e6:>9.2f} | "
                f"{nbytes / (1 << 20):>8.2f} | {gbps:>7}")

    newest_sites = newest.get("sites") or {}
    for site in sorted(newest_sites,
                       key=lambda s: -int(newest_sites[s][1])):
        lines.append(row(site, newest_sites[site]))
    if not newest_sites:
        lines.append("  (idle interval)")
    gauges = newest.get("gauges") or {}
    if gauges:
        lines.append("")
        lines.append("  gauges: " + ", ".join(
            f"{k}={v:g}" for k, v in sorted(gauges.items())))
    if len(intervals) > 1:
        totals: Dict[str, List[int]] = {}
        for iv in intervals:
            for site, st in (iv.get("sites") or {}).items():
                t = totals.setdefault(site, [0, 0, 0])
                t[0] += int(st[0])
                t[1] += int(st[1])
                t[2] += int(st[2])
        lines.append("")
        lines.append(f"  window ({len(intervals)} intervals):")
        for site in sorted(totals, key=lambda s: -totals[s][1]):
            lines.append(row(site, totals[site]))
    return "\n".join(lines)


# -- module singleton ---------------------------------------------------------

#: The process ring, None while disabled.  obs.events reads this global
#: directly (one ``is None`` branch) on every emit.
_RING: Optional[TelemetryRing] = None
_CONFIG_LOCK = threading.Lock()


def configure(enabled: bool, interval_ms: int, max_intervals: int) -> None:
    """(Re)configure the process ring from a session's conf: enable,
    disable, or keep the live ring when the shape is unchanged (so a
    repeat execute never resets accumulated intervals)."""
    global _RING
    with _CONFIG_LOCK:
        if not enabled:
            _RING = None
            return
        ring = _RING
        want_ns = max(1, int(interval_ms)) * 1_000_000
        if ring is not None and ring.interval_ns == want_ns and \
                ring.max_intervals == max(1, int(max_intervals)):
            return
        _RING = TelemetryRing(interval_ms, max_intervals)


def ring() -> Optional[TelemetryRing]:
    return _RING


def record_span(site: str, wall_ns: int, nbytes: int = 0) -> None:
    """Module-level fold (obs.events emit hook): no-op when disabled."""
    r = _RING
    if r is None:
        return
    r.record_span(site, wall_ns, nbytes)


def record_value(name: str, value: float) -> None:
    r = _RING
    if r is None:
        return
    r.record_value(name, value)


def register_gauge(name: str, fn: Callable[[], float]) -> None:
    r = _RING
    if r is None:
        return
    r.register_gauge(name, fn)


def completed_total() -> int:
    r = _RING
    return r.completed_total if r is not None else 0
