"""Event-log and Chrome-trace export.

Two output shapes for one event stream:

* **JSONL event log** (the Spark event-log analogue, conf
  ``spark.rapids.sql.tpu.obs.eventLogDir``): one ``{"type": "query"}``
  header line per query followed by its ``{"type": "event"}`` lines —
  append-only, so one file accumulates a session's queries and
  ``tools/rapidsprof.py`` post-processes it offline.
* **Chrome ``trace_event`` JSON** (Perfetto/chrome://tracing loadable):
  spans as complete ``"X"`` events, instants as ``"i"``, one track per
  (site, thread) pair named via ``"M"`` thread-name metadata, sorted by
  timestamp.

Engine-free (stdlib only) and duck-typed over events — Event objects
in-process, dicts after a JSONL round-trip.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Iterable, List, Tuple

from .events import SPAN, field


def _event_dict(ev) -> Dict[str, Any]:
    if isinstance(ev, dict):
        return ev
    return ev.to_dict()


# -- chrome trace -------------------------------------------------------------

def events_to_chrome(events: Iterable) -> Dict[str, Any]:
    """Build a Chrome ``trace_event`` document.  Timestamps convert from
    monotonic ns to the format's microseconds; tracks (tids) are one per
    (site, thread) so e.g. the async spill writer's spans never overlap
    the driver's dispatch spans."""
    tids: Dict[Tuple[str, str], int] = {}
    out: List[Dict[str, Any]] = []
    meta: List[Dict[str, Any]] = []

    def tid_for(site: str, thread: str) -> int:
        key = (site, thread)
        tid = tids.get(key)
        if tid is None:
            tid = tids[key] = len(tids) + 1
            meta.append({
                "name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
                "args": {"name": f"{site}/{thread}"},
            })
        return tid

    for ev in events:
        site = field(ev, "site") or "?"
        thread = field(ev, "thread") or "?"
        t0 = int(field(ev, "t0", 0) or 0)
        t1 = int(field(ev, "t1", 0) or 0)
        name = field(ev, "name") or site
        op_id = field(ev, "op_id") or ""
        args = dict(field(ev, "payload") or {})
        if op_id:
            args["op_id"] = op_id
        rec: Dict[str, Any] = {
            "name": name, "cat": site, "pid": 1,
            "tid": tid_for(site, thread), "ts": t0 / 1e3,
        }
        if args:
            rec["args"] = args
        if field(ev, "kind") == SPAN:
            rec["ph"] = "X"
            rec["dur"] = max(0, t1 - t0) / 1e3
        else:
            rec["ph"] = "i"
            rec["s"] = "t"
        out.append(rec)
    out.sort(key=lambda r: r["ts"])
    return {"traceEvents": meta + out, "displayTimeUnit": "ms"}


def write_chrome_trace(path: str, events: Iterable) -> None:
    doc = events_to_chrome(events)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f)


# -- JSONL event log ----------------------------------------------------------

def write_event_log(path: str, query_record: Dict[str, Any],
                    events: Iterable) -> None:
    """Append one query (header + events) to the JSONL log at ``path``."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    qid = query_record.get("id", 0)
    with open(path, "a", encoding="utf-8") as f:
        f.write(json.dumps(query_record) + "\n")
        for ev in events:
            rec = dict(_event_dict(ev))
            rec["type"] = "event"
            rec["q"] = qid
            f.write(json.dumps(rec) + "\n")


def read_event_log(path: str) -> List[Dict[str, Any]]:
    """Parse a JSONL event log back into a list of query dicts, each the
    header record with its ``"events"`` list attached (rapidsprof's
    input).  Unknown/blank lines are skipped so a log a crashed process
    truncated mid-line still loads."""
    queries: List[Dict[str, Any]] = []
    by_id: Dict[Any, Dict[str, Any]] = {}
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("type") == "query":
                rec["events"] = []
                queries.append(rec)
                by_id[rec.get("id")] = rec
            elif rec.get("type") == "event":
                q = by_id.get(rec.get("q"))
                if q is not None:
                    q["events"].append(rec)
    return queries
