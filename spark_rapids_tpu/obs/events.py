"""Query-scoped observability event bus.

The engine's instrumentation chokepoints (``utils.tracing``,
``utils.compile_registry``, ``mem.catalog``, ``parallel.exchange``,
``fault.*``, ``plan.adaptive``) emit typed span/instant events into ONE
bounded ring buffer while a query runs; ``session.execute`` opens an
epoch before its metric snapshots and drains it after, so the event
window matches the metric deltas exactly.  The reference analogue is the
Spark event log + the SQL UI's per-exec metrics feed, with
``NvtxWithMetrics`` (NvtxWithMetrics.scala:27-36) as the span model.

Design constraints (rapidslint R2/R3/R4 apply here like everywhere):

* **Disabled path is one branch**: :func:`emit_span` / :func:`emit_instant`
  read a single module global; when no epoch is open (obs disabled, or no
  query running) the cost is one ``is None`` test — the same disarmed-hook
  pattern as ``fault.inject.maybe_fire``.
* **Bounded**: the ring holds at most ``obs.ring.maxEvents`` events; once
  full, later events are counted in ``dropped`` instead of appended
  (surfaced as ``last_metrics['obsEventsDropped']``) — profiling a
  pathological query can never grow memory without bound.
* **No blocking**: appends take one uncontended lock, no waits, no joins.
* **Engine-free**: this module imports only the stdlib, so
  ``tools/rapidsprof.py`` can load the ``obs`` package standalone
  (the ``rapidslint`` loader pattern) without pulling in jax.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

SPAN = "span"
INSTANT = "instant"


class Event:
    """One timeline entry.  ``kind`` is ``span`` (t0..t1) or ``instant``
    (t0 == t1); times are ``time.monotonic_ns`` stamps; ``site`` names the
    emitting chokepoint (device/dispatch/h2d/d2h/spill/unspill/exchange/
    retry/fault/adaptive/io); ``op_id`` ties the event to a physical-plan
    node when the site knows one."""

    __slots__ = ("kind", "site", "name", "op_id", "t0", "t1", "thread",
                 "payload")

    def __init__(self, kind: str, site: str, name: str, op_id: str,
                 t0: int, t1: int, thread: str,
                 payload: Optional[Dict[str, Any]]):
        self.kind = kind
        self.site = site
        self.name = name
        self.op_id = op_id
        self.t0 = t0
        self.t1 = t1
        self.thread = thread
        self.payload = payload

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "kind": self.kind, "site": self.site, "name": self.name,
            "op_id": self.op_id, "t0": self.t0, "t1": self.t1,
            "thread": self.thread,
        }
        if self.payload:
            d["payload"] = self.payload
        return d

    def __repr__(self):
        return (f"Event({self.kind} {self.site}:{self.name} "
                f"op={self.op_id or '-'} dur={self.t1 - self.t0}ns)")


def field(ev, key: str, default=None):
    """Duck-typed event accessor: works on :class:`Event` objects and on
    the plain dicts a JSONL event log round-trips through."""
    if isinstance(ev, dict):
        return ev.get(key, default)
    return getattr(ev, key, default)


class EventBus:
    """Bounded ring of events.  Append-only while the epoch is open; the
    first ``max_events`` events win and later ones increment ``dropped``
    (deterministic for tests, and the query *start* — scans, first
    dispatches, spill onset — is what a truncated profile needs most)."""

    def __init__(self, max_events: int):
        self._max = max(1, int(max_events))
        self._lock = threading.Lock()
        self._events: deque = deque()
        self._dropped = 0

    def append(self, ev: Event) -> None:
        with self._lock:
            if len(self._events) >= self._max:
                self._dropped += 1
                return
            self._events.append(ev)

    def drain(self) -> Tuple[List[Event], int]:
        with self._lock:
            evs = list(self._events)
            self._events.clear()
            dropped = self._dropped
            self._dropped = 0
            return evs, dropped

    def __len__(self):
        with self._lock:
            return len(self._events)


# One live bus per process (queries execute serially per session; a
# nested execute — prewarm, recovery re-lowering — rides the outer
# epoch).  ``_BUS is None`` IS the disabled state the hot path tests.
_BUS: Optional[EventBus] = None
_TOKEN: Optional[int] = None
_QUERY_SEQ = 0
_EPOCH_LOCK = threading.Lock()


def active() -> bool:
    """True while an epoch is open — sites with costly payload
    construction may check this first; plain emits don't need to."""
    return _BUS is not None


def begin_query(enabled: bool, max_events: int) -> Optional[int]:
    """Open a per-query epoch; returns a token for :func:`end_query`, or
    None when obs is disabled or an outer epoch is already open (the
    nested call neither resets nor drains — its events fold into the
    outer query's timeline)."""
    global _BUS, _TOKEN, _QUERY_SEQ
    with _EPOCH_LOCK:
        if _TOKEN is not None:
            return None
        if not enabled:
            _BUS = None
            return None
        _QUERY_SEQ += 1
        _TOKEN = _QUERY_SEQ
        _BUS = EventBus(max_events)
        return _TOKEN


def end_query(token: Optional[int]) -> Tuple[List[Event], int]:
    """Close the epoch ``token`` opened and drain its (events, dropped).
    A None token (disabled / nested) is a no-op returning ([], 0) —
    straggler emits after the close (e.g. an async spill writer
    finishing late) hit the ``is None`` fast path and vanish."""
    global _BUS, _TOKEN
    if token is None:
        return [], 0
    with _EPOCH_LOCK:
        bus = _BUS
        if bus is None or token != _TOKEN:
            return [], 0
        _BUS = None
        _TOKEN = None
    return bus.drain()


def emit_span(site: str, name: str, op_id: str = "",
              t0: int = 0, t1: int = 0, **payload) -> None:
    """Record a timed range.  No-op (one ``is None`` test) outside an
    epoch."""
    bus = _BUS
    if bus is None:
        return
    bus.append(Event(SPAN, site, name, op_id, t0, t1,
                     threading.current_thread().name, payload or None))


def emit_instant(site: str, name: str, op_id: str = "", **payload) -> None:
    """Record a point event stamped now.  No-op outside an epoch."""
    bus = _BUS
    if bus is None:
        return
    t = time.monotonic_ns()
    bus.append(Event(INSTANT, site, name, op_id, t, t,
                     threading.current_thread().name, payload or None))
