"""Query-scoped observability event bus and per-query attribution scopes.

The engine's instrumentation chokepoints (``utils.tracing``,
``utils.compile_registry``, ``mem.catalog``, ``parallel.exchange``,
``fault.*``, ``plan.adaptive``) emit typed span/instant events into a
bounded ring buffer while a query runs; ``session.execute`` opens a
:class:`QueryScope` before its metric snapshots and drains it after, so
the event window matches the metric deltas exactly.  The reference
analogue is the Spark event log + the SQL UI's per-exec metrics feed,
with ``NvtxWithMetrics`` (NvtxWithMetrics.scala:27-36) as the span model.

Concurrency model (the serving runtime runs N ``session.execute`` calls
at once):

* Every top-level execute opens its own scope, bound to the opening
  thread in a thread->scope registry.  Helper threads a query spawns
  (stage read-ahead, spill writers, the deadline watchdog) are *adopted*
  into the spawning query's scope via :func:`adopt`, so their events and
  counters attribute to the right query.
* When exactly ONE scope is open process-wide (the serial case — all of
  tier-1), unbound threads fall back to that scope, which makes the
  concurrent model bit-identical to the old single-global-bus behavior.
  Under true concurrency an unbound, unadopted thread has no scope and
  its events vanish rather than pollute a random query's timeline.
* Scopes also carry the per-query metric counters
  (``utils.compile_registry`` / ``fault.metrics`` credit the current
  scope alongside their process-cumulative tallies) and the per-query
  fault-injection registry, so concurrent queries neither mix their
  compile/dispatch economics nor each other's injected faults.

Design constraints (rapidslint R2/R3/R4 apply here like everywhere):

* **Disabled path is cheap**: :func:`emit_span` / :func:`emit_instant`
  cost one dict probe + one ``is None`` test when no scope is open — the
  same disarmed-hook pattern as ``fault.inject.maybe_fire``.
* **Bounded**: the ring holds at most ``obs.ring.maxEvents`` events; once
  full, later events are counted in ``dropped`` instead of appended
  (surfaced as ``last_metrics['obsEventsDropped']``) — profiling a
  pathological query can never grow memory without bound.
* **No blocking**: appends take one uncontended lock, no waits, no joins.
* **Engine-free**: this module imports only the stdlib, so
  ``tools/rapidsprof.py`` can load the ``obs`` package standalone
  (the ``rapidslint`` loader pattern) without pulling in jax.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from . import timeseries as _ts

SPAN = "span"
INSTANT = "instant"

#: Process-cumulative events dropped across every query ring — the
#: telemetry gauge feed (per-query drops surface via obsEventsDropped).
_RING_DROPS_TOTAL = 0


def ring_drops_total() -> int:
    return _RING_DROPS_TOTAL


class Event:
    """One timeline entry.  ``kind`` is ``span`` (t0..t1) or ``instant``
    (t0 == t1); times are ``time.monotonic_ns`` stamps; ``site`` names the
    emitting chokepoint (device/dispatch/h2d/d2h/spill/unspill/exchange/
    retry/fault/adaptive/io); ``op_id`` ties the event to a physical-plan
    node when the site knows one."""

    __slots__ = ("kind", "site", "name", "op_id", "t0", "t1", "thread",
                 "payload")

    def __init__(self, kind: str, site: str, name: str, op_id: str,
                 t0: int, t1: int, thread: str,
                 payload: Optional[Dict[str, Any]]):
        self.kind = kind
        self.site = site
        self.name = name
        self.op_id = op_id
        self.t0 = t0
        self.t1 = t1
        self.thread = thread
        self.payload = payload

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "kind": self.kind, "site": self.site, "name": self.name,
            "op_id": self.op_id, "t0": self.t0, "t1": self.t1,
            "thread": self.thread,
        }
        if self.payload:
            d["payload"] = self.payload
        return d

    def __repr__(self):
        return (f"Event({self.kind} {self.site}:{self.name} "
                f"op={self.op_id or '-'} dur={self.t1 - self.t0}ns)")


def field(ev, key: str, default=None):
    """Duck-typed event accessor: works on :class:`Event` objects and on
    the plain dicts a JSONL event log round-trips through."""
    if isinstance(ev, dict):
        return ev.get(key, default)
    return getattr(ev, key, default)


class EventBus:
    """Bounded ring of events.  Append-only while the epoch is open; the
    first ``max_events`` events win and later ones increment ``dropped``
    (deterministic for tests, and the query *start* — scans, first
    dispatches, spill onset — is what a truncated profile needs most)."""

    def __init__(self, max_events: int):
        self._max = max(1, int(max_events))
        self._lock = threading.Lock()
        self._events: deque = deque()
        self._dropped = 0
        #: site -> drop count; a truncated profile's rollups silently
        #: under-attribute exactly these sites, so the summary banner
        #: must name them
        self._dropped_by_site: Dict[str, int] = {}

    def append(self, ev: Event) -> None:
        global _RING_DROPS_TOTAL
        with self._lock:
            if len(self._events) >= self._max:
                self._dropped += 1
                site = getattr(ev, "site", None) or "?"
                self._dropped_by_site[site] = \
                    self._dropped_by_site.get(site, 0) + 1
                _RING_DROPS_TOTAL += 1
                return
            self._events.append(ev)

    def drop_sites(self) -> Dict[str, int]:
        """Per-site drop counts since the last drain."""
        with self._lock:
            return dict(self._dropped_by_site)

    def drain(self) -> Tuple[List[Event], int]:
        with self._lock:
            evs = list(self._events)
            self._events.clear()
            dropped = self._dropped
            self._dropped = 0
            self._dropped_by_site = {}
            return evs, dropped

    def __len__(self):
        with self._lock:
            return len(self._events)


class QueryScope:
    """One executing query's attribution context.

    Carries the (optional) event ring, the per-query metric counters
    that ``utils.compile_registry`` / ``fault.metrics`` credit alongside
    their process-wide tallies, and the query's fault-injection
    registry.  A scope exists for every top-level ``session.execute``
    even with obs disabled — counter attribution and fault scoping are
    needed regardless; only ``bus`` is gated by ``obs.enabled``."""

    __slots__ = ("query_id", "bus", "fault_registry", "_lock", "_counters")

    def __init__(self, query_id: int, bus: Optional[EventBus]):
        self.query_id = query_id
        self.bus = bus
        self.fault_registry = None
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}

    def add(self, key: str, n) -> None:
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + n

    def counters_for(self, keys) -> Dict[str, int]:
        """This query's counter values for ``keys`` (0 when never hit) —
        the concurrent-safe replacement for a global snapshot delta."""
        with self._lock:
            return {k: self._counters.get(k, 0) for k in keys}


# Thread -> scope bindings plus the single-open-scope fallback.  With
# exactly one scope open, every thread resolves to it (identical to the
# historical one-global-bus behavior); with several open, only bound /
# adopted threads attribute.
_SCOPES: Dict[int, QueryScope] = {}
_OPEN: List[QueryScope] = []
_FALLBACK: Optional[QueryScope] = None
_QUERY_SEQ = 0
_EPOCH_LOCK = threading.Lock()


def current_scope() -> Optional[QueryScope]:
    """The scope the calling thread attributes to: its own binding, else
    the sole open scope, else None."""
    return _SCOPES.get(threading.get_ident()) or _FALLBACK


def task_key() -> Optional[QueryScope]:
    """Identity key for "which query/task is this thread working for" —
    used by the TpuSemaphore's per-task re-entrancy.  None = the
    process-wide default task (work outside any query)."""
    return _SCOPES.get(threading.get_ident()) or _FALLBACK


def scope_add(key: str, n) -> None:
    """Credit ``n`` to the current scope's ``key`` counter (no-op when
    the calling thread attributes to no query)."""
    sc = _SCOPES.get(threading.get_ident()) or _FALLBACK
    if sc is not None:
        sc.add(key, n)


def active() -> bool:
    """True while the calling thread attributes to a scope with a live
    event ring — sites with costly payload construction may check this
    first; plain emits don't need to."""
    sc = _SCOPES.get(threading.get_ident()) or _FALLBACK
    return sc is not None and sc.bus is not None


def _recompute_fallback_locked() -> None:
    global _FALLBACK
    _FALLBACK = _OPEN[0] if len(_OPEN) == 1 else None


def begin_query(enabled: bool, max_events: int) -> Optional[QueryScope]:
    """Open a per-query scope bound to the calling thread; returns the
    scope for :func:`end_query`, or None when this thread already runs
    inside a scope (a nested execute — prewarm, recovery re-lowering —
    neither resets nor drains: its events fold into the outer query's
    timeline).  ``enabled`` gates only the event ring; the scope itself
    (counters, fault registry, task identity) always exists."""
    global _QUERY_SEQ
    ident = threading.get_ident()
    with _EPOCH_LOCK:
        if _SCOPES.get(ident) is not None:
            return None
        _QUERY_SEQ += 1
        scope = QueryScope(
            _QUERY_SEQ, EventBus(max_events) if enabled else None)
        _SCOPES[ident] = scope
        _OPEN.append(scope)
        _recompute_fallback_locked()
        return scope


def end_query(scope: Optional[QueryScope]
              ) -> Tuple[List[Event], int, Dict[str, int]]:
    """Close ``scope`` and drain its (events, dropped, dropped_by_site).
    A None scope (nested execute) is a no-op returning ([], 0, {}).
    Straggler emits after the close (e.g. an async spill writer
    finishing late) find no scope and vanish."""
    if scope is None:
        return [], 0, {}
    with _EPOCH_LOCK:
        for ident in [i for i, s in _SCOPES.items() if s is scope]:
            del _SCOPES[ident]
        if scope in _OPEN:
            _OPEN.remove(scope)
        _recompute_fallback_locked()
    if scope.bus is None:
        return [], 0, {}
    by_site = scope.bus.drop_sites()
    events, dropped = scope.bus.drain()
    return events, dropped, by_site


class _adopt_ctx:
    """Bind the calling thread to ``scope`` for the duration (restoring
    any previous binding on exit).  No-op for a None scope or when the
    thread is already bound to it."""

    def __init__(self, scope: Optional[QueryScope]):
        self._scope = scope
        self._ident = None
        self._prev = None

    def __enter__(self):
        if self._scope is None:
            return self
        ident = threading.get_ident()
        with _EPOCH_LOCK:
            prev = _SCOPES.get(ident)
            if prev is self._scope:
                return self
            self._ident = ident
            self._prev = prev
            _SCOPES[ident] = self._scope
        return self

    def __exit__(self, *exc):
        if self._ident is None:
            return False
        with _EPOCH_LOCK:
            if self._prev is None:
                _SCOPES.pop(self._ident, None)
            else:
                _SCOPES[self._ident] = self._prev
        return False


def adopt(scope: Optional[QueryScope]) -> "_adopt_ctx":
    """Context manager a helper thread uses to attribute its work to the
    query that spawned it: capture ``current_scope()`` at submit/spawn
    time on the query thread, then run the helper body under
    ``with adopt(scope):``."""
    return _adopt_ctx(scope)


def emit_span(site: str, name: str, op_id: str = "",
              t0: int = 0, t1: int = 0, **payload) -> None:
    """Record a timed range.  No-op outside a scope with a live ring
    (the continuous telemetry fold still runs — it is process-scoped,
    not query-scoped, so late async-writer spans and inter-query work
    stay visible in the time-series view)."""
    if _ts._RING is not None:
        _ts.record_span(site, t1 - t0, int(payload.get("bytes", 0) or 0))
    sc = _SCOPES.get(threading.get_ident()) or _FALLBACK
    if sc is None or sc.bus is None:
        return
    sc.bus.append(Event(SPAN, site, name, op_id, t0, t1,
                        threading.current_thread().name, payload or None))


def emit_instant(site: str, name: str, op_id: str = "", **payload) -> None:
    """Record a point event stamped now.  No-op outside a scope with a
    live ring (the telemetry fold counts it regardless, like spans)."""
    if _ts._RING is not None:
        _ts.record_span(site, 0, int(payload.get("bytes", 0) or 0))
    sc = _SCOPES.get(threading.get_ident()) or _FALLBACK
    if sc is None or sc.bus is None:
        return
    t = time.monotonic_ns()
    sc.bus.append(Event(INSTANT, site, name, op_id, t, t,
                        threading.current_thread().name, payload or None))
