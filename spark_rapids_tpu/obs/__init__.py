"""Observability subsystem: query event bus, per-operator profiles,
Chrome-trace/JSONL export, and the ``tools/rapidsprof.py`` analysis CLI.

The package is deliberately engine-free (stdlib only, relative imports)
so ``rapidsprof`` can load it standalone the way ``rapidslint`` loads
``spark_rapids_tpu.analysis`` — without executing the engine's root
``__init__`` (which imports jax).  See ``docs/observability.md``.
"""

from __future__ import annotations

import logging
from typing import Callable, Optional

from .events import (  # noqa: F401 — re-exported emitter surface
    Event, EventBus, QueryScope, active, adopt, begin_query, current_scope,
    emit_instant, emit_span, end_query,
)
from . import critpath, sentinel, timeseries  # noqa: F401 — obs v2 surface

# -- explain sink -------------------------------------------------------------
#
# ``spark.rapids.sql.explain`` output used to be print()-ed straight to
# stdout (plan/overrides.py), spamming library embedders and pytest
# capture.  It now goes through this sink: a standard logger by default
# (enable with ``logging.getLogger("spark_rapids_tpu.explain")``), or a
# caller-installed callable for tests/tools.

_EXPLAIN_LOGGER = logging.getLogger("spark_rapids_tpu.explain")
_EXPLAIN_SINK: Optional[Callable[[str], None]] = None


def set_explain_sink(fn: Optional[Callable[[str], None]]) -> None:
    """Route explain output to ``fn(text)``; None restores the logger."""
    global _EXPLAIN_SINK
    _EXPLAIN_SINK = fn


def explain_sink(text: str) -> None:
    """Deliver one explain block (plan/overrides calls this when
    ``spark.rapids.sql.explain`` is on)."""
    sink = _EXPLAIN_SINK
    if sink is not None:
        sink(text)
        return
    _EXPLAIN_LOGGER.info("%s", text)
