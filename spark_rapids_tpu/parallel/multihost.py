"""Multi-host process groups: the DCN channel of the engine's distributed
story (SURVEY.md section 2.7 — the role RapidsShuffleManager's UCX/DCN
transport + executor discovery play for the reference).

TPU-first shape: there is no custom transport to write.  Each host runs one
process; ``jax.distributed.initialize`` forms the process group over the
coordinator, ``jax.devices()`` then spans EVERY host's chips, and the same
``jax.sharding.Mesh`` + ``lax.all_to_all`` exchange the engine already uses
single-host (``parallel.mesh_shuffle``) rides ICI within a slice and DCN
across slices — XLA picks the fabric per edge, no NCCL/MPI analogue needed.

Config keys mirror the deployment story:
  spark.rapids.multihost.coordinator   host:port of process 0
  spark.rapids.multihost.numProcesses  world size
  spark.rapids.multihost.processId    this process's rank

``init_multihost`` is idempotent and a no-op for world size 1 (the
single-process development mode every test runs in).
"""

from __future__ import annotations

from typing import Optional

from spark_rapids_tpu.config import RapidsConf, conf_int, conf_str

MULTIHOST_COORDINATOR = conf_str(
    "spark.rapids.multihost.coordinator", "",
    "host:port of the rank-0 coordinator for multi-host execution; empty "
    "means single-process mode.")
MULTIHOST_NUM_PROCESSES = conf_int(
    "spark.rapids.multihost.numProcesses", 1,
    "World size of the multi-host process group.")
MULTIHOST_PROCESS_ID = conf_int(
    "spark.rapids.multihost.processId", 0,
    "This process's rank in the multi-host group.")

_initialized = False


def init_multihost(conf: Optional[RapidsConf] = None,
                   coordinator: Optional[str] = None,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None) -> bool:
    """Join the process group (idempotent).  Returns True if a >1-process
    group is active after the call.

    After initialization ``jax.devices()`` lists every host's chips, so
    ``mesh_shuffle.make_mesh()`` builds a global mesh and the engine's
    exchange runs across hosts unchanged.
    """
    global _initialized
    conf = conf or RapidsConf()
    coordinator = coordinator or MULTIHOST_COORDINATOR.get(conf)
    num_processes = num_processes or MULTIHOST_NUM_PROCESSES.get(conf)
    process_id = process_id if process_id is not None \
        else MULTIHOST_PROCESS_ID.get(conf)
    if not coordinator or num_processes <= 1:
        return False
    if _initialized:
        return True
    import jax
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    _initialized = True
    return True


def world_info() -> dict:
    """(process_count, process_index, device counts) for observability."""
    import jax
    return {
        "process_count": jax.process_count(),
        "process_index": jax.process_index(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }
