"""Two-process (multi-HOST) dryrun of the mesh-shuffled aggregation.

The reference's shuffle transport serves multi-executor as the normal case
(shuffle-plugin UCXShuffleTransport.scala:47-235 — executors discover each
other and move shuffle blocks over the wire).  The TPU-first analogue
needs no custom transport: each host joins the process group via
``jax.distributed.initialize`` (parallel/multihost.py), the SAME jitted
SPMD program (partition -> all_to_all -> local merge agg,
parallel/distributed.py) runs on every process, and XLA's collectives
carry the bytes — ICI within a slice, DCN (here: Gloo over TCP) across
hosts.

Run one process per host:

    python -m spark_rapids_tpu.parallel.multihost_demo \
        --rank 0 --world 2 --coordinator 127.0.0.1:29500 [--devices 4]

Every rank verifies the GLOBAL result against a numpy oracle (outputs are
gathered with ``process_allgather``) and prints one JSON line.  Exercised
by tests/test_multihost.py and the CI ``multihost`` step.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rank", type=int, required=True)
    ap.add_argument("--world", type=int, required=True)
    ap.add_argument("--coordinator", required=True)
    ap.add_argument("--devices", type=int, default=4,
                    help="virtual CPU devices per process")
    ap.add_argument("--rows", type=int, default=256)
    args = ap.parse_args(argv)

    # CPU backend with N virtual devices per process — must be set before
    # jax initializes (the dryrun trick from tests/conftest.py, per host)
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") +
        f" --xla_force_host_platform_device_count={args.devices}")
    os.environ.pop("JAX_PLATFORMS", None)
    import jax
    jax.config.update("jax_platforms", "cpu")

    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.parallel.multihost import (
        init_multihost, world_info,
    )

    conf = RapidsConf({
        "spark.rapids.multihost.coordinator": args.coordinator,
        "spark.rapids.multihost.numProcesses": args.world,
        "spark.rapids.multihost.processId": args.rank,
    })
    active = init_multihost(conf)
    assert active, "multi-host group did not form"
    info = world_info()
    assert info["process_count"] == args.world, info
    assert info["global_devices"] == args.world * args.devices, info

    import numpy as np
    from jax.experimental import multihost_utils
    from jax.sharding import NamedSharding, PartitionSpec as P

    from spark_rapids_tpu.parallel.distributed import (
        make_distributed_agg_step,
    )
    from spark_rapids_tpu.parallel.mesh_shuffle import DATA_AXIS, make_mesh

    n = info["global_devices"]
    cap = args.rows
    n_keys = 17
    # every rank derives the identical GLOBAL dataset (same seed), then
    # contributes only its local shards — the multi-controller contract
    rng = np.random.RandomState(7)
    keys = rng.randint(0, n_keys, size=(n, cap)).astype(np.int64)
    values = rng.randint(-100, 100, size=(n, cap)).astype(np.int64)
    validity = rng.rand(n, cap) < 0.9
    num_rows = np.full(n, cap, dtype=np.int32)
    num_rows[-1] = cap // 2  # ragged shard

    mesh = make_mesh(n)
    s2 = NamedSharding(mesh, P(DATA_AXIS, None))
    s1 = NamedSharding(mesh, P(DATA_AXIS))
    lo = args.rank * args.devices
    hi = lo + args.devices

    def shard2(a):
        return jax.make_array_from_process_local_data(
            s2, np.ascontiguousarray(a[lo:hi]), a.shape)

    dk, dv, dva = shard2(keys), shard2(values), shard2(validity)
    dn = jax.make_array_from_process_local_data(
        s1, np.ascontiguousarray(num_rows[lo:hi]), num_rows.shape)

    step = make_distributed_agg_step(mesh, cap)
    gk, gs, ng = jax.block_until_ready(step(dk, dv, dva, dn))

    # gather every process's output shards for global verification
    gk_h = np.asarray(multihost_utils.process_allgather(gk, tiled=True))
    gs_h = np.asarray(multihost_utils.process_allgather(gs, tiled=True))
    ng_h = np.asarray(multihost_utils.process_allgather(ng, tiled=True))

    expect = {}
    for d in range(n):
        for r in range(num_rows[d]):
            k = int(keys[d, r])
            expect[k] = expect.get(k, 0) + (
                int(values[d, r]) if validity[d, r] else 0)
    got = {}
    for d in range(n):
        for i in range(int(ng_h[d])):
            got[int(gk_h[d, i])] = got.get(int(gk_h[d, i]), 0) + \
                int(gs_h[d, i])
    assert got == expect, f"rank {args.rank}: {got} != {expect}"

    print(json.dumps({
        "ok": True, "rank": args.rank,
        "process_count": info["process_count"],
        "local_devices": info["local_devices"],
        "global_devices": info["global_devices"],
        "groups": len(got), "rows": int(num_rows.sum()),
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
