"""Partitioning strategies (reference: GpuHashPartitioning.scala,
GpuRangePartitioner.scala, GpuRoundRobinPartitioning.scala,
GpuSinglePartitioning.scala — SURVEY.md section 2.5).

Each strategy computes a target-partition id per row, on device (for TPU
exchanges) and on host (CPU exchanges + oracle).  Hash partitioning uses
murmur3 pmod over per-type hash words; for fixed-width types the words are
the raw value bits (Spark-compatible placement), but for strings murmur3 is
fed this engine's internal polynomial hash words rather than the UTF-8
bytes, so string placement is internally consistent (CPU and TPU place
every row identically — required for mixed CPU/TPU plans to line up at
joins) but NOT byte-compatible with Apache Spark's murmur3 string hashing.
See docs/compatibility.md.
"""

from __future__ import annotations

import dataclasses
import re
from typing import List, Optional

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import ColumnBatch, HostBatch
from spark_rapids_tpu.exprs.base import (
    CpuEvalCtx, Expression, SortOrder, TpuEvalCtx,
)
from spark_rapids_tpu.exprs.hashing import murmur3_cols, murmur3_cols_cpu


class Partitioning:
    num_partitions: int = 1

    def device_partition_ids(self, batch: ColumnBatch, part_index: int):
        raise NotImplementedError

    def host_partition_ids(self, batch: HostBatch, part_index: int):
        raise NotImplementedError

    def prepare(self, sample_rows_fn):
        """Hook for strategies needing a pre-pass over the data (range)."""


@dataclasses.dataclass
class SinglePartitioning(Partitioning):
    num_partitions: int = 1

    def device_partition_ids(self, batch, part_index):
        return jnp.zeros(batch.capacity, dtype=jnp.int32)

    def host_partition_ids(self, batch, part_index):
        return np.zeros(batch.num_rows, dtype=np.int32)


@dataclasses.dataclass
class HashPartitioning(Partitioning):
    keys: List[Expression]
    num_partitions: int

    def device_partition_ids(self, batch, part_index):
        ctx = TpuEvalCtx(batch)
        vals = [k.tpu_eval(ctx) for k in self.keys]
        h = murmur3_cols(vals)  # int32, Spark-compatible
        n = jnp.int32(self.num_partitions)
        return ((h % n) + n) % n  # pmod

    def host_partition_ids(self, batch, part_index):
        ctx = CpuEvalCtx(batch)
        vals = [k.cpu_eval(ctx) for k in self.keys]
        h = murmur3_cols_cpu(vals)
        n = np.int32(self.num_partitions)
        return ((h % n) + n) % n


@dataclasses.dataclass
class RoundRobinPartitioning(Partitioning):
    num_partitions: int

    def device_partition_ids(self, batch, part_index):
        start = jnp.int32(part_index)
        return (start + jnp.arange(batch.capacity, dtype=jnp.int32)) \
            % jnp.int32(self.num_partitions)

    def host_partition_ids(self, batch, part_index):
        return (part_index + np.arange(batch.num_rows, dtype=np.int32)) \
            % np.int32(self.num_partitions)


#: Declarative regex -> PartitionSpec rules mapping a partitioning's class
#: name to the sharding its exchanged data carries inside a mesh-SPMD
#: program (docs/mesh.md "PartitionSpec rules").  ``("data",)`` means
#: row-sharded over the mesh data axis (the axis name matches
#: mesh_shuffle.DATA_AXIS); ``None`` means the strategy cannot lower into
#: the program and the exchange stays host-driven.  Both the lowering
#: (exchange._mesh_spmd_inline) and the verifier
#: (analysis.plan_verify.check_mesh_sharding) consume THIS table, so a
#: strategy cannot fuse under one and be rejected by the other.
#:
#: Hash / round-robin / range all shard by rows: their pid computations
#: are pure traced jnp over (batch, axis_index), with range bounds
#: sampled + sorted + picked in-program (device_bounds_in_program).
#: Single stays None: fusing it would leave each shard holding
#: "partition 0" locally, so a downstream global aggregate or limit
#: would run once PER SHARD (n rows where the contract is 1) —
#: single-partition consumers depend on seeing ONE merged partition,
#: which only the host-driven path provides.
MESH_PARTITION_RULES = (
    (r"^HashPartitioning", ("data",)),
    (r"^RoundRobinPartitioning", ("data",)),
    (r"^RangePartitioning", ("data",)),
    (r"^SinglePartitioning", None),
)


def match_partition_rules(name: str, rules=None):
    """First rule whose regex matches ``name`` (re.search) -> its
    PartitionSpec axis tuple, or None when no rule matches / the matched
    rule is an explicit None (both mean: not mesh-fusable)."""
    for pat, spec in (MESH_PARTITION_RULES if rules is None else rules):
        if re.search(pat, name):
            return spec
    return None


def mesh_compatible(p: Partitioning) -> bool:
    """Whether ``p``'s pid computation can lower INTO a mesh-SPMD
    shard_map program — a pure lookup of :data:`MESH_PARTITION_RULES`
    by class name (see the table's docstring for the rationale per
    strategy)."""
    return match_partition_rules(type(p).__name__) is not None


class RangePartitioning(Partitioning):
    """Sample-based range bounds (GpuRangePartitioner analogue).  Bounds are
    computed host-side from a sample by the exchange, then broadcast into the
    row->partition comparison (device: lexicographic compare against encoded
    bound words)."""

    def __init__(self, orders: List[SortOrder], key_ordinals: List[int],
                 num_partitions: int):
        self.orders = orders
        self.key_ordinals = key_ordinals
        self.num_partitions = num_partitions
        self.bound_rows: Optional[List[tuple]] = None  # host key tuples
        self._bound_words: Optional[tuple] = None  # device word arrays

    def prepare(self, sample_rows):
        """sample_rows: list of key tuples sampled from the input."""
        from spark_rapids_tpu.ops.cpu_exec import sort_key_fn
        n = self.num_partitions
        self._bound_words = None
        if not sample_rows or n <= 1:
            self.bound_rows = []
            return
        key = sort_key_fn(self.orders, list(range(len(self.orders))))
        ordered = sorted(sample_rows, key=key)
        bounds = []
        for i in range(1, n):
            idx = min(len(ordered) - 1, (i * len(ordered)) // n)
            bounds.append(ordered[idx])
        self.bound_rows = bounds

    def _host_cmp_le(self, row_key, bound) -> bool:
        from spark_rapids_tpu.ops.cpu_exec import sort_key_fn
        key = sort_key_fn(self.orders, list(range(len(self.orders))))
        return key(row_key) <= key(bound)

    def host_partition_ids(self, batch, part_index):
        assert self.bound_rows is not None, "range bounds not prepared"
        ids = np.zeros(batch.num_rows, dtype=np.int32)
        cols = [batch.columns[i].to_list() for i in self.key_ordinals]
        from spark_rapids_tpu.ops.cpu_exec import sort_key_fn
        keyf = sort_key_fn(self.orders, list(range(len(self.orders))))
        enc_bounds = [keyf(b) for b in self.bound_rows]
        for r in range(batch.num_rows):
            rk = keyf(tuple(c[r] for c in cols))
            p = 0
            for b in enc_bounds:
                if rk > b:
                    p += 1
                else:
                    break
            ids[r] = p
        return ids

    def device_partition_ids(self, batch, part_index):
        assert self.bound_rows is not None, "range bounds not prepared"
        from spark_rapids_tpu.exprs.base import DevVal
        from spark_rapids_tpu.kernels.sortkeys import encode_sort_keys
        cap = batch.capacity
        vals = [DevVal.from_column(batch.columns[i])
                for i in self.key_ordinals]
        ascs = [o.ascending for o in self.orders]
        nfs = [o.nulls_first for o in self.orders]
        words = encode_sort_keys(vals, ascs, nfs, batch.num_rows,
                                 liveness=False)
        # No liveness word: padding rows' pid is masked later.
        pid = jnp.zeros(cap, dtype=jnp.int32)
        for bound in self.bound_rows:
            bwords = self._encode_bound(bound)
            # row > bound (lexicographic over words)?
            gt = jnp.zeros(cap, dtype=jnp.bool_)
            eq = jnp.ones(cap, dtype=jnp.bool_)
            for w, bw in zip(words, bwords):
                gt = gt | (eq & (w > bw))
                eq = eq & (w == bw)
            pid = pid + gt.astype(jnp.int32)
        return pid

    def encode_bounds_device(self) -> tuple:
        """ALL N-1 bounds encoded with ONE batched host_to_device + ONE
        encode_sort_keys call (vs one H2D per bound in the eager
        :meth:`_encode_bound` path).  Returns a tuple of per-word device
        arrays, each shaped [num_bounds] — pytree-friendly, so the
        exchange passes them as traced arguments and range splits ride
        the jitted pid-sort program like hash/round-robin.  Cached until
        the next :meth:`prepare` resamples the bounds."""
        assert self.bound_rows is not None, "range bounds not prepared"
        if self._bound_words is not None:
            return self._bound_words
        if not self.bound_rows:
            self._bound_words = ()
            return self._bound_words
        from spark_rapids_tpu.batch import HostBatch, HostColumn, \
            host_to_device
        from spark_rapids_tpu.exprs.base import DevVal
        from spark_rapids_tpu.kernels.sortkeys import encode_sort_keys
        nb = len(self.bound_rows)
        fields, cols = [], []
        for i, o in enumerate(self.orders):
            dt = o.child.dtype
            fields.append((f"b{i}", dt))
            cols.append(HostColumn.from_list(
                dt, [b[i] for b in self.bound_rows]))
        hb = HostBatch(T.Schema(fields), cols)
        db = host_to_device(hb, capacity=nb)
        vals = [DevVal.from_column(c) for c in db.columns]
        ascs = [o.ascending for o in self.orders]
        nfs = [o.nulls_first for o in self.orders]
        words = encode_sort_keys(vals, ascs, nfs, db.num_rows,
                                 liveness=False)
        self._bound_words = tuple(w[:nb] for w in words)
        return self._bound_words

    def device_partition_ids_from_words(self, batch: ColumnBatch,
                                        bound_words: tuple):
        """Vectorized pid: ONE lexicographic compare of every row against
        ALL bounds (broadcast over a [cap, num_bounds] grid) — jit-safe,
        since the bounds arrive as traced word arrays instead of per-bound
        eager encodes.  pid = number of bounds the row exceeds, identical
        to the per-bound loop in :meth:`device_partition_ids`."""
        from spark_rapids_tpu.exprs.base import DevVal
        from spark_rapids_tpu.kernels.sortkeys import encode_sort_keys
        cap = batch.capacity
        if not bound_words:
            return jnp.zeros(cap, dtype=jnp.int32)
        vals = [DevVal.from_column(batch.columns[i])
                for i in self.key_ordinals]
        ascs = [o.ascending for o in self.orders]
        nfs = [o.nulls_first for o in self.orders]
        words = encode_sort_keys(vals, ascs, nfs, batch.num_rows,
                                 liveness=False)
        nb = int(bound_words[0].shape[0])
        gt = jnp.zeros((cap, nb), dtype=jnp.bool_)
        eq = jnp.ones((cap, nb), dtype=jnp.bool_)
        for w, bw in zip(words, bound_words):
            gt = gt | (eq & (w[:, None] > bw[None, :]))
            eq = eq & (w[:, None] == bw[None, :])
        return jnp.sum(gt, axis=1).astype(jnp.int32)

    def device_bounds_in_program(self, batch: ColumnBatch, axis_name: str,
                                 sample_per_shard: int) -> tuple:
        """Range bounds computed INSIDE a shard_map program — the fused
        replacement for the eager host :meth:`prepare` sample pre-pass.

        Each shard contributes its first ``sample_per_shard`` live rows'
        encoded key words (padding rows mask to an all-ones sentinel whose
        leading null-rank word no real row can produce, so they sort
        strictly last); an ``all_gather`` over the mesh data axis pools
        the samples, one multi-word ``lax.sort`` orders them, and bound i
        is the pooled sample at ``(i * L) // n`` clipped to the live
        count L — the same index formula as the host :meth:`prepare`.

        The bound CHOICE differs from the host sample's (different rows
        sampled), but the partitioned result does not: partition ids use
        strict lexicographic compares, so equal keys never split across
        partitions and a range-partitioned sort's output is identical for
        any bound choice.  Returns traced bound word arrays shaped like
        :meth:`encode_bounds_device`'s, for
        :meth:`device_partition_ids_from_words`."""
        import jax
        from spark_rapids_tpu.exprs.base import DevVal
        from spark_rapids_tpu.kernels.sortkeys import encode_sort_keys
        n = self.num_partitions
        if n <= 1:
            return ()
        vals = [DevVal.from_column(batch.columns[i])
                for i in self.key_ordinals]
        ascs = [o.ascending for o in self.orders]
        nfs = [o.nulls_first for o in self.orders]
        words = encode_sort_keys(vals, ascs, nfs, batch.num_rows,
                                 liveness=False)
        s_cap = min(batch.capacity, max(int(sample_per_shard), 1))
        live = jnp.arange(s_cap, dtype=jnp.int32) < batch.num_rows
        sentinel = ~jnp.uint32(0)
        swords = [jnp.where(live, w[:s_cap], sentinel) for w in words]
        gwords = [jax.lax.all_gather(w, axis_name).reshape(-1)
                  for w in swords]
        ordered = jax.lax.sort(tuple(gwords), num_keys=len(gwords),
                               is_stable=True)
        length = jax.lax.psum(jnp.sum(live.astype(jnp.int32)), axis_name)
        idxs = jnp.clip(
            (jnp.arange(1, n, dtype=jnp.int32) * length) // n,
            0, jnp.maximum(length - 1, 0))
        return tuple(w[idxs] for w in ordered)

    def _encode_bound(self, bound: tuple) -> list:
        """Encode one host bound row with the same word scheme as
        encode_sort_keys (minus the liveness word)."""
        from spark_rapids_tpu.batch import HostBatch, HostColumn, \
            host_to_device
        from spark_rapids_tpu.exprs.base import DevVal
        from spark_rapids_tpu.kernels.sortkeys import encode_sort_keys
        fields = []
        cols = []
        for i, (o, v) in enumerate(zip(self.orders, bound)):
            dt = o.child.dtype
            fields.append((f"b{i}", dt))
            cols.append(HostColumn.from_list(dt, [v]))
        hb = HostBatch(T.Schema(fields), cols)
        db = host_to_device(hb, capacity=1)
        vals = [DevVal.from_column(c) for c in db.columns]
        ascs = [o.ascending for o in self.orders]
        nfs = [o.nulls_first for o in self.orders]
        words = encode_sort_keys(vals, ascs, nfs, db.num_rows,
                                 liveness=False)
        return [w[0] for w in words]
