"""Distributed query step over a device mesh: the flagship SPMD pipeline
(partition -> ICI all-to-all -> local merge aggregation), demonstrating the
full multi-chip shuffle path that replaces the reference's
RapidsShuffleManager+UCX data plane (SURVEY.md section 2.7).

The same step structure the driver dry-runs: every device holds one shard of
rows, hashes its grouping keys, exchanges rows so equal keys co-locate, and
merge-aggregates locally — i.e. the Partial/Exchange/Final pipeline of
TpuHashAggregateExec, fused into one compiled SPMD program.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_tpu.parallel.mesh_shuffle import (
    DATA_AXIS, make_exchange_fn, make_mesh,
)


def _local_sum_by_key(keys, values, validity, num_rows, cap: int):
    """Per-device groupby-sum on int64 keys via sort + segment sums."""
    live = jnp.arange(cap, dtype=jnp.int32) < num_rows
    big = jnp.int64(jnp.iinfo(jnp.int64).max)
    k = jnp.where(live, keys, big)
    order = jnp.argsort(k, stable=True).astype(jnp.int32)
    ks = k[order]
    vs = jnp.where(validity[order] & live[order], values[order], 0)
    prev = jnp.concatenate([ks[:1] - 1, ks[:-1]])
    seg_start = live[order] & (ks != prev)
    seg_ids = jnp.clip(jnp.cumsum(seg_start.astype(jnp.int32)) - 1, 0,
                       cap - 1)
    sums = jax.ops.segment_sum(vs, seg_ids, num_segments=cap)
    n_groups = jnp.sum(seg_start).astype(jnp.int32)
    group_keys = jnp.where(seg_start, ks, big)
    gorder = jnp.argsort(jnp.where(seg_start, 0, 1), stable=True)
    out_keys = ks[gorder]
    return out_keys, sums, n_groups


def make_distributed_agg_step(mesh: Mesh, cap: int):
    """jitted SPMD fn: (keys [N,cap] i64, values [N,cap] i64,
    validity [N,cap] bool, num_rows [N]) ->
    (group_keys [N, N*cap], sums [N, N*cap], n_groups [N])."""
    n = mesh.shape[DATA_AXIS]
    exchange = make_exchange_fn(mesh, n_cols=2, cap=cap)

    try:
        from jax import shard_map  # jax >= 0.6 top-level export
    except ImportError:  # jax 0.4.x keeps it in experimental
        from jax.experimental.shard_map import shard_map

    def local_agg(keys, values, validity, num_rows):
        k, v, val, nr = keys[0], values[0], validity[0], num_rows[0]
        out_cap = int(k.shape[0])
        gk, gs, ng = _local_sum_by_key(k, v, val, nr, out_cap)
        return gk[None], gs[None], ng[None]

    from spark_rapids_tpu.parallel.mesh_shuffle import shard_map_kwargs
    local_agg_fn = jax.jit(shard_map(
        local_agg, mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS, None),
                  P(DATA_AXIS, None), P(DATA_AXIS)),
        out_specs=(P(DATA_AXIS, None), P(DATA_AXIS, None), P(DATA_AXIS)),
        **shard_map_kwargs()))

    def step(keys, values, validity, num_rows):
        pids = (jnp.abs(keys) % n).astype(jnp.int32)
        (d_cols, v_cols, new_rows) = exchange(
            [keys, values], [validity, validity], num_rows, pids)
        ex_keys, ex_vals = d_cols
        ex_kvalid, ex_vvalid = v_cols
        return local_agg_fn(ex_keys, ex_vals, ex_vvalid, new_rows)

    return jax.jit(step)


def run_distributed_agg_demo(n_devices: int, rows_per_device: int = 256,
                             n_keys: int = 17) -> dict:
    """Create an n-device mesh, run one full distributed aggregation step,
    verify against numpy, and return stats.  This is what
    ``__graft_entry__.dryrun_multichip`` calls."""
    mesh = make_mesh(n_devices)
    n = mesh.shape[DATA_AXIS]
    cap = rows_per_device
    rng = np.random.RandomState(7)
    keys = rng.randint(0, n_keys, size=(n, cap)).astype(np.int64)
    values = rng.randint(-100, 100, size=(n, cap)).astype(np.int64)
    validity = rng.rand(n, cap) < 0.9
    num_rows = np.full(n, cap, dtype=np.int32)
    num_rows[-1] = cap // 2  # ragged shard

    sharding = NamedSharding(mesh, P(DATA_AXIS, None))
    s1 = NamedSharding(mesh, P(DATA_AXIS))
    dk = jax.device_put(keys, sharding)
    dv = jax.device_put(values, sharding)
    dva = jax.device_put(validity, sharding)
    dn = jax.device_put(num_rows, s1)

    step = make_distributed_agg_step(mesh, cap)
    gk, gs, ng = jax.block_until_ready(step(dk, dv, dva, dn))

    # oracle
    expect = {}
    for d in range(n):
        for r in range(num_rows[d]):
            if validity[d, r]:
                expect[int(keys[d, r])] = expect.get(int(keys[d, r]), 0) + \
                    int(values[d, r])
            else:
                expect.setdefault(int(keys[d, r]), 0)
    got = {}
    gk_h = np.asarray(gk)
    gs_h = np.asarray(gs)
    ng_h = np.asarray(ng)
    for d in range(n):
        for i in range(int(ng_h[d])):
            got[int(gk_h[d, i])] = got.get(int(gk_h[d, i]), 0) + \
                int(gs_h[d, i])
    assert got == expect, f"distributed agg mismatch: {got} != {expect}"
    return {"devices": n, "groups": len(got), "rows": int(num_rows.sum())}


def run_distributed_query_demo(n_devices: int, n_rows: int = 4000) -> dict:
    """Execute a PLANNER-BUILT query (string group key included) with the
    mesh all-to-all as the engine's shuffle, and verify against a pure-CPU
    oracle session.

    This is the engine-level multi-chip path: TpuShuffleExchangeExec sees a
    >1-device mesh (spark.rapids.shuffle.ici.enabled) and routes the hash
    exchange through ``mesh_shuffle.mesh_exchange_batches`` — the analogue
    of running a real query through the reference's RapidsShuffleManager
    (RapidsShuffleInternalManager.scala:91-154) instead of Spark's fallback
    shuffle.  Requires the default platform to provide ``n_devices``
    devices (the dryrun subprocess forces CPU + device_count).
    """
    import jax
    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.session import TpuSparkSession

    assert len(jax.devices()) >= n_devices, \
        f"need {n_devices} devices, have {len(jax.devices())}"

    cats = ["alpha", "beta", "gamma", "delta", None,
            "a-much-longer-category-name"]
    rng = np.random.RandomState(11)
    cat = [cats[i] for i in rng.randint(0, len(cats), n_rows)]
    qty = rng.randint(1, 100, n_rows).astype(np.int64)
    price = (rng.rand(n_rows) * 50).round(3)

    def build(sess):
        df = sess.create_dataframe(
            {"cat": list(cat), "qty": qty.tolist(),
             "price": price.tolist()},
            num_partitions=6)
        return (df.filter(F.col("qty") > 10)
                  .group_by("cat")
                  .agg(F.sum(F.col("qty")).alias("s"),
                       F.count(F.col("qty")).alias("c"),
                       F.avg(F.col("price")).alias("a")))

    tpu = (TpuSparkSession.builder()
           .config("spark.rapids.shuffle.ici.enabled", True)
           .config("spark.rapids.sql.variableFloatAgg.enabled", True)
           # accurate-sync metrics: shuffleWallNs must measure the real
           # all_to_all (the demo REPORTS shuffle_gb_per_sec from it; the
           # default async lower bound would inflate it arbitrarily)
           .config("spark.rapids.sql.tpu.metrics.detailEnabled", True)
           .config("spark.sql.shuffle.partitions", n_devices)
           .get_or_create())
    got_rows = build(tpu).collect()

    mesh_ops = [op for op, ms in tpu.last_metrics.items()
                if isinstance(ms, dict) and ms.get("meshExchanges")]
    assert mesh_ops, \
        f"no exchange took the mesh path; metrics={tpu.last_metrics}"

    # and a SHUFFLED JOIN through the same collective (both sides
    # all-to-all'd by key over the mesh, broadcast planning disabled)
    tpu.conf.set("spark.sql.autoBroadcastJoinThreshold", -1)
    dim = tpu.create_dataframe(
        {"cat": [c for c in cats if c is not None],
         "bonus": list(range(len(cats) - 1))}, num_partitions=2)
    fact = tpu.create_dataframe(
        {"cat": list(cat), "qty": qty.tolist()}, num_partitions=4)
    joined = fact.join(dim, on="cat", how="left")
    jrows = joined.collect()
    assert len(jrows) == n_rows, (len(jrows), n_rows)
    join_mesh_ops = [op for op, ms in tpu.last_metrics.items()
                     if isinstance(ms, dict)
                     if ms.get("meshExchanges")]
    assert len(join_mesh_ops) >= 2, tpu.last_metrics  # both join sides

    # oracle: plain python
    expect = {}
    for c, q, p in zip(cat, qty, price):
        if q <= 10:
            continue
        s, n_, a = expect.get(c, (0, 0, 0.0))
        expect[c] = (s + int(q), n_ + 1, a + float(p))
    exp_rows = sorted(
        ((k, s, n_, s_p / n_) for k, (s, n_, s_p) in expect.items()),
        key=lambda r: (r[0] is None, str(r[0])))
    got_sorted = sorted(got_rows, key=lambda r: (r[0] is None, str(r[0])))
    assert len(exp_rows) == len(got_sorted), \
        f"{len(exp_rows)} != {len(got_sorted)}"
    for e, g in zip(exp_rows, got_sorted):
        assert e[0] == g[0] and e[1] == g[1] and e[2] == g[2] and \
            abs(e[3] - g[3]) < 1e-6, f"mismatch: {e} vs {g}"
    return {"devices": n_devices, "groups": len(exp_rows),
            "mesh_exchanges": len(mesh_ops)}


def run_distributed_scale_demo(n_devices: int,
                               n_rows: int = 1_000_000) -> dict:
    """The dryrun's SCALE leg: >=1M rows through the planner-built mesh
    pipeline with a deliberately small spill budget, reporting shuffle
    bytes moved and GB/s (the reference surfaces the same per-read
    shuffle accounting, RapidsCachingReader.scala:125-133; spill tiers
    are the "data > HBM" answer, SURVEY.md section 2.4).

    Asserts the mesh exchange carried >= the live payload of the rows and
    that the spill catalog actually fired.  Returns the stats dict the
    dryrun prints (shuffle_gb_per_sec is the wall-clock figure on
    whatever backend runs it — virtual CPU mesh in the driver's dryrun).
    """
    import jax
    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.session import TpuSparkSession

    assert len(jax.devices()) >= n_devices, \
        f"need {n_devices} devices, have {len(jax.devices())}"

    rng = np.random.RandomState(23)
    keys = rng.randint(0, 100_000, n_rows).astype(np.int64)
    qty = rng.randint(1, 100, n_rows).astype(np.int64)
    price = (rng.rand(n_rows) * 50).round(3)

    tpu = (TpuSparkSession.builder()
           .config("spark.rapids.shuffle.ici.enabled", True)
           .config("spark.rapids.sql.variableFloatAgg.enabled", True)
           # accurate-sync metrics: shuffleWallNs must measure the real
           # all_to_all (the demo REPORTS shuffle_gb_per_sec from it; the
           # default async lower bound would inflate it arbitrarily)
           .config("spark.rapids.sql.tpu.metrics.detailEnabled", True)
           .config("spark.sql.shuffle.partitions", n_devices)
           .get_or_create())
    from spark_rapids_tpu import types as T
    df = tpu.create_dataframe(
        {"k": (T.LONG, keys), "qty": (T.LONG, qty),
         "price": (T.DOUBLE, price)},
        num_partitions=n_devices).cache()
    q = (df.group_by("k")
           .agg(F.sum(F.col("qty")).alias("s"),
                F.count(F.col("qty")).alias("c"),
                F.avg(F.col("price")).alias("a")))
    # Force the device budget BELOW the cached working set on the LIVE
    # catalog (DeviceRuntime is a process singleton — a session conf set
    # after first init would be ignored) and evict: the measured run must
    # unspill its inputs from host under a budget it cannot fit, the
    # "data > HBM" posture of the reference's spill tiers (SURVEY 2.4).
    catalog = tpu.runtime.catalog
    old_budget = catalog.device_budget
    mem0 = dict(catalog.metrics)
    try:
        q.collect()          # warmup: compiles + materializes the cache
        catalog.device_budget = max((n_rows * 24) // 3, 1 << 20)
        catalog.reserve(0)   # push the cached inputs to host
        rows = q.collect()   # measured run: unspills under budget
    finally:
        catalog.device_budget = old_budget
    assert len(rows) == len(np.unique(keys)), \
        (len(rows), len(np.unique(keys)))

    sh_bytes = sh_wall = wire = 0
    for op, ms in tpu.last_metrics.items():
        if op == "memory" or not isinstance(ms, dict):
            continue
        sh_bytes += ms.get("shuffleBytes", 0)
        wire += ms.get("shuffleWireBytes", 0)
        sh_wall += ms.get("shuffleWallNs", 0)
    # the exchange carries PARTIAL-AGG output (100K distinct keys x agg
    # buffers), not raw rows — still megabytes at this scale
    assert sh_bytes >= 1 << 20, \
        f"mesh shuffle moved only {sh_bytes}B for {n_rows} rows"
    assert sh_wall > 0
    mem = tpu.last_metrics.get("memory", {})
    spilled = (mem.get("spilled_to_host", 0) - mem0["spilled_to_host"]) \
        + (mem.get("spilled_to_disk", 0) - mem0["spilled_to_disk"])
    unspilled = mem.get("unspilled", 0) - mem0["unspilled"]
    assert spilled > 0, f"spill never fired: {mem} (baseline {mem0})"
    assert unspilled > 0, \
        f"measured run never unspilled: {mem} (baseline {mem0})"
    gbps = sh_bytes / sh_wall  # bytes/ns == GB/s
    return {"devices": n_devices, "rows": n_rows,
            "shuffle_bytes": int(sh_bytes), "wire_bytes": int(wire),
            "shuffle_wall_ms": round(sh_wall / 1e6, 1),
            "shuffle_gb_per_sec": round(gbps, 3),
            "spilled_batches": int(spilled),
            "unspilled_batches": int(unspilled)}
