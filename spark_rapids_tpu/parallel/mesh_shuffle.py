"""Device-mesh shuffle: XLA all-to-all over ICI (the accelerated-shuffle
analogue of the reference's UCX transport, SURVEY.md section 2.7b).

Where the reference moves map-side device batches between executors with UCX
tag-matched sends (UCX.scala:247-311), the TPU build keeps each partition's
batch sharded over a ``jax.sharding.Mesh`` and exchanges rows with a single
``lax.all_to_all`` collective inside ``shard_map`` — the transfer rides ICI
and is scheduled by XLA, no progress thread / bounce buffers needed.

Layout contract: a *mesh batch* is a pytree of arrays whose leading axis is
the mesh's ``data`` axis (one slice per device): data[N, cap], validity
[N, cap], num_rows[N].  Varlen columns (strings, arrays) ride the same
collective as fixed-width columns: each device's flat element buffer is
re-bucketed by destination inside the SPMD program and moves as one
``[N, ecap]`` stream with per-bucket element counts, the offsets layout
rebuilt on the receive side — the TPU answer to the reference's
bounce-buffer framing of varlen buffers
(RapidsShuffleServer.scala:343-612), with no host staging on either side.

:func:`mesh_exchange_batches` is the engine-facing entry: it is what
``TpuShuffleExchangeExec`` calls when a >1-device mesh is active
(``spark.rapids.shuffle.ici.enabled``), making the collective the query
plan's shuffle rather than a standalone demo.
"""

from __future__ import annotations

import logging
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import ColumnBatch, DeviceColumn
from spark_rapids_tpu.kernels.layout import (
    gather_stacked_elements, gather_stacked_rows,
    stacked_row_compaction_indices,
)

DATA_AXIS = "data"


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    """n-device 1-D mesh on the ``data`` axis.

    When the default platform has fewer than ``n_devices`` chips (e.g. a
    single real TPU during development), fall back to the CPU backend's
    virtual devices (``--xla_force_host_platform_device_count``) so mesh
    logic is exercised without hardware — the same trick tests/conftest.py
    uses.  Raises if no backend can supply ``n_devices`` devices.
    """
    devs = jax.devices()
    if n_devices is not None and len(devs) < n_devices:
        try:
            cpu = jax.devices("cpu")
        except RuntimeError:
            cpu = []
        if len(cpu) >= n_devices:
            if devs and devs[0].platform != cpu[0].platform:
                # Through the explain sink (PR 10), not a bare print: a
                # silent backend switch is how a bench run mislabels CPU
                # virtual-device scaling as TPU scaling.
                logging.getLogger("spark_rapids_tpu.explain").warning(
                    "make_mesh: default platform %r has only %d device(s); "
                    "falling back to %d CPU virtual devices — the mesh "
                    "runs on cpu, NOT on %r",
                    devs[0].platform, len(devs), n_devices,
                    devs[0].platform)
            devs = cpu
        else:
            raise RuntimeError(
                f"need {n_devices} devices, default platform has "
                f"{len(devs)} and cpu has {len(cpu)}; set JAX_PLATFORMS=cpu "
                f"and --xla_force_host_platform_device_count={n_devices}")
    n = n_devices or len(devs)
    return Mesh(np.array(devs[:n]), (DATA_AXIS,))


def shard_map_kwargs() -> dict:
    """kwargs disabling shard_map's static replication checker.

    The checker has no rule for ``pallas_call`` (kernels/pallas_tier.py
    kernels traced inside mesh programs raise NotImplementedError) and
    mis-tracks ``lax.scan`` carries mixing a replicated build side with
    sharded probe rows.  It is advisory only — correctness never depends
    on it; output specs are verified structurally by plan_verify.  The
    kwarg is probed by name: jax 0.4.x calls it ``check_rep``, newer
    releases renamed it ``check_vma``.
    """
    import inspect
    try:
        from jax import shard_map  # jax >= 0.6 top-level export
    except ImportError:  # jax 0.4.x keeps it in experimental
        from jax.experimental.shard_map import shard_map
    params = inspect.signature(shard_map).parameters
    for kw in ("check_rep", "check_vma"):
        if kw in params:
            return {kw: False}
    return {}


def _local_partition_buckets(data_cols, validity_cols, num_rows, pids,
                             n: int, cap: int):
    """Split local rows into n destination buckets of fixed capacity cap.

    Returns (bucketed columns [n, cap], bucketed validity [n, cap],
    counts [n]).  Gather-formulated: bucket d row j = j-th local row with
    pid == d.
    """
    live = jnp.arange(cap, dtype=jnp.int32) < num_rows
    pids = jnp.where(live, pids, n)  # padding rows to a dead bucket
    # stable order rows by pid -> rows of bucket d are contiguous
    order = jnp.argsort(pids, stable=True).astype(jnp.int32)
    sorted_pids = pids[order]
    counts = jnp.zeros(n + 1, dtype=jnp.int32).at[sorted_pids].add(
        1, mode="drop")[:n]
    starts = jnp.concatenate([jnp.zeros(1, jnp.int32),
                              jnp.cumsum(counts).astype(jnp.int32)[:-1]])
    # bucket[d, j] = sorted row at starts[d] + j (valid when j < counts[d])
    d_idx = jnp.arange(n, dtype=jnp.int32)[:, None]
    j_idx = jnp.arange(cap, dtype=jnp.int32)[None, :]
    src = jnp.clip(starts[:, None] + j_idx, 0, cap - 1)
    in_bucket = j_idx < counts[:, None]
    rows = order[src]
    out_data = [jnp.where(in_bucket, c[rows], 0) for c in data_cols]
    out_valid = [jnp.where(in_bucket, v[rows], False)
                 for v in validity_cols]
    return out_data, out_valid, counts


def _compact_received(data_cols, validity_cols, counts, n: int, cap: int):
    """Concatenate n received buckets ([n, cap] each) into one local batch
    of capacity n*cap."""
    total = jnp.sum(counts)
    out_cap = n * cap
    flat_pos = jnp.arange(out_cap, dtype=jnp.int32)
    cum = jnp.cumsum(counts)
    starts = cum - counts
    bucket = jnp.searchsorted(cum, flat_pos, side="right").astype(jnp.int32)
    bucket_c = jnp.clip(bucket, 0, n - 1)
    within = flat_pos - starts[bucket_c]
    live = flat_pos < total
    within = jnp.clip(within, 0, cap - 1)
    out_data = [jnp.where(live, c[bucket_c, within], 0) for c in data_cols]
    out_valid = [jnp.where(live, v[bucket_c, within], False)
                 for v in validity_cols]
    return out_data, out_valid, total.astype(jnp.int32)


def make_exchange_fn(mesh: Mesh, n_cols: int, cap: int):
    """Build a jittable SPMD function exchanging rows by partition id.

    fn(data_cols [N,cap]xk, validity_cols [N,cap]xk, num_rows [N],
       pids [N,cap]) -> (data [N, N*cap]xk, validity ..., num_rows [N])
    """
    n = mesh.shape[DATA_AXIS]

    def spmd(data_cols, validity_cols, num_rows, pids):
        # inside shard_map: leading axis is local (size 1); drop it
        data_cols = [c[0] for c in data_cols]
        validity_cols = [v[0] for v in validity_cols]
        nr = num_rows[0]
        p = pids[0]
        b_data, b_valid, counts = _local_partition_buckets(
            data_cols, validity_cols, nr, p, n, cap)
        # exchange bucket d -> device d; receive one bucket per device
        r_data = [jax.lax.all_to_all(c, DATA_AXIS, 0, 0, tiled=False)
                  for c in b_data]
        r_valid = [jax.lax.all_to_all(v, DATA_AXIS, 0, 0, tiled=False)
                   for v in b_valid]
        r_counts = jax.lax.all_to_all(counts, DATA_AXIS, 0, 0, tiled=False)
        o_data, o_valid, o_rows = _compact_received(
            r_data, r_valid, r_counts, n, cap)
        return ([c[None] for c in o_data], [v[None] for v in o_valid],
                o_rows[None])

    try:
        from jax import shard_map  # jax >= 0.6 top-level export
    except ImportError:  # jax 0.4.x keeps it in experimental
        from jax.experimental.shard_map import shard_map
    in_specs = (
        [P(DATA_AXIS, None)] * n_cols,
        [P(DATA_AXIS, None)] * n_cols,
        P(DATA_AXIS),
        P(DATA_AXIS, None),
    )
    out_specs = ([P(DATA_AXIS, None)] * n_cols,
                 [P(DATA_AXIS, None)] * n_cols,
                 P(DATA_AXIS))
    return jax.jit(shard_map(spmd, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **shard_map_kwargs()))


# --------------------------------------------------------------------------
# Engine-facing batch exchange (strings/arrays included), device-resident
# --------------------------------------------------------------------------
#
# Shuffle payloads never visit the host.  The path is:
#
#   1. pack:    a per-device jitted pad-to-common-capacity of each local
#               batch's raw buffers (data, validity, offsets, pids), run on
#               the target mesh device after a device-to-device placement.
#   2. gather:  ``jax.make_array_from_single_device_arrays`` stitches the n
#               per-device shards into mesh-sharded globals — metadata only,
#               no copies.
#   3. exchange: ONE shard_map program buckets rows by destination device,
#               streams each varlen column's element buffer as a flat
#               per-bucket run (searchsorted over cumulative lengths — no
#               padded row matrix, so one long string no longer inflates
#               every row's slot), runs one lax.all_to_all per payload over
#               ICI, and compacts the n received buckets into a device-local
#               batch.
#   4. unshard: each output global's addressable shard *is* the per-device
#               result; one jitted squeeze per device yields plain
#               single-device arrays, so downstream per-partition programs
#               stay strictly local (no hidden collectives, no rendezvous
#               hazard between interleaved consumers).
#
# This is the TPU answer to the reference's device-resident shuffle: map
# output batches stay in the device store
# (RapidsShuffleInternalManager.scala:91-154) and receives land directly in
# device buffers (RapidsShuffleClient.scala:108-355); here both legs are a
# single XLA-scheduled collective.  tests/test_mesh_shuffle.py asserts that
# no payload-sized jax.device_get happens between map eval and consumption.


def _fit_1d(x, out_len: int):
    """Pad with zeros or truncate to ``out_len``.

    Truncation is safe because callers size out_len from live row / element
    counts (host_sizes): everything past them is padding."""
    in_len = int(x.shape[0])
    if in_len == out_len:
        return x
    if in_len > out_len:
        return x[:out_len]
    pad = jnp.zeros((out_len - in_len,), dtype=x.dtype)
    return jnp.concatenate([x, pad])


def _make_pack_fn(schema, cap: int, ecaps: dict):
    """Jitted per-device pack: fit every buffer of (columns, num_rows, pids)
    to the common capacities and add a leading shard axis of size 1."""

    def pack(columns, num_rows, pids):
        payloads = []
        for ci, f in enumerate(schema.fields):
            c = columns[ci]
            if c.offsets is not None:
                ecap = ecaps[ci]
                data = _fit_1d(c.data, ecap)
                # fit offsets: padded rows repeat the end offset
                # (zero-length); truncation keeps all live rows' offsets
                offs = c.offsets
                if int(offs.shape[0]) > cap + 1:
                    offs = offs[:cap + 1]
                elif int(offs.shape[0]) < cap + 1:
                    tail = jnp.full((cap + 1 - int(offs.shape[0]),),
                                    0, dtype=offs.dtype) + offs[-1]
                    offs = jnp.concatenate([offs, tail])
                payloads += [data[None], offs.astype(jnp.int32)[None],
                             _fit_1d(c.validity, cap)[None]]
            else:
                payloads += [_fit_1d(c.data, cap)[None],
                             _fit_1d(c.validity, cap)[None]]
        payloads.append(_fit_1d(pids.astype(jnp.int32), cap)[None])
        payloads.append(jnp.asarray(num_rows, jnp.int32).reshape(1))
        return payloads

    return jax.jit(pack)


@jax.jit
def _unshard(arrs):
    """Drop the leading shard axis of each per-device output shard — one
    dispatch per device, on that device."""
    return [a[0] for a in arrs]


def _exchange_shard(cols, nr, pid, sig, n: int, cap: int, ecaps,
                    out_cap: int, out_ecaps):
    """Per-shard body of the varlen re-bucketing all_to_all collective.

    Traceable and collective-bearing: must run inside ``shard_map`` over
    ``DATA_AXIS``.  Shared verbatim by the host-driven exchange
    (:func:`_make_mesh_payload_fn`) and the fused whole-stage SPMD path
    (:func:`exchange_batch_collective` via parallel.mesh_spmd), so the two
    routes are bit-identical by construction.

    ``cols`` is the flat single-device payload list in schema order
    (varlen -> elements[ecap], offsets[cap+1], validity[cap]; fixed ->
    data[cap], validity[cap]); ``ecaps``/``out_ecaps`` index by FIELD
    ordinal (0 for fixed columns).  Returns (outs, total): the received
    payload list in the same order (offsets rebuilt, zeros past the live
    region) and the received live-row count.
    """

    def a2a(x):
        return jax.lax.all_to_all(x, DATA_AXIS, 0, 0, tiled=False)

    live = jnp.arange(cap, dtype=jnp.int32) < nr
    pid = jnp.where(live, pid, n)  # padding rows -> dead bucket
    order = jnp.argsort(pid, stable=True).astype(jnp.int32)
    sorted_pid = pid[order]
    counts = jnp.zeros(n + 1, jnp.int32).at[sorted_pid].add(
        1, mode="drop")[:n]
    starts = jnp.cumsum(counts) - counts
    j_idx = jnp.arange(cap, dtype=jnp.int32)[None, :]
    src = jnp.clip(starts[:, None] + j_idx, 0, cap - 1)
    in_bucket = j_idx < counts[:, None]
    rows = order[src]  # [n, cap] source row per (dest bucket, slot)

    send = []          # bucketed payloads, one list entry per wire array
    recv_plan = []     # (kind, ...) mirror for the receive side
    slot = 0
    for vi, is_varlen in enumerate(sig):
        if is_varlen:
            data, offs, valid = cols[slot], cols[slot + 1], cols[slot + 2]
            ecap = ecaps[vi]
            lens = jnp.where(live, offs[1:] - offs[:-1], 0) \
                .astype(jnp.int32)
            slens = lens[order]
            scum = jnp.cumsum(slens).astype(jnp.int32)
            sexcl = scum - slens
            ecounts = jnp.zeros(n + 1, jnp.int32).at[sorted_pid].add(
                slens, mode="drop")[:n]
            estarts = jnp.cumsum(ecounts) - ecounts
            k = jnp.arange(ecap, dtype=jnp.int32)[None, :]
            pos = estarts[:, None] + k          # [n, ecap]
            r = jnp.clip(jnp.searchsorted(
                scum, pos, side="right").astype(jnp.int32), 0, cap - 1)
            src_e = offs[order[r]] + (pos - sexcl[r])
            elem = data[jnp.clip(src_e, 0, ecap - 1)]
            elem = jnp.where(k < ecounts[:, None], elem,
                             jnp.zeros((), data.dtype))
            blens = jnp.where(in_bucket, lens[rows], 0)
            bvalid = jnp.where(in_bucket, valid[rows], False)
            send += [elem, blens, bvalid, ecounts]
            recv_plan.append(("varlen", vi))
            slot += 3
        else:
            data, valid = cols[slot], cols[slot + 1]
            bdata = jnp.where(in_bucket, data[rows],
                              jnp.zeros((), data.dtype))
            bvalid = jnp.where(in_bucket, valid[rows], False)
            send += [bdata, bvalid]
            recv_plan.append(("fixed", vi))
            slot += 2

    wire = [a2a(x) for x in send] + [a2a(counts)]
    r_counts = wire[-1]

    # receive-side row compaction indices, shared by all columns
    # (kernels/layout.py sharded k-way gather primitives)
    bkt, within, live_o, total = stacked_row_compaction_indices(
        r_counts, n, cap, out_cap)

    outs = []
    wi = 0
    for kind, vi in recv_plan:
        if kind == "varlen":
            relem, rlens, rvalid, recounts = (
                wire[wi], wire[wi + 1], wire[wi + 2], wire[wi + 3])
            wi += 4
            lens_o = jnp.where(live_o, rlens[bkt, within], 0)
            offs_o = jnp.concatenate([
                jnp.zeros(1, jnp.int32),
                jnp.cumsum(lens_o).astype(jnp.int32)])
            elem_o = gather_stacked_elements(
                relem, recounts, n, ecaps[vi], out_ecaps[vi])
            valid_o = gather_stacked_rows(rvalid, bkt, within, live_o)
            outs += [elem_o, offs_o, valid_o]
        else:
            rdata, rvalid = wire[wi], wire[wi + 1]
            wi += 2
            data_o = gather_stacked_rows(rdata, bkt, within, live_o)
            valid_o = gather_stacked_rows(rvalid, bkt, within, live_o)
            outs += [data_o, valid_o]
    return outs, total


def exchange_batch_collective(batch: ColumnBatch, pid, n: int) -> ColumnBatch:
    """In-program mesh exchange of one per-shard batch by destination pid.

    The fused whole-stage SPMD entry (parallel.mesh_spmd): callable only
    inside ``shard_map`` over ``DATA_AXIS``, where ``batch`` is the
    shard-local producer output and ``pid`` int32[cap] names each row's
    destination device.  ZERO host syncs: wire capacities come from the
    batch's STATIC capacity buckets instead of the host-driven path's
    live-size metadata round trip — the fused boundary trades bucket
    padding on the wire for a sync-free dispatch.  Returns the shard's
    received batch (capacity round_up(n*cap), rows in sender order), bit
    identical to :func:`mesh_exchange_batches` output for the same rows.
    """
    from spark_rapids_tpu.batch import round_up_capacity
    from spark_rapids_tpu.kernels.layout import ensure_row_layout
    batch = ensure_row_layout(batch)
    schema = batch.schema
    cap = batch.capacity
    sig = tuple(f.dtype.is_string or getattr(f.dtype, "is_array", False)
                for f in schema.fields)
    ecaps = tuple(int(batch.columns[ci].data.shape[0]) if sig[ci] else 0
                  for ci in range(len(schema.fields)))
    out_cap = round_up_capacity(n * cap)
    out_ecaps = tuple(round_up_capacity(n * e, minimum=16) if e else 0
                      for e in ecaps)
    cols = []
    for ci, c in enumerate(batch.columns):
        if sig[ci]:
            cols += [c.data, c.offsets.astype(jnp.int32), c.validity]
        else:
            cols += [c.data, c.validity]
    outs, total = _exchange_shard(
        cols, batch.num_rows, jnp.asarray(pid, jnp.int32), sig, n, cap,
        ecaps, out_cap, out_ecaps)
    new_cols = []
    ai = 0
    for ci, f in enumerate(schema.fields):
        if sig[ci]:
            elem, offs, valid = outs[ai], outs[ai + 1], outs[ai + 2]
            ai += 3
            new_cols.append(DeviceColumn(f.dtype, elem, valid, offs))
        else:
            data, valid = outs[ai], outs[ai + 1]
            ai += 2
            new_cols.append(DeviceColumn(f.dtype, data, valid, None))
    return ColumnBatch(schema, new_cols, total, out_cap)


def _make_mesh_payload_fn(mesh: Mesh, sig, cap: int, ecaps: tuple,
                          out_cap: int, out_ecaps: tuple):
    """The SPMD exchange program over one batch schema shape.

    ``sig[i]`` is True for varlen columns.  Payload order per column:
    varlen -> (elements[ecap], offsets[cap+1], validity[cap]);
    fixed  -> (data[cap], validity[cap]); then pids[cap], num_rows[1].
    """
    n = mesh.shape[DATA_AXIS]

    def spmd(payloads):
        pls = [p[0] for p in payloads[:-1]]
        nr = payloads[-1][0]
        pid = pls[-1]
        cols = pls[:-1]
        outs, total = _exchange_shard(
            cols, nr, pid, sig, n, cap, ecaps, out_cap, out_ecaps)
        return [o[None] for o in outs] + [total[None]]

    try:
        from jax import shard_map  # jax >= 0.6 top-level export
    except ImportError:  # jax 0.4.x keeps it in experimental
        from jax.experimental.shard_map import shard_map
    in_specs = []
    for is_varlen in sig:
        k = 3 if is_varlen else 2
        in_specs += [P(DATA_AXIS, None)] * k
    in_specs += [P(DATA_AXIS, None), P(DATA_AXIS)]
    out_specs = []
    for is_varlen in sig:
        k = 3 if is_varlen else 2
        out_specs += [P(DATA_AXIS, None)] * k
    out_specs.append(P(DATA_AXIS))
    return jax.jit(shard_map(spmd, mesh=mesh, in_specs=(in_specs,),
                             out_specs=out_specs, **shard_map_kwargs()))


# Compiled exchange programs, keyed by (mesh, schema signature, capacities).
# LRU-capped: every new capacity bucket x schema shape compiles and retains
# an SPMD program, the same pathology the plan-fingerprint cache caps.
_EXCHANGE_CACHE_MAX = 64
_exchange_fn_cache: "OrderedDict" = None  # type: ignore[assignment]


def _cached(key, builder):
    global _exchange_fn_cache
    if _exchange_fn_cache is None:
        from collections import OrderedDict
        _exchange_fn_cache = OrderedDict()
    fn = _exchange_fn_cache.get(key)
    if fn is None:
        fn = builder()
        _exchange_fn_cache[key] = fn
        while len(_exchange_fn_cache) > _EXCHANGE_CACHE_MAX:
            _exchange_fn_cache.popitem(last=False)
    else:
        _exchange_fn_cache.move_to_end(key)
    return fn


def mesh_exchange_batches(mesh: Mesh, local_batches, pids_list,
                          schema, stats: Optional[dict] = None
                          ) -> List[ColumnBatch]:
    """Exchange rows of per-device batches so every row lands on the device
    its pid names — the engine's accelerated shuffle.

    ``local_batches``: one ColumnBatch (or None) per mesh device.
    ``pids_list``: per-batch int32[cap] destination device ids in [0, n).
    Returns one ColumnBatch per device; every array in the outputs is a
    plain single-device array on its mesh device, and no payload buffer
    touches the host anywhere on this path.

    ``stats`` (optional dict) receives byte accounting for the exchange —
    the role of the reference's per-read shuffle metrics
    (RapidsCachingReader.scala:125-133):
      payload_bytes — LIVE rows x fixed row bytes + live varlen element
                      bytes (what "shuffle bytes written" means upstream);
      wire_bytes    — total size of the padded arrays the all_to_all
                      actually moves (upper bound incl. bucket padding).
    """
    from spark_rapids_tpu.batch import round_up_capacity
    n = mesh.shape[DATA_AXIS]
    devices = list(mesh.devices.flat)
    assert len(local_batches) == n and len(pids_list) == n
    present = [i for i, b in enumerate(local_batches) if b is not None]
    if not present:
        return []

    # Common static capacities, sized by LIVE rows/elements — one scalar
    # metadata round trip (the analogue of the reference's metadata
    # request/response before buffer transfer), so a sparse batch that kept
    # a huge input capacity doesn't inflate the wire shapes n-fold.
    from spark_rapids_tpu.batch import host_sizes
    sizes = host_sizes([local_batches[i] for i in present])
    cap = round_up_capacity(max(max(r for r, _ in sizes), 1))
    sig = tuple(f.dtype.is_string or getattr(f.dtype, "is_array", False)
                for f in schema.fields)
    ecaps = {}
    vi = 0
    for ci, f in enumerate(schema.fields):
        if sig[ci]:
            ecaps[ci] = round_up_capacity(
                max(max(totals[vi] for _, totals in sizes), 1), minimum=16)
            vi += 1
    out_cap = round_up_capacity(n * cap)
    out_ecaps = {ci: round_up_capacity(n * e) for ci, e in ecaps.items()}

    if stats is not None:
        from spark_rapids_tpu.batch import fixed_row_bytes, \
            varlen_byte_scales
        frb = fixed_row_bytes(schema)
        vscales = varlen_byte_scales(schema)
        by_dev = {d: rows * frb + sum(
            t * sc for t, sc in zip(totals, vscales))
            for d, (rows, totals) in zip(present, sizes)}
        stats["bytes_per_device"] = [by_dev.get(d, 0) for d in range(n)]
        stats["payload_bytes"] = sum(by_dev.values())
        # wire arrays: per column, bucketed [n, cap] (or [n, ecap]) on each
        # of n devices -> n x the packed global size, + counts
        wire = 0
        for ci, f in enumerate(schema.fields):
            if sig[ci]:
                edt = np.dtype(np.uint8) if f.dtype.is_string \
                    else np.dtype(f.dtype.element.np_dtype)
                wire += n * n * (ecaps[ci] * edt.itemsize  # elements
                                 + cap * 4                 # lens
                                 + cap * 1)                # validity
            else:
                itemsize = np.dtype(f.dtype.np_dtype).itemsize
                wire += n * n * cap * (itemsize + 1)
        stats["wire_bytes"] = wire

    sig_key = tuple((f.dtype, sig[ci]) for ci, f in enumerate(schema.fields))
    ecaps_t = tuple(ecaps.get(ci, 0) for ci in range(len(schema.fields)))
    oecaps_t = tuple(out_ecaps.get(ci, 0) for ci in range(len(schema.fields)))

    pack = _cached(("pack", mesh, sig_key, cap, ecaps_t),
                   lambda: _make_pack_fn(schema, cap, ecaps))
    fn = _cached(("spmd", mesh, sig_key, cap, ecaps_t, out_cap, oecaps_t),
                 lambda: _make_mesh_payload_fn(
                     mesh, sig, cap, ecaps_t, out_cap, oecaps_t))

    # Per-device pack on the mesh device (device-to-device placement only).
    shards_per_payload = None
    for d in range(n):
        b = local_batches[d]
        if b is None:
            cols, nr, pid = _empty_cols(schema, ecaps), 0, \
                jnp.zeros(cap, jnp.int32)
        else:
            if any(c.codes is not None for c in b.columns):
                # Dictionary-encoded columns materialize before packing:
                # the collective's wire format is (elements, lens,
                # validity) per varlen column, and host_sizes above
                # already sized ecaps at MATERIALIZED totals.  (The
                # single-host exchange keeps codes on the wire —
                # exchange.dictAware — but cross-device pieces would each
                # need the whole dictionary; see docs/shuffle.md.)
                if stats is not None:
                    # bytes the encoded corridor gives up at this
                    # boundary: the MATERIALIZED element bytes of the
                    # encoded columns (host_sizes already fetched them —
                    # no extra sync), surfaced as the exchange's
                    # mesh_materialize obs instant
                    from spark_rapids_tpu.batch import varlen_byte_scales
                    vs = varlen_byte_scales(schema)
                    _, totals = sizes[present.index(d)]
                    enc_flags = [c.codes is not None
                                 for c in b.columns if c.is_varlen]
                    stats["materialized_bytes"] = \
                        stats.get("materialized_bytes", 0) + sum(
                            int(t) * sc for t, sc, e
                            in zip(totals, vs, enc_flags) if e)
                    stats["encoded_materialized"] = \
                        stats.get("encoded_materialized", 0) + 1
                from spark_rapids_tpu.kernels.layout import ensure_row_layout
                b = ensure_row_layout(b)
            cols, nr, pid = list(b.columns), b.num_rows, pids_list[d]
        moved = jax.device_put((cols, nr, pid), devices[d])
        payloads = pack(*moved)
        if shards_per_payload is None:
            shards_per_payload = [[] for _ in payloads]
        for si, p in enumerate(payloads):
            shards_per_payload[si].append(p)

    sh2 = NamedSharding(mesh, P(DATA_AXIS, None))
    sh1 = NamedSharding(mesh, P(DATA_AXIS))
    globals_ = []
    for shards in shards_per_payload:
        tail = shards[0].shape[1:]
        sh = sh2 if tail else sh1
        globals_.append(jax.make_array_from_single_device_arrays(
            (n,) + tail, sh, shards))

    outs = fn(globals_)

    # Unshard: collect each device's shard of every output, squeeze the
    # shard axis in one per-device dispatch.
    per_dev_arrays = [[] for _ in range(n)]
    dev_pos = {d: i for i, d in enumerate(devices)}
    for g in outs:
        for shard in g.addressable_shards:
            per_dev_arrays[dev_pos[shard.device]].append(shard.data)
    results: List[ColumnBatch] = []
    for d in range(n):
        arrs = _unshard(per_dev_arrays[d])
        cols = []
        ai = 0
        for ci, f in enumerate(schema.fields):
            if sig[ci]:
                elem, offs, valid = arrs[ai], arrs[ai + 1], arrs[ai + 2]
                ai += 3
                cols.append(DeviceColumn(f.dtype, elem, valid, offs))
            else:
                data, valid = arrs[ai], arrs[ai + 1]
                ai += 2
                cols.append(DeviceColumn(f.dtype, data, valid, None))
        results.append(ColumnBatch(schema, cols, arrs[ai], out_cap))
    return results


def _empty_cols(schema, ecaps):
    cols = []
    for ci, f in enumerate(schema.fields):
        if f.dtype.is_string or getattr(f.dtype, "is_array", False):
            edt = jnp.uint8 if f.dtype.is_string \
                else f.dtype.element.np_dtype
            cols.append(DeviceColumn(
                f.dtype, jnp.zeros(ecaps[ci], edt),
                jnp.zeros(1, jnp.bool_), jnp.zeros(2, jnp.int32)))
        else:
            cols.append(DeviceColumn(
                f.dtype, jnp.zeros(1, f.dtype.np_dtype),
                jnp.zeros(1, jnp.bool_), None))
    return cols
