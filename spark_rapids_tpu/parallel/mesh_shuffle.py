"""Device-mesh shuffle: XLA all-to-all over ICI (the accelerated-shuffle
analogue of the reference's UCX transport, SURVEY.md section 2.7b).

Where the reference moves map-side device batches between executors with UCX
tag-matched sends (UCX.scala:247-311), the TPU build keeps each partition's
batch sharded over a ``jax.sharding.Mesh`` and exchanges rows with a single
``lax.all_to_all`` collective inside ``shard_map`` — the transfer rides ICI
and is scheduled by XLA, no progress thread / bounce buffers needed.

Layout contract: a *mesh batch* is a pytree of arrays whose leading axis is
the mesh's ``data`` axis (one slice per device): data[N, cap], validity
[N, cap], num_rows[N].  Strings are not yet supported on this path (they
fall back to the host exchange) — the bucket padding story for varlen
buffers lands with the native transport work.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import ColumnBatch, DeviceColumn

DATA_AXIS = "data"


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    """n-device 1-D mesh on the ``data`` axis.

    When the default platform has fewer than ``n_devices`` chips (e.g. a
    single real TPU during development), fall back to the CPU backend's
    virtual devices (``--xla_force_host_platform_device_count``) so mesh
    logic is exercised without hardware — the same trick tests/conftest.py
    uses.  Raises if no backend can supply ``n_devices`` devices.
    """
    devs = jax.devices()
    if n_devices is not None and len(devs) < n_devices:
        try:
            cpu = jax.devices("cpu")
        except RuntimeError:
            cpu = []
        if len(cpu) >= n_devices:
            devs = cpu
        else:
            raise RuntimeError(
                f"need {n_devices} devices, default platform has "
                f"{len(devs)} and cpu has {len(cpu)}; set JAX_PLATFORMS=cpu "
                f"and --xla_force_host_platform_device_count={n_devices}")
    n = n_devices or len(devs)
    return Mesh(np.array(devs[:n]), (DATA_AXIS,))


def _local_partition_buckets(data_cols, validity_cols, num_rows, pids,
                             n: int, cap: int):
    """Split local rows into n destination buckets of fixed capacity cap.

    Returns (bucketed columns [n, cap], bucketed validity [n, cap],
    counts [n]).  Gather-formulated: bucket d row j = j-th local row with
    pid == d.
    """
    live = jnp.arange(cap, dtype=jnp.int32) < num_rows
    pids = jnp.where(live, pids, n)  # padding rows to a dead bucket
    # stable order rows by pid -> rows of bucket d are contiguous
    order = jnp.argsort(pids, stable=True).astype(jnp.int32)
    sorted_pids = pids[order]
    counts = jnp.zeros(n + 1, dtype=jnp.int32).at[sorted_pids].add(
        1, mode="drop")[:n]
    starts = jnp.concatenate([jnp.zeros(1, jnp.int32),
                              jnp.cumsum(counts).astype(jnp.int32)[:-1]])
    # bucket[d, j] = sorted row at starts[d] + j (valid when j < counts[d])
    d_idx = jnp.arange(n, dtype=jnp.int32)[:, None]
    j_idx = jnp.arange(cap, dtype=jnp.int32)[None, :]
    src = jnp.clip(starts[:, None] + j_idx, 0, cap - 1)
    in_bucket = j_idx < counts[:, None]
    rows = order[src]
    out_data = [jnp.where(in_bucket, c[rows], 0) for c in data_cols]
    out_valid = [jnp.where(in_bucket, v[rows], False)
                 for v in validity_cols]
    return out_data, out_valid, counts


def _compact_received(data_cols, validity_cols, counts, n: int, cap: int):
    """Concatenate n received buckets ([n, cap] each) into one local batch
    of capacity n*cap."""
    total = jnp.sum(counts)
    out_cap = n * cap
    flat_pos = jnp.arange(out_cap, dtype=jnp.int32)
    cum = jnp.cumsum(counts)
    starts = cum - counts
    bucket = jnp.searchsorted(cum, flat_pos, side="right").astype(jnp.int32)
    bucket_c = jnp.clip(bucket, 0, n - 1)
    within = flat_pos - starts[bucket_c]
    live = flat_pos < total
    within = jnp.clip(within, 0, cap - 1)
    out_data = [jnp.where(live, c[bucket_c, within], 0) for c in data_cols]
    out_valid = [jnp.where(live, v[bucket_c, within], False)
                 for v in validity_cols]
    return out_data, out_valid, total.astype(jnp.int32)


def all_to_all_exchange(mesh: Mesh, data_cols, validity_cols, num_rows,
                        pids):
    """SPMD row exchange: every row moves to the device ``pids`` names.

    Inputs are mesh-sharded: data_cols/validity_cols [N*cap] sharded on the
    leading axis? No — this function is built to be called INSIDE shard_map
    with per-device locals; see :func:`make_exchange_fn` for the wrapper.
    """
    raise NotImplementedError("use make_exchange_fn")


def make_exchange_fn(mesh: Mesh, n_cols: int, cap: int):
    """Build a jittable SPMD function exchanging rows by partition id.

    fn(data_cols [N,cap]xk, validity_cols [N,cap]xk, num_rows [N],
       pids [N,cap]) -> (data [N, N*cap]xk, validity ..., num_rows [N])
    """
    n = mesh.shape[DATA_AXIS]

    def spmd(data_cols, validity_cols, num_rows, pids):
        # inside shard_map: leading axis is local (size 1); drop it
        data_cols = [c[0] for c in data_cols]
        validity_cols = [v[0] for v in validity_cols]
        nr = num_rows[0]
        p = pids[0]
        b_data, b_valid, counts = _local_partition_buckets(
            data_cols, validity_cols, nr, p, n, cap)
        # exchange bucket d -> device d; receive one bucket per device
        r_data = [jax.lax.all_to_all(c, DATA_AXIS, 0, 0, tiled=False)
                  for c in b_data]
        r_valid = [jax.lax.all_to_all(v, DATA_AXIS, 0, 0, tiled=False)
                   for v in b_valid]
        r_counts = jax.lax.all_to_all(counts, DATA_AXIS, 0, 0, tiled=False)
        o_data, o_valid, o_rows = _compact_received(
            r_data, r_valid, r_counts, n, cap)
        return ([c[None] for c in o_data], [v[None] for v in o_valid],
                o_rows[None])

    from jax import shard_map
    in_specs = (
        [P(DATA_AXIS, None)] * n_cols,
        [P(DATA_AXIS, None)] * n_cols,
        P(DATA_AXIS),
        P(DATA_AXIS, None),
    )
    out_specs = ([P(DATA_AXIS, None)] * n_cols,
                 [P(DATA_AXIS, None)] * n_cols,
                 P(DATA_AXIS))
    return jax.jit(shard_map(spmd, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs))
