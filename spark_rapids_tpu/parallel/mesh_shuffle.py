"""Device-mesh shuffle: XLA all-to-all over ICI (the accelerated-shuffle
analogue of the reference's UCX transport, SURVEY.md section 2.7b).

Where the reference moves map-side device batches between executors with UCX
tag-matched sends (UCX.scala:247-311), the TPU build keeps each partition's
batch sharded over a ``jax.sharding.Mesh`` and exchanges rows with a single
``lax.all_to_all`` collective inside ``shard_map`` — the transfer rides ICI
and is scheduled by XLA, no progress thread / bounce buffers needed.

Layout contract: a *mesh batch* is a pytree of arrays whose leading axis is
the mesh's ``data`` axis (one slice per device): data[N, cap], validity
[N, cap], num_rows[N].  Strings ride the same collective as fixed-width
columns by flattening each device's (offsets, bytes) pair into a padded
``uint8[cap, maxlen]`` row matrix + ``int32[cap]`` lengths before the
all-to-all, and rebuilding the offsets layout on the receive side — the
TPU answer to the reference's bounce-buffer framing of varlen buffers
(RapidsShuffleServer.scala:343-612).

:func:`mesh_exchange_batches` is the engine-facing entry: it is what
``TpuShuffleExchangeExec`` calls when a >1-device mesh is active
(``spark.rapids.shuffle.ici.enabled``), making the collective the query
plan's shuffle rather than a standalone demo.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import ColumnBatch, DeviceColumn

DATA_AXIS = "data"


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    """n-device 1-D mesh on the ``data`` axis.

    When the default platform has fewer than ``n_devices`` chips (e.g. a
    single real TPU during development), fall back to the CPU backend's
    virtual devices (``--xla_force_host_platform_device_count``) so mesh
    logic is exercised without hardware — the same trick tests/conftest.py
    uses.  Raises if no backend can supply ``n_devices`` devices.
    """
    devs = jax.devices()
    if n_devices is not None and len(devs) < n_devices:
        try:
            cpu = jax.devices("cpu")
        except RuntimeError:
            cpu = []
        if len(cpu) >= n_devices:
            devs = cpu
        else:
            raise RuntimeError(
                f"need {n_devices} devices, default platform has "
                f"{len(devs)} and cpu has {len(cpu)}; set JAX_PLATFORMS=cpu "
                f"and --xla_force_host_platform_device_count={n_devices}")
    n = n_devices or len(devs)
    return Mesh(np.array(devs[:n]), (DATA_AXIS,))


def _local_partition_buckets(data_cols, validity_cols, num_rows, pids,
                             n: int, cap: int):
    """Split local rows into n destination buckets of fixed capacity cap.

    Returns (bucketed columns [n, cap], bucketed validity [n, cap],
    counts [n]).  Gather-formulated: bucket d row j = j-th local row with
    pid == d.
    """
    live = jnp.arange(cap, dtype=jnp.int32) < num_rows
    pids = jnp.where(live, pids, n)  # padding rows to a dead bucket
    # stable order rows by pid -> rows of bucket d are contiguous
    order = jnp.argsort(pids, stable=True).astype(jnp.int32)
    sorted_pids = pids[order]
    counts = jnp.zeros(n + 1, dtype=jnp.int32).at[sorted_pids].add(
        1, mode="drop")[:n]
    starts = jnp.concatenate([jnp.zeros(1, jnp.int32),
                              jnp.cumsum(counts).astype(jnp.int32)[:-1]])
    # bucket[d, j] = sorted row at starts[d] + j (valid when j < counts[d])
    d_idx = jnp.arange(n, dtype=jnp.int32)[:, None]
    j_idx = jnp.arange(cap, dtype=jnp.int32)[None, :]
    src = jnp.clip(starts[:, None] + j_idx, 0, cap - 1)
    in_bucket = j_idx < counts[:, None]
    rows = order[src]
    out_data = [jnp.where(in_bucket, c[rows], 0) for c in data_cols]
    out_valid = [jnp.where(in_bucket, v[rows], False)
                 for v in validity_cols]
    return out_data, out_valid, counts


def _compact_received(data_cols, validity_cols, counts, n: int, cap: int):
    """Concatenate n received buckets ([n, cap] each) into one local batch
    of capacity n*cap."""
    total = jnp.sum(counts)
    out_cap = n * cap
    flat_pos = jnp.arange(out_cap, dtype=jnp.int32)
    cum = jnp.cumsum(counts)
    starts = cum - counts
    bucket = jnp.searchsorted(cum, flat_pos, side="right").astype(jnp.int32)
    bucket_c = jnp.clip(bucket, 0, n - 1)
    within = flat_pos - starts[bucket_c]
    live = flat_pos < total
    within = jnp.clip(within, 0, cap - 1)
    out_data = [jnp.where(live, c[bucket_c, within], 0) for c in data_cols]
    out_valid = [jnp.where(live, v[bucket_c, within], False)
                 for v in validity_cols]
    return out_data, out_valid, total.astype(jnp.int32)


def make_exchange_fn(mesh: Mesh, n_cols: int, cap: int):
    """Build a jittable SPMD function exchanging rows by partition id.

    fn(data_cols [N,cap]xk, validity_cols [N,cap]xk, num_rows [N],
       pids [N,cap]) -> (data [N, N*cap]xk, validity ..., num_rows [N])
    """
    n = mesh.shape[DATA_AXIS]

    def spmd(data_cols, validity_cols, num_rows, pids):
        # inside shard_map: leading axis is local (size 1); drop it
        data_cols = [c[0] for c in data_cols]
        validity_cols = [v[0] for v in validity_cols]
        nr = num_rows[0]
        p = pids[0]
        b_data, b_valid, counts = _local_partition_buckets(
            data_cols, validity_cols, nr, p, n, cap)
        # exchange bucket d -> device d; receive one bucket per device
        r_data = [jax.lax.all_to_all(c, DATA_AXIS, 0, 0, tiled=False)
                  for c in b_data]
        r_valid = [jax.lax.all_to_all(v, DATA_AXIS, 0, 0, tiled=False)
                   for v in b_valid]
        r_counts = jax.lax.all_to_all(counts, DATA_AXIS, 0, 0, tiled=False)
        o_data, o_valid, o_rows = _compact_received(
            r_data, r_valid, r_counts, n, cap)
        return ([c[None] for c in o_data], [v[None] for v in o_valid],
                o_rows[None])

    from jax import shard_map
    in_specs = (
        [P(DATA_AXIS, None)] * n_cols,
        [P(DATA_AXIS, None)] * n_cols,
        P(DATA_AXIS),
        P(DATA_AXIS, None),
    )
    out_specs = ([P(DATA_AXIS, None)] * n_cols,
                 [P(DATA_AXIS, None)] * n_cols,
                 P(DATA_AXIS))
    return jax.jit(shard_map(spmd, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs))


# --------------------------------------------------------------------------
# Engine-facing batch exchange (strings included)
# --------------------------------------------------------------------------
#
# A ColumnBatch is lowered to a flat list of *payload* arrays, each with the
# row index as the leading axis:
#   fixed col   -> data[cap], validity[cap]
#   string col  -> bytes uint8[cap, maxlen], lengths int32[cap],
#                  validity[cap]
# One shard_map program buckets rows by destination device, runs ONE
# lax.all_to_all per payload over ICI, and compacts the n received buckets
# into a single local batch of capacity n*cap.  Row-major payloads mean the
# string bytes move on the same collective as the data — no separate varlen
# protocol.


def make_payload_exchange_fn(mesh: Mesh, ndims: Tuple[int, ...], cap: int):
    """Build the jitted SPMD exchange over arbitrary row-payload arrays.

    ``ndims[i]`` is the per-device rank of payload i (1 for [cap] vectors,
    2 for [cap, maxlen] byte matrices).  The returned fn maps
    (payloads [N, cap, ...], num_rows [N], pids [N, cap]) ->
    (payloads [N, N*cap, ...], counts [N]).
    """
    n = mesh.shape[DATA_AXIS]

    def spmd(payloads, num_rows, pids):
        pls = [p[0] for p in payloads]
        nr = num_rows[0]
        pid = pids[0]
        live = jnp.arange(cap, dtype=jnp.int32) < nr
        pid = jnp.where(live, pid, n)  # padding rows -> dead bucket
        order = jnp.argsort(pid, stable=True).astype(jnp.int32)
        sorted_pid = pid[order]
        counts = jnp.zeros(n + 1, jnp.int32).at[sorted_pid].add(
            1, mode="drop")[:n]
        starts = jnp.concatenate([
            jnp.zeros(1, jnp.int32),
            jnp.cumsum(counts).astype(jnp.int32)[:-1]])
        j_idx = jnp.arange(cap, dtype=jnp.int32)[None, :]
        src = jnp.clip(starts[:, None] + j_idx, 0, cap - 1)
        in_bucket = j_idx < counts[:, None]
        rows = order[src]  # [n, cap] source row per (dest bucket, slot)
        bucketed = []
        for p in pls:
            g = p[rows]  # [n, cap, ...trailing]
            mask = in_bucket.reshape(in_bucket.shape +
                                     (1,) * (g.ndim - 2))
            bucketed.append(jnp.where(mask, g, jnp.zeros((), g.dtype)))
        recv = [jax.lax.all_to_all(b, DATA_AXIS, 0, 0, tiled=False)
                for b in bucketed]
        r_counts = jax.lax.all_to_all(counts, DATA_AXIS, 0, 0, tiled=False)
        # compact the n received buckets into one local run of rows
        out_cap = n * cap
        flat = jnp.arange(out_cap, dtype=jnp.int32)
        cum = jnp.cumsum(r_counts)
        starts2 = cum - r_counts
        bucket = jnp.searchsorted(cum, flat, side="right").astype(jnp.int32)
        bucket_c = jnp.clip(bucket, 0, n - 1)
        within = jnp.clip(flat - starts2[bucket_c], 0, cap - 1)
        total = jnp.sum(r_counts).astype(jnp.int32)
        live_o = flat < total
        outs = []
        for r in recv:
            g = r[bucket_c, within]  # [out_cap, ...trailing]
            mask = live_o.reshape(live_o.shape + (1,) * (g.ndim - 1))
            outs.append(jnp.where(mask, g, jnp.zeros((), g.dtype)))
        return [o[None] for o in outs], total[None]

    from jax import shard_map
    in_specs = ([P(DATA_AXIS, *([None] * nd)) for nd in ndims],
                P(DATA_AXIS), P(DATA_AXIS, None))
    out_specs = ([P(DATA_AXIS, *([None] * nd)) for nd in ndims],
                 P(DATA_AXIS))
    return jax.jit(shard_map(spmd, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs))


_exchange_fn_cache: dict = {}


def _cached_payload_exchange_fn(mesh: Mesh, ndims: Tuple[int, ...],
                                cap: int):
    key = (mesh, ndims, cap)
    fn = _exchange_fn_cache.get(key)
    if fn is None:
        fn = make_payload_exchange_fn(mesh, ndims, cap)
        _exchange_fn_cache[key] = fn
    return fn


@functools.partial(jax.jit, static_argnames=("byte_cap",))
def _padded_to_flat(mat, lens, byte_cap: int):
    """Rebuild the cudf (offsets, flat bytes) layout from a padded byte
    matrix: one cumsum + one searchsorted-driven gather."""
    out_cap, maxlen = int(mat.shape[0]), int(mat.shape[1])
    offsets = jnp.concatenate([
        jnp.zeros(1, jnp.int32),
        jnp.cumsum(lens).astype(jnp.int32)])
    j = jnp.arange(byte_cap, dtype=jnp.int32)
    row = jnp.searchsorted(offsets[1:], j, side="right").astype(jnp.int32)
    row_c = jnp.clip(row, 0, out_cap - 1)
    within = jnp.clip(j - offsets[row_c], 0, max(maxlen - 1, 0))
    data = jnp.where(j < offsets[-1], mat[row_c, within], 0).astype(jnp.uint8)
    return data, offsets


def mesh_exchange_batches(mesh: Mesh, local_batches, pids_list,
                          schema) -> List[ColumnBatch]:
    """Exchange rows of per-device batches so every row lands on the device
    its pid names — the engine's accelerated shuffle.

    ``local_batches``: one ColumnBatch (or None) per mesh device.
    ``pids_list``: per-batch int32[cap] destination device ids in [0, n).
    Returns one ColumnBatch per device with capacity n*cap_common; output
    ``num_rows`` stays a device scalar (no host sync on this path).
    """
    from spark_rapids_tpu.batch import round_up_capacity
    n = mesh.shape[DATA_AXIS]
    assert len(local_batches) == n and len(pids_list) == n
    present = [i for i, b in enumerate(local_batches) if b is not None]
    if not present:
        return []

    # one bulk fetch of every raw buffer (+ pids) — single round trip
    fetch = []
    for i in present:
        b = local_batches[i]
        fetch.append((b.num_rows, pids_list[i],
                      [(c.data, c.validity, c.offsets) if c.is_string
                       else (c.data, c.validity) for c in b.columns]))
    host = jax.device_get(fetch)

    cap = round_up_capacity(max(max(int(h[0]) for h in host), 1))
    str_cols = [i for i, f in enumerate(schema.fields) if f.dtype.is_string]
    maxlens = {}
    for ci in str_cols:
        m = 1
        for h in host:
            nrows = int(h[0])
            offs = np.asarray(h[2][ci][2])
            if nrows:
                m = max(m, int(np.max(offs[1:nrows + 1] - offs[:nrows])))
        maxlens[ci] = round_up_capacity(m, minimum=8)

    # build stacked [n, cap, ...] payloads on host
    payload_np: List[np.ndarray] = []
    ndims: List[int] = []
    col_payload_slots = []  # per schema col: indices into payload list
    for ci, f in enumerate(schema.fields):
        if f.dtype.is_string:
            ml = maxlens[ci]
            col_payload_slots.append((len(payload_np),))
            payload_np.append(np.zeros((n, cap, ml), dtype=np.uint8))
            payload_np.append(np.zeros((n, cap), dtype=np.int32))
            payload_np.append(np.zeros((n, cap), dtype=np.bool_))
            ndims.extend([2, 1, 1])
        else:
            col_payload_slots.append((len(payload_np),))
            payload_np.append(np.zeros((n, cap), dtype=f.dtype.np_dtype))
            payload_np.append(np.zeros((n, cap), dtype=np.bool_))
            ndims.extend([1, 1])
    num_rows_np = np.zeros(n, dtype=np.int32)
    pids_np = np.zeros((n, cap), dtype=np.int32)

    for h, dev in zip(host, present):
        nrows = int(h[0])
        num_rows_np[dev] = nrows
        if nrows == 0:
            continue
        pids_np[dev, :nrows] = np.asarray(h[1])[:nrows]
        slot = 0
        for ci, f in enumerate(schema.fields):
            bufs = h[2][ci]
            if f.dtype.is_string:
                data = np.asarray(bufs[0])
                valid = np.asarray(bufs[1])
                offs = np.asarray(bufs[2]).astype(np.int64)
                ml = maxlens[ci]
                lens = (offs[1:nrows + 1] - offs[:nrows]).astype(np.int32)
                idx = np.clip(offs[:nrows, None] +
                              np.arange(ml, dtype=np.int64)[None, :],
                              0, max(len(data) - 1, 0))
                mask = np.arange(ml, dtype=np.int32)[None, :] < lens[:, None]
                payload_np[slot][dev, :nrows] = np.where(
                    mask, data[idx], 0)
                payload_np[slot + 1][dev, :nrows] = lens
                payload_np[slot + 2][dev, :nrows] = valid[:nrows]
                slot += 3
            else:
                payload_np[slot][dev, :nrows] = np.asarray(bufs[0])[:nrows]
                payload_np[slot + 1][dev, :nrows] = \
                    np.asarray(bufs[1])[:nrows]
                slot += 2

    sh2 = NamedSharding(mesh, P(DATA_AXIS, None))
    sh3 = NamedSharding(mesh, P(DATA_AXIS, None, None))
    sh1 = NamedSharding(mesh, P(DATA_AXIS))
    payloads = [jax.device_put(p, sh3 if p.ndim == 3 else sh2)
                for p in payload_np]
    d_rows = jax.device_put(num_rows_np, sh1)
    d_pids = jax.device_put(pids_np, sh2)

    fn = _cached_payload_exchange_fn(mesh, tuple(ndims), cap)
    out_payloads, counts = fn(payloads, d_rows, d_pids)

    # Materialize per-device LOCAL batches: slicing the mesh-sharded
    # globals lazily would make every downstream per-partition program a
    # hidden cross-device collective — interleaved consumers (join sides,
    # AQE groups) then deadlock the rendezvous.  One staged host hop keeps
    # all post-shuffle work strictly local, like the reference's receive
    # side landing bounce buffers into device-local batches.
    host_payloads = jax.device_get(list(out_payloads))
    counts_h = np.asarray(jax.device_get(counts))

    out_cap = n * cap
    out: List[ColumnBatch] = []
    for d in range(n):
        cols = []
        slot = 0
        for ci, f in enumerate(schema.fields):
            if f.dtype.is_string:
                ml = maxlens[ci]
                byte_cap = round_up_capacity(max(out_cap * ml, 16),
                                             minimum=16)
                data, offsets = _padded_to_flat(
                    jnp.asarray(host_payloads[slot][d]),
                    jnp.asarray(host_payloads[slot + 1][d]),
                    byte_cap)
                cols.append(DeviceColumn(
                    f.dtype, data,
                    jnp.asarray(host_payloads[slot + 2][d]), offsets))
                slot += 3
            else:
                cols.append(DeviceColumn(
                    f.dtype, jnp.asarray(host_payloads[slot][d]),
                    jnp.asarray(host_payloads[slot + 1][d]), None))
                slot += 2
        out.append(ColumnBatch(schema, cols,
                               jnp.asarray(int(counts_h[d]), jnp.int32),
                               out_cap))
    return out
