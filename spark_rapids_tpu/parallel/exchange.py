"""Shuffle and broadcast exchanges (reference: GpuShuffleExchangeExec.scala,
GpuBroadcastExchangeExec.scala; SURVEY.md sections 2.5, 2.7).

Single-host model: an exchange materializes its child's partitions, splits
every batch by target-partition id (device-side compaction for TPU plans,
numpy for CPU fallback), and regroups — the "fallback path (a)" of the
reference.  The device-mesh all-to-all path (ICI analogue) lives in
``parallel.mesh_shuffle`` and is used by the distributed runner.
"""

from __future__ import annotations

from typing import Iterator, List

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import ColumnBatch, HostBatch, HostColumn
from spark_rapids_tpu.kernels.layout import gather_rows
from spark_rapids_tpu.parallel.partitioning import (
    HashPartitioning, Partitioning, RangePartitioning,
    RoundRobinPartitioning, SinglePartitioning,
)
from spark_rapids_tpu.plan.physical import (
    CpuExec, ExecContext, PhysicalOp, TpuExec,
)
from spark_rapids_tpu.obs import events as obs_events
from spark_rapids_tpu.utils.compile_registry import instrumented_jit

def _range_sample_limit(ctx) -> int:
    from spark_rapids_tpu.config import CPU_RANGE_PARTITIONING_SAMPLE
    return max(1, CPU_RANGE_PARTITIONING_SAMPLE.get(ctx.conf))


def _collapse_local_conf(ctx) -> bool:
    """Single-process execution doesn't need a physical split: every
    downstream consumer sees all rows either way, and partitioning only
    constrains *placement* (trivially satisfied by one partition).
    Collapsing removes the per-batch count sync + one gather per target
    partition — pure overhead on one device.  The mesh (multi-device)
    path does its own all-to-all instead."""
    from spark_rapids_tpu.config import EXCHANGE_COLLAPSE_LOCAL
    return EXCHANGE_COLLAPSE_LOCAL.get(ctx.conf)


class CpuShuffleExchangeExec(CpuExec):
    def __init__(self, partitioning: Partitioning, child: PhysicalOp):
        super().__init__([child], child.output_schema)
        self.partitioning = partitioning

    def describe(self):
        p = self.partitioning
        return f"CpuShuffleExchange({type(p).__name__}, {p.num_partitions})"

    def num_partitions(self, ctx):
        if _collapse_local_conf(ctx):
            return 1
        return self.partitioning.num_partitions

    def partitions(self, ctx):
        n = self.partitioning.num_partitions
        in_parts = self.children[0].partitions(ctx)
        if _collapse_local_conf(ctx):
            # mirror the TPU exchange's local collapse so CPU and TPU
            # plans keep identical deterministic row orders (the compare
            # harness and mixed plans rely on it)
            def gen():
                for part in in_parts:
                    for hb in part:
                        yield hb

            return [gen()]
        all_batches: List[List[HostBatch]] = [list(p) for p in in_parts]
        if isinstance(self.partitioning, RangePartitioning):
            self.partitioning.prepare(_sample_host_keys(
                all_batches, self.partitioning.key_ordinals,
                _range_sample_limit(ctx)))
        out: List[List[HostBatch]] = [[] for _ in range(n)]
        for pi, batches in enumerate(all_batches):
            for hb in batches:
                ids = self.partitioning.host_partition_ids(hb, pi)
                # ONE stable argsort + split instead of N boolean-mask
                # scans: the stable sort keeps each target's rows in
                # original order (the deterministic order the compare
                # harness and mixed CPU/TPU plans rely on)
                order = np.argsort(ids, kind="stable")
                counts = np.bincount(ids, minlength=n)
                cuts = np.cumsum(counts)[:-1]
                split_cols = [(np.split(c.values[order], cuts),
                               np.split(c.validity[order], cuts))
                              for c in hb.columns]
                for p in range(n):
                    if counts[p] == 0:
                        continue
                    cols = [HostColumn(c.dtype, vs[p], vl[p])
                            for c, (vs, vl) in zip(hb.columns, split_cols)]
                    out[p].append(HostBatch(hb.schema, cols))
        return [iter(p) for p in out]


def _sample_host_keys(all_batches: List[List[HostBatch]],
                      key_ordinals: List[int],
                      limit: int) -> List[tuple]:
    rows: List[tuple] = []
    for batches in all_batches:
        for hb in batches:
            cols = [hb.columns[i].to_list() for i in key_ordinals]
            for r in range(hb.num_rows):
                rows.append(tuple(c[r] for c in cols))
                if len(rows) >= limit:
                    return rows
    return rows


class TpuShuffleExchangeExec(TpuExec):
    """Device-side partition split: pid per row (murmur3 pmod / range
    compare / round-robin), then one compaction per target partition —
    the single-host analogue of GPU partition + contiguousSplit
    (GpuPartitioning.scala:44-117)."""

    def __init__(self, partitioning: Partitioning, child: PhysicalOp):
        super().__init__([child], child.output_schema)
        self.partitioning = partitioning
        self._input_fns = []
        self._fused_map = None
        self._sort_by_pid = instrumented_jit(
            self._sort_by_pid_impl, label="TpuShuffleExchange:split",
            static_argnames=("n", "keep_encoded"))

    def absorb_input(self, fns):
        """Fuse upstream map-like stages into the partition-split program
        (one dispatch per batch for filter+project+hash+sort-by-pid)."""
        self._input_fns = list(fns)
        self._fused_map = None

    def _mesh_active(self, ctx) -> bool:
        return getattr(ctx, "mesh", None) is not None

    def _collapse_local(self, ctx) -> bool:
        return not self._mesh_active(ctx) and _collapse_local_conf(ctx)

    def describe(self):
        p = self.partitioning
        return f"TpuShuffleExchange({type(p).__name__}, {p.num_partitions})"

    def num_partitions(self, ctx):
        if self._mesh_active(ctx):
            from spark_rapids_tpu.parallel.mesh_shuffle import DATA_AXIS
            return ctx.mesh.shape[DATA_AXIS]
        if self._collapse_local(ctx):
            return 1
        return self.partitioning.num_partitions

    def _ensure_fused_map(self):
        """Compile any absorbed map stages (filter/project) into ONE
        program per batch; shared by the collapse-local and the adaptive
        bypass paths, which both skip the split but must still apply the
        absorbed stages."""
        if self._input_fns and self._fused_map is None:
            fns = list(self._input_fns)

            def composed(b):
                for f in fns:
                    b = f(b)
                return b

            self._fused_map = instrumented_jit(
                composed, label="TpuShuffleExchange:map")

    def has_materialized_split(self, ctx) -> bool:
        """True when this exchange's split already ran for ``ctx`` on the
        LIVE device generation, i.e. ``partitions`` would re-read the
        cached spillable pieces instead of re-splitting."""
        from spark_rapids_tpu.runtime.device import DeviceRuntime
        cached = getattr(self, "_split_cache", None)
        return cached is not None and cached[0]() is ctx and \
            cached[2] == DeviceRuntime.generation()

    def bypass_partitions(self, ctx):
        """Adaptive broadcast-switch probe elision (plan/adaptive): the
        consumer joins every partition against a broadcast build, so
        co-partitioning buys nothing — hand back the child's partitions
        with any absorbed map stages applied (one fused program per
        batch) and NO split: no pid programs, no piece gathers, no split
        host sync, no catalog registrations.  The exchange fault site
        still fires so injection specs aimed at exchanges cover elided
        ones, and the mesh path is never bypassed (its all_to_all IS the
        data movement)."""
        from spark_rapids_tpu.fault import inject
        inject.maybe_fire("exchange")
        if self._mesh_active(ctx):
            return self._mesh_partitions(ctx)
        ctx.metric(self.op_id, "shuffleElided").add(1)
        obs_events.emit_instant("exchange", "elided", self.op_id)
        self._ensure_fused_map()

        def gen(part):
            for db in part:
                yield self._fused_map(db) if self._fused_map else db

        return [gen(p) for p in self.children[0].partitions(ctx)]

    def pipeline_inline(self, ctx, build):
        if self._mesh_active(ctx):
            return self._mesh_spmd_inline(ctx, build)
        if not self._collapse_local(ctx):
            return None
        cf = build(self.children[0])
        fns = list(self._input_fns)

        def f(args):
            bs = cf(args)
            for fn in fns:
                bs = [fn(b) for b in bs]
            return bs

        return f

    def _mesh_spmd_inline(self, ctx, build):
        """Whole-stage SPMD fusion (mesh.spmd.enabled): instead of
        becoming a stage source that host-drives mesh_exchange_batches —
        one sync + restage per exchange — the exchange lowers INTO the
        surrounding stage program as an in-program all_to_all
        (mesh_shuffle.exchange_batch_collective).  Producer segment,
        shuffle and consumer segment then dispatch as ONE shard_map
        program with zero host syncs at the boundary.

        Returns None (exchange stays a host-driven stage source) when no
        mesh build scope is active, or when the partitioning matches no
        PartitionSpec rule (partitioning.MESH_PARTITION_RULES: single
        would leave each shard a private "partition 0", breaking global
        aggregates/limits) — unless mesh.spmd.autoFallback is off, which
        turns that silent fallback into an error for debugging fusion
        coverage.  Range partitioning fuses: its bounds are sampled,
        pooled (all_gather) and picked INSIDE the program
        (RangePartitioning.device_bounds_in_program), replacing the eager
        host prepare() pre-pass."""
        from spark_rapids_tpu.plan.pipeline import (
            concat_static, mesh_build_scope,
        )
        scope = mesh_build_scope()
        if scope is None:
            return None
        from spark_rapids_tpu.parallel.partitioning import (
            match_partition_rules,
        )
        if match_partition_rules(
                type(self.partitioning).__name__) is None:
            from spark_rapids_tpu.config import MESH_SPMD_AUTO_FALLBACK
            if not MESH_SPMD_AUTO_FALLBACK.get(ctx.conf):
                raise RuntimeError(
                    f"{self.describe()}: partitioning is not mesh-SPMD "
                    "compatible and spark.rapids.sql.tpu.mesh.spmd."
                    "autoFallback is disabled")
            obs_events.emit_instant(
                "exchange", "mesh_fallback", self.op_id,
                partitioning=type(self.partitioning).__name__)
            return None
        from spark_rapids_tpu.parallel.mesh_shuffle import (
            DATA_AXIS, exchange_batch_collective,
        )
        cf = build(self.children[0])
        fns = list(self._input_fns)
        n = ctx.mesh.shape[DATA_AXIS]
        part = _mesh_partitioning(self.partitioning, n)
        sample_per_shard = _range_sample_limit(ctx) if \
            isinstance(part, RangePartitioning) else 0
        scope.exchanges.append(self)

        def f(args):
            bs = cf(args)
            for fn in fns:
                bs = [fn(b) for b in bs]
            # one local concat per shard keeps pid assignment identical
            # to the host-driven path's merged batch (concat compacts
            # live rows at the front in input order, so row position —
            # all round-robin sees — matches _concat_all's)
            b = concat_static(bs, self.output_schema) if len(bs) != 1 \
                else bs[0]
            d = jax.lax.axis_index(DATA_AXIS)
            if isinstance(part, RangePartitioning):
                bounds = part.device_bounds_in_program(
                    b, DATA_AXIS, max(1, sample_per_shard // n))
                pid = part.device_partition_ids_from_words(b, bounds)
            else:
                pid = part.device_partition_ids(b, d)
            return [exchange_batch_collective(
                b, jnp.asarray(pid, jnp.int32), n)]

        return f

    def _sort_by_pid_impl(self, batch: ColumnBatch, part_index, n: int,
                          bound_words=None, keep_encoded: bool = False):
        """One pass: rows reordered so each target partition's rows are
        contiguous (the GPU `Table.partition` + contiguousSplit shape,
        GpuPartitioning.scala:44-117).  Returns (sorted batch, per-target
        row counts, per-target byte totals for each string column).

        ``bound_words`` (range partitioning only): pre-encoded range-bound
        word arrays passed as TRACED arguments, so range splits ride the
        same jitted program as hash/round-robin instead of the eager
        per-bound path.

        ``keep_encoded`` (dict-aware shuffle): the pid-sort permutes
        dictionary codes instead of materializing string bytes.  Byte
        totals always report MATERIALIZED per-target element totals for
        encoded columns (per-row entry lengths gathered through the
        codes) — they size the materialize-path byte caps and the
        encoded-path ``mat_byte_cap`` alike."""
        for f in self._input_fns:
            batch = f(batch)
        cap = batch.capacity
        if bound_words is not None:
            ids = self.partitioning.device_partition_ids_from_words(
                batch, bound_words)
        else:
            ids = self.partitioning.device_partition_ids(batch, part_index)
        live = jnp.arange(cap, dtype=jnp.int32) < batch.num_rows
        ids = jnp.where(live, ids, n)
        order = jnp.argsort(ids, stable=True).astype(jnp.int32)
        sorted_batch = gather_rows(batch, order, batch.num_rows,
                                   keep_encoded=keep_encoded)
        counts = jnp.zeros(n + 1, jnp.int32).at[ids].add(1)[:n]
        byte_totals = []
        for c in batch.columns:
            # ALL varlen columns (strings AND arrays), in column order —
            # the split's out_byte_caps align positionally with
            # gather_rows' varlen columns; totals are in element units
            # (bytes for strings, element count for arrays)
            if c.is_varlen:
                if c.codes is not None:
                    nd = int(c.offsets.shape[0]) - 1
                    ent_lens = (c.offsets[1:] - c.offsets[:-1]) \
                        .astype(jnp.int64)
                    codes_c = jnp.clip(c.codes, 0, max(nd - 1, 0))
                    lens = jnp.where(c.validity, ent_lens[codes_c], 0)
                else:
                    lens = (c.offsets[1:] - c.offsets[:-1]).astype(jnp.int64)
                byte_totals.append(jax.ops.segment_sum(
                    lens, ids, num_segments=n + 1)[:n])
        return sorted_batch, counts, byte_totals

    def _mesh_partitions(self, ctx):
        """ICI collective path: rows move between mesh devices with ONE
        lax.all_to_all per column payload (the reference's UCX transport
        role, RapidsShuffleTransport.scala:378-492, as a single compiled
        SPMD program)."""
        from spark_rapids_tpu.ops.tpu_exec import _concat_all
        from spark_rapids_tpu.parallel.mesh_shuffle import (
            DATA_AXIS, mesh_exchange_batches,
        )
        mesh = ctx.mesh
        n = mesh.shape[DATA_AXIS]
        batches: List[ColumnBatch] = []
        for part in self.children[0].partitions(ctx):
            batches.extend(part)
        if self._input_fns:
            if self._fused_map is None:
                fns = list(self._input_fns)

                def composed(b):
                    for f in fns:
                        b = f(b)
                    return b

                self._fused_map = instrumented_jit(
                    composed, label="TpuShuffleExchange:map")
            batches = [self._fused_map(b) for b in batches]
        if not batches:
            return [iter([]) for _ in range(n)]
        # re-key the partitioning onto the mesh: one output partition per
        # device (preserves range ordering / hash co-location)
        part = _mesh_partitioning(self.partitioning, n)
        if isinstance(part, RangePartitioning):
            part.prepare(_sample_device_keys([batches], part.key_ordinals,
                                             _range_sample_limit(ctx)))
        per_dev: List[List[ColumnBatch]] = [[] for _ in range(n)]
        for i, b in enumerate(batches):
            per_dev[i % n].append(b)
        local_batches, pids_list = [], []
        for d in range(n):
            merged = _concat_all(per_dev[d], self.output_schema)
            if merged is None:
                local_batches.append(None)
                pids_list.append(None)
                continue
            pid = part.device_partition_ids(merged, d)
            local_batches.append(merged)
            pids_list.append(jnp.asarray(pid, jnp.int32))
        import time as _time

        from spark_rapids_tpu.utils.tracing import metrics_detail
        stats: dict = {}
        t0 = _time.monotonic_ns()
        out = mesh_exchange_batches(mesh, local_batches, pids_list,
                                    self.output_schema, stats=stats)
        # No unconditional host sync here: blocking on the all_to_all kills
        # its async overlap with downstream dispatch (the whole point of
        # the collective path).  Default shuffleWallNs is therefore a
        # dispatch-wall LOWER BOUND; the accurate-sync path rides the
        # metrics-detail conf for measurement runs.
        if out and metrics_detail(ctx.conf):
            jax.block_until_ready(out)
            ctx.metric(self.op_id, "shuffleWallSyncs").add(1)
        wall_ns = _time.monotonic_ns() - t0
        ctx.metric(self.op_id, "meshExchanges").add(1)
        ctx.metric(self.op_id, "meshDevices").add(n)
        # shuffle throughput accounting (RapidsCachingReader.scala:125-133
        # role): bytes moved + wall time -> GB/s is derivable downstream
        ctx.metric(self.op_id, "shuffleBytes").add(
            stats.get("payload_bytes", 0))
        ctx.metric(self.op_id, "shuffleWireBytes").add(
            stats.get("wire_bytes", 0))
        ctx.metric(self.op_id, "shuffleWallNs").add(wall_ns)
        obs_events.emit_span(
            "exchange", "mesh", self.op_id, t0, t0 + wall_ns,
            bytes=stats.get("payload_bytes", 0), devices=n,
            bytes_per_device=stats.get("bytes_per_device"))
        if stats.get("encoded_materialized"):
            # the encoded-corridor gap at mesh boundaries, measured:
            # dict-encoded columns give up their codes here (the
            # collective wire format is materialized elements)
            ctx.metric(self.op_id, "meshEncodedMaterializedBytes").add(
                stats.get("materialized_bytes", 0))
            obs_events.emit_instant(
                "exchange", "mesh_materialize", self.op_id,
                batches=stats.get("encoded_materialized", 0),
                bytes=stats.get("materialized_bytes", 0))
        return [iter([b]) for b in out] if out else \
            [iter([]) for _ in range(n)]

    def partitions(self, ctx):
        from spark_rapids_tpu.fault import inject
        inject.maybe_fire("exchange")
        if self._mesh_active(ctx):
            return self._mesh_partitions(ctx)
        n = self.partitioning.num_partitions
        in_parts = self.children[0].partitions(ctx)
        if self._collapse_local(ctx):
            # one logical partition holding every input batch (with any
            # absorbed map stages applied as one fused program per batch);
            # no pid computation, no split, no sampling, no host syncs
            self._ensure_fused_map()

            def gen():
                for part in in_parts:
                    for db in part:
                        yield self._fused_map(db) if self._fused_map \
                            else db

            return [gen()]
        all_batches: List[List[ColumnBatch]] = [list(p) for p in in_parts]
        if isinstance(self.partitioning, RangePartitioning):
            self.partitioning.prepare(
                _sample_device_keys(all_batches,
                                    self.partitioning.key_ordinals,
                                    _range_sample_limit(ctx)))
        if isinstance(self.partitioning, SinglePartitioning):
            flat = [b for part in all_batches for b in part]
            return [iter(flat)]
        from spark_rapids_tpu.runtime.device import DeviceRuntime
        # Shuffle outputs accumulate across ALL partitions before any
        # consumer runs — exactly the working set the reference keeps in the
        # spillable shuffle catalog (RapidsShuffleInternalManager.scala:
        # 91-154, ShuffleBufferCatalog).  Register every piece so the budget
        # can push early partitions to host while later ones materialize.
        #
        # The split is memoized per query context: a task RETRY re-reads
        # the already-materialized (spillable) pieces instead of re-running
        # the whole upstream subtree — the role persisted shuffle files
        # play for Spark's task retry.  Handles stay open until the query
        # ends (ctx.close_deferred).  The cache holds the ctx via weakref:
        # exec nodes live as long as the session's plan cache, and a strong
        # ref would pin a finished query's whole object graph.
        # Generation-checked (fault.recovery): a device-lost reset bumps
        # the runtime generation, so a partition REPLAY recomputes the
        # split from lineage instead of draining pieces whose device
        # copies died with the old device.
        import weakref
        gen = DeviceRuntime.generation()
        cached = getattr(self, "_split_cache", None)
        if cached is not None and cached[0]() is ctx and cached[2] == gen:
            return [self._drain_cached(p) for p in cached[1]]
        catalog = DeviceRuntime.get(ctx.conf).catalog
        from spark_rapids_tpu.batch import (
            fixed_row_bytes, varlen_byte_scales,
        )
        from spark_rapids_tpu.config import SHUFFLE_SPLIT_V2
        frb = fixed_row_bytes(self.output_schema)
        vscales = varlen_byte_scales(self.output_schema)
        out: List[List] = [[] for _ in range(n)]
        import time as _time
        t0 = _time.monotonic_ns()
        if SHUFFLE_SPLIT_V2.get(ctx.conf):
            self._split_v2(ctx, all_batches, n, catalog, frb, vscales, out)
        else:
            self._split_v1(ctx, all_batches, n, catalog, frb, vscales, out)
        ctx.metric(self.op_id, "shufflePieces").add(
            sum(len(p) for p in out))
        # downstream AQE coalescing reads these instead of unspilling
        # batches just to count rows (GpuCustomShuffleReaderExec's use of
        # map-status sizes)
        self._last_part_rows = [sum(h.piece_rows for h in p) for p in out]
        self._last_part_bytes = [sum(h.piece_bytes for h in p) for p in out]
        # write-side shuffle metrics (single-host split path).  Wall time
        # covers pid-sort + the count sync(s); the final piece gathers may
        # still be in flight (async dispatch), so this is a lower bound on
        # split cost, not an upper
        ctx.metric(self.op_id, "shuffleBytes").add(
            sum(self._last_part_bytes))
        ctx.metric(self.op_id, "shuffleRows").add(sum(self._last_part_rows))
        split_t1 = _time.monotonic_ns()
        ctx.metric(self.op_id, "shuffleWallNs").add(split_t1 - t0)
        obs_events.emit_span(
            "exchange", "split", self.op_id, t0, split_t1,
            bytes=sum(self._last_part_bytes),
            rows=sum(self._last_part_rows),
            pieces=sum(len(p) for p in out), partitions=n)
        # planner-error accounting: the static size estimate the planner
        # used for this exchange's input (stashed by overrides) vs. the
        # actual materialized bytes just recorded — pure host arithmetic
        # on numbers the split's own sync fetched, no extra round trip
        est = getattr(self, "_aqe_est_bytes", None)
        if est is not None:
            actual = sum(self._last_part_bytes)
            pct = abs(est - actual) * 100.0 / max(actual, 1)
            ctx.metric(self.op_id, "aqeEstimateErrorPct").add(pct)
        self._split_cache = (weakref.ref(ctx), out, gen)
        return [self._drain_cached(p) for p in out]

    def _split_v2(self, ctx, all_batches, n, catalog, frb, vscales, out):
        """One-sync coalescing split: (1) dispatch the fused pid-sort
        program for EVERY input batch (nothing blocks, so B programs
        overlap on device); (2) fetch every batch's per-target counts and
        varlen byte totals in ONE bulk device_get (the host_sizes
        pattern); (3) assemble each target partition from ALL sorted
        batches with one k-way segment-gather dispatch — <=N pieces and
        ~B+N dispatches where the v1 path paid B syncs and B*(1+N)
        dispatches.  Spill-budget-aware: a partition whose coalesced size
        exceeds splitCoalesceMaxBytes falls back to per-batch pieces so
        the catalog can still spill early pieces independently."""
        from spark_rapids_tpu.batch import round_up_capacity
        from spark_rapids_tpu.config import (
            SHUFFLE_COALESCE_MAX_BYTES, SHUFFLE_DICT_AWARE,
        )
        from spark_rapids_tpu.kernels.layout import gather_segments_kway_run
        from spark_rapids_tpu.mem.catalog import PRIORITY_SHUFFLE_OUTPUT
        bound_words = None
        if isinstance(self.partitioning, RangePartitioning):
            # one batched H2D + one encode for ALL N-1 bounds; the word
            # arrays ride the jitted pid-sort as traced arguments
            bound_words = self.partitioning.encode_bounds_device()
        # dict-aware split (docs/shuffle.md): when any input column is
        # dictionary-encoded, the pid-sort permutes 4-byte codes and the
        # piece gather merges dictionaries instead of materializing string
        # bytes — decided BEFORE dispatch because it is a static arg of
        # the sort program (one cache key per mode, stable per query)
        keep_enc = SHUFFLE_DICT_AWARE.get(ctx.conf) and any(
            c.codes is not None
            for batches in all_batches for db in batches
            for c in db.columns)
        sorted_all = []
        for pi, batches in enumerate(all_batches):
            for db in batches:
                sorted_all.append(self._sort_by_pid(
                    db, pi, n, bound_words, keep_encoded=keep_enc))
                ctx.metric(self.op_id, "shuffleSplitDispatches").add(1)
        if not sorted_all:
            return
        host = jax.device_get([(c, bt) for _, c, bt in sorted_all])
        ctx.metric(self.op_id, "shuffleSyncs").add(1)
        counts_h = [np.asarray(c, dtype=np.int64) for c, _ in host]
        bytes_h = [[np.asarray(b, dtype=np.int64) for b in bt]
                   for _, bt in host]
        starts_h = [np.concatenate(([0], np.cumsum(c)))[:n]
                    for c in counts_h]
        cap_bytes = SHUFFLE_COALESCE_MAX_BYTES.get(ctx.conf)
        varlen_idx = [i for i, f in enumerate(self.output_schema.fields)
                      if f.dtype.is_string or f.dtype.is_array]

        def _col_encoded(ci, group):
            # encoded output requires EVERY contributing part encoded
            # (gather_segments_kway materializes mixed columns)
            return keep_enc and all(
                sorted_all[b][0].columns[varlen_idx[ci]].codes is not None
                for b in group)

        def _hbm_bytes(group, p, rows):
            # actual piece footprint: codes + dictionary buffers for
            # encoded columns, materialized elements otherwise — encoded
            # columns shrink the coalescing budget's view of a piece, so
            # more batches coalesce under the same cap
            t = rows * frb
            for ci, sc in enumerate(vscales):
                if _col_encoded(ci, group):
                    t += 4 * rows + sum(
                        int(sorted_all[b][0]
                            .columns[varlen_idx[ci]].data.shape[0])
                        for b in group)
                else:
                    t += sum(int(bytes_h[b][ci][p]) for b in group) * sc
            return t

        saved_total = 0
        for p in range(n):
            segs = [b for b in range(len(sorted_all))
                    if counts_h[b][p] > 0]
            if not segs:
                continue
            total_rows = sum(int(counts_h[b][p]) for b in segs)
            total_bytes = _hbm_bytes(segs, p, total_rows)
            if cap_bytes > 0 and total_bytes > cap_bytes and len(segs) > 1:
                groups = [[b] for b in segs]
            else:
                groups = [segs]
            for group in groups:
                rows = sum(int(counts_h[b][p]) for b in group)
                elems = [sum(int(bytes_h[b][ci][p]) for b in group)
                         for ci in range(len(vscales))]
                pcap = round_up_capacity(rows)
                # encoded columns: the slot is the OUTPUT mat_byte_cap —
                # same bucket of the same materialized total the plain
                # path would allocate, so downstream sizing is identical
                bcaps = [round_up_capacity(max(e, 16), minimum=16)
                         for e in elems]
                piece = gather_segments_kway_run(
                    [sorted_all[b][0] for b in group],
                    [int(starts_h[b][p]) for b in group],
                    [int(counts_h[b][p]) for b in group],
                    pcap, bcaps or None, keep_encoded=keep_enc)
                ctx.metric(self.op_id, "shuffleSplitDispatches").add(1)
                for ci, sc in enumerate(vscales):
                    if _col_encoded(ci, group):
                        wire = 4 * rows + sum(
                            int(sorted_all[b][0]
                                .columns[varlen_idx[ci]].data.shape[0])
                            for b in group)
                        saved_total += max(0, elems[ci] * sc - wire)
                h = catalog.register(piece, PRIORITY_SHUFFLE_OUTPUT)
                h.piece_rows = rows  # host-known: no sync for AQE sizing
                # piece_bytes stays the MATERIALIZED size either way, so
                # AQE coalescing decisions are bit-identical to encoded-off
                h.piece_bytes = rows * frb + sum(
                    e * sc for e, sc in zip(elems, vscales))
                ctx.defer_close(h)
                out[p].append(h)
        if keep_enc:
            ctx.metric(self.op_id, "shuffleEncodedBytesSaved").add(
                saved_total)

    def _split_v1(self, ctx, all_batches, n, catalog, frb, vscales, out):
        """Legacy per-batch split (one count sync per batch, one gather
        dispatch per (batch, target) pair) — kept behind
        splitV2.enabled=false as the bit-parity oracle for the coalescing
        engine."""
        from spark_rapids_tpu.batch import round_up_capacity
        from spark_rapids_tpu.mem.catalog import PRIORITY_SHUFFLE_OUTPUT
        for pi, batches in enumerate(all_batches):
            for db in batches:
                sorted_batch, counts, byte_totals = \
                    self._sort_by_pid(db, pi, n) \
                    if not isinstance(self.partitioning,
                                      RangePartitioning) \
                    else self._sort_by_pid_impl(db, pi, n)
                ctx.metric(self.op_id, "shuffleSplitDispatches").add(1)
                counts_h = np.asarray(jax.device_get(counts))
                bytes_h = [np.asarray(jax.device_get(b))
                           for b in byte_totals]
                ctx.metric(self.op_id, "shuffleSyncs").add(1)
                offset = 0
                for p in range(n):
                    cnt = int(counts_h[p])
                    if cnt == 0:
                        continue
                    pcap = round_up_capacity(cnt)
                    idx = offset + jnp.arange(pcap, dtype=jnp.int32)
                    bcaps = [round_up_capacity(max(int(bh[p]), 16),
                                               minimum=16)
                             for bh in bytes_h]
                    piece = gather_rows(sorted_batch, idx,
                                        jnp.asarray(cnt, jnp.int32),
                                        out_capacity=pcap,
                                        out_byte_caps=bcaps or None)
                    ctx.metric(self.op_id, "shuffleSplitDispatches").add(1)
                    h = catalog.register(piece, PRIORITY_SHUFFLE_OUTPUT)
                    h.piece_rows = cnt  # host-known: no sync for AQE sizing
                    h.piece_bytes = cnt * frb + \
                        sum(int(bh[p]) * sc
                            for bh, sc in zip(bytes_h, vscales))
                    ctx.defer_close(h)
                    out[p].append(h)
                    offset += cnt

    def _drain_cached(self, handles):
        # lazy, with ONE piece of read-ahead: when piece i is yielded,
        # piece i+1's unspill (an async H2D enqueue) is already in flight,
        # so the consumer's compute overlaps the next transfer.  Handles
        # stay registered (spillable + retry-reusable) until the query
        # closes them.  The overlap loop itself lives on the catalog
        # (prefetch) — shared with the cached-scan drive path.
        from spark_rapids_tpu.plan.physical import prefetch_spillables
        obs_events.emit_instant("exchange", "drain", self.op_id,
                                pieces=len(handles))
        return prefetch_spillables(handles)


def _mesh_partitioning(p: Partitioning, n: int) -> Partitioning:
    """Clone a partitioning with num_partitions = mesh device count, so one
    output partition maps to one device (range order and hash co-location
    are preserved by re-keying, not by folding pids mod n)."""
    if isinstance(p, HashPartitioning):
        return HashPartitioning(p.keys, n)
    if isinstance(p, RoundRobinPartitioning):
        return RoundRobinPartitioning(n)
    if isinstance(p, RangePartitioning):
        return RangePartitioning(p.orders, p.key_ordinals, n)
    return p  # SinglePartitioning


def _sample_device_keys(all_batches: List[List[ColumnBatch]],
                        key_ordinals: List[int],
                        limit: int) -> List[tuple]:
    """Sample <= ``limit`` key rows for range-bound computation.

    The keys are gathered down to the sample size ON DEVICE before any
    transfer: one bulk metadata get (num_rows + varlen offsets — bytes
    proportional to row count, not payload), then a right-sized head
    gather per contributing batch, then ONE bulk D2H for all gathered
    sub-batches.  The old path device_to_host'd every FULL batch (values
    included) just to read the first rows."""
    from spark_rapids_tpu.batch import device_to_host_many, round_up_capacity
    from spark_rapids_tpu.kernels.layout import dict_decode_column
    rows: List[tuple] = []
    # dict-encoded key columns (encoded corridor) materialize up front:
    # the offsets metadata below must describe ROW offsets, and bounds
    # need string content regardless
    subs = [ColumnBatch(
                T.Schema([db.schema.fields[i] for i in key_ordinals]),
                [dict_decode_column(c) if c.codes is not None else c
                 for c in (db.columns[i] for i in key_ordinals)],
                db.num_rows, db.capacity)
            for batches in all_batches for db in batches]
    if not subs:
        return rows
    meta = jax.device_get([
        (b.num_rows, [c.offsets for c in b.columns if c.is_varlen])
        for b in subs])
    gathered = []
    remaining = limit
    for sub, (nr, off_arrays) in zip(subs, meta):
        if remaining <= 0:
            break
        take = min(int(nr), remaining)
        if take <= 0:
            continue
        pcap = round_up_capacity(take)
        bcaps = [round_up_capacity(max(int(offs[take]), 16), minimum=16)
                 for offs in off_arrays]
        gathered.append(gather_rows(
            sub, jnp.arange(pcap, dtype=jnp.int32),
            jnp.asarray(take, jnp.int32),
            out_capacity=pcap, out_byte_caps=bcaps or None))
        remaining -= take
    for hb in device_to_host_many(gathered):
        cols = [c.to_list() for c in hb.columns]
        for r in range(hb.num_rows):
            rows.append(tuple(c[r] for c in cols))
            if len(rows) >= limit:
                return rows
    return rows


class CpuBroadcastExchangeExec(CpuExec):
    """Materialize the whole child once; every consumer partition sees the
    same single host batch (driver-side broadcast analogue,
    GpuBroadcastExchangeExec.scala:53-135)."""

    def __init__(self, child: PhysicalOp):
        super().__init__([child], child.output_schema)
        self._cached = None

    def num_partitions(self, ctx):
        return 1

    def materialize(self, ctx) -> HostBatch:
        if self._cached is None:
            batches = []
            for p in self.children[0].partitions(ctx):
                batches.extend(p)
            if batches:
                self._cached = HostBatch.concat(batches)
            else:
                from spark_rapids_tpu.plan.physical import _empty_host_col
                self._cached = HostBatch(self.output_schema, [
                    _empty_host_col(f) for f in self.output_schema.fields
                ])
        return self._cached

    def partitions(self, ctx):
        return [iter([self.materialize(ctx)])]
