"""Whole-stage mesh-SPMD execution: one shard_map program per stage.

The host-driven mesh shuffle (parallel.mesh_shuffle, used when
``spark.rapids.shuffle.ici.enabled`` is on) is already collective on the
wire, but the PLAN around it is still host-driven: the producer stage
dispatches, the driver syncs live sizes, restages per-device batches into
mesh globals, dispatches the exchange program, unshards, and only then
dispatches the consumer stage — one host sync plus two extra dispatch
boundaries per exchange.

With ``spark.rapids.sql.tpu.mesh.spmd.enabled`` this module compiles the
contiguous plan segments on EITHER side of a shuffle into ONE shard_map
program: the producer segment runs per shard, the exchange is an
in-program ``lax.all_to_all`` (mesh_shuffle.exchange_batch_collective —
the same varlen re-bucketing collective the host-driven path dispatches,
so the two routes are bit-identical by construction), and the consumer
segment keeps going on the received rows without the program ever
returning to the host.  Zero host syncs at the boundary: wire capacities
come from the inputs' STATIC capacity buckets, trading bucket padding on
the wire for a sync-free dispatch (docs/mesh.md's fusion table).

How a stage gets here: plan/pipeline's builder runs under a
MeshBuildScope when ``ExecContext.mesh_spmd_active()``; a mesh-compatible
``TpuShuffleExchangeExec`` then inlines as the collective instead of
becoming a stage source and records itself on the scope, and
``_run_stage`` diverts the stage to :func:`run_mesh_stage`.  Exchanges
whose partitioning cannot lower in-program (partitioning.mesh_compatible:
range, single) stay host-driven sources — per-stage auto-fallback, under
``spark.rapids.sql.tpu.mesh.spmd.autoFallback``.

Input lowering (the PartitionSpec pytree threaded through the program):

* distributed sources — batch k of a source goes to device ``k % n``
  (exactly the host-driven path's ``per_dev[k % n]`` interleave, so pid
  assignment matches bit-for-bit), stacked per round-robin *slot* into
  ``[n, ...]`` globals via ``jax.make_array_from_single_device_arrays``
  after a per-device jitted pack to the slot's common static capacities;
  every leaf enters the program with spec ``P("data", None, ...)``.
* replicated sources (broadcast-join build sides) — each leaf is
  ``device_put`` with ``NamedSharding(mesh, P())``: one identical copy
  per device, spec all-``None`` — broadcast lowers to replication.

Outputs leave with spec ``P("data")``; each device's addressable shard is
that shard's result batch, squeezed to plain single-device arrays so
downstream programs stay strictly local.  The stacked output globals are
registered ONCE with the spill catalog across the unshard window
(catalog.register_sharded: one handle, per-shard byte accounting).

``mesh:*`` fault-injection fires before the program launches, so a
device-lost replays the full producer+exchange+consumer segment from
lineage (plan/recovery ladder); compiled programs are cached per
(variant, device generation, static input signature) on the stage root.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import OrderedDict
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from spark_rapids_tpu.batch import ColumnBatch, DeviceColumn, \
    round_up_capacity
from spark_rapids_tpu.obs import events as obs_events
from spark_rapids_tpu.parallel.mesh_shuffle import (
    DATA_AXIS, _fit_1d, _unshard,
)
from spark_rapids_tpu.utils.compile_registry import instrumented_jit
from spark_rapids_tpu.utils.tracing import device_dispatch


def _is_varlen(f) -> bool:
    return f.dtype.is_string or getattr(f.dtype, "is_array", False)


def _payload_len(schema) -> int:
    """Flat payload arrays per batch of ``schema``: varlen columns ride as
    (elements, offsets, validity), fixed as (data, validity), plus one
    num_rows array."""
    return sum(3 if _is_varlen(f) else 2 for f in schema.fields) + 1


def _col_elem_cap(c) -> int:
    # dictionary-encoded columns materialize inside the pack's
    # ensure_row_layout guard: size the slot for the decoded bytes
    if c.codes is not None:
        return max(int(c.mat_byte_cap), 16)
    return int(c.data.shape[0])


def _pad_batch(schema, cap: int, ecaps: Tuple[int, ...]) -> ColumnBatch:
    """Zero-row batch at the slot's static capacities — the filler for
    mesh devices a source has no batch for (K not divisible by n)."""
    cols = []
    for ci, f in enumerate(schema.fields):
        if _is_varlen(f):
            edt = jnp.uint8 if f.dtype.is_string \
                else f.dtype.element.np_dtype
            cols.append(DeviceColumn(
                f.dtype, jnp.zeros(ecaps[ci], edt),
                jnp.zeros(cap, jnp.bool_), jnp.zeros(cap + 1, jnp.int32)))
        else:
            cols.append(DeviceColumn(
                f.dtype, jnp.zeros(cap, f.dtype.np_dtype),
                jnp.zeros(cap, jnp.bool_), None))
    return ColumnBatch(schema, cols, 0, cap)


# Per-device pack programs, keyed by (varlen signature, capacities) — the
# same LRU discipline as mesh_shuffle's exchange-program cache.
_PACK_CACHE_MAX = 64
_pack_cache: "OrderedDict" = OrderedDict()


def _pack_fn(schema, cap: int, ecaps: Tuple[int, ...]):
    """Jitted per-device pack of one ColumnBatch to the slot's common
    static capacities, each buffer gaining a leading shard axis of 1 —
    the per-shard half of a ``[n, ...]`` mesh global."""
    sig_key = tuple((f.dtype, _is_varlen(f)) for f in schema.fields)
    key = (sig_key, cap, ecaps)
    fn = _pack_cache.get(key)
    if fn is not None:
        _pack_cache.move_to_end(key)
        return fn

    def pack(b):
        from spark_rapids_tpu.kernels.layout import ensure_row_layout
        b = ensure_row_layout(b)
        out = []
        for ci, f in enumerate(b.schema.fields):
            c = b.columns[ci]
            if c.offsets is not None:
                offs = c.offsets
                if int(offs.shape[0]) > cap + 1:
                    offs = offs[:cap + 1]
                elif int(offs.shape[0]) < cap + 1:
                    tail = jnp.zeros((cap + 1 - int(offs.shape[0]),),
                                     offs.dtype) + offs[-1]
                    offs = jnp.concatenate([offs, tail])
                out += [_fit_1d(c.data, ecaps[ci])[None],
                        offs.astype(jnp.int32)[None],
                        _fit_1d(c.validity, cap)[None]]
            else:
                out += [_fit_1d(c.data, cap)[None],
                        _fit_1d(c.validity, cap)[None]]
        out.append(jnp.asarray(b.num_rows, jnp.int32).reshape(1))
        return out

    fn = instrumented_jit(pack, label="meshSpmd:pack")
    _pack_cache[key] = fn
    while len(_pack_cache) > _PACK_CACHE_MAX:
        _pack_cache.popitem(last=False)
    return fn


def _batch_from_payloads(schema, pls, cap: int,
                         squeeze: bool) -> ColumnBatch:
    """Rebuild a ColumnBatch from its flat payload list (``squeeze`` drops
    the leading shard axis — the in-program view of a slot's global)."""
    cols = []
    ai = 0
    for f in schema.fields:
        if _is_varlen(f):
            data, offs, valid = pls[ai], pls[ai + 1], pls[ai + 2]
            ai += 3
            if squeeze:
                data, offs, valid = data[0], offs[0], valid[0]
            cols.append(DeviceColumn(f.dtype, data, valid, offs))
        else:
            data, valid = pls[ai], pls[ai + 1]
            ai += 2
            if squeeze:
                data, valid = data[0], valid[0]
            cols.append(DeviceColumn(f.dtype, data, valid, None))
    nr = pls[ai]
    if squeeze:
        nr = nr[0]
    return ColumnBatch(schema, cols, nr, cap)


def _out_capacity(schema, pl) -> int:
    """Recover a flat output payload list's row capacity from its static
    shapes (trailing shard-axis layout: varlen offsets are [n, cap+1],
    fixed data is [n, cap])."""
    if schema.fields and _is_varlen(schema.fields[0]):
        return int(pl[1].shape[-1]) - 1
    return int(pl[0].shape[-1])


def _full_rank_spec(rank: int, sharded: bool):
    if not sharded:
        return P(*([None] * rank))
    return P(DATA_AXIS, *([None] * (rank - 1)))


def _global_batch(schema, pl, cap: int) -> ColumnBatch:
    """The STACKED view of one output: every leaf a mesh-sharded global.
    Used only for catalog accounting (register_sharded) — ``num_rows`` is
    the per-shard [n] count vector, not a scalar."""
    return _batch_from_payloads(schema, pl, cap, squeeze=False)


_OVERFLOW = threading.local()


def note_overflow_flag(flag) -> None:
    """Trace-time channel from a fused join to the mesh program: a join
    lowering with static bucketed output sizing calls this with its
    traced overflow bool; :func:`run_mesh_stage`'s program body collects
    every flag into one extra program output it checks post-dispatch
    (the only host read a fused stage pays, and only when a join fused).
    No-op outside a collecting mesh program body."""
    sink = getattr(_OVERFLOW, "sink", None)
    if sink is not None:
        sink.append(jnp.any(flag))


@contextlib.contextmanager
def _collect_overflow():
    prev = getattr(_OVERFLOW, "sink", None)
    sink = []
    _OVERFLOW.sink = sink
    try:
        yield sink
    finally:
        _OVERFLOW.sink = prev


def run_mesh_stage(root, ctx, variant: str,
                   shrink: bool = True) -> List[ColumnBatch]:
    """Execute a stage whose build fused >=1 exchange as ONE shard_map
    program over ``ctx.mesh`` — plan/pipeline._run_stage's mesh divert."""
    from spark_rapids_tpu.fault import inject
    inject.maybe_fire("mesh")
    from spark_rapids_tpu.plan import pipeline as PL
    from spark_rapids_tpu.runtime.device import DeviceRuntime
    mesh = ctx.mesh
    n = mesh.shape[DATA_AXIS]
    devices = list(mesh.devices.flat)
    sources, fn = PL._stage_build(root, ctx, variant)
    exchanges, replicated, joins = root._mesh_stage_info[variant]
    mats = PL._materialize_sources(sources, ctx, fuse=False)

    sh_rep = NamedSharding(mesh, P())
    flat_globals: List = []
    in_specs: List = []
    src_plans: List = []
    sig_parts: List = []
    for i, src in enumerate(sources):
        batches = mats[i][0]
        schema = src.output_schema
        if i in replicated:
            # broadcast build side: one identical copy per device, spec
            # all-None — replication, not sharding
            tds = []
            for b in batches:
                leaves, td = jax.tree_util.tree_flatten(b)
                for leaf in leaves:
                    g = jax.device_put(leaf, sh_rep)
                    flat_globals.append(g)
                    in_specs.append(_full_rank_spec(g.ndim, sharded=False))
                tds.append(td)
            src_plans.append(("rep", tds))
            sig_parts.append(("rep", tuple(tds)))
        else:
            # batch k -> device k % n, slot k // n: the host-driven mesh
            # path's per_dev interleave, so round-robin pids see every
            # row at the same position on the same device
            nslots = max(1, -(-len(batches) // n))
            slot_caps = []
            for s in range(nslots):
                group = [batches[s * n + d] if s * n + d < len(batches)
                         else None for d in range(n)]
                have = [b for b in group if b is not None]
                cap = round_up_capacity(
                    max((b.capacity for b in have), default=8))
                ecaps = tuple(
                    round_up_capacity(
                        max((_col_elem_cap(b.columns[ci]) for b in have),
                            default=16), minimum=16)
                    if _is_varlen(f) else 0
                    for ci, f in enumerate(schema.fields))
                pack = _pack_fn(schema, cap, ecaps)
                shards_per_payload: Optional[List[list]] = None
                for d in range(n):
                    b = group[d]
                    if b is None:
                        b = _pad_batch(schema, cap, ecaps)
                    payloads = pack(jax.device_put(b, devices[d]))
                    if shards_per_payload is None:
                        shards_per_payload = [[] for _ in payloads]
                    for pi, p in enumerate(payloads):
                        shards_per_payload[pi].append(p)
                for shards in shards_per_payload:
                    tail = shards[0].shape[1:]
                    spec = _full_rank_spec(len(tail) + 1, sharded=True)
                    flat_globals.append(
                        jax.make_array_from_single_device_arrays(
                            (n,) + tail, NamedSharding(mesh, spec),
                            shards))
                    in_specs.append(spec)
                slot_caps.append((cap, ecaps))
            src_plans.append(("dist", slot_caps))
            sig_parts.append(("dist", tuple(slot_caps)))

    cache = getattr(root, "_mesh_programs", None)
    if not isinstance(cache, dict):
        cache = {}
        root._mesh_programs = cache
    # per-output schemas, recorded when the program body traces: a stage
    # fn may emit batches that are NOT root.output_schema (the MXU hash
    # aggregate's trailing flags pseudo-batch) — rebuilding every output
    # against the root schema would misparse their payload lists
    scache = getattr(root, "_mesh_out_schemas", None)
    if not isinstance(scache, dict):
        scache = {}
        root._mesh_out_schemas = scache
    key = (variant, n, DeviceRuntime.generation(), tuple(sig_parts))
    program = cache.get(key)
    if program is None:
        def body(flat):
            from spark_rapids_tpu.kernels.layout import ensure_row_layout
            args = []
            pos = 0
            for plan, src2 in zip(src_plans, sources):
                schema2 = src2.output_schema
                if plan[0] == "rep":
                    bs = []
                    for td in plan[1]:
                        k = td.num_leaves
                        bs.append(jax.tree_util.tree_unflatten(
                            td, flat[pos:pos + k]))
                        pos += k
                    args.append(tuple(bs))
                else:
                    k = _payload_len(schema2)
                    bs = []
                    for cap, _ecaps in plan[1]:
                        bs.append(_batch_from_payloads(
                            schema2, flat[pos:pos + k], cap, squeeze=True))
                        pos += k
                    args.append(tuple(bs))
            with _collect_overflow() as ovf_flags:
                outs = fn(tuple(args))
            ovf = jnp.zeros(1, jnp.bool_)
            for flag in ovf_flags:
                ovf = ovf | jnp.reshape(flag, (1,))
            flat_out = []
            schemas = []
            for b in outs:
                b = ensure_row_layout(b)
                schemas.append(b.schema)
                pl = []
                for c in b.columns:
                    if c.offsets is not None:
                        pl += [c.data[None],
                               c.offsets.astype(jnp.int32)[None],
                               c.validity[None]]
                    else:
                        pl += [c.data[None], c.validity[None]]
                pl.append(jnp.asarray(b.num_rows, jnp.int32).reshape(1))
                flat_out.append(pl)
            scache[key] = schemas
            return flat_out, ovf

        try:
            from jax import shard_map  # jax >= 0.6 top-level export
        except ImportError:  # jax 0.4.x keeps it in experimental
            from jax.experimental.shard_map import shard_map
        # replication checker off unconditionally (not just for the
        # replicated-build fused join): pallas-tier kernels traced inside
        # the stage body have no replication rule — see shard_map_kwargs
        from spark_rapids_tpu.parallel.mesh_shuffle import shard_map_kwargs
        program = instrumented_jit(
            shard_map(body, mesh=mesh, in_specs=(tuple(in_specs),),
                      out_specs=P(DATA_AXIS), **shard_map_kwargs()),
            label=f"meshStage:{root.name}")
        cache[key] = program

    t0 = time.monotonic_ns()
    ctx.metric("pipeline", "programs").add(1)
    ctx.metric("pipeline", "meshProgramDispatches").add(1)
    for ex in exchanges:
        ctx.metric(ex.op_id, "meshBoundariesFused").add(1)
    for j in joins:
        ctx.metric(j.op_id, "meshJoinsFused").add(1)
    out_schema = root.output_schema
    overflowed = False
    results: List[ColumnBatch] = []
    with device_dispatch(ctx, "pipeline", root.name,
                         obs_op=root.op_id) as holder:
        out_lists, ovf_g = PL._run_oom_guarded(
            ctx, lambda: program(tuple(flat_globals)), args=(),
            retryable=True)
        # the ONLY host read of a fused stage, paid only when a join
        # fused: did any shard's bucketed join output overflow its
        # static capacity?  (a [n]-bool fetch after the one dispatch,
        # not a per-boundary shuffleSync)
        if joins:
            overflowed = bool(jax.device_get(ovf_g).any())
        if overflowed:
            holder["outputs"] = []
            out_lists = []
        # one catalog handle per stacked output global, closed right
        # after unsharding: per-shard HBM accounting without exposing a
        # long-lived spill victim that would gather every shard
        cat = DeviceRuntime.get(ctx.conf).catalog
        out_schemas = scache.get(key) or [out_schema] * len(out_lists)
        handles = [
            cat.register_sharded(
                _global_batch(sch, pl, _out_capacity(sch, pl)))
            for sch, pl in zip(out_schemas, out_lists)]
        bytes_per_device = [0] * n
        for h in handles:
            for d, v in enumerate(h.shard_bytes):
                bytes_per_device[d] += v
        dev_pos = {d: i for i, d in enumerate(devices)}
        for sch, pl in zip(out_schemas, out_lists):
            cap = _out_capacity(sch, pl)
            per_dev: List[list] = [[] for _ in range(n)]
            for g in pl:
                for shard in g.addressable_shards:
                    per_dev[dev_pos[shard.device]].append(shard.data)
            for d in range(n):
                arrs = _unshard(per_dev[d])
                results.append(_batch_from_payloads(
                    sch, arrs, cap, squeeze=False))
        for h in handles:
            h.close()
        if not overflowed:
            holder["outputs"] = results
    obs_events.emit_span(
        "mesh", "program", root.op_id, t0, time.monotonic_ns(),
        devices=n, fused_boundaries=len(exchanges),
        fused_joins=len(joins), bytes_per_device=bytes_per_device)
    if overflowed:
        # a shard's true join output exceeded its static bucket: the
        # fused results are invalid — rerun the whole stage host-driven
        # (the classic host-synced join sizes exactly)
        ctx.metric("pipeline", "meshFallbacks").add(1)
        obs_events.emit_instant(
            "mesh", "join_overflow_fallback", root.op_id,
            joins=[j.op_id for j in joins])
        from spark_rapids_tpu.config import MESH_SPMD_AUTO_FALLBACK
        if not MESH_SPMD_AUTO_FALLBACK.get(ctx.conf):
            raise RuntimeError(
                f"{root.name}: fused join output overflowed its static "
                "capacity bucket and "
                "spark.rapids.sql.tpu.mesh.spmd.autoFallback is disabled "
                "(raise mesh.spmd.join.growthFactor or enable "
                "autoFallback)")
        return PL.run_stage_unfused(root, ctx, variant, shrink=shrink)
    # sharding invariants for analysis/plan_verify.check_mesh_sharding:
    # declared specs on every program input/output, boundary flips only
    # at the recorded reshard (exchange) ops — or, for a stage fused
    # around a broadcast join only, at no boundary at all — and no
    # donation under sharding.  ``replicated`` lists the input leaf
    # indices that entered with an all-None (replicated) spec.
    rep_leaves = [i for i, sp in enumerate(in_specs)
                  if all(ax is None for ax in tuple(sp))]
    root._mesh_partition_specs = {
        "in_specs": list(in_specs),
        "out_specs": [P(DATA_AXIS)] * sum(len(pl) for pl in out_lists),
        "reshards": [ex.op_id for ex in exchanges],
        "joins": [j.op_id for j in joins],
        "replicated": rep_leaves,
        "dmask": (False,) * len(sources),
    }
    if shrink:
        results = PL._shrink_outputs_sharded(results, ctx)
    return results
