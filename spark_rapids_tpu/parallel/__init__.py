"""Partitioning, exchanges and the device-mesh shuffle (reference: SURVEY.md
sections 2.5 partitioning + 2.7 shuffle).  The single-host path regroups
batches between partition iterators; the multi-chip path shards batches over a
``jax.sharding.Mesh`` and exchanges rows with an XLA all-to-all inside
``shard_map`` (the ICI analogue of the reference's UCX transport)."""
