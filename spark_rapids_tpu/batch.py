"""Device-resident columnar batch model.

The TPU analogue of the reference's GpuColumnVector/ColumnarBatch layer
(GpuColumnVector.java:39, SURVEY.md section 2.3).  A cudf ``Table`` in GPU
memory becomes a :class:`ColumnBatch`: a struct of dense ``jax.Array`` buffers
staged in HBM.

TPU-first design decisions:

* **Static shapes.**  XLA compiles one executable per shape, so every batch is
  padded to a bucketed capacity (powers of two) and carries a dynamic
  ``num_rows`` scalar.  Kernels mask out rows >= num_rows.  This replaces the
  reference's dynamic cudf row counts and is the bucketed-padded-batch design
  called out in SURVEY.md section 7.
* **Pytree batches.**  ``ColumnBatch``/``DeviceColumn`` are registered pytrees
  with (schema, capacity) as static treedef aux data, so whole batches flow
  through ``jax.jit`` boundaries and fused pipeline stages without manual
  packing.
* **Validity masks, not sentinels.**  Every column has a bool validity array;
  NULL semantics live in the expression kernels.
* **Strings** use the cudf layout: ``offsets`` int32[cap+1] into a flat
  ``uint8`` byte buffer (itself bucketed), so most string ops become
  gather/scan ops which XLA handles well.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T

MIN_CAPACITY = 8
MIN_BYTE_CAPACITY = 16


class BucketPolicy:
    """THE shape-bucket policy: every capacity any exec ever bakes into a
    compiled program comes from this one object, so compiled-shape
    cardinality per schema is bounded by a single rule instead of drifting
    per call site (the recompilation-economics lever from SURVEY.md
    section 7; the ``compiledShapes`` metric proves the bound holds).

    Buckets are powers of two: row capacities >= ``min_rows``, varlen
    element/byte capacities >= ``min_bytes`` (strings ARE array<byte>, so
    both varlen kinds share the byte floor).
    """

    def __init__(self, min_rows: int = MIN_CAPACITY,
                 min_bytes: int = MIN_BYTE_CAPACITY):
        self.min_rows = min_rows
        self.min_bytes = min_bytes

    @staticmethod
    def quantize(n: int, minimum: int) -> int:
        cap = max(int(minimum), 1)
        n = max(int(n), 1)
        while cap < n:
            cap <<= 1
        return cap

    def rows(self, n: int) -> int:
        """Row-capacity bucket for ``n`` live rows."""
        return self.quantize(n, self.min_rows)

    def elems(self, n: int) -> int:
        """Varlen element/byte-capacity bucket for ``n`` elements."""
        return self.quantize(n, self.min_bytes)

    def hot_buckets(self, max_rows: int) -> List[int]:
        """The full row-bucket ladder up to ``max_rows`` — the shape set
        ``session.prewarm()`` compiles ahead of time."""
        out, cap = [], self.rows(1)
        while cap <= self.rows(max_rows):
            out.append(cap)
            cap <<= 1
        return out


#: Process-wide shared bucket policy (all exec inputs route through it).
BUCKETS = BucketPolicy()


def round_up_capacity(n: int, minimum: int = MIN_CAPACITY) -> int:
    """Bucketed capacity via the shared :data:`BUCKETS` policy: next power
    of two >= n (>= minimum)."""
    return BUCKETS.quantize(n, minimum)


# --------------------------------------------------------------------------
# Host-side column/batch: numpy representation used by IO, the CPU oracle and
# host<->HBM staging (the HostMemoryBuffer analogue).
# --------------------------------------------------------------------------


@dataclasses.dataclass
class HostColumn:
    dtype: T.DataType
    values: np.ndarray  # object ndarray of str|None for strings
    validity: np.ndarray  # bool, True = valid
    #: Dictionary-encoded strings (scan v2, docs/io.md): ``values`` hold
    #: int32 codes into this object array of entries, so staging moves
    #: indices instead of per-row bytes.  ``None`` = plain column.
    dictionary: Optional[np.ndarray] = None

    def __post_init__(self):
        self.values = np.asarray(self.values)
        self.validity = np.asarray(self.validity, dtype=np.bool_)
        assert len(self.values) == len(self.validity)
        if self.dictionary is not None:
            self.dictionary = np.asarray(self.dictionary, dtype=object)

    def __len__(self) -> int:
        return len(self.values)

    def decoded(self) -> "HostColumn":
        """Materialize a dictionary-encoded column to plain values (no-op
        for plain columns)."""
        if self.dictionary is None:
            return self
        n = len(self.values)
        values = np.empty(n, dtype=object)
        nd = len(self.dictionary)
        codes = np.asarray(self.values, dtype=np.int64)
        for i in range(n):
            c = codes[i]
            values[i] = (str(self.dictionary[c])
                         if self.validity[i] and 0 <= c < nd else "")
        return HostColumn(self.dtype, values, self.validity)

    @staticmethod
    def from_list(dtype: T.DataType, items: Sequence[Any]) -> "HostColumn":
        validity = np.array([x is not None for x in items], dtype=np.bool_)
        if dtype.is_string:
            values = np.array([x if x is not None else "" for x in items], dtype=object)
        elif dtype.is_array:
            values = np.empty(len(items), dtype=object)
            for i, x in enumerate(items):
                values[i] = list(x) if x is not None else []
        else:
            values = np.array(
                [x if x is not None else 0 for x in items], dtype=dtype.np_dtype
            )
        return HostColumn(dtype, values, validity)

    def to_list(self) -> List[Any]:
        if self.dictionary is not None:
            return self.decoded().to_list()
        out: List[Any] = []
        elem = self.dtype.element if self.dtype.is_array else None
        for v, ok in zip(self.values, self.validity):
            if not ok:
                out.append(None)
            elif self.dtype.is_string:
                out.append(str(v))
            elif self.dtype.is_array:
                out.append([_pyval(elem, e) for e in v])
            elif self.dtype == T.BOOLEAN:
                out.append(bool(v))
            elif self.dtype.is_fractional:
                out.append(float(v))
            else:
                out.append(int(v))
        return out


def _pyval(dtype: T.DataType, v):
    if v is None:
        return None  # element-level NULL (host representation only)
    if dtype.is_string:
        return str(v)
    if dtype == T.BOOLEAN:
        return bool(v)
    if dtype.is_fractional:
        return float(v)
    return int(v)


class HostBatch:
    """A host (numpy) table; the staging representation between IO and device."""

    def __init__(self, schema: T.Schema, columns: Sequence[HostColumn]):
        self.schema = schema
        self.columns = list(columns)
        nrows = {len(c) for c in self.columns}
        assert len(nrows) <= 1, f"ragged batch: {nrows}"
        self.num_rows = len(self.columns[0]) if self.columns else 0

    @staticmethod
    def from_pydict(data: Dict[str, Tuple[T.DataType, Sequence[Any]]]) -> "HostBatch":
        fields, cols = [], []
        for name, (dtype, items) in data.items():
            fields.append(T.Field(name, dtype))
            cols.append(HostColumn.from_list(dtype, items))
        return HostBatch(T.Schema(fields), cols)

    def to_pydict(self) -> Dict[str, List[Any]]:
        return {
            f.name: c.to_list() for f, c in zip(self.schema.fields, self.columns)
        }

    def column(self, name: str) -> HostColumn:
        return self.columns[self.schema.index_of(name)]

    def slice(self, start: int, length: int) -> "HostBatch":
        cols = [
            HostColumn(c.dtype, c.values[start : start + length],
                       c.validity[start : start + length], c.dictionary)
            for c in self.columns
        ]
        return HostBatch(self.schema, cols)

    @staticmethod
    def concat(batches: Sequence["HostBatch"]) -> "HostBatch":
        assert batches
        schema = batches[0].schema
        cols = []
        for i, f in enumerate(schema.fields):
            # dictionary-encoded parts decode first: dictionaries differ
            # per source chunk, so the concatenated column is plain
            parts = [b.columns[i].decoded() for b in batches]
            values = np.concatenate([p.values for p in parts])
            validity = np.concatenate([p.validity for p in parts])
            cols.append(HostColumn(f.dtype, values, validity))
        return HostBatch(schema, cols)

    def __repr__(self):
        return f"HostBatch({self.schema}, rows={self.num_rows})"


# --------------------------------------------------------------------------
# Device column
# --------------------------------------------------------------------------


class DeviceColumn:
    """One column staged in HBM: data buffer + validity mask (+ offsets).

    Dictionary-encoded strings (scan v2, docs/io.md) additionally carry
    ``codes`` — int32[cap] indices into the dictionary entries that
    data/offsets then describe — plus the static ``mat_byte_cap``: the
    byte-capacity bucket the column occupies once materialized
    (``kernels.layout.dict_decode_column``).  Encoded columns exist only
    between scan staging and the first consuming operator; every exec
    materializes at entry unless it is explicitly encode-aware.
    """

    def __init__(self, dtype: T.DataType, data, validity, offsets=None,
                 codes=None, mat_byte_cap: int = 0):
        self.dtype = dtype
        self.data = data
        self.validity = validity
        self.offsets = offsets  # strings only: int32[cap+1]
        self.codes = codes  # dict-encoded strings only: int32[cap]
        self.mat_byte_cap = int(mat_byte_cap)

    @property
    def is_string(self) -> bool:
        return self.dtype.is_string

    @property
    def is_varlen(self) -> bool:
        """Strings and arrays: flat element buffer + offsets."""
        return self.offsets is not None

    @property
    def is_dict(self) -> bool:
        """Dictionary-encoded string column (codes + dictionary buffers)."""
        return self.codes is not None

    def tree_flatten(self):
        if self.codes is not None:
            return ((self.data, self.validity, self.offsets, self.codes),
                    (self.dtype, True, True, self.mat_byte_cap))
        if self.offsets is None:
            return (self.data, self.validity), (self.dtype, False, False, 0)
        return ((self.data, self.validity, self.offsets),
                (self.dtype, True, False, 0))

    @classmethod
    def tree_unflatten(cls, aux, children):
        dtype, has_offsets, has_codes, mat_byte_cap = aux
        if has_codes:
            data, validity, offsets, codes = children
            return cls(dtype, data, validity, offsets, codes, mat_byte_cap)
        if has_offsets:
            data, validity, offsets = children
            return cls(dtype, data, validity, offsets)
        data, validity = children
        return cls(dtype, data, validity, None)

    def __repr__(self):
        shape = getattr(self.data, "shape", None)
        enc = ", dict" if self.codes is not None else ""
        return f"DeviceColumn({self.dtype}, data={shape}{enc})"


jax.tree_util.register_pytree_node(
    DeviceColumn, DeviceColumn.tree_flatten, DeviceColumn.tree_unflatten
)


class ColumnBatch:
    """A device table: columns + dynamic valid-row count + static capacity."""

    def __init__(self, schema: T.Schema, columns: Sequence[DeviceColumn], num_rows,
                 capacity: int):
        self.schema = schema
        self.columns = tuple(columns)
        self.num_rows = num_rows  # int32 scalar (device array inside jit)
        self.capacity = int(capacity)

    def column(self, name: str) -> DeviceColumn:
        return self.columns[self.schema.index_of(name)]

    @property
    def row_mask(self):
        """bool[cap]: True for rows < num_rows (the live rows)."""
        return jnp.arange(self.capacity, dtype=jnp.int32) < self.num_rows

    def with_columns(self, schema: T.Schema, columns: Sequence[DeviceColumn]
                     ) -> "ColumnBatch":
        return ColumnBatch(schema, columns, self.num_rows, self.capacity)

    def tree_flatten(self):
        return (tuple(self.columns), self.num_rows), (self.schema, self.capacity)

    @classmethod
    def tree_unflatten(cls, aux, children):
        schema, capacity = aux
        columns, num_rows = children
        return cls(schema, columns, num_rows, capacity)

    def __repr__(self):
        return f"ColumnBatch({self.schema}, cap={self.capacity})"

    def host_num_rows(self) -> int:
        return int(jax.device_get(self.num_rows))


jax.tree_util.register_pytree_node(
    ColumnBatch, ColumnBatch.tree_flatten, ColumnBatch.tree_unflatten
)


# --------------------------------------------------------------------------
# Host <-> device staging (the H2D/D2H copy layer; reference: GpuColumnVector
# host builders + copy, GpuColumnVector.java:41-130)
# --------------------------------------------------------------------------


def _string_host_to_buffers(values: np.ndarray, validity: np.ndarray,
                            byte_capacity: Optional[int] = None
                            ) -> Tuple[np.ndarray, np.ndarray]:
    """Encode an object array of strings to (offsets int32[n+1], bytes uint8)."""
    encoded = [
        (v if isinstance(v, bytes) else str(v).encode("utf-8")) if ok else b""
        for v, ok in zip(values, validity)
    ]
    lengths = np.fromiter((len(e) for e in encoded), dtype=np.int64,
                          count=len(encoded))
    offsets = np.zeros(len(encoded) + 1, dtype=np.int32)
    np.cumsum(lengths, out=offsets[1:])
    total = int(offsets[-1])
    cap = byte_capacity if byte_capacity is not None else round_up_capacity(
        max(total, 1), minimum=16)
    data = np.zeros(cap, dtype=np.uint8)
    if total:
        data[:total] = np.frombuffer(b"".join(encoded), dtype=np.uint8)
    return offsets, data


def _array_host_to_buffers(dtype: T.ArrayType, values: np.ndarray,
                           validity: np.ndarray
                           ) -> Tuple[np.ndarray, np.ndarray]:
    """Encode an object array of lists to (offsets int32[n+1], flat elems)
    — the same varlen layout strings use (strings ARE array<byte>)."""
    lists = [list(v) if ok else [] for v, ok in zip(values, validity)]
    if any(e is None for x in lists for e in x):
        raise NotImplementedError(
            "array element-level NULLs are host-only in the v1 nested "
            "envelope; keep such columns on the CPU path (see "
            "docs/compatibility.md)")
    lengths = np.fromiter((len(x) for x in lists), dtype=np.int64,
                          count=len(lists))
    offsets = np.zeros(len(lists) + 1, dtype=np.int32)
    np.cumsum(lengths, out=offsets[1:])
    total = int(offsets[-1])
    # shared varlen bucket floor (strings and arrays ride one policy so a
    # mixed suite compiles one ladder of element capacities, not two)
    cap = BUCKETS.elems(total)
    data = np.zeros(cap, dtype=dtype.element.np_dtype)
    if total:
        flat = [e for x in lists for e in x]
        data[:total] = np.asarray(flat, dtype=dtype.element.np_dtype)
    return offsets, data


def host_column_to_device(col: HostColumn, capacity: int,
                          device=None) -> DeviceColumn:
    n = len(col)
    assert capacity >= n
    validity = np.zeros(capacity, dtype=np.bool_)
    validity[:n] = col.validity
    put = (lambda x: jax.device_put(x, device)) if device is not None else jax.device_put
    if col.dictionary is not None and col.dtype.is_string:
        # dictionary-encoded staging: ship int32 codes plus the (small)
        # dictionary buffers instead of per-row string bytes
        entries = col.dictionary
        nd = max(len(entries), 1)
        ent_valid = np.ones(len(entries), dtype=np.bool_)
        d_offsets, d_data = _string_host_to_buffers(entries, ent_valid)
        dcap = round_up_capacity(nd)
        full_d_off = np.full(dcap + 1, d_offsets[-1], dtype=np.int32)
        full_d_off[: len(entries) + 1] = d_offsets
        raw = np.asarray(col.values, dtype=np.int64)
        safe = np.where(col.validity, np.clip(raw, 0, nd - 1), 0)
        codes = np.zeros(capacity, dtype=np.int32)
        codes[:n] = safe
        ent_lens = (d_offsets[1:] - d_offsets[:-1]).astype(np.int64)
        mat_total = int(ent_lens[safe[col.validity]].sum()) \
            if len(entries) and n else 0
        return DeviceColumn(col.dtype, put(d_data), put(validity),
                            put(full_d_off), put(codes),
                            BUCKETS.elems(mat_total))
    if col.dtype.is_string or col.dtype.is_array:
        if col.dtype.is_string:
            offsets, data = _string_host_to_buffers(col.values, col.validity)
        else:
            offsets, data = _array_host_to_buffers(col.dtype, col.values,
                                                   col.validity)
        full_offsets = np.full(capacity + 1, offsets[-1], dtype=np.int32)
        full_offsets[: n + 1] = offsets
        return DeviceColumn(col.dtype, put(data), put(validity), put(full_offsets))
    data = np.zeros(capacity, dtype=col.dtype.np_dtype)
    data[:n] = col.values
    return DeviceColumn(col.dtype, put(data), put(validity), None)


def host_to_device(batch: HostBatch, capacity: Optional[int] = None,
                   device=None) -> ColumnBatch:
    import time

    from spark_rapids_tpu.fault import inject
    from spark_rapids_tpu.utils.compile_registry import record_transfer
    inject.maybe_fire("h2d")
    t0 = time.monotonic_ns()
    cap = capacity if capacity is not None else round_up_capacity(batch.num_rows)
    cols = [host_column_to_device(c, cap, device) for c in batch.columns]
    num_rows = jnp.asarray(batch.num_rows, dtype=jnp.int32)
    if device is not None:
        num_rows = jax.device_put(num_rows, device)
    out = ColumnBatch(batch.schema, cols, num_rows, cap)
    nbytes = sum(getattr(leaf, "nbytes", 0)
                 for leaf in jax.tree_util.tree_leaves(out))
    # enqueue-side wall: device_put is async on real TPUs, so h2dTimeNs
    # is host-pack + transfer-enqueue time (h2d_gb_per_sec reads as an
    # upper bound there; exact on the synchronous CPU backend).  Blocking
    # here for accuracy would serialize staging against device compute —
    # the overlap this layer exists to create (same lower-bound policy as
    # dispatch wall vs. metrics.detailEnabled).
    record_transfer("h2d", nbytes, time.monotonic_ns() - t0)
    return out


def device_to_host_many(batches: Sequence[ColumnBatch],
                        keep_dictionary: bool = False) -> List[HostBatch]:
    # ONE bulk device_get for all batches' buffers AND num_rows scalars:
    # jax prefetches every leaf with copy_to_host_async before blocking, so
    # the whole pytree rides a single sync + round trip.  Per-column gets
    # serialize one RTT each — over a tunneled device that dominated query
    # wall time (see profile_bench.py).
    import time

    from spark_rapids_tpu.fault import inject
    from spark_rapids_tpu.utils.compile_registry import (
        guard_check, record_transfer,
    )
    inject.maybe_fire("d2h")
    guard_check(list(batches), "device_to_host_many")
    t0 = time.monotonic_ns()
    host = jax.device_get([
        (b.num_rows,
         [(c.data, c.validity, c.offsets, c.codes) if c.codes is not None
          else (c.data, c.validity, c.offsets) if c.offsets is not None
          else (c.data, c.validity) for c in b.columns])
        for b in batches])
    nbytes = sum(
        buf.nbytes
        for _num_rows, col_bufs in host
        for bufs in col_bufs for buf in bufs)
    record_transfer("d2h", nbytes, time.monotonic_ns() - t0)
    out = []
    for batch, (num_rows, col_bufs) in zip(batches, host):
        n = int(num_rows)
        out_cols = []
        for f, bufs in zip(batch.schema.fields, col_bufs):
            validity = np.asarray(bufs[1])[:n]
            if f.dtype.is_string and len(bufs) == 4:
                # dictionary-encoded: decode the (small) dictionary once,
                # then fan the per-row codes out through it.  Collection
                # D2H always returns plain values; ``keep_dictionary``
                # (spill tier transitions) keeps (codes, entries) so an
                # encoded piece survives spill/unspill encoded.
                d_off = np.asarray(bufs[2])
                raw = np.asarray(bufs[0]).tobytes()
                codes = np.asarray(bufs[3])[:n]
                nd = int(codes.max()) + 1 if n else 0
                entries = [raw[d_off[i]:d_off[i + 1]].decode(
                    "utf-8", errors="replace") for i in range(nd)]
                if keep_dictionary:
                    ents = np.array(entries or [""], dtype=object)
                    out_cols.append(HostColumn(
                        f.dtype, codes.astype(np.int64), validity, ents))
                    continue
                values = np.empty(n, dtype=object)
                for i in range(n):
                    values[i] = entries[codes[i]] if validity[i] else ""
                out_cols.append(HostColumn(f.dtype, values, validity))
            elif f.dtype.is_string:
                # one bytes() copy + per-row slicing of it: slicing a bytes
                # object is a cheap memcpy, vs. the per-row ndarray slice +
                # bytes() pair this replaced (2 object allocs + dtype
                # machinery per row)
                offsets = np.asarray(bufs[2])
                raw = np.asarray(bufs[0]).tobytes()
                values = np.empty(n, dtype=object)
                for i in range(n):
                    values[i] = raw[offsets[i]:offsets[i + 1]].decode(
                        "utf-8", errors="replace")
                out_cols.append(HostColumn(f.dtype, values, validity))
            elif f.dtype.is_array:
                data = np.asarray(bufs[0])
                offsets = np.asarray(bufs[2])
                values = np.empty(n, dtype=object)
                if n:
                    # one vectorized split at the live offsets instead of
                    # n fancy-indexed copies
                    for i, seg in enumerate(np.split(
                            data[:offsets[n]], offsets[1:n])):
                        values[i] = list(seg)
                out_cols.append(HostColumn(f.dtype, values, validity))
            else:
                data = np.asarray(bufs[0])[:n]
                out_cols.append(HostColumn(f.dtype, data, validity))
        out.append(HostBatch(batch.schema, out_cols))
    return out


def device_to_host(batch: ColumnBatch,
                   keep_dictionary: bool = False) -> HostBatch:
    return device_to_host_many([batch], keep_dictionary=keep_dictionary)[0]


def host_batch_bytes(hb: HostBatch) -> int:
    """Host bytes a :class:`HostBatch` occupies (spill-catalog host-tier
    accounting).  Computed ONCE per tier transition and cached on the
    handle — string columns hold python objects, so sizing them walks
    every value and must never sit on a per-call budget path."""
    total = 0
    for c in hb.columns:
        if c.dictionary is not None:
            total += c.values.nbytes + len(c.dictionary) + sum(
                len(str(x)) for x in c.dictionary)
        elif c.dtype.is_string:
            total += sum(len(str(x)) for x in c.values) + len(c.values)
        else:
            total += c.values.nbytes
        total += c.validity.nbytes
    return total


def host_sizes(batches: Sequence[ColumnBatch]) -> List[Tuple[int, List[int]]]:
    """Fetch (num_rows, [string byte totals...]) for many batches in ONE
    blocking transfer (one round trip instead of one per scalar).

    String byte totals read ``offsets[-1]`` — valid because offsets are
    constant past num_rows by construction.
    """
    from spark_rapids_tpu.utils.compile_registry import guard_check
    guard_check(list(batches), "host_sizes")

    def _varlen_total(c):
        if c.codes is not None:
            # Dictionary-encoded: report the MATERIALIZED byte total (what
            # any gather/concat consumer will hold after its row-layout
            # guard decodes the column), not the dictionary's size.
            ent_lens = (c.offsets[1:] - c.offsets[:-1]).astype(jnp.int32)
            nd = int(c.offsets.shape[0]) - 1
            codes_c = jnp.clip(c.codes, 0, max(nd - 1, 0))
            return jnp.sum(jnp.where(c.validity, ent_lens[codes_c], 0))
        return c.offsets[-1]

    scalars = [(b.num_rows,
                [_varlen_total(c) for c in b.columns if c.is_varlen])
               for b in batches]
    host = jax.device_get(scalars)
    return [(int(n), [int(t) for t in totals]) for n, totals in host]


def fixed_row_bytes(schema: T.Schema) -> int:
    """Estimated fixed-width bytes per row: data itemsize plus one validity
    byte per column; varlen columns contribute their 4-byte offset entry
    (element bytes are accounted separately from offsets[-1]).  This is the
    size estimate AQE uses for byte-based targets (the reference's
    map-status byte sizes)."""
    total = 0
    for f in schema.fields:
        dt = f.dtype
        if dt.is_string or dt.is_array:
            total += 5
        else:
            total += int(np.dtype(dt.np_dtype).itemsize) + 1
    return total


def varlen_byte_scales(schema: T.Schema) -> List[int]:
    """Per-varlen-column multiplier converting offsets[-1] element totals
    to bytes: 1 for strings (elements ARE bytes), element itemsize for
    arrays.  Order matches the varlen-column order host_sizes and
    gather_rows use."""
    out = []
    for f in schema.fields:
        if f.dtype.is_string:
            out.append(1)
        elif f.dtype.is_array:
            out.append(int(np.dtype(f.dtype.element.np_dtype).itemsize))
    return out


def colocate_batches(batches: Sequence[ColumnBatch]
                     ) -> Sequence[ColumnBatch]:
    """Move batches onto one device when they span several.

    After a device-resident mesh shuffle, each partition's batch lives on
    its own mesh device; a stage that merges several partitions into one
    program (global sort, final collect, broadcast build) must first gather
    them — a device-to-device transfer, never through the host.  No-op in
    the common single-device case."""
    devs = set()
    for b in batches:
        for leaf in jax.tree_util.tree_leaves(b):
            get_devs = getattr(leaf, "devices", None)
            if callable(get_devs):
                devs.update(get_devs())
    if len(devs) <= 1:
        return batches
    target = sorted(devs, key=lambda d: d.id)[0]
    return jax.device_put(list(batches), target)


def empty_device_batch(schema: T.Schema, capacity: int = MIN_CAPACITY) -> ColumnBatch:
    cols = []
    for f in schema.fields:
        validity = jnp.zeros(capacity, dtype=jnp.bool_)
        if f.dtype.is_string:
            cols.append(DeviceColumn(
                f.dtype,
                jnp.zeros(16, dtype=jnp.uint8),
                validity,
                jnp.zeros(capacity + 1, dtype=jnp.int32),
            ))
        else:
            cols.append(DeviceColumn(
                f.dtype, jnp.zeros(capacity, dtype=f.dtype.jnp_dtype), validity, None
            ))
    return ColumnBatch(schema, cols, jnp.asarray(0, dtype=jnp.int32), capacity)
