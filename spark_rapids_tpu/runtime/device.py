"""Device discovery and admission control.

Reference analogues:
* GpuDeviceManager.scala:31 — one accelerator per executor process, acquired
  once and bound for all task threads.  Here: the first JAX device (TPU chip
  when present, else CPU backend) is selected once per process.
* GpuSemaphore.scala:58-98 — bounds the number of concurrent tasks admitted
  to device memory; acquired at every host->device entry point and released
  when results leave the device.
"""

from __future__ import annotations

import threading
from typing import Optional

import jax

from spark_rapids_tpu.config import RapidsConf


class TpuSemaphore:
    """Counting semaphore bounding concurrent device-resident tasks.

    Unlike a plain semaphore it is re-entrant per TASK, matching
    GpuSemaphore.acquireIfNecessary semantics (GpuSemaphore.scala:74-87).
    In this single-process engine a query IS the task, and a query's device
    work spans threads: the main thread consumes while stage read-ahead
    workers (plan/physical.py gen_pipelined) drive nested plan sections.
    The hold depth is therefore shared across threads — a worker whose
    nested TPU section acquires while the main thread already holds the
    permit re-enters instead of deadlocking against its own consumer
    (thread-local depth wedged exactly that way: the worker blocked on the
    permit the main thread held while the main thread blocked on the
    worker's queue).  Releases pair by count, on any thread.
    """

    def __init__(self, permits: int):
        self._permits = max(1, permits)
        self._cond = threading.Condition()
        self._available = self._permits
        self._depth = 0

    def acquire(self):
        with self._cond:
            while True:
                if self._depth > 0:
                    # the task already holds a permit (possibly taken by a
                    # sibling thread while this one waited): re-enter
                    self._depth += 1
                    return
                if self._available > 0:
                    self._available -= 1
                    self._depth = 1
                    return
                self._cond.wait()

    def release(self):
        with self._cond:
            if self._depth <= 0:
                return
            self._depth -= 1
            if self._depth == 0:
                self._available += 1
                self._cond.notify()

    def release_all(self):
        with self._cond:
            if self._depth > 0:
                self._depth = 0
                self._available += 1
                self._cond.notify()

    def held_depth(self) -> int:
        """The task's re-entrant hold depth (0 = no permit held)."""
        with self._cond:
            return self._depth


class DeviceRuntime:
    """Process-wide device services (GpuDeviceManager analogue)."""

    _instance: Optional["DeviceRuntime"] = None
    _lock = threading.Lock()

    def __init__(self, conf: RapidsConf):
        self.conf = conf
        devices = jax.devices()
        tpus = [d for d in devices if d.platform == "tpu"]
        self.device = tpus[0] if tpus else devices[0]
        self.platform = self.device.platform
        self.semaphore = TpuSemaphore(conf.concurrent_tpu_tasks)
        from spark_rapids_tpu.mem.catalog import BufferCatalog
        self.catalog = BufferCatalog(conf)

    @classmethod
    def get(cls, conf: RapidsConf) -> "DeviceRuntime":
        with cls._lock:
            if cls._instance is None:
                cls._instance = DeviceRuntime(conf)
            return cls._instance

    @classmethod
    def reset(cls):
        with cls._lock:
            cls._instance = None
