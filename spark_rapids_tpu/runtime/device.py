"""Device discovery and admission control.

Reference analogues:
* GpuDeviceManager.scala:31 — one accelerator per executor process, acquired
  once and bound for all task threads.  Here: the first JAX device (TPU chip
  when present, else CPU backend) is selected once per process.
* GpuSemaphore.scala:58-98 — bounds the number of concurrent tasks admitted
  to device memory; acquired at every host->device entry point and released
  when results leave the device.
"""

from __future__ import annotations

import threading
from typing import Optional

import jax

from spark_rapids_tpu.config import RapidsConf


class TpuSemaphore:
    """Counting semaphore bounding concurrent device-resident tasks.

    Unlike a plain semaphore it is re-entrant per thread (a task thread that
    already holds it may re-acquire freely), matching
    GpuSemaphore.acquireIfNecessary semantics (GpuSemaphore.scala:74-87).
    """

    def __init__(self, permits: int):
        self._permits = max(1, permits)
        self._sem = threading.Semaphore(self._permits)
        self._held = threading.local()

    def acquire(self):
        depth = getattr(self._held, "depth", 0)
        if depth == 0:
            self._sem.acquire()
        self._held.depth = depth + 1

    def release(self):
        depth = getattr(self._held, "depth", 0)
        if depth <= 0:
            return
        self._held.depth = depth - 1
        if self._held.depth == 0:
            self._sem.release()

    def release_all(self):
        depth = getattr(self._held, "depth", 0)
        if depth > 0:
            self._held.depth = 0
            self._sem.release()

    def held_depth(self) -> int:
        """This thread's re-entrant hold depth (0 = no permit held)."""
        return getattr(self._held, "depth", 0)


class DeviceRuntime:
    """Process-wide device services (GpuDeviceManager analogue)."""

    _instance: Optional["DeviceRuntime"] = None
    _lock = threading.Lock()

    def __init__(self, conf: RapidsConf):
        self.conf = conf
        devices = jax.devices()
        tpus = [d for d in devices if d.platform == "tpu"]
        self.device = tpus[0] if tpus else devices[0]
        self.platform = self.device.platform
        self.semaphore = TpuSemaphore(conf.concurrent_tpu_tasks)
        from spark_rapids_tpu.mem.catalog import BufferCatalog
        self.catalog = BufferCatalog(conf)

    @classmethod
    def get(cls, conf: RapidsConf) -> "DeviceRuntime":
        with cls._lock:
            if cls._instance is None:
                cls._instance = DeviceRuntime(conf)
            return cls._instance

    @classmethod
    def reset(cls):
        with cls._lock:
            cls._instance = None
