"""Device discovery and admission control.

Reference analogues:
* GpuDeviceManager.scala:31 — one accelerator per executor process, acquired
  once and bound for all task threads.  Here: the first JAX device (TPU chip
  when present, else CPU backend) is selected once per process.
* GpuSemaphore.scala:58-98 — bounds the number of concurrent tasks admitted
  to device memory; acquired at every host->device entry point and released
  when results leave the device.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

import jax

from spark_rapids_tpu.config import RapidsConf


class TpuSemaphore:
    """Counting semaphore bounding concurrent device-resident tasks.

    Unlike a plain semaphore it is re-entrant per TASK, matching
    GpuSemaphore.acquireIfNecessary semantics (GpuSemaphore.scala:74-87).
    A query IS the task — identified by its ``obs.events`` QueryScope —
    and a query's device work spans threads: the main thread consumes
    while stage read-ahead workers (plan/physical.py gen_pipelined) drive
    nested plan sections.  The hold depth is therefore shared across the
    task's threads (bound or adopted into its scope) — a worker whose
    nested TPU section acquires while the consumer already holds the
    permit re-enters instead of deadlocking against its own consumer
    (thread-local depth wedged exactly that way: the worker blocked on the
    permit the main thread held while the main thread blocked on the
    worker's queue).  Releases pair by count, on any of the task's
    threads.

    With several queries in flight (the serving runtime), each holds its
    own depth entry, so two concurrent queries genuinely contend for
    permits instead of merging into one task — with ``permits=1`` their
    device phases serialize.  Work outside any query scope shares one
    process-wide default task (key None), the historical behavior.
    """

    def __init__(self, permits: int):
        self._permits = max(1, permits)
        self._cond = threading.Condition()
        self._available = self._permits
        # task key (QueryScope or None) -> re-entrant hold depth; a task
        # present in the map holds exactly one permit
        self._depths = {}

    @staticmethod
    def _task_key():
        from spark_rapids_tpu.obs import events as obs_events
        return obs_events.task_key()

    def acquire(self):
        key = self._task_key()
        with self._cond:
            while True:
                depth = self._depths.get(key, 0)
                if depth > 0:
                    # the task already holds a permit (possibly taken by a
                    # sibling thread while this one waited): re-enter
                    self._depths[key] = depth + 1
                    return
                if self._available > 0:
                    self._available -= 1
                    self._depths[key] = 1
                    return
                # bounded wait: release/notify still wakes immediately;
                # the bound only caps the C-level block so the fault
                # watchdog's async PartitionTimeout can be delivered to
                # a thread parked on device admission
                self._cond.wait(0.25)

    def release(self):
        key = self._task_key()
        with self._cond:
            depth = self._depths.get(key, 0)
            if depth <= 0:
                return
            if depth == 1:
                del self._depths[key]
                self._available += 1
                self._cond.notify()
            else:
                self._depths[key] = depth - 1

    def release_all(self):
        """Drop the calling task's whole hold (recovery: the failed
        attempt's permits must not outlive it).  Other tasks' holds are
        untouched — under concurrency their queries are still live."""
        key = self._task_key()
        with self._cond:
            if self._depths.pop(key, 0) > 0:
                self._available += 1
                self._cond.notify()

    def task_depth(self) -> int:
        """The CALLING task's re-entrant hold depth (0 = no permit held)
        — for acquire/release bookkeeping deltas within one query."""
        key = self._task_key()
        with self._cond:
            return self._depths.get(key, 0)

    def held_depth(self) -> int:
        """Total hold depth across ALL tasks (0 = nothing held by
        anyone) — the leak-detection contract plan_verify and the suite
        assert after every query/storm."""
        with self._cond:
            return sum(self._depths.values())


class DeviceRuntime:
    """Process-wide device services (GpuDeviceManager analogue)."""

    _instance: Optional["DeviceRuntime"] = None
    _lock = threading.Lock()
    # Bumped by every recover(): state derived from device buffers
    # (exchange split caches) records the generation it was built under
    # and treats a mismatch as invalid — a replay after a device loss
    # then recomputes from lineage instead of reading lost pieces.
    _generation = 0

    def __init__(self, conf: RapidsConf):
        self.conf = conf
        # startup pool sizing (GpuDeviceManager.initializeMemory role):
        # advisory on the accelerator backends, ignored by CPU; setdefault
        # so an operator's explicit env wins, and a no-op if the backend
        # already initialized (the fraction only binds at client creation)
        from spark_rapids_tpu.config import DEVICE_POOL_FRACTION
        os.environ.setdefault("XLA_PYTHON_CLIENT_MEM_FRACTION",
                              str(DEVICE_POOL_FRACTION.get(conf)))
        devices = jax.devices()
        tpus = [d for d in devices if d.platform == "tpu"]
        self.device = tpus[0] if tpus else devices[0]
        self.platform = self.device.platform
        self.semaphore = TpuSemaphore(conf.concurrent_tpu_tasks)
        from spark_rapids_tpu.mem.catalog import BufferCatalog
        self.catalog = BufferCatalog(conf)

    @classmethod
    def get(cls, conf: RapidsConf) -> "DeviceRuntime":
        with cls._lock:
            if cls._instance is None:
                cls._instance = DeviceRuntime(conf)
            return cls._instance

    @classmethod
    def reset(cls):
        with cls._lock:
            cls._instance = None

    @classmethod
    def generation(cls) -> int:
        with cls._lock:
            return cls._generation

    @classmethod
    def recover(cls, conf: RapidsConf, rescue: bool = True
                ) -> "DeviceRuntime":
        """Device-lost recovery: rebuild the runtime (fresh device pick +
        fresh semaphore — a permit wedged by the dead attempt cannot
        block the replay) while KEEPING the spill catalog so host/disk
        copies survive; its device tier is invalidated (best-effort
        rescue to host when ``rescue``, else marked lost — mem.catalog).

        The invalidation runs OUTSIDE the class lock: a rescue D2H
        against a sick device can block, and holding ``_lock`` through
        it would wedge every thread touching ``get()``/``generation()``
        — the hang this subsystem exists to prevent."""
        with cls._lock:
            old = cls._instance
            cls._generation += 1
            inst = DeviceRuntime(conf)
            if old is not None:
                inst.catalog = old.catalog
            cls._instance = inst
        if old is not None:
            old.catalog.invalidate_device_tier(rescue=rescue)
        return inst
