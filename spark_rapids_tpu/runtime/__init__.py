"""Device runtime services: device discovery/binding, the TpuSemaphore, and
the tiered memory catalog (reference: GpuDeviceManager.scala,
GpuSemaphore.scala, RapidsBufferCatalog.scala — SURVEY.md section 2.4)."""
