"""Python-worker admission for pandas execs.

Reference analogue: PythonWorkerSemaphore (python/PythonWorkerSemaphore.scala
:97) — the rapids plugin bounds how many python workers may run
concurrently so python memory stays within
``spark.rapids.python.concurrentPythonWorkers``.  Here python UDF code runs
in-process (threads share the interpreter), so the semaphore bounds
concurrent pandas-exec evaluations and, like the reference's GpuSemaphore
interplay, the DEVICE semaphore is released while python runs so TPU slots
are not held hostage by slow python.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional

from spark_rapids_tpu.config import RapidsConf, conf_int

CONCURRENT_PYTHON_WORKERS = conf_int(
    "spark.rapids.python.concurrentPythonWorkers", 4,
    "Concurrent python (pandas UDF / pandas exec) evaluations allowed "
    "per process (PythonWorkerSemaphore analogue).")

_lock = threading.Lock()
_sem: Optional[threading.Semaphore] = None
_sem_permits = 0


def _semaphore(conf: RapidsConf) -> threading.Semaphore:
    global _sem, _sem_permits
    with _lock:
        permits = max(1, CONCURRENT_PYTHON_WORKERS.get(conf))
        if _sem is None or permits != _sem_permits:
            _sem = threading.Semaphore(permits)
            _sem_permits = permits
        return _sem


@contextlib.contextmanager
def python_worker_slot(ctx):
    """Bound python concurrency; release the device semaphore while python
    runs (the GpuSemaphore release in GpuArrowEvalPythonExec.scala:484).

    Only a permit this thread actually HOLDS is released/re-acquired —
    release() at depth 0 is a no-op, so blindly re-acquiring afterwards
    would leak a permit and eventually deadlock device admission.
    """
    sem = _semaphore(ctx.conf)
    released_device = False
    if ctx.semaphore is not None and \
            getattr(ctx.semaphore, "held_depth", lambda: 0)() > 0:
        ctx.semaphore.release()
        released_device = True
    sem.acquire()
    try:
        yield
    finally:
        sem.release()
        if released_device:
            ctx.semaphore.acquire()
