"""Python-worker admission + out-of-process execution for pandas execs.

Reference analogues:
* PythonWorkerSemaphore (python/PythonWorkerSemaphore.scala:97) — bounds
  how many python workers may run concurrently so python memory stays
  within ``spark.rapids.python.concurrentPythonWorkers``.
* GpuArrowPythonRunner (GpuArrowEvalPythonExec.scala:365) + the patched
  worker (python/rapids/worker.py:22-67) — user python runs in a SEPARATE
  worker process, batches stream to/from it over Arrow IPC, and the device
  semaphore is released while the worker runs so TPU slots are not held
  hostage by slow python.

Here :func:`run_python_task` forks a worker per partition task (fork, not
spawn: pandas UDFs are arbitrary closures — fork inherits them without
cloudpickle).  Batches stream over pipes as length-prefixed frames of the
engine's native batch serializer (native/batch_runtime.cc — the project's
Arrow-IPC-analogue wire format, the same one the spill tiers use).  A
worker crash surfaces as :class:`PythonWorkerError` on the task, never a
hang, and leaves the engine reusable.
"""

from __future__ import annotations

import contextlib
import os
import struct
import threading
from typing import Optional

from spark_rapids_tpu.config import RapidsConf, conf_bool, conf_int

CONCURRENT_PYTHON_WORKERS = conf_int(
    "spark.rapids.python.concurrentPythonWorkers", 4,
    "Concurrent python (pandas UDF / pandas exec) evaluations allowed "
    "per process (PythonWorkerSemaphore analogue).")
PYTHON_OOP_ENABLED = conf_bool(
    "spark.rapids.python.outOfProcess.enabled", True,
    "Run pandas UDF / pandas-exec python in a forked worker process, "
    "streaming batches over framed IPC pipes (GpuArrowPythonRunner "
    "analogue): user code is isolated from the engine process and the "
    "device semaphore is released while it runs.  Off = in-process.")

_lock = threading.Lock()
_sem: Optional[threading.Semaphore] = None
_sem_permits = 0


def _semaphore(conf: RapidsConf) -> threading.Semaphore:
    global _sem, _sem_permits
    with _lock:
        permits = max(1, CONCURRENT_PYTHON_WORKERS.get(conf))
        if _sem is None or permits != _sem_permits:
            _sem = threading.Semaphore(permits)
            _sem_permits = permits
        return _sem


@contextlib.contextmanager
def python_worker_slot(ctx):
    """Bound python concurrency; release the device semaphore while python
    runs (the GpuSemaphore release in GpuArrowEvalPythonExec.scala:484).

    Only a permit this thread actually HOLDS is released/re-acquired —
    release() at depth 0 is a no-op, so blindly re-acquiring afterwards
    would leak a permit and eventually deadlock device admission.
    """
    sem = _semaphore(ctx.conf)
    released_device = False
    if ctx.semaphore is not None and \
            getattr(ctx.semaphore, "task_depth", lambda: 0)() > 0:
        ctx.semaphore.release()
        released_device = True
    sem.acquire()
    try:
        yield
    finally:
        sem.release()
        if released_device:
            ctx.semaphore.acquire()


class PythonWorkerError(RuntimeError):
    """A python worker task failed or its process died."""


# frame tags on both pipes
_MSG_BATCH = 0
_MSG_END = 1
_MSG_ERROR = 2

# pid of the most recent worker (observable by tests: != engine pid)
last_worker_pid: Optional[int] = None


def _write_frame(fd: int, tag: int, schema_idx: int, payload: bytes):
    buf = struct.pack("<BBI", tag, schema_idx, len(payload)) + payload
    view = memoryview(buf)
    while view:
        n = os.write(fd, view)
        view = view[n:]


def _read_exact(fd: int, n: int) -> Optional[bytes]:
    """Read exactly n bytes; None on clean EOF at a frame boundary."""
    chunks = []
    got = 0
    while got < n:
        b = os.read(fd, n - got)
        if not b:
            return None if not chunks else b"".join(chunks)
        chunks.append(b)
        got += len(b)
    return b"".join(chunks)


def run_python_task(ctx, task, inputs, in_schemas, out_schema):
    """Execute ``task`` in a forked worker process, streaming batches both
    ways (GpuArrowPythonRunner / python/rapids/worker.py analogue).

    ``task``: Callable[[Iterator[(schema_idx, HostBatch)]], Iterator[HostBatch]]
    — runs IN THE WORKER; receives the streamed inputs, yields outputs.
    ``inputs``: iterable of (schema_idx, HostBatch) streamed to the worker.
    ``in_schemas``: schema per index (deserialization in the worker).
    Yields output HostBatches as they stream back.  The python-worker
    semaphore bounds concurrent workers; the device semaphore is released
    for the worker's lifetime.  A dead worker raises PythonWorkerError.
    """
    from spark_rapids_tpu.native_rt import (
        deserialize_host_batch, serialize_host_batch,
    )
    if not PYTHON_OOP_ENABLED.get(ctx.conf):
        with python_worker_slot(ctx):
            yield from task(iter(inputs))
        return

    with python_worker_slot(ctx):
        in_r, in_w = os.pipe()
        out_r, out_w = os.pipe()
        import warnings
        with warnings.catch_warnings():
            # deliberate: fork is the only way to ship arbitrary UDF
            # closures without cloudpickle; the child never touches JAX
            # or its locks (numpy/pandas/ctypes only) and exits via
            # os._exit, so the generic fork-vs-threads warnings from
            # python 3.12 and jax's at-fork hook do not apply
            warnings.filterwarnings("ignore", category=DeprecationWarning)
            warnings.filterwarnings("ignore", category=RuntimeWarning,
                                    message=".*fork.*")
            pid = os.fork()
        if pid == 0:  # ---- worker ----
            try:
                os.close(in_w)
                os.close(out_r)

                def input_iter():
                    while True:
                        hdr = _read_exact(in_r, 6)
                        if hdr is None or len(hdr) < 6:
                            return
                        tag, sidx, ln = struct.unpack("<BBI", hdr)
                        if tag == _MSG_END:
                            return
                        payload = _read_exact(in_r, ln) if ln else b""
                        yield sidx, deserialize_host_batch(
                            payload, in_schemas[sidx])

                for hb in task(input_iter()):
                    _write_frame(out_w, _MSG_BATCH, 0,
                                 serialize_host_batch(hb))
                _write_frame(out_w, _MSG_END, 0, b"")
                os._exit(0)
            except BaseException:
                import traceback
                try:
                    _write_frame(out_w, _MSG_ERROR, 0,
                                 traceback.format_exc().encode())
                except BaseException:
                    pass
                os._exit(1)

        # ---- engine side ----
        global last_worker_pid
        last_worker_pid = pid
        os.close(in_r)
        os.close(out_w)

        feed_error = []

        def feed():
            try:
                for sidx, hb in inputs:
                    _write_frame(in_w, _MSG_BATCH, sidx,
                                 serialize_host_batch(hb))
                _write_frame(in_w, _MSG_END, 0, b"")
            except BrokenPipeError:
                pass  # worker died; the read loop reports it
            except BaseException as e:  # UPSTREAM failure (scan, expr...)
                # must reach the consumer — a swallowed upstream error
                # would look like clean EOF to the worker and surface as
                # silently truncated results
                feed_error.append(e)
            finally:
                try:
                    os.close(in_w)
                except OSError:
                    pass

        feeder = threading.Thread(target=feed, daemon=True)
        feeder.start()
        reaped = False
        try:
            while True:
                hdr = _read_exact(out_r, 6)
                if hdr is None or len(hdr) < 6:
                    _, status = os.waitpid(pid, 0)
                    reaped = True
                    raise PythonWorkerError(
                        f"python worker {pid} died mid-stream "
                        f"(wait status {status})")
                tag, _sidx, ln = struct.unpack("<BBI", hdr)
                payload = _read_exact(out_r, ln) if ln else b""
                if ln and (payload is None or len(payload) < ln):
                    # header arrived but the payload didn't: the worker
                    # died mid-write — report death, not garbage frames
                    _, status = os.waitpid(pid, 0)
                    reaped = True
                    raise PythonWorkerError(
                        f"python worker {pid} died mid-frame "
                        f"(wait status {status})")
                if tag == _MSG_END:
                    os.waitpid(pid, 0)
                    reaped = True
                    feeder.join(timeout=5)
                    if feed_error:
                        raise feed_error[0]
                    return
                if tag == _MSG_ERROR:
                    os.waitpid(pid, 0)
                    reaped = True
                    raise PythonWorkerError(
                        "python worker task failed:\n" +
                        payload.decode(errors="replace"))
                yield deserialize_host_batch(payload, out_schema)
        finally:
            for fd in (out_r,):
                try:
                    os.close(fd)
                except OSError:
                    pass
            feeder.join(timeout=5)
            if not reaped:
                # consumer abandoned the stream: stop the worker
                try:
                    os.kill(pid, 9)
                except ProcessLookupError:
                    pass
                try:
                    os.waitpid(pid, 0)
                except ChildProcessError:
                    pass


def run_single_input_task(ctx, task, part, in_schema, out_schema):
    """Single-input-schema convenience over :func:`run_python_task` (the
    shape every non-cogrouped pandas exec uses)."""
    return run_python_task(ctx, task, ((0, hb) for hb in part),
                           [in_schema], out_schema)
