"""Persistent plan-fingerprint statistics store (history/).

The cross-query half of adaptive execution: at query end the session
appends one JSONL record of runtime facts keyed by the plan fingerprint
(per-exchange row/byte counts, observed skew, spill pressure, compile
wall); before the next execution of the same fingerprint the seeding
pass (history.seeding) reads the record back to make AQE v1's runtime
decisions up front.  The store is the RAPIDS qualification/profiling
store role folded into the engine itself.

Deliberately stdlib-only with no package-relative imports:
``tools/rapidshist.py`` loads this file standalone (the same
runtime-free discipline as ``rapidslint``/``rapidsprof``), so a store
written on a TPU host can be inspected and pruned on any laptop.

Layout: ``<dir>/stats.jsonl``, append-per-query, one JSON object per
line (schema below, ``docs/history.md``).  Loads are lazy, cached per
directory and invalidated on file (mtime, size) change; the newest
record per fingerprint wins.  All module state is lock-guarded — the
store is process-shared across sessions exactly like serve/excache.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

#: Schema version stamped into every record.  v2: records additionally
#: carry ``dispatches``/``shuffle_bytes`` and the loader folds a robust
#: per-fingerprint aggregate (median/MAD over recent runs) alongside the
#: newest-wins record — the regression sentinel's baseline.
STORE_VERSION = 2

#: File the store lives in, under spark.rapids.sql.tpu.history.dir.
STORE_FILENAME = "stats.jsonl"

#: Numeric record keys folded into the per-fingerprint aggregate.
AGGREGATE_KEYS = ("wall_ns", "dispatches", "compile_count",
                  "shuffle_bytes", "spill_host_bytes", "spill_disk_bytes")

#: Per-fingerprint bound on runs the loader retains for aggregation
#: (``history.aggregateRuns`` asks for at most this many).
AGG_MAX_RUNS = 32

#: Conf-key prefixes excluded from the plan-relevant conf signature —
#: observability, history, sentinel and fault-injection knobs never
#: change the plan (faults distort a run's RUNTIME, which is exactly
#: what the regression sentinel must see compared against the same
#: fingerprint's clean baseline, not forked into a separate one).
_SIG_EXCLUDE_PREFIXES = (
    "spark.rapids.sql.tpu.metrics.",
    "spark.rapids.sql.tpu.obs.",
    "spark.rapids.sql.tpu.history.",
    "spark.rapids.sql.tpu.sentinel.",
    "spark.rapids.sql.tpu.faults.",
)

_lock = threading.Lock()
#: dir -> (mtime_ns, size, {fp_hash: record}, {fp_hash: [recent runs]})
_cache: Dict[str, Tuple[int, int, Dict[str, dict],
                        Dict[str, List[dict]]]] = {}
_stats = {
    "history_store_queries": 0,
    "history_store_hits": 0,
    "history_store_appends": 0,
}


def fingerprint_hash(fingerprint: str) -> str:
    """Stable short hash of a plan-fingerprint string (store key)."""
    return hashlib.sha1(fingerprint.encode("utf-8")).hexdigest()[:16]


def conf_signature(settings: Iterable[Tuple[str, Any]]) -> str:
    """Hash of the plan-relevant conf items.

    Seeded decisions recorded under one configuration must not leak
    into sessions planned under another, so records carry this
    signature and lookups require it to match.  The
    ``_SIG_EXCLUDE_PREFIXES`` families are excluded — they never alter
    the plan.
    """
    items = sorted((k, str(v)) for k, v in settings
                   if not k.startswith(_SIG_EXCLUDE_PREFIXES))
    blob = "\x1f".join(f"{k}\x1e{v}" for k, v in items)
    return hashlib.sha1(blob.encode("utf-8")).hexdigest()[:16]


def store_path(dir_path: str) -> str:
    return os.path.join(dir_path, STORE_FILENAME)


def _parse_lines(path: str) -> List[dict]:
    records: List[dict] = []
    try:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail write — ignore the line
                if isinstance(rec, dict) and rec.get("fp"):
                    records.append(rec)
    except OSError:
        return []
    return records


def _fold(records: List[dict], max_records: int
          ) -> Tuple[Dict[str, dict], Dict[str, List[dict]]]:
    """(newest record per fingerprint, recent runs per fingerprint);
    overall bounded to max_records newest (file order is append order,
    so later lines are newer); per-fingerprint runs bounded to
    AGG_MAX_RUNS newest."""
    if max_records and max_records > 0:
        records = records[-max_records:]
    folded: Dict[str, dict] = {}
    runs: Dict[str, List[dict]] = {}
    for rec in records:  # later lines overwrite earlier ones
        fp = str(rec["fp"])
        folded[fp] = rec
        lst = runs.setdefault(fp, [])
        lst.append(rec)
        if len(lst) > AGG_MAX_RUNS:
            del lst[0]
    return folded, runs


def _load_all(dir_path: str, max_records: int = 0
              ) -> Tuple[Dict[str, dict], Dict[str, List[dict]]]:
    """Load (cached) both fold shapes for a store dir."""
    path = store_path(dir_path)
    try:
        st = os.stat(path)
        stamp = (st.st_mtime_ns, st.st_size)
    except OSError:
        with _lock:
            _cache.pop(dir_path, None)
        return {}, {}
    with _lock:
        cached = _cache.get(dir_path)
        if cached is not None and (cached[0], cached[1]) == stamp:
            return cached[2], cached[3]
    folded, runs = _fold(_parse_lines(path), max_records)
    with _lock:
        _cache[dir_path] = (stamp[0], stamp[1], folded, runs)
    return folded, runs


def load(dir_path: str, max_records: int = 0) -> Dict[str, dict]:
    """Load (cached) the folded {fp_hash: record} map for a store dir."""
    return _load_all(dir_path, max_records)[0]


def runs_for(dir_path: str, fp_hash: str, conf_sig: str = "",
             max_records: int = 0) -> List[dict]:
    """The retained recent runs of one fingerprint, oldest first,
    restricted to ``conf_sig`` when given (a run recorded under a
    different plan-relevant configuration is a different workload)."""
    runs = _load_all(dir_path, max_records)[1].get(fp_hash, [])
    if conf_sig:
        runs = [r for r in runs if r.get("conf_sig") == conf_sig]
    return runs


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    if not n:
        return 0.0
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def aggregate_records(recs: List[dict]) -> Dict[str, Any]:
    """Fold a run list into the sentinel's baseline shape:
    ``{"n": len(recs), "keys": {key: {"median", "mad"}}}`` for every
    AGGREGATE_KEYS key."""
    keys: Dict[str, Dict[str, float]] = {}
    for key in AGGREGATE_KEYS:
        vals = [float(r.get(key, 0) or 0) for r in recs]
        med = _median(vals)
        mad = _median([abs(v - med) for v in vals])
        keys[key] = {"median": med, "mad": mad}
    return {"n": len(recs), "keys": keys}


def aggregate(dir_path: str, fp_hash: str, conf_sig: str = "",
              runs: int = 8, max_records: int = 0) -> Dict[str, Any]:
    """Robust aggregate over the last ``runs`` retained runs of a
    fingerprint — the regression sentinel's baseline, also shown by
    ``rapidshist --json``."""
    recs = runs_for(dir_path, fp_hash, conf_sig, max_records)
    if runs and runs > 0:
        recs = recs[-runs:]
    return aggregate_records(recs)


def lookup(dir_path: str, fp_hash: str, conf_sig: str,
           max_age_sec: float = 0.0, max_records: int = 0,
           now: Optional[float] = None) -> Optional[dict]:
    """Fetch the newest fresh record for a fingerprint, or None.

    Freshness: the record's conf signature must equal ``conf_sig`` and,
    when ``max_age_sec > 0``, its timestamp must be within the horizon.
    A miss (absent or stale) is the seeding pass's signal to degrade to
    exactly the unseeded plan.
    """
    with _lock:
        _stats["history_store_queries"] += 1
    rec = load(dir_path, max_records).get(fp_hash)
    if rec is None:
        return None
    if conf_sig and rec.get("conf_sig") != conf_sig:
        return None
    if max_age_sec and max_age_sec > 0:
        ts = float(rec.get("ts", 0.0) or 0.0)
        if (now if now is not None else time.time()) - ts > max_age_sec:
            return None
    with _lock:
        _stats["history_store_hits"] += 1
    return rec


def append(dir_path: str, record: dict) -> None:
    """Append one query record; creates the dir/file on first write."""
    record = dict(record)
    record.setdefault("v", STORE_VERSION)
    record.setdefault("ts", time.time())
    path = store_path(dir_path)
    line = json.dumps(record, sort_keys=True, separators=(",", ":"))
    with _lock:
        os.makedirs(dir_path, exist_ok=True)
        with open(path, "a", encoding="utf-8") as f:
            f.write(line + "\n")
        _cache.pop(dir_path, None)  # force reload on next lookup
        _stats["history_store_appends"] += 1


def prune(dir_path: str, max_records: int) -> Tuple[int, int]:
    """Rewrite the store keeping the newest record per fingerprint,
    bounded to the ``max_records`` newest overall.  Returns
    (records_before, records_after).  Used by tools/rapidshist.py."""
    path = store_path(dir_path)
    records = _parse_lines(path)
    before = len(records)
    folded = _fold(records, max_records)[0]
    # preserve append order among survivors
    keep_ids = {id(rec) for rec in folded.values()}
    survivors = [rec for rec in records if id(rec) in keep_ids]
    with _lock:
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            for rec in survivors:
                f.write(json.dumps(rec, sort_keys=True,
                                   separators=(",", ":")) + "\n")
        os.replace(tmp, path)
        _cache.pop(dir_path, None)
    return before, len(survivors)


def stats() -> Dict[str, int]:
    """Process-cumulative store counters (serve stats() rollup keys)."""
    with _lock:
        return dict(_stats)


def reset_stats() -> None:
    with _lock:
        for k in _stats:
            _stats[k] = 0


def invalidate_cache(dir_path: Optional[str] = None) -> None:
    with _lock:
        if dir_path is None:
            _cache.clear()
        else:
            _cache.pop(dir_path, None)
