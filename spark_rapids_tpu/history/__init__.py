"""Query intelligence (history/): cross-query learning and reuse.

Three cooperating pieces, active only when a session sets
``spark.rapids.sql.tpu.history.dir`` (and ``history.enabled`` stays
true) — with the subsystem off, plans and behavior are byte-for-byte
the history-free engine's:

* :mod:`~spark_rapids_tpu.history.store` — the persistent JSONL
  statistics store: one record of runtime facts per plan fingerprint,
  appended at query end, read back lazily.  Stdlib-only so
  ``tools/rapidshist.py`` can load it runtime-free.
* :mod:`~spark_rapids_tpu.history.seeding` — history-seeded planning:
  partition sizing, skew pre-split and the broadcast build side decided
  up front from the previous run's record.
* :mod:`~spark_rapids_tpu.history.fragcache` — the cross-query fragment
  cache: materialized root fragments kept as catalog-registered
  spillables; a repeat query re-executes zero dispatches.

This module is the session-facing glue: ``begin_query`` (seed the plan,
arm the fragment key on the ExecContext) and ``end_query`` (append the
store record).  Both are single-conf-read no-ops when the subsystem is
inactive, and ``end_query`` never lets a store IO failure fail the
query that just produced rows.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

from spark_rapids_tpu.history import store


def history_dir(conf) -> Optional[str]:
    """The active store directory, or None when the subsystem is off."""
    from spark_rapids_tpu.config import HISTORY_DIR, HISTORY_ENABLED
    d = HISTORY_DIR.get(conf)
    if not d or not HISTORY_ENABLED.get(conf):
        return None
    return d


def input_identity(plan) -> Optional[str]:
    """Input-identity half of the fragment key: (path, mtime_ns, size)
    per scanned file — an overwritten input invalidates the fragment —
    and the id-stable batch holders for in-memory sources (sound because
    the cache entry's lifetime is tied to the logical plan's liveness,
    like serve/excache).  None (no caching) when an input went missing
    or a source kind is unknown to this walk."""
    from spark_rapids_tpu.plan.logical import FileScan, InMemoryScan, Range
    parts = []

    def rec(node) -> bool:
        if isinstance(node, FileScan):
            for p in node.paths:
                try:
                    st = os.stat(p)
                except OSError:
                    return False
                parts.append(f"file:{p}:{st.st_mtime_ns}:{st.st_size}")
        elif isinstance(node, InMemoryScan):
            for b in node.batches:
                parts.append(f"mem:{id(b):x}")
        elif isinstance(node, Range):
            parts.append(f"range:{node.start}:{node.end}:{node.step}")
        return all(rec(c) for c in node.children)

    if not rec(plan):
        return None
    return "|".join(parts)


def predicted_wall_ns(conf, fp_hash: str, conf_sig: str,
                      min_runs: int = 3,
                      mad_k: float = 3.0) -> Optional[float]:
    """Sentinel-style latency prediction for front-door admission
    control (serve.frontend): median + ``mad_k`` * MAD of the history
    store's recorded wall_ns for this (fingerprint, conf-signature).
    None — never shed — when the history subsystem is off, the baseline
    is thinner than ``min_runs``, or the recorded medians are zero."""
    from spark_rapids_tpu.config import (
        HISTORY_AGGREGATE_RUNS, HISTORY_STORE_MAX_RECORDS,
    )
    d = history_dir(conf)
    if d is None:
        return None
    agg = store.aggregate(
        d, fp_hash, conf_sig,
        runs=HISTORY_AGGREGATE_RUNS.get(conf),
        max_records=HISTORY_STORE_MAX_RECORDS.get(conf))
    if agg.get("n", 0) < max(1, int(min_runs)):
        return None
    wall = agg.get("keys", {}).get("wall_ns") or {}
    median = float(wall.get("median", 0.0))
    if median <= 0:
        return None
    return median + float(mad_k) * float(wall.get("mad", 0.0))


def begin_query(session, plan, phys, ctx) -> None:
    """Arm the history hooks for one execution: consult the store to
    seed the physical plan (once per plan object) and put the fragment
    key on the context for collect_host/pipeline_collect."""
    conf = session.conf
    d = history_dir(conf)
    if d is None:
        return
    from spark_rapids_tpu.config import (
        HISTORY_FRAGMENTS_ENABLED, HISTORY_FRAGMENTS_MAX_BYTES,
        HISTORY_FRAGMENTS_MAX_ENTRIES, HISTORY_MAX_AGE_SEC,
        HISTORY_SEED_ENABLED, HISTORY_STORE_MAX_RECORDS,
    )
    from spark_rapids_tpu.plan.logical import plan_fingerprint
    fp_hash = store.fingerprint_hash(plan_fingerprint(plan))
    conf_sig = store.conf_signature(conf._settings.items())
    ctx._history_dir = d
    ctx._history_fp = fp_hash
    ctx._history_conf_sig = conf_sig
    if HISTORY_SEED_ENABLED.get(conf) and \
            not getattr(phys, "_history_seeded", False):
        # once per (process-shared) physical plan object: re-seeding a
        # later run would change split shapes and recompile programs the
        # first run already paid for
        phys._history_seeded = True
        ctx.metric("history", "statsStoreQueries").add(1)
        rec = store.lookup(
            d, fp_hash, conf_sig,
            max_age_sec=HISTORY_MAX_AGE_SEC.get(conf),
            max_records=HISTORY_STORE_MAX_RECORDS.get(conf))
        if rec is not None:
            from spark_rapids_tpu.history import seeding
            seeding.seed(phys, rec, ctx)
    if HISTORY_FRAGMENTS_ENABLED.get(conf) and session.runtime is not None:
        from spark_rapids_tpu.history.fragcache import fragment_cache
        fragment_cache().configure(
            HISTORY_FRAGMENTS_MAX_ENTRIES.get(conf),
            HISTORY_FRAGMENTS_MAX_BYTES.get(conf))
        sig = input_identity(plan)
        if sig is not None:
            ctx._history_frag_key = (fp_hash, conf_sig, sig)


def end_query(session, plan, phys, ctx, metrics: Dict[str, Any],
              wall_ns: int, out) -> List[Dict[str, Any]]:
    """Append this query's record to the store and run the regression
    sentinel against the store's aggregate of previous runs.  Returns
    the sentinel's alert list (empty when inactive, thin baseline, or
    in band).  The comparison runs BEFORE the append so a regressed run
    never poisons its own baseline; a store IO failure never fails the
    query that just produced rows."""
    d = getattr(ctx, "_history_dir", None)
    if d is None:
        return []
    from spark_rapids_tpu.history import seeding
    rec = seeding.harvest(phys, metrics, wall_ns,
                          getattr(out, "num_rows", 0),
                          ctx._history_fp, ctx._history_conf_sig)
    alerts: List[Dict[str, Any]] = []
    conf = session.conf
    from spark_rapids_tpu.config import (
        HISTORY_AGGREGATE_RUNS, HISTORY_STORE_MAX_RECORDS,
        SENTINEL_ENABLED, SENTINEL_MAD_THRESHOLD, SENTINEL_MIN_RUNS,
    )
    if SENTINEL_ENABLED.get(conf):
        from spark_rapids_tpu.obs import events as obs_events
        from spark_rapids_tpu.obs import sentinel
        agg = store.aggregate(
            d, ctx._history_fp, ctx._history_conf_sig,
            runs=HISTORY_AGGREGATE_RUNS.get(conf),
            max_records=HISTORY_STORE_MAX_RECORDS.get(conf))
        alerts = sentinel.check(rec, agg,
                                SENTINEL_MAD_THRESHOLD.get(conf),
                                SENTINEL_MIN_RUNS.get(conf))
        for alert in alerts:
            obs_events.emit_instant("history", "regression",
                                    ctx._history_fp, **alert)
    try:
        store.append(d, rec)
    except OSError:
        pass
    return alerts


def runtime_stats() -> Dict[str, int]:
    """Store + fragment-cache counters for the serve stats() rollup."""
    out = dict(store.stats())
    from spark_rapids_tpu.history.fragcache import fragment_cache
    out.update(fragment_cache().stats())
    from spark_rapids_tpu.obs import sentinel
    out["regression_alerts_total"] = sentinel.alerts_total()
    return out
