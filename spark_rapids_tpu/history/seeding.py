"""History-seeded planning (history/).

AQE v1 (plan/adaptive) re-discovers partition sizing, skew and the
broadcast build side from runtime statistics on EVERY run; this module
makes those decisions up front from the statistics store's record of a
previous run of the same (plan fingerprint, conf signature):

* **Shuffle partition sizing**: an exchange whose recorded total bytes
  fit in fewer partitions than the static count gets its partitioning
  right-sized to ``ceil(bytes / coalesce target)`` before the split
  runs — the first shuffle produces the coalesced layout directly, so
  runtime coalescing has nothing left to merge (this is also the
  bucket-policy lever: fewer partition counts means fewer compiled
  split shapes).  Hash/round-robin only; range needs its sampled
  bounds and mesh/collapse-local exchanges don't split by count.
* **Skew pre-split**: recorded per-partition bytes that flag as skewed
  under the adaptive thresholds mark the exchange
  (``_history_skew``); the consuming join ORs the marks into
  plan_groups' runtime flags, so the skewed partition is isolated and
  chunk-streamed from the first run.
* **Broadcast build side**: a join that switched to broadcast last run
  records the winning side; the hint (``_history_bc_side``) reorders
  the side probe so the switch materializes the right exchange first.

Every applied decision bumps ``historySeededDecisions`` and emits an
obs instant (site ``history``).  Seeding runs AT MOST ONCE per physical
plan object (the plan is process-shared via serve/excache — re-seeding
a later execution would change split shapes and recompile), and a
stats-absent or stats-stale store seeds nothing: the plan stays
byte-for-byte the unseeded one.

Harvest is the write half: after a query the session folds the facts
the engine already holds on the host (per-exchange ``_last_part_*``
recorded by the shuffle split's one bulk sync, the join's switch cache,
the metrics frame) into one store record — zero extra device syncs.
"""

from __future__ import annotations

import copy
from typing import List, Tuple


def _preorder(op) -> List[Tuple[str, object]]:
    """(path, op) per node, path = ``<preorder index>:<type name>`` —
    stable across processes for one (fingerprint, conf) plan shape."""
    out: List[Tuple[str, object]] = []

    def rec(node):
        out.append((f"{len(out)}:{type(node).__name__}", node))
        for c in node.children:
            rec(c)

    rec(op)
    return out


def _note(ctx, op_id: str, mechanism: str, **fields) -> None:
    ctx.metric(op_id, "historySeededDecisions").add(1)
    from spark_rapids_tpu.obs import events as obs_events
    obs_events.emit_instant("history", mechanism, op_id, **fields)


def seed(phys, record: dict, ctx) -> int:
    """Apply a store record's decisions to ``phys``; returns how many
    were applied.  Mutations are confined to the physical plan (a copied
    partitioning object, hint attributes) — the logical plan and its
    fingerprint are untouched."""
    from spark_rapids_tpu.ops.tpu_exec import TpuShuffledHashJoinExec
    from spark_rapids_tpu.parallel.exchange import TpuShuffleExchangeExec
    from spark_rapids_tpu.parallel.partitioning import (
        HashPartitioning, RoundRobinPartitioning,
    )
    from spark_rapids_tpu.plan import adaptive as _adaptive
    exchanges = {e.get("path"): e for e in record.get("exchanges", ())}
    joins = {j.get("path"): j for j in record.get("joins", ())}
    applied = 0
    for path, op in _preorder(phys):
        if isinstance(op, TpuShuffleExchangeExec):
            rec = exchanges.get(path)
            if rec is None:
                continue
            sizes = rec.get("bytes") or []
            if op._mesh_active(ctx) or op._collapse_local(ctx):
                continue
            n = op.partitioning.num_partitions
            if len(sizes) != n or n <= 1:
                continue
            target = max(1, _adaptive.target_bytes(ctx))
            want = max(1, -(-sum(sizes) // target))  # ceil
            if want < n and isinstance(
                    op.partitioning,
                    (HashPartitioning, RoundRobinPartitioning)):
                # copy before mutating: partitioning objects can be
                # shared with the logical plan, and the fingerprint must
                # keep describing the UNSEEDED shape
                p = copy.copy(op.partitioning)
                p.num_partitions = want
                op.partitioning = p
                _note(ctx, op.op_id, "seed_partitions",
                      before=n, after=want)
                applied += 1
            else:
                flags = _adaptive.skew_flags(ctx, list(sizes), "bytes")
                if any(flags):
                    op._history_skew = flags
                    _note(ctx, op.op_id, "seed_skew",
                          partitions=sum(1 for f in flags if f))
                    applied += 1
        elif isinstance(op, TpuShuffledHashJoinExec):
            rec = joins.get(path)
            side = rec.get("bc_side") if rec else None
            if side in ("left", "right"):
                op._history_bc_side = side
                _note(ctx, op.op_id, "seed_broadcast", side=side)
                applied += 1
    return applied


def harvest(phys, metrics: dict, wall_ns: int, out_rows: int,
            fp_hash: str, conf_sig: str) -> dict:
    """Fold one finished query's host-known runtime facts into a store
    record (history.store schema v1)."""
    from spark_rapids_tpu.ops.tpu_exec import TpuShuffledHashJoinExec
    from spark_rapids_tpu.parallel.exchange import TpuShuffleExchangeExec
    exchanges = []
    joins = []
    for path, op in _preorder(phys):
        if isinstance(op, TpuShuffleExchangeExec):
            rows = getattr(op, "_last_part_rows", None)
            nbytes = getattr(op, "_last_part_bytes", None)
            if rows is None and nbytes is None:
                continue
            exchanges.append({
                "path": path,
                "parts": len(nbytes if nbytes is not None else rows),
                "rows": [int(v) for v in rows] if rows else [],
                "bytes": [int(v) for v in nbytes] if nbytes else [],
            })
        elif isinstance(op, TpuShuffledHashJoinExec):
            cached = getattr(op, "_switch_cache", None)
            if cached is not None:
                joins.append({"path": path, "bc_side": cached[2]})

    def m(key):
        return int(metrics.get(key, 0) or 0)

    return {
        "fp": fp_hash,
        "conf_sig": conf_sig,
        "wall_ns": int(wall_ns),
        "out_rows": int(out_rows),
        "dispatches": m("dispatchCount"),
        "compile_count": m("compileCount"),
        "compile_wall_ns": m("compileWallNs"),
        "shuffle_bytes": m("shuffleBytes"),
        "spill_host_bytes": m("spillToHostBytes"),
        "spill_disk_bytes": m("spillToDiskBytes"),
        "exchanges": exchanges,
        "joins": joins,
    }
