"""Cross-query fragment cache (history/).

A process-wide, fingerprint-keyed cache of materialized root fragments:
when a whole-pipeline collect (plan/pipeline.pipeline_collect) finishes
a query whose session runs with a history dir, the fresh device outputs
are registered in the spill catalog (PRIORITY_FRAGMENT — the most
spillable band, so cached fragments yield HBM before any live query
data) and kept under a key of

    (plan fingerprint hash, plan-relevant conf signature, input identity)

where input identity is (path, mtime_ns, size) per scanned file and the
id-stable in-memory holders for InMemoryScan sources.  A repeat query
with the same key skips the whole subtree: ``collect_host`` serves the
cached batches straight through D2H — zero dispatches, zero compiles,
bit-identical rows (the cached device batches ARE the cold run's
outputs; host<->device round trips through the spill tiers preserve
them exactly).

Entries are never pinned: the batches ride the device->host->disk spill
tiers under catalog pressure like any other spillable, and the cache
itself is LRU-bounded by entry count and payload bytes
(``spark.rapids.sql.tpu.history.fragments.*``).  Each entry records the
device generation it was built under; a device-lost recovery bumps the
generation (runtime.device.DeviceRuntime.recover) and the next lookup
drops the entry and recomputes from lineage — same contract as the
exchange split cache.  Entry lifetime is also tied to the LOGICAL
plan's liveness via weakref (exactly serve/excache's discipline), which
keeps the id()-keyed parts of the fingerprint and input identity sound.

Thread safety: bookkeeping under one lock; batch materialization,
registration and victim closing run outside it.
"""

from __future__ import annotations

import threading
import weakref
from collections import OrderedDict
from typing import Any, List, Optional, Tuple

DEFAULT_MAX_ENTRIES = 64
DEFAULT_MAX_BYTES = 256 << 20


class _Fragment:
    __slots__ = ("plan_ref", "handles", "generation", "nbytes")

    def __init__(self, plan_ref, handles, generation, nbytes):
        self.plan_ref = plan_ref
        self.handles = handles
        self.generation = generation
        self.nbytes = nbytes


class FragmentCache:
    """LRU of materialized fragments, shared by every session."""

    def __init__(self, max_entries: int = DEFAULT_MAX_ENTRIES,
                 max_bytes: int = DEFAULT_MAX_BYTES):
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Any, _Fragment]" = OrderedDict()
        self._max_entries = max(1, int(max_entries))
        self._max_bytes = int(max_bytes)
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    @staticmethod
    def _ref(plan: Any):
        try:
            return weakref.ref(plan)
        except TypeError:
            return lambda: plan

    def configure(self, max_entries: int, max_bytes: int) -> None:
        with self._lock:
            self._max_entries = max(1, int(max_entries))
            self._max_bytes = int(max_bytes)
            victims = self._evict_locked()
        self._close_all(victims)

    # -- internal -----------------------------------------------------------

    def _evict_locked(self) -> List[_Fragment]:
        """Collect LRU victims past either bound (and dead-plan entries);
        caller closes them OUTSIDE the lock."""
        victims: List[_Fragment] = []
        dead = [k for k, e in self._entries.items() if e.plan_ref() is None]
        for k in dead:
            victims.append(self._entries.pop(k))
        total = sum(e.nbytes for e in self._entries.values())
        while self._entries and (
                len(self._entries) > self._max_entries
                or total > max(0, self._max_bytes)):
            _, ent = self._entries.popitem(last=False)
            total -= ent.nbytes
            victims.append(ent)
            self.evictions += 1
        return victims

    @staticmethod
    def _close_all(fragments: List[_Fragment]) -> None:
        for ent in fragments:
            for h in ent.handles:
                h.close()

    # -- public -------------------------------------------------------------

    def fetch(self, key: Any, ctx) -> Optional[List]:
        """Materialized device batches for ``key``, or None on miss.

        A hit re-hydrates the cached handles (overlapped unspill via the
        catalog prefetcher) WITHOUT taking device admission — the caller
        only runs D2H on the result.  Generation mismatch or a
        DeviceLostError during rehydration drops the entry (recompute
        from lineage) and reports a miss."""
        from spark_rapids_tpu.runtime.device import DeviceRuntime
        gen_now = DeviceRuntime.generation()
        with self._lock:
            ent = self._entries.get(key)
            if ent is not None and (ent.plan_ref() is None
                                    or ent.generation != gen_now):
                del self._entries[key]
                self.misses += 1
                stale = ent
            elif ent is None:
                self.misses += 1
                return None
            else:
                self._entries.move_to_end(key)
                stale = None
        if stale is not None:
            self._close_all([stale])
            return None
        from spark_rapids_tpu.plan.physical import prefetch_spillables
        try:
            devs = list(prefetch_spillables(ent.handles, depth=1))
        except Exception:
            # DeviceLostError (generation raced past the check), a handle
            # closed by a concurrent eviction, an unspill failure — every
            # rehydration failure degrades the same way: drop the entry
            # and let the caller recompute from lineage

            with self._lock:
                if self._entries.get(key) is ent:
                    del self._entries[key]
                self.misses += 1
            self._close_all([ent])
            return None
        with self._lock:
            self.hits += 1
        ctx.metric("history", "fragmentCacheHits").add(1)
        ctx.metric("history", "fragmentCacheBytes").add(ent.nbytes)
        from spark_rapids_tpu.obs import events as obs_events
        obs_events.emit_instant("history", "fragment_hit", "history",
                                bytes=ent.nbytes, batches=len(devs))
        return devs

    def insert(self, key: Any, plan: Any, outs: List, ctx) -> bool:
        """Adopt a finished collect's device outputs under ``key``.

        Registers every batch as a catalog spillable (PRIORITY_FRAGMENT)
        so the payload rides the spill tiers under pressure; first
        insert wins on a race.  Returns False when insertion is
        disabled (maxBytes <= 0) or the key is already cached."""
        from spark_rapids_tpu.runtime.device import DeviceRuntime
        with self._lock:
            if self._max_bytes <= 0:
                return False
            ent = self._entries.get(key)
            if ent is not None and ent.plan_ref() is not None:
                return False
        cat = DeviceRuntime.get(ctx.conf).catalog
        from spark_rapids_tpu.mem.catalog import (
            PRIORITY_FRAGMENT, device_batch_bytes,
        )
        handles = []
        nbytes = 0
        for b in outs:
            nbytes += device_batch_bytes(b)
            handles.append(cat.register(b, priority=PRIORITY_FRAGMENT))
        ent = _Fragment(self._ref(plan), handles,
                        DeviceRuntime.generation(), nbytes)
        with self._lock:
            prior = self._entries.get(key)
            if prior is not None and prior.plan_ref() is not None:
                loser: Optional[_Fragment] = ent  # racer won; drop ours
                victims: List[_Fragment] = []
            else:
                if prior is not None:
                    self._entries.pop(key)
                    victims = [prior]
                else:
                    victims = []
                loser = None
                self._entries[key] = ent
                self._entries.move_to_end(key)
                victims.extend(self._evict_locked())
        if loser is not None:
            self._close_all([loser])
            return False
        self._close_all(victims)
        return True

    def drop(self, key: Any) -> None:
        with self._lock:
            ent = self._entries.pop(key, None)
        if ent is not None:
            self._close_all([ent])

    def clear(self) -> None:
        with self._lock:
            victims = list(self._entries.values())
            self._entries.clear()
        self._close_all(victims)

    def stats(self):
        with self._lock:
            return {
                "fragment_cache_entries": len(self._entries),
                "fragment_cache_bytes": sum(
                    e.nbytes for e in self._entries.values()),
                "fragment_cache_hits": self.hits,
                "fragment_cache_misses": self.misses,
                "fragment_cache_evictions": self.evictions,
            }

    def __len__(self):
        with self._lock:
            return len(self._entries)


_SHARED: FragmentCache = FragmentCache()


def fragment_cache() -> FragmentCache:
    """The process singleton (serve/excache.shared_plan_cache analogue)."""
    return _SHARED
