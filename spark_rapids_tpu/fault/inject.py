"""Deterministic fault injection.

Conf ``spark.rapids.sql.tpu.faults.spec`` names faults to fire at
instrumented sites, e.g.::

    dispatch:oom@3;d2h:device_lost@1;spill:slow=200ms@2

Grammar (entries joined by ``;``)::

    entry    := site ":" kind ["=" duration] "@" n ["+"]
    site     := dispatch | h2d | d2h | spill | unspill | exchange | scan
                | mesh
    kind     := oom | device_lost | slow
    duration := <float> ("ms" | "s")     (slow only; default ms)
    n        := 1-based call index at that site; "+" = that call AND
                every call after it (persistent fault — used to exhaust
                device replays and force the CPU fallback)

Counters are per-site and reset every ``session.execute`` (the spec is
re-installed per query), so "the 3rd dispatch" is deterministic within a
query regardless of what ran before.  Injected errors carry an explicit
``rapids_error_class`` so they classify exactly as the spec says without
string matching.

Sites are wired where real faults strike: ``instrumented_jit`` dispatch
(utils.compile_registry), ``host_to_device`` / ``device_to_host_many``
(batch.py), catalog spill and unspill (mem.catalog — ``spill`` fires on
the async writer thread and the error surfaces at the consumer's
``get()``; ``unspill`` fires on the rehydration path), the shuffle
exchange split (parallel.exchange), the v2 scan's per-chunk decode
submission (io.scan_v2) and the fused mesh-SPMD stage dispatch
(parallel.mesh_spmd — ``mesh`` fires before the whole-stage program
launches, so device-lost replays the full producer+exchange+consumer
segment from lineage).  The disarmed fast path is one module-global
``is None`` test per call.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from spark_rapids_tpu.fault import metrics as fault_metrics
from spark_rapids_tpu.fault.errors import ErrorClass
from spark_rapids_tpu.obs import events as obs_events

SITES = ("dispatch", "h2d", "d2h", "spill", "unspill", "exchange", "scan",
         "mesh")
KINDS = ("oom", "device_lost", "slow")


class InjectedFault(Exception):
    """An error fired by the fault registry; classification is explicit
    via ``rapids_error_class`` (no message sniffing)."""

    def __init__(self, message: str, error_class: ErrorClass):
        super().__init__(message)
        self.rapids_error_class = error_class


class _Rule:
    __slots__ = ("site", "kind", "at", "persistent", "duration_s")

    def __init__(self, site: str, kind: str, at: int, persistent: bool,
                 duration_s: float):
        self.site = site
        self.kind = kind
        self.at = at
        self.persistent = persistent
        self.duration_s = duration_s

    def matches(self, count: int) -> bool:
        return count == self.at or (self.persistent and count > self.at)

    def __repr__(self):
        arm = f"@{self.at}{'+' if self.persistent else ''}"
        dur = f"={self.duration_s * 1000:g}ms" if self.kind == "slow" else ""
        return f"{self.site}:{self.kind}{dur}{arm}"


def parse_spec(spec: str) -> List[_Rule]:
    """Parse a faults.spec string; raises ValueError on bad grammar so a
    typo'd spec fails the query loudly instead of silently injecting
    nothing."""
    rules: List[_Rule] = []
    for raw in (spec or "").split(";"):
        entry = raw.strip()
        if not entry:
            continue
        try:
            site, rest = entry.split(":", 1)
            kindspec, at = rest.rsplit("@", 1)
            persistent = at.endswith("+")
            n = int(at[:-1] if persistent else at)
            kind, _, arg = kindspec.partition("=")
            site, kind = site.strip(), kind.strip()
            if site not in SITES:
                raise ValueError(f"unknown site {site!r} (one of {SITES})")
            if kind not in KINDS:
                raise ValueError(f"unknown kind {kind!r} (one of {KINDS})")
            if n < 1:
                raise ValueError("call index must be >= 1")
            duration_s = 0.0
            if kind == "slow":
                a = arg.strip().lower() or "100ms"
                if a.endswith("ms"):
                    duration_s = float(a[:-2]) / 1000.0
                elif a.endswith("s"):
                    duration_s = float(a[:-1])
                else:
                    duration_s = float(a) / 1000.0
            elif arg:
                raise ValueError(f"kind {kind!r} takes no argument")
            rules.append(_Rule(site, kind, n, persistent, duration_s))
        except ValueError as e:
            raise ValueError(
                f"bad faults.spec entry {entry!r}: {e} "
                f"(grammar: site:kind[=dur]@N[+])") from None
    return rules


class FaultRegistry:
    def __init__(self, rules: List[_Rule]):
        self._rules: Dict[str, List[_Rule]] = {}
        for r in rules:
            self._rules.setdefault(r.site, []).append(r)
        self._counts: Dict[str, int] = {}
        self._lock = threading.Lock()

    def fire(self, site: str) -> Optional[Tuple[_Rule, int]]:
        """Count one call at ``site``; the matching rule (if any) and the
        call index."""
        with self._lock:
            count = self._counts.get(site, 0) + 1
            self._counts[site] = count
            for r in self._rules.get(site, ()):
                if r.matches(count):
                    return r, count
        return None


_ACTIVE: Optional[FaultRegistry] = None
_INSTALL_LOCK = threading.Lock()


def install(spec: str) -> None:
    """(Re)install the registry from a spec string; empty/None clears it.
    Counters reset on every install, so each query sees a deterministic
    call numbering.

    Inside a query scope (session.execute installs after opening one)
    the registry lives ON the scope, so concurrent queries each see only
    their own session's faults.spec — one query's injected OOMs cannot
    fire into a neighbor.  Outside any scope (tests arming a site
    directly, staging paths like ml.to_device_batches) the registry is
    the process-global one, exactly the historical semantics."""
    global _ACTIVE
    rules = parse_spec(spec)
    reg = FaultRegistry(rules) if rules else None
    sc = obs_events.current_scope()
    if sc is not None:
        sc.fault_registry = reg
        return
    with _INSTALL_LOCK:
        _ACTIVE = reg


def uninstall() -> None:
    install("")


def active() -> bool:
    sc = obs_events.current_scope()
    if sc is not None and sc.fault_registry is not None:
        return True
    return _ACTIVE is not None


def maybe_fire(site: str) -> None:
    """Hot-path hook: no-op (one scope probe + ``is None`` test) unless
    a spec is installed.  A matching rule raises :class:`InjectedFault`
    (oom / device_lost) or sleeps (slow).
    """
    sc = obs_events.current_scope()
    reg = sc.fault_registry if sc is not None else _ACTIVE
    if reg is None:
        return
    hit = reg.fire(site)
    if hit is None:
        return
    rule, count = hit
    fault_metrics.record("faults_injected")
    obs_events.emit_instant("fault", "injected", at_site=site,
                            kind=rule.kind, count=count)
    if rule.kind == "oom":
        raise InjectedFault(
            f"RESOURCE_EXHAUSTED: injected OOM at {site} call {count} "
            f"({rule!r})", ErrorClass.RETRYABLE_OOM)
    if rule.kind == "device_lost":
        raise InjectedFault(
            f"INTERNAL: injected device loss (worker crashed) at {site} "
            f"call {count} ({rule!r})", ErrorClass.DEVICE_LOST)
    # slow: sleep in small slices so a deadline watchdog's async
    # PartitionTimeout lands within ~10ms of expiry instead of after the
    # whole stall (one big C-level sleep defers delivery to its end)
    deadline = time.monotonic() + rule.duration_s
    while True:
        left = deadline - time.monotonic()
        if left <= 0:
            return
        time.sleep(min(0.01, left))
