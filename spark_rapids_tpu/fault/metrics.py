"""Process-wide fault-tolerance counters.

Same snapshot/delta shape as utils.compile_registry: cumulative counters
under a lock; ``session.execute`` snapshots around each query and writes
the deltas into ``last_metrics`` (``retryCount``, ``backoffWallNs``,
``deviceLostCount``, ``partitionFallbackCount``, ``faultsInjected``).
"""

from __future__ import annotations

import threading
from typing import Dict

from spark_rapids_tpu.obs import events as obs_events

_LOCK = threading.Lock()
_STATS: Dict[str, int] = {
    "retries": 0,              # recovery-level replays (any class)
    "backoff_wall_ns": 0,      # wall ns slept in retry backoff
    "device_lost": 0,          # DEVICE_LOST-classified errors handled
    "partition_fallbacks": 0,  # partitions completed via the CPU path
    "faults_injected": 0,      # deterministic faults fired (inject.py)
}


def record(key: str, n: int = 1) -> None:
    with _LOCK:
        _STATS[key] += n
    # per-query attribution: fault counters also credit the executing
    # query's scope so concurrent queries don't read each other's
    # retries/faults out of the global delta
    obs_events.scope_add(key, n)
    # timeline entries for count-shaped keys (wall accumulations like
    # backoff_wall_ns already have their own spans at the call site)
    if key == "retries":
        obs_events.emit_instant("retry", "attempt")
    elif key in ("device_lost", "partition_fallbacks"):
        obs_events.emit_instant("fault", key)


def snapshot() -> Dict[str, int]:
    with _LOCK:
        return dict(_STATS)


def delta(before: Dict[str, int], after: Dict[str, int]) -> Dict[str, int]:
    return {k: after[k] - before.get(k, 0) for k in after}
