"""Fault-tolerance subsystem.

The reference engine treats fallback-on-failure as co-equal with the
kernels: anything the GPU cannot finish must still produce the Spark CPU
answer (SURVEY.md section 5 delegates failure *detection* to Spark task
retry + lineage).  This package gives the TPU engine the same posture,
organized in five pieces:

* :mod:`~spark_rapids_tpu.fault.errors` — error taxonomy.  Every raised
  error classifies as ``RETRYABLE_OOM`` (RESOURCE_EXHAUSTED allocation
  failures), ``DEVICE_LOST`` (XLA worker crashed/restarted, kernel
  faults, DATA_LOSS/INTERNAL status codes, partition deadline expiry) or
  ``NON_RETRYABLE`` (user errors, donated-dispatch OOM,
  KeyboardInterrupt/SystemExit — never retried).
* :mod:`~spark_rapids_tpu.fault.retry` — ONE :class:`RetryPolicy`
  (conf ``spark.rapids.sql.tpu.retry.maxAttempts`` /
  ``retry.backoffMs``; exponential backoff with deterministic
  per-attempt delays — no randomness, the delay is a pure function of
  the attempt index) behind every retry loop in the engine.  The old
  hand-rolled loops (``mem.catalog.run_with_oom_retry``,
  ``plan.physical.run_partition_with_retry``) are now thin wrappers.
* :mod:`~spark_rapids_tpu.fault.watchdog` — per-partition deadline
  (conf ``spark.rapids.sql.tpu.partition.timeoutSec``): a monitor
  thread raises a classified :class:`PartitionTimeout` into the driving
  thread instead of letting a wedged dot hang the suite for 40 minutes
  (round-5 VERDICT evidence).
* :mod:`~spark_rapids_tpu.fault.recovery` — device-lost recovery:
  reset the :class:`DeviceRuntime`, invalidate the spill catalog's
  device tier (host/disk copies survive and re-upload lazily), replay
  the failed partition; after ``retry.maxAttempts`` device replays,
  re-run just that partition through the CPU operator path (conf
  ``spark.rapids.sql.tpu.fallback.onDeviceError``) so the query still
  completes with Spark-CPU-identical results — per-partition fallback,
  never whole-query abort.
* :mod:`~spark_rapids_tpu.fault.inject` — deterministic fault injection
  (conf ``spark.rapids.sql.tpu.faults.spec``, e.g.
  ``"dispatch:oom@3;d2h:device_lost@1;spill:slow=200ms@2"``) wired into
  the dispatch, h2d, d2h, spill and exchange sites, so every recovery
  path is exercised in tier-1 without real hardware faults.

Per-query counters (``retryCount``, ``backoffWallNs``,
``deviceLostCount``, ``partitionFallbackCount``, ``faultsInjected``)
ride the same snapshot/delta machinery as the compile/dispatch metrics
(utils.compile_registry) into ``session.last_metrics`` and bench JSON.
"""

from spark_rapids_tpu.fault.errors import (  # noqa: F401
    DeviceLostError, ErrorClass, PartitionTimeout, classify_error,
)
from spark_rapids_tpu.fault.inject import InjectedFault  # noqa: F401
from spark_rapids_tpu.fault.retry import RetryPolicy  # noqa: F401
from spark_rapids_tpu.fault.watchdog import partition_deadline  # noqa: F401
