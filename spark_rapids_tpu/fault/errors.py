"""Error taxonomy: every raised error maps to one retry class.

The reference engine distinguishes retryable allocation failures
(RmmRapidsRetryIterator's RetryOOM/SplitAndRetryOOM) from fatal device
state loss (executor death -> Spark task retry on another executor).
XLA surfaces both through the same ``XlaRuntimeError`` channel, carrying
the ABSL status-code name in the message — classification is therefore
by status code + message shape, with an explicit escape hatch: an error
object carrying a ``rapids_error_class`` attribute (set by the fault
injector and by the donated-dispatch fail-fast path) classifies as
exactly that.
"""

from __future__ import annotations

import enum


class ErrorClass(enum.Enum):
    #: RESOURCE_EXHAUSTED allocation failures: spill-and-retry is sound.
    RETRYABLE_OOM = "retryable_oom"
    #: The device (or its runtime) is gone or wedged: XLA worker
    #: crashed/restarted, kernel fault, DATA_LOSS/INTERNAL/UNAVAILABLE
    #: status, or a partition deadline expiry.  Recovery = runtime reset
    #: + device-tier invalidation + replay, then per-partition CPU
    #: fallback.
    DEVICE_LOST = "device_lost"
    #: User errors, donated-dispatch OOM (inputs consumed at dispatch — a
    #: retry cannot re-present them), KeyboardInterrupt/SystemExit.
    #: Never retried.
    NON_RETRYABLE = "non_retryable"


class PartitionTimeout(RuntimeError):
    """A partition exceeded ``spark.rapids.sql.tpu.partition.timeoutSec``.

    Raised asynchronously into the driving thread by the deadline
    watchdog; classifies as DEVICE_LOST (a wedged device is
    indistinguishable from a lost one — recovery resets and replays)."""

    rapids_error_class = ErrorClass.DEVICE_LOST


class DeviceLostError(RuntimeError):
    """Raised by a spillable handle whose device-tier data did not
    survive a device loss (no host/disk copy existed to rescue)."""

    rapids_error_class = ErrorClass.DEVICE_LOST


#: XLA status-code names that mean the device/runtime is gone, and
#: message fragments the TPU runtime emits on worker death (the SF1 q2
#: crash shape from round 5).
_DEVICE_LOST_CODES = ("DATA_LOSS", "INTERNAL", "UNAVAILABLE", "ABORTED")
_DEVICE_LOST_FRAGMENTS = ("worker crashed", "worker restarted",
                          "kernel fault", "device lost", "device failed")

#: Exception type names jax raises for XLA runtime failures (the string
#: check mirrors mem.catalog.is_device_oom: the classes live in private
#: jaxlib modules that move between versions).
_XLA_ERROR_TYPES = ("XlaRuntimeError", "JaxRuntimeError")


def classify_error(err: BaseException) -> ErrorClass:
    """Map a raised error to its :class:`ErrorClass`."""
    if not isinstance(err, Exception):
        # KeyboardInterrupt / SystemExit / GeneratorExit: never retried
        return ErrorClass.NON_RETRYABLE
    explicit = getattr(err, "rapids_error_class", None)
    if isinstance(explicit, ErrorClass):
        return explicit
    if type(err).__name__ in _XLA_ERROR_TYPES:
        msg = str(err)
        if "RESOURCE_EXHAUSTED" in msg:
            return ErrorClass.RETRYABLE_OOM
        low = msg.lower()
        if any(code in msg for code in _DEVICE_LOST_CODES) or \
                any(frag in low for frag in _DEVICE_LOST_FRAGMENTS):
            return ErrorClass.DEVICE_LOST
    return ErrorClass.NON_RETRYABLE


def mark_non_retryable(err: Exception) -> Exception:
    """Pin ``err`` to NON_RETRYABLE (the donated-dispatch OOM path: the
    dispatch consumed its inputs, so no level of replay may re-present
    them to the same program)."""
    try:
        err.rapids_error_class = ErrorClass.NON_RETRYABLE
    except Exception:  # noqa: BLE001 — exceptions with __slots__
        pass
    return err
