"""The engine's single retry policy.

One :class:`RetryPolicy` (max attempts + exponential backoff) stands
behind every retry loop: the OOM spill-retry
(mem.catalog.run_with_oom_retry), the partition replay
(plan.physical.run_partition_with_retry -> fault.recovery) and the
whole-pipeline recovery.  Backoff delays are DETERMINISTIC — a pure
function of the attempt index (base * 2^(attempt-1)), no jitter and no
``random`` — so a faulted run replays identically, which the
fault-injection tests rely on.
"""

from __future__ import annotations

import time

from spark_rapids_tpu.fault import metrics as fault_metrics


class RetryPolicy:
    """Max attempts + deterministic exponential backoff.

    ``max_attempts`` counts TOTAL attempts (the first try included), so
    ``max_attempts=3`` means up to two replays after the initial
    failure.  ``delay_s(attempt)`` is the sleep taken AFTER the given
    1-based attempt failed.
    """

    def __init__(self, max_attempts: int, backoff_ms: float):
        self.max_attempts = max(1, int(max_attempts))
        self.backoff_ms = max(0.0, float(backoff_ms))

    @classmethod
    def from_conf(cls, conf) -> "RetryPolicy":
        from spark_rapids_tpu.config import (
            RETRY_BACKOFF_MS, RETRY_MAX_ATTEMPTS,
        )
        return cls(RETRY_MAX_ATTEMPTS.get(conf), RETRY_BACKOFF_MS.get(conf))

    def delay_s(self, attempt: int) -> float:
        """Deterministic per-attempt delay: backoffMs * 2^(attempt-1)."""
        return self.backoff_ms * (2 ** max(0, attempt - 1)) / 1000.0

    def backoff(self, attempt: int) -> None:
        """Sleep the attempt's delay, accounting the wall into
        ``backoffWallNs``."""
        d = self.delay_s(attempt)
        if d <= 0:
            return
        t0 = time.monotonic_ns()
        time.sleep(d)
        t1 = time.monotonic_ns()
        fault_metrics.record("backoff_wall_ns", t1 - t0)
        from spark_rapids_tpu.obs import events as obs_events
        obs_events.emit_span("retry", "backoff", t0=t0, t1=t1,
                             attempt=attempt)

    def __repr__(self):
        return (f"RetryPolicy(max_attempts={self.max_attempts}, "
                f"backoff_ms={self.backoff_ms})")
