"""Per-partition deadline watchdog.

Round-5 on-chip evidence (VERDICT.md): test_hashagg / test_tpch_like
hung 40+ minutes on a single dot with no watchdog.  This module arms a
deadline around each driven partition (conf
``spark.rapids.sql.tpu.partition.timeoutSec``; 0 = off, the tier-1
default — the bench driver turns it on): a monitor thread waits on an
event with the timeout and, on expiry, raises a classified
:class:`~spark_rapids_tpu.fault.errors.PartitionTimeout` INTO the
driving thread via ``PyThreadState_SetAsyncExc``.  The exception then
propagates through the partition driver's existing except/finally paths
(semaphore permits released, read-ahead workers stopped) and enters the
normal recovery machinery as a DEVICE_LOST-class error.

Limits (documented, inherent to in-process watchdogs): an async
exception is delivered between Python bytecodes, so a thread wedged
inside one long C call (a single giant XLA execute) sees it only when
that call returns.  Python-level stalls — polling loops, sliced sleeps,
iterator-driven pipelines — are interrupted within milliseconds of the
deadline.  Truly wedged C calls need process-level supervision (the CI
harness's per-test SIGALRM remains that backstop).
"""

from __future__ import annotations

import ctypes
import threading

from spark_rapids_tpu.fault.errors import PartitionTimeout


def _async_raise(tid: int, exc_class) -> None:
    ctypes.pythonapi.PyThreadState_SetAsyncExc(
        ctypes.c_ulong(tid), ctypes.py_object(exc_class))


def _async_revoke(tid: int) -> None:
    ctypes.pythonapi.PyThreadState_SetAsyncExc(ctypes.c_ulong(tid), None)


class partition_deadline:
    """Context manager arming a deadline for the current thread.

    ``partition_deadline(conf, label)`` reads
    ``spark.rapids.sql.tpu.partition.timeoutSec`` from ``conf``;
    ``partition_deadline(seconds, label)`` takes an explicit timeout.
    Timeout <= 0 disarms (zero overhead beyond one comparison).

    ``exc_type`` overrides the raised class (default
    :class:`PartitionTimeout`, which classifies DEVICE_LOST and enters
    recovery).  The serving scheduler arms per-submission deadlines with
    its own NON_RETRYABLE exception so an expired query aborts out of
    ``session.execute`` instead of being replayed by the retry ladder.
    """

    def __init__(self, conf_or_secs, label: str = "partition",
                 exc_type=PartitionTimeout):
        if isinstance(conf_or_secs, (int, float)):
            self.timeout = float(conf_or_secs)
        else:
            from spark_rapids_tpu.config import PARTITION_TIMEOUT_SEC
            self.timeout = float(PARTITION_TIMEOUT_SEC.get(conf_or_secs))
        self.label = label
        self.exc_type = exc_type
        self.fired = False
        self._thread = None

    def __enter__(self):
        if self.timeout <= 0:
            return self
        self._tid = threading.get_ident()
        self._cancel = threading.Event()
        self._lock = threading.Lock()
        self._done = False
        from spark_rapids_tpu.obs import events as obs_events
        # adopt the arming query's scope on the monitor so the fire
        # event lands in the right query's timeline under concurrency
        self._scope = obs_events.current_scope()
        self._thread = threading.Thread(
            target=self._watch, daemon=True,
            name=f"partition-deadline:{self.label}")
        self._thread.start()
        return self

    def _watch(self):
        if self._cancel.wait(self.timeout):
            return
        with self._lock:
            if self._done:
                return
            self.fired = True
            from spark_rapids_tpu.obs import events as obs_events
            with obs_events.adopt(self._scope):
                obs_events.emit_instant("fault", "watchdog_fire",
                                        label=self.label,
                                        timeout_s=self.timeout)
            _async_raise(self._tid, self.exc_type)

    def __exit__(self, exc_type, exc, tb):
        if self._thread is None:
            return False
        with self._lock:
            self._done = True
        self._cancel.set()
        self._thread.join(timeout=1.0)
        if self.fired:
            if exc_type is None:
                # fired in the gap between the body's last bytecode and
                # this __exit__: the async exception is pending but
                # undelivered — revoke it and raise synchronously so the
                # timeout can neither be lost nor pop at a random later
                # point
                _async_revoke(self._tid)
                raise self.exc_type(
                    f"{self.label} exceeded partition.timeoutSec="
                    f"{self.timeout:g}s")
            if exc_type is not self.exc_type:
                # the body raised its OWN error in the same instant the
                # deadline expired: the async PartitionTimeout is still
                # pending and would otherwise detonate at an arbitrary
                # later bytecode — revoke it; the body's error (already
                # classified by the recovery ladder) wins
                _async_revoke(self._tid)
        return False
