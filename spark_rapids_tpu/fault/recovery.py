"""Device-lost recovery and graceful degradation.

Recovery ladder for a failed partition (or whole-pipeline stage):

1. classify the error (fault.errors);
2. ``NON_RETRYABLE`` -> re-raise immediately (user errors,
   donated-dispatch OOM, KeyboardInterrupt);
3. ``RETRYABLE_OOM`` -> spill everything spillable
   (catalog.handle_alloc_failure) and replay;
4. ``DEVICE_LOST`` -> reset the DeviceRuntime (fresh semaphore +
   device pick, SAME catalog with its device tier invalidated — host
   and disk copies survive and re-upload lazily), then replay: the
   partition is a pure recomputation of its lineage (SURVEY.md section
   5), and the exchange split cache is generation-checked so a replay
   after a reset recomputes the split instead of reading lost pieces;
5. after ``retry.maxAttempts`` total attempts on a device-class error,
   re-run JUST THAT PARTITION through the CPU operator path
   (ops/cpu_exec, lowered from the query's logical plan with
   ``spark.rapids.sql.enabled=false``) when
   ``spark.rapids.sql.tpu.fallback.onDeviceError`` is true — the query
   completes with Spark-CPU-identical results; per-partition fallback,
   never whole-query abort.

Per-partition CPU fallback leans on an engine invariant the compare
harness already enforces: CPU and TPU plans lowered from the same
logical plan produce identical partition row sets and orders (the
exchange collapse and partitioning rules are mirrored on both sides).
When the partition counts nevertheless disagree, fallback degrades to
whole-query only for single-partition plans and otherwise re-raises the
device error.
"""

from __future__ import annotations

from typing import Callable, List, Optional

from spark_rapids_tpu.fault import metrics as fault_metrics
from spark_rapids_tpu.fault.errors import (
    ErrorClass, PartitionTimeout, classify_error,
)
from spark_rapids_tpu.fault.retry import RetryPolicy
from spark_rapids_tpu.fault.watchdog import partition_deadline


def _fallback_enabled(conf) -> bool:
    from spark_rapids_tpu.config import FALLBACK_ON_DEVICE_ERROR
    return FALLBACK_ON_DEVICE_ERROR.get(conf)


def partition_policy(conf) -> RetryPolicy:
    """The partition-replay policy: ``retry.maxAttempts`` unless the
    legacy ``spark.rapids.task.maxFailures`` is explicitly set (it was
    the knob of the loop this subsystem replaced)."""
    policy = RetryPolicy.from_conf(conf)
    legacy = conf._settings.get("spark.rapids.task.maxFailures")
    if legacy is not None:
        policy = RetryPolicy(int(legacy), policy.backoff_ms)
    return policy


def recover_device_lost(ctx, err: Optional[BaseException] = None) -> None:
    """Reset device state after a DEVICE_LOST-class failure.

    * bump the runtime generation + rebuild the DeviceRuntime (fresh
      semaphore: a wedged permit from the dead attempt cannot block the
      replay) while KEEPING the spill catalog, its device tier
      invalidated (mem.catalog.invalidate_device_tier).  A
      PartitionTimeout-triggered recovery skips the best-effort rescue
      D2H: the device is WEDGED, and a rescue copy against it would
      block the recovery path on the very hang being recovered from —
      device-tier handles go straight to TIER_LOST (lineage recompute);
    * release every permit the failed attempt still holds on the old
      semaphore (partitions are driven sequentially, so nothing else in
      this query is mid-flight), then re-point the query context at the
      REBUILT runtime: the replay must dispatch to the live device and
      take admission on the live semaphore, not the dead ones.
    """
    from spark_rapids_tpu.runtime.device import DeviceRuntime
    rescue = not isinstance(err, PartitionTimeout)
    rt = DeviceRuntime.recover(ctx.conf, rescue=rescue)
    if ctx.semaphore is not None:
        ctx.semaphore.release_all()
        ctx.semaphore = rt.semaphore
    if ctx.device is not None:
        ctx.device = rt.device


def _pre_replay(ctx, err, cls) -> None:
    """Recovery action taken before replaying a classified retryable
    error."""
    if cls is ErrorClass.DEVICE_LOST:
        recover_device_lost(ctx, err)
    elif cls is ErrorClass.RETRYABLE_OOM:
        from spark_rapids_tpu.runtime.device import DeviceRuntime
        DeviceRuntime.get(ctx.conf).catalog.handle_alloc_failure()


def _recover_loop(ctx, policy: RetryPolicy, attempt: Callable,
                  fallback: Callable, label: str,
                  error: Optional[Exception] = None,
                  attempts_used: int = 0):
    """The one recovery ladder behind both the per-partition and the
    whole-pipeline paths: classify -> NON_RETRYABLE re-raises ->
    retryable errors recover (spill / runtime reset) and replay under a
    fresh deadline with deterministic backoff -> exhausted attempts
    degrade to ``fallback()`` (None = fallback unavailable: the last
    device error re-raises).  Every DEVICE_LOST-classified error is
    counted exactly once, when it is processed here.
    """
    last = error
    attempts = attempts_used
    while True:
        if last is not None:
            cls = classify_error(last)
            if not isinstance(last, Exception) or \
                    cls is ErrorClass.NON_RETRYABLE:
                raise last
            if cls is ErrorClass.DEVICE_LOST:
                fault_metrics.record("device_lost")
            if attempts >= policy.max_attempts:
                out = fallback()
                if out is None:
                    raise last
                fault_metrics.record("partition_fallbacks")
                ctx.metric("task", "partitionFallbacks").add(1)
                return out
            _pre_replay(ctx, last, cls)
            fault_metrics.record("retries")
            ctx.metric("task", "retries").add(1)
            policy.backoff(attempts)
        try:
            with partition_deadline(ctx.conf, label):
                return attempt()
        except Exception as e:  # noqa: BLE001 — classified above
            last = e
            attempts += 1


def run_partition_with_retry(root, ctx, index: int,
                             error: Optional[Exception] = None) -> List:
    """Replay partition ``index`` of ``root`` under the unified policy.

    ``error`` is the failure that already consumed attempt 1 (the
    partition driver's first drive); None starts fresh.  Exhausted
    device-class errors degrade to the per-partition CPU fallback.
    """
    return _recover_loop(
        ctx, partition_policy(ctx.conf),
        attempt=lambda: list(root.partitions(ctx)[index]),
        fallback=lambda: _cpu_fallback_partition(root, ctx, index),
        label=f"partition:{index}", error=error,
        attempts_used=1 if error is not None else 0)


def run_pipeline_with_recovery(op, ctx):
    """Run the whole-pipeline collect under the recovery ladder.

    The pipeline path executes an entire query stage as one program, so
    recovery here is stage-grained: replay the stage (sources
    re-materialize from their lineage) and, once device attempts are
    exhausted, complete the query through the CPU plan.  Returns the
    HostBatch, or None when the plan isn't pipeline-viable (the caller
    then uses the iterator path, which has its own per-partition
    recovery — a non-viable probe returns from the first ``attempt()``
    without touching the fallback path).
    """
    from spark_rapids_tpu.plan.pipeline import pipeline_collect
    return _recover_loop(
        ctx, RetryPolicy.from_conf(ctx.conf),
        attempt=lambda: pipeline_collect(op, ctx),
        fallback=lambda: _cpu_fallback_collect(ctx),
        label="pipeline")


# -- CPU fallback -------------------------------------------------------------


def _cpu_plan(ctx):
    """The query's all-CPU physical plan (lowered once per ctx from the
    logical plan session.execute attached), or None when unavailable
    (bare ExecContext uses in unit tests)."""
    cached = getattr(ctx, "_cpu_fallback_plan", None)
    if cached is not None:
        return cached
    logical = getattr(ctx, "logical_plan", None)
    if logical is None:
        return None
    from spark_rapids_tpu.plan.overrides import TpuOverrides
    cpu_conf = ctx.conf.copy(**{"spark.rapids.sql.enabled": False})
    try:
        plan = TpuOverrides(cpu_conf).apply(logical)
    except Exception:  # noqa: BLE001 — fallback must not mask the
        return None    # original device error with a planner error
    ctx._cpu_fallback_plan = plan
    ctx._cpu_fallback_conf = cpu_conf
    return plan


def _cpu_fallback_partition(root, ctx, index: int) -> Optional[List]:
    """Run partition ``index`` of the CPU plan; None when fallback is
    off, no logical plan is attached, or the partition layouts of the
    two plans cannot be aligned."""
    if not _fallback_enabled(ctx.conf):
        return None
    cpu_root = _cpu_plan(ctx)
    if cpu_root is None:
        return None
    from spark_rapids_tpu.plan.physical import ExecContext
    cpu_ctx = ExecContext(ctx._cpu_fallback_conf)
    try:
        parts = cpu_root.partitions(cpu_ctx)
        n_tpu = root.num_partitions(ctx)
        if len(parts) != n_tpu:
            if n_tpu == 1 and index == 0:
                # single-partition plan: "that partition" IS the query
                return [hb for p in parts for hb in p]
            return None
        return list(parts[index])
    finally:
        cpu_ctx.close_deferred()


def _cpu_fallback_collect(ctx):
    """Complete the whole query through the CPU plan (pipeline-path
    degradation: the stage program spans every partition, so the
    fallback unit is the stage)."""
    if not _fallback_enabled(ctx.conf):
        return None
    cpu_root = _cpu_plan(ctx)
    if cpu_root is None:
        return None
    from spark_rapids_tpu.plan.physical import ExecContext, collect_host
    cpu_ctx = ExecContext(ctx._cpu_fallback_conf)
    return collect_host(cpu_root, cpu_ctx)
