"""Column function library (the pyspark.sql.functions analogue over the
expression library of SURVEY.md section 2.5)."""

from __future__ import annotations

from typing import Any, List, Optional, Union

from spark_rapids_tpu import types as T
from spark_rapids_tpu.dataframe import Column, _to_expr
from spark_rapids_tpu.exprs import aggregates as A
from spark_rapids_tpu.exprs import windows as W
from spark_rapids_tpu.exprs.base import (
    Alias, ColumnRef, Expression, Literal, SortOrder,
)


def col(name: str) -> Column:
    return Column(ColumnRef(name))


def lit(value: Any) -> Column:
    return Column(Literal(value))


# -- aggregates --------------------------------------------------------------


def _agg(cls, c) -> Column:
    return Column(cls(_to_expr(col(c) if isinstance(c, str) else c)))


def sum(c) -> Column:  # noqa: A001
    return _agg(A.Sum, c)


def count(c) -> Column:
    if isinstance(c, str) and c == "*":
        return Column(A.count_star())
    return _agg(A.Count, c)


def avg(c) -> Column:
    return _agg(A.Average, c)


mean = avg


def min(c) -> Column:  # noqa: A001
    return _agg(A.Min, c)


def max(c) -> Column:  # noqa: A001
    return _agg(A.Max, c)


def first(c, ignore_nulls: bool = False) -> Column:
    e = _to_expr(col(c) if isinstance(c, str) else c)
    return Column(A.First(e, ignore_nulls))


def last(c, ignore_nulls: bool = False) -> Column:
    e = _to_expr(col(c) if isinstance(c, str) else c)
    return Column(A.Last(e, ignore_nulls))


def stddev(c) -> Column:
    return _agg(A.StddevSamp, c)


stddev_samp = stddev


def stddev_pop(c) -> Column:
    return _agg(A.StddevPop, c)


def variance(c) -> Column:
    return _agg(A.VarianceSamp, c)


var_samp = variance


def var_pop(c) -> Column:
    return _agg(A.VariancePop, c)


def _binstat(cls, x, y) -> Column:
    x = col(x) if isinstance(x, str) else x
    y = col(y) if isinstance(y, str) else y
    return Column(cls(_to_expr(x), _to_expr(y)))


def corr(x, y) -> Column:
    return _binstat(A.Corr, x, y)


def covar_pop(x, y) -> Column:
    return _binstat(A.CovarPop, x, y)


def covar_samp(x, y) -> Column:
    return _binstat(A.CovarSamp, x, y)


def grouping_id() -> Column:
    """Bitmask of masked-out keys under rollup/cube/grouping sets."""
    from spark_rapids_tpu.exprs.aggregates import GroupingID
    return Column(GroupingID())


def percentile(c, percentage: float) -> Column:
    """Exact percentile with linear interpolation (Spark `percentile`);
    rewritten to a rank-and-interpolate pipeline at aggregation time."""
    from spark_rapids_tpu.exprs.aggregates import Percentile
    c = col(c) if isinstance(c, str) else c
    return Column(Percentile(_to_expr(c), percentage))


def count_distinct(c) -> Column:
    """count(DISTINCT c) — rewritten by the dataframe layer into the
    two-level distinct-aggregate plan (GroupedData._agg_with_distinct)."""
    return _agg(A.CountDistinct, c)


countDistinct = count_distinct


# -- scalar functions --------------------------------------------------------


def _unary(cls, c) -> Column:
    return Column(cls(_to_expr(col(c) if isinstance(c, str) else c)))


def abs(c) -> Column:  # noqa: A001
    from spark_rapids_tpu.exprs.arithmetic import Abs
    return _unary(Abs, c)


def sqrt(c) -> Column:
    from spark_rapids_tpu.exprs.mathexprs import Sqrt
    return _unary(Sqrt, c)


def exp(c) -> Column:
    from spark_rapids_tpu.exprs.mathexprs import Exp
    return _unary(Exp, c)


def log(c) -> Column:
    from spark_rapids_tpu.exprs.mathexprs import Log
    return _unary(Log, c)


def floor(c) -> Column:
    from spark_rapids_tpu.exprs.mathexprs import Floor
    return _unary(Floor, c)


def ceil(c) -> Column:
    from spark_rapids_tpu.exprs.mathexprs import Ceil
    return _unary(Ceil, c)


def round(c, scale: int = 0) -> Column:  # noqa: A001
    from spark_rapids_tpu.exprs.mathexprs import Round
    e = _to_expr(col(c) if isinstance(c, str) else c)
    return Column(Round(e, scale))


def pow(b, e) -> Column:  # noqa: A001
    from spark_rapids_tpu.exprs.mathexprs import Pow
    return Column(Pow(_to_expr(b), _to_expr(e)))


def coalesce(*cols) -> Column:
    from spark_rapids_tpu.exprs.nullexprs import Coalesce
    return Column(Coalesce(*[_to_expr(c) for c in cols]))


def isnull(c) -> Column:
    from spark_rapids_tpu.exprs.nullexprs import IsNull
    return _unary(IsNull, c)


def isnan(c) -> Column:
    from spark_rapids_tpu.exprs.nullexprs import IsNan
    return _unary(IsNan, c)


def when(condition, value) -> "CaseBuilder":
    return CaseBuilder().when(condition, value)


class CaseBuilder:
    def __init__(self):
        self._branches = []

    def when(self, condition, value) -> "CaseBuilder":
        self._branches.append((_to_expr(condition), _to_expr(value)))
        return self

    def otherwise(self, value) -> Column:
        from spark_rapids_tpu.exprs.conditional import CaseWhen
        return Column(CaseWhen(self._branches, _to_expr(value)))

    @property
    def column(self) -> Column:
        from spark_rapids_tpu.exprs.conditional import CaseWhen
        return Column(CaseWhen(self._branches, None))

    # allow using a CaseBuilder directly as a Column (no otherwise = NULL)
    @property
    def expr(self):
        return self.column.expr


def sinh(c) -> Column:
    from spark_rapids_tpu.exprs.mathexprs import Sinh
    return _unary(Sinh, c)


def cosh(c) -> Column:
    from spark_rapids_tpu.exprs.mathexprs import Cosh
    return _unary(Cosh, c)


def tanh(c) -> Column:
    from spark_rapids_tpu.exprs.mathexprs import Tanh
    return _unary(Tanh, c)


def cot(c) -> Column:
    from spark_rapids_tpu.exprs.mathexprs import Cot
    return _unary(Cot, c)


def initcap(c) -> Column:
    from spark_rapids_tpu.exprs.strings import InitCap
    return _unary(InitCap, c)


def to_date(c, fmt: str = "yyyy-MM-dd") -> Column:
    from spark_rapids_tpu.exprs.datetime import ToDate
    c = col(c) if isinstance(c, str) else c
    return Column(ToDate(_to_expr(c), fmt))


def date_format(c, fmt: str = "yyyy-MM-dd") -> Column:
    from spark_rapids_tpu.exprs.datetime import DateFormat
    c = col(c) if isinstance(c, str) else c
    return Column(DateFormat(_to_expr(c), fmt))


def weekday(c) -> Column:
    from spark_rapids_tpu.exprs.datetime import WeekDay
    return _unary(WeekDay, c)


def substring_index(c, delimiter: str, count: int) -> Column:
    from spark_rapids_tpu.exprs.strings import SubstringIndex
    c = col(c) if isinstance(c, str) else c
    return Column(SubstringIndex(_to_expr(c), delimiter, count))


def split(c, delimiter: str) -> Column:
    """split -> array<string>; CPU-only (variable-length elements)."""
    from spark_rapids_tpu.exprs.strings import StringSplit
    c = col(c) if isinstance(c, str) else c
    return Column(StringSplit(_to_expr(c), delimiter))


def hex(c) -> Column:  # noqa: A001
    from spark_rapids_tpu.exprs.strings import Hex
    return _unary(Hex, c)


def upper(c) -> Column:
    from spark_rapids_tpu.exprs.strings import Upper
    return _unary(Upper, c)


def lower(c) -> Column:
    from spark_rapids_tpu.exprs.strings import Lower
    return _unary(Lower, c)


def length(c) -> Column:
    from spark_rapids_tpu.exprs.strings import Length
    return _unary(Length, c)


def trim(c) -> Column:
    from spark_rapids_tpu.exprs.strings import StringTrim
    return _unary(StringTrim, c)


def concat(*cols) -> Column:
    from spark_rapids_tpu.exprs.strings import ConcatStrings
    return Column(ConcatStrings(*[_to_expr(
        col(c) if isinstance(c, str) else c) for c in cols]))


def substring(c, pos: int, length: int) -> Column:
    from spark_rapids_tpu.exprs.strings import Substring
    e = _to_expr(col(c) if isinstance(c, str) else c)
    return Column(Substring(e, pos, length))


def regexp_replace(c, pattern: str, replacement: str) -> Column:
    from spark_rapids_tpu.exprs.strings import RegExpReplace
    e = _to_expr(col(c) if isinstance(c, str) else c)
    return Column(RegExpReplace(e, pattern, replacement))


def replace(c, search: str, replacement: str) -> Column:
    from spark_rapids_tpu.exprs.strings import StringReplace
    e = _to_expr(col(c) if isinstance(c, str) else c)
    return Column(StringReplace(e, search, replacement))


def split_part(c, delimiter: str, part: int) -> Column:
    """1-based field extraction on a literal delimiter (Spark split_part /
    split(col, d).getItem(part-1))."""
    from spark_rapids_tpu.exprs.strings import SplitPart
    e = _to_expr(col(c) if isinstance(c, str) else c)
    return Column(SplitPart(e, delimiter, part))


def concat_ws(sep: str, *cols) -> Column:
    from spark_rapids_tpu.exprs.strings import ConcatWs
    return Column(ConcatWs(sep, *[_to_expr(
        col(c) if isinstance(c, str) else c) for c in cols]))


def shiftleft(c, n) -> Column:
    from spark_rapids_tpu.exprs.bitwise import ShiftLeft
    e = _to_expr(col(c) if isinstance(c, str) else c)
    return Column(ShiftLeft(e, _to_expr(n)))


def shiftright(c, n) -> Column:
    from spark_rapids_tpu.exprs.bitwise import ShiftRight
    e = _to_expr(col(c) if isinstance(c, str) else c)
    return Column(ShiftRight(e, _to_expr(n)))


def shiftrightunsigned(c, n) -> Column:
    from spark_rapids_tpu.exprs.bitwise import ShiftRightUnsigned
    e = _to_expr(col(c) if isinstance(c, str) else c)
    return Column(ShiftRightUnsigned(e, _to_expr(n)))


def bitwise_not(c) -> Column:
    from spark_rapids_tpu.exprs.bitwise import BitwiseNot
    return _unary(BitwiseNot, c)


bitwiseNOT = bitwise_not


def _bitwise_col(self: Column, other, cls_name: str) -> Column:
    from spark_rapids_tpu.exprs import bitwise as B
    return Column(getattr(B, cls_name)(self.expr, _to_expr(other)))


Column.bitwiseAND = lambda self, o: _bitwise_col(self, o, "BitwiseAnd")
Column.bitwiseOR = lambda self, o: _bitwise_col(self, o, "BitwiseOr")
Column.bitwiseXOR = lambda self, o: _bitwise_col(self, o, "BitwiseXor")


def unix_timestamp(c) -> Column:
    from spark_rapids_tpu.exprs.datetime import UnixTimestamp
    return _unary(UnixTimestamp, c)


def from_unixtime(c, fmt: str = "yyyy-MM-dd HH:mm:ss") -> Column:
    from spark_rapids_tpu.exprs.datetime import FromUnixTime
    e = _to_expr(col(c) if isinstance(c, str) else c)
    return Column(FromUnixTime(e, fmt))


def year(c) -> Column:
    from spark_rapids_tpu.exprs.datetime import Year
    return _unary(Year, c)


def month(c) -> Column:
    from spark_rapids_tpu.exprs.datetime import Month
    return _unary(Month, c)


def dayofmonth(c) -> Column:
    from spark_rapids_tpu.exprs.datetime import DayOfMonth
    return _unary(DayOfMonth, c)


def hash(*cols) -> Column:  # noqa: A001
    from spark_rapids_tpu.exprs.hashing import Murmur3Hash
    return Column(Murmur3Hash(*[_to_expr(
        col(c) if isinstance(c, str) else c) for c in cols]))


def get_item(c, ordinal: int) -> Column:
    from spark_rapids_tpu.exprs.misc import GetArrayItem
    e = _to_expr(col(c) if isinstance(c, str) else c)
    return Column(GetArrayItem(e, ordinal))


def size(c) -> Column:
    from spark_rapids_tpu.exprs.misc import ArraySize
    return _unary(ArraySize, c)


def array(*cols) -> Column:
    from spark_rapids_tpu.exprs.misc import CreateArray
    return Column(CreateArray(*[_to_expr(
        col(c) if isinstance(c, str) else c) for c in cols]))


def array_contains(c, value) -> Column:
    from spark_rapids_tpu.exprs.misc import ArrayContains
    c = col(c) if isinstance(c, str) else c
    return Column(ArrayContains(_to_expr(c), value))


def array_min(c) -> Column:
    from spark_rapids_tpu.exprs.misc import ArrayMin
    return _unary(ArrayMin, c)


def array_max(c) -> Column:
    from spark_rapids_tpu.exprs.misc import ArrayMax
    return _unary(ArrayMax, c)


def sort_array(c, asc: bool = True) -> Column:
    from spark_rapids_tpu.exprs.misc import SortArray
    c = col(c) if isinstance(c, str) else c
    return Column(SortArray(_to_expr(c), asc))


def array_position(c, value) -> Column:
    from spark_rapids_tpu.exprs.misc import ArrayPosition
    c = col(c) if isinstance(c, str) else c
    return Column(ArrayPosition(_to_expr(c), value))


def monotonically_increasing_id() -> Column:
    from spark_rapids_tpu.exprs.misc import MonotonicallyIncreasingID
    return Column(MonotonicallyIncreasingID())


def spark_partition_id() -> Column:
    from spark_rapids_tpu.exprs.misc import SparkPartitionID
    return Column(SparkPartitionID())


def rand(seed: int = 42) -> Column:
    from spark_rapids_tpu.exprs.misc import Rand
    return Column(Rand(seed))


def broadcast(df):
    """Hint: prefer broadcasting this side of a join
    (GpuBroadcastExchangeExec path)."""
    from spark_rapids_tpu.dataframe import DataFrame
    from spark_rapids_tpu.plan import logical as L
    return DataFrame(L.BroadcastHint(df.plan), df.session)


# -- python UDFs -------------------------------------------------------------


def udf(fn=None, return_type: T.DataType = T.DOUBLE):
    """Row-at-a-time python UDF.  With
    ``spark.rapids.sql.udfCompiler.enabled`` the planner attempts to compile
    its bytecode to columnar expressions (udf-compiler analogue); otherwise
    it runs on the host Arrow path."""
    from spark_rapids_tpu.exprs.python_udf import PythonUDF

    def wrap(f):
        def call(*cols) -> Column:
            exprs = [_to_expr(col(c) if isinstance(c, str) else c)
                     for c in cols]
            return Column(PythonUDF(f, return_type, *exprs))
        call.__name__ = getattr(f, "__name__", "udf")
        return call

    if fn is None:
        return wrap
    return wrap(fn)


def pandas_udf(fn=None, return_type: T.DataType = T.DOUBLE):
    """Vectorized pandas UDF (GpuArrowEvalPythonExec path)."""
    from spark_rapids_tpu.exprs.python_udf import PandasUDF

    def wrap(f):
        def call(*cols) -> Column:
            exprs = [_to_expr(col(c) if isinstance(c, str) else c)
                     for c in cols]
            return Column(PandasUDF(f, return_type, *exprs))
        call.__name__ = getattr(f, "__name__", "pandas_udf")
        return call

    if fn is None:
        return wrap
    return wrap(fn)


# -- window ------------------------------------------------------------------


class WindowSpec:
    def __init__(self, partition_by=None, order_by=None, frame=None):
        self._partition_by = partition_by or []
        self._order_by = order_by or []
        self._frame = frame

    def partition_by(self, *cols) -> "WindowSpec":
        exprs = [_to_expr(col(c) if isinstance(c, str) else c) for c in cols]
        return WindowSpec(exprs, self._order_by, self._frame)

    partitionBy = partition_by

    def order_by(self, *cols) -> "WindowSpec":
        from spark_rapids_tpu.dataframe import _to_order
        orders = [_to_order(c) for c in cols]
        return WindowSpec(self._partition_by, orders, self._frame)

    orderBy = order_by

    def _make_frame(self, kind: str, start, end) -> "WindowSpec":
        def bound(v, what):
            if v is None or v in (Window.unboundedPreceding,
                                  Window.unboundedFollowing):
                return None
            if isinstance(v, bool) or not isinstance(v, int):
                raise TypeError(
                    f"{kind} frame {what} bound must be an int, "
                    f"got {v!r}")
            return v
        return WindowSpec(self._partition_by, self._order_by,
                          W.WindowFrame(kind, bound(start, "start"),
                                        bound(end, "end")))

    def rows_between(self, start, end) -> "WindowSpec":
        return self._make_frame("rows", start, end)

    rowsBetween = rows_between

    def range_between(self, start, end) -> "WindowSpec":
        """Value-based frame over the single numeric order key (RANGE
        BETWEEN x PRECEDING AND y FOLLOWING)."""
        return self._make_frame("range", start, end)

    rangeBetween = range_between


class Window:
    unboundedPreceding = object()
    unboundedFollowing = object()
    currentRow = 0

    @staticmethod
    def partition_by(*cols) -> WindowSpec:
        return WindowSpec().partition_by(*cols)

    partitionBy = partition_by

    @staticmethod
    def order_by(*cols) -> WindowSpec:
        return WindowSpec().order_by(*cols)

    orderBy = order_by


class _OverColumn(Column):
    pass


def _over(self: Column, spec: WindowSpec) -> Column:
    e = self.expr
    name = None
    if isinstance(e, Alias):
        name, e = e.alias_name, e.children[0]
    w = W.WindowExpression(e, spec._partition_by, spec._order_by,
                           spec._frame)
    return Column(Alias(w, name) if name else w)


Column.over = _over  # type: ignore[attr-defined]


def row_number() -> Column:
    return Column(W.RowNumber())


def rank() -> Column:
    return Column(W.Rank())


def dense_rank() -> Column:
    return Column(W.DenseRank())


def lag(c, offset: int = 1, default=None) -> Column:
    e = _to_expr(col(c) if isinstance(c, str) else c)
    d = None if default is None else _to_expr(default)
    return Column(W.Lag(e, offset, d))


def lead(c, offset: int = 1, default=None) -> Column:
    e = _to_expr(col(c) if isinstance(c, str) else c)
    d = None if default is None else _to_expr(default)
    return Column(W.Lead(e, offset, d))
