"""Order-preserving uint32 sort-key encodings.

The TPU analogue of cudf ``Table.orderBy``'s comparators
(GpuSortExec.scala:241): every sort key column is encoded into one or more
``uint32`` words such that *lexicographic comparison of the word tuple*
equals the SQL ordering (ascending/descending, nulls first/last, padding
rows always last).  ``jax.lax.sort`` over the word list yields the
permutation.

Why 32-bit words: TPUs have no native 64-bit integer lanes — XLA *emulates*
u64 arithmetic/compares, which cripples the sort that every kernel here
(groupby, join, window, partition-split) is built on.  A 64-bit key split
into (hi, lo) u32 words compares identically under lexicographic multi-word
sort, and every op stays native.

Encodings:

* int8/16/32, date: one word — value ^ sign-bit (order-preserving bias)
* int64/timestamp: two words — biased hi 32 bits, raw lo 32 bits
* float/double: canonicalize NaN (Spark: NaN sorts greatest, -0.0 == 0.0),
  then the IEEE trick in (hi, lo) form: negative => flip all bits, else set
  the sign bit
* boolean: 0/1
* string: bytes padded with 0 and packed big-endian, 4 bytes per word, up
  to a configurable prefix (``spark.rapids.sql.tpu.sort.stringPrefixBytes``,
  default 64).  Byte-0 padding preserves "shorter prefix sorts first",
  matching Spark's unsigned-byte string comparison.  Strings equal in the
  prefix tie-break by full-length + dual 32-bit polynomial hash when
  exactness of *grouping* matters (groupby uses that); pure sort order
  beyond the prefix is documented approximate, like the reference flags
  incompat string cases.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import DeviceColumn
from spark_rapids_tpu.exprs.base import DevVal

DEFAULT_STRING_PREFIX_BYTES = 64

# numpy (not jnp) scalar: module import can happen lazily inside an active
# jit trace, where a jnp constant would be created as that trace's tracer
# and leak into every later program (UnexpectedTracerError)
_SIGN32 = np.uint32(1 << 31)

# f64 order words are backend-dependent:
#
# * CPU (tests, oracle, virtual mesh): real IEEE f64 — bitcast to a
#   (hi, lo) u32 pair, exact.
# * TPU: XLA emulates f64 as a float-float pair (two f32s: hi + lo), with
#   f32's exponent range — bitcasts of emulated f64 fail to compile, and
#   values outside ~[1e-38, 3.4e38] are already inf/0 on device.  The
#   emulation's own (hi, lo) split IS the encoding: s1 = f32(x),
#   s2 = f32(x - s1), compared lexicographically (standard double-float
#   comparison), using only native f32 bitcasts.  See
#   docs/compatibility.md "Double precision on TPU".


def _encode_fixed_words(v: DevVal) -> List[jnp.ndarray]:
    """Order-preserving u32 word list for a fixed-width column's values."""
    dt = v.dtype
    if dt == T.BOOLEAN:
        return [v.data.astype(jnp.uint32)]
    if dt in (T.BYTE, T.SHORT, T.INT, T.DATE):
        x = v.data.astype(jnp.int32)
        return [jax.lax.bitcast_convert_type(x, jnp.uint32) ^ _SIGN32]
    if dt in (T.LONG, T.TIMESTAMP):
        x = v.data.astype(jnp.int64)
        lo = jax.lax.convert_element_type(
            x & jnp.int64(0xFFFFFFFF), jnp.uint32)
        hi32 = jax.lax.convert_element_type(
            (x >> jnp.int64(32)) & jnp.int64(0xFFFFFFFF), jnp.uint32)
        return [hi32 ^ _SIGN32, lo]
    if dt == T.FLOAT:
        x = v.data.astype(jnp.float32)
        x = jnp.where(jnp.isnan(x), jnp.float32(jnp.nan), x)
        x = jnp.where(x == 0.0, jnp.float32(0.0), x)
        bits = jax.lax.bitcast_convert_type(x, jnp.uint32)
        neg = (bits & _SIGN32) != 0
        return [jnp.where(neg, ~bits, bits | _SIGN32)]
    if dt == T.DOUBLE:
        return _encode_double_words(v.data)
    raise TypeError(f"cannot encode sort key of type {dt}")


def _enc_f32_bits(f):
    """Order-preserving u32 encoding of a (native) f32 array."""
    bits = jax.lax.bitcast_convert_type(f.astype(jnp.float32), jnp.uint32)
    neg = (bits & _SIGN32) != 0
    return jnp.where(neg, ~bits, bits | _SIGN32)


def _encode_double_words(data) -> List[jnp.ndarray]:
    """u32 order words for f64 (Spark order: -inf..-0=0..+inf, NaN
    greatest), injective on device-representable canonicalized values."""
    if jax.default_backend() == "tpu":
        return _encode_double_words_ff(data)
    return _encode_double_words_bitcast(data)


def _encode_double_words_bitcast(data) -> List[jnp.ndarray]:
    """Exact (hi, lo) u32 pair via bitcast — real-f64 backends only."""
    x = data.astype(jnp.float64)
    x = jnp.where(jnp.isnan(x), jnp.float64(jnp.nan), x)
    x = jnp.where(x == 0.0, jnp.float64(0.0), x)
    pair = jax.lax.bitcast_convert_type(x, jnp.uint32)  # [..., 2] lo,hi
    lo, hi = pair[..., 0], pair[..., 1]
    neg = (hi & _SIGN32) != 0
    return [jnp.where(neg, ~hi, hi | _SIGN32),
            jnp.where(neg, ~lo, lo)]


def _encode_double_words_ff(data) -> List[jnp.ndarray]:
    """(nan-class, enc32(hi), enc32(lo)) for float-float-emulated f64.

    x < y  <=>  (f32(x), x - f32(x)) lexicographic (standard double-float
    comparison; both components signed, ordered by the f32 encoding).
    """
    x = data.astype(jnp.float64)
    isnan = jnp.isnan(x)
    x = jnp.where(isnan, jnp.float64(0.0), x)
    x = jnp.where(x == 0.0, jnp.float64(0.0), x)  # -0 -> +0
    s1 = x.astype(jnp.float32)
    r1 = x - s1.astype(jnp.float64)
    r1 = jnp.where(jnp.isinf(x), jnp.float64(0.0), r1)  # inf - inf = nan
    s2 = r1.astype(jnp.float32)
    cls = jnp.where(isnan, jnp.uint32(1), jnp.uint32(0))
    return [cls, _enc_f32_bits(s1), _enc_f32_bits(s2)]


# Backwards-compatible single-word view used by equality checks.
def _encode_fixed(v: DevVal) -> List[jnp.ndarray]:
    return _encode_fixed_words(v)


def string_prefix_words(col_or_val, prefix_bytes: int) -> List[jnp.ndarray]:
    """Big-endian packed u32 words of each row's first ``prefix_bytes``
    bytes."""
    v = col_or_val
    if getattr(v, "codes", None) is not None:
        # Dictionary-encoded: pack each ENTRY's prefix once, gather per row.
        nd = int(v.offsets.shape[0]) - 1
        ent = DevVal(v.dtype, v.data, jnp.ones(nd, dtype=jnp.bool_),
                     v.offsets)
        codes_c = jnp.clip(v.codes, 0, max(nd - 1, 0))
        return [jnp.where(v.validity, w[codes_c], jnp.uint32(0))
                for w in string_prefix_words(ent, prefix_bytes)]
    offsets, data = v.offsets, v.data
    cap = int(offsets.shape[0]) - 1
    nbytes = int(data.shape[0])
    lens = (offsets[1:] - offsets[:-1]).astype(jnp.int32)
    words: List[jnp.ndarray] = []
    n_words = (prefix_bytes + 3) // 4
    for w in range(n_words):
        word = jnp.zeros(cap, dtype=jnp.uint32)
        for b in range(4):
            j = w * 4 + b
            src = jnp.clip(offsets[:-1] + j, 0, nbytes - 1)
            byte = jnp.where(j < lens, data[src], 0).astype(jnp.uint32)
            word = (word << jnp.uint32(8)) | byte
        words.append(word)
    return words


def encode_sort_keys(vals: List[DevVal], ascendings: List[bool],
                     nulls_firsts: List[bool], num_rows,
                     string_prefix_bytes: int = DEFAULT_STRING_PREFIX_BYTES,
                     groupings: Optional[List[bool]] = None,
                     liveness: bool = True) -> List[jnp.ndarray]:
    """Full u32 key-word list for a multi-column sort.

    With ``liveness`` (the default), a leading word forces padding rows
    (row >= num_rows) to the end; each key column contributes a null-rank
    word then its value word(s).  The liveness bit is folded into the first
    null-rank word (both are un-negated 1-bit ranks) to save a sort pass.

    ``groupings[i]`` marks key i as *grouping-only*: the caller needs equal
    keys adjacent (groupby segmentation, window partitioning) but does not
    care about the order *between* distinct keys.  String columns then
    encode as (length, h1, h2) — 3 words instead of prefix_bytes/4 + 3 —
    which cuts the sort-operand count that drives TPU compile time.  Equal
    strings still always land adjacent; the only risk is a dual-32-bit-hash
    + length collision between *distinct* strings that interleave, the same
    collision class as the documented string join equality."""
    cap = int(vals[0].validity.shape[0]) if vals else 0
    words: List[jnp.ndarray] = []
    if liveness:
        live = jnp.arange(cap, dtype=jnp.int32) < num_rows
        words.append(jnp.where(live, 0, 1).astype(jnp.uint32))
    if groupings is None:
        groupings = [False] * len(vals)
    for v, asc, nf, grp in zip(vals, ascendings, nulls_firsts, groupings):
        null_rank = jnp.where(v.validity, 1, 0) if nf else \
            jnp.where(v.validity, 0, 1)
        words.append(null_rank.astype(jnp.uint32))
        if v.dtype.is_string:
            # Prefix words order the sort; the trailing (length, h1, h2)
            # tie-break words guarantee that *fully equal* strings always
            # sort adjacent even past the prefix, so group_segments /
            # window partitioning (which test full equality via
            # keys_equal_prev) never split one group across a run of
            # prefix-equal strings.  Beyond-prefix *order* between unequal
            # strings remains approximate (documented).
            from spark_rapids_tpu.exprs.strings import (
                string_hash2, string_lengths,
            )
            lens = string_lengths(v).astype(jnp.uint32)
            h1, h2 = string_hash2(v)
            tail = [lens, h1.astype(jnp.uint32), h2.astype(jnp.uint32)]
            if grp:
                vwords = tail
            else:
                vwords = string_prefix_words(v, string_prefix_bytes) + tail
        else:
            vwords = _encode_fixed_words(v)
        for w in vwords:
            w = jnp.where(v.validity, w, 0)  # nulls all compare equal
            words.append(w if asc else ~w)
    if liveness and len(words) >= 2:
        # Fold: (pad << 1) | null_rank_of_first_key.  Neither word is ever
        # negated for descending order, so the fold preserves the ordering.
        words = [(words[0] << jnp.uint32(1)) | words[1]] + words[2:]
    return words


# lax.sort compile time on this TPU toolchain grows ~2x per added operand
# (measured round 4: 8.6s / 17s / 67s / 171s cold for 1 / 2 / 3 / 5 key
# words at 64K-4M rows), so a 20-word string sort never finishes compiling.
# A least-significant-word-first chain of identical 2-operand stable sorts
# compiles once and stays flat (~20-35s for 20 passes) at <2x the direct
# sort's runtime — so on TPU any multi-word sort takes the LSD path.
_DIRECT_SORT_MAX_WORDS_TPU = 1


def argsort_by_words(words: List[jnp.ndarray], cap: int) -> jnp.ndarray:
    """Stable permutation (int32[cap]) ordering rows by the word tuple."""
    iota = jnp.arange(cap, dtype=jnp.int32)
    if not words:
        return iota
    if jax.default_backend() == "tpu" and \
            len(words) > _DIRECT_SORT_MAX_WORDS_TPU:
        return _argsort_lsd(words, iota)
    out = jax.lax.sort(tuple(words) + (iota,), num_keys=len(words),
                       is_stable=True)
    return out[-1]


def _argsort_lsd(words: List[jnp.ndarray], perm: jnp.ndarray) -> jnp.ndarray:
    """LSD radix argsort: stable-sort by each word, least significant first.

    After processing word i, rows are stably ordered by words[i:]; the final
    permutation therefore orders by the full lexicographic word tuple —
    identical to the direct multi-operand sort (cross-checked in
    tests/test_kernels_sort.py)."""
    for w in reversed(words):
        _, perm = jax.lax.sort((w[perm], perm), num_keys=1, is_stable=True)
    return perm


def keys_equal_prev(vals: List[DevVal]) -> jnp.ndarray:
    """bool[cap]: row i's key tuple exactly equals row i-1's (False at i=0).

    Used by sort-based groupby for exact segment boundaries.  Strings
    compare by (length, prefix words, dual 32-bit polynomial full hash) —
    an engineered-collision risk only, comparable to the reference
    partitioning on 32-bit murmur3."""
    cap = int(vals[0].validity.shape[0])
    eq = jnp.ones(cap, dtype=jnp.bool_)

    def shift_ne(x):
        prev = jnp.concatenate([x[:1], x[:-1]])
        return x != prev

    for v in vals:
        eq = eq & ~shift_ne(v.validity)
        if v.dtype.is_string:
            from spark_rapids_tpu.exprs.strings import (
                string_hash2, string_lengths,
            )
            lens = string_lengths(v)
            h1, h2 = string_hash2(v)
            cmp_words = [lens, h1, h2] + string_prefix_words(
                v, DEFAULT_STRING_PREFIX_BYTES)
            for x in cmp_words:
                same = ~shift_ne(x)
                eq = eq & jnp.where(v.validity, same, True)
        else:
            for w in _encode_fixed_words(v):
                same = ~shift_ne(w)
                eq = eq & jnp.where(v.validity, same, True)
    eq = eq.at[0].set(False)
    return eq
