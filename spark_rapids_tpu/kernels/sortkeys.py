"""Order-preserving uint64 sort-key encodings.

The TPU analogue of cudf ``Table.orderBy``'s comparators
(GpuSortExec.scala:241): every sort key column is encoded into one or more
``uint64`` words such that *lexicographic comparison of the word tuple* equals
the SQL ordering (ascending/descending, nulls first/last, padding rows always
last).  ``jax.lax.sort`` over the word list then yields the permutation.

Encodings:

* integral/date/timestamp: value ^ sign-bit (order-preserving bias to unsigned)
* float/double: widen to f64, canonicalize NaN (Spark: NaN sorts greatest,
  -0.0 == 0.0), then the IEEE trick — negative => flip all bits, else set sign
* boolean: 0/1
* string: bytes padded with 0 and packed big-endian, 8 bytes per word, up to a
  configurable prefix (``spark.rapids.sql.tpu.sort.stringPrefixBytes``,
  default 64).  Byte 0 padding preserves "shorter prefix sorts first", which
  matches Spark's unsigned-byte string comparison.  Strings equal in the
  prefix tie-break by full-length + polynomial hash when exactness of
  *grouping* matters (groupby uses that); pure sort order beyond the prefix is
  documented as approximate, like the reference flags incompat string cases.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import DeviceColumn
from spark_rapids_tpu.exprs.base import DevVal

DEFAULT_STRING_PREFIX_BYTES = 64

_SIGN64 = jnp.uint64(1 << 63)


def _encode_fixed(v: DevVal) -> jnp.ndarray:
    """One order-preserving u64 word for a fixed-width column's values."""
    dt = v.dtype
    if dt == T.BOOLEAN:
        return v.data.astype(jnp.uint64)
    if dt.is_integral or dt.is_datetime:
        x = v.data.astype(jnp.int64)
        return jax.lax.bitcast_convert_type(x, jnp.uint64) ^ _SIGN64
    if dt.is_fractional:
        x = v.data.astype(jnp.float64)
        # Spark sort semantics: all NaNs equal and greatest; -0.0 == 0.0.
        x = jnp.where(jnp.isnan(x), jnp.float64(jnp.nan), x)
        x = jnp.where(x == 0.0, jnp.float64(0.0), x)
        # f64 -> u32 pair -> u64 (TPU X64 rewriting lacks direct f64->u64).
        pair = jax.lax.bitcast_convert_type(x, jnp.uint32)
        bits = (pair[..., 1].astype(jnp.uint64) << jnp.uint64(32)) | \
            pair[..., 0].astype(jnp.uint64)
        neg = (bits & _SIGN64) != 0
        return jnp.where(neg, ~bits, bits | _SIGN64)
    raise TypeError(f"cannot encode sort key of type {dt}")


def string_prefix_words(col_or_val, prefix_bytes: int) -> List[jnp.ndarray]:
    """Big-endian packed u64 words of each row's first ``prefix_bytes`` bytes."""
    v = col_or_val
    offsets, data = v.offsets, v.data
    cap = int(offsets.shape[0]) - 1
    nbytes = int(data.shape[0])
    lens = (offsets[1:] - offsets[:-1]).astype(jnp.int32)
    words: List[jnp.ndarray] = []
    n_words = (prefix_bytes + 7) // 8
    row = jnp.arange(cap, dtype=jnp.int32)
    for w in range(n_words):
        word = jnp.zeros(cap, dtype=jnp.uint64)
        for b in range(8):
            j = w * 8 + b
            src = jnp.clip(offsets[:-1] + j, 0, nbytes - 1)
            byte = jnp.where(j < lens, data[src], 0).astype(jnp.uint64)
            word = (word << jnp.uint64(8)) | byte
        words.append(word)
    return words


def encode_sort_keys(vals: List[DevVal], ascendings: List[bool],
                     nulls_firsts: List[bool], num_rows,
                     string_prefix_bytes: int = DEFAULT_STRING_PREFIX_BYTES
                     ) -> List[jnp.ndarray]:
    """Full key-word list for a multi-column sort.

    Word 0 forces padding rows (row >= num_rows) to the end; each key column
    contributes a null-rank word then its value word(s).
    """
    cap = int(vals[0].validity.shape[0]) if vals else 0
    live = jnp.arange(cap, dtype=jnp.int32) < num_rows
    words: List[jnp.ndarray] = [jnp.where(live, 0, 1).astype(jnp.uint64)]
    for v, asc, nf in zip(vals, ascendings, nulls_firsts):
        null_rank = jnp.where(v.validity, 1, 0) if nf else \
            jnp.where(v.validity, 0, 1)
        words.append(null_rank.astype(jnp.uint64))
        if v.dtype.is_string:
            vwords = string_prefix_words(v, string_prefix_bytes)
        else:
            vwords = [_encode_fixed(v)]
        for w in vwords:
            w = jnp.where(v.validity, w, 0)  # nulls all compare equal
            words.append(w if asc else ~w)
    return words


def argsort_by_words(words: List[jnp.ndarray], cap: int) -> jnp.ndarray:
    """Stable permutation (int32[cap]) ordering rows by the word tuple."""
    iota = jnp.arange(cap, dtype=jnp.int32)
    out = jax.lax.sort(tuple(words) + (iota,), num_keys=len(words),
                       is_stable=True)
    return out[-1]


def keys_equal_prev(vals: List[DevVal]) -> jnp.ndarray:
    """bool[cap]: row i's key tuple exactly equals row i-1's (False at i=0).

    Used by sort-based groupby for exact segment boundaries.  Strings compare
    by (length, prefix words, dual 64-bit full hash) — an engineered-collision
    risk only, far stronger than the 32-bit hashes the reference partitions by.
    """
    cap = int(vals[0].validity.shape[0])
    eq = jnp.ones(cap, dtype=jnp.bool_)

    def shift_ne(x):
        prev = jnp.concatenate([x[:1], x[:-1]])
        return x != prev

    for v in vals:
        eq = eq & ~shift_ne(v.validity)
        if v.dtype.is_string:
            from spark_rapids_tpu.exprs.strings import string_hash2
            lens = (v.offsets[1:] - v.offsets[:-1]).astype(jnp.int32)
            h1, h2 = string_hash2(v)
            for x in (lens, h1, h2):
                same = ~shift_ne(x)
                eq = eq & jnp.where(v.validity, same, True)
        else:
            same = ~shift_ne(_encode_fixed(v))
            eq = eq & jnp.where(v.validity, same, True)
    eq = eq.at[0].set(False)
    return eq
