"""MXU slot aggregation: groupby as a one-hot matmul contraction.

The sort-based groupby (kernels/groupby.py — cudf's sort-groupby analogue)
pays an argsort plus several full-size gathers and scatter reductions per
batch; on TPU every one of those is an HBM-bound pass (~100-300 ms at 4M
rows).  This path instead aggregates straight into a fixed table of slots
with ONE fused one-hot contraction — the systolic array does the
segmented reduction:

  slot = key - min(key)                       # elementwise, EXACT
  sums = stacked_value_rows @ one_hot(slot)   # ONE einsum on the MXU

Slotting by the key's own value range makes slot <-> key a bijection —
no hash, no collisions, no purity machinery, and the output key columns
are reconstructed from slot indices without touching the input again.
Multi-column keys pack into ONE slot index by mixed radix: each
integral/date/bool key contributes a digit (its offset from the batch
minimum, plus a NULL digit when the column has NULLs) and the product of
radices must fit the table.  A batch whose packed key space exceeds the
table (or holds non-finite floats for a float sum) raises a
device-visible flag and the caller re-runs the exact sort path —
correctness never depends on data shape.

min/max/first/last ride the SAME slot index through the aggregate
classes' own segment kernels (one scatter-reduce pass, unsorted ids) —
bit-identical buffers and semantics to the sort path, minus the argsort.

Exactness of the reductions:
* Integer sums/counts ride 8-bit limb rows accumulated in f32 over
  bounded chunks (chunk sums stay < 2^24, exact in f32), recombined in
  int64 — bit-exact, including wrap-around.
* Float sums are 53-bit fixed-point limb rows against a per-chunk scale —
  error is at the final f64-rounding level (~1 ulp per chunk), tighter
  than a variable-order device reduction.

Reference role: the cudf hash aggregate (aggregate.scala:456) — re-imagined
for the MXU instead of a GPU hash table.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import (
    ColumnBatch, DeviceColumn, round_up_capacity,
)
from spark_rapids_tpu.exprs.base import DevVal
from spark_rapids_tpu.kernels.layout import compaction_indices

TABLE_SLOTS = 8192          # key-range capacity of the slot table
_CHUNK = 16384              # rows per exact-f32 accumulation chunk
_SIGN32 = np.uint32(0x80000000)


def _limb_rows_u32(w, use, bits: int) -> List[jnp.ndarray]:
    """f32 rows of ``bits``-wide limbs of a u32 word, zeroed where !use."""
    mask = jnp.uint32((1 << bits) - 1)
    rows = []
    for j in range(32 // bits):
        limb = ((w >> jnp.uint32(bits * j)) & mask).astype(jnp.float32)
        rows.append(jnp.where(use, limb, 0.0))
    return rows


def _int_value_words(x, use) -> List[Tuple[jnp.ndarray, bool]]:
    """(u32 word, biased) pairs whose limb sums recombine to sum(x) in
    int64.  The hi word is sign-biased by 2^31 so limbs stay unsigned."""
    x = x.astype(jnp.int64)
    lo = jax.lax.convert_element_type(x & jnp.int64(0xFFFFFFFF),
                                      jnp.uint32)
    hi = jax.lax.convert_element_type(
        (x >> jnp.int64(32)) & jnp.int64(0xFFFFFFFF), jnp.uint32)
    return [(jnp.where(use, lo, jnp.uint32(0)), False),
            (jnp.where(use, hi ^ _SIGN32, jnp.uint32(0)), True)]


_FIX_BITS = 53  # fixed-point precision of the float limb rows


def _float_limb_rows(x, use, nc: int, c: int):
    """(7 f32 limb rows, per-chunk f64 scales) for exact-ish float sums.

    Per chunk: scale = max|x| over the chunk; q = (x/scale + 1) * 2^53
    as int64; 8-bit limbs of q.  Rows accumulate exactly in f32 (ints
    < 2^24 per chunk); recombination is exact integer math until one
    final f64 rounding — per-row truncation error <= scale * 2^-53."""
    x = x.astype(jnp.float64)
    ax = jnp.abs(jnp.where(use, x, 0.0)).reshape(nc, c)
    cmax = jnp.max(ax, axis=1)
    scale = jnp.where(cmax > 0, cmax, 1.0)               # >= max|x|
    y = x.reshape(nc, c) / scale[:, None]                # in [-1, 1]
    z = jnp.where(use.reshape(nc, c), y + 1.0, 0.0)      # in [0, 2]
    qi = (z * float(2 ** _FIX_BITS)).astype(jnp.int64)   # <= 2^54
    rows = []
    for j in range(7):
        sh = jnp.int64(8 * (6 - j))
        limb = ((qi >> sh) & jnp.int64(0xFF)).astype(jnp.float32)
        rows.append(limb.reshape(nc * c))
    return rows, scale


def hash_group_aggregate(batch: ColumnBatch, key_vals: List[DevVal],
                         agg_inputs: List[DevVal], agg_fns: Sequence,
                         key_schema: T.Schema,
                         out_schema: T.Schema,
                         table: int = TABLE_SLOTS):
    """(group-key batch, per-agg buffer lists, n_groups, fallback flag).

    Buffer layout matches the sort-based update path (consumed unchanged
    by the merge stage).  ``fallback`` True means the key range did not
    fit the slot table (or a float sum saw non-finite values) — the
    caller MUST discard the result and use the sort path."""
    from spark_rapids_tpu.exprs.aggregates import (
        Average, Count, First, Last, Max, Min, Sum, unsorted_segment_ids,
    )

    cap = batch.capacity
    c = min(_CHUNK, cap)
    nc = cap // c
    live = jnp.arange(cap, dtype=jnp.int32) < batch.num_rows

    # ---- mixed-radix slot packing over all key columns -------------------
    # digit_i = k_i - min_i (or range_i for NULL); radix_i = range_i +
    # has_null_i; slot = sum(digit_i * stride_i).  Bijective onto
    # [0, prod(radix)); fallback when the packed space exceeds table+1.
    i64max = jnp.int64(jnp.iinfo(jnp.int64).max)
    i64min = jnp.int64(jnp.iinfo(jnp.int64).min)
    fallback = jnp.asarray(False)
    slot64 = jnp.zeros(cap, jnp.int64)
    stride = jnp.int64(1)
    prod_f = jnp.float64(1.0)
    key_decode = []  # (kmin, rng, radix, stride) per key, for output
    for kv in key_vals:
        kx = kv.data.astype(jnp.int64)
        usek = live & kv.validity
        any_key = jnp.any(usek)
        has_null = jnp.any(live & ~kv.validity)
        kmin = jnp.min(jnp.where(usek, kx, i64max))
        kmax = jnp.max(jnp.where(usek, kx, i64min))
        # wrap-around of (kmax - kmin) goes negative -> correctly rejected
        key_fits = (kmax - kmin >= 0) & (kmax - kmin < table + 1)
        fallback = fallback | (any_key & ~key_fits)
        kmin = jnp.where(any_key & key_fits, kmin, jnp.int64(0))
        rng = jnp.where(any_key & key_fits, kmax - kmin + 1, jnp.int64(0))
        radix = jnp.maximum(rng + has_null.astype(jnp.int64), jnp.int64(1))
        digit = jnp.where(usek, jnp.clip(kx - kmin, 0, table), rng)
        slot64 = slot64 + digit * stride
        key_decode.append((kmin, rng, radix, stride))
        stride = stride * radix
        prod_f = prod_f * radix.astype(jnp.float64)
    # capacity check in f64: an int64 stride product can wrap silently
    fallback = fallback | (prod_f > jnp.float64(table + 1))

    # slots: 0..table = packed key tuples, table+1 = dead rows
    tt = table + 2
    slot = jnp.where(live, jnp.clip(slot64, 0, table).astype(jnp.int32),
                     jnp.int32(table + 1))

    # ---- stacked einsum rows ---------------------------------------------
    rows: List[jnp.ndarray] = [live.astype(jnp.float32)]  # per-slot count
    agg_plan = []                                         # recombination
    for fn, v in zip(agg_fns, agg_inputs):
        if type(fn) in (Min, Max, First, Last):
            # one scatter-reduce pass over the same slot ids, via the
            # aggregate's own segment kernel (sort-path parity)
            agg_plan.append(("segment", fn, v))
            continue
        use = v.validity & live
        use_at = len(rows)
        rows.append(use.astype(jnp.float32))              # per-agg count
        if type(fn) is Count:
            agg_plan.append(("count", use_at))
            continue
        if v.dtype.is_integral or v.dtype == T.BOOLEAN:
            spec = []
            for w, biased in _int_value_words(v.data, use):
                at = len(rows)
                rows.extend(_limb_rows_u32(w, use, 8))
                spec.append((at, biased))
            agg_plan.append(("int_sum", use_at, spec, type(fn)))
        else:
            # fixed-point rows require finite, sanely-scaled values —
            # NaN/Inf (or near-overflow) batches take the sort path,
            # which propagates them with float semantics
            x64 = v.data.astype(jnp.float64)
            fallback = fallback | jnp.any(
                use & (~jnp.isfinite(x64) |
                       (jnp.abs(x64) > float(2.0 ** 1000))))
            at = len(rows)
            fr, scale = _float_limb_rows(v.data, use, nc, c)
            rows.extend(fr)
            agg_plan.append(("float_sum", use_at, at, scale, type(fn)))

    r_n = len(rows)
    stacked = jnp.stack(rows, axis=0)                     # [R, cap] f32
    stacked = stacked.reshape(r_n, nc, c).transpose(1, 0, 2)
    oh = jax.nn.one_hot(slot.reshape(nc, c), tt, dtype=jnp.float32)
    per_chunk = jnp.einsum("crn,cnt->crt", stacked, oh,
                           preferred_element_type=jnp.float32)
    # chunk partials are exact integers < 2^23: accumulate across chunks
    # in native i32 lanes up to 256 chunks (256 * 2^23 < 2^31), then in
    # i64 — a flat i32 sum would overflow past ~4M rows per batch
    pc_i = per_chunk.astype(jnp.int32)
    if nc > 256:
        pc_i = pc_i.reshape(nc // 256, 256, r_n, tt).sum(axis=1)
    totals_i = jnp.sum(pc_i.astype(jnp.int64), axis=0)    # [R, tt]

    live_cnt = totals_i[0]
    used = live_cnt[:table + 1] > 0                       # incl NULL group

    # ---- buffers ----------------------------------------------------------
    def _int_total(spec, use_at):
        total = jnp.zeros(tt, jnp.int64)
        for base_at, biased in spec:
            word_sum = jnp.zeros(tt, jnp.int64)
            for k in range(4):
                word_sum = word_sum + (totals_i[base_at + k]
                                       << jnp.int64(8 * k))
            if biased:
                cnt = totals_i[use_at]
                word_sum = (word_sum - (cnt << jnp.int64(31))) \
                    << jnp.int64(32)
            total = total + word_sum
        return total

    ng = table + 1
    ones_t = jnp.ones(ng, jnp.bool_)
    buffer_cols: List[List[DevVal]] = []
    for plan, fn in zip(agg_plan, agg_fns):
        kind = plan[0]
        if kind == "segment":
            _, sfn, sv = plan
            with unsorted_segment_ids():
                sb = sfn.segment_update(sv, slot, tt, live)
            bufs = [DevVal(b.dtype, b.data[:ng], b.validity[:ng])
                    for b in sb]
        elif kind == "count":
            cnt = totals_i[plan[1]][:ng]
            bufs = [DevVal(T.LONG, cnt, ones_t)]
        elif kind == "int_sum":
            _, use_at, spec, fcls = plan
            total = _int_total(spec, use_at)[:ng]
            cnt = totals_i[use_at][:ng]
            if fcls is Sum:
                bufs = [DevVal(fn.dtype, total.astype(fn.dtype.jnp_dtype),
                               ones_t),
                        DevVal(T.BOOLEAN, cnt > 0, ones_t)]
            else:  # Average over ints: exact f64 sum from the i64 total
                bufs = [DevVal(T.DOUBLE, total.astype(jnp.float64),
                               ones_t),
                        DevVal(T.LONG, cnt, ones_t)]
        else:  # float_sum
            _, use_at, base_at, scale, fcls = plan
            z = jnp.zeros((nc, tt), jnp.float64)
            for j in range(7):
                z = z + per_chunk[:, base_at + j, :].astype(jnp.float64) \
                    * float(2 ** (8 * (6 - j)))
            cnt_pc = per_chunk[:, use_at, :].astype(jnp.float64)
            y = z / float(2 ** _FIX_BITS) - cnt_pc
            total = jnp.sum(y * scale[:, None], axis=0)[:ng]
            cnt = totals_i[use_at][:ng]
            if fcls is Sum:
                bufs = [DevVal(T.DOUBLE, total, ones_t),
                        DevVal(T.BOOLEAN, cnt > 0, ones_t)]
            else:
                bufs = [DevVal(T.DOUBLE, total, ones_t),
                        DevVal(T.LONG, cnt, ones_t)]
        buffer_cols.append(bufs)

    # ---- compact used slots; keys reconstructed from slot indices -------
    # (mixed-radix decode: digit_i = (slot // stride_i) % radix_i; the
    # NULL digit rng_i decodes to validity False)
    idx, n_groups = compaction_indices(used, jnp.asarray(ng, jnp.int32))
    out_cap = round_up_capacity(ng)
    idx_p = jnp.pad(idx, (0, out_cap - idx.shape[0]))
    live_out = jnp.arange(out_cap, dtype=jnp.int32) < n_groups
    key_cols = []
    for kf, (kmin, rng, radix, stride) in zip(key_schema.fields,
                                              key_decode):
        d = (idx_p.astype(jnp.int64) // stride) % radix
        key_data = (kmin + d).astype(kf.dtype.jnp_dtype)
        key_valid = (d < rng) & live_out
        key_cols.append(DeviceColumn(kf.dtype, key_data, key_valid, None))
    group_keys = ColumnBatch(key_schema, key_cols, n_groups, out_cap)

    def _pad(a):
        return jnp.pad(a, [(0, out_cap - a.shape[0])] +
                       [(0, 0)] * (a.ndim - 1))

    compact_bufs = [[DevVal(b.dtype, _pad(b.data[idx]),
                            _pad(b.validity[idx])) for b in bufs]
                    for bufs in buffer_cols]
    return group_keys, compact_bufs, n_groups, fallback


def hash_agg_capable(mode: str, key_types: List[T.DataType],
                     agg_fns: Sequence) -> bool:
    """Static capability check: the MXU path covers sum/count/avg (einsum
    limb rows) plus min/max/first/last (slot scatter-reduce) over
    fixed-width inputs, grouped by any number of integral/date/bool keys
    (mixed-radix slot packing) or no key (global reduction)."""
    from spark_rapids_tpu.exprs.aggregates import (
        Average, Count, First, Last, Max, Min, Sum,
    )
    if mode != "update":
        return False
    for kt in key_types:
        if not (kt.is_integral or kt in (T.DATE, T.BOOLEAN)):
            return False
    for fn in agg_fns:
        if type(fn) in (Sum, Average, Min, Max, First, Last):
            if fn.child.dtype.is_string or fn.child.dtype.is_array:
                return False
        elif type(fn) is not Count:
            return False
    return True
