"""Row-movement kernels: gather, filter compaction, concatenation, head.

Reference analogues: cudf ``Table.filter`` (basicPhysicalOperators.scala:121),
``Table.concatenate`` (GpuCoalesceBatches.scala), ``contiguousSplit`` /
gather-based slicing (GpuPartitioning.scala:44-117).

All kernels are pure functions over pytree :class:`ColumnBatch` values and are
safe to call inside ``jax.jit``.  Output capacities are static arguments.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import (
    ColumnBatch, DeviceColumn, round_up_capacity,
)


def _string_lengths(col: DeviceColumn):
    return (col.offsets[1:] - col.offsets[:-1]).astype(jnp.int32)


def _rows_of_positions(offsets, nbytes: int):
    pos = jnp.arange(nbytes, dtype=jnp.int32)
    return jnp.searchsorted(offsets[1:], pos, side="right").astype(jnp.int32)


def _gather_string_column(col: DeviceColumn, indices, live, out_cap: int,
                          out_byte_cap: int) -> DeviceColumn:
    """Gather whole varlen rows (strings, arrays): new row r = old row
    indices[r].

    Output elements are rebuilt with the flat position->row mapping (one
    searchsorted over the new offsets), so the whole thing is gathers +
    a cumsum — no per-row loops.
    """
    src_lens = _string_lengths(col)
    new_lens = jnp.where(live, src_lens[indices], 0)
    new_offsets = jnp.concatenate([
        jnp.zeros(1, dtype=jnp.int32),
        jnp.cumsum(new_lens).astype(jnp.int32),
    ])
    rows = _rows_of_positions(new_offsets, out_byte_cap)
    rows_c = jnp.clip(rows, 0, out_cap - 1)
    pos_in_row = jnp.arange(out_byte_cap, dtype=jnp.int32) - new_offsets[rows_c]
    src_row = indices[rows_c]
    src_pos = col.offsets[src_row] + pos_in_row
    in_range = jnp.arange(out_byte_cap, dtype=jnp.int32) < new_offsets[-1]
    src_pos = jnp.clip(src_pos, 0, int(col.data.shape[0]) - 1)
    data = jnp.where(in_range, col.data[src_pos], 0).astype(col.data.dtype)
    validity = jnp.where(live, col.validity[indices], False)
    return DeviceColumn(col.dtype, data, validity, new_offsets)


def gather_rows(batch: ColumnBatch, indices, num_rows,
                out_capacity: Optional[int] = None,
                out_byte_caps: Optional[Sequence[int]] = None,
                keep_encoded: bool = False) -> ColumnBatch:
    """New batch whose row r is ``batch`` row ``indices[r]`` for r < num_rows.

    ``indices`` must be int32[out_capacity] (entries past ``num_rows`` are
    ignored).  ``out_byte_caps`` optionally gives the static byte capacity per
    string column (defaults to the input column's byte capacity — valid
    whenever the gather cannot grow total bytes, e.g. permutations/filters).

    ``keep_encoded`` keeps dictionary-encoded columns encoded: the gather
    permutes the 4-byte codes and shares the input's dictionary buffers
    unchanged.  Only valid when the gather cannot grow the materialized
    total (permutations/filters — exactly when the default byte caps are
    valid), since ``mat_byte_cap`` is carried through as-is.
    """
    if not keep_encoded:
        batch = ensure_row_layout(batch)
    out_cap = out_capacity if out_capacity is not None else batch.capacity
    live = jnp.arange(out_cap, dtype=jnp.int32) < num_rows
    indices = jnp.clip(indices.astype(jnp.int32), 0, batch.capacity - 1)
    indices = jnp.where(live, indices, 0)
    cols = []
    str_i = 0
    for col in batch.columns:
        if col.codes is not None:
            if out_byte_caps is not None:
                str_i += 1  # slot reserved; encoded keeps its mat bucket
            codes = jnp.where(live, col.codes[indices], 0)
            validity = jnp.where(live, col.validity[indices], False)
            cols.append(DeviceColumn(col.dtype, col.data, validity,
                                     col.offsets, codes, col.mat_byte_cap))
        elif col.is_varlen:
            bcap = (out_byte_caps[str_i] if out_byte_caps is not None
                    else int(col.data.shape[0]))
            str_i += 1
            cols.append(_gather_string_column(col, indices, live, out_cap, bcap))
        else:
            data = jnp.where(live, col.data[indices], 0).astype(col.data.dtype)
            validity = jnp.where(live, col.validity[indices], False)
            cols.append(DeviceColumn(col.dtype, data, validity, None))
    return ColumnBatch(batch.schema, cols, jnp.asarray(num_rows, jnp.int32),
                       out_cap)


def dict_decode_column(col: DeviceColumn) -> DeviceColumn:
    """Materialize a dictionary-encoded string column to plain row layout.

    The column's data/offsets describe the dictionary ENTRIES; ``codes``
    maps rows to entries and ``mat_byte_cap`` is the static byte bucket
    the materialized bytes fit in (computed at staging from the live
    codes).  Output matches what staging the decoded values would have
    produced: invalid/dead rows contribute zero bytes, offsets constant
    past the live region.  Safe inside ``jax.jit``.
    """
    assert col.codes is not None
    cap = int(col.codes.shape[0])
    nd = int(col.offsets.shape[0]) - 1
    ent_lens = (col.offsets[1:] - col.offsets[:-1]).astype(jnp.int32)
    codes_c = jnp.clip(col.codes, 0, max(nd - 1, 0))
    lens = jnp.where(col.validity, ent_lens[codes_c], 0)
    new_offsets = jnp.concatenate([
        jnp.zeros(1, dtype=jnp.int32),
        jnp.cumsum(lens).astype(jnp.int32),
    ])
    bcap = col.mat_byte_cap if col.mat_byte_cap > 0 else int(col.data.shape[0])
    rows = _rows_of_positions(new_offsets, bcap)
    rows_c = jnp.clip(rows, 0, cap - 1)
    pos_in_row = jnp.arange(bcap, dtype=jnp.int32) - new_offsets[rows_c]
    src_pos = col.offsets[codes_c[rows_c]] + pos_in_row
    src_pos = jnp.clip(src_pos, 0, int(col.data.shape[0]) - 1)
    in_range = jnp.arange(bcap, dtype=jnp.int32) < new_offsets[-1]
    data = jnp.where(in_range, col.data[src_pos], 0).astype(col.data.dtype)
    return DeviceColumn(col.dtype, data, col.validity, new_offsets)


def ensure_row_layout(batch: ColumnBatch) -> ColumnBatch:
    """Materialize any dictionary-encoded columns of ``batch`` to plain
    row layout.  Python-level no-op (returns the same object) when none
    are encoded, so it is free at every exec entry; the decode itself is
    traceable and safe inside ``jax.jit``."""
    if not any(c.codes is not None for c in batch.columns):
        return batch
    cols = [dict_decode_column(c) if c.codes is not None else c
            for c in batch.columns]
    return ColumnBatch(batch.schema, cols, batch.num_rows, batch.capacity)


def row_slices(batch: ColumnBatch, total_rows: int, rows_per: int):
    """Yield right-sized row-range slices of ``batch``, ``rows_per`` rows
    each.  ONE host round trip sizes every slice's varlen buffers from the
    offsets; slices past ``total_rows`` are not produced."""
    bounds = list(range(0, total_rows, max(rows_per, 1))) + [total_rows]
    varlen = [c for c in batch.columns if c.is_varlen]
    marks = jax.device_get(
        [c.offsets[jnp.asarray(bounds, jnp.int32)] for c in varlen]) \
        if varlen else []
    for i in range(len(bounds) - 1):
        start, cnt = bounds[i], bounds[i + 1] - bounds[i]
        pcap = round_up_capacity(cnt)
        idx = start + jnp.arange(pcap, dtype=jnp.int32)
        bcaps = [round_up_capacity(max(int(m[i + 1] - m[i]), 16),
                                   minimum=16) for m in marks]
        yield gather_rows(batch, idx, jnp.asarray(cnt, jnp.int32),
                          out_capacity=pcap, out_byte_caps=bcaps or None)


def compaction_indices(mask, num_rows):
    """(indices, count): stable order of rows where mask is True and live.

    ``indices`` is int32[cap] — positions of kept rows first (stable),
    then arbitrary padding.  Sort-free AND search-free: a cumsum ranks the
    kept rows and one scatter inverts the ranking.  A boolean stable-argsort
    is an O(n log^2 n) bitonic sort on TPU (~300 ms at 2M rows), and a
    searchsorted inversion is ~22 dependent gathers per row (~350 ms at
    4M); cumsum + scatter is two HBM passes.
    """
    cap = int(mask.shape[0])
    live = jnp.arange(cap, dtype=jnp.int32) < num_rows
    keep = mask & live
    csum = jnp.cumsum(keep.astype(jnp.int32))
    count = csum[cap - 1] if cap else jnp.int32(0)
    iota = jnp.arange(cap, dtype=jnp.int32)
    # kept row i lands at slot csum[i]-1; dropped row i scatters to the
    # GENUINELY unique out-of-bounds slot cap+i (mode="drop" discards it)
    # so the unique_indices promise holds and XLA emits a plain scatter
    # instead of a sort-based one.
    target = jnp.where(keep, csum - 1, cap + iota)
    idx = jnp.zeros(cap, dtype=jnp.int32).at[target].set(
        iota, mode="drop", unique_indices=True)
    return idx, count.astype(jnp.int32)


def compact(batch: ColumnBatch, mask) -> ColumnBatch:
    """Filter: keep rows where mask (bool[cap]) is True.  Single-phase —
    output capacity = input capacity (a filter can only shrink)."""
    indices, count = compaction_indices(mask, batch.num_rows)
    return gather_rows(batch, indices, count)


def take_head(batch: ColumnBatch, limit) -> ColumnBatch:
    """LocalLimit: clamp the live-row count (no data movement)."""
    n = jnp.minimum(batch.num_rows, jnp.asarray(limit, jnp.int32))
    return ColumnBatch(batch.schema, batch.columns, n, batch.capacity)


def _pack_kway(vals_list, los, his, out_cap: int):
    """K-way segment pack: input j's window ``[los[j], his[j])`` lands at
    the running output offset ``sum(his[:j] - los[:j])``; zeros elsewhere.

    This is THE scatter shape shared by every k-way assembly loop below
    (concat rows/bytes, split segments rows/bytes, dict code/byte
    merges): each value scatters once, rows outside the window target
    genuinely unique out-of-bounds slots (``out_cap + i``) so
    ``mode="drop"`` discards them while the ``unique_indices`` promise
    stays true and XLA emits a plain scatter.  The kernel tier's
    ``gatherScatter`` Pallas pack replaces the whole chain with one pass
    per output block when engaged (bit-identical; unsupported dtypes and
    degenerate shapes always take the XLA chain)."""
    los = [jnp.asarray(lo, jnp.int32) for lo in los]
    his = [jnp.asarray(hi, jnp.int32) for hi in his]

    def xla():
        out = jnp.zeros(out_cap, dtype=vals_list[0].dtype)
        off = jnp.asarray(0, jnp.int32)
        for vals, lo, hi in zip(vals_list, los, his):
            iota = jnp.arange(int(vals.shape[0]), dtype=jnp.int32)
            rel = iota - lo
            in_seg = (rel >= 0) & (iota < hi)
            tgt = jnp.where(in_seg, off + rel, out_cap + iota)
            out = out.at[tgt].set(vals, mode="drop", unique_indices=True)
            off = off + (hi - lo)
        return out

    from spark_rapids_tpu.kernels import pallas_tier as PT
    if out_cap < 1 or not PT.pack_supported(vals_list) or \
            any(int(v.shape[0]) < 1 for v in vals_list):
        return xla()
    resident = sum(int(v.shape[0]) * v.dtype.itemsize for v in vals_list)
    return PT.run(
        "gatherScatter",
        lambda interpret: PT.pack_segments(vals_list, los, his, out_cap,
                                           interpret=interpret),
        xla, resident_bytes=resident)


def concat_kway(batches: Sequence[ColumnBatch], out_capacity: int,
                out_byte_caps: Optional[Sequence[int]] = None) -> ColumnBatch:
    """Concatenate k batches (same schema) into ONE output allocation.

    The pairwise chain materializes k-1 growing intermediates, each a full
    read+write of everything concatenated so far — O(k * out_capacity) HBM
    traffic.  Here every input is written exactly ONCE at its row (and, for
    varlen columns, byte) offset: per input j, a scatter places its live
    rows at ``sum(num_rows[:j]) + i``; dead rows target genuinely unique
    out-of-bounds slots (``out_capacity + i``) so ``mode="drop"`` discards
    them while the ``unique_indices`` promise stays true and XLA emits a
    plain scatter (see :func:`compaction_indices`).

    Bit-identical to the :func:`concat_pair` chain: rows packed in input
    order, zeros past the live rows, varlen offsets rebuilt from one cumsum
    of the scattered live lengths (constant past the live total).  Safe
    inside ``jax.jit``; ``out_byte_caps`` defaults to the summed input byte
    capacities, matching the chain's accumulated default.
    """
    assert batches
    batches = [ensure_row_layout(b) for b in batches]
    if len(batches) == 1:
        return batches[0]
    schema = batches[0].schema
    for b in batches[1:]:
        assert b.schema == schema, f"{b.schema} != {schema}"
    ns = [b.num_rows for b in batches]
    acc = jnp.asarray(0, jnp.int32)
    for n in ns:
        acc = acc + n
    total = acc.astype(jnp.int32)
    zeros_lo = [jnp.asarray(0, jnp.int32)] * len(batches)

    def pack_rows(values_per_batch):
        return _pack_kway(values_per_batch, zeros_lo, ns, out_capacity)

    cols = []
    str_i = 0
    for ci, f in enumerate(schema.fields):
        parts = [b.columns[ci] for b in batches]
        validity = pack_rows([c.validity for c in parts])
        if parts[0].is_varlen:
            bcap = (out_byte_caps[str_i] if out_byte_caps is not None
                    else sum(int(c.data.shape[0]) for c in parts))
            str_i += 1
            lens = pack_rows([_string_lengths(c) for c in parts])
            new_offsets = jnp.concatenate([
                jnp.zeros(1, dtype=jnp.int32),
                jnp.cumsum(lens).astype(jnp.int32),
            ])
            # LIVE bytes only (offsets[num_rows], not offsets[-1]):
            # take_head truncates num_rows without repacking, so dead
            # rows keep growing offsets — their bytes must neither
            # advance the cursor nor overwrite the next input's region
            data = _pack_kway([c.data for c in parts], zeros_lo,
                              [c.offsets[n] for c, n in zip(parts, ns)],
                              bcap)
            cols.append(DeviceColumn(f.dtype, data, validity, new_offsets))
        else:
            data = pack_rows([c.data for c in parts])
            cols.append(DeviceColumn(f.dtype, data, validity, None))
    return ColumnBatch(schema, cols, total, out_capacity)


def _concat_kway_tuple(batches, out_capacity, out_byte_caps):
    return concat_kway(list(batches), out_capacity,
                       list(out_byte_caps) if out_byte_caps else None)


def concat_kway_run(batches: Sequence[ColumnBatch], out_capacity: int,
                    out_byte_caps: Optional[Sequence[int]] = None
                    ) -> ColumnBatch:
    """Eager-path entry: ONE compiled dispatch for the whole k-way concat
    (the pairwise chain ran as an eager op storm).  Cached per
    (input shape-bucket tuple, output caps) like every instrumented jit."""
    from spark_rapids_tpu.utils.compile_registry import instrumented_jit
    global _CONCAT_KWAY_JIT
    if _CONCAT_KWAY_JIT is None:
        _CONCAT_KWAY_JIT = instrumented_jit(
            _concat_kway_tuple, label="kernels:concatKway",
            static_argnames=("out_capacity", "out_byte_caps"))
    return _CONCAT_KWAY_JIT(
        tuple(batches), out_capacity,
        tuple(out_byte_caps) if out_byte_caps else None)


_CONCAT_KWAY_JIT = None


def gather_segments_kway(batches: Sequence[ColumnBatch], starts, counts,
                         out_capacity: int,
                         out_byte_caps: Optional[Sequence[int]] = None,
                         keep_encoded: bool = False) -> ColumnBatch:
    """Gather one contiguous row segment per input batch into ONE packed
    output batch: input j contributes rows ``[starts[j], starts[j]+counts[j])``
    at output row offset ``sum(counts[:j])``.

    This is the shuffle split's coalescing primitive: each input is a
    pid-sorted batch whose target-partition rows are contiguous, so one
    call assembles a whole target partition from every input batch — the
    write-combining replacement for one :func:`gather_rows` per
    (batch, partition) pair.  Same scatter shape as :func:`concat_kway`:
    every input is written exactly once at its row/byte offset, and rows
    outside the segment target genuinely unique out-of-bounds slots
    (``out_capacity + i``) so ``mode="drop"`` discards them while the
    ``unique_indices`` promise stays true.

    ``starts``/``counts`` are traced int32 scalars — different segment
    positions ride the same compiled program (the cache keys only on input
    capacity buckets and the static output caps).  Segments must lie
    within each input's live rows, so the varlen byte window
    ``offsets[start] .. offsets[start+count]`` covers exactly the
    segment's live bytes (offsets are constant past ``num_rows`` by
    construction; see concat_kway's live-bytes note).

    ``keep_encoded`` (dict-aware shuffle, docs/shuffle.md): when every
    input part of a string column is dictionary-encoded, the output stays
    encoded — codes are scattered with a per-input entry-base shift and
    the input dictionaries are packed back-to-back into one merged
    dictionary (entry bases are static: the cumsum of input dictionary
    capacities; byte bases are traced: the cumsum of live dictionary
    bytes, matching one dynamic scatter cursor per input exactly like
    concat_kway's byte packing).  The column's ``out_byte_caps`` slot
    then carries the OUTPUT ``mat_byte_cap`` (the materialized bucket a
    later :func:`dict_decode_column` needs), not a data-buffer capacity —
    the merged dictionary's capacity is the static sum of the input
    dictionary capacities.  Columns with any plain part fall back to
    materializing the encoded parts first.
    """
    assert batches
    if not keep_encoded:
        batches = [ensure_row_layout(b) for b in batches]
    schema = batches[0].schema
    for b in batches[1:]:
        assert b.schema == schema, f"{b.schema} != {schema}"
    starts = [jnp.asarray(s, jnp.int32) for s in starts]
    counts = [jnp.asarray(c, jnp.int32) for c in counts]
    seg_his = [s + c for s, c in zip(starts, counts)]
    acc = jnp.asarray(0, jnp.int32)
    for c in counts:
        acc = acc + c
    total = acc.astype(jnp.int32)

    def pack_segments(values_per_batch):
        return _pack_kway(values_per_batch, starts, seg_his, out_capacity)

    cols = []
    str_i = 0
    for ci, f in enumerate(schema.fields):
        parts = [b.columns[ci] for b in batches]
        if keep_encoded and any(c.codes is not None for c in parts) \
                and not all(c.codes is not None for c in parts):
            # mixed encoded/plain parts: no shared dictionary space exists,
            # so materialize the encoded ones and take the plain path
            parts = [dict_decode_column(c) if c.codes is not None else c
                     for c in parts]
        validity = pack_segments([c.validity for c in parts])
        if keep_encoded and all(c.codes is not None for c in parts):
            mat_cap = (out_byte_caps[str_i] if out_byte_caps is not None
                       else sum((c.mat_byte_cap or int(c.data.shape[0]))
                                for c in parts))
            str_i += 1
            shifted_codes = []
            ent_lens_parts = []
            entry_base = 0  # static: dictionary capacities are shapes
            for c in parts:
                shifted_codes.append(c.codes + entry_base)
                ent_lens_parts.append(
                    (c.offsets[1:] - c.offsets[:-1]).astype(jnp.int32))
                entry_base += int(c.offsets.shape[0]) - 1
            codes = pack_segments(shifted_codes)
            # merged dictionary: entry lens concatenate at static bases, so
            # one cumsum yields offsets whose per-input byte base equals the
            # dynamic packing cursor below (padded entries have zero lens)
            merged_offsets = jnp.concatenate([
                jnp.zeros(1, dtype=jnp.int32),
                jnp.cumsum(jnp.concatenate(ent_lens_parts)).astype(jnp.int32),
            ])
            dcap = sum(int(c.data.shape[0]) for c in parts)
            data = _pack_kway(
                [c.data for c in parts],
                [jnp.asarray(0, jnp.int32)] * len(parts),
                [c.offsets[int(c.offsets.shape[0]) - 1] for c in parts],
                dcap)
            cols.append(DeviceColumn(f.dtype, data, validity, merged_offsets,
                                     codes, mat_cap))
        elif parts[0].is_varlen:
            bcap = (out_byte_caps[str_i] if out_byte_caps is not None
                    else sum(int(c.data.shape[0]) for c in parts))
            str_i += 1
            lens = pack_segments([_string_lengths(c) for c in parts])
            new_offsets = jnp.concatenate([
                jnp.zeros(1, dtype=jnp.int32),
                jnp.cumsum(lens).astype(jnp.int32),
            ])
            data = _pack_kway(
                [c.data for c in parts],
                [c.offsets[s] for c, s in zip(parts, starts)],
                [c.offsets[s + n] for c, s, n in zip(parts, starts, counts)],
                bcap)
            cols.append(DeviceColumn(f.dtype, data, validity, new_offsets))
        else:
            data = pack_segments([c.data for c in parts])
            cols.append(DeviceColumn(f.dtype, data, validity, None))
    return ColumnBatch(schema, cols, total, out_capacity)


def _gather_segments_kway_tuple(batches, starts, counts, out_capacity,
                                out_byte_caps, keep_encoded=False):
    return gather_segments_kway(
        list(batches), list(starts), list(counts), out_capacity,
        list(out_byte_caps) if out_byte_caps else None,
        keep_encoded=keep_encoded)


def gather_segments_kway_run(batches: Sequence[ColumnBatch], starts, counts,
                             out_capacity: int,
                             out_byte_caps: Optional[Sequence[int]] = None,
                             keep_encoded: bool = False) -> ColumnBatch:
    """Eager-path entry: ONE compiled dispatch assembles a whole target
    partition from k pid-sorted batches.  Segment positions are traced, so
    every partition of a shuffle (and every repeat query) reuses the same
    executable per (input bucket tuple, output caps)."""
    from spark_rapids_tpu.utils.compile_registry import instrumented_jit
    global _GATHER_SEGMENTS_KWAY_JIT
    if _GATHER_SEGMENTS_KWAY_JIT is None:
        _GATHER_SEGMENTS_KWAY_JIT = instrumented_jit(
            _gather_segments_kway_tuple, label="kernels:gatherSegmentsKway",
            static_argnames=("out_capacity", "out_byte_caps", "keep_encoded"))
    return _GATHER_SEGMENTS_KWAY_JIT(
        tuple(batches),
        tuple(jnp.asarray(s, jnp.int32) for s in starts),
        tuple(jnp.asarray(c, jnp.int32) for c in counts),
        out_capacity,
        tuple(out_byte_caps) if out_byte_caps else None,
        keep_encoded)


_GATHER_SEGMENTS_KWAY_JIT = None


def stacked_row_compaction_indices(counts, n: int, cap: int, out_cap: int):
    """Row map compacting n stacked segments into one flat batch.

    The mesh exchange's receive side (and any [n, cap]-stacked layout)
    holds one segment per source with ``counts[d]`` live rows; output row
    r is segment ``bkt[r]`` row ``within[r]`` when ``live[r]``.  Returns
    ``(bkt, within, live, total)``, all over the static ``out_cap`` —
    searchsorted over the count cumsum, the sharded k-way sibling of
    :func:`gather_segments_kway`'s scatter (there the inputs are separate
    arrays; here one stacked axis, so a gather formulation wins).  Safe
    inside ``jax.jit`` and inside ``shard_map``.
    """
    total = jnp.sum(counts).astype(jnp.int32)
    cum = jnp.cumsum(counts)
    starts = cum - counts
    flat = jnp.arange(out_cap, dtype=jnp.int32)
    bkt = jnp.clip(jnp.searchsorted(
        cum, flat, side="right").astype(jnp.int32), 0, n - 1)
    within = jnp.clip(flat - starts[bkt], 0, cap - 1)
    live = flat < total
    return bkt, within, live, total


def gather_stacked_rows(stacked, bkt, within, live):
    """Apply a :func:`stacked_row_compaction_indices` map to one
    ``[n, cap]`` per-row payload (data or validity); dead output slots
    zero-fill (False for bool)."""
    return jnp.where(live, stacked[bkt, within],
                     jnp.zeros((), stacked.dtype))


def gather_stacked_elements(elems, ecounts, n: int, ecap: int,
                            out_ecap: int):
    """Compact n stacked varlen element streams (``elems[n, ecap]``,
    ``ecounts[d]`` live elements each) into one flat ``[out_ecap]``
    buffer — the element-axis counterpart of
    :func:`stacked_row_compaction_indices`, so a received varlen column's
    bytes land contiguous in segment order with zeros past the live
    total."""
    ecum = jnp.cumsum(ecounts)
    eexcl = ecum - ecounts
    p = jnp.arange(out_ecap, dtype=jnp.int32)
    eb = jnp.clip(jnp.searchsorted(
        ecum, p, side="right").astype(jnp.int32), 0, n - 1)
    ew = jnp.clip(p - eexcl[eb], 0, ecap - 1)
    return jnp.where(p < ecum[n - 1], elems[eb, ew],
                     jnp.zeros((), elems.dtype))


def concat_pair(a: ColumnBatch, b: ColumnBatch, out_capacity: int,
                out_byte_caps: Optional[Sequence[int]] = None) -> ColumnBatch:
    """Concatenate two batches (same schema) into one of static capacity.

    Gather-formulated: output row i reads a[i] when i < a.num_rows else
    b[i - a.num_rows].  ``out_capacity`` must be >= a.capacity + b.capacity
    is NOT required — only >= total live rows (host guarantees via sizing).
    """
    assert a.schema == b.schema, f"{a.schema} != {b.schema}"
    a, b = ensure_row_layout(a), ensure_row_layout(b)
    n_a = a.num_rows
    total = a.num_rows + b.num_rows
    live = jnp.arange(out_capacity, dtype=jnp.int32) < total
    i = jnp.arange(out_capacity, dtype=jnp.int32)
    from_a = i < n_a
    ia = jnp.clip(i, 0, a.capacity - 1)
    ib = jnp.clip(i - n_a, 0, b.capacity - 1)
    cols = []
    str_i = 0
    for f, ca, cb in zip(a.schema.fields, a.columns, b.columns):
        if ca.is_varlen:
            len_a = _string_lengths(ca)
            len_b = _string_lengths(cb)
            new_lens = jnp.where(
                live, jnp.where(from_a, len_a[ia], len_b[ib]), 0)
            new_offsets = jnp.concatenate([
                jnp.zeros(1, dtype=jnp.int32),
                jnp.cumsum(new_lens).astype(jnp.int32),
            ])
            bcap_a = int(ca.data.shape[0])
            bcap_b = int(cb.data.shape[0])
            bcap = (out_byte_caps[str_i] if out_byte_caps is not None
                    else bcap_a + bcap_b)
            str_i += 1
            rows = _rows_of_positions(new_offsets, bcap)
            rows_c = jnp.clip(rows, 0, out_capacity - 1)
            pos_in_row = jnp.arange(bcap, dtype=jnp.int32) - new_offsets[rows_c]
            row_from_a = from_a[rows_c]
            src_a = jnp.clip(ca.offsets[ia[rows_c]] + pos_in_row, 0, bcap_a - 1)
            src_b = jnp.clip(cb.offsets[ib[rows_c]] + pos_in_row, 0, bcap_b - 1)
            byte = jnp.where(row_from_a, ca.data[src_a], cb.data[src_b])
            in_range = jnp.arange(bcap, dtype=jnp.int32) < new_offsets[-1]
            data = jnp.where(in_range, byte, 0).astype(ca.data.dtype)
            validity = jnp.where(
                live, jnp.where(from_a, ca.validity[ia], cb.validity[ib]),
                False)
            cols.append(DeviceColumn(f.dtype, data, validity, new_offsets))
        else:
            data = jnp.where(from_a, ca.data[ia], cb.data[ib])
            data = jnp.where(live, data, 0).astype(ca.data.dtype)
            validity = jnp.where(
                live, jnp.where(from_a, ca.validity[ia], cb.validity[ib]),
                False)
            cols.append(DeviceColumn(f.dtype, data, validity, None))
    return ColumnBatch(a.schema, cols, total.astype(jnp.int32), out_capacity)
