"""Pallas TPU kernel for the string contains/LIKE '%needle%' scan
(VERDICT r4 item 8; reference: stringFunctions.scala's dedicated native
contains kernel over libcudf).

The XLA path (exprs/strings.py:_find_matches + _rows_with_match) costs:
L shifted gathers over the byte buffer, a per-byte ``searchsorted`` over
the offsets (log(cap) passes) and a segment-sum.  This kernel folds the
whole match scan into ONE pass over the byte buffer:

  match[p] = (AND_k data[p+k] == needle[k])        # needle bytes, static
           & NOT (OR_{k=1..L-1} is_start[p+k])     # stays inside one row

with the needle bytes baked into the program (literal needles only — the
same restriction the planner already enforces for device execution).
The per-row reduction then avoids ``rows_of_positions`` entirely:

  has[r] = cumsum(match)[off[r+1]] - cumsum(match)[off[r]] > 0

which is one cumsum pass + O(cap) gathers instead of O(nbytes log cap).

Layout: the byte buffer rides as 1-D u8 blocks; each program reads its
block AND the next block (a second BlockSpec shifted by one — Pallas
blocks cannot overlap, so the halo is expressed as a duplicate input)
and emits BLOCK match flags via L static slices of the concatenation.

Used automatically for Contains/Like-contains when the backend is a real
TPU: exprs/strings.py routes through the kernel tier's ``strings`` entry
(kernels.pallas_tier — conf gate ``spark.rapids.sql.tpu.pallas.strings.
enabled``, interpret mode under ``pallas.interpret``); the XLA
formulation remains both the CPU-backend path and the fallback.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.utils.compile_registry import instrumented_jit

BLOCK = 16384  # bytes of match output per program (128-aligned)


def use_pallas_strings() -> bool:
    """Deprecated: the decision now lives in the kernel tier
    (``spark.rapids.sql.tpu.pallas.strings.enabled`` + backend predicate;
    the env var survives one release as an alias).  Kept for callers that
    only need the boolean."""
    from spark_rapids_tpu.kernels import pallas_tier
    return pallas_tier.decide("strings").engaged


def _interpret() -> bool:
    """Deprecated alias resolution (tier ``pallas.interpret`` conf or the
    old env value) — the default for direct :func:`rows_with_match`
    callers; production traffic passes ``interpret`` explicitly through
    the tier."""
    from spark_rapids_tpu.kernels import pallas_tier
    return pallas_tier.decide("strings").interpret


def _match_kernel(cur_ref, nxt_ref, scur_ref, snxt_ref, out_ref, *,
                  needle: tuple, block: int):
    x = jnp.concatenate([cur_ref[...], nxt_ref[...]])
    m = x[0:block] == np.uint8(needle[0])
    for k in range(1, len(needle)):
        m = m & (x[k:k + block] == np.uint8(needle[k]))
    if len(needle) > 1:
        s = jnp.concatenate([scur_ref[...], snxt_ref[...]])
        cross = s[1:1 + block] != 0
        for k in range(2, len(needle)):
            cross = cross | (s[k:k + block] != 0)
        m = m & ~cross
    out_ref[...] = m.astype(jnp.int32)


@instrumented_jit(label="pallas:contains",
                  static_argnames=("needle", "interpret"))
def contains_match(data, offsets, needle: tuple, interpret: bool = False):
    """int32[nbytes_padded]: 1 where ``needle`` (tuple of byte values)
    matches starting at this byte position without crossing a row
    boundary.  ``data`` u8[nbytes], ``offsets`` int32[cap+1]."""
    from jax.experimental import pallas as pl

    nbytes = int(data.shape[0])
    padded = -(-nbytes // BLOCK) * BLOCK
    nblocks = padded // BLOCK
    if padded != nbytes:
        data = jnp.concatenate(
            [data, jnp.zeros(padded - nbytes, jnp.uint8)])
    # row-start mask: one O(cap) scatter.  ALL offsets are marked
    # (including the live-data end) so a match cannot extend into the
    # garbage region past the last row; index==padded drops harmlessly.
    starts = jnp.zeros(padded, jnp.uint8).at[offsets].set(1, mode="drop")

    spec_cur = pl.BlockSpec((BLOCK,), lambda i: (i,))
    spec_nxt = pl.BlockSpec(
        (BLOCK,), lambda i: (jnp.minimum(i + 1, nblocks - 1),))
    kernel = functools.partial(_match_kernel, needle=needle, block=BLOCK)
    out = pl.pallas_call(
        kernel,
        grid=(nblocks,),
        in_specs=[spec_cur, spec_nxt, spec_cur, spec_nxt],
        out_specs=pl.BlockSpec((BLOCK,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((padded,), jnp.int32),
        interpret=interpret,
    )(data, data, starts, starts)
    # the last block's halo duplicates itself (there is no next block);
    # kill any match that would need bytes past the live end — also
    # covers garbage bytes beyond offsets[-1] (buffer caps > live bytes)
    pos = jnp.arange(padded, dtype=jnp.int32)
    return out * (pos + len(needle) <= offsets[-1]).astype(jnp.int32)


def rows_with_match(data, offsets, validity, cap: int, needle: bytes,
                    interpret: bool = None):
    """bool[cap]: row contains ``needle`` — the Pallas-backed analogue of
    exprs.strings._rows_with_match.  ``interpret`` defaults to the tier
    decision (conf / deprecated env alias) for direct callers."""
    if len(needle) == 0:
        return jnp.ones(cap, dtype=jnp.bool_)
    if interpret is None:
        interpret = _interpret()
    match = contains_match(data, offsets, tuple(needle), interpret)
    # exclusive cumsum -> per-row match counts via two O(cap) gathers
    c = jnp.concatenate([jnp.zeros(1, jnp.int32),
                         jnp.cumsum(match).astype(jnp.int32)])
    padded = int(match.shape[0])
    off = jnp.clip(offsets.astype(jnp.int32), 0, padded)
    return (c[off[1:]] - c[off[:-1]]) > 0
