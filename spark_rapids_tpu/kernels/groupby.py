"""Sort-based groupby aggregation (cudf groupby analogue, aggregate.scala:456).

TPU-first: instead of a hash table (scatter-heavy, poor MXU/VPU fit), group
rows by *sorting* on the exact key columns, derive segment ids from adjacent
key equality, and run ``jax.ops.segment_*`` reductions with
``num_segments = capacity`` so shapes stay static.  The same machinery serves
partial (update) and final (merge) aggregation modes — mirroring the
reference's update/merge projections (aggregate.scala:420-431).
"""

from __future__ import annotations

import dataclasses
from typing import List, Sequence, Tuple

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import ColumnBatch, DeviceColumn
from spark_rapids_tpu.exprs.base import DevVal
from spark_rapids_tpu.kernels.layout import (
    compaction_indices, ensure_row_layout, gather_rows,
)
from spark_rapids_tpu.kernels.sort import argsort_batch
from spark_rapids_tpu.kernels.sortkeys import keys_equal_prev


@dataclasses.dataclass
class GroupSegments:
    """Result of grouping: row order and segment structure."""

    perm: jnp.ndarray        # int32[cap] sort permutation
    seg_ids: jnp.ndarray     # int32[cap] group id per *sorted* row
    seg_start: jnp.ndarray   # bool[cap] first sorted row of each group
    num_groups: jnp.ndarray  # int32 scalar
    live: jnp.ndarray        # bool[cap] sorted-row liveness


def group_segments(key_vals: List[DevVal], num_rows) -> GroupSegments:
    """Sort rows by key and mark exact group boundaries."""
    cap = int(key_vals[0].validity.shape[0])
    perm = argsort_batch(key_vals, [True] * len(key_vals),
                         [True] * len(key_vals), num_rows,
                         groupings=[True] * len(key_vals))
    live = jnp.arange(cap, dtype=jnp.int32) < num_rows
    # Reorder key columns by the permutation; strings need real byte gathers
    # for the adjacent-equality check (cheap relative to the sort itself).
    # Dictionary-encoded strings just permute their codes — the entry
    # buffer is row-order independent, so no byte gather is needed.
    sorted_keys = []
    for v in key_vals:
        if v.codes is not None:
            sorted_keys.append(DevVal(v.dtype, v.data, v.validity[perm],
                                      v.offsets, v.codes[perm],
                                      v.mat_byte_cap))
        elif v.dtype.is_string:
            sorted_keys.append(_gather_str_val(v, perm, cap))
        else:
            sorted_keys.append(DevVal(v.dtype, v.data[perm],
                                      v.validity[perm]))
    eq_prev = keys_equal_prev(sorted_keys)
    seg_start = live & ~eq_prev
    seg_ids = jnp.clip(jnp.cumsum(seg_start.astype(jnp.int32)) - 1, 0, cap - 1)
    num_groups = jnp.sum(seg_start).astype(jnp.int32)
    return GroupSegments(perm, seg_ids, seg_start, num_groups, live)


def groupby_aggregate(batch: ColumnBatch, key_vals: List[DevVal],
                      agg_inputs: List[DevVal], agg_fns: Sequence,
                      merge: bool,
                      key_schema: T.Schema,
                      buffer_schemas: List[List[T.DataType]],
                      out_schema: T.Schema) -> Tuple[ColumnBatch, List[List[DevVal]]]:
    """One-batch groupby.

    Returns (group-key batch of num_groups rows, per-agg buffer lists aligned
    with group order).  In ``merge`` mode ``agg_inputs`` holds lists of
    partial buffers per aggregate (flattened by caller) and ``segment_merge``
    is used; otherwise raw inputs + ``segment_update``.
    """
    cap = batch.capacity
    segs = group_segments(key_vals, batch.num_rows)

    # Representative key rows: compact sorted rows where seg_start.
    # Encoded key columns materialize here — downstream (merge rounds,
    # concat, output) only ever sees the row layout.
    key_cols = [DeviceColumn(v.dtype, v.data, v.validity, v.offsets,
                             v.codes, v.mat_byte_cap)
                for v in key_vals]
    key_batch = ensure_row_layout(
        ColumnBatch(key_schema, key_cols, batch.num_rows, cap))
    sorted_keys = gather_rows(key_batch, segs.perm, batch.num_rows)
    idx, count = compaction_indices(segs.seg_start, jnp.asarray(cap, jnp.int32))
    group_keys = gather_rows(sorted_keys, idx, segs.num_groups)

    out_buffers: List[List[DevVal]] = []
    if merge:
        flat_i = 0
        for fn, bufs in zip(agg_fns, buffer_schemas):
            n = len(bufs)
            partials = []
            for k in range(n):
                v = agg_inputs[flat_i]
                flat_i += 1
                partials.append(DevVal(v.dtype, v.data[segs.perm],
                                       v.validity[segs.perm]))
            out_buffers.append(fn.segment_merge(partials, segs.seg_ids, cap,
                                                segs.live))
    else:
        for fn, v in zip(agg_fns, agg_inputs):
            if v.codes is not None:
                # encoded input (Count over a dict string): permute codes,
                # entries are row-order independent
                sv = DevVal(v.dtype, v.data, v.validity[segs.perm],
                            v.offsets, v.codes[segs.perm], v.mat_byte_cap)
            elif v.dtype.is_string:
                sv = _gather_str_val(v, segs.perm, cap)
            else:
                sv = DevVal(v.dtype, v.data[segs.perm],
                            v.validity[segs.perm])
            out_buffers.append(fn.segment_update(sv, segs.seg_ids, cap,
                                                 segs.live))
    return group_keys, out_buffers


def _gather_str_val(v: DevVal, perm, cap: int) -> DevVal:
    col = DeviceColumn(v.dtype, v.data, v.validity, v.offsets)
    b = ColumnBatch(T.Schema([("s", v.dtype)]), [col],
                    jnp.asarray(cap, jnp.int32), cap)
    g = gather_rows(b, perm, jnp.asarray(cap, jnp.int32)).columns[0]
    return DevVal(v.dtype, g.data, g.validity, g.offsets)
