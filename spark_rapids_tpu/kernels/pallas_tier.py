"""Pallas kernel tier: the registry every TPU kernel ships through.

Each kernel declares, in ONE place (:func:`register`):

* a name and its conf gate (``spark.rapids.sql.tpu.pallas.<kernel>.enabled``),
* a backend predicate — compiled on a real TPU backend only, interpret
  mode under ``spark.rapids.sql.tpu.pallas.interpret`` so CPU tests can
  pin bit-identity (the generalization of the old
  ``use_pallas_strings()`` env switch),
* an automatic fallback to the existing XLA formulation (the
  splitV2/donation conf-gate pattern: the fallback IS the semantics, the
  kernel is only a faster lowering and must be bit-identical),
* a per-kernel obs span (site ``pallas``) so ``rapidsprof --critpath``
  attributes each win, and
* a shared VMEM residency budget (``pallas.vmemBudgetBytes``): a kernel
  whose resident working set would not fit falls back.

Call sites route through :func:`run` with two closures — the Pallas
lowering (given the resolved interpret flag) and the XLA fallback.  The
decision is taken at TRACE time (plain Python), so cached executables
skip it entirely; ``fallback_count()`` feeds the session's
``pallasFallbackCount`` metric delta.

The tier is also where the kernel bodies live: rapidslint R9 rejects any
``pl.pallas_call`` outside this file and ``pallas_strings.py``, because a
bare call bypasses the fallback contract, the obs span and the metric.

Kernel families (docs/kernels.md has the layout/VMEM notes):

* ``gatherScatter`` — segmented k-way pack (:func:`pack_segments`), the
  fused replacement for the scatter chains in layout.concat_kway /
  gather_segments_kway;
* ``joinProbe`` — fused hash-join probe (:func:`probe_join`) with a
  VMEM-resident build side, replacing join._phase1 + pair expansion +
  word verify;
* ``stringHash`` — per-row polynomial hashing (:func:`string_hash_rows`)
  over the byte buffer, replacing exprs.strings.string_hash2's
  pow-table + segment-sum formulation;
* ``strings`` — the contains/LIKE scan (kernels.pallas_strings), now
  conf-gated through the tier.
"""

from __future__ import annotations

import dataclasses
import functools
import os
import threading
import time
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp

from spark_rapids_tpu.config import (
    PALLAS_GATHER_SCATTER_ENABLED, PALLAS_INTERPRET,
    PALLAS_JOIN_PROBE_ENABLED, PALLAS_STRINGS_ENABLED,
    PALLAS_STRING_HASH_ENABLED, PALLAS_VMEM_BUDGET, RapidsConf,
)

#: Deprecated alias for the ``strings`` kernel gate (one release):
#: 0/false = off, interp = engage in interpret mode.  Honored only while
#: ``spark.rapids.sql.tpu.pallas.strings.enabled`` is not explicitly set.
_DEPRECATED_STRINGS_ENV = "SPARK_RAPIDS_PALLAS_STRINGS"


@dataclasses.dataclass(frozen=True)
class KernelSpec:
    """One registered kernel-tier entry."""

    name: str
    entry: object  # ConfEntry gating this kernel
    families: str  # what the kernel fuses
    fallback: str  # the XLA formulation it must stay bit-identical to


@dataclasses.dataclass(frozen=True)
class Decision:
    engaged: bool
    interpret: bool
    reason: str  # "" (engaged) | "off" | "backend" | "budget"


_KERNELS: Dict[str, KernelSpec] = {}


def register(name: str, entry, families: str, fallback: str) -> KernelSpec:
    spec = KernelSpec(name, entry, families, fallback)
    _KERNELS[name] = spec
    return spec


def registered() -> List[KernelSpec]:
    return [_KERNELS[k] for k in sorted(_KERNELS)]


_lock = threading.Lock()
_active_conf: Optional[RapidsConf] = None
_fallbacks = 0


def configure(conf: Optional[RapidsConf]) -> None:
    """Install the session conf the tier consults (session.execute does
    this per query, like obs_ts.configure); None reverts to the
    process-wide default conf."""
    global _active_conf
    _active_conf = conf


def _conf() -> RapidsConf:
    if _active_conf is not None:
        return _active_conf
    from spark_rapids_tpu.config import conf as process_conf
    return process_conf


def fallback_count() -> int:
    """Process-wide count of kernel-tier fallbacks taken at trace time
    (backend/budget/lowering-failure; conf-off does NOT count — a
    disabled kernel is policy, not a fallback)."""
    return _fallbacks


def _note_fallback() -> None:
    global _fallbacks
    with _lock:
        _fallbacks += 1


def decide(name: str, resident_bytes: int = 0) -> Decision:
    """Pure trace-time gate for one kernel invocation (no counting —
    :func:`run` translates non-"off" reasons into fallback counts)."""
    spec = _KERNELS[name]
    conf = _conf()
    enabled = bool(spec.entry.get(conf))
    interp = bool(PALLAS_INTERPRET.get(conf))
    if name == "strings" and not conf.explicitly_set(spec.entry.key):
        flag = os.environ.get(_DEPRECATED_STRINGS_ENV)
        if flag in ("0", "false"):
            enabled = False
        elif flag == "interp":
            interp = True
    if not enabled:
        return Decision(False, False, "off")
    if resident_bytes and resident_bytes > PALLAS_VMEM_BUDGET.get(conf):
        # the budget applies in interpret mode too, so CPU tests exercise
        # the same decision the TPU takes
        return Decision(False, False, "budget")
    if interp:
        return Decision(True, True, "")
    try:
        on_tpu = jax.default_backend() == "tpu"
    except Exception:
        on_tpu = False
    if on_tpu:
        return Decision(True, False, "")
    return Decision(False, False, "backend")


def run(name: str, pallas_fn: Callable, fallback_fn: Callable,
        resident_bytes: int = 0):
    """Dispatch one kernel invocation through the tier.

    ``pallas_fn(interpret: bool)`` builds the Pallas lowering;
    ``fallback_fn()`` builds the XLA formulation.  Runs at trace time:
    a lowering failure falls back (and counts) instead of failing the
    query, mirroring the splitV2 conf-gate pattern."""
    d = decide(name, resident_bytes)
    if not d.engaged:
        if d.reason != "off":
            _note_fallback()
        return fallback_fn()
    from spark_rapids_tpu.obs.events import emit_span
    t0 = time.monotonic_ns()
    try:
        out = pallas_fn(d.interpret)
    except Exception:
        _note_fallback()
        return fallback_fn()
    emit_span("pallas", name, t0=t0, t1=time.monotonic_ns(),
              interpret=d.interpret, resident_bytes=resident_bytes)
    return out


# ---------------------------------------------------------------------------
# gatherScatter: segmented k-way pack
# ---------------------------------------------------------------------------

#: Output elements per program instance (128-aligned).
PACK_BLOCK = 8192

#: Element dtypes the pack kernel lowers; anything else (f64, i64 on x64
#: hosts) silently takes the XLA scatter chain — see docs/kernels.md.
_PACK_DTYPES = ("bool", "uint8", "int32", "uint32", "float32")


def pack_supported(arrays) -> bool:
    return bool(arrays) and all(a.dtype.name in _PACK_DTYPES
                                for a in arrays)


def _iota1d(n: int):
    # 1-D iota does not lower on compiled TPU; 2-D broadcasted_iota does
    return jax.lax.broadcasted_iota(jnp.int32, (1, n), 1)


def _pack_kernel(tab_ref, *refs, k: int, block: int, sizes: tuple):
    out_ref = refs[-1]
    in_refs = refs[:-1]
    i = jnp.int32(0) + _program_id(0)
    p = i * block + _iota1d(block)  # (1, block) output positions
    acc = jnp.zeros((1, block), dtype=out_ref.dtype)
    # static walk of the segment table: position p belongs to input j iff
    # dst_start[j] <= p < dst_start[j+1]; its source index is then
    # lo[j] + (p - dst_start[j]).  Windows are disjoint by construction.
    for j in range(k):
        dst0 = tab_ref[0, j]
        dst1 = tab_ref[0, j + 1]
        src0 = tab_ref[1, j]
        data = in_refs[j][...]
        src = jnp.clip(src0 + (p - dst0), 0, sizes[j] - 1)
        sel = (p >= dst0) & (p < dst1)
        acc = jnp.where(sel, data[src], acc)
    out_ref[...] = acc.reshape((block,))


def _program_id(axis: int):
    from jax.experimental import pallas as pl
    return pl.program_id(axis)


def pack_segments(arrays, los, his, out_cap: int, *, interpret: bool):
    """Pallas k-way segment pack: ``out[dst_j + t] = arrays[j][los[j]+t]``
    for ``t in [0, his[j]-los[j])`` with ``dst_j`` the running total of
    earlier segment lengths; zeros elsewhere.  Bit-identical to
    layout._pack_kway's drop-mode scatter chain — the live window
    [lo, hi) is exactly what the scatters select, so take_head-truncated
    tail bytes can never leak."""
    from jax.experimental import pallas as pl

    k = len(arrays)
    out_dtype = arrays[0].dtype
    is_bool = out_dtype == jnp.bool_
    if is_bool:
        arrays = [a.astype(jnp.uint8) for a in arrays]
    los = [jnp.asarray(lo, jnp.int32) for lo in los]
    his = [jnp.asarray(hi, jnp.int32) for hi in his]
    dst = [jnp.zeros((), jnp.int32)]
    for lo, hi in zip(los, his):
        dst.append(dst[-1] + (hi - lo))
    # segment table (2, k+1) i32: row 0 cumulative dst starts (incl. the
    # total), row 1 source los (padded) — scalar-prefetch shaped, 2-D so
    # SMEM scalar loads stay legal on TPU
    tab = jnp.stack([jnp.stack(dst),
                     jnp.stack(los + [jnp.zeros((), jnp.int32)])])
    padded = -(-out_cap // PACK_BLOCK) * PACK_BLOCK
    nblocks = padded // PACK_BLOCK
    sizes = tuple(int(a.shape[0]) for a in arrays)
    kernel = functools.partial(_pack_kernel, k=k, block=PACK_BLOCK,
                               sizes=sizes)
    in_specs = [pl.BlockSpec(tab.shape, lambda i: (0, 0))]
    for a in arrays:
        in_specs.append(pl.BlockSpec(a.shape, lambda i: (0,)))
    out = pl.pallas_call(
        kernel,
        grid=(nblocks,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((PACK_BLOCK,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((padded,), arrays[0].dtype),
        interpret=interpret,
    )(tab, *arrays)
    out = out[:out_cap]
    return out != 0 if is_bool else out


# ---------------------------------------------------------------------------
# joinProbe: fused hash-join probe with a VMEM-resident build side
# ---------------------------------------------------------------------------


def _bsearch(sorted_vals, keys, n: int, side_right: bool):
    """Vectorized binary search == jnp.searchsorted(sorted_vals, keys,
    side): fixed-trip branchless bisection (the unique bound index is
    deterministic, so this is bit-identical to the XLA lowering)."""
    lo = jnp.zeros(keys.shape, jnp.int32)
    hi = jnp.full(keys.shape, n, jnp.int32)
    for _ in range(max(int(n).bit_length(), 1)):
        active = lo < hi
        mid = (lo + hi) >> 1
        v = sorted_vals[jnp.clip(mid, 0, n - 1)]
        pred = (v <= keys) if side_right else (v < keys)
        go = active & pred
        lo = jnp.where(go, mid + 1, lo)
        hi = jnp.where(active & ~pred, mid, hi)
    return lo


def _probe_kernel(lh1_ref, lmask_ref, rs_ref, perm_ref, av_ref, bv_ref,
                  aw_ref, bw_ref, pr_ref, br_ref, m_ref, tot_ref, *,
                  l_cap: int, r_cap: int, pair_cap: int, n_words: int):
    lh1 = lh1_ref[...]
    lmask = lmask_ref[...] != 0
    rs = rs_ref[...]
    # fused dual searchsorted (join._phase1) on the sorted build hashes
    lo_idx = _bsearch(rs, lh1, r_cap, side_right=False)
    hi_idx = _bsearch(rs, lh1, r_cap, side_right=True)
    counts = jnp.where(lmask, hi_idx - lo_idx, 0).astype(jnp.int32)
    total = jnp.sum(counts).astype(jnp.int32)
    # candidate expansion (searchsorted-on-cumsum), identical clips to
    # the XLA formulation in join_pairs_static
    cum = jnp.cumsum(counts).astype(jnp.int32)
    starts = cum - counts
    k = _iota1d(pair_cap).reshape((pair_cap,))
    probe_row = jnp.clip(_bsearch(cum, k, l_cap, side_right=True),
                         0, l_cap - 1)
    ordinal = (k - starts[probe_row]).astype(jnp.int32)
    build_pos = jnp.clip(lo_idx[probe_row] + ordinal, 0, r_cap - 1)
    build_row = perm_ref[...][build_pos]
    total_c = jnp.minimum(total, pair_cap)
    in_range = k < total_c
    # exact-match word verify (join._exact_eq, pre-encoded as u32 words)
    eq = (av_ref[...][probe_row] != 0) & (bv_ref[...][build_row] != 0)
    aw = aw_ref[...]
    bw = bw_ref[...]
    for w in range(n_words):
        eq = eq & (aw[w, probe_row] == bw[w, build_row])
    match = in_range & eq
    pr_ref[...] = probe_row.astype(jnp.int32)
    br_ref[...] = build_row.astype(jnp.int32)
    m_ref[...] = match.astype(jnp.int32)
    tot_ref[0, 0] = total


def probe_join(l_h1, l_mask, r_sorted, perm, a_words, a_valid,
               b_words, b_valid, pair_cap: int, *, interpret: bool):
    """Fused hash-join probe: both _phase1 searchsorted passes, the
    candidate expansion and the exact-match word verify in one kernel
    over the VMEM-resident build side.  Returns ``(probe_row i32,
    build_row i32, match bool, total i32)`` — exactly the candidate
    phase of join_pairs_static; probe_row stays sorted so the shared
    tail's ``indices_are_sorted`` promise holds."""
    from jax.experimental import pallas as pl

    l_cap = int(l_h1.shape[0])
    r_cap = int(r_sorted.shape[0])
    n_words = int(a_words.shape[0])
    kernel = functools.partial(_probe_kernel, l_cap=l_cap, r_cap=r_cap,
                               pair_cap=pair_cap, n_words=n_words)
    probe_row, build_row, match, tot = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((pair_cap,), jnp.int32),
                   jax.ShapeDtypeStruct((pair_cap,), jnp.int32),
                   jax.ShapeDtypeStruct((pair_cap,), jnp.int32),
                   jax.ShapeDtypeStruct((1, 1), jnp.int32)),
        interpret=interpret,
    )(l_h1, l_mask.astype(jnp.int32), r_sorted, perm,
      a_valid.astype(jnp.int32), b_valid.astype(jnp.int32),
      a_words, b_words)
    return probe_row, build_row, match != 0, tot[0, 0]


# ---------------------------------------------------------------------------
# stringHash: per-row dual polynomial hashing over the byte buffer
# ---------------------------------------------------------------------------

#: Rows hashed per program instance.
HASH_ROW_BLOCK = 512


def _string_hash_kernel(data_ref, off_ref, h1_ref, h2_ref, *, cap: int,
                        nbytes: int, block: int, base1: int, base2: int,
                        golden: int):
    i = jnp.int32(0) + _program_id(0)
    r = jnp.clip(i * block + _iota1d(block).reshape((block,)), 0, cap - 1)
    offs = off_ref[...]
    data = data_ref[...]
    start = offs[r].astype(jnp.int32)
    length = (offs[r + 1] - offs[r]).astype(jnp.int32)
    maxlen = jnp.max(length)

    def body(t, carry):
        h1, h2 = carry
        idx = jnp.clip(start + t, 0, nbytes - 1)
        b = data[idx].astype(jnp.uint32)
        act = t < length
        h1 = jnp.where(act, h1 * jnp.uint32(base1) + b, h1)
        h2 = jnp.where(act, h2 * jnp.uint32(base2) + b, h2)
        return h1, h2

    z = jnp.zeros((block,), jnp.uint32)
    h1, h2 = jax.lax.fori_loop(0, maxlen, body, (z, z))
    lw = length.astype(jnp.uint32) * jnp.uint32(golden)
    h1_ref[...] = h1 + lw
    h2_ref[...] = h2 + lw


def string_hash_rows(data, offsets, cap: int, bases, *, interpret: bool):
    """Row-blocked Horner evaluation of the dual polynomial row hashes.

    Bit-identical to exprs.strings.string_hash2's weighted segment-sum:
    uint32 addition is exact mod 2^32, so Horner over [start, end) equals
    sum(byte * base^(end-1-pos)) in any association, and rows past
    num_rows hash their (live-offset-bounded) windows identically on both
    paths."""
    from jax.experimental import pallas as pl

    nbytes = int(data.shape[0])
    padded_rows = -(-cap // HASH_ROW_BLOCK) * HASH_ROW_BLOCK
    nblocks = padded_rows // HASH_ROW_BLOCK
    kernel = functools.partial(
        _string_hash_kernel, cap=cap, nbytes=nbytes, block=HASH_ROW_BLOCK,
        base1=int(bases[0]), base2=int(bases[1]), golden=0x9E3779B9)
    offsets = offsets.astype(jnp.int32)
    h1, h2 = pl.pallas_call(
        kernel,
        grid=(nblocks,),
        in_specs=[pl.BlockSpec(data.shape, lambda i: (0,)),
                  pl.BlockSpec(offsets.shape, lambda i: (0,))],
        out_specs=(pl.BlockSpec((HASH_ROW_BLOCK,), lambda i: (i,)),
                   pl.BlockSpec((HASH_ROW_BLOCK,), lambda i: (i,))),
        out_shape=(jax.ShapeDtypeStruct((padded_rows,), jnp.uint32),
                   jax.ShapeDtypeStruct((padded_rows,), jnp.uint32)),
        interpret=interpret,
    )(data, offsets)
    return h1[:cap], h2[:cap]


# ---------------------------------------------------------------------------
# Registry entries (docs/kernels.md documents the full fallback matrix)
# ---------------------------------------------------------------------------

STRINGS = register(
    "strings", PALLAS_STRINGS_ENABLED,
    "contains/LIKE '%needle%' scan in one pass over the byte buffer",
    "exprs.strings._find_matches + segment-sum")
GATHER_SCATTER = register(
    "gatherScatter", PALLAS_GATHER_SCATTER_ENABLED,
    "segmented k-way gather/scatter pack (concat/split rows and bytes)",
    "layout._pack_kway drop-mode scatter chain")
JOIN_PROBE = register(
    "joinProbe", PALLAS_JOIN_PROBE_ENABLED,
    "hash-join probe: dual searchsorted + expansion + exact word verify",
    "join._phase1 + join_pairs_static candidate phase")
STRING_HASH = register(
    "stringHash", PALLAS_STRING_HASH_ENABLED,
    "dual polynomial row hashes over the byte buffer (Horner, row blocks)",
    "exprs.strings.string_hash2 pow-table + segment-sum")
