"""Equi-join kernels (cudf ``Table.onColumns(...).{inner,left,...}Join``
analogue, shims/spark300/GpuHashJoin.scala:282-308).

TPU-first design: no hash table.  The build side is *sorted by a 64-bit key
hash*; each probe row locates its candidate range with two ``searchsorted``
calls; candidates are verified by exact key comparison.  Output size is
data-dependent, so the join runs in two phases (SURVEY.md section 7's
bucketed-padded-batch recipe):

  phase 1 (jit, static shapes): per-probe candidate counts -> total pairs
           (+ unmatched-row counts for outer joins) -> host reads 3 scalars
  phase 2 (jit, static output capacity chosen by host): expand the pair list
           via searchsorted-on-cumsum, verify matches, compact, gather both
           sides' rows, stitch the output batch.

NULL equi-join keys never match (SQL semantics), including null==null.

Join types: inner, left, right, full, left_semi, left_anti, cross.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import ColumnBatch, round_up_capacity
from spark_rapids_tpu.utils.compile_registry import instrumented_jit
from spark_rapids_tpu.exprs.base import DevVal
from spark_rapids_tpu.kernels.layout import (
    compaction_indices, ensure_row_layout, gather_rows,
)

_C1 = np.uint32(0xCC9E2D51)
_C2 = np.uint32(0x1B873593)


def _mix32(h, w):
    k = (w * _C1)
    k = (k << jnp.uint32(15)) | (k >> jnp.uint32(17))
    k = k * _C2
    h = h ^ k
    h = (h << jnp.uint32(13)) | (h >> jnp.uint32(19))
    return h * jnp.uint32(5) + jnp.uint32(0xE6546B64)


def _key_hash2(vals: List[DevVal], code_over: Optional[list] = None):
    """(h1 u32[cap], h2 u32[cap], all_valid bool[cap]) over the key columns.

    Two independent 32-bit hashes (native on TPU — no u64 emulation).  The
    build side sorts by (h1, h2); probes range-scan on h1 and verify
    exactly.  Rows with any NULL key get sentinel ~0 hashes (sort last,
    never matched — SQL null-key semantics).

    ``code_over`` (encoded corridor v2, docs/io.md): per-column aligned
    canonical code arrays from :func:`align_dict_codes`.  A column with an
    override hashes ONE int32 word per row instead of its string content —
    valid because aligned codes are equal exactly when contents are equal.
    Hash VALUES differ from content hashing, but the join's pair order
    does not depend on them (equal keys hash equal either way, and
    equal-hash build rows keep their stable original order), so results
    stay bit-identical."""
    cap = int(vals[0].validity.shape[0])
    h1 = jnp.full(cap, jnp.uint32(0x12345678))
    h2 = jnp.full(cap, jnp.uint32(0x9E3779B9))
    ok = jnp.ones(cap, dtype=jnp.bool_)
    for ki, v in enumerate(vals):
        ok = ok & v.validity
        over = code_over[ki] if code_over is not None else None
        if over is not None:
            words = [over.astype(jnp.uint32)]
        elif v.dtype.is_string:
            from spark_rapids_tpu.exprs.strings import (
                string_hash2, string_lengths,
            )
            s1, s2 = string_hash2(v)
            words = [s1, s2, string_lengths(v).astype(jnp.uint32)]
        else:
            from spark_rapids_tpu.kernels.sortkeys import \
                _encode_fixed_words
            words = _encode_fixed_words(v)
        for w in words:
            h1 = _mix32(h1, w)
            h2 = _mix32(h2, w ^ jnp.uint32(0xA5A5A5A5))
    sentinel = ~jnp.uint32(0)
    return (jnp.where(ok, h1, sentinel), jnp.where(ok, h2, sentinel), ok)


def _exact_eq(a_vals: List[DevVal], a_idx, b_vals: List[DevVal], b_idx,
              code_over: Optional[list] = None):
    """Exact key equality for gathered index pairs (both sides valid).

    ``code_over``: per-column (a_codes, b_codes) pairs of ALIGNED
    canonical codes — equality is then one int32 compare per pair, and it
    is EXACT (no residual hash-collision risk), since aligned codes are
    equal iff entry contents are equal."""
    eq = jnp.ones(a_idx.shape, dtype=jnp.bool_)
    for ki, (va, vb) in enumerate(zip(a_vals, b_vals)):
        eq = eq & va.validity[a_idx] & vb.validity[b_idx]
        over = code_over[ki] if code_over is not None else None
        if over is not None:
            oa, ob = over
            eq = eq & (oa[a_idx] == ob[b_idx])
        elif va.dtype.is_string:
            from spark_rapids_tpu.exprs.strings import (
                string_hash2, string_lengths,
            )
            from spark_rapids_tpu.kernels.sortkeys import (
                DEFAULT_STRING_PREFIX_BYTES, string_prefix_words,
            )
            la = string_lengths(va)[a_idx]
            lb = string_lengths(vb)[b_idx]
            a1, a2 = string_hash2(va)
            b1, b2 = string_hash2(vb)
            eq = eq & (la == lb) & (a1[a_idx] == b1[b_idx]) & \
                (a2[a_idx] == b2[b_idx])
            # Also compare the first 64 bytes exactly: a false match now
            # needs simultaneous collision of both 32-bit hashes AND an
            # identical 64-byte prefix + length — residual risk documented
            # in docs/compatibility.md.
            for wa, wb in zip(
                    string_prefix_words(va, DEFAULT_STRING_PREFIX_BYTES),
                    string_prefix_words(vb, DEFAULT_STRING_PREFIX_BYTES)):
                eq = eq & (wa[a_idx] == wb[b_idx])
        else:
            from spark_rapids_tpu.kernels.sortkeys import \
                _encode_fixed_words
            for wa, wb in zip(_encode_fixed_words(va),
                              _encode_fixed_words(vb)):
                eq = eq & (wa[a_idx] == wb[b_idx])
    return eq


def _exact_words(vals: List[DevVal], code_over: Optional[list] = None):
    """Pre-encoded u32 word matrix + combined validity for one side's key
    columns: ``(words u32[W, cap], valid bool[cap])``.

    Word-for-word the comparisons :func:`_exact_eq` performs — aligned
    codes (bit-preserving int32->u32 cast), string length + dual hashes +
    64-byte prefix words, :func:`_encode_fixed_words` for fixed types —
    so ``valid[a] & valid[b] & AND_w(words_a[w, a] == words_b[w, b])``
    equals ``_exact_eq`` at any index pair.  This is the layout the
    kernel tier's join-probe kernel keeps VMEM-resident."""
    cap = int(vals[0].validity.shape[0])
    valid = jnp.ones(cap, dtype=jnp.bool_)
    words: List[jnp.ndarray] = []
    for ki, v in enumerate(vals):
        valid = valid & v.validity
        over = code_over[ki] if code_over is not None else None
        if over is not None:
            words.append(over.astype(jnp.uint32))
        elif v.dtype.is_string:
            from spark_rapids_tpu.exprs.strings import (
                string_hash2, string_lengths,
            )
            from spark_rapids_tpu.kernels.sortkeys import (
                DEFAULT_STRING_PREFIX_BYTES, string_prefix_words,
            )
            s1, s2 = string_hash2(v)
            words += [string_lengths(v).astype(jnp.uint32), s1, s2]
            words += string_prefix_words(v, DEFAULT_STRING_PREFIX_BYTES)
        else:
            from spark_rapids_tpu.kernels.sortkeys import \
                _encode_fixed_words
            words += _encode_fixed_words(v)
    return jnp.stack(words), valid


def _exact_word_count(vals: List[DevVal],
                      code_over: Optional[list] = None) -> int:
    """Static W of :func:`_exact_words` (for VMEM budgeting before any
    array is built)."""
    from spark_rapids_tpu.kernels.sortkeys import (
        DEFAULT_STRING_PREFIX_BYTES,
    )
    n = 0
    for ki, v in enumerate(vals):
        over = code_over[ki] if code_over is not None else None
        if over is not None:
            n += 1
        elif v.dtype.is_string:
            n += 3 + (DEFAULT_STRING_PREFIX_BYTES + 3) // 4
        elif v.dtype in (T.LONG, T.TIMESTAMP):
            n += 2
        elif v.dtype == T.DOUBLE:
            # backend-dependent: 2 bitcast words on real-f64 hosts, 3
            # float-float words on TPU (_encode_double_words)
            n += 3 if jax.default_backend() == "tpu" else 2
        else:
            n += 1
    return n


#: Entry-pair table guard for :func:`align_dict_codes`: alignment builds
#: an [nd_a, nd_b] boolean content-equality grid; past this many cells
#: the memory/FLOP cost beats rehashing content through the codes, so
#: the caller falls back to content mode (still encoded, still exact
#: under the same residual-collision policy as plain string joins).
DICT_ALIGN_MAX_CELLS = 1 << 22


def _entry_eq_matrix(ent_a: DevVal, ent_b: DevVal):
    """[nd_a, nd_b] bool: dictionary entry contents equal.  Same equality
    policy as :func:`_exact_eq`'s string branch — dual 32-bit hashes +
    length + exact 64-byte prefix — applied entry-vs-entry."""
    from spark_rapids_tpu.exprs.strings import string_hash2
    from spark_rapids_tpu.kernels.sortkeys import (
        DEFAULT_STRING_PREFIX_BYTES, string_prefix_words,
    )
    a1, a2 = string_hash2(ent_a)
    b1, b2 = string_hash2(ent_b)
    la = (ent_a.offsets[1:] - ent_a.offsets[:-1]).astype(jnp.int32)
    lb = (ent_b.offsets[1:] - ent_b.offsets[:-1]).astype(jnp.int32)
    eq = (a1[:, None] == b1[None, :]) & (a2[:, None] == b2[None, :]) & \
        (la[:, None] == lb[None, :])
    for wa, wb in zip(
            string_prefix_words(ent_a, DEFAULT_STRING_PREFIX_BYTES),
            string_prefix_words(ent_b, DEFAULT_STRING_PREFIX_BYTES)):
        eq = eq & (wa[:, None] == wb[None, :])
    return eq


def _entries_of(v: DevVal) -> DevVal:
    nd = int(v.offsets.shape[0]) - 1
    return DevVal(v.dtype, v.data, jnp.ones(nd, dtype=jnp.bool_), v.offsets)


def align_dict_codes(lv: DevVal, rv: DevVal,
                     max_cells: int = DICT_ALIGN_MAX_CELLS):
    """Rendezvous alignment of two dictionary-encoded key columns into one
    canonical code space, so the join can hash/compare int32 codes.

    Returns ``(l_codes, r_codes)`` int32[cap] arrays where equal values
    mean equal string contents, or ``None`` when either side is not
    encoded or the entry-pair table would exceed ``max_cells``.

    Both sides canonicalize against the LARGER dictionary (the "dst"):
    every entry maps to the FIRST content-equal dst entry (argmax over the
    content-equality grid), which also collapses duplicate entries —
    shuffle-merged dictionaries legitimately repeat entries across their
    input pieces, so raw codes are NOT comparable even within one
    dictionary.  A src entry absent from dst maps to the distinct
    negative code ``-1 - entry`` (never equal to any canonical dst code,
    and rows sharing that src entry cannot match any dst row — its
    content does not exist on the other side).  Shared-dictionary sides
    (``data``/``offsets`` the same objects — the scan corridor's common
    case) skip the cross table and self-canonicalize once.  Invalid rows
    pass through masked by validity downstream, as everywhere else."""
    if lv.codes is None or rv.codes is None:
        return None
    nd_l = int(lv.offsets.shape[0]) - 1
    nd_r = int(rv.offsets.shape[0]) - 1
    if nd_l == 0 or nd_r == 0:
        return None

    def row_codes(v, mapping, nd):
        codes_c = jnp.clip(v.codes, 0, max(nd - 1, 0))
        return mapping[codes_c].astype(jnp.int32)

    shared = lv.data is rv.data and lv.offsets is rv.offsets
    if shared:
        if nd_l * nd_l > max_cells:
            return None
        ent = _entries_of(lv)
        canon = jnp.argmax(_entry_eq_matrix(ent, ent),
                           axis=1).astype(jnp.int32)
        return row_codes(lv, canon, nd_l), row_codes(rv, canon, nd_r)
    if nd_l * nd_r + max(nd_l, nd_r) ** 2 > max_cells:
        return None
    # translate the smaller dictionary into the larger's code space
    src, dst, src_is_left = (lv, rv, True) if nd_l <= nd_r else \
        (rv, lv, False)
    nd_src, nd_dst = (nd_l, nd_r) if src_is_left else (nd_r, nd_l)
    ent_src, ent_dst = _entries_of(src), _entries_of(dst)
    canon_dst = jnp.argmax(_entry_eq_matrix(ent_dst, ent_dst),
                           axis=1).astype(jnp.int32)
    cross = _entry_eq_matrix(ent_src, ent_dst)
    found = jnp.any(cross, axis=1)
    # argmax picks the FIRST content-equal dst entry — already canonical
    mapped = jnp.where(found, jnp.argmax(cross, axis=1).astype(jnp.int32),
                       -1 - jnp.arange(nd_src, dtype=jnp.int32))
    src_codes = row_codes(src, mapped, nd_src)
    dst_codes = row_codes(dst, canon_dst, nd_dst)
    return (src_codes, dst_codes) if src_is_left else \
        (dst_codes, src_codes)


@dataclasses.dataclass
class JoinSizing:
    """Host-visible scalars from phase 1 (+ device arrays reused by phase 2)."""

    total_pairs: int
    probe_cap: int
    build_cap: int


def _phase1(probe_h1, probe_ok, probe_live, build_sorted_h1, build_live_n):
    # candidate ranges on h1 only (h2 + exact keys verified in phase 2)
    lo = jnp.searchsorted(build_sorted_h1, probe_h1, side="left")
    hi = jnp.searchsorted(build_sorted_h1, probe_h1, side="right")
    counts = jnp.where(probe_ok & probe_live, hi - lo, 0).astype(jnp.int32)
    return lo.astype(jnp.int32), counts, jnp.sum(counts)


_phase1_jit = instrumented_jit(_phase1, label="join:phase1")


def _build_sort(h1, h2):
    cap = int(h1.shape[0])
    iota = jnp.arange(cap, dtype=jnp.int32)
    s1, _s2, perm = jax.lax.sort((h1, h2, iota), num_keys=2, is_stable=True)
    return perm, s1


_build_sort_jit = instrumented_jit(_build_sort, label="join:build_sort")


def join_pairs(left_keys: List[DevVal], left_num_rows,
               right_keys: List[DevVal], right_num_rows,
               pair_cap_hint: Optional[int] = None):
    """Compute matching (left_idx, right_idx) pair arrays.

    Returns (l_idx i32[pair_cap], r_idx i32[pair_cap], n_pairs i32 scalar,
    l_match_counts i64[l_cap], r_matched bool[r_cap]).  Pairs are compacted to
    the front.  Host sync: one scalar read for sizing.
    """
    l_cap = int(left_keys[0].validity.shape[0])
    r_cap = int(right_keys[0].validity.shape[0])
    l_live = jnp.arange(l_cap, dtype=jnp.int32) < left_num_rows
    r_live = jnp.arange(r_cap, dtype=jnp.int32) < right_num_rows

    # Encoded corridor v2: when both sides of a key column arrive
    # dictionary-encoded, align their codes once (eager — the decision
    # depends on host-known dictionary shapes) and hash/compare int32
    # codes instead of string content.  Per column: override on BOTH
    # sides or neither, so the hashes stay symmetric.
    l_over: List[Optional[jnp.ndarray]] = []
    r_over: List[Optional[jnp.ndarray]] = []
    for lv, rv in zip(left_keys, right_keys):
        pair = align_dict_codes(lv, rv)
        l_over.append(None if pair is None else pair[0])
        r_over.append(None if pair is None else pair[1])
    any_over = any(o is not None for o in l_over)

    l_h1, l_h2, l_ok = _key_hash2(left_keys, l_over if any_over else None)
    r_h1, r_h2, r_ok = _key_hash2(right_keys, r_over if any_over else None)
    sentinel = ~jnp.uint32(0)
    r_h1 = jnp.where(r_live & r_ok, r_h1, sentinel)
    perm, r_sorted = _build_sort_jit(r_h1, r_h2)
    # Sentinel rows (~0 hash) are never matched because probe rows with ok
    # hash ~0 are masked by probe_ok in phase 1.
    lo, counts, total = _phase1_jit(l_h1, l_ok, l_live, r_sorted,
                                    right_num_rows)

    total_pairs = int(jax.device_get(total))
    pair_cap = round_up_capacity(max(total_pairs, 1))
    if pair_cap_hint is not None:
        pair_cap = max(pair_cap, pair_cap_hint)

    # aligned codes ride into phase 2 as bare arrays (a None column is a
    # valid empty pytree) — NEVER wrapped in DevVals, where a stray
    # materialization would clip the -1-i sentinels into entry 0
    code_pairs = [None if a is None else (a, b)
                  for a, b in zip(l_over, r_over)] if any_over else None

    @jax.jit
    def phase2(lo, counts, perm, l_keys, r_keys, code_pairs, total):
        cum = jnp.cumsum(counts)
        starts = cum - counts
        k = jnp.arange(pair_cap, dtype=jnp.int32)
        probe_row = jnp.searchsorted(cum, k, side="right").astype(jnp.int32)
        probe_row = jnp.clip(probe_row, 0, l_cap - 1)
        ordinal = (k - starts[probe_row]).astype(jnp.int32)
        build_pos = jnp.clip(lo[probe_row] + ordinal, 0, r_cap - 1)
        build_row = perm[build_pos]
        in_range = k < total
        match = in_range & _exact_eq(l_keys, probe_row, r_keys, build_row,
                                     code_pairs)
        # compact matches to the front
        order = jnp.argsort(jnp.where(match, 0, 1), stable=True)
        n_pairs = jnp.sum(match).astype(jnp.int32)
        l_idx = probe_row[order]
        r_idx = build_row[order]
        # per-left-row match counts + right matched flags (for outer joins)
        ones = match.astype(jnp.int32)
        l_counts = jax.ops.segment_sum(ones, probe_row, num_segments=l_cap,
                                       indices_are_sorted=True)
        r_matched = jax.ops.segment_max(
            ones, build_row, num_segments=r_cap) > 0
        return l_idx.astype(jnp.int32), r_idx.astype(jnp.int32), n_pairs, \
            l_counts, r_matched

    return phase2(lo, counts, perm, left_keys, right_keys, code_pairs,
                  total)


def join_pairs_static(left_keys: List[DevVal], left_num_rows,
                      right_keys: List[DevVal], right_num_rows,
                      pair_cap: int):
    """Fully-traced :func:`join_pairs`: the pair capacity is a STATIC
    argument chosen by the caller (mesh SPMD fuses the join into one
    ``shard_map`` program, so there is no host to read the phase-1 total).

    Returns ``(l_idx, r_idx, n_pairs, l_counts, r_matched, overflow)``
    where ``overflow`` is a traced bool: the true pair total exceeded
    ``pair_cap``.  On overflow the pair list is TRUNCATED (results are
    wrong) — the caller must check the flag and fall back to the
    host-driven two-phase path.  Safe inside ``jax.jit`` / ``shard_map``.
    """
    l_cap = int(left_keys[0].validity.shape[0])
    r_cap = int(right_keys[0].validity.shape[0])
    l_live = jnp.arange(l_cap, dtype=jnp.int32) < left_num_rows
    r_live = jnp.arange(r_cap, dtype=jnp.int32) < right_num_rows

    # encoded corridor: alignment decisions depend only on host-known
    # dictionary shapes / object identity, so they are trace-safe
    l_over: List[Optional[jnp.ndarray]] = []
    r_over: List[Optional[jnp.ndarray]] = []
    for lv, rv in zip(left_keys, right_keys):
        pair = align_dict_codes(lv, rv)
        l_over.append(None if pair is None else pair[0])
        r_over.append(None if pair is None else pair[1])
    any_over = any(o is not None for o in l_over)

    l_h1, _l_h2, l_ok = _key_hash2(left_keys, l_over if any_over else None)
    r_h1, r_h2, r_ok = _key_hash2(right_keys, r_over if any_over else None)
    sentinel = ~jnp.uint32(0)
    r_h1 = jnp.where(r_live & r_ok, r_h1, sentinel)
    perm, r_sorted = _build_sort(r_h1, r_h2)

    code_pairs = [None if a is None else (a, b)
                  for a, b in zip(l_over, r_over)] if any_over else None

    def xla_candidates():
        lo, counts, total = _phase1(l_h1, l_ok, l_live, r_sorted,
                                    right_num_rows)
        total_c = jnp.minimum(total, pair_cap)
        cum = jnp.cumsum(counts)
        starts = cum - counts
        k = jnp.arange(pair_cap, dtype=jnp.int32)
        probe_row = jnp.searchsorted(cum, k, side="right").astype(jnp.int32)
        probe_row = jnp.clip(probe_row, 0, l_cap - 1)
        ordinal = (k - starts[probe_row]).astype(jnp.int32)
        build_pos = jnp.clip(lo[probe_row] + ordinal, 0, r_cap - 1)
        build_row = perm[build_pos]
        in_range = k < total_c
        match = in_range & _exact_eq(left_keys, probe_row, right_keys,
                                     build_row, code_pairs)
        return probe_row, build_row, match, total

    def pallas_candidates(interpret):
        a_words, a_valid = _exact_words(left_keys,
                                        l_over if any_over else None)
        b_words, b_valid = _exact_words(right_keys,
                                        r_over if any_over else None)
        from spark_rapids_tpu.kernels import pallas_tier as PT
        return PT.probe_join(l_h1, l_ok & l_live, r_sorted, perm,
                             a_words, a_valid, b_words, b_valid,
                             pair_cap, interpret=interpret)

    # VMEM residency: the sorted build hashes, the permutation, the build
    # word matrix and validity must all stay resident for the fused probe
    from spark_rapids_tpu.kernels import pallas_tier as PT
    n_words = _exact_word_count(right_keys, r_over if any_over else None)
    resident = r_cap * (4 + 4 + 4 * n_words + 4)
    probe_row, build_row, match, total = PT.run(
        "joinProbe", pallas_candidates, xla_candidates,
        resident_bytes=resident)
    overflow = total > pair_cap
    order = jnp.argsort(jnp.where(match, 0, 1), stable=True)
    n_pairs = jnp.sum(match).astype(jnp.int32)
    l_idx = probe_row[order].astype(jnp.int32)
    r_idx = build_row[order].astype(jnp.int32)
    ones = match.astype(jnp.int32)
    l_counts = jax.ops.segment_sum(ones, probe_row, num_segments=l_cap,
                                   indices_are_sorted=True)
    r_matched = jax.ops.segment_max(ones, build_row,
                                    num_segments=r_cap) > 0
    return l_idx, r_idx, n_pairs, l_counts, r_matched, overflow


def _static_byte_caps(batch: ColumnBatch, growth: float,
                      out_cap: int = 0) -> List[int]:
    """Static growth-scaled output byte capacities per varlen column.

    A join gather can DUPLICATE one side's rows up to the pair count (a
    6-row build side probed by 200 rows emits its strings ~200 times),
    so input bytes alone under-size wildly: scale by the row expansion
    ``out_cap / capacity`` too — growth x expansion x input bytes holds
    as long as the duplicated rows' average length stays within growth of
    the input average; the in-program needed-bytes check catches the
    adversarial tail.  ``batch`` must already be in row layout."""
    expand = max(1.0, out_cap / batch.capacity) if out_cap else 1.0
    return [round_up_capacity(
        max(int(int(c.data.shape[0]) * growth * expand), 1), minimum=16)
        for c in batch.columns if c.is_varlen]


def _needed_bytes(batch: ColumnBatch, indices, live) -> List[jnp.ndarray]:
    """Traced per-varlen-column byte totals a gather at ``indices`` needs
    (the in-program sibling of :func:`_string_byte_caps` — no host sync).
    ``batch`` must already be in row layout."""
    needs = []
    for c in batch.columns:
        if c.is_varlen:
            lens = (c.offsets[1:] - c.offsets[:-1]).astype(jnp.int32)
            needs.append(jnp.sum(jnp.where(
                live, lens[jnp.clip(indices, 0, batch.capacity - 1)], 0)))
    return needs


def _caps_overflow(needs: List[jnp.ndarray], caps: List[int]):
    """Traced bool: any needed byte total exceeds its static capacity.
    Mandatory check — :func:`gather_rows` silently truncates varlen data
    past the byte cap (its ``in_range`` mask), so an undetected overflow
    would corrupt output instead of failing."""
    ovf = jnp.asarray(False)
    for need, cap in zip(needs, caps):
        ovf = ovf | (need > cap)
    return ovf


def stitch_join_output_static(left: ColumnBatch, right: ColumnBatch,
                              l_idx, r_idx, n_pairs, l_counts, r_matched,
                              join_type: str, out_schema: T.Schema,
                              growth: float):
    """Traced :func:`stitch_join_output` with STATIC output capacities.

    Row capacities: semi/anti at the left capacity (a filter — can never
    overflow); inner at the pair capacity; outer at
    ``round_up_capacity(pair_cap + l_cap + r_cap)`` (pairs plus every
    possibly-unmatched row — also exact, never overflows).  Varlen byte
    capacities are growth-scaled static buckets with an in-program
    needed-bytes check.  Returns ``(batch, overflow)``; on overflow the
    batch content is invalid and the caller must fall back."""
    left = ensure_row_layout(left)
    right = ensure_row_layout(right)
    l_cap, r_cap = left.capacity, right.capacity
    pair_cap = int(l_idx.shape[0])
    l_live = jnp.arange(l_cap, dtype=jnp.int32) < left.num_rows
    r_live = jnp.arange(r_cap, dtype=jnp.int32) < right.num_rows
    no_ovf = jnp.asarray(False)

    if join_type in ("left_semi", "left_anti"):
        if join_type == "left_semi":
            mask = l_live & (l_counts > 0)
        else:
            mask = l_live & (l_counts == 0)
        idx, count = compaction_indices(mask, left.num_rows)
        # pure row filter of the left side: default caps exact, no overflow
        return gather_rows(left, idx, count), no_ovf

    if join_type == "inner":
        live = jnp.arange(pair_cap, dtype=jnp.int32) < n_pairs
        lcaps = _static_byte_caps(left, growth, out_cap=pair_cap)
        rcaps = _static_byte_caps(right, growth, out_cap=pair_cap)
        ovf = _caps_overflow(_needed_bytes(left, l_idx, live), lcaps) | \
            _caps_overflow(_needed_bytes(right, r_idx, live), rcaps)
        lg = gather_rows(left, l_idx, n_pairs, out_capacity=pair_cap,
                         out_byte_caps=lcaps or None)
        rg = gather_rows(right, r_idx, n_pairs, out_capacity=pair_cap,
                         out_byte_caps=rcaps or None)
        return ColumnBatch(out_schema, list(lg.columns) + list(rg.columns),
                           n_pairs, pair_cap), ovf

    if join_type in ("left", "right", "full"):
        add_left = join_type in ("left", "full")
        add_right = join_type in ("right", "full")
        un_l_mask = l_live & (l_counts == 0) if add_left else \
            jnp.zeros(l_cap, dtype=jnp.bool_)
        un_r_mask = r_live & ~r_matched if add_right else \
            jnp.zeros(r_cap, dtype=jnp.bool_)
        n_un_l = jnp.sum(un_l_mask).astype(jnp.int32)
        n_un_r = jnp.sum(un_r_mask).astype(jnp.int32)
        total = n_pairs + n_un_l + n_un_r
        out_cap = round_up_capacity(pair_cap + l_cap + r_cap)

        un_l_idx, _ = compaction_indices(un_l_mask, left.num_rows)
        un_r_idx, _ = compaction_indices(un_r_mask, right.num_rows)

        i = jnp.arange(out_cap, dtype=jnp.int32)
        in_pairs = i < n_pairs
        in_un_l = (i >= n_pairs) & (i < n_pairs + n_un_l)
        li = jnp.where(in_pairs, l_idx[jnp.clip(i, 0, pair_cap - 1)],
                       un_l_idx[jnp.clip(i - n_pairs, 0, l_cap - 1)])
        li = jnp.where(in_un_l | in_pairs, li, 0)
        l_valid = in_pairs | in_un_l
        ri = jnp.where(in_pairs, r_idx[jnp.clip(i, 0, pair_cap - 1)],
                       un_r_idx[jnp.clip(i - n_pairs - n_un_l, 0,
                                         r_cap - 1)])
        in_un_r = (i >= n_pairs + n_un_l) & (i < n_pairs + n_un_l + n_un_r)
        ri = jnp.where(in_pairs | in_un_r, ri, 0)
        r_valid = in_pairs | in_un_r

        live = jnp.arange(out_cap, dtype=jnp.int32) < total
        # needed = matched pairs' bytes + unmatched rows' bytes; unmatched
        # rows alone can fill a whole input, so scale by growth + 1.
        # The needed-bytes mask is `live` alone (matching the gather,
        # which copies row 0's bytes for null-padded rows) — masking by
        # validity too would let a truncation slip past the overflow check
        lcaps = _static_byte_caps(left, growth + 1.0, out_cap=out_cap)
        rcaps = _static_byte_caps(right, growth + 1.0, out_cap=out_cap)
        ovf = _caps_overflow(
            _needed_bytes(left, jnp.where(l_valid, li, 0), live),
            lcaps) | _caps_overflow(
            _needed_bytes(right, jnp.where(r_valid, ri, 0), live),
            rcaps)
        lg = gather_rows(left, jnp.where(l_valid, li, 0), total,
                         out_capacity=out_cap, out_byte_caps=lcaps or None)
        rg = gather_rows(right, jnp.where(r_valid, ri, 0), total,
                         out_capacity=out_cap, out_byte_caps=rcaps or None)
        lcols = [type(c)(c.dtype, c.data, c.validity & l_valid, c.offsets)
                 for c in lg.columns]
        rcols = [type(c)(c.dtype, c.data, c.validity & r_valid, c.offsets)
                 for c in rg.columns]
        return ColumnBatch(out_schema, lcols + rcols, total, out_cap), ovf

    raise ValueError(f"unsupported join type: {join_type}")


def hash_join_static(left: ColumnBatch, left_keys: List[DevVal],
                     right: ColumnBatch, right_keys: List[DevVal],
                     join_type: str, out_schema: T.Schema,
                     growth: float = 2.0):
    """Fully-traced equi-join with capacity-bucketed output sizing (no
    host sync — the mesh-SPMD fused path).  The pair capacity is the
    BucketPolicy quantization of ``left.capacity * growth``; residual
    conditions are NOT supported (they host-sync for byte sizing — the
    lowering gates on ``condition is None``).  Returns
    ``(batch, overflow)``: on overflow the caller must discard the batch
    and rerun the stage host-driven."""
    pair_cap = round_up_capacity(max(int(left.capacity * growth), 1))
    l_idx, r_idx, n_pairs, l_counts, r_matched, ovf = join_pairs_static(
        left_keys, left.num_rows, right_keys, right.num_rows, pair_cap)
    out, ovf2 = stitch_join_output_static(
        left, right, l_idx, r_idx, n_pairs, l_counts, r_matched,
        join_type, out_schema, growth)
    return out, ovf | ovf2


def _string_byte_caps(batch: ColumnBatch, indices, live) -> List[int]:
    """Host-sync sizing of output byte capacities for string columns.

    Encoded columns size at their MATERIALIZED per-row lengths (entry
    lengths gathered through clipped codes, NULL rows zero) — the output
    gather materializes, and these caps must match encoded-off bit for
    bit."""
    caps = []
    for c in batch.columns:
        if c.is_string:
            if c.codes is not None:
                nd = int(c.offsets.shape[0]) - 1
                ent_lens = (c.offsets[1:] - c.offsets[:-1]).astype(jnp.int64)
                codes_c = jnp.clip(c.codes, 0, max(nd - 1, 0))
                lens = jnp.where(c.validity, ent_lens[codes_c], 0)
            else:
                lens = (c.offsets[1:] - c.offsets[:-1]).astype(jnp.int64)
            total = jnp.sum(jnp.where(live, lens[jnp.clip(
                indices, 0, batch.capacity - 1)], 0))
            caps.append(round_up_capacity(int(jax.device_get(total)),
                                          minimum=16))
    return caps


def _filter_pairs(left: ColumnBatch, right: ColumnBatch, l_idx, r_idx,
                  n_pairs, condition):
    """Apply a residual join condition to the matched pairs BEFORE any
    null-padding (GpuHashJoin.scala:265-271: the condition gates matches,
    so a row whose every match fails becomes an *unmatched* outer row).

    Only the columns the condition references are gathered.  Returns the
    filtered (l_idx, r_idx, n_pairs, l_counts, r_matched).
    """
    from spark_rapids_tpu.exprs.base import TpuEvalCtx
    pair_cap = int(l_idx.shape[0])
    l_cap, r_cap = left.capacity, right.capacity
    refs = set(condition.references)
    live = jnp.arange(pair_cap, dtype=jnp.int32) < n_pairs

    fields, cols = [], []
    for side, idx in ((left, l_idx), (right, r_idx)):
        for f, c in zip(side.schema.fields, side.columns):
            if f.name not in refs:
                continue
            sub = ColumnBatch(T.Schema([f]), [c], side.num_rows,
                              side.capacity)
            bcaps = _string_byte_caps(sub, idx, live)
            g = gather_rows(sub, idx, n_pairs, out_capacity=pair_cap,
                            out_byte_caps=bcaps or None)
            fields.append(f)
            cols.append(g.columns[0])
    paired = ColumnBatch(T.Schema(fields), cols, n_pairs, pair_cap)
    v = condition.tpu_eval(TpuEvalCtx(paired))
    keep = live & v.validity & v.data.astype(jnp.bool_)

    order = jnp.argsort(jnp.where(keep, 0, 1), stable=True).astype(jnp.int32)
    new_n = jnp.sum(keep).astype(jnp.int32)
    new_l = l_idx[order]
    new_r = r_idx[order]
    ones = keep.astype(jnp.int32)
    l_counts = jax.ops.segment_sum(
        ones, jnp.clip(l_idx, 0, l_cap - 1), num_segments=l_cap)
    r_matched = jax.ops.segment_max(
        ones, jnp.clip(r_idx, 0, r_cap - 1), num_segments=r_cap) > 0
    return new_l, new_r, new_n, l_counts, r_matched


def hash_join(left: ColumnBatch, left_keys: List[DevVal],
              right: ColumnBatch, right_keys: List[DevVal],
              join_type: str, out_schema: T.Schema,
              condition=None) -> ColumnBatch:
    """Full equi-join of two batches.  Output columns = left cols ++ right
    cols (semi/anti: left only), per ``out_schema``.  ``condition`` is an
    optional residual expression applied to matched pairs (before outer
    null-padding, so it changes which rows count as matched)."""
    l_idx, r_idx, n_pairs, l_counts, r_matched = join_pairs(
        left_keys, left.num_rows, right_keys, right.num_rows)
    if condition is not None:
        l_idx, r_idx, n_pairs, l_counts, r_matched = _filter_pairs(
            left, right, l_idx, r_idx, n_pairs, condition)
    return stitch_join_output(left, right, l_idx, r_idx, n_pairs, l_counts,
                              r_matched, join_type, out_schema)


def stitch_join_output(left: ColumnBatch, right: ColumnBatch, l_idx, r_idx,
                       n_pairs, l_counts, r_matched, join_type: str,
                       out_schema: T.Schema) -> ColumnBatch:
    """Materialize the joined batch from matched pair index arrays."""
    l_cap, r_cap = left.capacity, right.capacity
    pair_cap = int(l_idx.shape[0])
    l_live = jnp.arange(l_cap, dtype=jnp.int32) < left.num_rows
    r_live = jnp.arange(r_cap, dtype=jnp.int32) < right.num_rows

    if join_type in ("left_semi", "left_anti"):
        if join_type == "left_semi":
            mask = l_live & (l_counts > 0)
        else:
            mask = l_live & (l_counts == 0)
        idx, count = compaction_indices(mask, left.num_rows)
        return gather_rows(left, idx, count)

    if join_type == "inner":
        live = jnp.arange(pair_cap, dtype=jnp.int32) < n_pairs
        lcaps = _string_byte_caps(left, l_idx, live)
        rcaps = _string_byte_caps(right, r_idx, live)
        lg = gather_rows(left, l_idx, n_pairs, out_capacity=pair_cap,
                         out_byte_caps=lcaps or None)
        rg = gather_rows(right, r_idx, n_pairs, out_capacity=pair_cap,
                         out_byte_caps=rcaps or None)
        return ColumnBatch(out_schema, list(lg.columns) + list(rg.columns),
                           n_pairs, pair_cap)

    if join_type in ("left", "right", "full"):
        # Unmatched-left rows (left/full) and unmatched-right rows
        # (right/full) are appended after the matched pairs with the other
        # side NULL-padded.
        add_left = join_type in ("left", "full")
        add_right = join_type in ("right", "full")
        un_l_mask = l_live & (l_counts == 0) if add_left else \
            jnp.zeros(l_cap, dtype=jnp.bool_)
        un_r_mask = r_live & ~r_matched if add_right else \
            jnp.zeros(r_cap, dtype=jnp.bool_)
        n_un_l = jnp.sum(un_l_mask).astype(jnp.int32)
        n_un_r = jnp.sum(un_r_mask).astype(jnp.int32)
        total = n_pairs + n_un_l + n_un_r
        total_h = int(jax.device_get(total))
        out_cap = round_up_capacity(max(total_h, 1))

        un_l_idx, _ = compaction_indices(un_l_mask, left.num_rows)
        un_r_idx, _ = compaction_indices(un_r_mask, right.num_rows)

        @jax.jit
        def stitch_indices(l_idx, r_idx, un_l_idx, un_r_idx, n_pairs, n_un_l,
                           n_un_r):
            i = jnp.arange(out_cap, dtype=jnp.int32)
            in_pairs = i < n_pairs
            in_un_l = (i >= n_pairs) & (i < n_pairs + n_un_l)
            li = jnp.where(in_pairs, l_idx[jnp.clip(i, 0, pair_cap - 1)],
                           un_l_idx[jnp.clip(i - n_pairs, 0, l_cap - 1)])
            li = jnp.where(in_un_l | in_pairs, li, 0)
            l_valid = in_pairs | in_un_l
            ri = jnp.where(in_pairs, r_idx[jnp.clip(i, 0, pair_cap - 1)],
                           un_r_idx[jnp.clip(i - n_pairs - n_un_l, 0,
                                             r_cap - 1)])
            in_un_r = (i >= n_pairs + n_un_l) & (i < n_pairs + n_un_l + n_un_r)
            ri = jnp.where(in_pairs | in_un_r, ri, 0)
            r_valid = in_pairs | in_un_r
            return li, l_valid, ri, r_valid

        li, l_valid, ri, r_valid = stitch_indices(
            l_idx, r_idx, un_l_idx, un_r_idx, n_pairs, n_un_l, n_un_r)
        live = jnp.arange(out_cap, dtype=jnp.int32) < total
        # caps must count what the gather COPIES, not what stays valid:
        # null-padded rows gather row 0's bytes (validity masked after),
        # so size over the zeroed indices with the live mask alone — a
        # `live & valid` mask undersizes and truncates the last real rows
        lcaps = _string_byte_caps(left, jnp.where(l_valid, li, 0), live)
        rcaps = _string_byte_caps(right, jnp.where(r_valid, ri, 0), live)
        # NULL-pad: gather with index 0 for padded side, then mask validity.
        lg = gather_rows(left, jnp.where(l_valid, li, 0), total,
                         out_capacity=out_cap, out_byte_caps=lcaps or None)
        rg = gather_rows(right, jnp.where(r_valid, ri, 0), total,
                         out_capacity=out_cap, out_byte_caps=rcaps or None)
        lcols = [type(c)(c.dtype, c.data, c.validity & l_valid, c.offsets)
                 for c in lg.columns]
        rcols = [type(c)(c.dtype, c.data, c.validity & r_valid, c.offsets)
                 for c in rg.columns]
        return ColumnBatch(out_schema, lcols + rcols, total, out_cap)

    raise ValueError(f"unsupported join type: {join_type}")


def cross_join(left: ColumnBatch, right: ColumnBatch,
               out_schema: T.Schema) -> ColumnBatch:
    """Cartesian product (GpuCartesianProductExec analogue)."""
    return nested_loop_join(left, right, "cross", None, out_schema)


def _cross_pairs(left: ColumnBatch, right: ColumnBatch, condition):
    """All-pairs index arrays (optionally condition-filtered):
    (l_idx, r_idx, n_pairs, l_counts, r_matched).  Pair capacity is
    n_l * n_r — callers bound it by chunking the left side."""
    l_cap, r_cap = left.capacity, right.capacity
    n_l = int(jax.device_get(left.num_rows))
    n_r = int(jax.device_get(right.num_rows))
    total = n_l * n_r
    pair_cap = round_up_capacity(max(total, 1))
    i = jnp.arange(pair_cap, dtype=jnp.int32)
    li = jnp.where(n_r > 0, i // max(n_r, 1), 0).astype(jnp.int32)
    ri = jnp.where(n_r > 0, i % max(n_r, 1), 0).astype(jnp.int32)
    n_pairs = jnp.asarray(total, jnp.int32)
    l_live = jnp.arange(l_cap, dtype=jnp.int32) < left.num_rows
    r_live = jnp.arange(r_cap, dtype=jnp.int32) < right.num_rows
    if condition is not None:
        return _filter_pairs(left, right, li, ri, n_pairs, condition)
    l_counts = jnp.where(l_live, n_r, 0).astype(jnp.int32)
    r_matched = r_live & (n_l > 0)
    return li, ri, n_pairs, l_counts, r_matched


def nested_loop_join(left: ColumnBatch, right: ColumnBatch, join_type: str,
                     condition, out_schema: T.Schema) -> ColumnBatch:
    """All-pairs join with an optional condition — every join type
    (GpuBroadcastNestedLoopJoinExec.scala:305: the reference runs outer /
    semi NLJ on device too).  Matched pairs = cross pairs passing the
    condition; unmatched rows null-pad per the join type."""
    li, ri, n_pairs, l_counts, r_matched = _cross_pairs(
        left, right, condition)
    if join_type == "cross":
        join_type = "inner"
    return stitch_join_output(left, right, li, ri, n_pairs, l_counts,
                              r_matched, join_type, out_schema)


def nested_loop_join_streamed(left_chunks, left_empty: ColumnBatch,
                              right: ColumnBatch, join_type: str,
                              condition, out_schema: T.Schema):
    """right/full NLJ with the left side STREAMED in bounded chunks (the
    reference streams broadcast NLJ per stream batch,
    GpuBroadcastNestedLoopJoinExec.scala:305) — no n_l*n_r pair-space
    allocation.  Right-unmatched rows are a property of the WHOLE left
    side, so matched flags accumulate across chunks and the
    left-NULL-padded remainder is emitted once at the end.

    ``left_empty`` is an empty batch of the left schema used for the final
    right-unmatched emission (also correct when ``left_chunks`` is empty).
    Yields one batch per chunk plus the final remainder batch."""
    assert join_type in ("right", "full"), join_type
    r_cap = right.capacity
    acc = jnp.zeros(r_cap, dtype=jnp.bool_)
    # per-chunk: matched pairs (+ left-unmatched padding for 'full' —
    # left rows belong to exactly one chunk, right is fully present)
    per_chunk = "inner" if join_type == "right" else "left"
    for lb in left_chunks:
        li, ri, n_pairs, l_counts, r_matched = _cross_pairs(
            lb, right, condition)
        acc = acc | r_matched
        yield stitch_join_output(lb, right, li, ri, n_pairs, l_counts,
                                 r_matched, per_chunk, out_schema)
    pair1 = round_up_capacity(1)
    zero_idx = jnp.zeros(pair1, jnp.int32)
    yield stitch_join_output(
        left_empty, right, zero_idx, zero_idx,
        jnp.asarray(0, jnp.int32),
        jnp.zeros(left_empty.capacity, jnp.int32), acc, "right",
        out_schema)
