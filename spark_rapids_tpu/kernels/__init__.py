"""Static-shape device kernels for the columnar engine.

This package is the TPU analogue of libcudf's kernel surface
(SURVEY.md section 2.9): everything the reference does through cudf JNI calls
(filter, orderBy, groupby aggregate, joins, concatenate, partition) is
implemented here as jit-friendly JAX code over the padded
:class:`~spark_rapids_tpu.batch.ColumnBatch` layout.

Design rules (see batch.py / SURVEY.md section 7):

* all shapes static at trace time; dynamic row counts are ``num_rows`` scalars
  plus masks;
* kernels whose *output* size is data-dependent (join, concat growth) use the
  two-phase pattern: a jitted sizing pass returns scalar counts, the host
  buckets them to a power-of-two capacity, and a second jitted pass runs with
  that static capacity.  The compile cache amortizes this across batches;
* row movement prefers *gather* so XLA can fuse freely; the exceptions
  (compaction ranking, k-way concat) are single-pass scatters with
  genuinely unique indices so XLA emits plain scatters, not sort-based
  ones.
"""

from spark_rapids_tpu.kernels.layout import (
    compact,
    concat_kway,
    concat_pair,
    gather_rows,
    take_head,
)
from spark_rapids_tpu.kernels.sort import argsort_batch, sort_batch
from spark_rapids_tpu.kernels.sortkeys import encode_sort_keys
