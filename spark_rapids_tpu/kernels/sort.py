"""Multi-column sort over a ColumnBatch (cudf ``Table.orderBy`` analogue,
GpuSortExec.scala:241)."""

from __future__ import annotations

from typing import List

import jax.numpy as jnp

from spark_rapids_tpu.batch import ColumnBatch
from spark_rapids_tpu.exprs.base import DevVal
from spark_rapids_tpu.kernels.layout import gather_rows
from spark_rapids_tpu.kernels.sortkeys import (
    DEFAULT_STRING_PREFIX_BYTES,
    argsort_by_words,
    encode_sort_keys,
)


def argsort_batch(key_vals: List[DevVal], ascendings: List[bool],
                  nulls_firsts: List[bool], num_rows,
                  string_prefix_bytes: int = DEFAULT_STRING_PREFIX_BYTES,
                  groupings=None):
    """Permutation sorting rows by the given evaluated key columns.

    ``groupings`` marks columns that only need equal keys adjacent (see
    encode_sort_keys) — groupby/window partitioning pass it to keep string
    sorts at 3 key words instead of ~19."""
    cap = int(key_vals[0].validity.shape[0])
    words = encode_sort_keys(key_vals, ascendings, nulls_firsts, num_rows,
                             string_prefix_bytes, groupings=groupings)
    return argsort_by_words(words, cap)


def sort_batch(batch: ColumnBatch, key_vals: List[DevVal],
               ascendings: List[bool], nulls_firsts: List[bool],
               string_prefix_bytes: int = DEFAULT_STRING_PREFIX_BYTES
               ) -> ColumnBatch:
    perm = argsort_batch(key_vals, ascendings, nulls_firsts, batch.num_rows,
                         string_prefix_bytes)
    return gather_rows(batch, perm, batch.num_rows)
