"""Physical operator implementations: TPU execs (device kernels) and their
CPU fallback twins (numpy/python), mirroring the reference's GpuExec library
(SURVEY.md section 2.5) plus per-operator CPU fallback."""
