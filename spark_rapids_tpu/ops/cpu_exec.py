"""CPU (host, numpy/python) physical operators — the per-operator fallback
path.  In the reference, unsupported operators simply stay as Spark CPU execs
(RapidsMeta.scala willNotWorkOnGpu); here the engine owns both sides, so every
operator has an explicit host implementation with Spark CPU semantics.  These
double as the correctness oracle for the TPU execs.
"""

from __future__ import annotations

import itertools
import math
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import HostBatch, HostColumn
from spark_rapids_tpu.exprs.aggregates import AggregateExpression
from spark_rapids_tpu.exprs.base import (
    CpuEvalCtx, Expression, SortOrder, output_name,
)
from spark_rapids_tpu.plan.physical import CpuExec, ExecContext, PhysicalOp


def _rows(batch: HostBatch) -> List[tuple]:
    cols = [c.to_list() for c in batch.columns]
    return list(zip(*cols)) if cols else [() for _ in range(batch.num_rows)]


def _from_rows(schema: T.Schema, rows: List[tuple]) -> HostBatch:
    cols = []
    for i, f in enumerate(schema.fields):
        items = [r[i] for r in rows]
        cols.append(HostColumn.from_list(f.dtype, items))
    return HostBatch(schema, cols)


def sort_key_fn(orders: List[SortOrder], key_ordinals: List[int]
                ) -> Callable[[tuple], tuple]:
    """Spark-semantics sort key for python rows (NaN greatest, nulls per
    nulls_first, descending via wrapper)."""

    class _Desc:
        __slots__ = ("v",)

        def __init__(self, v):
            self.v = v

        def __lt__(self, o):
            return o.v < self.v

        def __eq__(self, o):
            return o.v == self.v

    def enc(v, o: SortOrder):
        if v is None:
            return (0 if o.nulls_first else 1, 0)
        if isinstance(v, float) and math.isnan(v):
            core = (1, 0.0)
        elif isinstance(v, bool):
            core = (0, int(v))
        elif isinstance(v, str):
            core = (0, v.encode("utf-8"))
        else:
            core = (0, v)
        rank = 1 if o.nulls_first else 0
        return (rank, core if o.ascending else _Desc(core))

    def key(row):
        return tuple(enc(row[i], o) for i, o in zip(key_ordinals, orders))

    return key


class CpuInMemoryScanExec(CpuExec):
    def __init__(self, batches: List[HostBatch], schema: T.Schema,
                 num_partitions: int):
        super().__init__([], schema)
        self.batches = batches
        self._n = max(1, num_partitions)

    def num_partitions(self, ctx):
        return self._n

    def partitions(self, ctx):
        parts: List[List[HostBatch]] = [[] for _ in range(self._n)]
        for i, b in enumerate(self.batches):
            parts[i % self._n].append(b)
        return [iter(p) for p in parts]


class CpuRangeExec(CpuExec):
    def __init__(self, start, end, step, num_partitions, schema):
        super().__init__([], schema)
        self.start, self.end, self.step = start, end, step
        self._n = max(1, num_partitions)

    def num_partitions(self, ctx):
        return self._n

    def partitions(self, ctx):
        total = max(0, -(-(self.end - self.start) // self.step))
        per = -(-total // self._n)

        def gen(p):
            lo = self.start + p * per * self.step
            hi = min(self.start + (p + 1) * per * self.step, self.end) \
                if self.step > 0 else max(
                    self.start + (p + 1) * per * self.step, self.end)
            vals = np.arange(lo, hi, self.step, dtype=np.int64)
            if len(vals):
                yield HostBatch(self.output_schema, [
                    HostColumn(T.LONG, vals, np.ones(len(vals), np.bool_))
                ])

        return [gen(p) for p in range(self._n)]


class CpuProjectExec(CpuExec):
    def __init__(self, exprs: List[Expression], child: PhysicalOp,
                 schema: T.Schema):
        super().__init__([child], schema)
        self.exprs = exprs

    def describe(self):
        return f"CpuProject({', '.join(f.name for f in self.output_schema)})"

    def partitions(self, ctx):
        def gen(part):
            for hb in part:
                cctx = CpuEvalCtx(hb)
                cols = [e.cpu_eval(cctx).to_column() for e in self.exprs]
                yield HostBatch(self.output_schema, cols)

        return [gen(p) for p in self.children[0].partitions(ctx)]


class CpuFilterExec(CpuExec):
    def __init__(self, condition: Expression, child: PhysicalOp):
        super().__init__([child], child.output_schema)
        self.condition = condition

    def describe(self):
        return f"CpuFilter({self.condition!r})"

    def partitions(self, ctx):
        def gen(part):
            for hb in part:
                cctx = CpuEvalCtx(hb)
                v = self.condition.cpu_eval(cctx)
                keep = v.validity & v.values.astype(bool)
                cols = [HostColumn(c.dtype, c.values[keep], c.validity[keep])
                        for c in hb.columns]
                out = HostBatch(hb.schema, cols)
                if out.num_rows:
                    yield out

        return [gen(p) for p in self.children[0].partitions(ctx)]


class CpuUnionExec(CpuExec):
    def __init__(self, children: List[PhysicalOp], schema: T.Schema):
        super().__init__(children, schema)

    def num_partitions(self, ctx):
        return sum(c.num_partitions(ctx) for c in self.children)

    def partitions(self, ctx):
        out = []
        for c in self.children:
            for p in c.partitions(ctx):
                out.append(self._rename(p))
        return out

    def _rename(self, part):
        for hb in part:
            yield HostBatch(self.output_schema, hb.columns)


class CpuLocalLimitExec(CpuExec):
    def __init__(self, n: int, child: PhysicalOp):
        super().__init__([child], child.output_schema)
        self.n = n

    def partitions(self, ctx):
        def gen(part):
            left = self.n
            for hb in part:
                if left <= 0:
                    break
                if hb.num_rows > left:
                    hb = hb.slice(0, left)
                left -= hb.num_rows
                yield hb

        return [gen(p) for p in self.children[0].partitions(ctx)]


class CpuSortExec(CpuExec):
    def __init__(self, orders: List[SortOrder], key_ordinals: List[int],
                 child: PhysicalOp):
        super().__init__([child], child.output_schema)
        self.orders = orders
        self.key_ordinals = key_ordinals

    def describe(self):
        return f"CpuSort({len(self.orders)} keys)"

    def partitions(self, ctx):
        key = sort_key_fn(self.orders, self.key_ordinals)

        def gen(part):
            rows = []
            for hb in part:
                rows.extend(_rows(hb))
            rows.sort(key=key)
            if rows:
                yield _from_rows(self.output_schema, rows)

        return [gen(p) for p in self.children[0].partitions(ctx)]


class CpuAggregateExec(CpuExec):
    """Whole-aggregation on host: dict-of-key-tuples grouping.

    Used when the agg falls back; partial/final split is unnecessary on host
    because this exec runs *after* an exchange has co-located each key's rows
    (or on a single partition for reductions)."""

    def __init__(self, key_exprs: List[Expression],
                 key_ordinals_in_child: List[Expression],
                 aggs: List[AggregateExpression], child: PhysicalOp,
                 schema: T.Schema):
        super().__init__([child], schema)
        self.key_exprs = key_exprs
        self.aggs = aggs

    def describe(self):
        return f"CpuAggregate(keys={len(self.key_exprs)})"

    def partitions(self, ctx):
        def gen(part):
            groups: Dict[tuple, List[List]] = {}
            key_order: List[tuple] = []
            n_aggs = len(self.aggs)
            for hb in part:
                cctx = CpuEvalCtx(hb)
                key_cols = [e.cpu_eval(cctx).to_column().to_list()
                            for e in self.key_exprs]
                in_cols = []
                for a in self.aggs:
                    v = a.fn.child.cpu_eval(cctx)
                    in_cols.append((v.values, v.validity))
                for r in range(hb.num_rows):
                    k = tuple(col[r] for col in key_cols)
                    if k not in groups:
                        groups[k] = [[] for _ in range(n_aggs)]
                        key_order.append(k)
                    g = groups[k]
                    for i in range(n_aggs):
                        vals, valid = in_cols[i]
                        g[i].append((vals[r], bool(valid[r])))
            if not key_order and not self.key_exprs:
                key_order = [()]
                groups[()] = [[] for _ in range(n_aggs)]
            if not key_order:
                return
            rows = []
            for k in key_order:
                out_row = list(k)
                for i, a in enumerate(self.aggs):
                    pairs = groups[k][i]
                    if pairs:
                        vals = np.array([p[0] for p in pairs])
                        valid = np.array([p[1] for p in pairs], dtype=bool)
                    else:
                        vals = np.zeros(0)
                        valid = np.zeros(0, dtype=bool)
                    out_row.append(a.fn.cpu_reduce(vals, valid))
                rows.append(tuple(out_row))
            yield _from_rows(self.output_schema, rows)

        return [gen(p) for p in self.children[0].partitions(ctx)]


class CpuHashJoinExec(CpuExec):
    def __init__(self, left: PhysicalOp, right: PhysicalOp,
                 left_keys: List[Expression], right_keys: List[Expression],
                 how: str, condition: Optional[Expression],
                 schema: T.Schema):
        super().__init__([left, right], schema)
        self.left_keys = left_keys
        self.right_keys = right_keys
        self.how = how
        self.condition = condition

    def describe(self):
        return f"CpuHashJoin({self.how})"

    def num_partitions(self, ctx):
        return self.children[0].num_partitions(ctx)

    def partitions(self, ctx):
        lparts = self.children[0].partitions(ctx)
        rparts = self.children[1].partitions(ctx)
        assert len(lparts) == len(rparts), \
            f"join partition mismatch {len(lparts)} vs {len(rparts)}"

        def eval_keys(hb, exprs):
            cctx = CpuEvalCtx(hb)
            cols = [e.cpu_eval(cctx).to_column().to_list() for e in exprs]
            return [tuple(c[i] for c in cols) for i in range(hb.num_rows)]

        def gen(lp, rp):
            lrows, lkeys = [], []
            for hb in lp:
                lrows.extend(_rows(hb))
                lkeys.extend(eval_keys(hb, self.left_keys))
            rrows, rkeys = [], []
            for hb in rp:
                rrows.extend(_rows(hb))
                rkeys.extend(eval_keys(hb, self.right_keys))
            build: Dict[tuple, List[int]] = {}
            for j, k in enumerate(rkeys):
                if any(v is None for v in k):
                    continue
                build.setdefault(k, []).append(j)
            out = []
            l_matched = [False] * len(lrows)
            r_matched = [False] * len(rrows)
            semi = self.how in ("left_semi", "left_anti")
            r_width = len(rrows[0]) if rrows else \
                len(self.children[1].output_schema)
            l_width = len(lrows[0]) if lrows else \
                len(self.children[0].output_schema)
            for i, k in enumerate(lkeys):
                matches = [] if any(v is None for v in k) else \
                    build.get(k, [])
                for j in matches:
                    row = lrows[i] + rrows[j]
                    if self.condition is not None and not \
                            self._cond(row):
                        continue
                    l_matched[i] = True
                    r_matched[j] = True
                    if not semi:
                        out.append(row)
            if self.how in ("left", "full"):
                for i in range(len(lrows)):
                    if not l_matched[i]:
                        out.append(lrows[i] + (None,) * r_width)
            if self.how in ("right", "full"):
                for j in range(len(rrows)):
                    if not r_matched[j]:
                        out.append((None,) * l_width + rrows[j])
            if self.how == "left_semi":
                out = [lrows[i] for i in range(len(lrows)) if l_matched[i]]
            if self.how == "left_anti":
                out = [lrows[i] for i in range(len(lrows)) if not l_matched[i]]
            if out:
                yield _from_rows(self.output_schema, out)

        return [gen(lp, rp) for lp, rp in zip(lparts, rparts)]

    def _cond(self, row):
        # Evaluate the residual condition over a single joined row.  The
        # condition can reference both sides even for semi/anti joins whose
        # OUTPUT schema is left-only, so bind against left ++ right.
        sch = T.Schema(list(self.children[0].output_schema.fields) +
                       list(self.children[1].output_schema.fields))
        hb = _from_rows(sch, [row])
        v = self.condition.cpu_eval(CpuEvalCtx(hb))
        return bool(v.validity[0]) and bool(v.values[0])


class CpuNestedLoopJoinExec(CpuExec):
    """Cartesian / conditioned cross join (GpuBroadcastNestedLoopJoinExec +
    GpuCartesianProductExec fallback)."""

    def __init__(self, left: PhysicalOp, right: PhysicalOp, how: str,
                 condition: Optional[Expression], schema: T.Schema):
        super().__init__([left, right], schema)
        self.how = how
        self.condition = condition

    def num_partitions(self, ctx):
        # right/full need one global pass over both sides
        if self.how in ("right", "full"):
            return 1
        return self.children[0].num_partitions(ctx)

    def partitions(self, ctx):
        # Broadcast model: right side fully materialized once.
        rrows = []
        for p in self.children[1].partitions(ctx):
            for hb in p:
                rrows.extend(_rows(hb))
        lsch = self.children[0].output_schema
        rsch = self.children[1].output_schema
        l_nulls = tuple(None for _ in lsch.fields)
        r_nulls = tuple(None for _ in rsch.fields)
        lparts = self.children[0].partitions(ctx)

        def matches_of(lrow):
            return [(j, rrow) for j, rrow in enumerate(rrows)
                    if self.condition is None or self._cond(lrow, rrow)]

        def gen(lp):
            out = []
            for hb in lp:
                for lrow in _rows(hb):
                    ms = matches_of(lrow)
                    if self.how == "left_semi":
                        if ms:
                            out.append(lrow)
                    elif self.how == "left_anti":
                        if not ms:
                            out.append(lrow)
                    elif ms:
                        out.extend(lrow + rrow for _, rrow in ms)
                    elif self.how == "left":
                        out.append(lrow + r_nulls)
            if out:
                yield _from_rows(self.output_schema, out)

        if self.how in ("right", "full"):
            def gen_all():
                r_matched: set = set()
                out = []
                for part in lparts:
                    for hb in part:
                        for lrow in _rows(hb):
                            ms = matches_of(lrow)
                            r_matched.update(j for j, _ in ms)
                            if ms:
                                out.extend(lrow + rrow for _, rrow in ms)
                            elif self.how == "full":
                                out.append(lrow + r_nulls)
                for j, rrow in enumerate(rrows):
                    if j not in r_matched:
                        out.append(l_nulls + rrow)
                if out:
                    yield _from_rows(self.output_schema, out)

            return [gen_all()]
        return [gen(p) for p in lparts]

    def _cond(self, lrow, rrow):
        lsch = self.children[0].output_schema
        rsch = self.children[1].output_schema
        sch = T.Schema(list(lsch.fields) + list(rsch.fields))
        hb = _from_rows(sch, [lrow + rrow])
        v = self.condition.cpu_eval(CpuEvalCtx(hb))
        return bool(v.validity[0]) and bool(v.values[0])


class CpuExpandExec(CpuExec):
    def __init__(self, projections: List[List[Expression]], child: PhysicalOp,
                 schema: T.Schema):
        super().__init__([child], schema)
        self.projections = projections

    def partitions(self, ctx):
        def gen(part):
            for hb in part:
                cctx = CpuEvalCtx(hb)
                for proj in self.projections:
                    cols = [e.cpu_eval(cctx).to_column() for e in proj]
                    yield HostBatch(self.output_schema, cols)

        return [gen(p) for p in self.children[0].partitions(ctx)]


class CpuSampleExec(CpuExec):
    def __init__(self, fraction: float, seed: int, child: PhysicalOp):
        super().__init__([child], child.output_schema)
        self.fraction = fraction
        self.seed = seed

    def partitions(self, ctx):
        def gen(pi, part):
            rng = np.random.RandomState(self.seed + pi)
            for hb in part:
                keep = rng.rand(hb.num_rows) < self.fraction
                cols = [HostColumn(c.dtype, c.values[keep], c.validity[keep])
                        for c in hb.columns]
                out = HostBatch(hb.schema, cols)
                if out.num_rows:
                    yield out

        return [gen(i, p)
                for i, p in enumerate(self.children[0].partitions(ctx))]


class CpuGenerateExec(CpuExec):
    """explode/posexplode (+ outer) host fallback / oracle."""

    def __init__(self, column: str, alias: str, pos: bool, outer: bool,
                 child: PhysicalOp, schema: T.Schema):
        super().__init__([child], schema)
        self.column = column
        self.alias = alias
        self.pos = pos
        self.outer = outer

    def partitions(self, ctx):
        def gen(part):
            for hb in part:
                ci = hb.schema.index_of(self.column)
                cols = [c.to_list() for c in hb.columns]
                out_rows = []
                for r in range(hb.num_rows):
                    row = tuple(c[r] for c in cols)
                    arr = row[ci]
                    rest = row[:ci] + row[ci + 1:]
                    if arr:
                        for p, e in enumerate(arr):
                            out_rows.append(
                                rest + ((p,) if self.pos else ()) + (e,))
                    elif self.outer:
                        out_rows.append(
                            rest + ((None,) if self.pos else ()) + (None,))
                if out_rows:
                    yield _from_rows(self.output_schema, out_rows)

        return [gen(p) for p in self.children[0].partitions(ctx)]
