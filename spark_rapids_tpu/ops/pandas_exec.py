"""Pandas-exec family: map / grouped-map / cogrouped-map / grouped-agg
python execution over Arrow-shaped host batches.

Reference analogues (sql-plugin/.../execution/python/):
* GpuMapInPandasExec — :class:`CpuMapInPandasExec`
* GpuFlatMapGroupsInPandasExec — :class:`CpuFlatMapGroupsInPandasExec`
* GpuFlatMapCoGroupsInPandasExec — :class:`CpuFlatMapCoGroupsInPandasExec`
* GpuAggregateInPandasExec — :class:`CpuAggregateInPandasExec`
* GpuWindowInPandasExec — :class:`CpuWindowInPandasExec`

Like the reference, the engine side of these ops is data movement: device
batches come back to host columnar form, user python runs OUT OF PROCESS
in a forked worker streaming framed batches over pipes
(GpuArrowPythonRunner / python/rapids/worker.py analogue —
runtime/python_worker.py), bounded by the PythonWorkerSemaphore analogue
with the device semaphore released meanwhile, and results stage back to
HBM via the planner's automatic transitions.
"""

from __future__ import annotations

from typing import Callable, List

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import HostBatch, HostColumn
from spark_rapids_tpu.plan.physical import CpuExec, ExecContext, PhysicalOp
from spark_rapids_tpu.runtime.python_worker import (
    run_python_task, run_single_input_task,
)


def _to_pandas(hb: HostBatch):
    import pandas as pd
    return pd.DataFrame(hb.to_pydict())


def pandas_to_host_batch(pdf, schema: T.Schema) -> HostBatch:
    cols = []
    n = len(pdf)
    for f in schema.fields:
        if f.name not in pdf.columns:
            raise ValueError(
                f"pandas result is missing column {f.name!r}; has "
                f"{list(pdf.columns)}")
        s = pdf[f.name]
        validity = ~s.isna().to_numpy() if n else np.zeros(0, dtype=bool)
        if f.dtype.is_string:
            values = np.array(
                [("" if not ok else str(v))
                 for v, ok in zip(s.tolist(), validity)], dtype=object)
        else:
            values = s.fillna(0).to_numpy().astype(f.dtype.np_dtype)
        cols.append(HostColumn(f.dtype, values,
                               np.asarray(validity, dtype=np.bool_)))
    return HostBatch(schema, cols)


class CpuMapInPandasExec(CpuExec):
    """fn(Iterator[pd.DataFrame]) -> Iterator[pd.DataFrame], one call per
    partition (pyspark mapInPandas semantics)."""

    def __init__(self, fn: Callable, child: PhysicalOp, schema: T.Schema):
        super().__init__([child], schema)
        self.fn = fn

    def describe(self):
        return "CpuMapInPandas"

    def partitions(self, ctx: ExecContext):
        in_schema = self.children[0].output_schema
        out_schema = self.output_schema
        fn = self.fn

        def task(frames):  # runs in the worker process
            def pdf_iter():
                for _i, hb in frames:
                    yield _to_pandas(hb)

            for pdf in fn(pdf_iter()):
                hb = pandas_to_host_batch(pdf, out_schema)
                if hb.num_rows:
                    yield hb

        def gen(part):
            yield from run_single_input_task(ctx, task, part, in_schema,
                                             out_schema)

        return [gen(p) for p in self.children[0].partitions(ctx)]


class CpuFlatMapGroupsInPandasExec(CpuExec):
    """Per-group fn(pd.DataFrame) -> pd.DataFrame after a hash exchange on
    the grouping keys (child must be key-partitioned by the planner)."""

    def __init__(self, key_names: List[str], fn: Callable, child: PhysicalOp,
                 schema: T.Schema):
        super().__init__([child], schema)
        self.key_names = key_names
        self.fn = fn

    def describe(self):
        return f"CpuFlatMapGroupsInPandas(keys={self.key_names})"

    def partitions(self, ctx: ExecContext):
        in_schema = self.children[0].output_schema
        out_schema = self.output_schema
        fn, key_names = self.fn, self.key_names

        def task(frames):  # runs in the worker process
            batches = [hb for _i, hb in frames]
            if not batches:
                return
            pdf = _to_pandas(HostBatch.concat(batches))
            for _k, grp in pdf.groupby(key_names, dropna=False, sort=True):
                hb = pandas_to_host_batch(fn(grp), out_schema)
                if hb.num_rows:
                    yield hb

        def gen(part):
            yield from run_single_input_task(ctx, task, part, in_schema,
                                             out_schema)

        return [gen(p) for p in self.children[0].partitions(ctx)]


class CpuFlatMapCoGroupsInPandasExec(CpuExec):
    """Per-key fn(left_group_pdf, right_group_pdf) -> pd.DataFrame; both
    sides hash-exchanged on their keys to co-partition."""

    def __init__(self, left_names: List[str], right_names: List[str],
                 fn: Callable, left: PhysicalOp, right: PhysicalOp,
                 schema: T.Schema):
        super().__init__([left, right], schema)
        self.left_names = left_names
        self.right_names = right_names
        self.fn = fn

    def describe(self):
        return "CpuFlatMapCoGroupsInPandas"

    def num_partitions(self, ctx):
        return self.children[0].num_partitions(ctx)

    def partitions(self, ctx: ExecContext):
        import pandas as pd
        lparts = self.children[0].partitions(ctx)
        rparts = self.children[1].partitions(ctx)
        assert len(lparts) == len(rparts)
        lsch = self.children[0].output_schema
        rsch = self.children[1].output_schema

        def empty_pdf(schema):
            return pd.DataFrame({
                f.name: pd.Series([], dtype=object if f.dtype.is_string
                                  else f.dtype.np_dtype)
                for f in schema.fields})

        def norm_key(k):
            # pandas nulls group under NaN, and NaN != NaN would keep the
            # two sides' null groups from pairing — canonicalize to None
            parts = k if isinstance(k, tuple) else (k,)
            return tuple(None if (p is None or (isinstance(p, float)
                                                and p != p)) else p
                         for p in parts)

        fn = self.fn
        left_names, right_names = self.left_names, self.right_names
        out_schema = self.output_schema

        def task(frames):  # runs in the worker process
            lbs, rbs = [], []
            for i, hb in frames:
                (lbs if i == 0 else rbs).append(hb)
            lpdf = _to_pandas(HostBatch.concat(lbs)) if lbs else \
                empty_pdf(lsch)
            rpdf = _to_pandas(HostBatch.concat(rbs)) if rbs else \
                empty_pdf(rsch)
            lgroups = {norm_key(k): g for k, g in lpdf.groupby(
                left_names, dropna=False)} if len(lpdf) else {}
            rgroups = {norm_key(k): g for k, g in rpdf.groupby(
                right_names, dropna=False)} if len(rpdf) else {}
            keys = sorted(set(lgroups) | set(rgroups),
                          key=lambda k: (str(k),))
            for k in keys:
                lg = lgroups.get(k, lpdf.iloc[0:0])
                rg = rgroups.get(k, rpdf.iloc[0:0])
                hb = pandas_to_host_batch(fn(lg, rg), out_schema)
                if hb.num_rows:
                    yield hb

        def gen(lp, rp):
            def inputs():
                for hb in lp:
                    yield 0, hb
                for hb in rp:
                    yield 1, hb

            yield from run_python_task(ctx, task, inputs(),
                                       [lsch, rsch], out_schema)

        return [gen(lp, rp) for lp, rp in zip(lparts, rparts)]


class CpuAggregateInPandasExec(CpuExec):
    """One output row per group; each agg value is fn(pd.Series) over the
    group's column (pyspark GROUPED_AGG pandas_udf shape)."""

    def __init__(self, key_names: List[str], agg_specs, child: PhysicalOp,
                 schema: T.Schema):
        super().__init__([child], schema)
        self.key_names = key_names
        self.agg_specs = agg_specs  # (out_name, fn, dtype, col)

    def describe(self):
        return f"CpuAggregateInPandas(keys={self.key_names})"

    def partitions(self, ctx: ExecContext):
        in_schema = self.children[0].output_schema
        out_schema = self.output_schema
        key_names, agg_specs = self.key_names, self.agg_specs

        def task(frames):  # runs in the worker process
            batches = [hb for _i, hb in frames]
            if not batches:
                return
            pdf = _to_pandas(HostBatch.concat(batches))
            rows = []
            for k, grp in pdf.groupby(key_names, dropna=False, sort=True):
                key_vals = k if isinstance(k, tuple) else (k,)
                vals = [fn(grp[col]) for _n, fn, _dt, col in agg_specs]
                rows.append(tuple(key_vals) + tuple(vals))
            if not rows:
                return
            cols = []
            for i, f in enumerate(out_schema.fields):
                items = [r[i] for r in rows]
                items = [None if _is_nan(x) else x for x in items]
                cols.append(HostColumn.from_list(f.dtype, items))
            yield HostBatch(out_schema, cols)

        def gen(part):
            yield from run_single_input_task(ctx, task, part, in_schema,
                                             out_schema)

        return [gen(p) for p in self.children[0].partitions(ctx)]


def _is_nan(x) -> bool:
    try:
        return x is None or (isinstance(x, float) and x != x)
    except TypeError:
        return False


class CpuWindowInPandasExec(CpuExec):
    """Unbounded-frame pandas window (GpuWindowInPandasExec analogue):
    fn(group pd.Series) -> scalar, broadcast to every row of the
    partition; all input columns pass through."""

    def __init__(self, key_names: List[str], win_specs, child: PhysicalOp,
                 schema: T.Schema):
        super().__init__([child], schema)
        self.key_names = key_names
        self.win_specs = win_specs

    def describe(self):
        return f"CpuWindowInPandas(keys={self.key_names})"

    def partitions(self, ctx: ExecContext):
        in_schema = self.children[0].output_schema
        out_schema = self.output_schema
        key_names, win_specs = self.key_names, self.win_specs

        def task(frames):  # runs in the worker process
            batches = [hb for _i, hb in frames]
            if not batches:
                return
            pdf = _to_pandas(HostBatch.concat(batches))
            grouped = pdf.groupby(key_names, dropna=False, sort=False)
            for name, fn, _dt, col in win_specs:
                pdf[name] = grouped[col].transform(lambda s, fn=fn: fn(s))
            hb = pandas_to_host_batch(pdf, out_schema)
            if hb.num_rows:
                yield hb

        def gen(part):
            yield from run_single_input_task(ctx, task, part, in_schema,
                                             out_schema)

        return [gen(p) for p in self.children[0].partitions(ctx)]
