"""TPU physical operators: each one lowers its per-batch work to a jitted XLA
computation over the pytree :class:`ColumnBatch` (the analogue of the
reference's cudf-JNI calls inside ``doExecuteColumnar`` closures,
basicPhysicalOperators.scala:35-141, aggregate.scala:312, GpuSortExec.scala,
GpuHashJoin.scala).

jit granularity: one compiled program per (exec, schema, capacity-bucket).
Pipelines of Project/Filter ops fuse naturally because each exec's jit is
cheap to cache and XLA fuses elementwise chains into single kernels.
"""

from __future__ import annotations

import functools
from typing import Iterator, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import (
    ColumnBatch, DeviceColumn, HostBatch, empty_device_batch, host_to_device,
    round_up_capacity,
)
from spark_rapids_tpu.exprs.aggregates import AggregateExpression
from spark_rapids_tpu.exprs.base import DevVal, Expression, SortOrder, TpuEvalCtx
from spark_rapids_tpu.kernels.groupby import groupby_aggregate
from spark_rapids_tpu.kernels.join import cross_join, hash_join
from spark_rapids_tpu.kernels.layout import (
    compact, gather_rows, take_head,
)
from spark_rapids_tpu.kernels.sort import sort_batch
from spark_rapids_tpu.plan.physical import ExecContext, PhysicalOp, TpuExec
from spark_rapids_tpu.utils.compile_registry import instrumented_jit


def shrink_to_fit(batch: ColumnBatch,
                  sizes: Optional[tuple] = None) -> ColumnBatch:
    """Re-bucket a sparse batch down to its live-row count.

    The padded-capacity model means ops like filter/aggregate can leave
    batches with few live rows in huge buffers; every downstream kernel then
    pays O(capacity).  At pipeline breaks we pay one host sync + gather to
    move to the right power-of-two bucket — the CoalesceGoal/TargetSize
    analogue in reverse (GpuCoalesceBatches.scala).

    ``sizes`` is an optional pre-fetched (num_rows, [string byte totals])
    pair (see :func:`~spark_rapids_tpu.batch.host_sizes`) so callers
    shrinking many batches pay ONE round trip, not one per batch.
    """
    from spark_rapids_tpu.batch import host_sizes
    if sizes is None:
        sizes = host_sizes([batch])[0]
    n, str_totals = sizes
    cap = round_up_capacity(max(n, 1))
    if batch.capacity <= cap * 2:
        return batch
    byte_caps = [round_up_capacity(max(t, 16), minimum=16)
                 for t in str_totals]
    idx = jnp.arange(cap, dtype=jnp.int32)
    return gather_rows(batch, idx, jnp.asarray(n, jnp.int32),
                       out_capacity=cap, out_byte_caps=byte_caps or None)


# trailing pseudo-batch of the hash-agg pipeline stage: num_rows counts
# collided batches (compared by object identity)
_HASH_FLAGS_SCHEMA = T.Schema([("__hashagg_flags", T.INT)])


def _reserve_for(ctx, batches: List[ColumnBatch], factor: int = 2) -> None:
    """Budget headroom before a large concat/gather: ask the catalog to
    evict lower-priority spillable batches so input + output fit
    (SpillableColumnarBatch.scala:27 callers' reserve pattern)."""
    if not batches:
        return
    from spark_rapids_tpu.mem.catalog import device_batch_bytes
    from spark_rapids_tpu.runtime.device import DeviceRuntime
    total = sum(device_batch_bytes(b) for b in batches)
    DeviceRuntime.get(ctx.conf).catalog.reserve(factor * total)


def _release_build_staging(ctx: ExecContext, depth0: int) -> None:
    """Give back the H2D admission permits taken while materializing a
    catalog-registered build side.  Build batches park in the spill
    catalog instead of flowing on to DeviceToHostExec, so the release
    that normally pairs each staging acquire never happens — without
    this give-back the task-wide hold depth leaks for the process
    lifetime, silently shrinking device admission for every later
    query.  The pipeline collect counts H2D acquires in
    ``ctx._pipeline_h2d`` and releases that many in its finally, so the
    count is walked back by the same amount."""
    sem = ctx.semaphore
    if sem is None:
        return
    extra = max(0, sem.task_depth() - depth0)
    for _ in range(extra):
        sem.release()
    if extra and hasattr(ctx, "_pipeline_h2d"):
        ctx._pipeline_h2d = max(0, ctx._pipeline_h2d - extra)


def _concat_all(batches: List[ColumnBatch], schema: T.Schema,
                sizes: Optional[List[tuple]] = None
                ) -> Optional[ColumnBatch]:
    """Concatenate a partition's batches into one (RequireSingleBatch goal,
    GpuCoalesceBatches.scala:105-110).  Sizes the output by host-visible
    totals, fetched in ONE round trip for all batches (or passed in
    pre-fetched via ``sizes``); the k-way kernel then writes every input
    once into a single output allocation and the whole concat rides ONE
    compiled dispatch (the pairwise chain dispatched an eager op storm
    and materialized k-1 growing intermediates)."""
    if not batches:
        return None
    if len(batches) == 1:
        return batches[0]
    from spark_rapids_tpu.batch import colocate_batches, host_sizes
    from spark_rapids_tpu.kernels.layout import concat_kway_run
    batches = list(colocate_batches(batches))
    if sizes is None:
        sizes = host_sizes(batches)
    total_rows = sum(n for n, _ in sizes)
    cap = round_up_capacity(max(total_rows, 1))
    n_str = sum(1 for f in schema.fields
                if f.dtype.is_string or f.dtype.is_array)
    byte_caps = [
        round_up_capacity(max(sum(s[1][j] for s in sizes), 16), minimum=16)
        for j in range(n_str)
    ]
    return concat_kway_run(batches, cap, out_byte_caps=byte_caps or None)


class TpuRangeExec(TpuExec):
    """GpuRangeExec analogue: generates ids directly in HBM."""

    def __init__(self, start, end, step, num_parts, schema: T.Schema):
        super().__init__([], schema)
        self.start, self.end, self.step = start, end, step
        self._n = max(1, num_parts)

    def num_partitions(self, ctx):
        return self._n

    def partitions(self, ctx):
        total = max(0, -(-(self.end - self.start) // self.step))
        per = -(-total // self._n)
        max_batch = 1 << 20

        def gen(p):
            lo_i = self.start + p * per * self.step
            count = max(0, min(per, total - p * per))
            done = 0
            while done < count:
                n = min(max_batch, count - done)
                cap = round_up_capacity(n)
                start = lo_i + done * self.step
                data = start + jnp.arange(cap, dtype=jnp.int64) * self.step
                col = DeviceColumn(T.LONG, data,
                                   jnp.arange(cap, dtype=jnp.int32) < n, None)
                yield ColumnBatch(self.output_schema, [col],
                                  jnp.asarray(n, jnp.int32), cap)
                done += n

        return [gen(p) for p in range(self._n)]


class TpuProjectExec(TpuExec):
    def __init__(self, exprs: List[Expression], child: PhysicalOp,
                 schema: T.Schema):
        super().__init__([child], schema)
        self.exprs = exprs

        def run(batch: ColumnBatch) -> ColumnBatch:
            ctx = TpuEvalCtx(batch)
            cols = [e.tpu_eval(ctx).to_column() for e in self.exprs]
            return ColumnBatch(schema, cols, batch.num_rows, batch.capacity)

        self.batch_fn = run
        self._run = instrumented_jit(run, label="TpuProject")

    def describe(self):
        return f"TpuProject({', '.join(f.name for f in self.output_schema)})"

    def pipeline_inline(self, ctx, build):
        cf = build(self.children[0])
        return lambda args: [self.batch_fn(b) for b in cf(args)]

    def partitions(self, ctx):
        return [map(self._run, p)
                for p in self.children[0].partitions(ctx)]


class TpuFilterExec(TpuExec):
    def __init__(self, condition: Expression, child: PhysicalOp):
        super().__init__([child], child.output_schema)
        self.condition = condition

        def run(batch: ColumnBatch) -> ColumnBatch:
            ctx = TpuEvalCtx(batch)
            v = self.condition.tpu_eval(ctx)
            keep = v.validity & v.data.astype(jnp.bool_)
            return compact(batch, keep)

        self.batch_fn = run
        self._run = instrumented_jit(run, label="TpuFilter")

    def describe(self):
        return f"TpuFilter({self.condition!r})"

    def pipeline_inline(self, ctx, build):
        cf = build(self.children[0])
        return lambda args: [self.batch_fn(b) for b in cf(args)]

    def partitions(self, ctx):
        return [map(self._run, p)
                for p in self.children[0].partitions(ctx)]


class TpuUnionExec(TpuExec):
    def __init__(self, children: List[PhysicalOp], schema: T.Schema):
        super().__init__(children, schema)

    def num_partitions(self, ctx):
        return sum(c.num_partitions(ctx) for c in self.children)

    def pipeline_inline(self, ctx, build):
        cfs = [build(c) for c in self.children]

        def f(args):
            out = []
            for cf in cfs:
                for b in cf(args):
                    out.append(ColumnBatch(self.output_schema, b.columns,
                                           b.num_rows, b.capacity))
            return out

        return f

    def partitions(self, ctx):
        out = []
        for c in self.children:
            for p in c.partitions(ctx):
                out.append(self._rename(p))
        return out

    def _rename(self, part):
        for db in part:
            yield ColumnBatch(self.output_schema, db.columns, db.num_rows,
                              db.capacity)


class TpuCoalesceBatchesExec(TpuExec):
    """Concat small batches up to the target row goal
    (GpuCoalesceBatches.scala:115; the hot path for downstream op
    efficiency)."""

    def __init__(self, child: PhysicalOp, target_rows: int = 1 << 20):
        super().__init__([child], child.output_schema)
        self.target_rows = target_rows

    def pipeline_inline(self, ctx, build):
        # inside one compiled program batches are virtual — coalescing
        # is a no-op (consumers concat statically where they need to)
        return build(self.children[0])

    def partitions(self, ctx):
        def gen(part):
            pending: List[ColumnBatch] = []
            pending_rows = 0
            for db in part:
                n = db.host_num_rows()
                if n == 0:
                    continue
                if pending_rows + n > self.target_rows and pending:
                    out = _concat_all(pending, self.output_schema)
                    if out is not None:
                        yield out
                    pending, pending_rows = [], 0
                pending.append(db)
                pending_rows += n
            out = _concat_all(pending, self.output_schema)
            if out is not None:
                yield out

        return [gen(p) for p in self.children[0].partitions(ctx)]


# The adaptive planning logic itself (grouping rule, skew detection,
# stat accounting, legal broadcast sides) lives in plan/adaptive; these
# module-level aliases keep the historical import surface of this module
# stable (tests and tooling import the grouping rule from here).
from spark_rapids_tpu.plan import adaptive as _adaptive  # noqa: E402

_aqe_part_stats = _adaptive.part_stats
_aqe_target_rows = _adaptive.target_rows
_aqe_target_bytes = _adaptive.target_bytes
_aqe_target_for = _adaptive.target_for
_group_by_target = _adaptive.group_by_target
_coalesce_partition_lists = _adaptive.coalesce_partition_lists


def _aqe_enabled(ctx) -> bool:
    """Gate for the coalescing consumers (reader / agg merge / join pair
    grouping): the adaptive master switch AND the legacy coalesce conf."""
    return _adaptive.coalesce_enabled(ctx)


class TpuCoalescedShuffleReaderExec(TpuExec):
    """AQE-style post-shuffle partition coalescing as a general plan
    operator (GpuCustomShuffleReaderExec analogue): groups small
    post-exchange partitions so each downstream task covers a worthwhile
    row count.  The planner inserts it above exchanges feeding sort and
    window; the hash aggregate and shuffled join coalesce inline (they
    reuse the size fetch for output sizing)."""

    def __init__(self, child: PhysicalOp):
        super().__init__([child], child.output_schema)

    def describe(self):
        return "TpuCoalescedShuffleReader"

    def pipeline_inline(self, ctx, build):
        # inside one compiled program partitioning is virtual
        return build(self.children[0])

    def num_partitions(self, ctx):
        return self.children[0].num_partitions(ctx)

    def partitions(self, ctx):
        import itertools
        child = self.children[0]
        lazy_parts = child.partitions(ctx)
        if not _aqe_enabled(ctx) or len(lazy_parts) <= 1:
            return lazy_parts
        sizes, unit = _aqe_part_stats(child, len(lazy_parts))
        if sizes is not None:
            # spill-friendly path: sizes came with the shuffle (no unspill
            # just to count rows); chain the lazy generators per group.
            # Skewed partitions stay ALONE (their per-source pieces stream
            # through un-merged rather than dragging neighbors into one
            # giant downstream task).
            groups, _gflags = _adaptive.plan_groups(
                ctx, self.op_id, lazy_parts, sizes, unit)
            ctx.metric(self.op_id, "coalescedTo").add(len(groups))
            return [itertools.chain(*g) for g in groups]
        parts = [list(p) for p in lazy_parts]
        from spark_rapids_tpu.batch import host_sizes
        flat = [b for p in parts for b in p]
        if not flat:
            return [iter([])]
        flat_sizes = host_sizes(flat)
        by_id = {id(b): s[0] for b, s in zip(flat, flat_sizes)}
        sizes = [sum(by_id[id(b)] for b in p) for p in parts]
        groups = _coalesce_partition_lists(parts, sizes,
                                           _aqe_target_rows(ctx))
        ctx.metric(self.op_id, "coalescedTo").add(len(groups))
        return [iter(g) for g in groups]


class TpuFusedMapExec(TpuExec):
    """A chain of map-like stages (project/filter) compiled as ONE XLA
    program per batch.  Collapsing dispatch count matters doubly on TPU:
    host->device dispatch latency amortizes, and XLA fuses the whole chain
    into a single HBM pass (the role GpuCoalesceBatches + JIT fusion play
    for the reference's per-op cudf calls)."""

    def __init__(self, child: PhysicalOp, fns, schema: T.Schema,
                 labels: List[str]):
        super().__init__([child], schema)
        self.fns = list(fns)
        self.labels = labels

        def composed(batch: ColumnBatch) -> ColumnBatch:
            for f in self.fns:
                batch = f(batch)
            return batch

        self.batch_fn = composed
        self._run = instrumented_jit(composed, label="TpuFusedMap")

    def describe(self):
        return f"TpuFusedMap({' -> '.join(self.labels)})"

    def pipeline_inline(self, ctx, build):
        cf = build(self.children[0])
        return lambda args: [self.batch_fn(b) for b in cf(args)]

    def partitions(self, ctx):
        return [map(self._run, p)
                for p in self.children[0].partitions(ctx)]


class TpuLocalLimitExec(TpuExec):
    def __init__(self, n: int, child: PhysicalOp):
        super().__init__([child], child.output_schema)
        self.n = n

    def pipeline_inline(self, ctx, build):
        cf = build(self.children[0])

        def f(args):
            out = []
            left = jnp.asarray(self.n, jnp.int32)
            for b in cf(args):
                h = take_head(b, left)
                left = jnp.maximum(left - h.num_rows, 0)
                out.append(h)
            return out

        return f

    def partitions(self, ctx):
        def gen(part):
            left = self.n
            for db in part:
                if left <= 0:
                    break
                db = take_head(db, left)
                got = db.host_num_rows()
                left -= got
                if got:
                    yield db

        return [gen(p) for p in self.children[0].partitions(ctx)]


class TpuSortExec(TpuExec):
    """Whole-partition sort (cudf Table.orderBy analogue).  Requires a single
    batch, so it concats first — like the reference's RequireSingleBatch goal
    for global sorts (GpuSortExec.scala:50-98)."""

    def __init__(self, orders: List[SortOrder], key_exprs: List[Expression],
                 child: PhysicalOp, string_prefix_bytes: int = None):
        super().__init__([child], child.output_schema)
        self.orders = orders
        self.key_exprs = key_exprs
        self._input_fns = []
        if string_prefix_bytes is None:
            from spark_rapids_tpu.kernels.sort import \
                DEFAULT_STRING_PREFIX_BYTES
            string_prefix_bytes = DEFAULT_STRING_PREFIX_BYTES
        self.string_prefix_bytes = string_prefix_bytes

        def run(batch: ColumnBatch) -> ColumnBatch:
            for f in self._input_fns:
                batch = f(batch)
            ctx = TpuEvalCtx(batch)
            vals = [e.tpu_eval(ctx) for e in self.key_exprs]
            return sort_batch(batch, vals,
                              [o.ascending for o in self.orders],
                              [o.nulls_first for o in self.orders],
                              string_prefix_bytes=self.string_prefix_bytes)

        self._run = instrumented_jit(run, label="TpuSort")

    def absorb_input(self, fns):
        # project/filter commute with concat (row-wise / stable), so fused
        # stages run once on the merged batch
        self._input_fns = list(fns)

    def describe(self):
        return f"TpuSort({len(self.orders)} keys)"

    def pipeline_inline(self, ctx, build):
        from spark_rapids_tpu.plan.pipeline import concat_static
        cf = build(self.children[0])

        def f(args):
            batches = cf(args)
            if not batches:
                return []
            return [self._run(concat_static(batches, self.output_schema))]

        return f

    def partitions(self, ctx):
        def gen(part):
            batches = list(part)
            _reserve_for(ctx, batches)
            merged = _concat_all(batches, self.output_schema)
            if merged is not None:
                yield self._run(merged)

        return [gen(p) for p in self.children[0].partitions(ctx)]


def _buffer_schema(key_names: List[str], keys: List[Expression],
                   aggs: List[AggregateExpression]) -> T.Schema:
    fields = [T.Field(n, e.dtype, e.nullable)
              for n, e in zip(key_names, keys)]
    for i, a in enumerate(aggs):
        for j, spec in enumerate(a.fn.buffers()):
            fields.append(T.Field(f"__buf_{i}_{j}", spec.dtype, True))
    return T.Schema(fields)


class TpuHashAggregateExec(TpuExec):
    """Sort-based groupby aggregation, two-mode (update/merge) like the
    reference's Partial/Final plumbing (aggregate.scala:420-524).

    mode="update": raw rows -> per-partition partial batch
                   (group keys + agg buffers).
    mode="merge":  partial batches (post-exchange) -> merged groups ->
                   finalized output projection.
    """

    def __init__(self, mode: str, key_exprs: List[Expression],
                 key_names: List[str], aggs: List[AggregateExpression],
                 child: PhysicalOp, schema: T.Schema):
        assert mode in ("update", "merge")
        super().__init__([child], schema)
        self.mode = mode
        # partial outputs have far fewer live rows than capacity: end the
        # compiled stage here so the driver re-buckets before downstream
        # concats/sorts pay O(padded capacity)
        self.pipeline_stage_break = (mode == "update")
        self.key_exprs = key_exprs
        self.key_names = key_names
        self.aggs = aggs
        self.key_schema = T.Schema([
            T.Field(n, e.dtype, e.nullable)
            for n, e in zip(key_names, key_exprs)
        ])
        self.buffer_schemas = [[s.dtype for s in a.fn.buffers()]
                               for a in aggs]
        from spark_rapids_tpu.kernels.hashagg import hash_agg_capable
        self._hash_capable = hash_agg_capable(
            mode, [e.dtype for e in key_exprs], [a.fn for a in aggs])
        self._hash_disabled = False  # sticky off after a collided batch
        from spark_rapids_tpu.kernels.hashagg import TABLE_SLOTS
        self._mxu_table = TABLE_SLOTS  # refreshed from conf in _hash_active

        @instrumented_jit(label="TpuHashAggregate")
        def run(batch: ColumnBatch) -> ColumnBatch:
            return self._aggregate_batch(batch)

        @instrumented_jit(label="TpuHashAggregate:hash")
        def run_hash(batch: ColumnBatch):
            return self._aggregate_batch_hash(batch)

        self._run = run
        self._run_hash = run_hash
        # the merge input is always a fresh >1-way concat this exec built
        # (never a cached/spill-held batch) and is consumed here: donate
        # its buffers so concat + merge don't hold two full copies
        self._merge_run = instrumented_jit(self._merge_partials,
                                           label="TpuHashAggregate:merge")
        self._merge_run_donate = instrumented_jit(
            self._merge_partials, label="TpuHashAggregate:merge",
            donate_argnums=(0,))
        self._input_fns = []

    def absorb_input(self, fns):
        """Fuse upstream map-like stages (project/filter) into this exec's
        per-batch compiled program — one XLA dispatch instead of N
        (critical when dispatch latency is high; also lets XLA fuse
        elementwise work into the aggregation's sort pass)."""
        self._input_fns = list(fns)

        def run(batch: ColumnBatch) -> ColumnBatch:
            for f in self._input_fns:
                batch = f(batch)
            return self._aggregate_batch(batch)

        def run_hash(batch: ColumnBatch):
            for f in self._input_fns:
                batch = f(batch)
            return self._aggregate_batch_hash(batch)

        self._run = instrumented_jit(run, label="TpuHashAggregate")
        self._run_hash = instrumented_jit(run_hash,
                                          label="TpuHashAggregate:hash")

    def _hash_active(self, ctx) -> bool:
        from spark_rapids_tpu.config import (
            HASH_AGG_MXU_ENABLED, HASH_AGG_MXU_SLOTS,
        )
        if not (self._hash_capable and not self._hash_disabled and
                HASH_AGG_MXU_ENABLED.get(ctx.conf)):
            return False
        self._mxu_table = HASH_AGG_MXU_SLOTS.get(ctx.conf)
        return True

    def describe(self):
        return f"TpuHashAggregate({self.mode}, keys={len(self.key_exprs)})"

    def stage_variant(self, ctx) -> str:
        """Key for the pipeline stage cache: the update stage compiles a
        hash-path and a sort-path program (the latter built on demand when
        a collided batch forces the exact fallback)."""
        if self.mode == "update" and self._hash_active(ctx):
            return "hash"
        return "sort"

    def stage_may_rerun(self, ctx) -> bool:
        """The MXU update stage's epilogue may re-dispatch the exact sort
        variant on the SAME materialized inputs — the pipeline must not
        donate them (plan/pipeline._stage_may_rerun)."""
        return self.mode == "update" and self._hash_active(ctx)

    def pipeline_inline(self, ctx, build):
        from spark_rapids_tpu.plan.pipeline import concat_static
        cf = build(self.children[0])
        child_schema = self.children[0].output_schema
        use_hash = self.mode == "update" and self._hash_active(ctx)

        def f(args):
            batches = cf(args)
            for fn in self._input_fns:  # absorbed map stages
                batches = [fn(b) for b in batches]
            if self.mode == "update":
                # Emit per-batch partials as stage outputs: the stage break
                # re-buckets them to live size (one sizes sync), so the
                # downstream merge sorts a few thousand rows — merging here
                # would concat at FULL padded capacity and sort O(sum of
                # input caps) rows inside the program (seconds at 16M).
                if use_hash:
                    outs, ncoll = [], jnp.asarray(0, jnp.int32)
                    for b in batches:
                        p, fl = self._aggregate_batch_hash(b)
                        outs.append(p)
                        ncoll = ncoll + fl.astype(jnp.int32)
                    flag_col = DeviceColumn(T.INT,
                                            jnp.zeros(16, jnp.int32),
                                            jnp.ones(16, jnp.bool_))
                    outs.append(ColumnBatch(_HASH_FLAGS_SCHEMA,
                                            [flag_col], ncoll, 16))
                    return outs
                return [self._aggregate_batch(b) for b in batches]
            if not batches:
                if self.key_exprs:
                    return []
                merged = empty_device_batch(child_schema)
            else:
                merged = concat_static(batches, child_schema)
            return [self._aggregate_batch(merged)]

        return f

    def postprocess_stage_outputs(self, ctx, outs, rerun):
        """MXU-path stage epilogue: the trailing pseudo-batch's num_rows
        counts flagged batches (key range over the slot table, NaN/Inf
        float inputs).  Any flag discards the stage and re-runs the exact
        sort variant — correctness never depends on data shape."""
        if not outs or outs[-1].schema is not _HASH_FLAGS_SCHEMA:
            return outs
        # a mesh-sharded stage unshards one flags pseudo-batch PER
        # device (all trailing — the flags batch is the last program
        # output) — pop and sum every one of them
        flagged = 0
        while outs and outs[-1].schema is _HASH_FLAGS_SCHEMA:
            flagged += outs.pop().host_num_rows()
        if flagged:
            self._hash_disabled = True
            ctx.metric(self.op_id, "hashAggFallback").add(1)
            return rerun()
        ctx.metric(self.op_id, "mxuAggBatches").add(len(outs))
        return outs

    # -- core ---------------------------------------------------------------

    def _eval_keys(self, batch) -> List[DevVal]:
        if self.mode == "update":
            # String group keys stay dictionary-encoded when the scan
            # delivered them that way: the sort-based grouping only needs
            # lengths/hashes/prefixes, all of which gather through the
            # codes, so the dictionary is hashed once instead of per row.
            from spark_rapids_tpu.exprs.base import eval_maybe_encoded
            ctx = TpuEvalCtx(batch)
            return [eval_maybe_encoded(e, ctx) if e.dtype.is_string
                    else e.tpu_eval(ctx) for e in self.key_exprs]
        # merge mode: keys are the leading child columns by position
        return [DevVal.from_column(batch.columns[i])
                for i in range(len(self.key_exprs))]

    @staticmethod
    def _eval_agg_input(fn, ctx) -> DevVal:
        # Count consumes only validity, so a dictionary-encoded string
        # child stays encoded — no byte materialization just to count rows
        from spark_rapids_tpu.exprs.aggregates import Count
        from spark_rapids_tpu.exprs.base import eval_maybe_encoded
        if type(fn) is Count and fn.child.dtype.is_string:
            return eval_maybe_encoded(fn.child, ctx)
        return fn.child.tpu_eval(ctx)

    def _synth_key(self, batch) -> List[DevVal]:
        """Zero grouping keys (global reduction): constant key, one group."""
        cap = batch.capacity
        return [DevVal(T.INT, jnp.zeros(cap, dtype=jnp.int32),
                       jnp.ones(cap, dtype=jnp.bool_))]

    def _aggregate_batch(self, batch: ColumnBatch) -> ColumnBatch:
        keyless = not self.key_exprs
        key_vals = self._synth_key(batch) if keyless else \
            self._eval_keys(batch)
        key_schema = T.Schema([("__k", T.INT)]) if keyless else \
            self.key_schema

        if self.mode == "update":
            ctx = TpuEvalCtx(batch)
            agg_inputs = [self._eval_agg_input(a.fn, ctx)
                          for a in self.aggs]
            merge = False
        else:
            nk = len(self.key_exprs) if not keyless else 0
            agg_inputs = []
            i = nk
            for bufs in self.buffer_schemas:
                for _ in bufs:
                    agg_inputs.append(DevVal.from_column(batch.columns[i]))
                    i += 1
            merge = True

        group_keys, buffers = groupby_aggregate(
            batch, key_vals, agg_inputs, [a.fn for a in self.aggs], merge,
            key_schema, self.buffer_schemas, self.output_schema)

        num_groups = group_keys.num_rows
        if keyless:
            # A reduction always emits exactly one row; empty input yields
            # the identity buffers -> SQL defaults (count=0, sum=NULL...).
            num_groups = jnp.asarray(1, jnp.int32)
        cap = batch.capacity

        if self.mode == "update":
            cols = [] if keyless else list(group_keys.columns)
            for bufs in buffers:
                for b in bufs:
                    cols.append(DeviceColumn(b.dtype, b.data,
                                             b.validity, b.offsets))
            return ColumnBatch(self.output_schema, cols, num_groups, cap)

        # merge mode: finalize each agg into its output column
        cols = [] if keyless else list(group_keys.columns)
        for a, bufs in zip(self.aggs, buffers):
            v = a.fn.finalize(bufs)
            cols.append(DeviceColumn(v.dtype, v.data, v.validity, v.offsets))
        return ColumnBatch(self.output_schema, cols, num_groups, cap)

    def _aggregate_batch_hash(self, batch: ColumnBatch):
        """(partial batch, fallback flag) via the MXU slot kernel — same
        output layout as the sort-based update path.  flag=True means the
        result is INVALID (key range exceeded the slot table, or a float
        sum saw NaN/Inf) and the caller must re-run the sort path."""
        from spark_rapids_tpu.kernels.hashagg import hash_group_aggregate
        keyless = not self.key_exprs
        key_vals = self._synth_key(batch) if keyless else \
            self._eval_keys(batch)
        key_schema = T.Schema([("__k", T.INT)]) if keyless else \
            self.key_schema
        ctx = TpuEvalCtx(batch)
        agg_inputs = [self._eval_agg_input(a.fn, ctx) for a in self.aggs]
        group_keys, buffers, num_groups, collided = hash_group_aggregate(
            batch, key_vals, agg_inputs, [a.fn for a in self.aggs],
            key_schema, self.output_schema, table=self._mxu_table)
        if keyless:
            num_groups = jnp.asarray(1, jnp.int32)
        cols = [] if keyless else list(group_keys.columns)
        for bufs in buffers:
            for b in bufs:
                cols.append(DeviceColumn(b.dtype, b.data, b.validity,
                                         b.offsets))
        out = ColumnBatch(self.output_schema, cols, num_groups,
                          group_keys.capacity)
        return out, collided

    def partitions(self, ctx):
        child_schema = self.children[0].output_schema

        if self.mode == "merge":
            # Inputs are partial-buffer batches (post-exchange): concat the
            # whole partition FIRST, then merge+finalize once.  Re-merging
            # finalized outputs would be wrong (avg, first/last...).
            #
            # AQE-style partition coalescing (GpuCustomShuffleReaderExec
            # role): post-shuffle partitions are often tiny; group small
            # ones so one compiled merge covers a worthwhile row count and
            # downstream sees fewer partitions.
            import itertools

            from spark_rapids_tpu.batch import host_sizes
            child = self.children[0]
            lazy_parts = child.partitions(ctx)
            all_sizes: dict = {}
            if _aqe_enabled(ctx) and len(lazy_parts) > 1:
                sizes, unit = _aqe_part_stats(child, len(lazy_parts))
                if sizes is not None:
                    # spill-friendly: shuffle-known sizes, lazy chaining;
                    # skewed partitions stay un-merged (plan/adaptive)
                    groups, _gflags = _adaptive.plan_groups(
                        ctx, self.op_id, lazy_parts, sizes, unit)
                    parts = [itertools.chain(*g) for g in groups]
                else:
                    mats = [list(p) for p in lazy_parts]
                    # one round trip for every batch's sizes across ALL
                    # partitions (row counts + string byte totals), reused
                    # by the concat below
                    flat = [b for p in mats for b in p]
                    flat_sizes = host_sizes(flat) if flat else []
                    all_sizes = {id(b): s
                                 for b, s in zip(flat, flat_sizes)}
                    sizes = [sum(all_sizes[id(b)][0] for b in p)
                             for p in mats]
                    parts = _coalesce_partition_lists(
                        mats, sizes, _aqe_target_rows(ctx))
            else:
                parts = lazy_parts

            def gen(part):
                batches = list(part)
                pre = [all_sizes[id(b)] for b in batches] \
                    if batches and all(id(b) in all_sizes for b in batches) \
                    else None
                _reserve_for(ctx, batches)
                merged = _concat_all(batches, child_schema, sizes=pre)
                if merged is None:
                    if self.key_exprs:
                        return
                    # keyless reduction on empty input -> SQL default row
                    merged = empty_device_batch(child_schema)
                yield self._run(merged)

            return [gen(p) for p in parts]
        else:
            # update mode: aggregate each batch, then combine this
            # partition's partials: concat + buffer-merge (the reference's
            # concatenateBatches + merge-aggregate loop,
            # aggregate.scala:434-492).  Partials stay in their input-sized
            # buffers (no per-batch host sync); the downstream pipeline
            # break right-sizes them in one round trip.
            def gen(part):
                from spark_rapids_tpu.plan.pipeline import _donation_enabled
                batches = list(part)
                partials = self._update_partials(ctx, batches)
                if not partials:
                    return
                if len(partials) == 1:
                    yield partials[0]
                    return
                merged = _concat_all(partials, self.output_schema)
                run = self._merge_run_donate if _donation_enabled(ctx) \
                    else self._merge_run
                yield run(merged)

        return [gen(p) for p in self.children[0].partitions(ctx)]

    def _update_partials(self, ctx, batches):
        """Per-batch partials, preferring the MXU slot path; any flagged
        batch (key range over the slot table, or NaN/Inf float inputs —
        device-verified) re-runs on the exact sort path, and the MXU path
        turns off for this exec."""
        if not self._hash_active(ctx):
            return [self._run(db) for db in batches]
        pairs = [self._run_hash(db) for db in batches]
        flags = jax.device_get([f for _, f in pairs]) if pairs else []
        if not any(bool(f) for f in flags):
            ctx.metric(self.op_id, "mxuAggBatches").add(len(pairs))
            return [p for p, _ in pairs]
        self._hash_disabled = True
        ctx.metric(self.op_id, "hashAggFallback").add(1)
        return [self._run(db) for db in batches]

    def _merge_partials(self, merged: ColumnBatch) -> ColumnBatch:
        """Merge concatenated update-mode outputs back to one partial batch
        per partition (keys + buffers -> keys + buffers)."""
        keyless = not self.key_exprs
        key_vals = self._synth_key(merged) if keyless else [
            DevVal.from_column(merged.columns[i])
            for i in range(len(self.key_exprs))
        ]
        key_schema = T.Schema([("__k", T.INT)]) if keyless else \
            self.key_schema
        nk = 0 if keyless else len(self.key_exprs)
        agg_inputs = []
        i = nk
        for bufs in self.buffer_schemas:
            for _ in bufs:
                agg_inputs.append(DevVal.from_column(merged.columns[i]))
                i += 1
        group_keys, buffers = groupby_aggregate(
            merged, key_vals, agg_inputs, [a.fn for a in self.aggs], True,
            key_schema, self.buffer_schemas, self.output_schema)
        num_groups = group_keys.num_rows
        if keyless:
            num_groups = jnp.asarray(1, jnp.int32)
        cols = [] if keyless else list(group_keys.columns)
        for bufs in buffers:
            for b in bufs:
                cols.append(DeviceColumn(b.dtype, b.data, b.validity,
                                         b.offsets))
        return ColumnBatch(self.output_schema, cols, num_groups,
                           merged.capacity)


def _eval_join_keys(exprs, batch, dict_keys: bool):
    """Evaluate equi-join key expressions against one side's batch.

    With ``dict_keys`` (spark.rapids.sql.tpu.join.dictKeys.enabled),
    string keys that arrived dictionary-encoded from the scan/shuffle
    corridor stay encoded — join_pairs then hashes/compares int32 codes
    when both sides align (rendezvous-translating divergent dictionaries)
    and falls back to content hashing THROUGH the codes otherwise, both
    bit-identical to materialized keys.  Off: keys materialize here, so
    the kernel never sees codes."""
    from spark_rapids_tpu.exprs.base import eval_maybe_encoded
    ctx = TpuEvalCtx(batch)
    if dict_keys:
        return [eval_maybe_encoded(e, ctx) if e.dtype.is_string
                else e.tpu_eval(ctx) for e in exprs]
    return [e.tpu_eval(ctx) for e in exprs]


class TpuShuffledHashJoinExec(TpuExec):
    """Equi-join per co-partitioned pair (GpuShuffledHashJoinExec analogue).
    Residual conditions are applied as a post-join filter for inner joins
    (GpuHashJoin.scala:265-271); outer+condition falls back at planning."""

    def __init__(self, left: PhysicalOp, right: PhysicalOp,
                 left_keys: List[Expression], right_keys: List[Expression],
                 how: str, condition: Optional[Expression],
                 schema: T.Schema):
        super().__init__([left, right], schema)
        self.left_keys = left_keys
        self.right_keys = right_keys
        self.how = how
        self.condition = condition

    def describe(self):
        return f"TpuShuffledHashJoin({self.how})"

    def num_partitions(self, ctx):
        return self.children[0].num_partitions(ctx)

    _FUSABLE_HOWS = ("inner", "left", "right", "full", "left_semi",
                     "left_anti")

    def pipeline_inline(self, ctx, build):
        """Mesh-SPMD fusion: lower the join INTO the surrounding
        shard_map program.  Both input shuffles fuse as in-program
        all_to_alls over the same key hash, so each shard holds a
        co-partitioned (left, right) pair — every join type is correct
        per shard — and the per-shard join runs with STATIC bucketed
        output sizing (kernels.join.hash_join_static), no host sync for
        the pair total.  A traced overflow flag rides the program's
        outputs (parallel.mesh_spmd.note_overflow_flag); when the true
        output exceeded its bucket the stage transparently reruns
        host-driven.  Returns None (host path: AQE coalescing, skew
        splits, broadcast switch, residual conditions) unless both
        children are rule-matched mesh exchanges."""
        from spark_rapids_tpu.parallel.exchange import (
            TpuShuffleExchangeExec,
        )
        from spark_rapids_tpu.parallel.partitioning import (
            match_partition_rules,
        )
        from spark_rapids_tpu.plan.pipeline import (
            concat_static, mesh_build_scope,
        )
        scope = mesh_build_scope()
        if scope is None or self.condition is not None or \
                self.how not in self._FUSABLE_HOWS:
            return None
        # static pre-check BEFORE building any child: a child that would
        # not fuse must leave this op (not its subtree) the stage source
        for ch in self.children:
            if not (isinstance(ch, TpuShuffleExchangeExec) and
                    ch._mesh_active(ctx) and
                    match_partition_rules(
                        type(ch.partitioning).__name__) is not None):
                return None
        from spark_rapids_tpu.config import (
            JOIN_DICT_KEYS_ENABLED, MESH_SPMD_JOIN_GROWTH,
        )
        from spark_rapids_tpu.kernels.join import hash_join_static
        from spark_rapids_tpu.parallel.mesh_spmd import note_overflow_flag
        growth = MESH_SPMD_JOIN_GROWTH.get(ctx.conf)
        dict_keys = JOIN_DICT_KEYS_ENABLED.get(ctx.conf)
        lf = build(self.children[0])
        rf = build(self.children[1])
        lsch = self.children[0].output_schema
        rsch = self.children[1].output_schema
        scope.joins.append(self)

        def f(args):
            lb = concat_static(lf(args), lsch)
            rb = concat_static(rf(args), rsch)
            lkeys = _eval_join_keys(self.left_keys, lb, dict_keys)
            rkeys = _eval_join_keys(self.right_keys, rb, dict_keys)
            out, ovf = hash_join_static(lb, lkeys, rb, rkeys, self.how,
                                        self.output_schema, growth=growth)
            note_overflow_flag(ovf)
            return [out]

        return f

    def partitions(self, ctx):
        import itertools
        from spark_rapids_tpu.config import JOIN_DICT_KEYS_ENABLED
        self._dict_keys = JOIN_DICT_KEYS_ENABLED.get(ctx.conf)
        lchild, rchild = self.children
        if self.num_partitions(ctx) > 1:
            switched = self._try_broadcast_switch(ctx)
            if switched is not None:
                return switched
        lparts = lchild.partitions(ctx)
        rparts = rchild.partitions(ctx)
        assert len(lparts) == len(rparts)
        skew_flags = [False] * len(lparts)

        if _aqe_enabled(ctx) and len(lparts) > 1:
            # Pair coalescing (GpuCustomShuffleReaderExec role for joins):
            # group co-partitioned (left, right) pairs by COMBINED size so
            # both sides stay aligned; plan_groups keeps a skewed pair
            # ALONE and flags it for the per-piece chunked join below.
            lsz, lunit = _aqe_part_stats(lchild, len(lparts))
            rsz, runit = _aqe_part_stats(rchild, len(rparts))
            if lsz is not None and rsz is not None and lunit == runit:
                # spill-friendly: shuffle-known sizes, lazy chaining (each
                # group's pieces unspill only when that pair is joined)
                sizes = [a + b for a, b in zip(lsz, rsz)]
                unit = lunit
                record = True
            else:
                lparts = [list(p) for p in lparts]
                rparts = [list(p) for p in rparts]
                from spark_rapids_tpu.batch import host_sizes
                flat = [b for p in lparts + rparts for b in p]
                by_id = {id(b): s[0]
                         for b, s in zip(flat, host_sizes(flat))} \
                    if flat else {}
                sizes = [sum(by_id[id(b)] for b in lp) +
                         sum(by_id[id(b)] for b in rp)
                         for lp, rp in zip(lparts, rparts)]
                unit = "rows"
                record = False  # these sizes cost a fetch, not free stats
            # history-seeded skew marks recorded on either exchange by a
            # previous run (history.seeding) isolate known-hot
            # partitions before this run's stats would
            seed = getattr(lchild, "_history_skew", None)
            if seed is None:
                seed = getattr(rchild, "_history_skew", None)
            groups, skew_flags = _adaptive.plan_groups(
                ctx, self.op_id, list(zip(lparts, rparts)), sizes, unit,
                record=record, detect_skew=self.how != "full",
                seed_flags=seed)
            lparts = [itertools.chain(*(lp for lp, _ in g))
                      for g in groups]
            rparts = [itertools.chain(*(rp for _, rp in g))
                      for g in groups]

        def gen(lp, rp, skewed):
            lbs, rbs = list(lp), list(rp)
            _reserve_for(ctx, lbs + rbs)
            if skewed and self.how != "full":
                yield from self._join_skewed(ctx, lbs, rbs)
                return
            lb = _concat_all(lbs, self.children[0].output_schema)
            rb = _concat_all(rbs, self.children[1].output_schema)
            out = self._join_pair(lb, rb)
            if out is not None:
                yield out

        return [gen(lp, rp, sk)
                for lp, rp, sk in zip(lparts, rparts, skew_flags)]

    def _try_broadcast_switch(self, ctx):
        """Dynamic broadcast switch (AQE OptimizeShuffledHashJoin +
        GpuCustomShuffleReaderExec role): try each legal build side in
        preference order; the FIRST whose exchange materializes under
        spark.sql.autoBroadcastJoinThreshold actual bytes wins.  The
        already-split shuffle pieces become the broadcast build (no
        recompute), and when the probe side's exchange has not split yet
        its shuffle is ELIDED entirely (bypass_partitions): no pid
        programs, no piece gathers, no split host sync on that side.
        Returns the broadcast-shaped partition list, or None to keep the
        shuffled shape."""
        from spark_rapids_tpu.parallel.exchange import (
            TpuShuffleExchangeExec,
        )
        if not _adaptive.replan_joins_enabled(ctx):
            return None
        thr = _adaptive.broadcast_threshold(ctx)
        if thr < 0:
            return None
        lchild, rchild = self.children
        sides = _adaptive.broadcast_build_sides(self.how)
        hint = getattr(self, "_history_bc_side", None)
        if hint in sides:
            # history-seeded build side (history.seeding): try the side
            # that won last run first, so the switch materializes the
            # right exchange without probing the other side
            sides = [hint] + [s for s in sides if s != hint]
        for side in sides:
            build = rchild if side == "right" else lchild
            probe = lchild if side == "right" else rchild
            bparts = build.partitions(ctx)
            bbytes = getattr(build, "_last_part_bytes", None)
            if bbytes is None or len(bbytes) != len(bparts) or \
                    sum(bbytes) > thr:
                continue
            _adaptive.record_stats(ctx, self.op_id, bbytes, "bytes")
            if isinstance(probe, TpuShuffleExchangeExec) and \
                    not probe.has_materialized_split(ctx):
                sparts = probe.bypass_partitions(ctx)
            else:
                # the probe already split (it was tried as a build
                # candidate, or a shared subtree ran it): read its
                # spillable pieces rather than re-running the upstream
                sparts = probe.partitions(ctx)
            return self._broadcast_partitions(ctx, side, bparts, sparts)
        return None

    def _broadcast_partitions(self, ctx, side, build_parts, stream_parts):
        """Execute as a broadcast join: materialize the small side once,
        join every stream partition against it.  The build handle is
        cached per (ctx, device generation) — a device-lost reset bumps
        the generation, so a partition REPLAY rebuilds the broadcast from
        lineage instead of reading a handle whose device copy died with
        the old device (fault.recovery contract, like the exchange's
        split cache)."""
        import weakref

        from spark_rapids_tpu.runtime.device import DeviceRuntime
        build_schema = self.children[1 if side == "right" else 0] \
            .output_schema
        stream_schema = self.children[0 if side == "right" else 1] \
            .output_schema
        gen_now = DeviceRuntime.generation()
        cached = getattr(self, "_switch_cache", None)
        if cached is not None and cached[0]() is ctx and \
                cached[1] == gen_now and cached[2] == side:
            bh = cached[3]
        else:
            bbs = [b for p in build_parts for b in p]
            _reserve_for(ctx, bbs)
            bc = _concat_all(bbs, build_schema)
            bh = None
            if bc is not None:
                bh = DeviceRuntime.get(ctx.conf).catalog.register(bc)
                ctx.defer_close(bh)
                del bc
            self._switch_cache = (weakref.ref(ctx), gen_now, side, bh)
        ctx.metric(self.op_id, "replannedBroadcast").add(1)
        ctx.metric(self.op_id, "aqeBroadcastSwitches").add(1)
        _adaptive.note_event(ctx, self.op_id, "broadcast_switch")

        def gen(part):
            sbs = list(part)
            if not sbs:
                return
            _reserve_for(ctx, sbs)
            sb = _concat_all(sbs, stream_schema)
            b = bh.get() if bh is not None else None
            lb, rb = (sb, b) if side == "right" else (b, sb)
            out = self._join_pair(lb, rb)
            if out is not None:
                yield out

        return [gen(p) for p in stream_parts]

    def _join_skewed(self, ctx, lbs, rbs):
        """Skewed-group handling (AQE OptimizeSkewedJoin role): instead
        of one giant stream-side concat+join, the stream side is joined
        PER SOURCE PIECE — the pieces the shuffle split already produced
        (its non-coalesced path) — and any single piece whose bytes
        exceed the target is further cut into row-granularity chunks, so
        the join's pair-space allocation is bounded per dispatch even
        when the whole skewed partition arrived as one piece.  Stream
        rows belong to exactly one chunk, so outer null-padding of the
        stream side per chunk stays correct; 'full' tracks unmatched
        rows on BOTH sides and is never chunked (caller guards).  ONE
        host round trip yields every piece's rows + varlen totals."""
        from spark_rapids_tpu.batch import (
            fixed_row_bytes, host_sizes, varlen_byte_scales,
        )
        split_left = self.how != "right"
        stream = lbs if split_left else rbs
        build = rbs if split_left else lbs
        stream_schema = self.children[0 if split_left else 1].output_schema
        build_schema = self.children[1 if split_left else 0].output_schema
        build_b = _concat_all(build, build_schema)
        from spark_rapids_tpu.kernels.layout import row_slices
        frb = fixed_row_bytes(stream_schema)
        vscales = varlen_byte_scales(stream_schema)
        target = max(_aqe_target_bytes(ctx), 1)
        plan = []
        chunks = 0
        for piece, (rows, vtotals) in zip(
                stream, host_sizes(stream) if stream else []):
            if rows == 0:
                continue
            pbytes = rows * frb + \
                sum(t * s for t, s in zip(vtotals, vscales))
            n_chunks = max(1, min(rows, -(-pbytes // target)))
            rows_per = -(-rows // n_chunks)
            plan.append((piece, rows, rows_per))
            chunks += -(-rows // rows_per)
        ctx.metric(self.op_id, "skewSplitChunks").add(chunks)
        if not plan:
            # no live stream rows: only a build-only shape can produce
            # output (it cannot for the non-'full' hows chunked here)
            out = self._join_pair(
                *((None, build_b) if split_left else (build_b, None)))
            if out is not None:
                yield out
            return
        for piece, rows, rows_per in plan:
            for sb in row_slices(piece, rows, rows_per):
                lb, rb = (sb, build_b) if split_left else (build_b, sb)
                out = self._join_pair(lb, rb)
                if out is not None:
                    yield out

    def _join_pair(self, lb, rb) -> Optional[ColumnBatch]:
        lsch = self.children[0].output_schema
        rsch = self.children[1].output_schema
        if lb is None and self.how in ("inner", "left", "left_semi",
                                       "left_anti", "cross"):
            return None
        if lb is None:
            lb = empty_device_batch(lsch)
        if rb is None:
            if self.how in ("inner", "right", "cross", "left_semi"):
                if self.how in ("inner", "right", "cross"):
                    return None
                # left_semi with empty right = empty
                return None
            rb = empty_device_batch(rsch)
        dict_keys = getattr(self, "_dict_keys", False)
        lkeys = _eval_join_keys(self.left_keys, lb, dict_keys)
        rkeys = _eval_join_keys(self.right_keys, rb, dict_keys)
        # the residual condition runs INSIDE the join (it gates matches
        # before null-padding — GpuHashJoin.scala:265-271), so outer and
        # semi/anti joins with conditions are correct on device
        return hash_join(lb, lkeys, rb, rkeys, self.how, self.output_schema,
                         condition=self.condition)


class TpuNestedLoopJoinExec(TpuExec):
    """All-pairs join with optional condition, every join type; right side
    broadcast-materialized (GpuBroadcastNestedLoopJoinExec.scala:305 +
    GpuCartesianProductExec analogue)."""

    def __init__(self, left: PhysicalOp, right: PhysicalOp, how: str,
                 condition: Optional[Expression], schema: T.Schema):
        super().__init__([left, right], schema)
        self.how = how
        self.condition = condition

    def describe(self):
        return f"TpuNestedLoopJoin({self.how})"

    def num_partitions(self, ctx):
        if self.how in ("right", "full"):
            return 1
        return self.children[0].num_partitions(ctx)

    def partitions(self, ctx):
        from spark_rapids_tpu.config import NLJ_PAIR_CAPACITY
        from spark_rapids_tpu.kernels.join import (
            nested_loop_join, nested_loop_join_streamed,
        )
        from spark_rapids_tpu.kernels.layout import row_slices
        from spark_rapids_tpu.runtime.device import DeviceRuntime
        budget = max(NLJ_PAIR_CAPACITY.get(ctx.conf), 1)
        lsch = self.children[0].output_schema
        rsch = self.children[1].output_schema
        depth0 = ctx.semaphore.task_depth() if ctx.semaphore else 0
        rbatches = []
        for p in self.children[1].partitions(ctx):
            rbatches.extend(p)
        rb = _concat_all(rbatches, rsch)
        # The broadcast-materialized side lives in the spill catalog (the
        # reference registers broadcast tables with the buffer catalog) —
        # evictable under memory pressure, re-fetched per use.
        rh = None
        n_r = 0
        if rb is not None:
            n_r = rb.host_num_rows()
            catalog = DeviceRuntime.get(ctx.conf).catalog
            rh = catalog.register(rb)
            ctx.defer_close(rh)
            del rb
        _release_build_staging(ctx, depth0)

        def rb_local():
            return rh.get() if rh is not None else empty_device_batch(rsch)

        lparts = self.children[0].partitions(ctx)
        rows_per = max(1, budget // max(n_r, 1))

        if self.how in ("right", "full"):
            # right-unmatched rows are a property of the WHOLE left side:
            # stream left chunks against the full right, accumulating
            # right-matched flags; remainder emitted at the end
            def gen_all():
                lbatches = [b for p in lparts for b in p]
                _reserve_for(ctx, lbatches)
                lb = _concat_all(lbatches, lsch) or empty_device_batch(lsch)
                r = rb_local()
                n_l = lb.host_num_rows()
                if n_l * max(n_r, 1) <= budget:
                    yield nested_loop_join(lb, r, self.how, self.condition,
                                           self.output_schema)
                    return
                ctx.metric(self.op_id, "nljChunks").add(
                    -(-n_l // rows_per))
                yield from nested_loop_join_streamed(
                    row_slices(lb, n_l, rows_per),
                    empty_device_batch(lsch), r, self.how, self.condition,
                    self.output_schema)

            return [gen_all()]

        def gen(lp):
            for lb in lp:
                r = rb_local()
                n_l = lb.host_num_rows()
                if n_l * max(n_r, 1) <= budget:
                    yield nested_loop_join(lb, r, self.how, self.condition,
                                           self.output_schema)
                    continue
                # inner/left/semi/anti: each left row's outcome only needs
                # the FULL right side — chunking the left is exact
                ctx.metric(self.op_id, "nljChunks").add(
                    -(-n_l // rows_per))
                for chunk in row_slices(lb, n_l, rows_per):
                    yield nested_loop_join(chunk, r, self.how,
                                           self.condition,
                                           self.output_schema)

        return [gen(p) for p in lparts]


class TpuExpandExec(TpuExec):
    """Grouping-sets expansion via repeated projections
    (GpuExpandExec.scala)."""

    def __init__(self, projections: List[List[Expression]], child: PhysicalOp,
                 schema: T.Schema):
        super().__init__([child], schema)
        self.projections = projections
        self._runs = []
        for proj in projections:
            def make(proj=proj):
                @instrumented_jit(label="TpuExpand")
                def run(batch):
                    ctx = TpuEvalCtx(batch)
                    cols = [e.tpu_eval(ctx).to_column() for e in proj]
                    return ColumnBatch(schema, cols, batch.num_rows,
                                       batch.capacity)
                return run
            self._runs.append(make())

    def pipeline_inline(self, ctx, build):
        cf = build(self.children[0])
        return lambda args: [run(b) for b in cf(args)
                             for run in self._runs]

    def partitions(self, ctx):
        def gen(part):
            for db in part:
                for run in self._runs:
                    yield run(db)

        return [gen(p) for p in self.children[0].partitions(ctx)]


class TpuSampleExec(TpuExec):
    """Bernoulli sample.  Uses the same host RNG stream as the CPU exec so
    CPU-vs-TPU compare tests agree."""

    def __init__(self, fraction: float, seed: int, child: PhysicalOp):
        super().__init__([child], child.output_schema)
        self.fraction = fraction
        self.seed = seed

    def partitions(self, ctx):
        def gen(pi, part):
            rng = np.random.RandomState(self.seed + pi)
            for db in part:
                n = db.host_num_rows()
                keep_host = rng.rand(n) < self.fraction
                keep = jnp.zeros(db.capacity, dtype=jnp.bool_).at[:n].set(
                    jnp.asarray(keep_host))
                out = compact(db, keep)
                yield out

        return [gen(i, p)
                for i, p in enumerate(self.children[0].partitions(ctx))]


class TpuCachedScanExec(TpuExec):
    """Reads (and on first run populates) a CacheHolder of spillable device
    batches (df.cache() analogue — SURVEY.md section 5 checkpoint/resume:
    cached batches are evictable through the device->host->disk tiers)."""

    def __init__(self, holder, child: Optional[PhysicalOp],
                 schema: T.Schema):
        super().__init__([child] if child is not None else [], schema)
        self.holder = holder

    def describe(self):
        return "TpuCachedScan"

    def num_partitions(self, ctx):
        if self.holder.is_materialized:
            return len(self.holder.partitions)
        return self.children[0].num_partitions(ctx)

    def _materialize(self, ctx):
        from spark_rapids_tpu.runtime.device import DeviceRuntime
        catalog = DeviceRuntime.get(ctx.conf).catalog
        parts = []
        for p in self.children[0].partitions(ctx):
            handles = []
            for db in p:
                handles.append(catalog.register(shrink_to_fit(db)))
            parts.append(handles)
        self.holder.partitions = parts

    def partitions(self, ctx):
        if not self.holder.is_materialized:
            self._materialize(ctx)
        # overlapped unspill: under memory pressure the cached handles sit
        # on host/disk, and the drive loop keeps the next rehydration in
        # flight while the consumer computes on the current batch
        from spark_rapids_tpu.plan.physical import prefetch_spillables
        return [prefetch_spillables(p) for p in self.holder.partitions]


class TpuBroadcastHashJoinExec(TpuExec):
    """Hash join against a broadcast build side: the build side is
    materialized ONCE (all partitions concatenated on device) and every
    stream partition joins against it — no shuffle on either side
    (GpuBroadcastHashJoinExec analogue, shims/spark300).

    ``broadcast_side`` is "right" or "left".  Planner guarantees the join
    type is legal for the broadcast side (no broadcast of the outer side's
    opposite: right broadcast for inner/left/semi/anti, left broadcast for
    inner/right)."""

    def __init__(self, stream: PhysicalOp, broadcast: PhysicalOp,
                 left_keys: List[Expression], right_keys: List[Expression],
                 how: str, broadcast_side: str,
                 condition: Optional[Expression], schema: T.Schema):
        super().__init__([stream, broadcast], schema)
        self.left_keys = left_keys
        self.right_keys = right_keys
        self.how = how
        self.broadcast_side = broadcast_side
        self.condition = condition
        self._bc_cache = None  # (weakref(ctx), SpillableBatch | None)

    def describe(self):
        return f"TpuBroadcastHashJoin({self.how}, bc={self.broadcast_side})"

    def num_partitions(self, ctx):
        return self.children[0].num_partitions(ctx)

    # planner-legal broadcast combinations (unmatched BUILD rows are never
    # emitted, so a replicated build joined per shard stays exact)
    _FUSABLE_HOWS = {
        "right": ("inner", "left", "left_semi", "left_anti"),
        "left": ("inner", "right"),
    }

    def pipeline_inline(self, ctx, build):
        """Mesh-SPMD fusion: join per shard inside the fused shard_map
        program with the build side REPLICATED — its stage sources are
        recorded in ``scope.replicated`` so parallel.mesh_spmd feeds them
        as PartitionSpec-() globals (every shard sees the full build,
        like the host path's broadcast handle).  The planner-guaranteed
        build-side legality (class docstring) means no unmatched build
        row is ever emitted, so replaying the build on every shard never
        duplicates output rows.  Output sizing is static-bucketed
        (hash_join_static) with the same traced overflow -> host-rerun
        contract as the shuffled join.  Returns None when the build
        subtree contains an exchange (it would fuse as a collective and
        SHARD the build) or shares nodes with the stream subtree (shared
        sources cannot be both replicated and distributed)."""
        from spark_rapids_tpu.parallel.exchange import (
            TpuShuffleExchangeExec,
        )
        from spark_rapids_tpu.plan.pipeline import (
            concat_static, mesh_build_scope,
        )
        scope = mesh_build_scope()
        if scope is None or self.condition is not None or \
                self.how not in self._FUSABLE_HOWS.get(
                    self.broadcast_side, ()):
            return None

        bc_nodes = list(self._walk(self.children[1]))
        if any(isinstance(o, TpuShuffleExchangeExec) for o in bc_nodes):
            return None
        if {id(o) for o in bc_nodes} & \
                {id(o) for o in self._walk(self.children[0])}:
            return None
        from spark_rapids_tpu.config import (
            JOIN_DICT_KEYS_ENABLED, MESH_SPMD_JOIN_GROWTH,
        )
        from spark_rapids_tpu.kernels.join import hash_join_static
        from spark_rapids_tpu.parallel.mesh_spmd import note_overflow_flag
        growth = MESH_SPMD_JOIN_GROWTH.get(ctx.conf)
        dict_keys = JOIN_DICT_KEYS_ENABLED.get(ctx.conf)
        before = len(scope.sources)
        bf = build(self.children[1])
        scope.replicated.update(range(before, len(scope.sources)))
        sf = build(self.children[0])
        bc_schema = self.children[1].output_schema
        stream_schema = self.children[0].output_schema
        scope.joins.append(self)

        def f(args):
            sb = concat_static(sf(args), stream_schema)
            bc = concat_static(bf(args), bc_schema)
            lb, rb = (sb, bc) if self.broadcast_side == "right" \
                else (bc, sb)
            lkeys = _eval_join_keys(self.left_keys, lb, dict_keys)
            rkeys = _eval_join_keys(self.right_keys, rb, dict_keys)
            out, ovf = hash_join_static(lb, lkeys, rb, rkeys, self.how,
                                        self.output_schema, growth=growth)
            note_overflow_flag(ovf)
            return [out]

        return f

    @staticmethod
    def _walk(op):
        yield op
        for c in op.children:
            yield from TpuBroadcastHashJoinExec._walk(c)

    def _broadcast_handle(self, ctx):
        """Materialize the build side ONCE per query and register it with
        the spill catalog (the reference keeps broadcast build batches in
        the buffer catalog, spillable like everything else — an
        unregistered cached build side would be un-evictable HBM).  The
        handle is ctx-scoped (weakref, like the exchange's split cache)
        and defer-closed, so a finished query's build side leaves the
        catalog instead of pinning device budget and spill files."""
        import weakref
        cached = self._bc_cache
        if cached is not None and cached[0]() is ctx:
            return cached[1]
        depth0 = ctx.semaphore.task_depth() if ctx.semaphore else 0
        batches = []
        for p in self.children[1].partitions(ctx):
            batches.extend(p)
        bc = _concat_all(batches, self.children[1].output_schema)
        handle = None
        if bc is not None:
            from spark_rapids_tpu.runtime.device import DeviceRuntime
            catalog = DeviceRuntime.get(ctx.conf).catalog
            handle = catalog.register(bc)
            ctx.defer_close(handle)
        self._bc_cache = (weakref.ref(ctx), handle)
        _release_build_staging(ctx, depth0)
        return handle

    def partitions(self, ctx):
        from spark_rapids_tpu.config import JOIN_DICT_KEYS_ENABLED
        bh = self._broadcast_handle(ctx)
        bc_schema = self.children[1].output_schema
        stream_schema = self.children[0].output_schema
        dict_keys = JOIN_DICT_KEYS_ENABLED.get(ctx.conf)

        def gen(part):
            for sb in part:
                # re-fetch per stream batch: a spilled build side frees
                # real HBM between batches and unspills on demand
                bc_local = bh.get() if bh is not None else \
                    empty_device_batch(bc_schema)
                if self.broadcast_side == "right":
                    lb, rb = sb, bc_local
                else:
                    lb, rb = bc_local, sb
                lkeys = _eval_join_keys(self.left_keys, lb, dict_keys)
                rkeys = _eval_join_keys(self.right_keys, rb, dict_keys)
                yield hash_join(lb, lkeys, rb, rkeys, self.how,
                                self.output_schema,
                                condition=self.condition)

        return [gen(p) for p in self.children[0].partitions(ctx)]


class TpuGenerateExec(TpuExec):
    """explode/posexplode of a fixed-width-element array column
    (GpuGenerateExec analogue, GpuGenerateExec.scala): one flat-position →
    parent-row mapping (searchsorted over the array offsets) drives a
    whole-row gather of the kept columns; the element buffer IS the new
    column.  Output capacity = the array column's element capacity
    (static); live rows = total elements (device scalar — no host sync)."""

    def __init__(self, column: str, alias: str, pos: bool,
                 child: PhysicalOp, schema: T.Schema):
        super().__init__([child], schema)
        self.column = column
        self.alias = alias
        self.pos = pos

    def describe(self):
        kind = "posexplode" if self.pos else "explode"
        return f"TpuGenerate({kind}({self.column}))"

    def _explode_batch(self, batch: ColumnBatch) -> ColumnBatch:
        from spark_rapids_tpu.exprs.strings import rows_of_positions
        child_schema = batch.schema
        ci = child_schema.index_of(self.column)
        arr = batch.columns[ci]
        elem_cap = int(arr.data.shape[0])
        total = arr.offsets[batch.num_rows].astype(jnp.int32)
        live = jnp.arange(elem_cap, dtype=jnp.int32) < total
        parent = jnp.clip(rows_of_positions(arr.offsets, elem_cap),
                          0, batch.capacity - 1)
        kept = [i for i in range(len(child_schema)) if i != ci]
        kept_schema = T.Schema([child_schema.fields[i] for i in kept])
        kept_batch = ColumnBatch(kept_schema,
                                 [batch.columns[i] for i in kept],
                                 batch.num_rows, batch.capacity)
        # string columns can EXPAND (parent rows repeat); size on host
        bcaps = []
        for i in kept:
            c = batch.columns[i]
            if c.is_varlen:
                lens = (c.offsets[1:] - c.offsets[:-1]).astype(jnp.int64)
                tot = jnp.sum(jnp.where(live, lens[parent], 0))
                bcaps.append(round_up_capacity(
                    max(int(jax.device_get(tot)), 16), minimum=16))
        g = gather_rows(kept_batch, parent, total, out_capacity=elem_cap,
                        out_byte_caps=bcaps or None)
        cols = list(g.columns)
        if self.pos:
            pos_col = jnp.arange(elem_cap, dtype=jnp.int32) - \
                arr.offsets[parent]
            cols.append(DeviceColumn(
                T.INT, jnp.where(live, pos_col, 0), live, None))
        elem_valid = live & arr.validity[parent]
        cols.append(DeviceColumn(self.output_schema.fields[-1].dtype,
                                 jnp.where(live, arr.data, 0),
                                 elem_valid, None))
        return ColumnBatch(self.output_schema, cols, total, elem_cap)

    def partitions(self, ctx):
        def gen(part):
            for db in part:
                yield self._explode_batch(db)

        return [gen(p) for p in self.children[0].partitions(ctx)]
