"""Window exec: sort-once + segmented-scan window functions
(reference: GpuWindowExec.scala:99, GpuWindowExpression.scala:93-116; design
notes in exprs/windows.py)."""

from __future__ import annotations

import math
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import ColumnBatch, DeviceColumn, HostBatch
from spark_rapids_tpu.exprs.aggregates import (
    AggregateFunction, Average, Count, Max, Min, Sum,
)
from spark_rapids_tpu.exprs.base import (
    CpuEvalCtx, DevVal, Expression, SortOrder, TpuEvalCtx,
)
from spark_rapids_tpu.exprs.windows import (
    DenseRank, Lag, Lead, Rank, RowNumber, WindowExpression, WindowFrame,
)
from spark_rapids_tpu.kernels.groupby import _gather_str_val
from spark_rapids_tpu.kernels.layout import gather_rows
from spark_rapids_tpu.kernels.sort import argsort_batch
from spark_rapids_tpu.kernels.sortkeys import keys_equal_prev
from spark_rapids_tpu.ops.cpu_exec import _from_rows, _rows, sort_key_fn
from spark_rapids_tpu.ops.tpu_exec import _concat_all
from spark_rapids_tpu.plan.physical import CpuExec, PhysicalOp, TpuExec


# ---------------------------------------------------------------------------
# Device window math
# ---------------------------------------------------------------------------


def _prefix_incl(x):
    return jnp.cumsum(x)


def _range_sum(prefix, a, b):
    """sum x[a..b] inclusive from an inclusive prefix sum (0 when b < a)."""
    hi = prefix[jnp.clip(b, 0, prefix.shape[0] - 1)]
    lo = jnp.where(a > 0, prefix[jnp.clip(a - 1, 0, prefix.shape[0] - 1)], 0)
    return jnp.where(b >= a, hi - lo, 0)


def _range_minmax(x, a, b, is_min: bool):
    """Sliding min/max over [a,b] via a log-doubling sparse table."""
    cap = int(x.shape[0])
    levels = max(1, cap.bit_length())
    sp = [x]
    for j in range(1, levels):
        half = 1 << (j - 1)
        shifted = jnp.concatenate([sp[-1][half:],
                                   jnp.full(half, sp[-1][-1], x.dtype)])
        sp.append(jnp.minimum(sp[-1], shifted) if is_min
                  else jnp.maximum(sp[-1], shifted))
    table = jnp.stack(sp)  # [levels, cap]
    length = jnp.maximum(b - a + 1, 1)
    k = (jnp.ceil(jnp.log2(length.astype(jnp.float64) + 1e-9)) - 1)
    k = jnp.clip(k.astype(jnp.int32), 0, levels - 1)
    i1 = jnp.clip(a, 0, cap - 1)
    i2 = jnp.clip(b - (1 << k) + 1, 0, cap - 1)
    v1 = table[k, i1]
    v2 = table[k, i2]
    return jnp.minimum(v1, v2) if is_min else jnp.maximum(v1, v2)


class _Segments:
    """Row-position structure of the sorted batch."""

    def __init__(self, cap, live, seg_start, peers_change):
        pos = jnp.arange(cap, dtype=jnp.int32)
        self.pos = pos
        self.live = live
        self.seg_start_pos = jnp.maximum(
            jax.lax.cummax(jnp.where(seg_start, pos, -1)), 0)
        seg_ids = jnp.cumsum(seg_start.astype(jnp.int32)) - 1
        self.seg_ids = jnp.clip(seg_ids, 0, cap - 1)
        n_live = jnp.sum(live.astype(jnp.int32))
        seg_len = jax.ops.segment_sum(live.astype(jnp.int32), self.seg_ids,
                                      num_segments=cap, indices_are_sorted=True)
        self.seg_end_pos = self.seg_start_pos + \
            jnp.maximum(seg_len[self.seg_ids] - 1, 0)
        # peers: change = seg_start | order-key change
        change = seg_start | peers_change
        self.peer_start_pos = jnp.maximum(
            jax.lax.cummax(jnp.where(change, pos, -1)), 0)
        nxt = jnp.where(change, pos, cap)
        rev_min = jnp.flip(jax.lax.cummin(jnp.flip(nxt)))
        nxt_change = jnp.concatenate(
            [rev_min[1:], jnp.full(1, cap, jnp.int32)])
        self.peer_end_pos = jnp.minimum(
            nxt_change.astype(jnp.int32) - 1, self.seg_end_pos)
        self.change = change


def _search_boundary(keys, target, lo0, hi0, strict: bool):
    """Vectorized binary search: first position p in [lo0, hi0+1) with
    keys[p] > target (strict) or >= target; hi0+1 when none.  keys must
    ascend within each row's [lo0, hi0] span."""
    cap = int(keys.shape[0])
    lo = lo0.astype(jnp.int32)
    hi = (hi0 + 1).astype(jnp.int32)
    for _ in range(cap.bit_length() + 1):
        active = lo < hi
        mid = (lo + hi) // 2
        kv = keys[jnp.clip(mid, 0, cap - 1)]
        pred = (kv > target) if strict else (kv >= target)
        hi = jnp.where(active & pred, mid, hi)
        lo = jnp.where(active & ~pred, mid + 1, lo)
    return lo


def _bounded_range_bounds(frame: WindowFrame, segs: _Segments,
                          okey, ascending: bool, nulls_first: bool):
    """Value-based RANGE frame bounds: rows whose single numeric order
    key lies in [k+start, k+end].  NULL and NaN keys frame over their
    peer block (Spark: each is only a peer of its own kind); the search
    span excludes those contiguous blocks so the keys stay monotone.
    UNBOUNDED bounds reach the partition edge (null blocks included),
    matching Spark's partition-boundary semantics."""
    kd = okey.data
    # widen so k + offset cannot wrap in a narrow key dtype
    kd = kd.astype(jnp.int64) if okey.dtype.is_integral or         okey.dtype in (T.DATE, T.TIMESTAMP) else kd.astype(jnp.float64)
    if not ascending and kd.dtype == jnp.int64:
        # -INT64_MIN wraps; saturate one ulp first.  INT64_MIN and
        # INT64_MIN+1 become frame-peers at that one extreme
        # (docs/compatibility.md).
        imin = jnp.int64(jnp.iinfo(jnp.int64).min)
        kd = jnp.where(kd == imin, imin + 1, kd)
    keys = kd if ascending else -kd
    is_nan = jnp.isnan(keys) if okey.dtype.is_fractional else         jnp.zeros_like(okey.validity)
    finite = okey.validity & ~is_nan
    cap = segs.pos.shape[0]

    def seg_count(mask):
        return jax.ops.segment_sum(
            (mask & segs.live).astype(jnp.int32), segs.seg_ids,
            num_segments=cap, indices_are_sorted=True)[segs.seg_ids]

    nulls_in_seg = seg_count(~okey.validity)
    nans_in_seg = seg_count(is_nan)
    # nulls sit at the span edge given by nulls_first; NaN sorts past
    # every finite value (Spark), i.e. last ascending / first descending
    lo0 = segs.seg_start_pos + jnp.where(nulls_first, nulls_in_seg, 0)
    hi0 = segs.seg_end_pos - jnp.where(nulls_first, 0, nulls_in_seg)
    if ascending:
        hi0 = hi0 - nans_in_seg
    else:
        lo0 = lo0 + nans_in_seg
    k = keys

    def _target(off):
        # k + off with SATURATING int64 arithmetic: near INT64_MAX /
        # INT64_MIN a wrapped target flips the binary-search ordering
        # and produces empty frames (round-5 review finding).
        if k.dtype != jnp.int64:
            return k + off
        info = jnp.iinfo(jnp.int64)
        off = int(off)
        if off >= 0:
            return jnp.where(k > info.max - off, jnp.int64(info.max),
                             k + jnp.int64(off))
        return jnp.where(k < info.min - off, jnp.int64(info.min),
                         k + jnp.int64(off))

    if frame.start is None:
        a = segs.seg_start_pos  # partition edge, null/NaN blocks included
    else:
        a = _search_boundary(keys, _target(frame.start), lo0, hi0,
                             strict=False)
    if frame.end is None:
        b = segs.seg_end_pos
    else:
        b = _search_boundary(keys, _target(frame.end), lo0, hi0,
                             strict=True) - 1
    a = jnp.where(finite, a, segs.peer_start_pos)
    b = jnp.where(finite, b, segs.peer_end_pos)
    return a, b


def _frame_bounds(frame: WindowFrame, segs: _Segments, okeys=None,
                  order_by=None):
    """(a, b) inclusive row-position bounds of the frame per row."""
    if frame.is_unbounded_whole:
        return segs.seg_start_pos, segs.seg_end_pos
    if frame.kind == "range":
        if frame.is_running:
            return segs.seg_start_pos, segs.peer_end_pos
        # bounded value range: exactly one numeric order key (validated
        # by WindowExpression.tpu_supported)
        assert okeys is not None and len(okeys) == 1, \
            "bounded RANGE frame needs exactly one order key"
        o = order_by[0]
        return _bounded_range_bounds(frame, segs, okeys[0],
                                     o.ascending, o.nulls_first)
    a = segs.seg_start_pos if frame.start is None else \
        jnp.maximum(segs.pos + frame.start, segs.seg_start_pos)
    b = segs.seg_end_pos if frame.end is None else \
        jnp.minimum(segs.pos + frame.end, segs.seg_end_pos)
    return a, b


def _eval_window_fn(w: WindowExpression, segs: _Segments,
                    sorted_batch: ColumnBatch, ctx: TpuEvalCtx,
                    sorted_okeys=None) -> DevVal:
    fn = w.function
    cap = sorted_batch.capacity
    one = jnp.int32(1)
    if isinstance(fn, RowNumber):
        out = segs.pos - segs.seg_start_pos + one
        return DevVal(T.INT, out.astype(jnp.int32), segs.live)
    if isinstance(fn, Rank):
        out = segs.peer_start_pos - segs.seg_start_pos + one
        return DevVal(T.INT, out.astype(jnp.int32), segs.live)
    if isinstance(fn, DenseRank):
        c = jnp.cumsum(segs.change.astype(jnp.int32))
        out = c - c[segs.seg_start_pos] + one
        return DevVal(T.INT, out.astype(jnp.int32), segs.live)
    if isinstance(fn, Lag):
        off = fn.offset
        direction = -1 if not isinstance(fn, Lead) else 1
        target = segs.pos + direction * off
        in_seg = (target >= segs.seg_start_pos) & \
            (target <= segs.seg_end_pos)
        v = fn.children[0].tpu_eval(ctx)
        tgt = jnp.clip(target, 0, cap - 1)
        if v.dtype.is_string:
            g = _gather_str_val(v, tgt, cap)
            data, offsets = g.data, g.offsets
            validity = jnp.where(in_seg, g.validity, False)
            if len(fn.children) > 1:
                # literal default fill not supported for strings yet
                pass
            return DevVal(v.dtype, data, validity & segs.live, offsets)
        data = v.data[tgt]
        validity = jnp.where(in_seg, v.validity[tgt], False)
        if len(fn.children) > 1:
            d = fn.children[1].tpu_eval(ctx)
            data = jnp.where(in_seg, data, d.data)
            validity = jnp.where(in_seg, validity, d.validity)
        return DevVal(v.dtype, data, validity & segs.live)
    if isinstance(fn, AggregateFunction):
        v = fn.child.tpu_eval(ctx)
        a, b = _frame_bounds(w.frame, segs, sorted_okeys, w.order_by)
        valid = v.validity & segs.live
        cnt_prefix = _prefix_incl(valid.astype(jnp.int64))
        frame_cnt = _range_sum(cnt_prefix, a, b)
        if isinstance(fn, Count):
            return DevVal(T.LONG, frame_cnt.astype(jnp.int64), segs.live)
        if isinstance(fn, (Sum, Average)):
            acc_dt = jnp.float64 if (v.dtype.is_fractional or
                                     isinstance(fn, Average)) else jnp.int64
            x = jnp.where(valid, v.data, 0).astype(acc_dt)
            prefix = _prefix_incl(x)
            total = _range_sum(prefix, a, b)
            if isinstance(fn, Average):
                out = total.astype(jnp.float64) / \
                    jnp.maximum(frame_cnt, 1).astype(jnp.float64)
                return DevVal(T.DOUBLE, out,
                              (frame_cnt > 0) & segs.live)
            out_dt = fn.dtype.jnp_dtype
            return DevVal(fn.dtype, total.astype(out_dt),
                          (frame_cnt > 0) & segs.live)
        if isinstance(fn, (Min, Max)):
            is_min = isinstance(fn, Min)
            jdt = fn.dtype.jnp_dtype
            if fn.dtype.is_fractional:
                ident = jnp.asarray(jnp.inf if is_min else -jnp.inf, jdt)
            elif fn.dtype == T.BOOLEAN:
                ident = jnp.asarray(True if is_min else False)
            else:
                info = jnp.iinfo(jdt)
                ident = jnp.asarray(info.max if is_min else info.min, jdt)
            x = jnp.where(valid, v.data.astype(jdt), ident)
            out = _range_minmax(x, a, b, is_min)
            return DevVal(fn.dtype, out, (frame_cnt > 0) & segs.live)
    raise NotImplementedError(f"window fn {fn.name}")


class TpuWindowExec(TpuExec):
    def __init__(self, window_exprs: List[WindowExpression],
                 output_names: List[str], child: PhysicalOp,
                 schema: T.Schema):
        super().__init__([child], schema)
        self.window_exprs = window_exprs
        self.output_names = output_names
        w0 = window_exprs[0]
        self.part_keys = w0.partition_by
        self.order_by = w0.order_by
        for w in window_exprs[1:]:
            assert repr(w.partition_by) == repr(self.part_keys) and \
                repr(w.order_by) == repr(self.order_by), \
                "one Window exec handles one (partition, order) spec"

        from spark_rapids_tpu.utils.compile_registry import (
            instrumented_jit,
        )

        @instrumented_jit(label="TpuWindow")
        def run(batch: ColumnBatch) -> ColumnBatch:
            return self._compute(batch)

        self._run = run

    def describe(self):
        return f"TpuWindow({len(self.window_exprs)} exprs)"

    def _compute(self, batch: ColumnBatch) -> ColumnBatch:
        cap = batch.capacity
        ctx0 = TpuEvalCtx(batch)
        pkeys = [e.tpu_eval(ctx0) for e in self.part_keys]
        okeys = [o.child.tpu_eval(ctx0) for o in self.order_by]
        all_vals = pkeys + okeys
        ascs = [True] * len(pkeys) + [o.ascending for o in self.order_by]
        nfs = [True] * len(pkeys) + [o.nulls_first for o in self.order_by]
        if all_vals:
            groupings = [True] * len(pkeys) + [False] * len(okeys)
            perm = argsort_batch(all_vals, ascs, nfs, batch.num_rows,
                                 groupings=groupings)
        else:
            perm = jnp.arange(cap, dtype=jnp.int32)
        sorted_batch = gather_rows(batch, perm, batch.num_rows)
        live = jnp.arange(cap, dtype=jnp.int32) < batch.num_rows

        ctx = TpuEvalCtx(sorted_batch)
        sorted_pkeys = [e.tpu_eval(ctx) for e in self.part_keys]
        sorted_okeys = [o.child.tpu_eval(ctx) for o in self.order_by]
        if sorted_pkeys:
            seg_start = live & ~keys_equal_prev(sorted_pkeys)
        else:
            seg_start = live & (jnp.arange(cap, dtype=jnp.int32) == 0)
        if sorted_okeys:
            peers_change = live & ~keys_equal_prev(sorted_okeys)
        else:
            peers_change = jnp.zeros(cap, dtype=jnp.bool_)
        segs = _Segments(cap, live, seg_start, peers_change)

        cols = list(sorted_batch.columns)
        for w in self.window_exprs:
            v = _eval_window_fn(w, segs, sorted_batch, ctx, sorted_okeys)
            cols.append(DeviceColumn(v.dtype, v.data, v.validity, v.offsets))
        return ColumnBatch(self.output_schema, cols, batch.num_rows, cap)

    def partitions(self, ctx):
        def gen(part):
            merged = _concat_all(list(part), self.children[0].output_schema)
            if merged is not None:
                yield self._run(merged)

        return [gen(p) for p in self.children[0].partitions(ctx)]


class CpuWindowExec(CpuExec):
    """Python oracle with exact Spark window semantics."""

    def __init__(self, window_exprs: List[WindowExpression],
                 output_names: List[str], child: PhysicalOp,
                 schema: T.Schema):
        super().__init__([child], schema)
        self.window_exprs = window_exprs
        self.output_names = output_names

    def partitions(self, ctx):
        def gen(part):
            batches = list(part)
            if not batches:
                return
            hb = HostBatch.concat(batches)
            yield self._compute(hb)

        return [gen(p) for p in self.children[0].partitions(ctx)]

    def _compute(self, hb: HostBatch) -> HostBatch:
        w0 = self.window_exprs[0]
        cctx = CpuEvalCtx(hb)
        pvals = [e.cpu_eval(cctx).to_column().to_list()
                 for e in w0.partition_by]
        ovals = [o.child.cpu_eval(cctx).to_column().to_list()
                 for o in w0.order_by]
        n = hb.num_rows
        rows = _rows(hb)
        pkey = [tuple(c[i] for c in pvals) for i in range(n)] if pvals \
            else [()] * n
        okey = [tuple(c[i] for c in ovals) for i in range(n)] if ovals \
            else [()] * n
        keyf = sort_key_fn(
            [SortOrder(o.child, o.ascending, o.nulls_first)
             for o in w0.order_by], list(range(len(w0.order_by))))
        idx = sorted(range(n), key=lambda i: (
            _pkey_sort(pkey[i]), keyf(okey[i])))
        out_rows = []
        # group by partition key
        groups = {}
        for i in idx:
            groups.setdefault(pkey[i], []).append(i)
        hb_cols = [c.to_list() for c in hb.columns]
        for w, _name in zip(self.window_exprs, self.output_names):
            pass
        extra_cols = [[None] * n for _ in self.window_exprs]
        order_pos = {i: p for p, i in enumerate(idx)}
        for g in groups.values():
            for wi, w in enumerate(self.window_exprs):
                vals = self._eval_group(w, g, okey, hb)
                for j, i in enumerate(g):
                    extra_cols[wi][i] = vals[j]
        out = []
        for i in idx:
            out.append(tuple(c[i] for c in hb_cols) +
                       tuple(extra_cols[wi][i]
                             for wi in range(len(self.window_exprs))))
        return _from_rows(self.output_schema, out)

    def _eval_group(self, w: WindowExpression, g: List[int], okey,
                    hb: HostBatch):
        fn = w.function
        m = len(g)
        if isinstance(fn, RowNumber):
            return [j + 1 for j in range(m)]
        if isinstance(fn, Rank):
            out, last, r = [], None, 0
            for j in range(m):
                if okey[g[j]] != last:
                    r = j + 1
                    last = okey[g[j]]
                out.append(r)
            return out
        if isinstance(fn, DenseRank):
            out, last, r = [], object(), 0
            for j in range(m):
                if okey[g[j]] != last:
                    r += 1
                    last = okey[g[j]]
                out.append(r)
            return out
        cctx = CpuEvalCtx(hb)
        if isinstance(fn, Lag):
            v = fn.children[0].cpu_eval(cctx).to_column().to_list()
            d = fn.children[1].cpu_eval(cctx).to_column().to_list() \
                if len(fn.children) > 1 else None
            direction = 1 if isinstance(fn, Lead) else -1
            out = []
            for j in range(m):
                t = j + direction * fn.offset
                if 0 <= t < m:
                    out.append(v[g[t]])
                else:
                    out.append(d[g[j]] if d is not None else None)
            return out
        if isinstance(fn, AggregateFunction):
            v = fn.child.cpu_eval(cctx)
            vals, valid = v.values, v.validity
            out = []
            for j in range(m):
                a, b = self._bounds(w, j, m, g, okey)
                sel = [g[k] for k in range(a, b + 1)] if b >= a else []
                import numpy as np
                gv = np.array([vals[i] for i in sel]) if sel else \
                    np.zeros(0)
                gm = np.array([bool(valid[i]) for i in sel], dtype=bool) \
                    if sel else np.zeros(0, dtype=bool)
                out.append(fn.cpu_reduce(gv, gm))
            return out
        raise NotImplementedError(fn.name)

    def _bounds(self, w: WindowExpression, j: int, m: int, g, okey):
        frame = w.frame
        if frame.is_unbounded_whole:
            return 0, m - 1
        if frame.kind == "range":
            if frame.is_running:
                b = j
                while b + 1 < m and okey[g[b + 1]] == okey[g[j]]:
                    b += 1
                return 0, b
            # bounded value range over the single numeric order key
            if len(w.order_by) != 1:
                raise ValueError(
                    "a bounded RANGE frame requires exactly one "
                    "ORDER BY expression")
            o = w.order_by[0]
            kd = o.child.dtype
            if not kd.is_numeric and kd not in (T.DATE, T.TIMESTAMP):
                raise ValueError(
                    f"bounded RANGE frames need a numeric order key, "
                    f"got {kd}")
            sgn = 1 if o.ascending else -1
            # okey entries are tuples over all order keys; bounded range
            # has exactly one
            kv = [okey[g[i]][0] for i in range(m)]
            k = kv[j]
            def _is_nan(v):
                return v is not None and v != v

            if _is_nan(k):
                # NaN keys frame over their peer (NaN) block
                a = j
                while a - 1 >= 0 and _is_nan(kv[a - 1]):
                    a -= 1
                b = j
                while b + 1 < m and _is_nan(kv[b + 1]):
                    b += 1
                return a, b
            if k is None:
                # NULL keys frame over their peer (null) block
                a = j
                while a - 1 >= 0 and kv[a - 1] is None:
                    a -= 1
                b = j
                while b + 1 < m and kv[b + 1] is None:
                    b += 1
                return a, b
            lo_v = None if frame.start is None else k + sgn * frame.start
            hi_v = None if frame.end is None else k + sgn * frame.end

            def inside(v):
                if lo_v is not None and sgn * v < sgn * lo_v:
                    return False
                if hi_v is not None and sgn * v > sgn * hi_v:
                    return False
                return True

            def finite(v):
                return v is not None and v == v  # excludes NULL and NaN

            hits = [i for i in range(m) if finite(kv[i]) and inside(kv[i])]
            # UNBOUNDED bounds reach the partition edge (incl. the
            # null/NaN blocks), matching Spark
            a = 0 if frame.start is None else (hits[0] if hits else m)
            b = m - 1 if frame.end is None else (hits[-1] if hits else -1)
            return a, b
        a = 0 if frame.start is None else max(0, j + frame.start)
        b = m - 1 if frame.end is None else min(m - 1, j + frame.end)
        return a, b


def _pkey_sort(k: tuple):
    return tuple((v is None, str(v)) for v in k)
