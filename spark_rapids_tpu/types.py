"""Data type system for the TPU columnar engine.

Mirrors the v0.3 supported-type envelope of the reference
(GpuOverrides.scala:397-409): boolean, byte, short, int, long, float, double,
date, timestamp, string.  Each SQL type maps to a dense on-device
representation chosen for TPU/XLA friendliness:

  - integral/float types -> the matching jnp dtype
  - boolean              -> jnp.bool_
  - date                 -> int32 days since epoch
  - timestamp            -> int64 microseconds since epoch (UTC only, like the
                            reference: GpuOverrides.scala:309 timezone check)
  - string               -> offsets(int32[n+1]) + bytes(uint8[byte_cap]),
                            the cudf-style layout (SURVEY.md section 7)

Null handling: every device column carries a validity mask (bool, True=valid);
SQL NULL semantics are implemented in the expression kernels, not by sentinel
values.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax.numpy as jnp
import numpy as np


class DataType:
    """Base class for SQL data types."""

    #: jnp dtype of the primary data buffer on device.
    jnp_dtype: Any = None
    #: numpy dtype used by the host/CPU-oracle representation.
    np_dtype: Any = None

    @property
    def name(self) -> str:
        return type(self).__name__.replace("Type", "").lower()

    def __repr__(self) -> str:
        return self.name

    def __eq__(self, other: object) -> bool:
        return type(self) is type(other)

    def __hash__(self) -> int:
        return hash(type(self))

    @property
    def is_numeric(self) -> bool:
        return isinstance(self, (IntegralType, FractionalType))

    @property
    def is_integral(self) -> bool:
        return isinstance(self, IntegralType)

    @property
    def is_fractional(self) -> bool:
        return isinstance(self, FractionalType)

    @property
    def is_string(self) -> bool:
        return isinstance(self, StringType)

    @property
    def is_datetime(self) -> bool:
        return isinstance(self, (DateType, TimestampType))

    @property
    def is_array(self) -> bool:
        return False


class NumericType(DataType):
    pass


class IntegralType(NumericType):
    pass


class FractionalType(NumericType):
    pass


class BooleanType(DataType):
    jnp_dtype = jnp.bool_
    np_dtype = np.bool_


class ByteType(IntegralType):
    jnp_dtype = jnp.int8
    np_dtype = np.int8


class ShortType(IntegralType):
    jnp_dtype = jnp.int16
    np_dtype = np.int16


class IntegerType(IntegralType):
    jnp_dtype = jnp.int32
    np_dtype = np.int32


class LongType(IntegralType):
    jnp_dtype = jnp.int64
    np_dtype = np.int64


class FloatType(FractionalType):
    jnp_dtype = jnp.float32
    np_dtype = np.float32


class DoubleType(FractionalType):
    jnp_dtype = jnp.float64
    np_dtype = np.float64


class DateType(DataType):
    """Days since unix epoch, int32 (matches Spark's internal representation)."""

    jnp_dtype = jnp.int32
    np_dtype = np.int32


class TimestampType(DataType):
    """Microseconds since unix epoch, int64, UTC only."""

    jnp_dtype = jnp.int64
    np_dtype = np.int64


class StringType(DataType):
    """Variable-length UTF-8: offsets int32[n+1] + flat uint8 byte buffer."""

    jnp_dtype = jnp.uint8
    np_dtype = np.object_  # host oracle keeps python str / None


class NullType(DataType):
    """Type of an untyped NULL literal."""

    jnp_dtype = jnp.int32
    np_dtype = np.int32


class ArrayType(DataType):
    """array<element>: the start of the nested-type envelope
    (reference gates most nested types too — GpuOverrides.scala:397-409).

    Device layout mirrors strings (which are array<byte>): flat element
    buffer + offsets int32[n+1] + row validity.  v1 restrictions: elements
    are fixed-width (no array<string>/array<array>) and element-level
    NULLs are not represented (the reference's early versions gated the
    same).  Host oracle keeps python lists / None.
    """

    np_dtype = np.object_

    def __init__(self, element: DataType):
        assert element.jnp_dtype is not None and \
            not isinstance(element, ArrayType), \
            f"unsupported array element type: {element}"
        self.element = element
        # array<string> exists only on the host (CPU-engine results of
        # e.g. split()); device layout needs fixed-width elements
        self.jnp_dtype = None if element.is_string else element.jnp_dtype

    @property
    def name(self) -> str:
        return f"array<{self.element.name}>"

    def __eq__(self, other) -> bool:
        return isinstance(other, ArrayType) and self.element == other.element

    def __hash__(self) -> int:
        return hash((ArrayType, self.element))

    @property
    def is_array(self) -> bool:
        return True


# Singletons, Spark-style.
BOOLEAN = BooleanType()
BYTE = ByteType()
SHORT = ShortType()
INT = IntegerType()
LONG = LongType()
FLOAT = FloatType()
DOUBLE = DoubleType()
DATE = DateType()
TIMESTAMP = TimestampType()
STRING = StringType()
NULL = NullType()

ALL_TYPES = (BOOLEAN, BYTE, SHORT, INT, LONG, FLOAT, DOUBLE, DATE, TIMESTAMP, STRING)

_NAME_TO_TYPE = {t.name: t for t in ALL_TYPES}
_NAME_TO_TYPE.update({"int": INT, "bigint": LONG, "smallint": SHORT, "tinyint": BYTE})

# Numeric widening lattice for implicit binary-op promotion (Spark semantics).
_NUMERIC_ORDER = [BYTE, SHORT, INT, LONG, FLOAT, DOUBLE]


def type_from_name(name: str) -> DataType:
    return _NAME_TO_TYPE[name.lower()]


def promote(a: DataType, b: DataType) -> DataType:
    """Common type for a binary numeric operation (Spark's findTightestCommonType)."""
    if a == b:
        return a
    if isinstance(a, NullType):
        return b
    if isinstance(b, NullType):
        return a
    if a.is_numeric and b.is_numeric:
        ia, ib = _NUMERIC_ORDER.index(a), _NUMERIC_ORDER.index(b)
        # long + float -> double to avoid precision loss (Spark behavior is
        # float, but double is the safe superset; we follow Spark: wider wins).
        return _NUMERIC_ORDER[max(ia, ib)]
    # date/timestamp compare+arithmetic against their integral carriers
    # (date = int32 days, timestamp = int64 micros)
    if a.is_datetime or b.is_datetime:
        def norm(t: DataType) -> DataType:
            if isinstance(t, DateType):
                return INT
            if isinstance(t, TimestampType):
                return LONG
            return t
        na, nb = norm(a), norm(b)
        if na.is_numeric and nb.is_numeric:
            return promote(na, nb)
    raise TypeError(f"no common type for {a} and {b}")


def np_scalar(dt: DataType, value: Any):
    """Convert a python value to the numpy scalar for the host representation."""
    if value is None:
        return None
    if dt.is_string:
        return str(value)
    return dt.np_dtype(value)


@dataclasses.dataclass(frozen=True)
class Field:
    name: str
    dtype: DataType
    nullable: bool = True

    def __repr__(self) -> str:
        n = "" if self.nullable else " not null"
        return f"{self.name}: {self.dtype}{n}"


class Schema:
    """Ordered collection of named, typed fields."""

    def __init__(self, fields):
        self.fields: Tuple[Field, ...] = tuple(
            f if isinstance(f, Field) else Field(*f) for f in fields
        )
        self._index = {f.name: i for i, f in enumerate(self.fields)}
        if len(self._index) != len(self.fields):
            raise ValueError(f"duplicate column names in schema: {self.fields}")

    @property
    def names(self):
        return [f.name for f in self.fields]

    @property
    def types(self):
        return [f.dtype for f in self.fields]

    def index_of(self, name: str) -> int:
        return self._index[name]

    def field(self, name: str) -> Field:
        return self.fields[self._index[name]]

    def __contains__(self, name: str) -> bool:
        return name in self._index

    def __len__(self) -> int:
        return len(self.fields)

    def __iter__(self):
        return iter(self.fields)

    def __getitem__(self, i):
        if isinstance(i, str):
            return self.field(i)
        return self.fields[i]

    def __eq__(self, other) -> bool:
        return isinstance(other, Schema) and self.fields == other.fields

    def __hash__(self) -> int:
        return hash(self.fields)

    def __repr__(self) -> str:
        return "Schema(" + ", ".join(repr(f) for f in self.fields) + ")"
