"""ctypes bindings for the native host runtime (native/batch_runtime.cc).

Builds the shared library on first use (g++ -O3 -shared) and caches it next
to the source.  Every entry point has a pure-python fallback so the engine
works even where a toolchain is unavailable — but the native path is the
default, mirroring how the reference's host runtime is native
(SURVEY.md section 2.9).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(_HERE, "native", "batch_runtime.cc")
_SO = os.path.join(_HERE, "native", "libbatch_runtime.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> Optional[str]:
    if os.path.exists(_SO) and \
            os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return _SO
    try:
        subprocess.run(
            ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-o", _SO,
             _SRC],
            check=True, capture_output=True, timeout=120)
        return _SO
    except Exception:
        return None


def get_lib():
    """The loaded native library, or None (python fallback)."""
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        so = _build()
        if so is None:
            return None
        lib = ctypes.CDLL(so)
        u64 = ctypes.c_uint64
        p8 = ctypes.POINTER(ctypes.c_uint8)
        lib.batch_serialized_size.restype = u64
        lib.batch_serialize.restype = u64
        lib.batch_read_header.restype = ctypes.c_int32
        lib.batch_deserialize_index.restype = ctypes.c_int32
        lib.arena_create.restype = ctypes.c_void_p
        lib.arena_alloc.restype = ctypes.c_void_p
        lib.arena_alloc.argtypes = [ctypes.c_void_p, u64]
        lib.arena_free.argtypes = [ctypes.c_void_p, ctypes.c_void_p, u64]
        lib.arena_destroy.argtypes = [ctypes.c_void_p]
        lib.arena_stats.argtypes = [ctypes.c_void_p, ctypes.POINTER(u64),
                                    ctypes.POINTER(u64), ctypes.POINTER(u64)]
        lib.lz_compress_bound.restype = u64
        lib.lz_compress_bound.argtypes = [u64]
        lib.lz_compress.restype = u64
        lib.lz_compress.argtypes = [p8, u64, p8, u64]
        lib.lz_decompress.restype = ctypes.c_int32
        lib.lz_decompress.argtypes = [p8, u64, p8, u64]
        _lib = lib
        return _lib


# ---------------------------------------------------------------------------
# Batch (de)serialization — JCudfSerialization analogue
# ---------------------------------------------------------------------------

_TYPE_CODES = {}
_CODE_TYPES = {}


def _codes():
    if _TYPE_CODES:
        return
    from spark_rapids_tpu import types as T
    for i, t in enumerate(T.ALL_TYPES):
        _TYPE_CODES[t] = i
        _CODE_TYPES[i] = t


def _col_buffers(col) -> Tuple[np.ndarray, np.ndarray, Optional[np.ndarray]]:
    """HostColumn -> (data bytes, validity bytes, offsets bytes|None)."""
    from spark_rapids_tpu import types as T
    if col.dtype.is_string:
        encoded = [
            (str(v).encode("utf-8") if ok else b"")
            for v, ok in zip(col.values, col.validity)
        ]
        lens = np.fromiter((len(e) for e in encoded), dtype=np.int64,
                           count=len(encoded))
        offsets = np.zeros(len(encoded) + 1, dtype=np.int32)
        offsets[1:] = np.cumsum(lens)
        data = np.frombuffer(b"".join(encoded), dtype=np.uint8).copy()
        return data, col.validity.astype(np.uint8), offsets
    return (np.ascontiguousarray(col.values).view(np.uint8),
            col.validity.astype(np.uint8), None)


def serialize_host_batch(hb) -> bytes:
    """HostBatch -> one contiguous framed buffer (native when available)."""
    _codes()
    cols = [(f.dtype, *_col_buffers(c))
            for f, c in zip(hb.schema.fields, hb.columns)]
    lib = get_lib()
    n = len(cols)
    type_codes = np.array([_TYPE_CODES[c[0]] for c in cols], dtype=np.uint8)
    datas = [np.ascontiguousarray(c[1]).view(np.uint8) for c in cols]
    valids = [np.ascontiguousarray(c[2]) for c in cols]
    offs = [None if c[3] is None else
            np.ascontiguousarray(c[3]).view(np.uint8) for c in cols]
    data_lens = np.array([d.nbytes for d in datas], dtype=np.uint64)
    valid_lens = np.array([v.nbytes for v in valids], dtype=np.uint64)
    off_lens = np.array([0 if o is None else o.nbytes for o in offs],
                        dtype=np.uint64)
    if lib is None:
        return _py_serialize(hb.num_rows, type_codes, datas, valids, offs)
    u64a = data_lens.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))
    size = lib.batch_serialized_size(
        n, u64a,
        valid_lens.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        off_lens.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)))
    out = np.zeros(int(size), dtype=np.uint8)
    PP = ctypes.POINTER(ctypes.c_uint8) * n
    dp = PP(*[d.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
              for d in datas])
    vp = PP(*[v.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
              for v in valids])
    zero = np.zeros(1, dtype=np.uint8)
    op = PP(*[(o if o is not None else zero).ctypes.data_as(
        ctypes.POINTER(ctypes.c_uint8)) for o in offs])
    wrote = lib.batch_serialize(
        n, ctypes.c_uint64(hb.num_rows),
        type_codes.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        dp, data_lens.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        vp, valid_lens.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        op, off_lens.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.c_uint64(out.nbytes))
    assert wrote, "native serialization failed"
    return out[:int(wrote)].tobytes()


def deserialize_host_batch(buf: bytes, schema):
    """Framed buffer -> HostBatch (zero-copy views into the buffer)."""
    _codes()
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.batch import HostBatch, HostColumn
    lib = get_lib()
    arr = np.frombuffer(buf, dtype=np.uint8)
    if lib is None:
        return _py_deserialize(arr, schema)
    n_cols = ctypes.c_int32()
    n_rows = ctypes.c_uint64()
    ok = lib.batch_read_header(
        arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.c_uint64(arr.nbytes), ctypes.byref(n_cols),
        ctypes.byref(n_rows))
    assert ok, "bad batch frame"
    n = n_cols.value
    u64arr = lambda: np.zeros(n, dtype=np.uint64)  # noqa: E731
    tc = np.zeros(n, dtype=np.uint8)
    d_off, d_len = u64arr(), u64arr()
    v_off, v_len = u64arr(), u64arr()
    o_off, o_len = u64arr(), u64arr()
    P64 = ctypes.POINTER(ctypes.c_uint64)
    ok = lib.batch_deserialize_index(
        arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.c_uint64(arr.nbytes),
        tc.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        d_off.ctypes.data_as(P64), d_len.ctypes.data_as(P64),
        v_off.ctypes.data_as(P64), v_len.ctypes.data_as(P64),
        o_off.ctypes.data_as(P64), o_len.ctypes.data_as(P64))
    assert ok, "corrupt batch frame"
    rows = int(n_rows.value)
    cols = []
    for i, f in enumerate(schema.fields):
        validity = arr[int(v_off[i]):int(v_off[i]) + int(v_len[i])] \
            .astype(bool)
        if f.dtype.is_string:
            offsets = arr[int(o_off[i]):int(o_off[i]) + int(o_len[i])] \
                .view(np.int32)
            data = arr[int(d_off[i]):int(d_off[i]) + int(d_len[i])]
            values = np.empty(rows, dtype=object)
            raw = data.tobytes()
            for r in range(rows):
                values[r] = raw[offsets[r]:offsets[r + 1]].decode(
                    "utf-8", errors="replace")
            cols.append(HostColumn(f.dtype, values, validity))
        else:
            data = arr[int(d_off[i]):int(d_off[i]) + int(d_len[i])] \
                .view(f.dtype.np_dtype)
            cols.append(HostColumn(f.dtype, data.copy(), validity))
    return HostBatch(schema, cols)


def _py_serialize(n_rows, type_codes, datas, valids, offs) -> bytes:
    import struct
    out = [struct.pack("<IIIQ", 0x54505542, 1, len(datas), n_rows)]
    pos = 20

    def pad(b):
        nonlocal pos
        extra = (-pos) % 8
        out.append(b"\0" * extra)
        pos += extra

    for i in range(len(datas)):
        d = datas[i].tobytes()
        v = valids[i].tobytes()
        o = b"" if offs[i] is None else offs[i].tobytes()
        out.append(struct.pack("<BBQQQ", int(type_codes[i]),
                               1 if o else 0, len(d), len(v), len(o)))
        pos += 26
        pad(b"")
        for b in (d, v, o):
            if b or True:
                out.append(b)
                pos += len(b)
                pad(b"")
    return b"".join(out)


def _py_deserialize(arr, schema):
    # mirror of the native index walk
    import struct
    from spark_rapids_tpu.batch import HostBatch, HostColumn
    buf = arr.tobytes()
    magic, version, n, n_rows = struct.unpack_from("<IIIQ", buf, 0)
    assert magic == 0x54505542
    pos = 20
    cols = []
    for i, f in enumerate(schema.fields):
        t, has_o, dl, vl, ol = struct.unpack_from("<BBQQQ", buf, pos)
        pos += 26
        pos += (-pos) % 8
        d = buf[pos:pos + dl]
        pos += dl + ((-dl) % 8)
        v = np.frombuffer(buf[pos:pos + vl], dtype=np.uint8).astype(bool)
        pos += vl + ((-vl) % 8)
        if ol:
            o = np.frombuffer(buf[pos:pos + ol], dtype=np.int32)
            pos += ol + ((-ol) % 8)
            values = np.empty(n_rows, dtype=object)
            for r in range(n_rows):
                values[r] = d[o[r]:o[r + 1]].decode("utf-8",
                                                    errors="replace")
            cols.append(HostColumn(f.dtype, values, v))
        else:
            cols.append(HostColumn(
                f.dtype, np.frombuffer(d, dtype=f.dtype.np_dtype).copy(), v))
    return HostBatch(schema, cols)


# ---------------------------------------------------------------------------
# Host staging arena — PinnedMemoryPool analogue
# ---------------------------------------------------------------------------


class ArenaBuffer:
    """A host staging buffer leased from the arena."""

    __slots__ = ("array", "ptr", "size")

    def __init__(self, array: np.ndarray, ptr: int, size: int):
        self.array = array
        self.ptr = ptr
        self.size = size


class HostArena:
    """Aligned recycling host allocator (native; python fallback)."""

    def __init__(self, pool_limit_bytes: int = 1 << 30):
        self._lib = get_lib()
        if self._lib is not None:
            self._arena = self._lib.arena_create(
                ctypes.c_uint64(pool_limit_bytes))
        else:
            self._arena = None

    def alloc(self, size: int) -> ArenaBuffer:
        if self._arena:
            ptr = self._lib.arena_alloc(self._arena, ctypes.c_uint64(size))
            assert ptr, "arena OOM"
            buf = (ctypes.c_uint8 * size).from_address(ptr)
            return ArenaBuffer(np.frombuffer(buf, dtype=np.uint8), ptr, size)
        return ArenaBuffer(np.zeros(size, dtype=np.uint8), 0, size)

    def free(self, b: ArenaBuffer):
        if self._arena and b.ptr:
            self._lib.arena_free(self._arena, ctypes.c_void_p(b.ptr),
                                 ctypes.c_uint64(b.size))
            b.ptr = 0

    def stats(self):
        if not self._arena:
            return {"allocated": 0, "pooled": 0, "high_water": 0}
        a = ctypes.c_uint64()
        p = ctypes.c_uint64()
        h = ctypes.c_uint64()
        self._lib.arena_stats(self._arena, ctypes.byref(a), ctypes.byref(p),
                              ctypes.byref(h))
        return {"allocated": a.value, "pooled": p.value,
                "high_water": h.value}

    def close(self):
        if self._arena:
            self._lib.arena_destroy(self._arena)
            self._arena = None


def lz_compress(data: bytes) -> Optional[bytes]:
    """Native LZ4-style block compression; None when the library is
    unavailable or the emit bound is exceeded (caller stores raw)."""
    lib = get_lib()
    if lib is None:
        return None
    import ctypes
    n = len(data)
    bound = lib.lz_compress_bound(n)
    out = ctypes.create_string_buffer(bound)
    # zero-copy view of the immutable bytes (the C side only reads src)
    src = ctypes.cast(ctypes.c_char_p(data or b"\x00"),
                      ctypes.POINTER(ctypes.c_uint8))
    written = lib.lz_compress(
        src, n, ctypes.cast(out, ctypes.POINTER(ctypes.c_uint8)), bound)
    if written == 0 and n > 0:
        return None
    return out.raw[:written]


def lz_decompress(data: bytes, out_size: int) -> Optional[bytes]:
    lib = get_lib()
    if lib is None:
        return None
    import ctypes
    n = len(data)
    out = ctypes.create_string_buffer(max(out_size, 1))
    src = ctypes.cast(ctypes.c_char_p(data or b"\x00"),
                      ctypes.POINTER(ctypes.c_uint8))
    rc = lib.lz_decompress(
        src, n, ctypes.cast(out, ctypes.POINTER(ctypes.c_uint8)),
        out_size)
    if rc != 0:
        raise ValueError("corrupt nativelz stream")
    return out.raw[:out_size]
