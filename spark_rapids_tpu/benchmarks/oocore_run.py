"""Out-of-core proof at scale: TPC-H q1/q18 file-backed under a
deliberately tiny device spill budget, green, with spill metrics asserted
nonzero — the "data > HBM" demonstration of the 3-tier spill catalog
(SURVEY.md section 2.4; the reference's RapidsDeviceMemoryStore ->
RapidsHostMemoryStore -> RapidsDiskStore chain).

    python -m spark_rapids_tpu.benchmarks.oocore_run \
        [--sf 10] [--budget-mb 256] [--queries q1,q18] [--out BENCH_OOCORE.md]

The dataset is the sf1_run parquet generator at the requested scale
(SF10 lineitem = 60M rows).  The TPU-plan session runs with
``spark.rapids.memory.tpu.spillBudgetBytes`` forced far below the
working set, so the input cache + shuffle pieces MUST spill device->host
(->disk) for the queries to complete; results are checksum-verified
against an unconstrained CPU-engine run of the same files.
"""

from __future__ import annotations

import argparse
import json
import os
import time

from spark_rapids_tpu.benchmarks.sf1_run import (
    _checksum, generate_dataset,
)


def _session(tpu: bool, root: str, budget_bytes: int, extra_conf=None):
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.session import TpuSparkSession
    conf = {
        "spark.rapids.sql.enabled": tpu,
        "spark.sql.shuffle.partitions": 4,
        "spark.rapids.sql.variableFloatAgg.enabled": True,
    }
    if tpu:
        conf["spark.rapids.memory.tpu.spillBudgetBytes"] = budget_bytes
        conf.update(extra_conf or {})
    s = TpuSparkSession(RapidsConf(conf))
    for name in ("lineitem", "orders", "customer", "supplier", "nation",
                 "part", "partsupp", "region"):
        df = s.read.parquet(os.path.join(root, name))
        if tpu:
            # device-cache the inputs: at these scales the cache CANNOT
            # fit the budget, which is the point — the catalog must keep
            # the query alive by spilling
            df = df.cache()
        df.create_or_replace_temp_view(name)
    return s


def run(sf: float, budget_mb: int, queries, out_path: str,
        extra_conf=None) -> dict:
    """``extra_conf`` overlays the TPU session's conf — e.g.
    ``{"spark.rapids.sql.tpu.spill.async.enabled": False}`` to compare the
    async writer against the v1 synchronous spill on the same workload."""
    from spark_rapids_tpu.runtime.device import DeviceRuntime

    # DeviceRuntime is a process singleton: without a reset the catalog
    # keeps whatever spill budget the FIRST session of the process chose,
    # and the tiny budget below is silently ignored (no spills -> the
    # out-of-core assertion fails).  Reset before and after (the
    # tests/test_mem.py pattern) so the budget binds here and nothing
    # leaks into later sessions/tests.
    DeviceRuntime.reset()
    try:
        return _run_inner(sf, budget_mb, queries, out_path, extra_conf)
    finally:
        DeviceRuntime.reset()


def _run_inner(sf: float, budget_mb: int, queries, out_path: str,
               extra_conf=None) -> dict:
    from spark_rapids_tpu.benchmarks.tpch_like import QUERIES
    from spark_rapids_tpu.runtime.device import DeviceRuntime

    root = generate_dataset(sf)
    budget = budget_mb << 20
    # generate_dataset ran its own engine sessions, (re)claiming the
    # DeviceRuntime singleton with a default budget — reset AFTER it so
    # the tiny-budget session below actually constructs the catalog
    DeviceRuntime.reset()
    tpu = _session(True, root, budget, extra_conf)
    assert tpu.runtime.catalog.device_budget == budget, \
        "spill budget did not bind (stale DeviceRuntime singleton?)"
    cpu = _session(False, root, budget)
    results = {}
    for qname in queries:
        sql = QUERIES[qname]
        t0 = time.monotonic()
        t_rows = tpu.sql(sql).collect()
        t_s = time.monotonic() - t0
        mem = dict(tpu.runtime.catalog.metrics)
        t0 = time.monotonic()
        c_rows = cpu.sql(sql).collect()
        c_s = time.monotonic() - t0
        tc, cc = _checksum(t_rows), _checksum(c_rows)
        ok = tc[0] == cc[0] and len(tc[1]) == len(cc[1]) and all(
            abs(a - b) <= 1e-4 * max(1.0, abs(a), abs(b))
            for a, b in zip(tc[1], cc[1]))
        results[qname] = {
            "tpu_s": round(t_s, 2), "cpu_s": round(c_s, 2),
            "rows": tc[0], "agree": ok,
            "spilled_to_host": mem.get("spilled_to_host", 0),
            "spilled_to_disk": mem.get("spilled_to_disk", 0),
            "unspilled": mem.get("unspilled", 0),
        }
        print(f"{qname}: tpu {t_s:.1f}s cpu {c_s:.1f}s rows={tc[0]} "
              f"agree={ok} spills={mem}", flush=True)
        _write(sf, budget_mb, results, out_path)

    total_spills = sum(r["spilled_to_host"] + r["spilled_to_disk"]
                       for r in results.values())
    assert total_spills > 0, \
        f"budget {budget_mb}MB never forced a spill — not an " \
        f"out-of-core run: {results}"
    assert all(r["agree"] for r in results.values()), results
    return results


def _write(sf, budget_mb, results, out_path):
    lines = [
        f"# Out-of-core proof — TPC-H SF{sf:g}, "
        f"{budget_mb} MB device budget",
        "",
        f"lineitem = {int(sf * 6_000_000):,} rows; device spill budget "
        f"forced to {budget_mb} MB (working set is far larger), so the "
        "spill catalog must page batches device->host(->disk) for the "
        "queries to complete.  Checksums vs an unconstrained CPU-engine "
        "run.",
        "",
        "| query | tpu s | cpu s | rows | agree | spilled host/disk | "
        "unspilled |",
        "|---|---|---|---|---|---|---|",
    ]
    for q, r in sorted(results.items()):
        lines.append(
            f"| {q} | {r['tpu_s']} | {r['cpu_s']} | {r['rows']} | "
            f"{'yes' if r['agree'] else 'NO'} | "
            f"{r['spilled_to_host']}/{r['spilled_to_disk']} | "
            f"{r['unspilled']} |")
    lines.append("")
    with open(out_path, "w") as f:
        f.write("\n".join(lines))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--sf", type=float, default=10.0)
    ap.add_argument("--budget-mb", type=int, default=256)
    ap.add_argument("--queries", default="q1,q18")
    ap.add_argument("--out", default="BENCH_OOCORE.md")
    ap.add_argument("--sync-spill", action="store_true",
                    help="disable the async spill writer (v1 semantics)")
    a = ap.parse_args(argv)
    extra = {"spark.rapids.sql.tpu.spill.async.enabled": False} \
        if a.sync_spill else None
    res = run(a.sf, a.budget_mb, a.queries.split(","), a.out,
              extra_conf=extra)
    print(json.dumps({"sf": a.sf, "budget_mb": a.budget_mb,
                      "results": res}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
