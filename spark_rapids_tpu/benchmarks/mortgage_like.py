"""Mortgage-like ETL benchmark: the reference's third benchmark family
(integration_tests/.../mortgage/MortgageSpark.scala, mortgage_test.py) —
a loan-performance + acquisition pipeline rather than a star-schema query
set.  Rebuilt to the engine's API with the same stage shapes:

* file-driven entry (the Run.csv analogue lives in
  tests/test_mortgage_like.py: datagen written to CSV, read back through
  the engine's CSV scan; cf. ReadPerformanceCsv / ReadAcquisitionCsv,
  MortgageSpark.scala:35-119)
* date-string decomposition into year/month columns
* conditional delinquency flags + two-level groupby with min/max
  (CreatePerformanceDelinquency, MortgageSpark.scala:218-247)
* a 12-month explode over a literal array with floor/pmod bucket math
  (the "josh_mody" expansion, MortgageSpark.scala:269-297)
* broadcast name-mapping join normalizing messy seller strings
  (NameMapping, MortgageSpark.scala:120-215; CreateAcquisition coalesce)
* the CleanAcquisitionPrime inner join + a reporting aggregate
* the SimpleAggregates and AggregatesWithJoin query variants
  (MortgageSpark.scala:350-420)

Synthetic datagen, seeded; ``sf`` scales rows like the TPC-alike suites.
"""

from __future__ import annotations

import numpy as np

from spark_rapids_tpu import types as T

SELLERS_RAW = [
    "WELLS FARGO BANK, N.A.", "WELLS FARGO BANK, NA",
    "JPMORGAN CHASE BANK, NA", "JP MORGAN CHASE BANK, NA",
    "BANK OF AMERICA, N.A.", "QUICKEN LOANS INC.", "USAA FEDERAL BANK",
    "PENNYMAC CORP.", "FLAGSTAR BANK, FSB", "OTHER",
]
SELLER_MAP = [
    ("WELLS FARGO BANK, N.A.", "Wells Fargo"),
    ("WELLS FARGO BANK, NA", "Wells Fargo"),
    ("JPMORGAN CHASE BANK, NA", "JP Morgan Chase"),
    ("JP MORGAN CHASE BANK, NA", "JP Morgan Chase"),
    ("BANK OF AMERICA, N.A.", "Bank of America"),
    ("QUICKEN LOANS INC.", "Quicken Loans"),
    ("PENNYMAC CORP.", "PennyMac"),
    ("FLAGSTAR BANK, FSB", "Flagstar Bank"),
]
PURPOSES = ["P", "C", "R", "U"]
PROP_TYPES = ["SF", "CO", "CP", "MH", "PU"]
OCC = ["P", "S", "I"]
STATES = ["CA", "TX", "NY", "FL", "IL", "WA", "GA", "OH"]


def n_loans(sf: float) -> int:
    return max(20, int(sf * 5_000))


def gen_performance(sf: float, seed: int = 31):
    """Monthly loan-performance rows: ~24 months per loan."""
    loans = n_loans(sf)
    r = np.random.RandomState(seed)
    months_per = 24
    n = loans * months_per
    loan_id = np.repeat(np.arange(1, loans + 1), months_per)
    # months 2000-01 .. 2001-12
    seq = np.tile(np.arange(months_per), loans)
    year = 2000 + seq // 12
    month = seq % 12 + 1
    period = np.array([f"{y:04d}-{m:02d}-01" for y, m in zip(year, month)],
                      dtype=object)
    # delinquency bursts: mostly 0, occasionally escalating
    status = np.maximum(r.randint(-8, 10, n), 0).astype(np.int32)
    upb = (r.rand(n) * 300_000).round(2)
    upb[r.rand(n) < 0.02] = 0.0
    return {
        "loan_id": (T.LONG, loan_id),
        "monthly_reporting_period": (T.STRING, period),
        "servicer": (T.STRING, r.choice(SELLERS_RAW, n)),
        "interest_rate": (T.DOUBLE, (r.rand(n) * 5 + 2).round(3)),
        "current_actual_upb": (T.DOUBLE, upb),
        "loan_age": (T.DOUBLE, seq.astype(np.float64)),
        "current_loan_delinquency_status": (T.INT, status),
    }


def gen_acquisition(sf: float, seed: int = 32):
    loans = n_loans(sf)
    r = np.random.RandomState(seed)
    return {
        "loan_id": (T.LONG, np.arange(1, loans + 1)),
        "seller_name": (T.STRING, r.choice(SELLERS_RAW, loans)),
        "orig_interest_rate": (T.DOUBLE, (r.rand(loans) * 5 + 2).round(3)),
        "orig_upb": (T.INT, r.randint(50_000, 500_000, loans)
                     .astype(np.int32)),
        "orig_loan_term": (T.INT, r.choice([180, 240, 360], loans)
                           .astype(np.int32)),
        "orig_ltv": (T.DOUBLE, (r.rand(loans) * 60 + 30).round(1)),
        "dti": (T.DOUBLE, (r.rand(loans) * 40 + 5).round(1)),
        "borrower_credit_score": (T.DOUBLE, r.randint(450, 850, loans)
                                  .astype(np.float64)),
        "first_home_buyer": (T.STRING, r.choice(["Y", "N", "U"], loans)),
        "loan_purpose": (T.STRING, r.choice(PURPOSES, loans)),
        "property_type": (T.STRING, r.choice(PROP_TYPES, loans)),
        "occupancy_status": (T.STRING, r.choice(OCC, loans)),
        "property_state": (T.STRING, r.choice(STATES, loans)),
        "zip": (T.INT, r.randint(10_000, 99_999, loans).astype(np.int32)),
    }


def register_mortgage(session, sf: float = 0.1, num_partitions: int = 3):
    for name, data in (("perf_raw", gen_performance(sf)),
                       ("acq_raw", gen_acquisition(sf))):
        df = session.create_dataframe(data, num_partitions=num_partitions)
        session.register_view(name, df)


def _perf_prepared(perf):
    """Date decomposition (CreatePerformanceDelinquency.prepare, which
    runs to_date + year/month/dayofmonth over the period string)."""
    from spark_rapids_tpu import functions as F
    d = F.to_date(perf["monthly_reporting_period"])
    return (perf
            .with_column("timestamp_year", F.year(d))
            .with_column("timestamp_month", F.month(d)))


def delinquency_frame(perf):
    """Per-loan ever-30/90/180 flags (MortgageSpark.scala:232-260)."""
    from spark_rapids_tpu import functions as F
    month_idx = perf["timestamp_year"] * 12 + perf["timestamp_month"]
    status = perf["current_loan_delinquency_status"]
    flagged = (perf
               .with_column("month_idx", month_idx)
               .with_column("d30", F.when(status >= 1, month_idx)
                            .otherwise(None))
               .with_column("d90", F.when(status >= 3, month_idx)
                            .otherwise(None))
               .with_column("d180", F.when(status >= 6, month_idx)
                            .otherwise(None)))
    agg = (flagged.group_by("loan_id")
           .agg(F.max("current_loan_delinquency_status").alias("worst"),
                F.min("d30").alias("delinquency_30"),
                F.min("d90").alias("delinquency_90"),
                F.min("d180").alias("delinquency_180")))
    return (agg
            .with_column("ever_30", agg["worst"] >= 1)
            .with_column("ever_90", agg["worst"] >= 3)
            .with_column("ever_180", agg["worst"] >= 6)
            .drop("worst"))


def twelve_month_expansion(perf_joined):
    """Explode a 12-entry literal month array and re-bucket with
    floor/pmod month math (MortgageSpark.scala:269-297)."""
    from spark_rapids_tpu import functions as F
    df = perf_joined.with_column(
        "month_y", F.array(*[F.lit(i) for i in range(12)]))
    df = df.explode("month_y", alias="month_y")
    base = df["timestamp_year"] * 12 + df["timestamp_month"] - 24000
    df = df.with_column("bucket",
                        F.floor((base - df["month_y"]) / F.lit(12.0))
                        .cast(T.LONG))
    agg = (df.group_by("loan_id", "bucket", "month_y")
           .agg(F.max("current_loan_delinquency_status")
                .alias("delinquency_12"),
                F.min("current_actual_upb").alias("upb_12")))
    months_total = F.lit(24000) + agg["bucket"] * 12 + agg["month_y"]
    tmp = months_total % 12
    return (agg
            .with_column("timestamp_year",
                         F.floor((months_total + F.lit(-1)) / F.lit(12.0))
                         .cast(T.INT))
            .with_column("timestamp_month",
                         F.when(tmp == 0, 12).otherwise(tmp).cast(T.INT))
            .with_column("delinquency_12",
                         (agg["delinquency_12"] > 3).cast(T.INT)
                         + (agg["upb_12"] == 0).cast(T.INT))
            .drop("bucket", "month_y"))


def _seller_mapping(session):
    data = {
        "from_seller_name": (T.STRING,
                             np.array([a for a, _ in SELLER_MAP],
                                      dtype=object)),
        "to_seller_name": (T.STRING,
                           np.array([b for _, b in SELLER_MAP],
                                    dtype=object)),
    }
    return session.create_dataframe(data, num_partitions=1)


def clean_acquisition(session, acq):
    """Broadcast name normalization (CreateAcquisition,
    MortgageSpark.scala:300-315): left-join the mapping, coalesce to the
    original name when unmapped."""
    from spark_rapids_tpu import functions as F
    mapping = F.broadcast(_seller_mapping(session))
    acq = acq.join(mapping, on=acq["seller_name"]
                   == mapping["from_seller_name"], how="left")
    return (acq.with_column("seller",
                            F.coalesce(acq["to_seller_name"],
                                       acq["seller_name"]))
            .drop("from_seller_name", "to_seller_name", "seller_name"))


def run_mortgage(session):
    """Full ETL (the reference's Run.csv/parquet pipeline,
    MortgageSpark.scala:325-347): delinquency expansion joined back to
    performance, inner-joined to the cleaned acquisition frame, reduced
    to a deterministic reporting aggregate.  Consumes the registered
    ``perf_raw``/``acq_raw`` views (see :func:`register_mortgage`)."""
    perf = _perf_prepared(session.table("perf_raw"))
    delinq = delinquency_frame(perf)
    joined = perf.join(delinq, on="loan_id", how="left")
    twelve = twelve_month_expansion(joined)
    perf_final = perf.join(
        twelve, on=["loan_id", "timestamp_year", "timestamp_month"],
        how="left")
    acq = clean_acquisition(session, session.table("acq_raw"))
    full = perf_final.join(acq, on="loan_id", how="inner")
    from spark_rapids_tpu import functions as F
    out = (full.group_by("property_state", "seller")
           .agg(F.count("loan_id").alias("rows_n"),
                F.sum("delinquency_12").alias("delinq_12_sum"),
                F.avg("interest_rate").alias("avg_rate"),
                F.avg("borrower_credit_score").alias("avg_score"),
                F.max("current_actual_upb").alias("max_upb"))
           .order_by("property_state", "seller"))
    return out


def simple_aggregates(session):
    """SimpleAggregates (MortgageSpark.scala:350-366): per-loan monthly
    max rate, joined to acquisition, min-of-max by (zip, month)."""
    from spark_rapids_tpu import functions as F
    perf = _perf_prepared(session.table("perf_raw"))
    max_rate = (perf.group_by("timestamp_month", "loan_id")
                .agg(F.max("interest_rate").alias("max_monthly_rate")))
    acq = session.table("acq_raw")
    joined = max_rate.join(acq, on="loan_id", how="inner")
    return (joined.group_by("zip", "timestamp_month")
            .agg(F.min("max_monthly_rate").alias("min_max_monthly_rate"))
            .order_by("zip", "timestamp_month"))


def aggregates_with_join(session):
    """AggregatesWithJoin (MortgageSpark.scala:393-420): anonymize the
    loan key through the engine's murmur3 hash, pre-aggregate each side,
    left join the aggregates."""
    from spark_rapids_tpu import functions as F
    perf = session.table("perf_raw")
    acq = session.table("acq_raw")
    perf_a = (perf.with_column("loan_id_hash", F.hash(perf["loan_id"]))
              .group_by("loan_id_hash")
              .agg(F.min("interest_rate").alias("min_int_rate")))
    acq_a = (acq.with_column("loan_id_hash", F.hash(acq["loan_id"]))
             .group_by("loan_id_hash")
             .agg(F.first("orig_interest_rate", ignore_nulls=True)
                  .alias("first_int_rate"),
                  F.max("dti").alias("max_dti")))
    out = perf_a.join(acq_a, on="loan_id_hash", how="left")
    return (out.with_column("max_dti",
                            F.coalesce(out["max_dti"], F.lit(0.0)))
            .order_by("loan_id_hash"))


def aggregates_with_percentiles(session):
    """AggregatesWithPercentiles (MortgageSpark.scala:368-390): per
    anonymized loan, min/max/avg plus the 50/75/90/99th exact
    percentiles of the monthly interest rate.  The reference wraps each
    output in round(x, 4); the "like" adaptation compares raw doubles
    (fixed-decimal rounding sits one emulation ULP from a tie)."""
    from spark_rapids_tpu import functions as F
    perf = session.table("perf_raw")
    anon = perf.with_column("loan_id_hash", F.hash(perf["loan_id"]))
    return (anon.group_by("loan_id_hash")
            .agg(F.min("interest_rate").alias("interest_rate_min"),
                 F.max("interest_rate").alias("interest_rate_max"),
                 F.avg("interest_rate").alias("interest_rate_avg"),
                 F.percentile("interest_rate", 0.5)
                 .alias("interest_rate_50p"),
                 F.percentile("interest_rate", 0.75)
                 .alias("interest_rate_75p"),
                 F.percentile("interest_rate", 0.9)
                 .alias("interest_rate_90p"),
                 F.percentile("interest_rate", 0.99)
                 .alias("interest_rate_99p"))
            .order_by("loan_id_hash"))
