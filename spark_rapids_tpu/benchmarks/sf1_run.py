"""File-backed TPC-H SF1 run: generate parquet tables once (lineitem = 6M
rows — true TPC-H SF1 row counts), run every TPC-H-like query on the TPU
engine AND the CPU engine from the files, verify agreement, and emit a
timing table (the BenchUtils.runBench role,
integration_tests/.../common/BenchUtils.scala:109-240).

    python -m spark_rapids_tpu.benchmarks.sf1_run [--sf 1.0] [--out BENCH_SF1.md]

Correctness: row counts must match exactly; numeric columns are
checksummed (sums rounded to 2dp) and compared within float-agg
tolerance.  The parquet dataset is cached under /tmp keyed by scale.
"""

from __future__ import annotations

import argparse
import json
import os
import time

# TPC-H SF1 row counts; the synthetic generator's own `sf` knob is
# rows = sf * 60_000 for lineitem, so generator_sf = 100 * true_sf
_GEN_PER_TRUE_SF = 100


def _dataset_dir(true_sf: float) -> str:
    import tempfile
    return os.path.join(tempfile.gettempdir(),
                        f"rapids_tpu_tpch_sf{true_sf:g}")


def generate_dataset(true_sf: float, num_partitions: int = 4) -> str:
    """Write the TPC-H-like tables as parquet once; returns the dir.
    The completion marker records a schema fingerprint, so a schema or
    scale change regenerates instead of reusing stale files (a pure
    value-distribution change with the same columns still needs a manual
    directory wipe)."""
    from spark_rapids_tpu.benchmarks import datagen
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.session import TpuSparkSession

    root = _dataset_dir(true_sf)
    marker = os.path.join(root, "_COMPLETE")
    gen_sf = true_sf * _GEN_PER_TRUE_SF
    tables = [
        ("lineitem", datagen.gen_lineitem),
        ("orders", datagen.gen_orders),
        ("customer", datagen.gen_customer),
        ("supplier", datagen.gen_supplier),
        ("nation", lambda _sf: datagen.gen_nation()),
        ("part", datagen.gen_part),
        ("partsupp", datagen.gen_partsupp),
        ("region", lambda _sf: datagen.gen_region()),
    ]
    # cheap fingerprint: every table's column names + dtypes (from a
    # tiny-scale probe of the same generators) + the scale
    cols = {n: sorted((k, str(dt)) for k, (dt, _) in g(0.001).items())
            for n, g in tables}
    fingerprint = json.dumps({"cols": cols, "gen_sf": gen_sf},
                             sort_keys=True)
    if os.path.exists(marker) and open(marker).read() == fingerprint:
        return root
    s = TpuSparkSession(RapidsConf({"spark.rapids.sql.enabled": False}))
    for name, gen in tables:
        df = s.create_dataframe(gen(gen_sf),
                                num_partitions=num_partitions)
        df.write_parquet(os.path.join(root, name), mode="overwrite")
        print(f"wrote {name}", flush=True)
    open(marker, "w").write(fingerprint)
    return root


def _session(tpu: bool, root: str):
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.session import TpuSparkSession
    s = TpuSparkSession(RapidsConf({
        "spark.rapids.sql.enabled": tpu,
        "spark.sql.shuffle.partitions": 4,
        "spark.rapids.sql.variableFloatAgg.enabled": True,
    }))
    for name in ("lineitem", "orders", "customer", "supplier", "nation",
                 "part", "partsupp", "region"):
        df = s.read.parquet(os.path.join(root, name))
        # BOTH engines cache inputs after the first read so the timing
        # table compares engine steady-state, not cache-vs-reread
        df = df.cache()
        df.create_or_replace_temp_view(name)
    return s


def _checksum(rows):
    """(row count, rounded numeric sums) — agreement proxy for large
    results where a full row-by-row compare would dominate the run."""
    if not rows:
        return (0, ())
    sums = []
    for j in range(len(rows[0])):
        v = [r[j] for r in rows if r[j] is not None]
        if v and isinstance(v[0], (int, float)) and \
                not isinstance(v[0], bool):
            sums.append(round(float(sum(v)), 2))
    return (len(rows), tuple(sums))


def run(true_sf: float, out_path: str) -> dict:
    from spark_rapids_tpu.benchmarks.bench_utils import run_bench
    from spark_rapids_tpu.benchmarks.tpch_like import QUERIES

    root = generate_dataset(true_sf)
    results = {}
    sessions = {"tpu": _session(True, root), "cpu": _session(False, root)}
    # Query-outer so the report can be (re)written after every query: a
    # timeout partway through a long run still leaves a usable table.
    # Cost of the interleave: both sessions' input caches stay live for
    # the whole run (TPU's on device — spillable, budget-enforced — and
    # CPU's in host memory) instead of one engine at a time.
    for qname in sorted(QUERIES):
        sql = QUERIES[qname]
        for label, s in sessions.items():
            rep = run_bench(s, qname, lambda: s.sql(sql),
                            iterations=1, warmups=1, keep_rows=True)
            r = results.setdefault(qname, {})
            r[f"{label}_s"] = round(rep["best_s"], 3)
            r[f"{label}_check"] = _checksum(rep["rows"])
            print(f"{label} {qname}: {r[f'{label}_s']}s "
                  f"rows={r[f'{label}_check'][0]}", flush=True)
        _write_report(true_sf, results, out_path)

    rep = _write_report(true_sf, results, out_path)
    print(f"\nwrote {out_path}; all_agree={rep['all_agree']}", flush=True)
    return rep


def _write_report(true_sf: float, results: dict, out_path: str) -> dict:
    lines = [
        f"# TPC-H-like SF{true_sf:g} file-backed timings",
        "",
        "Parquet-backed run (lineitem = "
        f"{int(true_sf * 6_000_000):,} rows); TPU inputs device-cached "
        "after the first read (spillable).  Checksums = (row count, "
        "rounded numeric column sums); both engines must agree.",
        "",
        "| query | tpu s | cpu s | speedup | rows | agree |",
        "|---|---|---|---|---|---|",
    ]
    all_ok = True
    for qname in sorted(results):
        r = results[qname]
        if "tpu_check" not in r or "cpu_check" not in r:
            continue  # mid-query interruption
        tc, cc = r["tpu_check"], r["cpu_check"]
        ok = tc[0] == cc[0] and len(tc[1]) == len(cc[1]) and all(
            abs(a - b) <= 1e-4 * max(1.0, abs(a), abs(b))
            for a, b in zip(tc[1], cc[1]))
        all_ok = all_ok and ok
        sp = r["cpu_s"] / r["tpu_s"] if r["tpu_s"] else float("inf")
        lines.append(f"| {qname} | {r['tpu_s']} | {r['cpu_s']} | "
                     f"{sp:.2f}x | {tc[0]} | {'yes' if ok else 'NO'} |")
        r["speedup"] = round(sp, 3)
        r["agree"] = ok
    done = [r for r in results.values() if "agree" in r]
    tot_t = sum(r["tpu_s"] for r in done)
    tot_c = sum(r["cpu_s"] for r in done)
    ratio = f"{tot_c / tot_t:.2f}x" if tot_t > 0 else "n/a"
    lines += ["",
              f"Total steady-state over {len(done)} queries: "
              f"tpu {tot_t:.2f}s, cpu {tot_c:.2f}s ({ratio})", ""]
    with open(out_path, "w") as f:
        f.write("\n".join(lines))
    return {"all_agree": all_ok, "queries": results,
            "total_tpu_s": round(tot_t, 3), "total_cpu_s": round(tot_c, 3)}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--sf", type=float, default=1.0)
    ap.add_argument("--out", default="BENCH_SF1.md")
    args = ap.parse_args(argv)
    rep = run(args.sf, args.out)
    print(json.dumps({"sf": args.sf, "all_agree": rep["all_agree"],
                      "total_tpu_s": rep["total_tpu_s"],
                      "total_cpu_s": rep["total_cpu_s"]}))
    return 0 if rep["all_agree"] else 1


if __name__ == "__main__":
    import sys
    sys.exit(main())
