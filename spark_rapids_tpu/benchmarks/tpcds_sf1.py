"""File-backed TPC-DS-like SF1 run for the HARD query class (q64/q14
multi-way join + sort, q47/q57 windowed monthly deltas, q97 full-outer
overlap): generate parquet once at a true-SF row scale (store_sales =
2.9M rows/SF ~ TPC-DS's 2.88M), run each query on the TPU and CPU
engines from the files, verify agreement, emit a timing table
(BenchUtils.runBench role, integration_tests/.../BenchUtils.scala:109-240;
query list order follows tpcds_test.py:21-50).

    python -m spark_rapids_tpu.benchmarks.tpcds_sf1 [--sf 1.0]
        [--queries q64,q14,q47,q57,q97] [--out BENCH_SFDS.md]
"""

from __future__ import annotations

import argparse
import json
import os
import time

# tpcds_like generators make store_sales = sf * 100_000 rows; true
# TPC-DS SF1 store_sales is ~2.88M
_GEN_PER_TRUE_SF = 29

_TABLES = ("store_sales", "store_returns", "catalog_sales",
           "catalog_returns", "web_sales", "web_returns", "item",
           "customer", "customer_address", "household_demographics",
           "date_dim", "store", "promotion")


def _dataset_dir(true_sf: float) -> str:
    import tempfile
    return os.path.join(tempfile.gettempdir(),
                        f"rapids_tpu_tpcds_sf{true_sf:g}")


def generate_dataset(true_sf: float, num_partitions: int = 4) -> str:
    from spark_rapids_tpu.benchmarks import tpcds_like as ds
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.session import TpuSparkSession

    root = _dataset_dir(true_sf)
    marker = os.path.join(root, "_COMPLETE")
    gen_sf = true_sf * _GEN_PER_TRUE_SF
    cols = {n: sorted((k, str(dt)) for k, (dt, _) in t.items())
            for n, t in ds.build_tables(0.001).items()}
    fingerprint = json.dumps({"cols": cols, "gen_sf": gen_sf},
                             sort_keys=True)
    if os.path.exists(marker) and open(marker).read() == fingerprint:
        return root
    s = TpuSparkSession(RapidsConf({"spark.rapids.sql.enabled": False}))
    os.makedirs(root, exist_ok=True)
    for name, data in ds.build_tables(gen_sf).items():
        t0 = time.monotonic()
        df = s.create_dataframe(data, num_partitions=num_partitions)
        df.write_parquet(os.path.join(root, name), mode="overwrite")
        print(f"wrote {name} in {time.monotonic() - t0:.1f}s", flush=True)
    with open(marker, "w") as f:
        f.write(fingerprint)
    return root


def _session(tpu: bool, root: str):
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.session import TpuSparkSession
    s = TpuSparkSession(RapidsConf({
        "spark.rapids.sql.enabled": tpu,
        "spark.sql.shuffle.partitions": 4,
        "spark.rapids.sql.variableFloatAgg.enabled": True,
    }))
    for name in _TABLES:
        df = s.read.parquet(os.path.join(root, name))
        df = df.cache()  # steady-state timing on both engines
        df.create_or_replace_temp_view(name)
    return s


def run(true_sf: float, qnames, out_path: str) -> dict:
    from spark_rapids_tpu.benchmarks.bench_utils import run_bench
    from spark_rapids_tpu.benchmarks.sf1_run import _checksum
    from spark_rapids_tpu.benchmarks.tpcds_like import QUERIES

    root = generate_dataset(true_sf)
    results = {}
    sessions = {"tpu": _session(True, root), "cpu": _session(False, root)}
    for qname in qnames:
        sql = QUERIES[qname]
        for label, s in sessions.items():
            rep = run_bench(s, qname, lambda: s.sql(sql),
                            iterations=1, warmups=1, keep_rows=True)
            r = results.setdefault(qname, {})
            r[f"{label}_s"] = round(rep["best_s"], 3)
            r[f"{label}_check"] = _checksum(rep["rows"])
            print(f"{label} {qname}: {r[f'{label}_s']}s "
                  f"rows={r[f'{label}_check'][0]}", flush=True)
        _write_report(true_sf, results, out_path)
    rep = _write_report(true_sf, results, out_path)
    print(f"\nwrote {out_path}; all_agree={rep['all_agree']}", flush=True)
    return rep


def _write_report(true_sf: float, results: dict, out_path: str) -> dict:
    lines = [
        f"# TPC-DS-like SF{true_sf:g} file-backed timings (hard queries)",
        "",
        f"Parquet-backed (store_sales = "
        f"{int(true_sf * _GEN_PER_TRUE_SF * 100_000):,} rows); inputs "
        "device-cached after first read (spillable).  Checksums = (row "
        "count, rounded numeric sums); both engines must agree.",
        "",
        "| query | tpu s | cpu s | speedup | rows | agree |",
        "|---|---|---|---|---|---|",
    ]
    all_ok = True
    for qname in results:
        r = results[qname]
        if "tpu_check" not in r or "cpu_check" not in r:
            continue
        tc, cc = r["tpu_check"], r["cpu_check"]
        ok = tc[0] == cc[0] and len(tc[1]) == len(cc[1]) and all(
            abs(a - b) <= 1e-4 * max(1.0, abs(a), abs(b))
            for a, b in zip(tc[1], cc[1]))
        all_ok = all_ok and ok
        sp = r["cpu_s"] / r["tpu_s"] if r["tpu_s"] else float("inf")
        lines.append(f"| {qname} | {r['tpu_s']} | {r['cpu_s']} | "
                     f"{sp:.2f}x | {tc[0]} | {'yes' if ok else 'NO'} |")
        r["speedup"] = round(sp, 3)
        r["agree"] = ok
    done = [r for r in results.values() if "agree" in r]
    tot_t = sum(r["tpu_s"] for r in done)
    tot_c = sum(r["cpu_s"] for r in done)
    ratio = f"{tot_c / tot_t:.2f}x" if tot_t > 0 else "n/a"
    lines += ["", f"Total steady-state over {len(done)} queries: "
              f"tpu {tot_t:.2f}s, cpu {tot_c:.2f}s ({ratio})", ""]
    with open(out_path, "w") as f:
        f.write("\n".join(lines))
    return {"all_agree": all_ok, "queries": results,
            "total_tpu_s": round(tot_t, 3), "total_cpu_s": round(tot_c, 3)}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--sf", type=float, default=1.0)
    ap.add_argument("--queries", default="q64,q14,q47,q57,q97")
    ap.add_argument("--out", default="BENCH_SFDS.md")
    args = ap.parse_args(argv)
    rep = run(args.sf, [q.strip() for q in args.queries.split(",")],
              args.out)
    print(json.dumps({"sf": args.sf, "all_agree": rep["all_agree"],
                      "total_tpu_s": rep["total_tpu_s"],
                      "total_cpu_s": rep["total_cpu_s"]}))
    return 0 if rep["all_agree"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
