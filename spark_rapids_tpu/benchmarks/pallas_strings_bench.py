"""Pallas vs XLA string-contains at 1M rows (VERDICT r4 item 8: "a
measured win or a documented finding that XLA is already at parity").

    python -m spark_rapids_tpu.benchmarks.pallas_strings_bench [--rows N]

Builds a 1M-row string column (12-byte average), times the XLA
formulation (exprs.strings._rows_with_match's gather+searchsorted path)
against the Pallas one-pass kernel on the current backend, verifies they
agree, and prints one JSON line.
"""

from __future__ import annotations

import argparse
import json
import os
import time

import numpy as np


def run(rows: int, needle: str = "acme") -> dict:
    # build inputs BEFORE flipping the env: the XLA path must not take
    # the Pallas branch
    os.environ["SPARK_RAPIDS_PALLAS_STRINGS"] = "0"
    import jax
    import jax.numpy as jnp
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.batch import HostBatch, host_to_device
    from spark_rapids_tpu.exprs.base import DevVal
    from spark_rapids_tpu.exprs import strings as S
    from spark_rapids_tpu.kernels import pallas_strings as PS

    rng = np.random.RandomState(3)
    frags = np.array(["acme", "corp", "ax", "me", "xyzzy", "ac", "cme",
                      "a", ""])
    strs = ["".join(rng.choice(frags, rng.randint(1, 5)))
            for _ in range(rows)]
    hb = HostBatch.from_pydict({"s": (T.STRING, strs)})
    db = host_to_device(hb)
    col = db.columns[0]
    v = DevVal(col.dtype, col.data, col.validity, col.offsets)
    nb = needle.encode()

    xla_fn = jax.jit(lambda d, o, val: S._rows_with_match(
        DevVal(col.dtype, d, val, o), nb))
    pal_fn = jax.jit(lambda d, o, val: PS.rows_with_match(
        d, o, val, v.capacity, nb))

    def best_of(fn, n=5):
        out = fn(v.data, v.offsets, v.validity)
        jax.block_until_ready(out)  # compile + warm
        best = float("inf")
        for _ in range(n):
            t0 = time.monotonic()
            jax.block_until_ready(fn(v.data, v.offsets, v.validity))
            best = min(best, time.monotonic() - t0)
        return best, out

    t_xla, r_xla = best_of(xla_fn)
    os.environ["SPARK_RAPIDS_PALLAS_STRINGS"] = "1"
    t_pal, r_pal = best_of(pal_fn)

    agree = bool(np.array_equal(np.asarray(r_xla)[:rows],
                                np.asarray(r_pal)[:rows]))
    nbytes = int(col.data.shape[0])
    return {
        "metric": "contains_1m",
        "rows": rows, "byte_buffer": nbytes,
        "backend": jax.default_backend(),
        "xla_s": round(t_xla, 5), "pallas_s": round(t_pal, 5),
        "speedup_pallas_vs_xla": round(t_xla / t_pal, 3),
        "xla_gb_per_sec": round(nbytes / t_xla / 1e9, 3),
        "pallas_gb_per_sec": round(nbytes / t_pal / 1e9, 3),
        "agree": agree,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1_000_000)
    a = ap.parse_args(argv)
    res = run(a.rows)
    print(json.dumps(res))
    assert res["agree"], "pallas and xla disagree"
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
