"""TPC-DS-like star-schema benchmark: synthetic store_sales fact + item /
date_dim / customer / store dimensions, and query definitions shaped like
the TPC-DS reporting set (TpcdsLikeSpark analogue,
integration_tests/.../TpcdsLikeSpark.scala — adapted to the engine's
type/op envelope the same way TpchLike is).

Query shapes covered: dimension-filtered fact scans with multi-way joins,
group-by + order-by + limit reporting rollups (q3/q42/q52/q55 family),
multi-aggregate demographic profiles (q7), two-level aggregation with a
HAVING-style post-filter (q65 family), windowed category shares
(q53/q89/q98), year-over-year self joins (q2/q59), rollup-via-union
(q22), three-branch channel unions (q14/q33), running cumulative windows
(q51), semi-join frequent-buyer selection (q34), premium-vs-average
subquery joins (q92), return-adjusted left joins (q93), and INTERSECT/
EXCEPT customer-overlap counts (q38/q87).
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from spark_rapids_tpu import types as T

BRANDS = [f"brand#{i}" for i in range(1, 21)]
CATEGORIES = ["Books", "Electronics", "Home", "Jewelry", "Men", "Music",
              "Shoes", "Sports", "Toys", "Women"]
STATES = ["CA", "GA", "IL", "NY", "TX", "WA"]
EDU = ["Primary", "Secondary", "College", "2 yr Degree", "4 yr Degree"]
CLASSES = [f"class#{i}" for i in range(1, 9)]
CITIES = ["Midway", "Fairview", "Oakland", "Salem", "Georgetown",
          "Greenville", "Springdale", "Riverside"]
COUNTIES = [f"{c} County" for c in
            ["Orange", "Walker", "Daviess", "Ziebach", "Barrow", "Luce"]]

# date_dim spans 1998-1999 weekly granularity style: d_date_sk is a dense key


def gen_date_dim() -> Dict:
    n = 730  # two years of days
    sk = np.arange(1, n + 1)
    year = np.where(sk <= 365, 1998, 1999)
    doy = np.where(sk <= 365, sk, sk - 365)
    moy = np.minimum((doy - 1) // 30 + 1, 12)
    return {
        "d_date_sk": (T.LONG, sk),
        "d_year": (T.INT, year.astype(np.int32)),
        "d_moy": (T.INT, moy.astype(np.int32)),
        "d_dom": (T.INT, ((doy - 1) % 30 + 1).astype(np.int32)),
        "d_qoy": (T.INT, ((moy - 1) // 3 + 1).astype(np.int32)),
        "d_week_seq": (T.INT, ((sk - 1) // 7 + 1).astype(np.int32)),
    }


def gen_item(sf: float, seed: int = 21) -> Dict:
    n = max(10, int(sf * 2_000))
    r = np.random.RandomState(seed)
    return {
        "i_item_sk": (T.LONG, np.arange(1, n + 1)),
        "i_brand": (T.STRING, r.choice(BRANDS, n)),
        "i_category": (T.STRING, r.choice(CATEGORIES, n)),
        "i_class": (T.STRING, r.choice(CLASSES, n)),
        "i_manufact_id": (T.INT, r.randint(1, 100, n).astype(np.int32)),
        "i_current_price": (T.DOUBLE, (r.rand(n) * 99 + 1).round(2)),
    }


def gen_customer(sf: float, seed: int = 22) -> Dict:
    n = max(10, int(sf * 1_000))
    r = np.random.RandomState(seed)
    return {
        "c_customer_sk": (T.LONG, np.arange(1, n + 1)),
        "c_birth_year": (T.INT, r.randint(1924, 1992, n).astype(np.int32)),
        "c_education": (T.STRING, r.choice(EDU, n)),
        "c_state": (T.STRING, r.choice(STATES, n)),
        "c_current_addr_sk": (T.LONG, r.randint(1, _n_addr(sf) + 1, n)),
        "c_current_hdemo_sk": (T.LONG, r.randint(1, 21, n)),
        "c_first_name": (T.STRING,
                         np.array([f"name_{i % 97}" for i in range(n)])),
    }


def _n_addr(sf: float) -> int:
    return max(10, int(sf * 500))


def gen_customer_address(sf: float, seed: int = 27) -> Dict:
    n = _n_addr(sf)
    r = np.random.RandomState(seed)
    return {
        "ca_address_sk": (T.LONG, np.arange(1, n + 1)),
        "ca_state": (T.STRING, r.choice(STATES, n)),
        "ca_city": (T.STRING, r.choice(CITIES, n)),
        "ca_county": (T.STRING, r.choice(COUNTIES, n)),
        "ca_gmt_offset": (T.INT, r.choice([-8, -7, -6, -5], n)
                          .astype(np.int32)),
    }


def gen_household_demographics(seed: int = 28) -> Dict:
    n = 20
    r = np.random.RandomState(seed)
    return {
        "hd_demo_sk": (T.LONG, np.arange(1, n + 1)),
        "hd_dep_count": (T.INT, r.randint(0, 10, n).astype(np.int32)),
        "hd_buy_potential": (T.STRING,
                             r.choice(["0-500", "501-1000", "1001-5000",
                                       ">10000", "Unknown"], n)),
        "hd_vehicle_count": (T.INT, r.randint(0, 5, n).astype(np.int32)),
    }


def gen_store(seed: int = 23) -> Dict:
    n = 12
    r = np.random.RandomState(seed)
    return {
        "s_store_sk": (T.LONG, np.arange(1, n + 1)),
        "s_state": (T.STRING, r.choice(STATES, n)),
        "s_city": (T.STRING, r.choice(CITIES, n)),
        "s_county": (T.STRING, r.choice(COUNTIES, n)),
    }


def gen_promotion(seed: int = 25) -> Dict:
    n = 30
    r = np.random.RandomState(seed)
    return {
        "p_promo_sk": (T.LONG, np.arange(1, n + 1)),
        "p_channel_email": (T.STRING, r.choice(["Y", "N"], n)),
        "p_channel_event": (T.STRING, r.choice(["Y", "N"], n)),
    }


def _with_nulls(r, vals, frac: float):
    """Python list with ~frac of entries NULL (nullable foreign keys —
    the q76/q97 family counts rows by which key is missing)."""
    mask = r.rand(len(vals)) < frac
    return [None if m else int(v) for m, v in zip(mask, vals)]


def gen_store_sales(sf: float, seed: int = 24) -> Dict:
    n = max(100, int(sf * 100_000))
    r = np.random.RandomState(seed)
    n_item = max(10, int(sf * 2_000))
    n_cust = max(10, int(sf * 1_000))
    price = (r.rand(n) * 200 + 1).round(2)
    qty = r.randint(1, 101, n)
    return {
        "ss_sold_date_sk": (T.LONG, r.randint(1, 731, n)),
        "ss_item_sk": (T.LONG, r.randint(1, n_item + 1, n)),
        "ss_customer_sk": (T.LONG,
                           _with_nulls(r, r.randint(1, n_cust + 1, n),
                                       0.03)),
        "ss_store_sk": (T.LONG, r.randint(1, 13, n)),
        "ss_promo_sk": (T.LONG,
                        _with_nulls(r, r.randint(1, 31, n), 0.05)),
        "ss_ticket_number": (T.LONG, r.randint(1, n // 3 + 2, n)),
        "ss_quantity": (T.INT, qty.astype(np.int32)),
        "ss_sales_price": (T.DOUBLE, price),
        "ss_ext_sales_price": (T.DOUBLE, (price * qty).round(2)),
        "ss_ext_discount_amt": (T.DOUBLE, (r.rand(n) * 100).round(2)),
        "ss_net_profit": (T.DOUBLE, ((r.rand(n) - 0.3) * 500).round(2)),
    }


def gen_store_returns(sf: float, seed: int = 26, sales: Dict = None) -> Dict:
    """Returns SAMPLE real store_sales rows (same ticket/item/customer/
    store keys, later return date, quantity <= sold quantity) so the
    sale<->return joins in the q17/q50/q64 class actually match lines —
    like dsdgen's coupled fact generation.  Pass ``sales`` to reuse an
    already-generated fact (must come from gen_store_sales(sf))."""
    n = max(20, int(sf * 10_000))
    r = np.random.RandomState(seed)
    ss = sales if sales is not None else gen_store_sales(sf)
    n_ss = len(ss["ss_ticket_number"][1])
    pick = r.randint(0, n_ss, n)
    sold_date = np.asarray(ss["ss_sold_date_sk"][1])[pick]
    lag = r.randint(1, 120, n)
    cust = ss["ss_customer_sk"][1]
    return {
        "sr_returned_date_sk": (T.LONG,
                                np.minimum(sold_date + lag, 730)),
        "sr_item_sk": (T.LONG, np.asarray(ss["ss_item_sk"][1])[pick]),
        "sr_customer_sk": (T.LONG, [cust[i] for i in pick]),
        "sr_store_sk": (T.LONG, np.asarray(ss["ss_store_sk"][1])[pick]),
        "sr_ticket_number": (T.LONG,
                             np.asarray(ss["ss_ticket_number"][1])[pick]),
        "sr_return_quantity": (
            T.INT, np.maximum(
                1, np.asarray(ss["ss_quantity"][1])[pick] // 2)
            .astype(np.int32)),
        "sr_return_amt": (T.DOUBLE, (r.rand(n) * 300).round(2)),
    }


def gen_catalog_sales(sf: float, seed: int = 29) -> Dict:
    """Catalog channel fact — ~40% the store fact's size, same key
    space (TPC-DS catalog_sales role)."""
    n = max(60, int(sf * 40_000))
    r = np.random.RandomState(seed)
    n_item = max(10, int(sf * 2_000))
    n_cust = max(10, int(sf * 1_000))
    price = (r.rand(n) * 250 + 1).round(2)
    qty = r.randint(1, 101, n)
    return {
        "cs_sold_date_sk": (T.LONG, r.randint(1, 731, n)),
        "cs_item_sk": (T.LONG, r.randint(1, n_item + 1, n)),
        "cs_bill_customer_sk": (T.LONG,
                                _with_nulls(r, r.randint(1, n_cust + 1, n),
                                            0.02)),
        "cs_promo_sk": (T.LONG, r.randint(1, 31, n)),
        "cs_order_number": (T.LONG, r.randint(1, n // 2 + 2, n)),
        "cs_quantity": (T.INT, qty.astype(np.int32)),
        "cs_sales_price": (T.DOUBLE, price),
        "cs_ext_sales_price": (T.DOUBLE, (price * qty).round(2)),
        "cs_ext_discount_amt": (T.DOUBLE, (r.rand(n) * 120).round(2)),
        "cs_net_profit": (T.DOUBLE, ((r.rand(n) - 0.3) * 600).round(2)),
        # drawn LAST so earlier columns keep their values across versions;
        # some orders genuinely ship from several warehouses (q16's
        # multi-warehouse EXISTS shape)
        "cs_warehouse_sk": (T.LONG, r.randint(1, 7, n)),
    }


def gen_web_sales(sf: float, seed: int = 30) -> Dict:
    """Web channel fact — ~20% the store fact's size (web_sales role)."""
    n = max(40, int(sf * 20_000))
    r = np.random.RandomState(seed)
    n_item = max(10, int(sf * 2_000))
    n_cust = max(10, int(sf * 1_000))
    price = (r.rand(n) * 180 + 1).round(2)
    qty = r.randint(1, 101, n)
    return {
        "ws_sold_date_sk": (T.LONG, r.randint(1, 731, n)),
        "ws_item_sk": (T.LONG, r.randint(1, n_item + 1, n)),
        "ws_bill_customer_sk": (T.LONG,
                                _with_nulls(r, r.randint(1, n_cust + 1, n),
                                            0.02)),
        "ws_order_number": (T.LONG, r.randint(1, n // 2 + 2, n)),
        "ws_quantity": (T.INT, qty.astype(np.int32)),
        "ws_sales_price": (T.DOUBLE, price),
        "ws_ext_sales_price": (T.DOUBLE, (price * qty).round(2)),
        "ws_net_profit": (T.DOUBLE, ((r.rand(n) - 0.25) * 400).round(2)),
        # drawn last (see cs_warehouse_sk); q95's multi-warehouse orders
        "ws_warehouse_sk": (T.LONG, r.randint(1, 7, n)),
    }


def gen_web_returns(sf: float, seed: int = 31, sales: Dict = None) -> Dict:
    """Samples web_sales lines (coupled keys, like gen_store_returns)."""
    n = max(10, int(sf * 2_000))
    r = np.random.RandomState(seed)
    ws = sales if sales is not None else gen_web_sales(sf)
    n_ws = len(ws["ws_order_number"][1])
    pick = r.randint(0, n_ws, n)
    sold = np.asarray(ws["ws_sold_date_sk"][1])[pick]
    cust = ws["ws_bill_customer_sk"][1]
    return {
        "wr_returned_date_sk": (T.LONG,
                                np.minimum(sold + r.randint(1, 90, n), 730)),
        "wr_item_sk": (T.LONG, np.asarray(ws["ws_item_sk"][1])[pick]),
        "wr_refunded_customer_sk": (T.LONG, [cust[i] for i in pick]),
        "wr_order_number": (T.LONG,
                            np.asarray(ws["ws_order_number"][1])[pick]),
        "wr_return_quantity": (
            T.INT, np.maximum(
                1, np.asarray(ws["ws_quantity"][1])[pick] // 3)
            .astype(np.int32)),
        "wr_return_amt": (T.DOUBLE, (r.rand(n) * 200).round(2)),
    }


def gen_catalog_returns(sf: float, seed: int = 32, sales: Dict = None) -> Dict:
    """Samples catalog_sales lines (coupled keys)."""
    n = max(15, int(sf * 4_000))
    r = np.random.RandomState(seed)
    cs = sales if sales is not None else gen_catalog_sales(sf)
    n_cs = len(cs["cs_order_number"][1])
    pick = r.randint(0, n_cs, n)
    sold = np.asarray(cs["cs_sold_date_sk"][1])[pick]
    cust = cs["cs_bill_customer_sk"][1]
    return {
        "cr_returned_date_sk": (T.LONG,
                                np.minimum(sold + r.randint(1, 100, n),
                                           730)),
        "cr_item_sk": (T.LONG, np.asarray(cs["cs_item_sk"][1])[pick]),
        "cr_refunded_customer_sk": (T.LONG, [cust[i] for i in pick]),
        "cr_order_number": (T.LONG,
                            np.asarray(cs["cs_order_number"][1])[pick]),
        "cr_return_quantity": (
            T.INT, np.maximum(
                1, np.asarray(cs["cs_quantity"][1])[pick] // 4)
            .astype(np.int32)),
        "cr_return_amount": (T.DOUBLE, (r.rand(n) * 250).round(2)),
    }


def gen_warehouse(seed: int = 33) -> Dict:
    n = 6
    r = np.random.RandomState(seed)
    return {
        "w_warehouse_sk": (T.LONG, np.arange(1, n + 1)),
        "w_warehouse_name": (T.STRING,
                             np.array([f"Warehouse#{i}"
                                       for i in range(1, n + 1)])),
        "w_state": (T.STRING, r.choice(STATES, n)),
    }


def gen_inventory(sf: float, seed: int = 34) -> Dict:
    """Weekly stock snapshots (inventory role): random (date, item,
    warehouse) observations rather than the full cross product, sized to
    stay proportional to the fact tables."""
    n = max(200, int(sf * 30_000))
    r = np.random.RandomState(seed)
    n_item = max(10, int(sf * 2_000))
    # snapshot dates on week boundaries across both years
    dates = np.arange(7, 731, 7)
    return {
        "inv_date_sk": (T.LONG, r.choice(dates, n)),
        "inv_item_sk": (T.LONG, r.randint(1, n_item + 1, n)),
        "inv_warehouse_sk": (T.LONG, r.randint(1, 7, n)),
        "inv_quantity_on_hand": (T.INT,
                                 r.randint(0, 1000, n).astype(np.int32)),
    }


def build_tables(sf: float) -> Dict[str, Dict]:
    """All tables at one scale; the sales facts are generated once and
    fed to their returns generators (they sample sale lines)."""
    ss = gen_store_sales(sf)
    cs = gen_catalog_sales(sf)
    ws = gen_web_sales(sf)
    return {
        "store_sales": ss,
        "store_returns": gen_store_returns(sf, sales=ss),
        "catalog_sales": cs,
        "catalog_returns": gen_catalog_returns(sf, sales=cs),
        "web_sales": ws,
        "web_returns": gen_web_returns(sf, sales=ws),
        "item": gen_item(sf),
        "customer": gen_customer(sf),
        "customer_address": gen_customer_address(sf),
        "household_demographics": gen_household_demographics(),
        "date_dim": gen_date_dim(),
        "store": gen_store(),
        "promotion": gen_promotion(),
        "warehouse": gen_warehouse(),
        "inventory": gen_inventory(sf),
    }


def register_tpcds(session, sf: float = 0.1, num_partitions: int = 4):
    tables = build_tables(sf)
    for name, data in tables.items():
        df = session.create_dataframe(data, num_partitions=num_partitions)
        session.register_view(name, df)


# -- queries (TpcdsLikeSpark adaptation) ------------------------------------

Q3 = """
SELECT d_year, i_brand, sum(ss_ext_sales_price) AS sum_agg
FROM store_sales
JOIN date_dim ON d_date_sk = ss_sold_date_sk
JOIN item ON i_item_sk = ss_item_sk
WHERE i_manufact_id = 52 AND d_moy = 11
GROUP BY d_year, i_brand
ORDER BY d_year, sum_agg DESC, i_brand
LIMIT 100
"""

Q7 = """
SELECT i_category,
       avg(ss_quantity) AS agg1,
       avg(ss_sales_price) AS agg2,
       avg(ss_ext_sales_price) AS agg3,
       avg(ss_ext_discount_amt) AS agg4
FROM store_sales
JOIN customer ON c_customer_sk = ss_customer_sk
JOIN item ON i_item_sk = ss_item_sk
WHERE c_education = 'College' AND c_birth_year < 1970
GROUP BY i_category
ORDER BY i_category
"""

Q42 = """
SELECT d_year, i_category, sum(ss_ext_sales_price) AS total
FROM store_sales
JOIN date_dim ON d_date_sk = ss_sold_date_sk
JOIN item ON i_item_sk = ss_item_sk
WHERE d_moy = 12 AND i_current_price > 50
GROUP BY d_year, i_category
ORDER BY total DESC, d_year, i_category
LIMIT 100
"""

Q52 = """
SELECT d_year, i_brand, sum(ss_ext_sales_price) AS ext_price
FROM store_sales
JOIN date_dim ON d_date_sk = ss_sold_date_sk
JOIN item ON i_item_sk = ss_item_sk
WHERE d_moy = 11 AND d_year = 1998
GROUP BY d_year, i_brand
ORDER BY d_year, ext_price DESC, i_brand
LIMIT 100
"""

Q55 = """
SELECT i_brand, sum(ss_ext_sales_price) AS ext_price
FROM store_sales
JOIN date_dim ON d_date_sk = ss_sold_date_sk
JOIN item ON i_item_sk = ss_item_sk
WHERE d_moy = 6 AND d_year = 1999
GROUP BY i_brand
ORDER BY ext_price DESC, i_brand
LIMIT 100
"""

Q65 = """
SELECT s_state, i_category, sum(ss_net_profit) AS profit
FROM store_sales
JOIN store ON s_store_sk = ss_store_sk
JOIN item ON i_item_sk = ss_item_sk
GROUP BY s_state, i_category
HAVING sum(ss_net_profit) > 0
ORDER BY s_state, profit DESC
"""

Q13 = """
SELECT avg(ss_quantity) AS avg_qty,
       avg(ss_ext_sales_price) AS avg_price,
       sum(ss_ext_discount_amt) AS total_disc
FROM store_sales
JOIN store ON s_store_sk = ss_store_sk
JOIN customer ON c_customer_sk = ss_customer_sk
WHERE s_state IN ('CA', 'TX')
  AND c_education IN ('College', '4 yr Degree')
  AND ss_sales_price BETWEEN 50 AND 150
"""

Q19 = """
SELECT i_brand, i_manufact_id, sum(ss_ext_sales_price) AS ext_price
FROM store_sales
JOIN date_dim ON d_date_sk = ss_sold_date_sk
JOIN item ON i_item_sk = ss_item_sk
JOIN customer ON c_customer_sk = ss_customer_sk
JOIN store ON s_store_sk = ss_store_sk
WHERE d_moy = 11 AND d_year = 1998 AND i_manufact_id < 40
  AND c_state <> s_state
GROUP BY i_brand, i_manufact_id
ORDER BY ext_price DESC, i_brand, i_manufact_id
LIMIT 100
"""

Q26 = """
SELECT i_category,
       avg(ss_quantity) AS agg1,
       avg(ss_sales_price) AS agg2
FROM store_sales
JOIN promotion ON p_promo_sk = ss_promo_sk
JOIN item ON i_item_sk = ss_item_sk
WHERE p_channel_email = 'N' OR p_channel_event = 'N'
GROUP BY i_category
ORDER BY i_category
"""

Q29 = """
SELECT i_category,
       sum(ss_quantity) AS sold,
       sum(sr_return_quantity) AS returned
FROM store_sales
JOIN store_returns ON sr_item_sk = ss_item_sk
  AND sr_customer_sk = ss_customer_sk
JOIN item ON i_item_sk = ss_item_sk
GROUP BY i_category
ORDER BY i_category
"""

Q36 = """
SELECT i_category, profit,
       rank() OVER (ORDER BY profit DESC) AS rk
FROM (
  SELECT i_category, sum(ss_net_profit) AS profit
  FROM store_sales
  JOIN item ON i_item_sk = ss_item_sk
  GROUP BY i_category
)
ORDER BY rk, i_category
"""

Q43 = """
SELECT s_state, d_moy, sum(ss_ext_sales_price) AS total
FROM store_sales
JOIN date_dim ON d_date_sk = ss_sold_date_sk
JOIN store ON s_store_sk = ss_store_sk
WHERE d_year = 1998
GROUP BY s_state, d_moy
ORDER BY s_state, d_moy
"""

Q48 = """
SELECT sum(CASE WHEN ss_quantity BETWEEN 1 AND 20 THEN 1 ELSE 0 END)
         AS bucket1,
       sum(CASE WHEN ss_quantity BETWEEN 21 AND 40 THEN 1 ELSE 0 END)
         AS bucket2,
       sum(CASE WHEN ss_quantity BETWEEN 41 AND 100 THEN 1 ELSE 0 END)
         AS bucket3
FROM store_sales
JOIN store ON s_store_sk = ss_store_sk
WHERE s_state IN ('CA', 'NY', 'TX')
"""

Q53 = """
SELECT i_manufact_id, d_moy, sum_sales,
       avg(sum_sales) OVER (PARTITION BY i_manufact_id)
         AS avg_manufact_sales
FROM (
  SELECT i_manufact_id, d_moy, sum(ss_sales_price) AS sum_sales
  FROM store_sales
  JOIN item ON i_item_sk = ss_item_sk
  JOIN date_dim ON d_date_sk = ss_sold_date_sk
  WHERE d_year = 1999 AND i_manufact_id < 20
  GROUP BY i_manufact_id, d_moy
)
ORDER BY i_manufact_id, d_moy
"""

Q59 = """
SELECT y1.s_state, y1.total AS sales_1998, y2.total AS sales_1999
FROM (
  SELECT s_state, sum(ss_ext_sales_price) AS total
  FROM store_sales
  JOIN date_dim ON d_date_sk = ss_sold_date_sk
  JOIN store ON s_store_sk = ss_store_sk
  WHERE d_year = 1998
  GROUP BY s_state
) y1
JOIN (
  SELECT s_state, sum(ss_ext_sales_price) AS total
  FROM store_sales
  JOIN date_dim ON d_date_sk = ss_sold_date_sk
  JOIN store ON s_store_sk = ss_store_sk
  WHERE d_year = 1999
  GROUP BY s_state
) y2 ON y1.s_state = y2.s_state
ORDER BY y1.s_state
"""

Q61 = """
SELECT p.s_state, p.promo_sales, t.total_sales
FROM (
  SELECT s_state, sum(ss_ext_sales_price) AS promo_sales
  FROM store_sales
  JOIN store ON s_store_sk = ss_store_sk
  JOIN promotion ON p_promo_sk = ss_promo_sk
  WHERE p_channel_email = 'Y' OR p_channel_event = 'Y'
  GROUP BY s_state
) p
JOIN (
  SELECT s_state, sum(ss_ext_sales_price) AS total_sales
  FROM store_sales
  JOIN store ON s_store_sk = ss_store_sk
  GROUP BY s_state
) t ON p.s_state = t.s_state
ORDER BY p.s_state
"""

Q68 = """
SELECT ss_ticket_number, ss_customer_sk,
       sum(ss_ext_sales_price) AS amt,
       sum(ss_net_profit) AS profit
FROM store_sales
JOIN store ON s_store_sk = ss_store_sk
WHERE s_state = 'CA'
GROUP BY ss_ticket_number, ss_customer_sk
HAVING sum(ss_ext_sales_price) > 500
ORDER BY ss_ticket_number, ss_customer_sk
LIMIT 100
"""

Q73 = """
SELECT c_state, count(DISTINCT ss_customer_sk) AS buyers,
       count(*) AS line_items
FROM store_sales
JOIN customer ON c_customer_sk = ss_customer_sk
GROUP BY c_state
ORDER BY c_state
"""

Q79 = """
SELECT s_state, ss_customer_sk, sum(ss_net_profit) AS profit
FROM store_sales
JOIN store ON s_store_sk = ss_store_sk
JOIN date_dim ON d_date_sk = ss_sold_date_sk
WHERE d_moy BETWEEN 1 AND 3
GROUP BY s_state, ss_customer_sk
HAVING sum(ss_net_profit) > 300
ORDER BY s_state, profit DESC, ss_customer_sk
LIMIT 100
"""

Q89 = """
SELECT i_category, d_moy, sum_sales, avg_monthly_sales
FROM (
  SELECT i_category, d_moy, sum_sales,
         avg(sum_sales) OVER (PARTITION BY i_category)
           AS avg_monthly_sales
  FROM (
    SELECT i_category, d_moy, sum(ss_sales_price) AS sum_sales
    FROM store_sales
    JOIN item ON i_item_sk = ss_item_sk
    JOIN date_dim ON d_date_sk = ss_sold_date_sk
    WHERE d_year = 1998
    GROUP BY i_category, d_moy
  )
)
WHERE sum_sales > avg_monthly_sales
ORDER BY i_category, d_moy
"""

Q98 = """
SELECT i_category, i_brand, itemrevenue,
       itemrevenue * 100.0 / cat_rev AS revenueratio
FROM (
  SELECT i_category, i_brand, itemrevenue,
         sum(itemrevenue) OVER (PARTITION BY i_category) AS cat_rev
  FROM (
    SELECT i_category, i_brand, sum(ss_ext_sales_price) AS itemrevenue
    FROM store_sales
    JOIN item ON i_item_sk = ss_item_sk
    JOIN date_dim ON d_date_sk = ss_sold_date_sk
    WHERE d_year = 1999
    GROUP BY i_category, i_brand
  )
)
ORDER BY i_category, i_brand
"""

Q14 = """
SELECT channel, i_category, sum(sales) AS total_sales,
       count(*) AS groups_n
FROM (
  SELECT 'first_half' AS channel, i_category,
         sum(ss_ext_sales_price) AS sales
  FROM store_sales
  JOIN item ON i_item_sk = ss_item_sk
  JOIN date_dim ON d_date_sk = ss_sold_date_sk
  WHERE d_moy BETWEEN 1 AND 6
  GROUP BY i_category
  UNION ALL
  SELECT 'second_half' AS channel, i_category,
         sum(ss_ext_sales_price) AS sales
  FROM store_sales
  JOIN item ON i_item_sk = ss_item_sk
  JOIN date_dim ON d_date_sk = ss_sold_date_sk
  WHERE d_moy BETWEEN 7 AND 12
  GROUP BY i_category
)
GROUP BY channel, i_category
ORDER BY channel, i_category
"""

Q2 = """
SELECT m1.d_moy, m1.total AS total_1998, m2.total AS total_1999,
       m2.total / m1.total AS growth
FROM (
  SELECT d_moy, sum(ss_ext_sales_price) AS total
  FROM store_sales
  JOIN date_dim ON d_date_sk = ss_sold_date_sk
  WHERE d_year = 1998
  GROUP BY d_moy
) m1
JOIN (
  SELECT d_moy, sum(ss_ext_sales_price) AS total
  FROM store_sales
  JOIN date_dim ON d_date_sk = ss_sold_date_sk
  WHERE d_year = 1999
  GROUP BY d_moy
) m2 ON m1.d_moy = m2.d_moy
ORDER BY m1.d_moy
"""

Q22 = """
SELECT i_category, i_brand, avg(ss_quantity) AS qoh
FROM store_sales
JOIN item ON i_item_sk = ss_item_sk
GROUP BY i_category, i_brand
UNION ALL
SELECT i_category, 'ALL' AS i_brand, avg(ss_quantity) AS qoh
FROM store_sales
JOIN item ON i_item_sk = ss_item_sk
GROUP BY i_category
ORDER BY i_category, i_brand, qoh
"""

Q25 = """
SELECT i_category, s_state,
       sum(ss_net_profit) AS profit,
       min(ss_net_profit) AS min_profit,
       max(ss_net_profit) AS max_profit
FROM store_sales
JOIN item ON i_item_sk = ss_item_sk
JOIN store ON s_store_sk = ss_store_sk
WHERE ss_quantity > 10
GROUP BY i_category, s_state
ORDER BY i_category, s_state
"""

Q33 = """
SELECT i_manufact_id, sum(total_sales) AS total_sales
FROM (
  SELECT i_manufact_id, sum(ss_ext_sales_price) AS total_sales
  FROM store_sales
  JOIN item ON i_item_sk = ss_item_sk
  JOIN date_dim ON d_date_sk = ss_sold_date_sk
  WHERE d_moy = 1
  GROUP BY i_manufact_id
  UNION ALL
  SELECT i_manufact_id, sum(ss_ext_sales_price) AS total_sales
  FROM store_sales
  JOIN item ON i_item_sk = ss_item_sk
  JOIN date_dim ON d_date_sk = ss_sold_date_sk
  WHERE d_moy = 2
  GROUP BY i_manufact_id
  UNION ALL
  SELECT i_manufact_id, sum(ss_ext_sales_price) AS total_sales
  FROM store_sales
  JOIN item ON i_item_sk = ss_item_sk
  JOIN date_dim ON d_date_sk = ss_sold_date_sk
  WHERE d_moy = 3
  GROUP BY i_manufact_id
)
GROUP BY i_manufact_id
ORDER BY total_sales DESC, i_manufact_id
LIMIT 100
"""

Q34 = """
SELECT c_state, count(*) AS frequent_buyers
FROM customer
LEFT SEMI JOIN (
  SELECT ss_customer_sk
  FROM store_sales
  GROUP BY ss_customer_sk
  HAVING count(*) > 15
) f ON c_customer_sk = ss_customer_sk
GROUP BY c_state
ORDER BY c_state
"""

Q51 = """
SELECT i_category, d_moy, sum_sales,
       sum(sum_sales) OVER (PARTITION BY i_category ORDER BY d_moy
                            ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW)
         AS cume_sales
FROM (
  SELECT i_category, d_moy, sum(ss_sales_price) AS sum_sales
  FROM store_sales
  JOIN item ON i_item_sk = ss_item_sk
  JOIN date_dim ON d_date_sk = ss_sold_date_sk
  WHERE d_year = 1998
  GROUP BY i_category, d_moy
)
ORDER BY i_category, d_moy
"""

Q92 = """
SELECT i_category, count(*) AS premium_items
FROM item
JOIN (
  SELECT i_category AS cat, avg(i_current_price) AS avg_price
  FROM item
  GROUP BY i_category
) a ON i_category = cat
WHERE i_current_price > avg_price * 1.2
GROUP BY i_category
ORDER BY i_category
"""

Q93 = """
SELECT ss_customer_sk, sum(act_sales) AS sumsales
FROM (
  SELECT ss_customer_sk,
         CASE WHEN sr_return_quantity IS NOT NULL
              THEN (ss_quantity - sr_return_quantity) * ss_sales_price
              ELSE ss_quantity * ss_sales_price END AS act_sales
  FROM store_sales
  LEFT JOIN store_returns ON sr_item_sk = ss_item_sk
    AND sr_customer_sk = ss_customer_sk
)
GROUP BY ss_customer_sk
ORDER BY sumsales DESC, ss_customer_sk
LIMIT 100
"""

Q38 = """
SELECT count(*) AS common_customers
FROM (
  SELECT ss_customer_sk FROM store_sales
  JOIN date_dim ON d_date_sk = ss_sold_date_sk
  WHERE d_moy BETWEEN 1 AND 6
  INTERSECT
  SELECT ss_customer_sk FROM store_sales
  JOIN date_dim ON d_date_sk = ss_sold_date_sk
  WHERE d_moy BETWEEN 7 AND 12
)
"""

Q87 = """
SELECT count(*) AS never_returned
FROM (
  SELECT ss_customer_sk FROM store_sales
  EXCEPT
  SELECT sr_customer_sk FROM store_returns
)
"""

Q67 = """
SELECT i_category, i_brand, s_state, sum(ss_ext_sales_price) AS sales
FROM store_sales
JOIN item ON i_item_sk = ss_item_sk
JOIN store ON s_store_sk = ss_store_sk
GROUP BY ROLLUP(i_category, i_brand, s_state)
ORDER BY i_category, i_brand, s_state, sales
LIMIT 200
"""

# -- round-5 additions: toward the reference's full 103-query list ----------
# (tpcds_test.py:21-50; TpcdsLikeSpark.scala query classes, adapted to the
# synthetic star schema the same way the round-4 set was)

Q1 = """
WITH ctr AS (
  SELECT sr_customer_sk AS ctr_customer_sk, sr_store_sk AS ctr_store_sk,
         sum(sr_return_amt) AS ctr_total_return
  FROM store_returns
  JOIN date_dim ON d_date_sk = sr_returned_date_sk
  WHERE d_year = 1998
  GROUP BY sr_customer_sk, sr_store_sk),
avg_ctr AS (
  SELECT ctr_store_sk AS av_store_sk,
         avg(ctr_total_return) * 1.2 AS threshold
  FROM ctr GROUP BY ctr_store_sk)
SELECT c_customer_sk
FROM ctr
JOIN avg_ctr ON ctr_store_sk = av_store_sk
JOIN customer ON c_customer_sk = ctr_customer_sk
JOIN store ON s_store_sk = ctr_store_sk
WHERE ctr_total_return > threshold AND s_state = 'TX'
ORDER BY c_customer_sk
LIMIT 100
"""

Q4 = """
WITH year_total AS (
  SELECT ss_customer_sk AS customer_sk, d_year AS dyear,
         sum(ss_ext_sales_price - ss_ext_discount_amt) AS year_total,
         's' AS sale_type
  FROM store_sales JOIN date_dim ON d_date_sk = ss_sold_date_sk
  WHERE ss_customer_sk IS NOT NULL
  GROUP BY ss_customer_sk, d_year
  UNION ALL
  SELECT ws_bill_customer_sk AS customer_sk, d_year AS dyear,
         sum(ws_ext_sales_price) AS year_total, 'w' AS sale_type
  FROM web_sales JOIN date_dim ON d_date_sk = ws_sold_date_sk
  WHERE ws_bill_customer_sk IS NOT NULL
  GROUP BY ws_bill_customer_sk, d_year)
SELECT s1_cust
FROM (SELECT customer_sk AS s1_cust, year_total AS s1_tot FROM year_total
      WHERE sale_type = 's' AND dyear = 1998) s1
JOIN (SELECT customer_sk AS s2_cust, year_total AS s2_tot FROM year_total
      WHERE sale_type = 's' AND dyear = 1999) s2 ON s1_cust = s2_cust
JOIN (SELECT customer_sk AS w1_cust, year_total AS w1_tot FROM year_total
      WHERE sale_type = 'w' AND dyear = 1998) w1 ON s1_cust = w1_cust
JOIN (SELECT customer_sk AS w2_cust, year_total AS w2_tot FROM year_total
      WHERE sale_type = 'w' AND dyear = 1999) w2 ON s1_cust = w2_cust
WHERE s1_tot > 0 AND w1_tot > 0
  AND w2_tot / w1_tot > s2_tot / s1_tot
ORDER BY s1_cust
LIMIT 100
"""

Q5 = """
SELECT channel, sum(sales) AS sales, sum(returns_amt) AS returns_amt,
       sum(profit) AS profit
FROM (
  SELECT 'store channel' AS channel, ss_ext_sales_price AS sales,
         0.0 AS returns_amt, ss_net_profit AS profit
  FROM store_sales JOIN date_dim ON d_date_sk = ss_sold_date_sk
  WHERE d_year = 1998
  UNION ALL
  SELECT 'store channel' AS channel, 0.0 AS sales,
         sr_return_amt AS returns_amt, 0.0 AS profit
  FROM store_returns JOIN date_dim ON d_date_sk = sr_returned_date_sk
  WHERE d_year = 1998
  UNION ALL
  SELECT 'catalog channel' AS channel, cs_ext_sales_price AS sales,
         0.0 AS returns_amt, cs_net_profit AS profit
  FROM catalog_sales JOIN date_dim ON d_date_sk = cs_sold_date_sk
  WHERE d_year = 1998
  UNION ALL
  SELECT 'catalog channel' AS channel, 0.0 AS sales,
         cr_return_amount AS returns_amt, 0.0 AS profit
  FROM catalog_returns JOIN date_dim ON d_date_sk = cr_returned_date_sk
  WHERE d_year = 1998
  UNION ALL
  SELECT 'web channel' AS channel, ws_ext_sales_price AS sales,
         0.0 AS returns_amt, ws_net_profit AS profit
  FROM web_sales JOIN date_dim ON d_date_sk = ws_sold_date_sk
  WHERE d_year = 1998
  UNION ALL
  SELECT 'web channel' AS channel, 0.0 AS sales,
         wr_return_amt AS returns_amt, 0.0 AS profit
  FROM web_returns JOIN date_dim ON d_date_sk = wr_returned_date_sk
  WHERE d_year = 1998
)
GROUP BY ROLLUP(channel)
ORDER BY channel, sales
"""

Q8 = """
SELECT s_store_sk, sum(ss_net_profit) AS net_profit
FROM store_sales
JOIN store ON s_store_sk = ss_store_sk
JOIN customer ON c_customer_sk = ss_customer_sk
JOIN customer_address ON ca_address_sk = c_current_addr_sk
WHERE ca_county IN ('Orange County', 'Walker County', 'Barrow County')
GROUP BY s_store_sk
ORDER BY s_store_sk
"""

Q9 = """
SELECT count(CASE WHEN ss_quantity BETWEEN 1 AND 20 THEN 1 END) AS cnt1,
       avg(CASE WHEN ss_quantity BETWEEN 1 AND 20
                THEN ss_ext_sales_price END) AS avg1,
       count(CASE WHEN ss_quantity BETWEEN 21 AND 40 THEN 1 END) AS cnt2,
       avg(CASE WHEN ss_quantity BETWEEN 21 AND 40
                THEN ss_ext_sales_price END) AS avg2,
       count(CASE WHEN ss_quantity BETWEEN 41 AND 60 THEN 1 END) AS cnt3,
       avg(CASE WHEN ss_quantity BETWEEN 41 AND 60
                THEN ss_ext_sales_price END) AS avg3,
       count(CASE WHEN ss_quantity BETWEEN 61 AND 80 THEN 1 END) AS cnt4,
       avg(CASE WHEN ss_quantity BETWEEN 61 AND 80
                THEN ss_ext_sales_price END) AS avg4,
       count(CASE WHEN ss_quantity BETWEEN 81 AND 100 THEN 1 END) AS cnt5,
       avg(CASE WHEN ss_quantity BETWEEN 81 AND 100
                THEN ss_ext_sales_price END) AS avg5
FROM store_sales
"""

Q10 = """
SELECT c_state, c_education, count(*) AS cnt,
       min(c_birth_year) AS min_year, max(c_birth_year) AS max_year
FROM customer
LEFT SEMI JOIN store_sales ON ss_customer_sk = c_customer_sk
LEFT SEMI JOIN web_sales ON ws_bill_customer_sk = c_customer_sk
GROUP BY c_state, c_education
ORDER BY c_state, c_education
"""

Q11 = """
WITH year_total AS (
  SELECT ss_customer_sk AS customer_sk, d_year AS dyear,
         sum(ss_ext_sales_price) AS year_total, 's' AS sale_type
  FROM store_sales JOIN date_dim ON d_date_sk = ss_sold_date_sk
  WHERE ss_customer_sk IS NOT NULL
  GROUP BY ss_customer_sk, d_year
  UNION ALL
  SELECT ws_bill_customer_sk AS customer_sk, d_year AS dyear,
         sum(ws_ext_sales_price) AS year_total, 'w' AS sale_type
  FROM web_sales JOIN date_dim ON d_date_sk = ws_sold_date_sk
  WHERE ws_bill_customer_sk IS NOT NULL
  GROUP BY ws_bill_customer_sk, d_year)
SELECT c_customer_sk, c_first_name
FROM (SELECT customer_sk AS s1_cust, year_total AS s1_tot FROM year_total
      WHERE sale_type = 's' AND dyear = 1998) s1
JOIN (SELECT customer_sk AS s2_cust, year_total AS s2_tot FROM year_total
      WHERE sale_type = 's' AND dyear = 1999) s2 ON s1_cust = s2_cust
JOIN (SELECT customer_sk AS w1_cust, year_total AS w1_tot FROM year_total
      WHERE sale_type = 'w' AND dyear = 1998) w1 ON s1_cust = w1_cust
JOIN (SELECT customer_sk AS w2_cust, year_total AS w2_tot FROM year_total
      WHERE sale_type = 'w' AND dyear = 1999) w2 ON s1_cust = w2_cust
JOIN customer ON c_customer_sk = s1_cust
WHERE s1_tot > 0 AND w1_tot > 0 AND w2_tot / w1_tot > s2_tot / s1_tot
ORDER BY c_customer_sk
LIMIT 100
"""

Q12 = """
WITH rev AS (
  SELECT i_class, i_category, sum(ws_ext_sales_price) AS itemrevenue
  FROM web_sales
  JOIN item ON i_item_sk = ws_item_sk
  JOIN date_dim ON d_date_sk = ws_sold_date_sk
  WHERE i_category IN ('Books', 'Home', 'Sports') AND d_moy BETWEEN 2 AND 3
  GROUP BY i_class, i_category)
SELECT i_class, i_category, itemrevenue,
       itemrevenue * 100.0 /
         sum(itemrevenue) OVER (PARTITION BY i_category) AS revenueratio
FROM rev
ORDER BY i_category, i_class, revenueratio
"""

Q15 = """
SELECT ca_state, d_qoy, sum(cs_sales_price) AS total_sales
FROM catalog_sales
JOIN customer ON c_customer_sk = cs_bill_customer_sk
JOIN customer_address ON ca_address_sk = c_current_addr_sk
JOIN date_dim ON d_date_sk = cs_sold_date_sk
WHERE d_year = 1998 AND cs_sales_price > 100
GROUP BY ca_state, d_qoy
ORDER BY ca_state, d_qoy
"""

Q17 = """
SELECT i_brand, s_state,
       count(ss_quantity) AS store_sales_cnt,
       avg(ss_quantity) AS store_sales_avg,
       stddev(ss_quantity) AS store_sales_sd,
       count(sr_return_quantity) AS store_ret_cnt,
       avg(sr_return_quantity) AS store_ret_avg,
       count(cs_quantity) AS catalog_cnt,
       avg(cs_quantity) AS catalog_avg
FROM store_sales
JOIN store_returns ON sr_ticket_number = ss_ticket_number
                  AND sr_item_sk = ss_item_sk
JOIN catalog_sales ON cs_bill_customer_sk = sr_customer_sk
                  AND cs_item_sk = sr_item_sk
JOIN item ON i_item_sk = ss_item_sk
JOIN store ON s_store_sk = ss_store_sk
GROUP BY i_brand, s_state
ORDER BY i_brand, s_state
LIMIT 100
"""

Q20 = """
WITH rev AS (
  SELECT i_class, i_category, sum(cs_ext_sales_price) AS itemrevenue
  FROM catalog_sales
  JOIN item ON i_item_sk = cs_item_sk
  JOIN date_dim ON d_date_sk = cs_sold_date_sk
  WHERE i_category IN ('Electronics', 'Jewelry', 'Toys')
    AND d_moy BETWEEN 2 AND 3
  GROUP BY i_class, i_category)
SELECT i_class, i_category, itemrevenue,
       itemrevenue * 100.0 /
         sum(itemrevenue) OVER (PARTITION BY i_category) AS revenueratio
FROM rev
ORDER BY i_category, i_class, revenueratio
"""

Q23A = """
WITH frequent_items AS (
  SELECT ss_item_sk AS fi_item_sk
  FROM store_sales JOIN date_dim ON d_date_sk = ss_sold_date_sk
  WHERE d_year = 1998
  GROUP BY ss_item_sk
  HAVING count(*) > 4),
per_cust AS (
  SELECT ss_customer_sk AS pc_cust,
         sum(ss_quantity * ss_sales_price) AS spend
  FROM store_sales
  WHERE ss_customer_sk IS NOT NULL
  GROUP BY ss_customer_sk),
best_customers AS (
  SELECT pc_cust AS bc_cust
  FROM per_cust
  CROSS JOIN (SELECT max(spend) * 0.5 AS thr FROM per_cust) m
  WHERE spend > thr)
SELECT sum(sales) AS total_sales
FROM (
  SELECT cs_quantity * cs_sales_price AS sales
  FROM catalog_sales
  LEFT SEMI JOIN frequent_items ON fi_item_sk = cs_item_sk
  LEFT SEMI JOIN best_customers ON bc_cust = cs_bill_customer_sk
  UNION ALL
  SELECT ws_quantity * ws_sales_price AS sales
  FROM web_sales
  LEFT SEMI JOIN frequent_items ON fi_item_sk = ws_item_sk
  LEFT SEMI JOIN best_customers ON bc_cust = ws_bill_customer_sk
)
"""

Q23B = """
WITH frequent_items AS (
  SELECT ss_item_sk AS fi_item_sk
  FROM store_sales JOIN date_dim ON d_date_sk = ss_sold_date_sk
  WHERE d_year = 1998
  GROUP BY ss_item_sk
  HAVING count(*) > 4),
per_cust AS (
  SELECT ss_customer_sk AS pc_cust,
         sum(ss_quantity * ss_sales_price) AS spend
  FROM store_sales
  WHERE ss_customer_sk IS NOT NULL
  GROUP BY ss_customer_sk),
best_customers AS (
  SELECT pc_cust AS bc_cust
  FROM per_cust
  CROSS JOIN (SELECT max(spend) * 0.5 AS thr FROM per_cust) m
  WHERE spend > thr)
SELECT cust, sum(sales) AS total_sales
FROM (
  SELECT cs_bill_customer_sk AS cust, cs_quantity * cs_sales_price AS sales
  FROM catalog_sales
  LEFT SEMI JOIN frequent_items ON fi_item_sk = cs_item_sk
  LEFT SEMI JOIN best_customers ON bc_cust = cs_bill_customer_sk
  UNION ALL
  SELECT ws_bill_customer_sk AS cust, ws_quantity * ws_sales_price AS sales
  FROM web_sales
  LEFT SEMI JOIN frequent_items ON fi_item_sk = ws_item_sk
  LEFT SEMI JOIN best_customers ON bc_cust = ws_bill_customer_sk
)
GROUP BY cust
ORDER BY total_sales DESC, cust
LIMIT 100
"""

Q27 = """
SELECT s_state, i_category,
       avg(ss_quantity) AS agg1,
       avg(ss_sales_price) AS agg2,
       avg(ss_ext_sales_price) AS agg3
FROM store_sales
JOIN store ON s_store_sk = ss_store_sk
JOIN item ON i_item_sk = ss_item_sk
JOIN customer ON c_customer_sk = ss_customer_sk
WHERE c_education = 'College'
GROUP BY ROLLUP(s_state, i_category)
ORDER BY s_state, i_category
"""

Q28 = """
SELECT b1_avg, b1_cnt, b2_avg, b2_cnt, b3_avg, b3_cnt,
       b4_avg, b4_cnt, b5_avg, b5_cnt, b6_avg, b6_cnt
FROM (SELECT avg(ss_sales_price) AS b1_avg, count(ss_sales_price) AS b1_cnt
      FROM store_sales WHERE ss_quantity BETWEEN 0 AND 5) t1
CROSS JOIN
     (SELECT avg(ss_sales_price) AS b2_avg, count(ss_sales_price) AS b2_cnt
      FROM store_sales WHERE ss_quantity BETWEEN 6 AND 10) t2
CROSS JOIN
     (SELECT avg(ss_sales_price) AS b3_avg, count(ss_sales_price) AS b3_cnt
      FROM store_sales WHERE ss_quantity BETWEEN 11 AND 15) t3
CROSS JOIN
     (SELECT avg(ss_sales_price) AS b4_avg, count(ss_sales_price) AS b4_cnt
      FROM store_sales WHERE ss_quantity BETWEEN 16 AND 20) t4
CROSS JOIN
     (SELECT avg(ss_sales_price) AS b5_avg, count(ss_sales_price) AS b5_cnt
      FROM store_sales WHERE ss_quantity BETWEEN 21 AND 25) t5
CROSS JOIN
     (SELECT avg(ss_sales_price) AS b6_avg, count(ss_sales_price) AS b6_cnt
      FROM store_sales WHERE ss_quantity BETWEEN 26 AND 30) t6
"""

Q30 = """
WITH wr_total AS (
  SELECT wr_refunded_customer_sk AS wrt_cust, c_state AS wrt_state,
         sum(wr_return_amt) AS wrt_total
  FROM web_returns
  JOIN customer ON c_customer_sk = wr_refunded_customer_sk
  JOIN date_dim ON d_date_sk = wr_returned_date_sk
  WHERE d_year = 1998
  GROUP BY wr_refunded_customer_sk, c_state),
state_avg AS (
  SELECT wrt_state AS sa_state, avg(wrt_total) * 1.2 AS threshold
  FROM wr_total GROUP BY wrt_state)
SELECT wrt_cust, wrt_total
FROM wr_total
JOIN state_avg ON wrt_state = sa_state
WHERE wrt_total > threshold
ORDER BY wrt_cust
LIMIT 100
"""

Q31 = """
WITH ss_cty AS (
  SELECT ca_county AS county, d_qoy AS qoy,
         sum(ss_ext_sales_price) AS store_sales_tot
  FROM store_sales
  JOIN customer ON c_customer_sk = ss_customer_sk
  JOIN customer_address ON ca_address_sk = c_current_addr_sk
  JOIN date_dim ON d_date_sk = ss_sold_date_sk
  WHERE d_year = 1998
  GROUP BY ca_county, d_qoy),
ws_cty AS (
  SELECT ca_county AS county, d_qoy AS qoy,
         sum(ws_ext_sales_price) AS web_sales_tot
  FROM web_sales
  JOIN customer ON c_customer_sk = ws_bill_customer_sk
  JOIN customer_address ON ca_address_sk = c_current_addr_sk
  JOIN date_dim ON d_date_sk = ws_sold_date_sk
  WHERE d_year = 1998
  GROUP BY ca_county, d_qoy)
SELECT ss1_county, ws2_tot / ws1_tot AS web_growth,
       ss2_tot / ss1_tot AS store_growth
FROM (SELECT county AS ss1_county, store_sales_tot AS ss1_tot
      FROM ss_cty WHERE qoy = 1) ss1
JOIN (SELECT county AS ss2_county, store_sales_tot AS ss2_tot
      FROM ss_cty WHERE qoy = 2) ss2 ON ss1_county = ss2_county
JOIN (SELECT county AS ws1_county, web_sales_tot AS ws1_tot
      FROM ws_cty WHERE qoy = 1) ws1 ON ss1_county = ws1_county
JOIN (SELECT county AS ws2_county, web_sales_tot AS ws2_tot
      FROM ws_cty WHERE qoy = 2) ws2 ON ss1_county = ws2_county
WHERE ss1_tot > 0 AND ws1_tot > 0
  AND ws2_tot / ws1_tot > ss2_tot / ss1_tot
ORDER BY ss1_county
"""


Q35 = """
SELECT c_state, c_education, count(*) AS cnt,
       avg(c_birth_year) AS avg_year,
       max(c_birth_year) AS max_year,
       sum(c_birth_year) AS sum_year
FROM customer
LEFT SEMI JOIN store_sales ON ss_customer_sk = c_customer_sk
GROUP BY c_state, c_education
ORDER BY c_state, c_education
"""

Q37 = """
SELECT i_item_sk, i_brand, i_current_price
FROM item
LEFT SEMI JOIN catalog_sales ON cs_item_sk = i_item_sk
WHERE i_current_price BETWEEN 20 AND 40
ORDER BY i_item_sk
LIMIT 100
"""

Q39A = """
SELECT item_sk, moy, qavg, qsd / qavg AS cov
FROM (
  SELECT cs_item_sk AS item_sk, d_moy AS moy,
         stddev(cs_quantity) AS qsd, avg(cs_quantity) AS qavg
  FROM catalog_sales
  JOIN date_dim ON d_date_sk = cs_sold_date_sk
  WHERE d_year = 1998
  GROUP BY cs_item_sk, d_moy)
WHERE qavg > 0 AND qsd / qavg > 0.5
ORDER BY item_sk, moy
LIMIT 100
"""

Q39B = """
WITH iv AS (
  SELECT cs_item_sk AS item_sk, d_moy AS moy,
         stddev(cs_quantity) AS qsd, avg(cs_quantity) AS qavg
  FROM catalog_sales
  JOIN date_dim ON d_date_sk = cs_sold_date_sk
  WHERE d_year = 1998
  GROUP BY cs_item_sk, d_moy)
SELECT i1, moy1, cov1, moy2, cov2
FROM (SELECT item_sk AS i1, moy AS moy1, qsd / qavg AS cov1 FROM iv
      WHERE qavg > 0 AND qsd / qavg > 0.5) v1
JOIN (SELECT item_sk AS i2, moy AS moy2, qsd / qavg AS cov2 FROM iv
      WHERE qavg > 0 AND qsd / qavg > 0.5) v2
  ON i1 = i2 AND moy1 + 1 = moy2
ORDER BY i1, moy1
LIMIT 100
"""

Q40 = """
SELECT i_category,
       sum(CASE WHEN d_dom < 15
                THEN cs_ext_sales_price - coalesce(cr_return_amount, 0.0)
                ELSE 0.0 END) AS sales_before,
       sum(CASE WHEN d_dom >= 15
                THEN cs_ext_sales_price - coalesce(cr_return_amount, 0.0)
                ELSE 0.0 END) AS sales_after
FROM catalog_sales
LEFT JOIN catalog_returns ON cr_order_number = cs_order_number
                         AND cr_item_sk = cs_item_sk
JOIN item ON i_item_sk = cs_item_sk
JOIN date_dim ON d_date_sk = cs_sold_date_sk
WHERE d_moy = 4
GROUP BY i_category
ORDER BY i_category
"""

Q41 = """
SELECT DISTINCT i_class, i_category
FROM item
WHERE i_current_price BETWEEN 30 AND 50
  AND i_category IN ('Books', 'Music', 'Home')
ORDER BY i_class, i_category
LIMIT 100
"""

Q44 = """
WITH perf AS (
  SELECT ss_item_sk AS item_sk, avg(ss_net_profit) AS rank_col
  FROM store_sales
  GROUP BY ss_item_sk),
asc_rank AS (
  SELECT item_sk AS best_sk, rank() OVER (ORDER BY rank_col DESC) AS rnk_up
  FROM perf),
desc_rank AS (
  SELECT item_sk AS worst_sk, rank() OVER (ORDER BY rank_col ASC)
           AS rnk_down
  FROM perf)
SELECT rnk_up, best_brand, worst_brand
FROM (SELECT rnk_up, i_brand AS best_brand FROM asc_rank
      JOIN item ON i_item_sk = best_sk WHERE rnk_up <= 10) b
JOIN (SELECT rnk_down, i_brand AS worst_brand FROM desc_rank
      JOIN item ON i_item_sk = worst_sk WHERE rnk_down <= 10) w
  ON rnk_up = rnk_down
ORDER BY rnk_up
"""

Q45 = """
SELECT ca_city, sum(ws_ext_sales_price) AS total_sales
FROM web_sales
JOIN customer ON c_customer_sk = ws_bill_customer_sk
JOIN customer_address ON ca_address_sk = c_current_addr_sk
JOIN item ON i_item_sk = ws_item_sk
WHERE i_manufact_id IN (5, 17, 33, 61, 85)
GROUP BY ca_city
ORDER BY ca_city
"""

Q46 = """
SELECT ss_ticket_number, c_customer_sk, ca_city, s_city,
       sum(ss_net_profit) AS profit
FROM store_sales
JOIN store ON s_store_sk = ss_store_sk
JOIN customer ON c_customer_sk = ss_customer_sk
JOIN customer_address ON ca_address_sk = c_current_addr_sk
WHERE ca_city <> s_city
GROUP BY ss_ticket_number, c_customer_sk, ca_city, s_city
ORDER BY c_customer_sk, ss_ticket_number
LIMIT 100
"""

Q47 = """
WITH mb AS (
  SELECT i_brand, d_year, d_moy, sum(ss_ext_sales_price) AS sum_sales
  FROM store_sales
  JOIN item ON i_item_sk = ss_item_sk
  JOIN date_dim ON d_date_sk = ss_sold_date_sk
  GROUP BY i_brand, d_year, d_moy),
v2 AS (
  SELECT i_brand, d_year, d_moy, sum_sales,
         avg(sum_sales) OVER (PARTITION BY i_brand, d_year)
           AS avg_monthly_sales,
         lag(sum_sales, 1) OVER (PARTITION BY i_brand
                                 ORDER BY d_year, d_moy) AS psum,
         lead(sum_sales, 1) OVER (PARTITION BY i_brand
                                  ORDER BY d_year, d_moy) AS nsum
  FROM mb)
SELECT i_brand, d_year, d_moy, sum_sales, avg_monthly_sales, psum, nsum
FROM v2
WHERE d_year = 1999 AND avg_monthly_sales > 0
  AND sum_sales - avg_monthly_sales > 0.1 * avg_monthly_sales
ORDER BY i_brand, d_moy
LIMIT 100
"""

Q49 = """
WITH in_web AS (
  SELECT ws_item_sk AS w_item,
         sum(coalesce(wr_return_quantity, 0)) AS w_ret,
         sum(ws_quantity) AS w_qty
  FROM web_sales
  LEFT JOIN web_returns ON wr_order_number = ws_order_number
                       AND wr_item_sk = ws_item_sk
  GROUP BY ws_item_sk),
in_cat AS (
  SELECT cs_item_sk AS c_item,
         sum(coalesce(cr_return_quantity, 0)) AS c_ret,
         sum(cs_quantity) AS c_qty
  FROM catalog_sales
  LEFT JOIN catalog_returns ON cr_order_number = cs_order_number
                           AND cr_item_sk = cs_item_sk
  GROUP BY cs_item_sk)
SELECT channel, item_sk, ret_ratio,
       rank() OVER (PARTITION BY channel ORDER BY ret_ratio DESC)
         AS ret_rank
FROM (
  SELECT 'web' AS channel, w_item AS item_sk,
         w_ret * 1.0 / w_qty AS ret_ratio
  FROM in_web WHERE w_qty > 0
  UNION ALL
  SELECT 'catalog' AS channel, c_item AS item_sk,
         c_ret * 1.0 / c_qty AS ret_ratio
  FROM in_cat WHERE c_qty > 0)
ORDER BY channel, ret_rank, item_sk
LIMIT 100
"""

Q50 = """
SELECT s_state, s_city,
       sum(CASE WHEN sr_returned_date_sk - ss_sold_date_sk <= 30
                THEN 1 ELSE 0 END) AS d30,
       sum(CASE WHEN sr_returned_date_sk - ss_sold_date_sk > 30
                 AND sr_returned_date_sk - ss_sold_date_sk <= 60
                THEN 1 ELSE 0 END) AS d60,
       sum(CASE WHEN sr_returned_date_sk - ss_sold_date_sk > 60
                 AND sr_returned_date_sk - ss_sold_date_sk <= 90
                THEN 1 ELSE 0 END) AS d90,
       sum(CASE WHEN sr_returned_date_sk - ss_sold_date_sk > 90
                THEN 1 ELSE 0 END) AS d120
FROM store_sales
JOIN store_returns ON sr_ticket_number = ss_ticket_number
                  AND sr_item_sk = ss_item_sk
JOIN store ON s_store_sk = ss_store_sk
GROUP BY s_state, s_city
ORDER BY s_state, s_city
"""

Q54 = """
WITH my_customers AS (
  SELECT DISTINCT cs_bill_customer_sk AS mc_sk
  FROM catalog_sales
  JOIN date_dim ON d_date_sk = cs_sold_date_sk
  WHERE d_moy = 3 AND d_year = 1998 AND cs_bill_customer_sk IS NOT NULL),
rev AS (
  SELECT mc_sk, sum(ss_ext_sales_price) AS revenue
  FROM store_sales
  JOIN my_customers ON ss_customer_sk = mc_sk
  JOIN date_dim ON d_date_sk = ss_sold_date_sk
  WHERE d_moy BETWEEN 4 AND 6 AND d_year = 1998
  GROUP BY mc_sk)
SELECT cast(revenue / 1000 AS int) AS segment, count(*) AS num_customers
FROM rev
GROUP BY cast(revenue / 1000 AS int)
ORDER BY segment
LIMIT 100
"""

Q56 = """
SELECT i_class, sum(total_sales) AS total_sales
FROM (
  SELECT i_class, sum(ss_ext_sales_price) AS total_sales
  FROM store_sales JOIN item ON i_item_sk = ss_item_sk
  JOIN date_dim ON d_date_sk = ss_sold_date_sk
  WHERE d_moy = 2 GROUP BY i_class
  UNION ALL
  SELECT i_class, sum(cs_ext_sales_price) AS total_sales
  FROM catalog_sales JOIN item ON i_item_sk = cs_item_sk
  JOIN date_dim ON d_date_sk = cs_sold_date_sk
  WHERE d_moy = 2 GROUP BY i_class
  UNION ALL
  SELECT i_class, sum(ws_ext_sales_price) AS total_sales
  FROM web_sales JOIN item ON i_item_sk = ws_item_sk
  JOIN date_dim ON d_date_sk = ws_sold_date_sk
  WHERE d_moy = 2 GROUP BY i_class
)
GROUP BY i_class
ORDER BY total_sales, i_class
LIMIT 100
"""

Q57 = """
WITH mb AS (
  SELECT i_category, d_year, d_moy, sum(cs_ext_sales_price) AS sum_sales
  FROM catalog_sales
  JOIN item ON i_item_sk = cs_item_sk
  JOIN date_dim ON d_date_sk = cs_sold_date_sk
  GROUP BY i_category, d_year, d_moy),
v2 AS (
  SELECT i_category, d_year, d_moy, sum_sales,
         avg(sum_sales) OVER (PARTITION BY i_category, d_year)
           AS avg_monthly_sales,
         lag(sum_sales, 1) OVER (PARTITION BY i_category
                                 ORDER BY d_year, d_moy) AS psum,
         lead(sum_sales, 1) OVER (PARTITION BY i_category
                                  ORDER BY d_year, d_moy) AS nsum
  FROM mb)
SELECT i_category, d_year, d_moy, sum_sales, avg_monthly_sales, psum, nsum
FROM v2
WHERE d_year = 1999 AND avg_monthly_sales > 0
  AND sum_sales - avg_monthly_sales > 0.1 * avg_monthly_sales
ORDER BY i_category, d_moy
LIMIT 100
"""

Q60 = """
SELECT i_category, sum(total_sales) AS total_sales
FROM (
  SELECT i_category, sum(ss_ext_sales_price) AS total_sales
  FROM store_sales JOIN item ON i_item_sk = ss_item_sk
  JOIN date_dim ON d_date_sk = ss_sold_date_sk
  WHERE d_moy = 9 GROUP BY i_category
  UNION ALL
  SELECT i_category, sum(cs_ext_sales_price) AS total_sales
  FROM catalog_sales JOIN item ON i_item_sk = cs_item_sk
  JOIN date_dim ON d_date_sk = cs_sold_date_sk
  WHERE d_moy = 9 GROUP BY i_category
  UNION ALL
  SELECT i_category, sum(ws_ext_sales_price) AS total_sales
  FROM web_sales JOIN item ON i_item_sk = ws_item_sk
  JOIN date_dim ON d_date_sk = ws_sold_date_sk
  WHERE d_moy = 9 GROUP BY i_category
)
GROUP BY i_category
ORDER BY i_category, total_sales
"""

Q62 = """
SELECT d_moy,
       sum(CASE WHEN wr_returned_date_sk - ws_sold_date_sk <= 30
                THEN 1 ELSE 0 END) AS d30,
       sum(CASE WHEN wr_returned_date_sk - ws_sold_date_sk > 30
                 AND wr_returned_date_sk - ws_sold_date_sk <= 60
                THEN 1 ELSE 0 END) AS d60,
       sum(CASE WHEN wr_returned_date_sk - ws_sold_date_sk > 60
                THEN 1 ELSE 0 END) AS d90
FROM web_sales
JOIN web_returns ON wr_order_number = ws_order_number
                AND wr_item_sk = ws_item_sk
JOIN date_dim ON d_date_sk = ws_sold_date_sk
GROUP BY d_moy
ORDER BY d_moy
"""

Q63 = """
WITH sm AS (
  SELECT s_store_sk, d_moy, sum(ss_ext_sales_price) AS sum_sales
  FROM store_sales
  JOIN store ON s_store_sk = ss_store_sk
  JOIN date_dim ON d_date_sk = ss_sold_date_sk
  WHERE d_year = 1998
  GROUP BY s_store_sk, d_moy)
SELECT s_store_sk, d_moy, sum_sales, avg_monthly
FROM (
  SELECT s_store_sk, d_moy, sum_sales,
         avg(sum_sales) OVER (PARTITION BY s_store_sk) AS avg_monthly
  FROM sm)
WHERE avg_monthly > 0 AND sum_sales > 1.1 * avg_monthly
ORDER BY s_store_sk, d_moy
LIMIT 100
"""

Q64 = """
WITH cs AS (
  SELECT i_item_sk AS item_sk, s_store_sk AS store_sk,
         c_customer_sk AS cust_sk, ca_city AS city, d_year AS syear,
         sum(ss_ext_sales_price) AS sales,
         sum(sr_return_amt) AS refunds,
         count(*) AS cnt
  FROM store_sales
  JOIN store_returns ON sr_item_sk = ss_item_sk
                    AND sr_ticket_number = ss_ticket_number
  JOIN date_dim ON d_date_sk = ss_sold_date_sk
  JOIN item ON i_item_sk = ss_item_sk
  JOIN customer ON c_customer_sk = ss_customer_sk
  JOIN customer_address ON ca_address_sk = c_current_addr_sk
  JOIN store ON s_store_sk = ss_store_sk
  WHERE i_current_price BETWEEN 5 AND 80
  GROUP BY i_item_sk, s_store_sk, c_customer_sk, ca_city, d_year)
SELECT i1, cu1, city1, sales1, sales2
FROM (SELECT item_sk AS i1, cust_sk AS cu1, city AS city1,
             sales AS sales1, cnt AS cnt1 FROM cs WHERE syear = 1998) cs1
JOIN (SELECT item_sk AS i2, cust_sk AS cu2, city AS city2,
             sales AS sales2, cnt AS cnt2 FROM cs WHERE syear = 1999) cs2
  ON i1 = i2 AND cu1 = cu2
WHERE sales2 > sales1
ORDER BY i1, cu1, city1, sales2
LIMIT 100
"""

Q66 = """
SELECT s_city, s_state, d_year,
       sum(CASE WHEN d_moy = 1 THEN ss_ext_sales_price ELSE 0.0 END)
         AS jan_sales,
       sum(CASE WHEN d_moy = 2 THEN ss_ext_sales_price ELSE 0.0 END)
         AS feb_sales,
       sum(CASE WHEN d_moy = 3 THEN ss_ext_sales_price ELSE 0.0 END)
         AS mar_sales,
       sum(CASE WHEN d_moy = 4 THEN ss_ext_sales_price ELSE 0.0 END)
         AS apr_sales,
       sum(CASE WHEN d_moy = 5 THEN ss_ext_sales_price ELSE 0.0 END)
         AS may_sales,
       sum(CASE WHEN d_moy = 6 THEN ss_ext_sales_price ELSE 0.0 END)
         AS jun_sales,
       sum(CASE WHEN d_moy >= 7 THEN ss_ext_sales_price ELSE 0.0 END)
         AS h2_sales
FROM store_sales
JOIN store ON s_store_sk = ss_store_sk
JOIN date_dim ON d_date_sk = ss_sold_date_sk
GROUP BY s_city, s_state, d_year
ORDER BY s_city, s_state, d_year
"""

Q69 = """
SELECT c_state, c_education, count(*) AS cnt
FROM customer
LEFT SEMI JOIN store_sales ON ss_customer_sk = c_customer_sk
LEFT ANTI JOIN web_sales ON ws_bill_customer_sk = c_customer_sk
GROUP BY c_state, c_education
ORDER BY c_state, c_education
"""

Q71 = """
SELECT i_brand, d_dom, sum(ext_price) AS ext_price
FROM (
  SELECT ss_item_sk AS sold_item_sk, ss_sold_date_sk AS time_sk,
         ss_ext_sales_price AS ext_price
  FROM store_sales
  UNION ALL
  SELECT cs_item_sk AS sold_item_sk, cs_sold_date_sk AS time_sk,
         cs_ext_sales_price AS ext_price
  FROM catalog_sales
  UNION ALL
  SELECT ws_item_sk AS sold_item_sk, ws_sold_date_sk AS time_sk,
         ws_ext_sales_price AS ext_price
  FROM web_sales
)
JOIN item ON i_item_sk = sold_item_sk
JOIN date_dim ON d_date_sk = time_sk
WHERE d_moy = 11 AND i_manufact_id BETWEEN 1 AND 40
GROUP BY i_brand, d_dom
ORDER BY i_brand, d_dom
LIMIT 100
"""

Q74 = """
WITH year_total AS (
  SELECT ss_customer_sk AS customer_sk, d_year AS dyear,
         max(ss_ext_sales_price) AS year_max, 's' AS sale_type
  FROM store_sales JOIN date_dim ON d_date_sk = ss_sold_date_sk
  WHERE ss_customer_sk IS NOT NULL
  GROUP BY ss_customer_sk, d_year
  UNION ALL
  SELECT ws_bill_customer_sk AS customer_sk, d_year AS dyear,
         max(ws_ext_sales_price) AS year_max, 'w' AS sale_type
  FROM web_sales JOIN date_dim ON d_date_sk = ws_sold_date_sk
  WHERE ws_bill_customer_sk IS NOT NULL
  GROUP BY ws_bill_customer_sk, d_year)
SELECT s1_cust
FROM (SELECT customer_sk AS s1_cust, year_max AS s1_tot FROM year_total
      WHERE sale_type = 's' AND dyear = 1998) s1
JOIN (SELECT customer_sk AS s2_cust, year_max AS s2_tot FROM year_total
      WHERE sale_type = 's' AND dyear = 1999) s2 ON s1_cust = s2_cust
JOIN (SELECT customer_sk AS w1_cust, year_max AS w1_tot FROM year_total
      WHERE sale_type = 'w' AND dyear = 1998) w1 ON s1_cust = w1_cust
JOIN (SELECT customer_sk AS w2_cust, year_max AS w2_tot FROM year_total
      WHERE sale_type = 'w' AND dyear = 1999) w2 ON s1_cust = w2_cust
WHERE s1_tot > 0 AND w1_tot > 0 AND w2_tot / w1_tot > s2_tot / s1_tot
ORDER BY s1_cust
LIMIT 100
"""

Q75 = """
WITH all_sales AS (
  SELECT d_year AS yr, i_brand AS brand, sum(sales_cnt) AS sales_cnt
  FROM (
    SELECT d_year, i_brand, ss_quantity AS sales_cnt
    FROM store_sales JOIN item ON i_item_sk = ss_item_sk
    JOIN date_dim ON d_date_sk = ss_sold_date_sk
    UNION ALL
    SELECT d_year, i_brand, cs_quantity AS sales_cnt
    FROM catalog_sales JOIN item ON i_item_sk = cs_item_sk
    JOIN date_dim ON d_date_sk = cs_sold_date_sk
    UNION ALL
    SELECT d_year, i_brand, ws_quantity AS sales_cnt
    FROM web_sales JOIN item ON i_item_sk = ws_item_sk
    JOIN date_dim ON d_date_sk = ws_sold_date_sk
  )
  GROUP BY d_year, i_brand)
SELECT cy_brand, py_cnt, cy_cnt, cy_cnt - py_cnt AS sales_cnt_diff
FROM (SELECT brand AS cy_brand, sales_cnt AS cy_cnt FROM all_sales
      WHERE yr = 1999) cy
JOIN (SELECT brand AS py_brand, sales_cnt AS py_cnt FROM all_sales
      WHERE yr = 1998) py ON cy_brand = py_brand
WHERE cy_cnt < py_cnt
ORDER BY sales_cnt_diff, cy_brand
LIMIT 100
"""

Q76 = """
SELECT channel, col_name, d_year, d_qoy, i_category,
       count(*) AS sales_cnt, sum(ext_sales_price) AS sales_amt
FROM (
  SELECT 'store' AS channel, 'ss_customer_sk' AS col_name, d_year, d_qoy,
         i_category, ss_ext_sales_price AS ext_sales_price
  FROM store_sales
  JOIN item ON i_item_sk = ss_item_sk
  JOIN date_dim ON d_date_sk = ss_sold_date_sk
  WHERE ss_customer_sk IS NULL
  UNION ALL
  SELECT 'catalog' AS channel, 'cs_bill_customer_sk' AS col_name, d_year,
         d_qoy, i_category, cs_ext_sales_price AS ext_sales_price
  FROM catalog_sales
  JOIN item ON i_item_sk = cs_item_sk
  JOIN date_dim ON d_date_sk = cs_sold_date_sk
  WHERE cs_bill_customer_sk IS NULL
  UNION ALL
  SELECT 'web' AS channel, 'ws_bill_customer_sk' AS col_name, d_year,
         d_qoy, i_category, ws_ext_sales_price AS ext_sales_price
  FROM web_sales
  JOIN item ON i_item_sk = ws_item_sk
  JOIN date_dim ON d_date_sk = ws_sold_date_sk
  WHERE ws_bill_customer_sk IS NULL
)
GROUP BY channel, col_name, d_year, d_qoy, i_category
ORDER BY channel, col_name, d_year, d_qoy, i_category
LIMIT 100
"""

Q78 = """
WITH ss_noret AS (
  SELECT d_year AS ss_year, ss_item_sk AS ss_item,
         ss_customer_sk AS ss_cust,
         sum(ss_quantity) AS ss_qty, sum(ss_sales_price) AS ss_amt
  FROM store_sales
  LEFT JOIN store_returns ON sr_ticket_number = ss_ticket_number
                         AND sr_item_sk = ss_item_sk
  JOIN date_dim ON d_date_sk = ss_sold_date_sk
  WHERE sr_ticket_number IS NULL AND ss_customer_sk IS NOT NULL
  GROUP BY d_year, ss_item_sk, ss_customer_sk),
ws_noret AS (
  SELECT d_year AS ws_year, ws_item_sk AS ws_item,
         ws_bill_customer_sk AS ws_cust,
         sum(ws_quantity) AS ws_qty, sum(ws_sales_price) AS ws_amt
  FROM web_sales
  LEFT JOIN web_returns ON wr_order_number = ws_order_number
                       AND wr_item_sk = ws_item_sk
  JOIN date_dim ON d_date_sk = ws_sold_date_sk
  WHERE wr_order_number IS NULL AND ws_bill_customer_sk IS NOT NULL
  GROUP BY d_year, ws_item_sk, ws_bill_customer_sk)
SELECT ss_year, ss_item, ss_cust, ss_qty, ws_qty
FROM ss_noret
JOIN ws_noret ON ws_year = ss_year AND ws_item = ss_item
             AND ws_cust = ss_cust
WHERE ws_qty > 0
ORDER BY ss_year, ss_item, ss_cust
LIMIT 100
"""

Q81 = """
WITH cr_total AS (
  SELECT cr_refunded_customer_sk AS crt_cust, c_state AS crt_state,
         sum(cr_return_amount) AS crt_total
  FROM catalog_returns
  JOIN customer ON c_customer_sk = cr_refunded_customer_sk
  JOIN date_dim ON d_date_sk = cr_returned_date_sk
  WHERE d_year = 1998
  GROUP BY cr_refunded_customer_sk, c_state),
state_avg AS (
  SELECT crt_state AS sa_state, avg(crt_total) * 1.2 AS threshold
  FROM cr_total GROUP BY crt_state)
SELECT crt_cust, crt_total
FROM cr_total
JOIN state_avg ON crt_state = sa_state
WHERE crt_total > threshold
ORDER BY crt_cust
LIMIT 100
"""

Q82 = """
SELECT i_item_sk, i_brand, i_current_price
FROM item
LEFT SEMI JOIN store_sales ON ss_item_sk = i_item_sk
WHERE i_current_price BETWEEN 50 AND 70
ORDER BY i_item_sk
LIMIT 100
"""

Q85 = """
SELECT hd_buy_potential,
       avg(wr_return_quantity) AS avg_ret_qty,
       avg(wr_return_amt) AS avg_ret_amt,
       count(*) AS cnt
FROM web_returns
JOIN customer ON c_customer_sk = wr_refunded_customer_sk
JOIN household_demographics ON hd_demo_sk = c_current_hdemo_sk
GROUP BY hd_buy_potential
ORDER BY hd_buy_potential
"""

Q88 = """
SELECT c1, c2, c3, c4
FROM (SELECT count(*) AS c1 FROM store_sales
      JOIN date_dim ON d_date_sk = ss_sold_date_sk
      WHERE d_dom BETWEEN 1 AND 7) t1
CROSS JOIN
     (SELECT count(*) AS c2 FROM store_sales
      JOIN date_dim ON d_date_sk = ss_sold_date_sk
      WHERE d_dom BETWEEN 8 AND 14) t2
CROSS JOIN
     (SELECT count(*) AS c3 FROM store_sales
      JOIN date_dim ON d_date_sk = ss_sold_date_sk
      WHERE d_dom BETWEEN 15 AND 21) t3
CROSS JOIN
     (SELECT count(*) AS c4 FROM store_sales
      JOIN date_dim ON d_date_sk = ss_sold_date_sk
      WHERE d_dom BETWEEN 22 AND 30) t4
"""

Q90 = """
SELECT am_cnt * 1.0 / pm_cnt AS am_pm_ratio
FROM (SELECT count(*) AS am_cnt FROM web_sales
      JOIN date_dim ON d_date_sk = ws_sold_date_sk
      WHERE d_dom < 15) am
CROSS JOIN
     (SELECT count(*) AS pm_cnt FROM web_sales
      JOIN date_dim ON d_date_sk = ws_sold_date_sk
      WHERE d_dom >= 15) pm
"""

Q91 = """
SELECT c_education, d_moy,
       sum(sr_return_amt) AS returns_loss
FROM store_returns
JOIN customer ON c_customer_sk = sr_customer_sk
JOIN date_dim ON d_date_sk = sr_returned_date_sk
WHERE d_year = 1998
GROUP BY c_education, d_moy
ORDER BY c_education, d_moy
"""

Q94 = """
SELECT count(DISTINCT ws_order_number) AS order_count,
       sum(ws_ext_sales_price) AS total_shipping_cost,
       sum(ws_net_profit) AS total_net_profit
FROM web_sales
LEFT ANTI JOIN web_returns ON wr_order_number = ws_order_number
JOIN date_dim ON d_date_sk = ws_sold_date_sk
WHERE d_year = 1998
"""

Q96 = """
SELECT count(*) AS cnt
FROM store_sales
JOIN customer ON c_customer_sk = ss_customer_sk
JOIN household_demographics ON hd_demo_sk = c_current_hdemo_sk
JOIN store ON s_store_sk = ss_store_sk
WHERE hd_dep_count = 5 AND s_state = 'CA'
"""

Q97 = """
WITH ssci AS (
  SELECT ss_customer_sk AS s_cust, ss_item_sk AS s_item
  FROM store_sales
  WHERE ss_customer_sk IS NOT NULL
  GROUP BY ss_customer_sk, ss_item_sk),
csci AS (
  SELECT cs_bill_customer_sk AS c_cust, cs_item_sk AS c_item
  FROM catalog_sales
  WHERE cs_bill_customer_sk IS NOT NULL
  GROUP BY cs_bill_customer_sk, cs_item_sk)
SELECT sum(CASE WHEN s_cust IS NOT NULL AND c_cust IS NULL
                THEN 1 ELSE 0 END) AS store_only,
       sum(CASE WHEN s_cust IS NULL AND c_cust IS NOT NULL
                THEN 1 ELSE 0 END) AS catalog_only,
       sum(CASE WHEN s_cust IS NOT NULL AND c_cust IS NOT NULL
                THEN 1 ELSE 0 END) AS store_and_catalog
FROM ssci
FULL JOIN csci ON s_cust = c_cust AND s_item = c_item
"""

Q99 = """
SELECT d_moy,
       sum(CASE WHEN cr_returned_date_sk - cs_sold_date_sk <= 30
                THEN 1 ELSE 0 END) AS d30,
       sum(CASE WHEN cr_returned_date_sk - cs_sold_date_sk > 30
                 AND cr_returned_date_sk - cs_sold_date_sk <= 60
                THEN 1 ELSE 0 END) AS d60,
       sum(CASE WHEN cr_returned_date_sk - cs_sold_date_sk > 60
                THEN 1 ELSE 0 END) AS d90plus
FROM catalog_sales
JOIN catalog_returns ON cr_order_number = cs_order_number
                    AND cr_item_sk = cs_item_sk
JOIN date_dim ON d_date_sk = cs_sold_date_sk
GROUP BY d_moy
ORDER BY d_moy
"""

SS_MAX = """
SELECT count(*) AS total,
       count(ss_sold_date_sk) AS cnt_date,
       max(ss_sold_date_sk) AS max_date,
       max(ss_item_sk) AS max_item,
       max(ss_customer_sk) AS max_cust,
       max(ss_quantity) AS max_qty,
       max(ss_ext_sales_price) AS max_price
FROM store_sales
"""


# -- round-5 wave 2: the 18 queries closing the reference's 103-query list
# (tpcds_test.py:21-50) -------------------------------------------------

Q6 = """
SELECT c_state, count(*) AS cnt
FROM store_sales
JOIN customer ON c_customer_sk = ss_customer_sk
JOIN item ON i_item_sk = ss_item_sk
JOIN date_dim ON d_date_sk = ss_sold_date_sk
JOIN (
  SELECT i_category AS cat, avg(i_current_price) AS avg_price
  FROM item GROUP BY i_category
) a ON i_category = cat
WHERE d_year = 1998 AND d_moy = 1 AND i_current_price > 1.2 * avg_price
GROUP BY c_state
HAVING count(*) >= 10
ORDER BY cnt, c_state
LIMIT 100
"""

Q14A = """
WITH cross_items AS (
  SELECT ss_item_sk AS ci_item_sk FROM store_sales
  INTERSECT
  SELECT cs_item_sk FROM catalog_sales
  INTERSECT
  SELECT ws_item_sk FROM web_sales),
avg_sales AS (
  SELECT avg(q * p) AS average_sales FROM (
    SELECT ss_quantity AS q, ss_sales_price AS p FROM store_sales
    UNION ALL
    SELECT cs_quantity, cs_sales_price FROM catalog_sales
    UNION ALL
    SELECT ws_quantity, ws_sales_price FROM web_sales))
SELECT channel, i_brand, sum_sales
FROM (
  SELECT channel, i_brand, sum(sales) AS sum_sales
  FROM (
    SELECT 'store' AS channel, i_brand,
           ss_quantity * ss_sales_price AS sales
    FROM store_sales
    JOIN item ON i_item_sk = ss_item_sk
    LEFT SEMI JOIN cross_items ON ci_item_sk = ss_item_sk
    UNION ALL
    SELECT 'catalog' AS channel, i_brand,
           cs_quantity * cs_sales_price AS sales
    FROM catalog_sales
    JOIN item ON i_item_sk = cs_item_sk
    LEFT SEMI JOIN cross_items ON ci_item_sk = cs_item_sk
    UNION ALL
    SELECT 'web' AS channel, i_brand,
           ws_quantity * ws_sales_price AS sales
    FROM web_sales
    JOIN item ON i_item_sk = ws_item_sk
    LEFT SEMI JOIN cross_items ON ci_item_sk = ws_item_sk
  )
  GROUP BY channel, i_brand
) CROSS JOIN avg_sales
WHERE sum_sales > average_sales
ORDER BY channel, i_brand
LIMIT 100
"""

Q14B = """
WITH cross_items AS (
  SELECT ss_item_sk AS ci_item_sk FROM store_sales
  INTERSECT
  SELECT cs_item_sk FROM catalog_sales
  INTERSECT
  SELECT ws_item_sk FROM web_sales)
SELECT ty.i_brand, ty_sales, ly_sales, ty_sales / ly_sales AS growth
FROM (
  SELECT i_brand, sum(ss_quantity * ss_sales_price) AS ty_sales
  FROM store_sales
  JOIN item ON i_item_sk = ss_item_sk
  JOIN date_dim ON d_date_sk = ss_sold_date_sk
  LEFT SEMI JOIN cross_items ON ci_item_sk = ss_item_sk
  WHERE d_year = 1999
  GROUP BY i_brand
) ty
JOIN (
  SELECT i_brand AS ly_brand,
         sum(ss_quantity * ss_sales_price) AS ly_sales
  FROM store_sales
  JOIN item ON i_item_sk = ss_item_sk
  JOIN date_dim ON d_date_sk = ss_sold_date_sk
  LEFT SEMI JOIN cross_items ON ci_item_sk = ss_item_sk
  WHERE d_year = 1998
  GROUP BY i_brand
) ly ON ly_brand = ty.i_brand
WHERE ly_sales > 0
ORDER BY ty.i_brand
LIMIT 100
"""

Q16 = """
SELECT count(DISTINCT cs_order_number) AS order_count,
       sum(cs_ext_sales_price) AS total_shipping_cost,
       sum(cs_net_profit) AS total_net_profit
FROM catalog_sales
LEFT ANTI JOIN catalog_returns ON cr_order_number = cs_order_number
LEFT SEMI JOIN (
  SELECT multi_wh_order FROM (
    SELECT cs_order_number AS multi_wh_order, cs_warehouse_sk
    FROM catalog_sales
    GROUP BY cs_order_number, cs_warehouse_sk
  )
  GROUP BY multi_wh_order
  HAVING count(*) > 1
) mw ON multi_wh_order = cs_order_number
JOIN date_dim ON d_date_sk = cs_sold_date_sk
WHERE d_year = 1998 AND d_moy BETWEEN 2 AND 4
"""

Q18 = """
SELECT i_category, c_state,
       avg(cs_quantity) AS agg1,
       avg(cs_sales_price) AS agg2,
       avg(cs_ext_sales_price) AS agg3,
       avg(cs_net_profit) AS agg4
FROM catalog_sales
JOIN item ON i_item_sk = cs_item_sk
JOIN customer ON c_customer_sk = cs_bill_customer_sk
JOIN date_dim ON d_date_sk = cs_sold_date_sk
WHERE d_year = 1998
GROUP BY ROLLUP(i_category, c_state)
ORDER BY i_category, c_state
LIMIT 100
"""

Q21 = """
SELECT *
FROM (
  SELECT w_warehouse_name, inv_item_sk,
         sum(CASE WHEN d_date_sk < 365
                  THEN inv_quantity_on_hand ELSE 0 END) AS inv_before,
         sum(CASE WHEN d_date_sk >= 365
                  THEN inv_quantity_on_hand ELSE 0 END) AS inv_after
  FROM inventory
  JOIN warehouse ON w_warehouse_sk = inv_warehouse_sk
  JOIN date_dim ON d_date_sk = inv_date_sk
  GROUP BY w_warehouse_name, inv_item_sk
)
WHERE inv_before > 0
  AND inv_after / inv_before >= 0.666
  AND inv_after / inv_before <= 1.5
ORDER BY w_warehouse_name, inv_item_sk
LIMIT 100
"""

Q24A = """
WITH ssales AS (
  SELECT c_customer_sk AS cust, s_store_sk AS store_sk,
         i_item_sk AS item_sk, sum(ss_sales_price) AS netpaid
  FROM store_sales
  JOIN store ON s_store_sk = ss_store_sk
  JOIN item ON i_item_sk = ss_item_sk
  JOIN customer ON c_customer_sk = ss_customer_sk
  WHERE i_category = 'Jewelry'
  GROUP BY c_customer_sk, s_store_sk, i_item_sk)
SELECT cust, paid
FROM (
  SELECT cust, sum(netpaid) AS paid FROM ssales GROUP BY cust
) CROSS JOIN (
  SELECT 0.05 * avg(netpaid) AS thr FROM ssales
) t
WHERE paid > thr
ORDER BY cust
LIMIT 100
"""

Q24B = """
WITH ssales AS (
  SELECT c_customer_sk AS cust, s_store_sk AS store_sk,
         i_item_sk AS item_sk, sum(ss_sales_price) AS netpaid
  FROM store_sales
  JOIN store ON s_store_sk = ss_store_sk
  JOIN item ON i_item_sk = ss_item_sk
  JOIN customer ON c_customer_sk = ss_customer_sk
  WHERE i_category = 'Electronics'
  GROUP BY c_customer_sk, s_store_sk, i_item_sk)
SELECT cust, paid
FROM (
  SELECT cust, sum(netpaid) AS paid FROM ssales GROUP BY cust
) CROSS JOIN (
  SELECT 0.05 * avg(netpaid) AS thr FROM ssales
) t
WHERE paid > thr
ORDER BY cust
LIMIT 100
"""

Q32 = """
WITH avg_disc AS (
  SELECT cs_item_sk AS ad_item,
         1.3 * avg(cs_ext_discount_amt) AS thr
  FROM catalog_sales
  JOIN date_dim ON d_date_sk = cs_sold_date_sk
  WHERE d_year = 1998
  GROUP BY cs_item_sk)
SELECT sum(cs_ext_discount_amt) AS excess_discount
FROM catalog_sales
JOIN avg_disc ON ad_item = cs_item_sk
JOIN date_dim ON d_date_sk = cs_sold_date_sk
WHERE d_year = 1998 AND cs_ext_discount_amt > thr
"""

Q58 = """
WITH ss_items AS (
  SELECT ss_item_sk AS s_item, sum(ss_ext_sales_price) AS ss_rev
  FROM store_sales
  JOIN date_dim ON d_date_sk = ss_sold_date_sk
  WHERE d_moy = 3 GROUP BY ss_item_sk),
cs_items AS (
  SELECT cs_item_sk AS c_item, sum(cs_ext_sales_price) AS cs_rev
  FROM catalog_sales
  JOIN date_dim ON d_date_sk = cs_sold_date_sk
  WHERE d_moy = 3 GROUP BY cs_item_sk),
ws_items AS (
  SELECT ws_item_sk AS w_item, sum(ws_ext_sales_price) AS ws_rev
  FROM web_sales
  JOIN date_dim ON d_date_sk = ws_sold_date_sk
  WHERE d_moy = 3 GROUP BY ws_item_sk)
SELECT s_item, ss_rev, cs_rev, ws_rev,
       (ss_rev + cs_rev + ws_rev) / 3 AS average
FROM ss_items
JOIN cs_items ON c_item = s_item
JOIN ws_items ON w_item = s_item
WHERE ss_rev >= 0.9 * cs_rev AND ss_rev <= 1.1 * cs_rev
  AND ss_rev >= 0.9 * ws_rev AND ss_rev <= 1.1 * ws_rev
ORDER BY s_item
LIMIT 100
"""

Q70 = """
SELECT total_sum, s_state, ranking
FROM (
  SELECT s_state, total_sum,
         rank() OVER (ORDER BY total_sum DESC) AS ranking
  FROM (
    SELECT s_state, sum(ss_net_profit) AS total_sum
    FROM store_sales
    JOIN store ON s_store_sk = ss_store_sk
    JOIN date_dim ON d_date_sk = ss_sold_date_sk
    WHERE d_year = 1998
    GROUP BY s_state
  )
)
ORDER BY ranking, s_state
"""

Q72 = """
SELECT i_item_sk AS item_sk, w_warehouse_name, d_week_seq,
       count(*) AS low_stock_cnt
FROM catalog_sales
JOIN inventory ON inv_item_sk = cs_item_sk
JOIN warehouse ON w_warehouse_sk = inv_warehouse_sk
JOIN item ON i_item_sk = cs_item_sk
JOIN date_dim ON d_date_sk = cs_sold_date_sk
WHERE inv_quantity_on_hand < cs_quantity AND d_year = 1998
GROUP BY i_item_sk, w_warehouse_name, d_week_seq
ORDER BY low_stock_cnt DESC, i_item_sk, w_warehouse_name, d_week_seq
LIMIT 100
"""

Q77 = """
WITH ss AS (
  SELECT ss_store_sk AS store_id, sum(ss_ext_sales_price) AS sales,
         sum(ss_net_profit) AS profit
  FROM store_sales
  JOIN date_dim ON d_date_sk = ss_sold_date_sk
  WHERE d_year = 1998 GROUP BY ss_store_sk),
sr AS (
  SELECT sr_store_sk AS ret_store_id, sum(sr_return_amt) AS ret
  FROM store_returns GROUP BY sr_store_sk),
cs AS (
  SELECT sum(cs_ext_sales_price) AS sales, sum(cs_net_profit) AS profit
  FROM catalog_sales
  JOIN date_dim ON d_date_sk = cs_sold_date_sk
  WHERE d_year = 1998),
cr AS (
  SELECT sum(cr_return_amount) AS ret FROM catalog_returns),
ws AS (
  SELECT ws_warehouse_sk AS wh_id, sum(ws_ext_sales_price) AS sales,
         sum(ws_net_profit) AS profit
  FROM web_sales
  JOIN date_dim ON d_date_sk = ws_sold_date_sk
  WHERE d_year = 1998 GROUP BY ws_warehouse_sk),
wr AS (
  SELECT ws_warehouse_sk AS ret_wh_id, sum(wr_return_amt) AS ret
  FROM web_returns
  JOIN web_sales ON ws_order_number = wr_order_number
                AND ws_item_sk = wr_item_sk
  GROUP BY ws_warehouse_sk)
SELECT channel, id, sum(sales) AS sales, sum(ret) AS ret,
       sum(profit) AS profit
FROM (
  SELECT 'store channel' AS channel, store_id AS id, sales,
         coalesce(ret, 0.0) AS ret, profit
  FROM ss LEFT JOIN sr ON ret_store_id = store_id
  UNION ALL
  SELECT 'catalog channel' AS channel, 0 AS id, sales, ret, profit
  FROM cs CROSS JOIN cr
  UNION ALL
  SELECT 'web channel' AS channel, wh_id AS id, sales,
         coalesce(ret, 0.0) AS ret, profit
  FROM ws LEFT JOIN wr ON ret_wh_id = wh_id
)
GROUP BY ROLLUP(channel, id)
ORDER BY channel, id
LIMIT 100
"""

Q80 = """
WITH ssr AS (
  SELECT s_store_sk AS id, sum(ss_ext_sales_price) AS sales,
         sum(coalesce(sr_return_amt, 0.0)) AS ret,
         sum(ss_net_profit) AS profit
  FROM store_sales
  LEFT JOIN store_returns ON sr_item_sk = ss_item_sk
                         AND sr_ticket_number = ss_ticket_number
  JOIN date_dim ON d_date_sk = ss_sold_date_sk
  JOIN store ON s_store_sk = ss_store_sk
  WHERE d_year = 1998
  GROUP BY s_store_sk),
csr AS (
  SELECT cs_warehouse_sk AS id, sum(cs_ext_sales_price) AS sales,
         sum(coalesce(cr_return_amount, 0.0)) AS ret,
         sum(cs_net_profit) AS profit
  FROM catalog_sales
  LEFT JOIN catalog_returns ON cr_item_sk = cs_item_sk
                           AND cr_order_number = cs_order_number
  JOIN date_dim ON d_date_sk = cs_sold_date_sk
  WHERE d_year = 1998
  GROUP BY cs_warehouse_sk),
wsr AS (
  SELECT ws_warehouse_sk AS id, sum(ws_ext_sales_price) AS sales,
         sum(coalesce(wr_return_amt, 0.0)) AS ret,
         sum(ws_net_profit) AS profit
  FROM web_sales
  LEFT JOIN web_returns ON wr_item_sk = ws_item_sk
                       AND wr_order_number = ws_order_number
  JOIN date_dim ON d_date_sk = ws_sold_date_sk
  WHERE d_year = 1998
  GROUP BY ws_warehouse_sk)
SELECT channel, id, sum(sales) AS sales, sum(ret) AS ret,
       sum(profit) AS profit
FROM (
  SELECT 'store channel' AS channel, id, sales, ret, profit FROM ssr
  UNION ALL
  SELECT 'catalog channel' AS channel, id, sales, ret, profit FROM csr
  UNION ALL
  SELECT 'web channel' AS channel, id, sales, ret, profit FROM wsr
)
GROUP BY ROLLUP(channel, id)
ORDER BY channel, id
LIMIT 100
"""

Q83 = """
WITH sr AS (
  SELECT sr_item_sk AS s_item, sum(sr_return_quantity) AS sr_qty
  FROM store_returns
  JOIN date_dim ON d_date_sk = sr_returned_date_sk
  WHERE d_moy BETWEEN 6 AND 8 GROUP BY sr_item_sk),
cr AS (
  SELECT cr_item_sk AS c_item, sum(cr_return_quantity) AS cr_qty
  FROM catalog_returns
  JOIN date_dim ON d_date_sk = cr_returned_date_sk
  WHERE d_moy BETWEEN 6 AND 8 GROUP BY cr_item_sk),
wr AS (
  SELECT wr_item_sk AS w_item, sum(wr_return_quantity) AS wr_qty
  FROM web_returns
  JOIN date_dim ON d_date_sk = wr_returned_date_sk
  WHERE d_moy BETWEEN 6 AND 8 GROUP BY wr_item_sk)
SELECT s_item, sr_qty, cr_qty, wr_qty,
       sr_qty + cr_qty + wr_qty AS total_qty
FROM sr
JOIN cr ON c_item = s_item
JOIN wr ON w_item = s_item
ORDER BY s_item
LIMIT 100
"""

Q84 = """
SELECT c_customer_sk, c_first_name, count(*) AS cnt
FROM store_returns
JOIN customer ON c_customer_sk = sr_customer_sk
JOIN customer_address ON ca_address_sk = c_current_addr_sk
JOIN household_demographics ON hd_demo_sk = c_current_hdemo_sk
WHERE ca_city = 'Midway' AND hd_dep_count >= 3
GROUP BY c_customer_sk, c_first_name
ORDER BY c_customer_sk
LIMIT 100
"""

Q86 = """
SELECT i_category, i_class, total_sum,
       rank() OVER (PARTITION BY i_category
                    ORDER BY total_sum DESC) AS rank_within
FROM (
  SELECT i_category, i_class, sum(ws_net_profit) AS total_sum
  FROM web_sales
  JOIN item ON i_item_sk = ws_item_sk
  JOIN date_dim ON d_date_sk = ws_sold_date_sk
  WHERE d_year = 1998
  GROUP BY ROLLUP(i_category, i_class)
)
ORDER BY i_category, i_class, rank_within
LIMIT 100
"""

Q95 = """
WITH ws_wh AS (
  SELECT wh_order FROM (
    SELECT ws_order_number AS wh_order, ws_warehouse_sk
    FROM web_sales
    GROUP BY ws_order_number, ws_warehouse_sk
  )
  GROUP BY wh_order
  HAVING count(*) > 1)
SELECT count(DISTINCT ws_order_number) AS order_count,
       sum(ws_ext_sales_price) AS total_shipping_cost,
       sum(ws_net_profit) AS total_net_profit
FROM web_sales
LEFT SEMI JOIN ws_wh ON wh_order = ws_order_number
LEFT SEMI JOIN web_returns ON wr_order_number = ws_order_number
JOIN date_dim ON d_date_sk = ws_sold_date_sk
WHERE d_year = 1998
"""

QUERIES = {"q3": Q3, "q7": Q7, "q13": Q13, "q14": Q14, "q19": Q19,
           "q26": Q26, "q29": Q29, "q36": Q36, "q42": Q42, "q43": Q43,
           "q48": Q48, "q52": Q52, "q53": Q53, "q55": Q55, "q59": Q59,
           "q61": Q61, "q65": Q65, "q68": Q68, "q73": Q73, "q79": Q79,
           "q89": Q89, "q98": Q98,
           "q2": Q2, "q22": Q22, "q25": Q25, "q33": Q33,
           "q34": Q34, "q51": Q51, "q92": Q92, "q93": Q93,
           "q38": Q38, "q87": Q87, "q67": Q67,
           # round-5 additions
           "q1": Q1, "q4": Q4, "q5": Q5, "q8": Q8, "q9": Q9,
           "q10": Q10, "q11": Q11, "q12": Q12, "q15": Q15, "q17": Q17,
           "q20": Q20, "q23a": Q23A, "q23b": Q23B, "q27": Q27,
           "q28": Q28, "q30": Q30, "q31": Q31,
           "q35": Q35, "q37": Q37, "q39a": Q39A, "q39b": Q39B,
           "q40": Q40, "q41": Q41, "q44": Q44, "q45": Q45, "q46": Q46,
           "q47": Q47, "q49": Q49, "q50": Q50, "q54": Q54, "q56": Q56,
           "q57": Q57, "q60": Q60, "q62": Q62, "q63": Q63, "q64": Q64,
           "q66": Q66, "q69": Q69, "q71": Q71, "q74": Q74, "q75": Q75,
           "q76": Q76, "q78": Q78, "q81": Q81, "q82": Q82, "q85": Q85,
           "q88": Q88, "q90": Q90, "q91": Q91, "q94": Q94, "q96": Q96,
           "q97": Q97, "q99": Q99, "ss_max": SS_MAX,
           # round-5 wave 2: the final 18 of the reference's 103-query list
           "q6": Q6, "q14a": Q14A, "q14b": Q14B, "q16": Q16, "q18": Q18,
           "q21": Q21, "q24a": Q24A, "q24b": Q24B, "q32": Q32,
           "q58": Q58, "q70": Q70, "q72": Q72, "q77": Q77, "q80": Q80,
           "q83": Q83, "q84": Q84, "q86": Q86, "q95": Q95}
