"""TPC-DS-like star-schema benchmark: synthetic store_sales fact + item /
date_dim / customer / store dimensions, and query definitions shaped like
the TPC-DS reporting set (TpcdsLikeSpark analogue,
integration_tests/.../TpcdsLikeSpark.scala — adapted to the engine's
type/op envelope the same way TpchLike is).

Query shapes covered: dimension-filtered fact scans with multi-way joins,
group-by + order-by + limit reporting rollups (q3/q42/q52/q55 family),
multi-aggregate demographic profiles (q7), two-level aggregation with a
HAVING-style post-filter (q65 family), windowed category shares
(q53/q89/q98), year-over-year self joins (q2/q59), rollup-via-union
(q22), three-branch channel unions (q14/q33), running cumulative windows
(q51), semi-join frequent-buyer selection (q34), premium-vs-average
subquery joins (q92), return-adjusted left joins (q93), and INTERSECT/
EXCEPT customer-overlap counts (q38/q87).
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from spark_rapids_tpu import types as T

BRANDS = [f"brand#{i}" for i in range(1, 21)]
CATEGORIES = ["Books", "Electronics", "Home", "Jewelry", "Men", "Music",
              "Shoes", "Sports", "Toys", "Women"]
STATES = ["CA", "GA", "IL", "NY", "TX", "WA"]
EDU = ["Primary", "Secondary", "College", "2 yr Degree", "4 yr Degree"]

# date_dim spans 1998-1999 weekly granularity style: d_date_sk is a dense key


def gen_date_dim() -> Dict:
    n = 730  # two years of days
    sk = np.arange(1, n + 1)
    year = np.where(sk <= 365, 1998, 1999)
    doy = np.where(sk <= 365, sk, sk - 365)
    moy = np.minimum((doy - 1) // 30 + 1, 12)
    return {
        "d_date_sk": (T.LONG, sk),
        "d_year": (T.INT, year.astype(np.int32)),
        "d_moy": (T.INT, moy.astype(np.int32)),
        "d_dom": (T.INT, ((doy - 1) % 30 + 1).astype(np.int32)),
    }


def gen_item(sf: float, seed: int = 21) -> Dict:
    n = max(10, int(sf * 2_000))
    r = np.random.RandomState(seed)
    return {
        "i_item_sk": (T.LONG, np.arange(1, n + 1)),
        "i_brand": (T.STRING, r.choice(BRANDS, n)),
        "i_category": (T.STRING, r.choice(CATEGORIES, n)),
        "i_manufact_id": (T.INT, r.randint(1, 100, n).astype(np.int32)),
        "i_current_price": (T.DOUBLE, (r.rand(n) * 99 + 1).round(2)),
    }


def gen_customer(sf: float, seed: int = 22) -> Dict:
    n = max(10, int(sf * 1_000))
    r = np.random.RandomState(seed)
    return {
        "c_customer_sk": (T.LONG, np.arange(1, n + 1)),
        "c_birth_year": (T.INT, r.randint(1924, 1992, n).astype(np.int32)),
        "c_education": (T.STRING, r.choice(EDU, n)),
        "c_state": (T.STRING, r.choice(STATES, n)),
    }


def gen_store(seed: int = 23) -> Dict:
    n = 12
    r = np.random.RandomState(seed)
    return {
        "s_store_sk": (T.LONG, np.arange(1, n + 1)),
        "s_state": (T.STRING, r.choice(STATES, n)),
    }


def gen_promotion(seed: int = 25) -> Dict:
    n = 30
    r = np.random.RandomState(seed)
    return {
        "p_promo_sk": (T.LONG, np.arange(1, n + 1)),
        "p_channel_email": (T.STRING, r.choice(["Y", "N"], n)),
        "p_channel_event": (T.STRING, r.choice(["Y", "N"], n)),
    }


def gen_store_sales(sf: float, seed: int = 24) -> Dict:
    n = max(100, int(sf * 100_000))
    r = np.random.RandomState(seed)
    n_item = max(10, int(sf * 2_000))
    n_cust = max(10, int(sf * 1_000))
    price = (r.rand(n) * 200 + 1).round(2)
    qty = r.randint(1, 101, n)
    return {
        "ss_sold_date_sk": (T.LONG, r.randint(1, 731, n)),
        "ss_item_sk": (T.LONG, r.randint(1, n_item + 1, n)),
        "ss_customer_sk": (T.LONG, r.randint(1, n_cust + 1, n)),
        "ss_store_sk": (T.LONG, r.randint(1, 13, n)),
        "ss_promo_sk": (T.LONG, r.randint(1, 31, n)),
        "ss_ticket_number": (T.LONG, r.randint(1, n // 3 + 2, n)),
        "ss_quantity": (T.INT, qty.astype(np.int32)),
        "ss_sales_price": (T.DOUBLE, price),
        "ss_ext_sales_price": (T.DOUBLE, (price * qty).round(2)),
        "ss_ext_discount_amt": (T.DOUBLE, (r.rand(n) * 100).round(2)),
        "ss_net_profit": (T.DOUBLE, ((r.rand(n) - 0.3) * 500).round(2)),
    }


def gen_store_returns(sf: float, seed: int = 26) -> Dict:
    n = max(20, int(sf * 10_000))
    r = np.random.RandomState(seed)
    n_item = max(10, int(sf * 2_000))
    n_cust = max(10, int(sf * 1_000))
    return {
        "sr_returned_date_sk": (T.LONG, r.randint(1, 731, n)),
        "sr_item_sk": (T.LONG, r.randint(1, n_item + 1, n)),
        "sr_customer_sk": (T.LONG, r.randint(1, n_cust + 1, n)),
        "sr_return_quantity": (T.INT, r.randint(1, 30, n).astype(np.int32)),
        "sr_return_amt": (T.DOUBLE, (r.rand(n) * 300).round(2)),
    }


def register_tpcds(session, sf: float = 0.1, num_partitions: int = 4):
    tables = {
        "store_sales": gen_store_sales(sf),
        "store_returns": gen_store_returns(sf),
        "item": gen_item(sf),
        "customer": gen_customer(sf),
        "date_dim": gen_date_dim(),
        "store": gen_store(),
        "promotion": gen_promotion(),
    }
    for name, data in tables.items():
        df = session.create_dataframe(data, num_partitions=num_partitions)
        session.register_view(name, df)


# -- queries (TpcdsLikeSpark adaptation) ------------------------------------

Q3 = """
SELECT d_year, i_brand, sum(ss_ext_sales_price) AS sum_agg
FROM store_sales
JOIN date_dim ON d_date_sk = ss_sold_date_sk
JOIN item ON i_item_sk = ss_item_sk
WHERE i_manufact_id = 52 AND d_moy = 11
GROUP BY d_year, i_brand
ORDER BY d_year, sum_agg DESC, i_brand
LIMIT 100
"""

Q7 = """
SELECT i_category,
       avg(ss_quantity) AS agg1,
       avg(ss_sales_price) AS agg2,
       avg(ss_ext_sales_price) AS agg3,
       avg(ss_ext_discount_amt) AS agg4
FROM store_sales
JOIN customer ON c_customer_sk = ss_customer_sk
JOIN item ON i_item_sk = ss_item_sk
WHERE c_education = 'College' AND c_birth_year < 1970
GROUP BY i_category
ORDER BY i_category
"""

Q42 = """
SELECT d_year, i_category, sum(ss_ext_sales_price) AS total
FROM store_sales
JOIN date_dim ON d_date_sk = ss_sold_date_sk
JOIN item ON i_item_sk = ss_item_sk
WHERE d_moy = 12 AND i_current_price > 50
GROUP BY d_year, i_category
ORDER BY total DESC, d_year, i_category
LIMIT 100
"""

Q52 = """
SELECT d_year, i_brand, sum(ss_ext_sales_price) AS ext_price
FROM store_sales
JOIN date_dim ON d_date_sk = ss_sold_date_sk
JOIN item ON i_item_sk = ss_item_sk
WHERE d_moy = 11 AND d_year = 1998
GROUP BY d_year, i_brand
ORDER BY d_year, ext_price DESC, i_brand
LIMIT 100
"""

Q55 = """
SELECT i_brand, sum(ss_ext_sales_price) AS ext_price
FROM store_sales
JOIN date_dim ON d_date_sk = ss_sold_date_sk
JOIN item ON i_item_sk = ss_item_sk
WHERE d_moy = 6 AND d_year = 1999
GROUP BY i_brand
ORDER BY ext_price DESC, i_brand
LIMIT 100
"""

Q65 = """
SELECT s_state, i_category, sum(ss_net_profit) AS profit
FROM store_sales
JOIN store ON s_store_sk = ss_store_sk
JOIN item ON i_item_sk = ss_item_sk
GROUP BY s_state, i_category
HAVING sum(ss_net_profit) > 0
ORDER BY s_state, profit DESC
"""

Q13 = """
SELECT avg(ss_quantity) AS avg_qty,
       avg(ss_ext_sales_price) AS avg_price,
       sum(ss_ext_discount_amt) AS total_disc
FROM store_sales
JOIN store ON s_store_sk = ss_store_sk
JOIN customer ON c_customer_sk = ss_customer_sk
WHERE s_state IN ('CA', 'TX')
  AND c_education IN ('College', '4 yr Degree')
  AND ss_sales_price BETWEEN 50 AND 150
"""

Q19 = """
SELECT i_brand, i_manufact_id, sum(ss_ext_sales_price) AS ext_price
FROM store_sales
JOIN date_dim ON d_date_sk = ss_sold_date_sk
JOIN item ON i_item_sk = ss_item_sk
JOIN customer ON c_customer_sk = ss_customer_sk
JOIN store ON s_store_sk = ss_store_sk
WHERE d_moy = 11 AND d_year = 1998 AND i_manufact_id < 40
  AND c_state <> s_state
GROUP BY i_brand, i_manufact_id
ORDER BY ext_price DESC, i_brand, i_manufact_id
LIMIT 100
"""

Q26 = """
SELECT i_category,
       avg(ss_quantity) AS agg1,
       avg(ss_sales_price) AS agg2
FROM store_sales
JOIN promotion ON p_promo_sk = ss_promo_sk
JOIN item ON i_item_sk = ss_item_sk
WHERE p_channel_email = 'N' OR p_channel_event = 'N'
GROUP BY i_category
ORDER BY i_category
"""

Q29 = """
SELECT i_category,
       sum(ss_quantity) AS sold,
       sum(sr_return_quantity) AS returned
FROM store_sales
JOIN store_returns ON sr_item_sk = ss_item_sk
  AND sr_customer_sk = ss_customer_sk
JOIN item ON i_item_sk = ss_item_sk
GROUP BY i_category
ORDER BY i_category
"""

Q36 = """
SELECT i_category, profit,
       rank() OVER (ORDER BY profit DESC) AS rk
FROM (
  SELECT i_category, sum(ss_net_profit) AS profit
  FROM store_sales
  JOIN item ON i_item_sk = ss_item_sk
  GROUP BY i_category
)
ORDER BY rk, i_category
"""

Q43 = """
SELECT s_state, d_moy, sum(ss_ext_sales_price) AS total
FROM store_sales
JOIN date_dim ON d_date_sk = ss_sold_date_sk
JOIN store ON s_store_sk = ss_store_sk
WHERE d_year = 1998
GROUP BY s_state, d_moy
ORDER BY s_state, d_moy
"""

Q48 = """
SELECT sum(CASE WHEN ss_quantity BETWEEN 1 AND 20 THEN 1 ELSE 0 END)
         AS bucket1,
       sum(CASE WHEN ss_quantity BETWEEN 21 AND 40 THEN 1 ELSE 0 END)
         AS bucket2,
       sum(CASE WHEN ss_quantity BETWEEN 41 AND 100 THEN 1 ELSE 0 END)
         AS bucket3
FROM store_sales
JOIN store ON s_store_sk = ss_store_sk
WHERE s_state IN ('CA', 'NY', 'TX')
"""

Q53 = """
SELECT i_manufact_id, d_moy, sum_sales,
       avg(sum_sales) OVER (PARTITION BY i_manufact_id)
         AS avg_manufact_sales
FROM (
  SELECT i_manufact_id, d_moy, sum(ss_sales_price) AS sum_sales
  FROM store_sales
  JOIN item ON i_item_sk = ss_item_sk
  JOIN date_dim ON d_date_sk = ss_sold_date_sk
  WHERE d_year = 1999 AND i_manufact_id < 20
  GROUP BY i_manufact_id, d_moy
)
ORDER BY i_manufact_id, d_moy
"""

Q59 = """
SELECT y1.s_state, y1.total AS sales_1998, y2.total AS sales_1999
FROM (
  SELECT s_state, sum(ss_ext_sales_price) AS total
  FROM store_sales
  JOIN date_dim ON d_date_sk = ss_sold_date_sk
  JOIN store ON s_store_sk = ss_store_sk
  WHERE d_year = 1998
  GROUP BY s_state
) y1
JOIN (
  SELECT s_state, sum(ss_ext_sales_price) AS total
  FROM store_sales
  JOIN date_dim ON d_date_sk = ss_sold_date_sk
  JOIN store ON s_store_sk = ss_store_sk
  WHERE d_year = 1999
  GROUP BY s_state
) y2 ON y1.s_state = y2.s_state
ORDER BY y1.s_state
"""

Q61 = """
SELECT p.s_state, p.promo_sales, t.total_sales
FROM (
  SELECT s_state, sum(ss_ext_sales_price) AS promo_sales
  FROM store_sales
  JOIN store ON s_store_sk = ss_store_sk
  JOIN promotion ON p_promo_sk = ss_promo_sk
  WHERE p_channel_email = 'Y' OR p_channel_event = 'Y'
  GROUP BY s_state
) p
JOIN (
  SELECT s_state, sum(ss_ext_sales_price) AS total_sales
  FROM store_sales
  JOIN store ON s_store_sk = ss_store_sk
  GROUP BY s_state
) t ON p.s_state = t.s_state
ORDER BY p.s_state
"""

Q68 = """
SELECT ss_ticket_number, ss_customer_sk,
       sum(ss_ext_sales_price) AS amt,
       sum(ss_net_profit) AS profit
FROM store_sales
JOIN store ON s_store_sk = ss_store_sk
WHERE s_state = 'CA'
GROUP BY ss_ticket_number, ss_customer_sk
HAVING sum(ss_ext_sales_price) > 500
ORDER BY ss_ticket_number, ss_customer_sk
LIMIT 100
"""

Q73 = """
SELECT c_state, count(DISTINCT ss_customer_sk) AS buyers,
       count(*) AS line_items
FROM store_sales
JOIN customer ON c_customer_sk = ss_customer_sk
GROUP BY c_state
ORDER BY c_state
"""

Q79 = """
SELECT s_state, ss_customer_sk, sum(ss_net_profit) AS profit
FROM store_sales
JOIN store ON s_store_sk = ss_store_sk
JOIN date_dim ON d_date_sk = ss_sold_date_sk
WHERE d_moy BETWEEN 1 AND 3
GROUP BY s_state, ss_customer_sk
HAVING sum(ss_net_profit) > 300
ORDER BY s_state, profit DESC, ss_customer_sk
LIMIT 100
"""

Q89 = """
SELECT i_category, d_moy, sum_sales, avg_monthly_sales
FROM (
  SELECT i_category, d_moy, sum_sales,
         avg(sum_sales) OVER (PARTITION BY i_category)
           AS avg_monthly_sales
  FROM (
    SELECT i_category, d_moy, sum(ss_sales_price) AS sum_sales
    FROM store_sales
    JOIN item ON i_item_sk = ss_item_sk
    JOIN date_dim ON d_date_sk = ss_sold_date_sk
    WHERE d_year = 1998
    GROUP BY i_category, d_moy
  )
)
WHERE sum_sales > avg_monthly_sales
ORDER BY i_category, d_moy
"""

Q98 = """
SELECT i_category, i_brand, itemrevenue,
       itemrevenue * 100.0 / cat_rev AS revenueratio
FROM (
  SELECT i_category, i_brand, itemrevenue,
         sum(itemrevenue) OVER (PARTITION BY i_category) AS cat_rev
  FROM (
    SELECT i_category, i_brand, sum(ss_ext_sales_price) AS itemrevenue
    FROM store_sales
    JOIN item ON i_item_sk = ss_item_sk
    JOIN date_dim ON d_date_sk = ss_sold_date_sk
    WHERE d_year = 1999
    GROUP BY i_category, i_brand
  )
)
ORDER BY i_category, i_brand
"""

Q14 = """
SELECT channel, i_category, sum(sales) AS total_sales,
       count(*) AS groups_n
FROM (
  SELECT 'first_half' AS channel, i_category,
         sum(ss_ext_sales_price) AS sales
  FROM store_sales
  JOIN item ON i_item_sk = ss_item_sk
  JOIN date_dim ON d_date_sk = ss_sold_date_sk
  WHERE d_moy BETWEEN 1 AND 6
  GROUP BY i_category
  UNION ALL
  SELECT 'second_half' AS channel, i_category,
         sum(ss_ext_sales_price) AS sales
  FROM store_sales
  JOIN item ON i_item_sk = ss_item_sk
  JOIN date_dim ON d_date_sk = ss_sold_date_sk
  WHERE d_moy BETWEEN 7 AND 12
  GROUP BY i_category
)
GROUP BY channel, i_category
ORDER BY channel, i_category
"""

Q2 = """
SELECT m1.d_moy, m1.total AS total_1998, m2.total AS total_1999,
       m2.total / m1.total AS growth
FROM (
  SELECT d_moy, sum(ss_ext_sales_price) AS total
  FROM store_sales
  JOIN date_dim ON d_date_sk = ss_sold_date_sk
  WHERE d_year = 1998
  GROUP BY d_moy
) m1
JOIN (
  SELECT d_moy, sum(ss_ext_sales_price) AS total
  FROM store_sales
  JOIN date_dim ON d_date_sk = ss_sold_date_sk
  WHERE d_year = 1999
  GROUP BY d_moy
) m2 ON m1.d_moy = m2.d_moy
ORDER BY m1.d_moy
"""

Q22 = """
SELECT i_category, i_brand, avg(ss_quantity) AS qoh
FROM store_sales
JOIN item ON i_item_sk = ss_item_sk
GROUP BY i_category, i_brand
UNION ALL
SELECT i_category, 'ALL' AS i_brand, avg(ss_quantity) AS qoh
FROM store_sales
JOIN item ON i_item_sk = ss_item_sk
GROUP BY i_category
ORDER BY i_category, i_brand, qoh
"""

Q25 = """
SELECT i_category, s_state,
       sum(ss_net_profit) AS profit,
       min(ss_net_profit) AS min_profit,
       max(ss_net_profit) AS max_profit
FROM store_sales
JOIN item ON i_item_sk = ss_item_sk
JOIN store ON s_store_sk = ss_store_sk
WHERE ss_quantity > 10
GROUP BY i_category, s_state
ORDER BY i_category, s_state
"""

Q33 = """
SELECT i_manufact_id, sum(total_sales) AS total_sales
FROM (
  SELECT i_manufact_id, sum(ss_ext_sales_price) AS total_sales
  FROM store_sales
  JOIN item ON i_item_sk = ss_item_sk
  JOIN date_dim ON d_date_sk = ss_sold_date_sk
  WHERE d_moy = 1
  GROUP BY i_manufact_id
  UNION ALL
  SELECT i_manufact_id, sum(ss_ext_sales_price) AS total_sales
  FROM store_sales
  JOIN item ON i_item_sk = ss_item_sk
  JOIN date_dim ON d_date_sk = ss_sold_date_sk
  WHERE d_moy = 2
  GROUP BY i_manufact_id
  UNION ALL
  SELECT i_manufact_id, sum(ss_ext_sales_price) AS total_sales
  FROM store_sales
  JOIN item ON i_item_sk = ss_item_sk
  JOIN date_dim ON d_date_sk = ss_sold_date_sk
  WHERE d_moy = 3
  GROUP BY i_manufact_id
)
GROUP BY i_manufact_id
ORDER BY total_sales DESC, i_manufact_id
LIMIT 100
"""

Q34 = """
SELECT c_state, count(*) AS frequent_buyers
FROM customer
LEFT SEMI JOIN (
  SELECT ss_customer_sk
  FROM store_sales
  GROUP BY ss_customer_sk
  HAVING count(*) > 15
) f ON c_customer_sk = ss_customer_sk
GROUP BY c_state
ORDER BY c_state
"""

Q51 = """
SELECT i_category, d_moy, sum_sales,
       sum(sum_sales) OVER (PARTITION BY i_category ORDER BY d_moy
                            ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW)
         AS cume_sales
FROM (
  SELECT i_category, d_moy, sum(ss_sales_price) AS sum_sales
  FROM store_sales
  JOIN item ON i_item_sk = ss_item_sk
  JOIN date_dim ON d_date_sk = ss_sold_date_sk
  WHERE d_year = 1998
  GROUP BY i_category, d_moy
)
ORDER BY i_category, d_moy
"""

Q92 = """
SELECT i_category, count(*) AS premium_items
FROM item
JOIN (
  SELECT i_category AS cat, avg(i_current_price) AS avg_price
  FROM item
  GROUP BY i_category
) a ON i_category = cat
WHERE i_current_price > avg_price * 1.2
GROUP BY i_category
ORDER BY i_category
"""

Q93 = """
SELECT ss_customer_sk, sum(act_sales) AS sumsales
FROM (
  SELECT ss_customer_sk,
         CASE WHEN sr_return_quantity IS NOT NULL
              THEN (ss_quantity - sr_return_quantity) * ss_sales_price
              ELSE ss_quantity * ss_sales_price END AS act_sales
  FROM store_sales
  LEFT JOIN store_returns ON sr_item_sk = ss_item_sk
    AND sr_customer_sk = ss_customer_sk
)
GROUP BY ss_customer_sk
ORDER BY sumsales DESC, ss_customer_sk
LIMIT 100
"""

Q38 = """
SELECT count(*) AS common_customers
FROM (
  SELECT ss_customer_sk FROM store_sales
  JOIN date_dim ON d_date_sk = ss_sold_date_sk
  WHERE d_moy BETWEEN 1 AND 6
  INTERSECT
  SELECT ss_customer_sk FROM store_sales
  JOIN date_dim ON d_date_sk = ss_sold_date_sk
  WHERE d_moy BETWEEN 7 AND 12
)
"""

Q87 = """
SELECT count(*) AS never_returned
FROM (
  SELECT ss_customer_sk FROM store_sales
  EXCEPT
  SELECT sr_customer_sk FROM store_returns
)
"""

Q67 = """
SELECT i_category, i_brand, s_state, sum(ss_ext_sales_price) AS sales
FROM store_sales
JOIN item ON i_item_sk = ss_item_sk
JOIN store ON s_store_sk = ss_store_sk
GROUP BY ROLLUP(i_category, i_brand, s_state)
ORDER BY i_category, i_brand, s_state, sales
LIMIT 200
"""

QUERIES = {"q3": Q3, "q7": Q7, "q13": Q13, "q14": Q14, "q19": Q19,
           "q26": Q26, "q29": Q29, "q36": Q36, "q42": Q42, "q43": Q43,
           "q48": Q48, "q52": Q52, "q53": Q53, "q55": Q55, "q59": Q59,
           "q61": Q61, "q65": Q65, "q68": Q68, "q73": Q73, "q79": Q79,
           "q89": Q89, "q98": Q98,
           "q2": Q2, "q22": Q22, "q25": Q25, "q33": Q33,
           "q34": Q34, "q51": Q51, "q92": Q92, "q93": Q93,
           "q38": Q38, "q87": Q87, "q67": Q67}
