"""TPC-DS-like star-schema benchmark: synthetic store_sales fact + item /
date_dim / customer / store dimensions, and query definitions shaped like
the TPC-DS reporting set (TpcdsLikeSpark analogue,
integration_tests/.../TpcdsLikeSpark.scala — adapted to the engine's
type/op envelope the same way TpchLike is).

Query shapes covered: dimension-filtered fact scans with multi-way joins,
group-by + order-by + limit reporting rollups (q3/q42/q52/q55 family),
multi-aggregate demographic profiles (q7), and a two-level aggregation with
a HAVING-style post-filter (q65 family).
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from spark_rapids_tpu import types as T

BRANDS = [f"brand#{i}" for i in range(1, 21)]
CATEGORIES = ["Books", "Electronics", "Home", "Jewelry", "Men", "Music",
              "Shoes", "Sports", "Toys", "Women"]
STATES = ["CA", "GA", "IL", "NY", "TX", "WA"]
EDU = ["Primary", "Secondary", "College", "2 yr Degree", "4 yr Degree"]

# date_dim spans 1998-1999 weekly granularity style: d_date_sk is a dense key


def gen_date_dim() -> Dict:
    n = 730  # two years of days
    sk = np.arange(1, n + 1)
    year = np.where(sk <= 365, 1998, 1999)
    doy = np.where(sk <= 365, sk, sk - 365)
    moy = np.minimum((doy - 1) // 30 + 1, 12)
    return {
        "d_date_sk": (T.LONG, sk),
        "d_year": (T.INT, year.astype(np.int32)),
        "d_moy": (T.INT, moy.astype(np.int32)),
        "d_dom": (T.INT, ((doy - 1) % 30 + 1).astype(np.int32)),
    }


def gen_item(sf: float, seed: int = 21) -> Dict:
    n = max(10, int(sf * 2_000))
    r = np.random.RandomState(seed)
    return {
        "i_item_sk": (T.LONG, np.arange(1, n + 1)),
        "i_brand": (T.STRING, r.choice(BRANDS, n)),
        "i_category": (T.STRING, r.choice(CATEGORIES, n)),
        "i_manufact_id": (T.INT, r.randint(1, 100, n).astype(np.int32)),
        "i_current_price": (T.DOUBLE, (r.rand(n) * 99 + 1).round(2)),
    }


def gen_customer(sf: float, seed: int = 22) -> Dict:
    n = max(10, int(sf * 1_000))
    r = np.random.RandomState(seed)
    return {
        "c_customer_sk": (T.LONG, np.arange(1, n + 1)),
        "c_birth_year": (T.INT, r.randint(1924, 1992, n).astype(np.int32)),
        "c_education": (T.STRING, r.choice(EDU, n)),
        "c_state": (T.STRING, r.choice(STATES, n)),
    }


def gen_store(seed: int = 23) -> Dict:
    n = 12
    r = np.random.RandomState(seed)
    return {
        "s_store_sk": (T.LONG, np.arange(1, n + 1)),
        "s_state": (T.STRING, r.choice(STATES, n)),
    }


def gen_store_sales(sf: float, seed: int = 24) -> Dict:
    n = max(100, int(sf * 100_000))
    r = np.random.RandomState(seed)
    n_item = max(10, int(sf * 2_000))
    n_cust = max(10, int(sf * 1_000))
    price = (r.rand(n) * 200 + 1).round(2)
    qty = r.randint(1, 101, n)
    return {
        "ss_sold_date_sk": (T.LONG, r.randint(1, 731, n)),
        "ss_item_sk": (T.LONG, r.randint(1, n_item + 1, n)),
        "ss_customer_sk": (T.LONG, r.randint(1, n_cust + 1, n)),
        "ss_store_sk": (T.LONG, r.randint(1, 13, n)),
        "ss_quantity": (T.INT, qty.astype(np.int32)),
        "ss_sales_price": (T.DOUBLE, price),
        "ss_ext_sales_price": (T.DOUBLE, (price * qty).round(2)),
        "ss_ext_discount_amt": (T.DOUBLE, (r.rand(n) * 100).round(2)),
        "ss_net_profit": (T.DOUBLE, ((r.rand(n) - 0.3) * 500).round(2)),
    }


def register_tpcds(session, sf: float = 0.1, num_partitions: int = 4):
    tables = {
        "store_sales": gen_store_sales(sf),
        "item": gen_item(sf),
        "customer": gen_customer(sf),
        "date_dim": gen_date_dim(),
        "store": gen_store(),
    }
    for name, data in tables.items():
        df = session.create_dataframe(data, num_partitions=num_partitions)
        session.register_view(name, df)


# -- queries (TpcdsLikeSpark adaptation) ------------------------------------

Q3 = """
SELECT d_year, i_brand, sum(ss_ext_sales_price) AS sum_agg
FROM store_sales
JOIN date_dim ON d_date_sk = ss_sold_date_sk
JOIN item ON i_item_sk = ss_item_sk
WHERE i_manufact_id = 52 AND d_moy = 11
GROUP BY d_year, i_brand
ORDER BY d_year, sum_agg DESC, i_brand
LIMIT 100
"""

Q7 = """
SELECT i_category,
       avg(ss_quantity) AS agg1,
       avg(ss_sales_price) AS agg2,
       avg(ss_ext_sales_price) AS agg3,
       avg(ss_ext_discount_amt) AS agg4
FROM store_sales
JOIN customer ON c_customer_sk = ss_customer_sk
JOIN item ON i_item_sk = ss_item_sk
WHERE c_education = 'College' AND c_birth_year < 1970
GROUP BY i_category
ORDER BY i_category
"""

Q42 = """
SELECT d_year, i_category, sum(ss_ext_sales_price) AS total
FROM store_sales
JOIN date_dim ON d_date_sk = ss_sold_date_sk
JOIN item ON i_item_sk = ss_item_sk
WHERE d_moy = 12 AND i_current_price > 50
GROUP BY d_year, i_category
ORDER BY total DESC, d_year, i_category
LIMIT 100
"""

Q52 = """
SELECT d_year, i_brand, sum(ss_ext_sales_price) AS ext_price
FROM store_sales
JOIN date_dim ON d_date_sk = ss_sold_date_sk
JOIN item ON i_item_sk = ss_item_sk
WHERE d_moy = 11 AND d_year = 1998
GROUP BY d_year, i_brand
ORDER BY d_year, ext_price DESC, i_brand
LIMIT 100
"""

Q55 = """
SELECT i_brand, sum(ss_ext_sales_price) AS ext_price
FROM store_sales
JOIN date_dim ON d_date_sk = ss_sold_date_sk
JOIN item ON i_item_sk = ss_item_sk
WHERE d_moy = 6 AND d_year = 1999
GROUP BY i_brand
ORDER BY ext_price DESC, i_brand
LIMIT 100
"""

Q65 = """
SELECT s_state, i_category, sum(ss_net_profit) AS profit
FROM store_sales
JOIN store ON s_store_sk = ss_store_sk
JOIN item ON i_item_sk = ss_item_sk
GROUP BY s_state, i_category
HAVING sum(ss_net_profit) > 0
ORDER BY s_state, profit DESC
"""

QUERIES = {"q3": Q3, "q7": Q7, "q42": Q42, "q52": Q52, "q55": Q55,
           "q65": Q65}
