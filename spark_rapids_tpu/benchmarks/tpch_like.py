"""TPC-H-like query definitions (TpchLikeSpark analogue — queries adapted to
the supported type/op envelope, same shapes: scan-heavy aggregation, multi-way
joins, group-by + order-by)."""

from __future__ import annotations

# date literals as days-since-epoch: 1994-01-01 = 8766, 1995-01-01 = 9131,
# 1998-09-02 = 10471, 1995-03-15 = 9204
Q1 = """
SELECT l_returnflag, l_linestatus,
       sum(l_quantity) AS sum_qty,
       sum(l_extendedprice) AS sum_base_price,
       avg(l_quantity) AS avg_qty,
       avg(l_extendedprice) AS avg_price,
       avg(l_discount) AS avg_disc,
       count(*) AS count_order
FROM lineitem
WHERE l_shipdate <= 10471
GROUP BY l_returnflag, l_linestatus
ORDER BY l_returnflag, l_linestatus
"""

Q3 = """
SELECT o_orderkey, o_orderdate, o_shippriority,
       sum(l_extendedprice) AS revenue
FROM customer
JOIN orders ON c_custkey = o_custkey
JOIN lineitem ON l_orderkey = o_orderkey
WHERE c_mktsegment = 'BUILDING'
  AND o_orderdate < 9204
  AND l_shipdate > 9204
GROUP BY o_orderkey, o_orderdate, o_shippriority
ORDER BY revenue DESC, o_orderdate
LIMIT 10
"""

Q5 = """
SELECT n_name, sum(l_extendedprice) AS revenue
FROM customer
JOIN orders ON c_custkey = o_custkey
JOIN lineitem ON l_orderkey = o_orderkey
JOIN supplier ON l_suppkey = s_suppkey
JOIN nation ON s_nationkey = n_nationkey
WHERE o_orderdate >= 8766 AND o_orderdate < 9131
GROUP BY n_name
ORDER BY revenue DESC
"""

Q6 = """
SELECT sum(l_extendedprice) AS revenue
FROM lineitem
WHERE l_shipdate >= 8766 AND l_shipdate < 9131
  AND l_discount BETWEEN 0.05 AND 0.07
  AND l_quantity < 24
"""

Q10 = """
SELECT c_custkey, c_name, sum(l_extendedprice) AS revenue, c_acctbal
FROM customer
JOIN orders ON c_custkey = o_custkey
JOIN lineitem ON l_orderkey = o_orderkey
WHERE o_orderdate >= 8766 AND o_orderdate < 8766 + 90
  AND l_returnflag = 'R'
GROUP BY c_custkey, c_name, c_acctbal
ORDER BY revenue DESC
LIMIT 20
"""

Q12 = """
SELECT l_shipmode, count(*) AS mode_count
FROM orders
JOIN lineitem ON o_orderkey = l_orderkey
WHERE l_shipmode IN ('MAIL', 'SHIP')
  AND l_commitdate < l_receiptdate
  AND l_shipdate < l_commitdate
  AND l_receiptdate >= 8766 AND l_receiptdate < 9131
GROUP BY l_shipmode
ORDER BY l_shipmode
"""

Q14 = """
SELECT sum(l_extendedprice) AS promo_revenue
FROM lineitem
WHERE l_shipdate >= 9131 AND l_shipdate < 9161 AND l_discount > 0.02
"""

QUERIES = {"q1": Q1, "q3": Q3, "q5": Q5, "q6": Q6, "q10": Q10, "q12": Q12,
           "q14": Q14}
