"""TPC-H-like query definitions, all 22 (TpchLikeSpark analogue — queries
adapted to the supported type/op envelope: date literals as days-since-epoch,
correlated/EXISTS/IN subqueries hand-decorrelated into joins against
aggregated subqueries or LEFT SEMI / LEFT ANTI joins, scalar subqueries via
CROSS JOIN of one-row aggregates, post-aggregate arithmetic through nested
subqueries)."""

from __future__ import annotations

# date literals as days-since-epoch: 1994-01-01 = 8766, 1995-01-01 = 9131,
# 1998-09-02 = 10471, 1995-03-15 = 9204
Q1 = """
SELECT l_returnflag, l_linestatus,
       sum(l_quantity) AS sum_qty,
       sum(l_extendedprice) AS sum_base_price,
       avg(l_quantity) AS avg_qty,
       avg(l_extendedprice) AS avg_price,
       avg(l_discount) AS avg_disc,
       count(*) AS count_order
FROM lineitem
WHERE l_shipdate <= 10471
GROUP BY l_returnflag, l_linestatus
ORDER BY l_returnflag, l_linestatus
"""

Q3 = """
SELECT o_orderkey, o_orderdate, o_shippriority,
       sum(l_extendedprice) AS revenue
FROM customer
JOIN orders ON c_custkey = o_custkey
JOIN lineitem ON l_orderkey = o_orderkey
WHERE c_mktsegment = 'BUILDING'
  AND o_orderdate < 9204
  AND l_shipdate > 9204
GROUP BY o_orderkey, o_orderdate, o_shippriority
ORDER BY revenue DESC, o_orderdate
LIMIT 10
"""

Q5 = """
SELECT n_name, sum(l_extendedprice) AS revenue
FROM customer
JOIN orders ON c_custkey = o_custkey
JOIN lineitem ON l_orderkey = o_orderkey
JOIN supplier ON l_suppkey = s_suppkey
JOIN nation ON s_nationkey = n_nationkey
WHERE o_orderdate >= 8766 AND o_orderdate < 9131
GROUP BY n_name
ORDER BY revenue DESC
"""

Q6 = """
SELECT sum(l_extendedprice) AS revenue
FROM lineitem
WHERE l_shipdate >= 8766 AND l_shipdate < 9131
  AND l_discount BETWEEN 0.05 AND 0.07
  AND l_quantity < 24
"""

Q10 = """
SELECT c_custkey, c_name, sum(l_extendedprice) AS revenue, c_acctbal
FROM customer
JOIN orders ON c_custkey = o_custkey
JOIN lineitem ON l_orderkey = o_orderkey
WHERE o_orderdate >= 8766 AND o_orderdate < 8766 + 90
  AND l_returnflag = 'R'
GROUP BY c_custkey, c_name, c_acctbal
ORDER BY revenue DESC
LIMIT 20
"""

Q12 = """
SELECT l_shipmode, count(*) AS mode_count
FROM orders
JOIN lineitem ON o_orderkey = l_orderkey
WHERE l_shipmode IN ('MAIL', 'SHIP')
  AND l_commitdate < l_receiptdate
  AND l_shipdate < l_commitdate
  AND l_receiptdate >= 8766 AND l_receiptdate < 9131
GROUP BY l_shipmode
ORDER BY l_shipmode
"""

Q14 = """
SELECT sum(l_extendedprice) AS promo_revenue
FROM lineitem
WHERE l_shipdate >= 9131 AND l_shipdate < 9161 AND l_discount > 0.02
"""

Q2 = """
SELECT s_acctbal, s_name, n_name, p_partkey, p_mfgr
FROM part
JOIN partsupp ON p_partkey = ps_partkey
JOIN supplier ON s_suppkey = ps_suppkey
JOIN nation ON s_nationkey = n_nationkey
JOIN region ON n_regionkey = r_regionkey
JOIN (
  SELECT ps_partkey AS mpk, min(ps_supplycost) AS min_cost
  FROM partsupp
  JOIN supplier ON s_suppkey = ps_suppkey
  JOIN nation ON s_nationkey = n_nationkey
  JOIN region ON n_regionkey = r_regionkey
  WHERE r_name = 'EUROPE'
  GROUP BY ps_partkey
) mc ON p_partkey = mpk AND ps_supplycost = min_cost
WHERE p_size = 15 AND r_name = 'EUROPE'
ORDER BY s_acctbal DESC, n_name, s_name, p_partkey
LIMIT 100
"""

Q4 = """
SELECT o_orderpriority, count(*) AS order_count
FROM orders
LEFT SEMI JOIN lineitem ON l_orderkey = o_orderkey
  AND l_commitdate < l_receiptdate
WHERE o_orderdate >= 8582 AND o_orderdate < 8674
GROUP BY o_orderpriority
ORDER BY o_orderpriority
"""

Q7 = """
SELECT supp_nation, cust_nation, year(l_shipdate) AS l_year,
       sum(l_extendedprice) AS revenue
FROM lineitem
JOIN supplier ON s_suppkey = l_suppkey
JOIN orders ON o_orderkey = l_orderkey
JOIN customer ON c_custkey = o_custkey
JOIN (SELECT n_nationkey AS snk, n_name AS supp_nation FROM nation) nx
  ON s_nationkey = snk
JOIN (SELECT n_nationkey AS cnk, n_name AS cust_nation FROM nation) ny
  ON c_nationkey = cnk
WHERE ((supp_nation = 'FRANCE' AND cust_nation = 'GERMANY')
    OR (supp_nation = 'GERMANY' AND cust_nation = 'FRANCE'))
  AND l_shipdate BETWEEN 9131 AND 9861
GROUP BY supp_nation, cust_nation, year(l_shipdate)
ORDER BY supp_nation, cust_nation, l_year
"""

Q8 = """
SELECT o_year, brazil_rev / total_rev AS mkt_share
FROM (
  SELECT o_year,
         sum(brazil_volume) AS brazil_rev,
         sum(volume) AS total_rev
  FROM (
    SELECT year(o_orderdate) AS o_year,
           l_extendedprice AS volume,
           CASE WHEN n2name = 'BRAZIL' THEN l_extendedprice
                ELSE 0.0 END AS brazil_volume
    FROM lineitem
    JOIN part ON p_partkey = l_partkey
    JOIN supplier ON s_suppkey = l_suppkey
    JOIN orders ON o_orderkey = l_orderkey
    JOIN customer ON c_custkey = o_custkey
    JOIN (SELECT n_nationkey AS cnk, n_regionkey AS crk FROM nation) n1
      ON c_nationkey = cnk
    JOIN region ON crk = r_regionkey
    JOIN (SELECT n_nationkey AS snk, n_name AS n2name FROM nation) n2
      ON s_nationkey = snk
    WHERE r_name = 'AMERICA'
      AND o_orderdate BETWEEN 9131 AND 9861
      AND p_size < 30
  )
  GROUP BY o_year
)
ORDER BY o_year
"""

Q9 = """
SELECT n_name, year(o_orderdate) AS o_year,
       sum(l_extendedprice * (1 - l_discount)
           - ps_supplycost * l_quantity) AS profit
FROM lineitem
JOIN supplier ON s_suppkey = l_suppkey
JOIN partsupp ON ps_suppkey = l_suppkey AND ps_partkey = l_partkey
JOIN part ON p_partkey = l_partkey
JOIN orders ON o_orderkey = l_orderkey
JOIN nation ON s_nationkey = n_nationkey
WHERE p_name LIKE '%green%'
GROUP BY n_name, year(o_orderdate)
ORDER BY n_name, o_year DESC
"""

Q11 = """
SELECT ps_partkey, value
FROM (
  SELECT ps_partkey, sum(ps_supplycost * ps_availqty) AS value
  FROM partsupp
  JOIN supplier ON s_suppkey = ps_suppkey
  JOIN nation ON s_nationkey = n_nationkey
  WHERE n_name = 'GERMANY'
  GROUP BY ps_partkey
)
CROSS JOIN (
  SELECT sum(ps_supplycost * ps_availqty) AS total
  FROM partsupp
  JOIN supplier ON s_suppkey = ps_suppkey
  JOIN nation ON s_nationkey = n_nationkey
  WHERE n_name = 'GERMANY'
)
WHERE value > total * 0.0001
ORDER BY value DESC, ps_partkey
"""

Q13 = """
SELECT c_count, count(*) AS custdist
FROM (
  SELECT c_custkey, count(o_orderkey) AS c_count
  FROM customer
  LEFT JOIN orders ON c_custkey = o_custkey
    AND o_orderpriority <> '1-URGENT'
  GROUP BY c_custkey
)
GROUP BY c_count
ORDER BY custdist DESC, c_count DESC
"""

Q15 = """
SELECT s_suppkey, s_name, total_revenue
FROM supplier
JOIN (
  SELECT l_suppkey AS rsk, sum(l_extendedprice) AS total_revenue
  FROM lineitem
  WHERE l_shipdate >= 9496 AND l_shipdate < 9587
  GROUP BY l_suppkey
) r ON s_suppkey = rsk
CROSS JOIN (
  SELECT max(total_revenue) AS max_rev
  FROM (
    SELECT sum(l_extendedprice) AS total_revenue
    FROM lineitem
    WHERE l_shipdate >= 9496 AND l_shipdate < 9587
    GROUP BY l_suppkey
  )
)
WHERE abs(total_revenue - max_rev) <= max_rev * 0.000001
ORDER BY s_suppkey
"""

Q16 = """
SELECT p_brand, p_type, p_size, count(DISTINCT ps_suppkey) AS supplier_cnt
FROM partsupp
JOIN part ON p_partkey = ps_partkey
LEFT ANTI JOIN (
  SELECT s_suppkey FROM supplier WHERE s_name LIKE '%0000009%'
) bad ON ps_suppkey = s_suppkey
WHERE p_brand <> 'Brand#45' AND p_size IN (1, 4, 7, 10, 15)
GROUP BY p_brand, p_type, p_size
ORDER BY supplier_cnt DESC, p_brand, p_type, p_size
LIMIT 100
"""

Q17 = """
SELECT total / 7.0 AS avg_yearly
FROM (
  SELECT sum(l_extendedprice) AS total
  FROM lineitem
  JOIN part ON p_partkey = l_partkey
  JOIN (
    SELECT l_partkey AS apk, avg(l_quantity) AS avg_qty
    FROM lineitem
    GROUP BY l_partkey
  ) a ON l_partkey = apk
  WHERE p_brand = 'Brand#23' AND l_quantity < avg_qty * 0.5
)
"""

Q18 = """
SELECT c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice,
       sum(l_quantity) AS total_qty
FROM customer
JOIN orders ON c_custkey = o_custkey
JOIN lineitem ON o_orderkey = l_orderkey
LEFT SEMI JOIN (
  SELECT l_orderkey AS bok
  FROM lineitem
  GROUP BY l_orderkey
  HAVING sum(l_quantity) > 150
) big ON o_orderkey = bok
GROUP BY c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice
ORDER BY o_totalprice DESC, o_orderdate, o_orderkey
LIMIT 100
"""

Q19 = """
SELECT sum(l_extendedprice) AS revenue
FROM lineitem
JOIN part ON p_partkey = l_partkey
WHERE (p_brand = 'Brand#12'
       AND p_container IN ('SM CASE', 'SM BOX', 'SM PACK', 'SM PKG')
       AND l_quantity BETWEEN 1 AND 11 AND p_size BETWEEN 1 AND 5
       AND l_shipmode IN ('AIR', 'REG AIR'))
   OR (p_brand = 'Brand#23'
       AND p_container IN ('MED BAG', 'MED BOX', 'MED PKG', 'MED PACK')
       AND l_quantity BETWEEN 10 AND 20 AND p_size BETWEEN 1 AND 10)
   OR (p_brand = 'Brand#34'
       AND p_container IN ('LG CASE', 'LG BOX', 'LG PACK', 'LG PKG')
       AND l_quantity BETWEEN 20 AND 30 AND p_size BETWEEN 1 AND 15)
"""

Q20 = """
SELECT s_name
FROM supplier
JOIN nation ON s_nationkey = n_nationkey
LEFT SEMI JOIN (
  SELECT ps_suppkey
  FROM partsupp
  LEFT SEMI JOIN (
    SELECT p_partkey FROM part WHERE p_name LIKE 'forest%'
  ) fp ON ps_partkey = p_partkey
  JOIN (
    SELECT l_partkey AS hpk, l_suppkey AS hsk,
           sum(l_quantity) AS period_qty
    FROM lineitem
    WHERE l_shipdate >= 8766 AND l_shipdate < 9131
    GROUP BY l_partkey, l_suppkey
  ) h ON ps_partkey = hpk AND ps_suppkey = hsk
  WHERE ps_availqty > period_qty * 0.5
) ok ON s_suppkey = ps_suppkey
WHERE n_name = 'CANADA'
ORDER BY s_name
"""

Q21 = """
SELECT s_name, count(*) AS numwait
FROM lineitem
JOIN orders ON o_orderkey = l_orderkey AND o_orderstatus = 'F'
JOIN supplier ON s_suppkey = l_suppkey
JOIN nation ON s_nationkey = n_nationkey
LEFT SEMI JOIN (
  SELECT l_orderkey AS ok2, l_suppkey AS sk2 FROM lineitem
) l2 ON ok2 = l_orderkey AND sk2 <> l_suppkey
LEFT ANTI JOIN (
  SELECT l_orderkey AS ok3, l_suppkey AS sk3 FROM lineitem
  WHERE l_receiptdate > l_commitdate
) l3 ON ok3 = l_orderkey AND sk3 <> l_suppkey
WHERE l_receiptdate > l_commitdate AND n_name = 'GERMANY'
GROUP BY s_name
ORDER BY numwait DESC, s_name
LIMIT 100
"""

Q22 = """
SELECT cntrycode, count(*) AS numcust, sum(c_acctbal) AS totacctbal
FROM (
  SELECT substring(c_phone, 1, 2) AS cntrycode, c_acctbal, c_custkey
  FROM customer
  CROSS JOIN (
    SELECT avg(c_acctbal) AS avg_bal FROM customer WHERE c_acctbal > 0.0
  )
  WHERE c_acctbal > avg_bal
    AND substring(c_phone, 1, 2) IN ('13', '31', '23', '29', '30', '18',
                                     '17')
)
LEFT ANTI JOIN orders ON o_custkey = c_custkey
GROUP BY cntrycode
ORDER BY cntrycode
"""

QUERIES = {f"q{i}": q for i, q in enumerate(
    [Q1, Q2, Q3, Q4, Q5, Q6, Q7, Q8, Q9, Q10, Q11, Q12, Q13, Q14, Q15,
     Q16, Q17, Q18, Q19, Q20, Q21, Q22], start=1)}
