"""Benchmark workloads and harness (reference: integration_tests
TpchLikeSpark / TpcdsLikeSpark / BenchUtils — SURVEY.md section 4.5)."""
